"""Closed-loop controller integration tests (VERDICT round-1 item 3).

Drives >=10 ticks of the scrape->decide->render->apply->verify loop through
a synthetic signal source positioned just before the 09:00 peak edge, so
`is_peak` flips mid-run, and asserts the applied NodePool patches change
with it — the automation of the operator's demo_20->demo_21 switch.
"""

import json
import time

import numpy as np
import pytest

from ccka_tpu.actuation.sink import DryRunSink, KubectlSink
from ccka_tpu.config import default_config
from ccka_tpu.harness.controller import Controller, controller_from_config
from ccka_tpu.policy import RulePolicy
from ccka_tpu.signals.synthetic import SyntheticSignalSource


@pytest.fixture()
def cfg_edge():
    """Default config; sources started at 08:58 flip to peak at tick 4."""
    return default_config()


def _source_at_peak_edge(cfg):
    # 08:58:00 -> ticks 0..3 off-peak, tick 4+ peak (30s ticks).
    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals, start_unix_s=8 * 3600 + 58 * 60)


def test_controller_ten_ticks_flip_peak(cfg_edge):
    cfg = cfg_edge
    src = _source_at_peak_edge(cfg)
    sink = DryRunSink()
    lines = []
    ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, sink,
                      interval_s=0.0, log_fn=lines.append)
    reports = ctrl.run(ticks=10)

    assert len(reports) == 10
    assert all(r.applied and r.verified for r in reports)
    # The peak edge: first 4 off-peak, rest peak.
    assert [r.is_peak for r in reports] == [False] * 4 + [True] * 6
    assert reports[0].profile == "offpeak" and reports[-1].profile == "peak"

    # The sink's stored pool state follows the flip: off-peak leaves the
    # spot pool on aggressive consolidation + OFFPEAK_ZONES; peak pins
    # PEAK_ZONES and conservative consolidation (demo_20 vs demo_21).
    spot_pool = cfg.cluster.pools[0].name
    observed = sink.observed_state(spot_pool)
    assert observed["consolidationPolicy"] == "WhenEmpty"  # peak, conservative
    assert observed["zones"] == list(cfg.cluster.peak_zones)

    # Structured KPI log: one JSON line per tick, machine-parseable.
    assert len(lines) == 10
    rec = json.loads(lines[-1])
    assert rec["t"] == 9 and rec["is_peak"] is True
    assert rec["cost_usd_hr"] > 0

    # Patch stream actually changed at the flip: compare rendered commands
    # of an off-peak tick vs a peak tick.
    cmds = [c.render() for c in sink.commands]
    offpeak_reqs = [c for c in cmds[:8] if "us-east-2a" in c]
    peak_reqs = [c for c in cmds[-8:] if "us-east-2c" in c]
    assert offpeak_reqs and peak_reqs


def test_controller_through_fake_kubectl_runner(cfg_edge):
    """Same loop through KubectlSink with an injected fake kubectl that
    maintains a NodePool store — exercises the real argv path."""
    cfg = cfg_edge
    store: dict[str, dict] = {p.name: {"requirements": []}
                              for p in cfg.cluster.pools}
    calls = []

    def fake_kubectl(argv):
        calls.append(list(argv))
        assert argv[0] == "kubectl"
        if argv[1] == "patch":
            name, ptype, patch = argv[3], argv[4], json.loads(argv[6])
            entry = store.setdefault(name, {})
            if ptype == "--type=merge":
                entry.setdefault("spec", {}).setdefault("disruption", {}
                    ).update(patch["spec"]["disruption"])
            else:
                entry["requirements"] = patch[0]["value"]
            return 0, "patched"
        if argv[1] == "get":
            name = argv[3]
            entry = store.get(name)
            if entry is None:
                return 1, "not found"
            if "jsonpath" in argv[-1]:
                reqs = entry.get("requirements", [])
                out = "\n".join(
                    f"{r['key']}=In:{' '.join(r['values'])}" for r in reqs)
                return 0, out
            doc = {"spec": {"disruption": entry.get("spec", {}).get(
                       "disruption", {}),
                   "template": {"spec": {"requirements":
                                         entry.get("requirements", [])}}}}
            return 0, json.dumps(doc)
        return 1, f"unhandled {argv}"

    src = _source_at_peak_edge(cfg)
    ctrl = Controller(cfg, RulePolicy(cfg.cluster), src,
                      KubectlSink(fake_kubectl), interval_s=0.0,
                      log_fn=lambda _line: None)
    reports = ctrl.run(ticks=10)
    assert all(r.applied and r.verified for r in reports)
    # Every tick patched both pools (merge + json per pool) and read back.
    patch_calls = [c for c in calls if c[1] == "patch"]
    assert len(patch_calls) == 10 * 2 * 2
    # Post-flip store holds the peak profile.
    od_pool = cfg.cluster.pools[1].name
    dis = store[od_pool]["spec"]["disruption"]
    assert dis == {"consolidationPolicy": "WhenEmpty",
                   "consolidateAfter": "120s"}


def test_controller_sleeps_between_ticks(cfg_edge):
    cfg = cfg_edge
    naps = []
    ctrl = Controller(cfg, RulePolicy(cfg.cluster),
                      _source_at_peak_edge(cfg), DryRunSink(),
                      interval_s=30.0, log_fn=lambda _l: None,
                      sleep_fn=naps.append)
    ctrl.run(ticks=3)
    assert naps == [30.0, 30.0]  # no sleep after the final tick


def test_controller_reports_unverified_on_mangling_sink(cfg_edge):
    """A sink that silently drops the requirements patch must surface as
    verified=False (the skeptical read-back discipline)."""
    cfg = cfg_edge

    class DroppingSink(DryRunSink):
        def _patch(self, cmd):
            if cmd.patch_type == "json":
                self.commands.append(cmd)
                return True  # accepted but silently dropped
            return super()._patch(cmd)

    ctrl = Controller(cfg, RulePolicy(cfg.cluster),
                      _source_at_peak_edge(cfg), DroppingSink(),
                      interval_s=0.0, log_fn=lambda _l: None)
    reports = ctrl.run(ticks=2)
    # The patch "applies" only via the fallback mechanism failing -> the
    # apply itself reports not-ok (read-back at both paths empty).
    assert not any(r.applied and r.verified for r in reports)


def test_controller_from_config_wires_dry_run(cfg_edge):
    ctrl = controller_from_config(cfg_edge, RulePolicy(cfg_edge.cluster),
                                  interval_s=0.0,
                                  log_fn=lambda _l: None)
    assert isinstance(ctrl.sink, DryRunSink)
    reports = ctrl.run(ticks=1)
    assert reports[0].applied


def test_controller_from_config_refuses_live_multiregion_shared_context():
    """Live multi-region with one shared kubectl context would apply both
    regions' NodePool patches (same pool names, different zone sets) to one
    cluster each tick — refused up front, not discovered at verify time."""
    from ccka_tpu.config import multi_region_config

    cfg = multi_region_config()
    with pytest.raises(ValueError, match="runner per region"):
        controller_from_config(cfg, RulePolicy(cfg.cluster), live=True,
                               runner=lambda argv: (0, "{}"))
    # Per-region runners satisfy the gate, and a live tick drives EVERY
    # region's runner (no region silently actuated through another's).
    calls = {r.name: 0 for r in cfg.cluster.regions}

    def make_runner(name):
        def run(argv):
            calls[name] += 1
            return (0, "{}")
        return run

    ctrl = controller_from_config(
        cfg, RulePolicy(cfg.cluster), live=True,
        region_runners={n: make_runner(n) for n in calls},
        interval_s=0.0, lock=False, log_fn=lambda _l: None)
    assert set(ctrl.region_sinks) == {r.name for r in cfg.cluster.regions}
    assert all(isinstance(s, KubectlSink)
               for s in ctrl.region_sinks.values())
    ctrl.run(ticks=1)
    assert all(c > 0 for c in calls.values()), calls


def test_controller_from_config_builds_runners_from_kube_contexts():
    """RegionSpec.kube_context is the operator/CLI path through the live
    multi-region gate: each region's sink gets a runner pinned to that
    region's kubeconfig context via `kubectl --context`."""
    import dataclasses

    from ccka_tpu.config import FrameworkConfig, multi_region_config

    base = multi_region_config()
    regions = tuple(dataclasses.replace(r, kube_context=f"ctx-{r.name}")
                    for r in base.cluster.regions)
    cluster = dataclasses.replace(base.cluster, regions=regions)
    cfg = FrameworkConfig(cluster=cluster).validate()
    ctrl = controller_from_config(cfg, RulePolicy(cfg.cluster), live=True,
                                  interval_s=0.0, lock=False,
                                  log_fn=lambda _l: None)
    assert set(ctrl.region_sinks) == {r.name for r in regions}
    # The wired runner really pins --context.
    from ccka_tpu.actuation.sink import context_runner
    seen = []
    runner = context_runner("ctx-a", base=lambda argv: (seen.append(argv),
                                                        (0, "{}"))[1])
    runner(["kubectl", "get", "nodepool", "x"])
    assert seen[0][:3] == ["kubectl", "--context", "ctx-a"]


@pytest.mark.slow  # ISSUE 16 lane-time rule:
# MPC replanning keeps its forecast-driven fast-lane representative.
def test_controller_with_mpc_backend_replans(cfg_edge):
    """The receding-horizon path: controller triggers replan() on schedule
    and MPC decide() drives valid patches end to end."""
    from ccka_tpu.train.mpc import MPCBackend

    cfg = cfg_edge.with_overrides(**{"train.mpc_iters": 2})
    backend = MPCBackend(cfg, horizon=8, iters=2, replan_every=4)
    src = _source_at_peak_edge(cfg)
    sink = DryRunSink()
    ctrl = Controller(cfg, backend, src, sink, interval_s=0.0,
                      log_fn=lambda _l: None)
    reports = ctrl.run(ticks=8)
    assert all(r.applied for r in reports)
    # Patches rendered from MPC actions are structurally valid Karpenter
    # JSON: both pools patched every tick.
    pools = {c.name for c in sink.commands}
    assert pools == {p.name for p in cfg.cluster.pools}
    assert np.isfinite([r.cost_usd_hr for r in reports]).all()


class TestSubprocessRunnerHardening:
    """VERDICT r2 weak #10: a hung kubectl must not freeze the control
    loop; transient API failures get bounded backoff, real errors none."""

    def test_hanging_command_times_out(self):
        from ccka_tpu.actuation.sink import _subprocess_runner

        t0 = time.monotonic()
        rc, out = _subprocess_runner(["sleep", "30"], timeout_s=0.2,
                                     retries=1, backoff_s=0.01)
        assert rc == 124 and "timed out" in out
        assert time.monotonic() - t0 < 5  # (2 attempts x 0.2s) + slack

    def test_transient_failure_retries_with_backoff(self, tmp_path):
        from ccka_tpu.actuation.sink import _subprocess_runner

        # Script fails with a transient-looking error once, then succeeds.
        marker = tmp_path / "attempted"
        script = tmp_path / "flaky.sh"
        script.write_text(
            "#!/bin/sh\n"
            f"if [ -e {marker} ]; then echo recovered; exit 0; fi\n"
            f"touch {marker}\n"
            "echo 'dial tcp: connection refused' >&2\n"
            "exit 1\n")
        script.chmod(0o755)
        sleeps = []
        rc, out = _subprocess_runner([str(script)], retries=2,
                                     backoff_s=0.5, sleep=sleeps.append)
        assert rc == 0 and "recovered" in out
        assert sleeps == [0.5]  # one retry, first backoff step

    def test_permanent_failure_does_not_retry(self, tmp_path):
        from ccka_tpu.actuation.sink import _subprocess_runner

        count = tmp_path / "count"
        script = tmp_path / "notfound.sh"
        script.write_text(
            "#!/bin/sh\n"
            f"echo x >> {count}\n"
            "echo 'Error from server (NotFound): nodepool not found' >&2\n"
            "exit 1\n")
        script.chmod(0o755)
        sleeps = []
        rc, out = _subprocess_runner([str(script)], retries=2,
                                     backoff_s=0.5, sleep=sleeps.append)
        assert rc == 1 and "NotFound" in out
        assert len(count.read_text().splitlines()) == 1  # exactly 1 attempt
        assert sleeps == []

    def test_missing_binary_fails_fast(self):
        from ccka_tpu.actuation.sink import _subprocess_runner

        rc, out = _subprocess_runner(["/nonexistent/kubectl-xyz", "get"],
                                     retries=2, backoff_s=0.01)
        assert rc == 127

    def test_total_deadline_bounds_all_attempts(self):
        """ADVICE r3: retries share ONE deadline — a degraded API server
        can cost a command ~deadline_s total, never retries x timeout."""
        from ccka_tpu.actuation.sink import _subprocess_runner

        t0 = time.monotonic()
        rc, out = _subprocess_runner(["sleep", "30"], timeout_s=10.0,
                                     deadline_s=0.5, retries=5,
                                     backoff_s=0.01)
        elapsed = time.monotonic() - t0
        assert rc == 124
        assert elapsed < 3.0  # one ~0.5s attempt + slack, NOT 6 x 10s

    def test_backoff_beyond_deadline_stops_retrying(self, tmp_path):
        from ccka_tpu.actuation.sink import _subprocess_runner

        count = tmp_path / "count"
        script = tmp_path / "flaky.sh"
        script.write_text(
            "#!/bin/sh\n"
            f"echo x >> {count}\n"
            "echo 'dial tcp: connection refused' >&2\n"
            "exit 1\n")
        script.chmod(0o755)
        # Backoff (10s) would overshoot the 0.3s deadline: no second try.
        rc, out = _subprocess_runner([str(script)], retries=3,
                                     deadline_s=0.3, backoff_s=10.0)
        assert rc == 1
        assert len(count.read_text().splitlines()) == 1

    def test_wait_condition_timeout_is_not_transient(self, tmp_path):
        """ADVICE r3: `kubectl wait`'s "timed out waiting for the
        condition" is a real failure (the mutate may have succeeded) —
        a bare "timeout" substring match would re-issue it."""
        from ccka_tpu.actuation.sink import _subprocess_runner, _transient

        assert not _transient("error: timed out waiting for the condition")
        assert not _transient("error: unknown flag: --timeout-x")
        assert _transient("unexpected EOF")  # client-go disconnect
        assert _transient("Error from server: EOF")  # apiserver drop
        assert _transient("net/http: TLS handshake timeout")

        count = tmp_path / "count"
        script = tmp_path / "wait.sh"
        script.write_text(
            "#!/bin/sh\n"
            f"echo x >> {count}\n"
            "echo 'error: timed out waiting for the condition' >&2\n"
            "exit 1\n")
        script.chmod(0o755)
        rc, out = _subprocess_runner([str(script)], retries=3,
                                     backoff_s=0.01)
        assert rc == 1
        assert len(count.read_text().splitlines()) == 1  # no retry


def _spot_node(name: str, instance_id: str, zone: str,
               pool: str = "spot-preferred") -> dict:
    return {
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {"karpenter.sh/capacity-type": "spot",
                       "karpenter.sh/nodepool": pool,
                       "topology.kubernetes.io/zone": zone},
        },
        "spec": {"providerID": f"aws:///{zone}/{instance_id}"},
    }


def _sqs_event(instance_id: str, detail_type: str, region: str = "us-east-2",
               handle: str = "rh-1") -> dict:
    return {
        "MessageId": "m-" + instance_id,
        "ReceiptHandle": handle,
        "Body": json.dumps({
            "version": "0",
            "detail-type": detail_type,
            "source": "aws.ec2",
            "region": region,
            "detail": {"instance-id": instance_id,
                       "instance-action": "terminate"},
        }),
    }


class TestSpotInterruptions:
    """VERDICT r3 missing #3: the live half of spot interruptions — the
    EventBridge→SQS warning feed Karpenter's `settings.interruptionQueue=""`
    disabled (`05_karpenter.sh:136`), wired into the controller as a
    cordon+drain response with an immediate state-estimate decrement."""

    def test_feed_parses_and_acks_canned_events(self):
        from ccka_tpu.signals.live import SpotInterruptionFeed

        calls = []

        def runner(argv):
            calls.append(list(argv))
            if argv[:3] == ["aws", "sqs", "receive-message"]:
                return 0, json.dumps({"Messages": [
                    _sqs_event("i-0spot1",
                               "EC2 Spot Instance Interruption Warning",
                               handle="rh-a"),
                    _sqs_event("i-0spot2",
                               "EC2 Instance Rebalance Recommendation",
                               handle="rh-b"),
                    {"MessageId": "m-x", "ReceiptHandle": "rh-c",
                     "Body": "not json"},
                ]})
            return 0, ""

        feed = SpotInterruptionFeed("https://sqs.example/q", runner=runner,
                                    region="us-east-2")
        warnings = feed.poll()
        assert [(w.instance_id, w.action) for w in warnings] == [
            ("i-0spot1", "terminate"), ("i-0spot2", "rebalance")]
        # Every message acked (including the junk one) in ONE batch call —
        # no redelivery, no per-message CLI spawns in the tick path.
        acks = [c for c in calls
                if c[:3] == ["aws", "sqs", "delete-message-batch"]]
        assert len(acks) == 1
        entries = json.loads(acks[0][acks[0].index("--entries") + 1])
        assert {e["ReceiptHandle"] for e in entries} == {
            "rh-a", "rh-b", "rh-c"}

    def test_feed_degrades_on_cli_failure(self):
        from ccka_tpu.signals.live import SpotInterruptionFeed

        feed = SpotInterruptionFeed("https://sqs.example/q",
                                    runner=lambda argv: (1, "boom"))
        assert feed.poll() == []
        feed2 = SpotInterruptionFeed("https://sqs.example/q",
                                     runner=lambda argv: (0, "not json"))
        assert feed2.poll() == []

    def test_warning_tick_drains_and_adjusts_estimate(self):
        """A terminate warning produces the cordon+drain sequence on the
        owning sink, decrements the spot estimate in the node's zone, and
        the tick report carries the counts. Rebalance: counted, no drain."""
        from ccka_tpu.actuation.sink import DryRunSink, ManifestCommand
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.live import InterruptionWarning
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()
        zone = cfg.cluster.zones[1]
        sink = DryRunSink()
        node = _spot_node("ip-10-0-1-23", "i-0spot1", zone)
        sink.objects[("node", "", "ip-10-0-1-23")] = node

        class Feed:
            def __init__(self):
                self.polls = 0

            def poll(self):
                self.polls += 1
                if self.polls == 1:
                    return [InterruptionWarning("i-0spot1", "terminate",
                                                "EC2 Spot..."),
                            InterruptionWarning("i-0gone", "terminate",
                                                "EC2 Spot..."),
                            InterruptionWarning("i-0spot1", "rebalance",
                                                "Rebalance...")]
                return []

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, sink,
                          interval_s=0.0, interruption_feed=Feed(),
                          log_fn=lambda _l: None)
        # Seed the estimate with spot capacity in the node's zone.
        spot_pool = cfg.cluster.pool_index("spot-preferred")
        ctrl.state = ctrl.state._replace(
            nodes=ctrl.state.nodes.at[spot_pool, 1, 0].set(3.0))
        rep = ctrl.tick(0)
        assert rep.interruption_warnings == 3
        assert rep.nodes_drained == 1
        # Cordon then drain hit the sink for the mapped node.
        lifecycle = [c for c in sink.commands
                     if isinstance(c, ManifestCommand)
                     and c.action in ("cordon", "drain")]
        assert [(c.action, c.name) for c in lifecycle] == [
            ("cordon", "ip-10-0-1-23"), ("drain", "ip-10-0-1-23")]
        # Dry-run store marks the node unschedulable + drained.
        assert node["spec"]["unschedulable"] is True
        assert node["metadata"]["annotations"]["ccka.io/drained"] == "true"
        # 'interruptions' stage shows up in the tick timings.
        assert "interruptions" in rep.timings_ms

    def test_estimate_decrement_lands_in_right_cell(self):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.live import InterruptionWarning
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()
        zone = cfg.cluster.zones[2]
        sink = DryRunSink()
        sink.objects[("node", "", "n1")] = _spot_node("n1", "i-07f", zone)
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, sink,
                          interval_s=0.0, log_fn=lambda _l: None)
        spot_pool = cfg.cluster.pool_index("spot-preferred")
        ctrl.state = ctrl.state._replace(
            nodes=ctrl.state.nodes.at[spot_pool, 2, 0].set(2.0))
        n = ctrl._drain_for_warnings(
            [InterruptionWarning("i-07f", "terminate", "x")])
        assert n == 1
        nodes = np.asarray(ctrl.state.nodes)
        assert nodes[spot_pool, 2, 0] == 2.0 - 1.0
        # Clipped at zero: a second drain of the same (now empty) cell
        # cannot go negative.
        ctrl.state = ctrl.state._replace(
            nodes=ctrl.state.nodes.at[spot_pool, 2, 0].set(0.0))
        sink.objects[("node", "", "n1")] = _spot_node("n1", "i-07f", zone)
        ctrl._drain_for_warnings(
            [InterruptionWarning("i-07f", "terminate", "x")])
        assert np.asarray(ctrl.state.nodes).min() >= 0.0

    def test_duplicate_warning_drains_once(self):
        """At-least-once SQS delivery: a redelivered terminate warning for
        an already-drained instance must not drain or decrement again."""
        from ccka_tpu.actuation.sink import DryRunSink, ManifestCommand
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.live import InterruptionWarning
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()
        zone = cfg.cluster.zones[0]
        sink = DryRunSink()
        sink.objects[("node", "", "n1")] = _spot_node("n1", "i-0dup", zone)
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, sink,
                          interval_s=0.0, log_fn=lambda _l: None)
        spot_pool = cfg.cluster.pool_index("spot-preferred")
        ctrl.state = ctrl.state._replace(
            nodes=ctrl.state.nodes.at[spot_pool, 0, 0].set(3.0))
        w = InterruptionWarning("i-0dup", "terminate", "x")
        # Same-batch duplicate AND a next-tick redelivery.
        assert ctrl._drain_for_warnings([w, w]) == 1
        assert ctrl._drain_for_warnings([w]) == 0
        assert np.asarray(ctrl.state.nodes)[spot_pool, 0, 0] == 2.0
        drains = [c for c in sink.commands
                  if isinstance(c, ManifestCommand) and c.action == "drain"]
        assert len(drains) == 1

    def test_unresolved_warning_retries_then_drains(self):
        """An acked warning whose node listing transiently fails (or whose
        node hasn't registered) is retried next tick instead of lost —
        SQS acks at poll time, so the controller is the only memory."""
        from ccka_tpu.actuation.sink import DryRunSink, ManifestCommand
        from ccka_tpu.harness.controller import (_PENDING_WARNING_TTL,
                                                 Controller)
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.live import InterruptionWarning
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()
        sink = DryRunSink()

        class OneShotFeed:
            def __init__(self):
                self.fired = False

            def poll(self):
                if not self.fired:
                    self.fired = True
                    return [InterruptionWarning("i-0late", "terminate",
                                                "x")]
                return []

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, sink,
                          interval_s=0.0, interruption_feed=OneShotFeed(),
                          log_fn=lambda _l: None)
        # Tick 0: warning arrives but no node matches -> carried over.
        rep0 = ctrl.tick(0)
        assert rep0.nodes_drained == 0
        assert "i-0late" in ctrl._pending_warnings
        # Node registers late; tick 1 resolves the carried warning.
        sink.objects[("node", "", "late-node")] = _spot_node(
            "late-node", "i-0late", cfg.cluster.zones[0])
        rep1 = ctrl.tick(1)
        assert rep1.nodes_drained == 1
        assert ctrl._pending_warnings == {}
        drains = [c for c in sink.commands
                  if isinstance(c, ManifestCommand) and c.action == "drain"]
        assert [c.name for c in drains] == ["late-node"]

    def test_drain_failure_retries_next_tick(self):
        """ADVICE r4 (medium): a matched node whose drain transiently
        fails must carry the warning into the pending buffer — the
        2-minute notice survives a kubectl hiccup and the drain is
        retried (and the estimate decremented) on the next tick."""
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.live import InterruptionWarning
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()

        class FlakySink(DryRunSink):
            def __init__(self):
                super().__init__()
                self.drain_calls = 0

            def drain_node(self, name, grace_s=30):
                self.drain_calls += 1
                if self.drain_calls == 1:
                    return False  # transient kubectl failure
                return super().drain_node(name, grace_s=grace_s)

        sink = FlakySink()
        sink.objects[("node", "", "n1")] = _spot_node(
            "n1", "i-0flaky", cfg.cluster.zones[0])
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, sink,
                          interval_s=0.0, log_fn=lambda _l: None)
        spot_pool = cfg.cluster.pool_index("spot-preferred")
        ctrl.state = ctrl.state._replace(
            nodes=ctrl.state.nodes.at[spot_pool, 0, 0].set(2.0))
        w = InterruptionWarning("i-0flaky", "terminate", "x")
        assert ctrl._drain_for_warnings([w]) == 0
        assert "i-0flaky" in ctrl._pending_warnings  # carried, not lost
        # Next tick re-offers the carried warning; drain succeeds now.
        assert ctrl._drain_for_warnings([w]) == 1
        assert ctrl._pending_warnings == {}
        assert np.asarray(ctrl.state.nodes)[spot_pool, 0, 0] == 1.0

    def test_unresolved_warning_expires_after_ttl(self):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import (_PENDING_WARNING_TTL,
                                                 Controller)
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.live import InterruptionWarning
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                          interval_s=0.0, log_fn=lambda _l: None)
        w = InterruptionWarning("i-0ghost", "terminate", "x")
        ctrl._drain_for_warnings([w])
        assert ctrl._pending_warnings["i-0ghost"][1] == _PENDING_WARNING_TTL
        for _ in range(_PENDING_WARNING_TTL):
            ctrl._drain_for_warnings([w])
        assert "i-0ghost" not in ctrl._pending_warnings  # gave up, logged

    def test_from_config_wires_feed_from_queue_url(self):
        from ccka_tpu.harness.controller import controller_from_config
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.live import SpotInterruptionFeed

        cfg = default_config().with_overrides(**{
            "signals.interruption_queue_url": "https://sqs.example/q"})
        ctrl = controller_from_config(
            cfg, RulePolicy(cfg.cluster),
            interruption_runner=lambda argv: (1, ""))
        assert isinstance(ctrl.interruption_feed, SpotInterruptionFeed)
        assert ctrl.interruption_feed.queue_url == "https://sqs.example/q"
        # No URL -> no feed.
        ctrl2 = controller_from_config(default_config(),
                                       RulePolicy(cfg.cluster))
        assert ctrl2.interruption_feed is None


class TestControllerLock:
    """Single-writer race guard: two control loops on one cluster would
    ping-pong demo_20/demo_21 patches (the hazard the reference only
    partially guards with port checks, demo_18:58-65)."""

    def test_second_instance_fails_fast(self, tmp_path):
        from ccka_tpu.harness.controller import ControllerLock

        a = ControllerLock("demo1", lock_dir=str(tmp_path))
        b = ControllerLock("demo1", lock_dir=str(tmp_path))
        a.acquire()
        with pytest.raises(RuntimeError, match="another controller"):
            b.acquire()
        a.release()
        b.acquire()  # freed lock is reacquirable
        b.release()

    def test_per_cluster_isolation(self, tmp_path):
        from ccka_tpu.harness.controller import ControllerLock

        a = ControllerLock("demo1", lock_dir=str(tmp_path))
        b = ControllerLock("other", lock_dir=str(tmp_path))
        a.acquire()
        b.acquire()  # different cluster, no contention
        a.release()
        b.release()

    def test_controller_lock_wiring(self, cfg_edge, tmp_path):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import (Controller,
                                                 ControllerLockHeld)

        cfg = cfg_edge
        src = _source_at_peak_edge(cfg)
        d = str(tmp_path)  # isolated lock dir: never the host-global one
        c1 = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                        interval_s=0.0, lock=True, lock_dir=d,
                        log_fn=lambda _line: None)
        with pytest.raises(ControllerLockHeld):
            Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                       interval_s=0.0, lock=True, lock_dir=d,
                       log_fn=lambda _line: None)
        c1.run(ticks=1)
        c1.close()  # releases lock
        c2 = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                        interval_s=0.0, lock=True, lock_dir=d,
                        log_fn=lambda _line: None)
        c2.close()
