"""Fleet controller: one batched decide driving N per-cluster sinks.

VERDICT r2 missing #5 / BASELINE config #5: fleet-scale *control*, not just
fleet-scale simulation — a single on-device batched inference tick whose
actions fan out to per-cluster actuation sinks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.harness.fleet import (FleetController,
                                    fleet_controller_from_config)
from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy


@pytest.fixture(scope="module")
def cfg():
    # Tiny pipeline depth keeps state small across 128 clusters.
    return default_config().with_overrides(**{"sim.horizon_steps": 16})


def test_one_batched_tick_drives_128_sinks(cfg):
    """>=100 dry-run sinks per the VERDICT done-criterion: every cluster's
    sink receives that cluster's patches from ONE batched decide."""
    n = 128
    ctrl = fleet_controller_from_config(
        cfg, RulePolicy(cfg.cluster), n, horizon_ticks=8, seed=3)
    reports = ctrl.run(ticks=2)
    for rep in reports:
        assert rep.n_clusters == n
        assert rep.applied == n          # all dry-run applies succeed
        assert rep.cost_usd_hr > 0
    pool_names = {p.name for p in cfg.cluster.pools}
    for sink in ctrl.sinks:
        # Both pools patched on every tick for every cluster.
        assert {c.name for c in sink.commands} == pool_names
        # Tick 2 state is readable back per cluster (observe discipline).
        state = sink.observed_state(cfg.cluster.pools[0].name)
        assert state.get("zones")


def test_fleet_actions_vary_with_per_cluster_signals(cfg):
    """Clusters see independent signal streams; a signal-dependent policy
    (carbon-aware zone weights) must be able to diverge across the fleet —
    i.e. the batch axis carries real per-cluster state, not one broadcast
    decision."""
    n = 16
    ctrl = fleet_controller_from_config(
        cfg, CarbonAwarePolicy(cfg.cluster), n, horizon_ticks=8, seed=11)
    # Streams genuinely differ across the fleet at t=0.
    carbon = np.asarray(ctrl._traces.carbon_g_kwh)   # [N, T, Z]
    assert np.std(carbon[:, 0, 0]) > 0
    # Probe the device tick directly: packed actions for distinct clusters.
    packed, _, _ = ctrl._fleet_tick(ctrl.states, jnp.int32(0),
                                    jax.random.key(0))
    packed = np.asarray(packed)
    assert packed.shape[0] == n
    zw00 = np.stack([
        np.asarray(ctrl._unpack_action(packed[i, :-1]).zone_weight)[0, 0]
        for i in range(n)])
    assert np.std(zw00) > 1e-6  # decisions diverge across clusters


def test_fleet_state_advances_and_accumulates(cfg):
    ctrl = fleet_controller_from_config(
        cfg, RulePolicy(cfg.cluster), 8, horizon_ticks=8, seed=0)
    ctrl.run(ticks=3)
    t = np.asarray(ctrl.states.time_s)
    assert t.shape == (8,)
    assert np.all(t == 3 * cfg.sim.dt_s)
    assert np.all(np.asarray(ctrl.states.acc_cost_usd) > 0)


@pytest.mark.slow  # ISSUE 16 lane-time rule: the pipelined-vs-sync
# bitwise gate is pinned per record by the streaming bench stage.
def test_pipelined_run_matches_sequential_ticks(cfg):
    """`run()` dispatches tick t+1 before fanning out tick t and pushes
    apply through the worker pool; neither may change WHAT is applied —
    same reports, same per-sink command streams as synchronous ticks."""
    n = 24
    seq = fleet_controller_from_config(
        cfg, RulePolicy(cfg.cluster), n, horizon_ticks=8, seed=5,
        fanout_workers=1)
    pipe = fleet_controller_from_config(
        cfg, RulePolicy(cfg.cluster), n, horizon_ticks=8, seed=5,
        fanout_workers=8)
    r_seq = [seq.tick(t) for t in range(3)]
    r_pipe = pipe.run(ticks=3)
    pipe.close()
    for a, b in zip(r_seq, r_pipe):
        assert (a.t, a.applied, a.slo_ok) == (b.t, b.applied, b.slo_ok)
        np.testing.assert_allclose(a.cost_usd_hr, b.cost_usd_hr, rtol=1e-6)
        np.testing.assert_allclose(a.carbon_g_hr, b.carbon_g_hr, rtol=1e-6)
    for sa, sb in zip(seq.sinks, pipe.sinks):
        assert [(c.name, c.patch_type, c.patch) for c in sa.commands] \
            == [(c.name, c.patch_type, c.patch) for c in sb.commands]


def test_fleet_requires_device_batched_source(cfg):
    from ccka_tpu.actuation.sink import DryRunSink

    class NoBatch:  # a replay/live-shaped source without the device path
        pass

    with pytest.raises(ValueError, match="device-batched"):
        FleetController(cfg, RulePolicy(cfg.cluster), NoBatch(),
                        [DryRunSink()])


def test_cli_fleet_command(cfg, capsys):
    import json

    from ccka_tpu.cli import main

    assert main(["fleet", "--clusters", "8", "--ticks", "2"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["clusters"] == 8 and out["applied_frac"] == 1.0
    assert out["fleet_cost_usd_hr_last"] > 0


@pytest.mark.slow  # ISSUE 16 lane-time rule: batched-plan parity is
# exercised every record by the factory stage's one-dispatch planner.
def test_optimize_plan_batch_matches_single(cfg):
    """vmap'd fleet planning is the same optimization per item."""
    from ccka_tpu.models import action_to_latent
    from ccka_tpu.policy.rule import neutral_action
    from ccka_tpu.signals.synthetic import SyntheticSignalSource
    from ccka_tpu.sim import SimParams, initial_state
    from ccka_tpu.train.mpc import optimize_plan, optimize_plan_batch

    params = SimParams.from_config(cfg)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    h, iters, n = 6, 3, 3
    base = action_to_latent(neutral_action(cfg.cluster), cfg.cluster)
    lat0 = jnp.broadcast_to(base, (h,) + base.shape)
    traces = [src.trace(h, seed=i) for i in range(n)]
    state0 = initial_state(cfg)

    singles = [optimize_plan(params, cfg.cluster, cfg.train, state0,
                             tr, lat0, iters=iters).plan_latent
               for tr in traces]
    batched = optimize_plan_batch(
        params, cfg.cluster, cfg.train,
        jax.tree.map(lambda x: jnp.broadcast_to(x, (n,) + x.shape), state0),
        jax.tree.map(lambda *xs: jnp.stack(xs), *traces),
        jnp.broadcast_to(lat0, (n,) + lat0.shape), iters=iters)
    assert batched.plan_latent.shape == (n, h, base.shape[-1])
    for i in range(n):
        np.testing.assert_allclose(np.asarray(batched.plan_latent[i]),
                                   np.asarray(singles[i]),
                                   rtol=2e-3, atol=2e-3)
    # Distinct traces → distinct plans (the batch isn't degenerate).
    assert not np.allclose(np.asarray(batched.plan_latent[0]),
                           np.asarray(batched.plan_latent[1]))
