"""Test environment: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip hardware is unavailable in CI, so the default lane runs on
`--xla_force_host_platform_device_count=8` CPU devices — the sharding tests
in `tests/test_parallel.py` genuinely split batches across those 8 devices,
mirroring how the driver dry-runs the multi-chip path
(`__graft_entry__.dryrun_multichip`).

Set ``CCKA_TEST_TPU=1`` to instead run on the real accelerator: the CPU
override is skipped, so the axon sitecustomize's ``jax_platforms=axon,cpu``
selection stands and the tunneled TPU chip is used (note the env var
``JAX_PLATFORMS`` alone cannot redirect this — see
.claude/skills/verify/SKILL.md). That lane also un-skips `-m tpu` smoke
tests.
"""

import os

if os.environ.get("CCKA_TEST_TPU", "") != "1":
    # The session may arrive with JAX_PLATFORMS pointing at an accelerator;
    # the CPU lane must override it, not setdefault around it. The env var
    # alone is not enough: pytest's plugin chain imports jax before this
    # conftest runs, baking the platform default — so also update the live
    # config (safe: no backend is initialized during plugin import).
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flag = "--xla_force_host_platform_device_count=8"
    if _flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import json  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402

import ccka_tpu  # noqa: E402
from ccka_tpu.config import default_config  # noqa: E402

_LANE_TIMES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "data", "lane_times.json")
_SESSION_T0 = {"t": None}

# Pinned tier-1 wall-clock budget (ISSUE 3 satellite). The driver's
# tier-1 command runs under `timeout 870`, so a lane that drifts past
# ~840s is one slow test away from a hard kill: exceeding this budget
# warns on stderr and stamps the lane_times row, and the remedy is the
# ROADMAP rule — mark offenders `slow` where they only duplicate
# fast-lane coverage.
_LANE_BUDGET_S = 840.0


def pytest_sessionstart(session):
    _SESSION_T0["t"] = time.time()


def pytest_sessionfinish(session, exitstatus):
    """Record the tier-1 lane wall-clock automatically (ISSUE 2
    satellite): full `-m "not slow"` runs append {round, wall_clock_s,
    passed, failed} to data/lane_times.json — ROADMAP's lane table reads
    from there instead of hand-edited rows. Partial runs (file/keyword
    selections, other mark exprs) don't pollute the record."""
    if getattr(session.config.option, "markexpr", "") != "not slow":
        return
    targets = getattr(session.config.option, "file_or_dir", [])
    if targets not in ([], ["tests/"], ["tests"]):
        return
    # Only COMPLETE runs are measurements: a Ctrl-C (exitstatus 2), a
    # usage error, or an -x early stop would record a bogus wall-clock —
    # the exact drift this file exists to end. Test failures (exit 1)
    # still record: the lane ran fully and the row says what failed.
    if exitstatus not in (0, 1) or getattr(session, "shouldstop", False):
        return
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    if tr is None or _SESSION_T0["t"] is None:
        return
    try:
        with open(_LANE_TIMES, encoding="utf-8") as fh:
            rows = json.load(fh)
    except (OSError, json.JSONDecodeError):
        rows = []
    env_round = os.environ.get("CCKA_ROUND", "")
    # Without CCKA_ROUND, re-runs record the CURRENT (last-seen) round —
    # repeated tier-1 runs inside one round append measurements of that
    # round rather than fabricating new round numbers; a new round
    # announces itself via CCKA_ROUND=<n>. The inference is a footgun
    # when the operator FORGOT the env var at a round boundary, so the
    # row self-describes (`round_inferred`) and a one-line warning says
    # which round the measurement was attributed to (ISSUE 11
    # satellite — the bench-history sentinel must be able to tell a
    # labeled row from a guessed one).
    last_round = max((r.get("round") or 0 for r in rows), default=0)
    wall = round(time.time() - _SESSION_T0["t"], 1)
    # A run that executed zero tests (--collect-only, a bad -k filter)
    # is not a lane measurement — recording its wall-clock would hand
    # the budget gate a meaningless "best" row.
    if not tr.stats.get("passed") and not tr.stats.get("failed"):
        return
    round_inferred = not env_round.isdigit()
    row = {
        "round": int(env_round) if env_round.isdigit() else max(
            last_round, 1),
        "date": time.strftime("%Y-%m-%d"),
        "wall_clock_s": wall,
        "passed": len(tr.stats.get("passed", [])),
        "failed": len(tr.stats.get("failed", [])),
        "platform": ("tpu" if os.environ.get("CCKA_TEST_TPU") == "1"
                     else "cpu"),
    }
    if round_inferred:
        import sys

        row["round_inferred"] = True
        print(f"\n# note: CCKA_ROUND unset — lane row attributed to "
              f"round {row['round']} (the last recorded round) and "
              "stamped round_inferred; set CCKA_ROUND=<n> when running "
              "the lane for a NEW round", file=sys.stderr)
    if wall > _LANE_BUDGET_S:
        import sys

        row["over_budget"] = True
        row["budget_s"] = _LANE_BUDGET_S
        print(f"\n# WARNING: tier-1 lane wall-clock {wall:.0f}s exceeds "
              f"the pinned {_LANE_BUDGET_S:.0f}s budget (the driver "
              "kills the lane at 870s) — mark tests that only duplicate "
              "fast-lane coverage `slow` (see ROADMAP's lane-time "
              "section)", file=sys.stderr)
    rows.append(row)
    os.makedirs(os.path.dirname(_LANE_TIMES), exist_ok=True)
    with open(_LANE_TIMES, "w", encoding="utf-8") as fh:
        json.dump(rows, fh, indent=1)
        fh.write("\n")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: smoke tests for the real accelerator "
        "(run with CCKA_TEST_TPU=1)")
    config.addinivalue_line(
        "markers", "slow: compile-heavy tests (8-device mesh, receding-"
        "horizon MPC, end-to-end CLI train)")
    config.addinivalue_line(
        "markers", "quick: the <=2-minute iteration lane (`-m quick`) — "
        "config/codec, golden patch bytes, bootstrap/burst/harness "
        "wire formats, telemetry/exposition; no training or long "
        "rollout compiles")
    config.addinivalue_line(
        "markers", "live_cluster: real-kubectl integration lane against a "
        "kind/k3d cluster (opt in with CCKA_TEST_CLUSTER=1; auto-skips "
        "when no apiserver answers)")


# Modules whose tests are compile-light (host-side wire formats, config,
# golden patches): together ~1 min on CPU. Auto-marked `quick` so the
# iteration lane needs no per-test annotations and new tests in these
# files join it automatically.
_QUICK_MODULES = {
    "test_config", "test_policy_actuation", "test_bootstrap",
    "test_burst", "test_telemetry", "test_cli_harness", "test_doc_sync",
}


def pytest_collection_modifyitems(config, items):
    """Auto-mark the quick lane; keep `-m tpu` smoke tests out of the CPU
    lane (CCKA_TEST_TPU=1 runs them)."""
    for item in items:
        if item.module.__name__ in _QUICK_MODULES:
            item.add_marker(pytest.mark.quick)
    if os.environ.get("CCKA_TEST_TPU", "") == "1":
        return
    skip = pytest.mark.skip(reason="TPU lane: run with CCKA_TEST_TPU=1")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def cfg():
    return default_config()


@pytest.fixture(scope="session")
def small_cfg():
    """A shrunken config for fast simulator tests."""
    return default_config().with_overrides(**{
        "sim.horizon_steps": 64,
        "train.batch_clusters": 4,
        "train.unroll_steps": 8,
    })
