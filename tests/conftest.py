"""Test environment: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip hardware is unavailable in CI; sharding tests run on
`--xla_force_host_platform_device_count=8` CPU devices, mirroring how the
driver dry-runs the multi-chip path (`__graft_entry__.dryrun_multichip`).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

import pytest  # noqa: E402

import ccka_tpu  # noqa: E402
from ccka_tpu.config import default_config  # noqa: E402


@pytest.fixture(scope="session")
def cfg():
    return default_config()


@pytest.fixture(scope="session")
def small_cfg():
    """A shrunken config for fast simulator tests."""
    return default_config().with_overrides(**{
        "sim.horizon_steps": 64,
        "train.batch_clusters": 4,
        "train.unroll_steps": 8,
    })
