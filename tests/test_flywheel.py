"""Continual-learning flywheel (ISSUE 20): ledger mining determinism,
curriculum allocation, checksummed provenance + checkpoint tamper
refusal, the promotion gate battery, the refusal paths the satellite
names (gate failure leaves the incumbent untouched, tampered lineage
refused, rollback restores the parent digest bitwise, seeded reruns
reproduce the same digests), the `ccka flywheel` operator surface, and
the bench-history flywheel invariant gates (an injected bad record
exits 1, the committed history stays clean).
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.train import flywheel as fw_mod
from ccka_tpu.train.checkpoint import (PARAMS_DIGEST_KEY, load_params_npz,
                                       params_digest, save_params_npz)
from ccka_tpu.train.flywheel import (Flywheel, load_provenance,
                                     promotion_gates, write_provenance)
from ccka_tpu.train.mining import (WeaknessCell, curriculum_digest,
                                   curriculum_from_cells,
                                   mine_weakness_cells)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One tiny shared distill geometry: pairs_base == pairs_max keeps every
# curriculum cell on ONE compiled (pairs, steps) geometry so the module
# compiles the factory kernel once.
TINY = dict(steps=32, block_T=32, t_chunk=32, pairs_base=2, pairs_max=2,
            iterations=40, seed=7)


@pytest.fixture(scope="module")
def cfg():
    return default_config()


# -- synthetic ledgers for the mine stage ------------------------------------


def _write_jsonl(path, rows):
    with open(path, "w", encoding="utf-8") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    return str(path)


@pytest.fixture(scope="module")
def ledgers(tmp_path_factory):
    """Hand-built decision/tournament/incident JSONLs with the exact
    row shapes the live observatories write — inference pressure is
    made dominant so the ranking is predictable."""
    d = tmp_path_factory.mktemp("ledgers")
    decisions = _write_jsonl(d / "decisions.jsonl", [
        {"t": t,
         "objective": {"total": 1.0,
                       "shares": {"cost": 0.1, "carbon": 0.1,
                                  "slo_pending": 0.3,
                                  "slo_violation": 0.5,
                                  "migration": 0.0},
                       "by_class": {"class0": 0.8, "class1": 0.2}},
         "shadow": {"diverged": t % 2 == 0,
                    "objective": {"total": 0.8}},
         "exo": {"is_peak": True}}
        for t in range(8)])
    tournament = _write_jsonl(d / "tournament.jsonl", [
        {"kind": "board", "t": 7, "window_ticks": 8, "policy": "rule",
         "board": {"carbon": {
             "win_rate": 0.25, "wins": 2, "comparisons": 8,
             "classes": {"inference": {"win_rate": 0.75,
                                       "comparisons": 8},
                         "batch": {"win_rate": 0.25,
                                   "comparisons": 8},
                         "background": {"win_rate": 0.0,
                                        "comparisons": 8}}}}}])
    incidents = _write_jsonl(d / "incidents.jsonl", [
        {"kind": "incident", "id": 1, "t": 5, "trigger": "slo_burn"}])
    return {"decisions": decisions, "tournament": tournament,
            "incidents": incidents}


class TestMining:
    def test_empty_evidence_returns_library_floor(self):
        cells = mine_weakness_cells(top_k=6)
        assert cells, "a cold-start flywheel must still get a curriculum"
        assert all(isinstance(c, WeaknessCell) for c in cells)
        assert all(c.intensity in ("off", "moderate") for c in cells)
        assert cells == mine_weakness_cells(top_k=6)

    def test_mine_is_deterministic_over_files(self, ledgers):
        kw = dict(decisions_path=ledgers["decisions"],
                  tournament_path=ledgers["tournament"],
                  incidents_path=ledgers["incidents"], top_k=8)
        a, b = mine_weakness_cells(**kw), mine_weakness_cells(**kw)
        assert a == b
        assert [c.score for c in a] == sorted(
            (c.score for c in a), reverse=True)

    def test_evidence_shapes_the_ranking(self, ledgers):
        """The synthetic ledgers put their pressure on inference (0.5
        violation share + a 0.75 tournament loss rate), so inference
        cells must top the board, stamped with the peak regime the
        shadow regret recorded and the incident urgency multiplier."""
        cells = mine_weakness_cells(
            decisions_path=ledgers["decisions"],
            tournament_path=ledgers["tournament"],
            incidents_path=ledgers["incidents"], top_k=4)
        assert cells[0].workload_class == "inference"
        assert cells[0].tenant_regime == "peak"
        assert cells[0].evidence["urgency"] > 1.0
        assert cells[0].evidence["tournament_loss_rate"] == 0.75

    def test_curriculum_allocation_monotone_and_bounded(self):
        cells = [
            WeaknessCell("flash-crowd", "off", "inference", "peak", 3.0),
            WeaknessCell("mixed", "off", "background", "peak", 1.0),
            WeaknessCell("flash-crowd", "off", "batch", "peak", 1.5),
        ]
        cur = curriculum_from_cells(cells, pairs_base=4, pairs_max=16)
        by_sc = {r["scenario"]: r for r in cur}
        assert by_sc["flash-crowd"]["score"] == 4.5  # merged duplicate
        assert sorted(by_sc["flash-crowd"]["classes"]) == ["batch",
                                                           "inference"]
        assert by_sc["flash-crowd"]["pairs"] == 16   # top score → cap
        assert 4 <= by_sc["mixed"]["pairs"] < by_sc["flash-crowd"]["pairs"]
        with pytest.raises(ValueError, match="empty weakness-cell"):
            curriculum_from_cells([])

    def test_curriculum_digest_pins_content(self):
        cur = curriculum_from_cells(
            [WeaknessCell("mixed", "off", "background", "peak", 1.0)])
        d1 = curriculum_digest(cur)
        assert d1 == curriculum_digest(json.loads(json.dumps(cur)))
        bumped = [dict(cur[0], pairs=cur[0]["pairs"] + 1)]
        assert curriculum_digest(bumped) != d1


class TestCheckpointDigest:
    """The satellite fix: `load_params_npz` re-derives the params
    digest and REFUSES a tampered checkpoint."""

    def _params(self):
        rng = np.random.default_rng(3)
        return {"actor": {"w": rng.normal(size=(4, 3)).astype(np.float32),
                          "b": np.zeros(3, np.float32)},
                "critic": {"w": rng.normal(size=(4,)).astype(np.float32)}}

    def test_round_trip_stamps_and_verifies(self, tmp_path):
        path = str(tmp_path / "ck.npz")
        save_params_npz(path, self._params(), meta={"tag": "t"})
        tree, meta = load_params_npz(path)
        assert meta[PARAMS_DIGEST_KEY] == params_digest(tree)
        assert meta["tag"] == "t"

    def test_tampered_params_refused(self, tmp_path):
        path = str(tmp_path / "ck.npz")
        save_params_npz(path, self._params(), meta={})
        with np.load(path, allow_pickle=False) as z:
            arrays = {k: z[k].copy() for k in z.files}
        key = next(k for k in arrays if k != "__meta__")
        arrays[key] = arrays[key] + 1.0  # the tamper
        np.savez(path, **arrays)
        with pytest.raises(ValueError, match="digest"):
            load_params_npz(path)

    def test_nested_and_flat_trees_hash_identically(self):
        p = self._params()
        flat = {"actor/w": p["actor"]["w"], "actor/b": p["actor"]["b"],
                "critic/w": p["critic"]["w"]}
        assert params_digest(p) == params_digest(flat)


class TestProvenance:
    def _record(self):
        cur = curriculum_from_cells(
            [WeaknessCell("mixed", "off", "background", "peak", 1.0)])
        return {"generation": 1,
                "parent": {"name": "rule", "digest": ""},
                "curriculum": cur,
                "curriculum_digest": curriculum_digest(cur),
                "ledger_window": {"rows": 8},
                "seeds": {"base": 7},
                "checkpoint": "challenger.npz",
                "checkpoint_digest": "ab" * 32}

    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "prov.json")
        write_provenance(path, self._record())
        rec = load_provenance(path)
        assert rec["generation"] == 1 and rec["record_digest"]

    def test_tampered_record_refused(self, tmp_path):
        path = str(tmp_path / "prov.json")
        write_provenance(path, self._record())
        doc = json.load(open(path))
        doc["checkpoint_digest"] = "ff" * 32  # edit after signing
        json.dump(doc, open(path, "w"))
        with pytest.raises(ValueError, match="tampered|digest mismatch"):
            load_provenance(path)

    def test_missing_required_field_refused(self, tmp_path):
        path = str(tmp_path / "prov.json")
        rec = self._record()
        del rec["seeds"]
        write_provenance(path, rec)  # digest-valid but partial
        with pytest.raises(ValueError, match="missing required"):
            load_provenance(path)

    def test_curriculum_digest_mismatch_refused(self, tmp_path):
        path = str(tmp_path / "prov.json")
        rec = self._record()
        rec["curriculum_digest"] = "00" * 32
        write_provenance(path, rec)  # signed over the WRONG pin
        with pytest.raises(ValueError, match="curriculum digest"):
            load_provenance(path)


class TestPromotionGates:
    """The gate battery as pure arithmetic — every refusal axis."""

    def _rows(self, ratio=0.95, rel=0.0):
        return [{"scenario": "mixed", "intensity": "off", "pairs": 4,
                 "challenger_vs_incumbent_usd_per_slo_hour": ratio,
                 "class_deltas": {
                     "inference": {"rel_delta": rel},
                     "batch": {"rel_delta": 0.0},
                     "background": {"rel_delta": 0.0}}}]

    def _prov(self):
        return {"record_digest": "d" * 64}

    def test_eligible_on_clean_evidence(self):
        d = promotion_gates(self._rows(), provenance=self._prov())
        assert d["eligible"], d
        assert d["gates"]["mean_ratio"] == 0.95

    def test_no_improvement_refused(self):
        d = promotion_gates(self._rows(ratio=1.01),
                            provenance=self._prov())
        assert not d["eligible"] and not d["gates"]["cells_improved"]

    def test_class_regression_beyond_tolerance_refused(self):
        d = promotion_gates(self._rows(rel=0.08),
                            provenance=self._prov())
        assert not d["eligible"]
        assert not d["gates"]["class_regression_ok"]
        assert d["gates"]["worst_class_rel_delta"]["inference"] == 0.08

    def test_empty_evidence_refused(self):
        assert not promotion_gates([], provenance=self._prov())["eligible"]

    def test_missing_provenance_refused(self):
        assert not promotion_gates(self._rows())["eligible"]

    def test_history_regressions_refuse(self):
        bad = [{"kind": "recovery_invariant", "round": 9}]
        d = promotion_gates(self._rows(), provenance=self._prov(),
                            history_regressions=bad)
        assert not d["eligible"] and not d["gates"]["history_ok"]
        clean = [{"kind": "headline", "round": 9}]  # trend, not a gate
        assert promotion_gates(self._rows(), provenance=self._prov(),
                               history_regressions=clean)["eligible"]

    def _board(self, usd=0.0, slo=0.0, rate=0.0, comps=16):
        return {"win_rate": rate, "comparisons": comps,
                "classes": {"inference": {"comparisons": comps,
                                          "usd_delta": usd,
                                          "slo_delta": slo}}}

    def test_shadow_outcomes(self):
        cases = [
            (self._board(comps=0), "no_comparisons", False),
            (self._board(usd=-0.5), "class_harm", False),
            (self._board(usd=1e-7), "non_inferior", True),
            (self._board(usd=0.5, rate=0.8), "win", True),
            (self._board(usd=0.5, rate=0.2), "material_loss", False),
        ]
        for board, outcome, ok in cases:
            d = promotion_gates(self._rows(), provenance=self._prov(),
                                shadow_board=board)
            assert d["gates"]["shadow_outcome"] == outcome
            assert d["gates"]["shadow_ok"] is ok
            assert d["eligible"] is ok


# -- the artifact loop (one tiny real distill, shared) -----------------------


@pytest.fixture(scope="module")
def arc(cfg, tmp_path_factory):
    """Generation 1 mined + distilled once at the TINY geometry; the
    mutation tests below each copy this root before touching it."""
    root = str(tmp_path_factory.mktemp("fw"))
    fw = Flywheel(cfg, root, **TINY)
    cells = fw.mine(top_k=2)
    rep = fw.distill(cells, generation=1,
                     ledger_window={"rows": 0, "seed": TINY["seed"]})
    params, _meta = load_params_npz(rep["checkpoint"])
    eval_rows = fw.evaluate(params, rep["produced"])
    decision = promotion_gates(eval_rows, provenance=rep["provenance"])
    return {"root": root, "cells": cells, "rep": rep,
            "eval": eval_rows, "decision": decision}


def _copy_root(arc, tmp_path, cfg):
    root = str(tmp_path / "fw")
    shutil.copytree(arc["root"], root)
    return Flywheel(cfg, root, **TINY)


class TestFlywheelArtifacts:
    def test_distill_writes_verified_provenance(self, arc, cfg):
        fw = Flywheel(cfg, arc["root"], **TINY)
        st = fw.status()
        assert st["incumbent"] == "rule"
        assert st["generations"][0]["provenance"] == "verified"
        prov = arc["rep"]["provenance"]
        assert prov["checkpoint_digest"] == arc["rep"]["checkpoint_digest"]
        assert prov["curriculum_digest"] == curriculum_digest(
            arc["rep"]["curriculum"])

    def test_challenger_beats_rule_on_its_cells(self, arc):
        """The superiority evidence the gate battery rides: even TINY
        distillation beats the hand rule on the mined cells."""
        assert arc["decision"]["eligible"], arc["decision"]
        assert arc["decision"]["gates"]["mean_ratio"] < 1.0

    def test_gate_failure_leaves_incumbent_untouched(self, arc, cfg,
                                                     tmp_path):
        fw = _copy_root(arc, tmp_path, cfg)
        bad = {"eligible": False,
               "gates": {"cells_improved": False}}
        with pytest.raises(ValueError, match="promotion refused"):
            fw.promote(1, bad)
        assert fw.incumbent() == ("rule", None)
        assert not os.path.exists(fw.live_npz)
        assert not os.path.exists(fw.live_json)

    def test_tampered_provenance_refuses_promotion(self, arc, cfg,
                                                   tmp_path):
        fw = _copy_root(arc, tmp_path, cfg)
        prov_path = os.path.join(fw.gen_dir(1), "provenance.json")
        doc = json.load(open(prov_path))
        doc["checkpoint_digest"] = "00" * 32
        json.dump(doc, open(prov_path, "w"))
        with pytest.raises(ValueError, match="tampered|digest mismatch"):
            fw.promote(1, arc["decision"])
        assert fw.incumbent() == ("rule", None)

    def test_tampered_checkpoint_refuses_promotion(self, arc, cfg,
                                                   tmp_path):
        fw = _copy_root(arc, tmp_path, cfg)
        ckpt = os.path.join(fw.gen_dir(1), "challenger.npz")
        with np.load(ckpt, allow_pickle=False) as z:
            arrays = {k: z[k].copy() for k in z.files}
        key = next(k for k in sorted(arrays) if k != "__meta__")
        arrays[key] = arrays[key] + 0.5
        np.savez(ckpt, **arrays)
        with pytest.raises(ValueError, match="digest"):
            fw.promote(1, arc["decision"])
        assert fw.incumbent() == ("rule", None)

    def test_promote_swaps_live_and_rollback_restores(self, arc, cfg,
                                                      tmp_path):
        fw = _copy_root(arc, tmp_path, cfg)
        live = fw.promote(1, arc["decision"])
        assert live["name"] == "gen-001"
        name, params = fw.incumbent()
        assert name == "gen-001"
        assert params_digest(params) == arc["rep"]["checkpoint_digest"]
        # A swapped-in stray live file is refused, not adopted.
        doc = json.load(open(fw.live_json))
        doc["digest"] = "11" * 32
        json.dump(doc, open(fw.live_json, "w"))
        with pytest.raises(ValueError, match="swapped outside"):
            fw.incumbent()
        json.dump(live, open(fw.live_json, "w"))
        # Rollback: gen-001's parent is the rule profile → demotion
        # clears the live checkpoint entirely.
        new_live = fw.rollback(incident={"id": 1, "t": 3})
        assert new_live["name"] == "rule"
        assert new_live["rolled_back_from"]["name"] == "gen-001"
        assert fw.incumbent() == ("rule", None)
        with pytest.raises(ValueError, match="nothing is promoted"):
            fw.rollback()

    @pytest.mark.slow  # ISSUE 16 lane-time rule: a second full distill
    # on top of the module fixture's; the bitwise-restore contract is
    # re-proven by the slow runner e2e and the record's rollback_ok
    # bench-diff gate, and the fast lane keeps the rule-parent rollback.
    def test_second_generation_rollback_is_bitwise(self, arc, cfg,
                                                   tmp_path):
        """The satellite's rollback contract at full strength: promote
        gen-1, distill + promote gen-2 warm-started ON gen-1, then roll
        back — the restored live params must hash to EXACTLY the parent
        digest the gen-2 promotion recorded."""
        fw = _copy_root(arc, tmp_path, cfg)
        fw.promote(1, arc["decision"])
        rep2 = fw.distill(arc["cells"], generation=2,
                          ledger_window={"rows": 0})
        assert rep2["parent"]["name"] == "gen-001"
        assert rep2["parent"]["digest"] == arc["rep"]["checkpoint_digest"]
        p2, _ = load_params_npz(rep2["checkpoint"])
        rows2 = fw.evaluate(p2, rep2["produced"])
        d2 = promotion_gates(rows2, provenance=rep2["provenance"])
        live2 = fw.promote(2, dict(d2, eligible=True))
        assert live2["parent"]["digest"] == arc["rep"]["checkpoint_digest"]
        restored = fw.rollback(incident={"id": 2, "t": 9})
        assert restored["name"] == "gen-001"
        name, params = fw.incumbent()
        assert name == "gen-001"
        assert params_digest(params) == arc["rep"]["checkpoint_digest"]

    def test_seeded_rerun_reproduces_digests(self, arc, cfg,
                                             tmp_path):
        """The determinism contract: a fresh-root rerun with the same
        seed mines the same cells and distills a challenger with the
        same curriculum AND checkpoint digests."""
        fw = Flywheel(cfg, str(tmp_path / "fw-b"), **TINY)
        cells = fw.mine(top_k=2)
        assert cells == arc["cells"]
        rep = fw.distill(cells, generation=1,
                         ledger_window={"rows": 0,
                                        "seed": TINY["seed"]})
        assert rep["curriculum_digest"] == arc["rep"]["curriculum_digest"]
        assert rep["checkpoint_digest"] == arc["rep"]["checkpoint_digest"]

    def test_challenger_slot_guards(self, arc, cfg):
        with pytest.raises(ValueError, match="does not exist"):
            fw_mod.set_challenger_checkpoint("/no/such/file.npz")
        fw_mod.set_challenger_checkpoint("")
        with pytest.raises(ValueError, match="no challenger checkpoint"):
            fw_mod.challenger_backend(cfg)
        fw_mod.set_challenger_checkpoint(arc["rep"]["checkpoint"])
        try:
            backend = fw_mod.challenger_backend(cfg)
            assert backend is not None
        finally:
            fw_mod.set_challenger_checkpoint("")

    def test_challenger_candidate_registered(self):
        from ccka_tpu.obs.tournament import CANDIDATE_BUILDERS

        assert "flywheel-challenger" in CANDIDATE_BUILDERS


@pytest.mark.slow  # ISSUE 16 lane-time rule: the full service-driven
# two-generation arc (record → mine → distill → shadow lane → gate →
# promote → divergence rollback) re-proves what the fast-lane artifact
# tests and bench.py --flywheel-only's recorded gate battery already
# cover; the fleet-service runs compile several programs.
class TestFlywheelRunnerE2E:
    def test_two_generations_promote_and_roll_back(self, cfg, tmp_path):
        from ccka_tpu.harness.flywheel import FlywheelRunner

        fw = Flywheel(cfg, str(tmp_path / "fw"), **dict(
            TINY, pairs_base=2, pairs_max=3))
        runner = FlywheelRunner(cfg, fw,
                                scratch=str(tmp_path / "scratch"),
                                n_tenants=4, record_ticks=8,
                                shadow_ticks=10, watch_ticks=8,
                                top_k=2, seed=211)
        out = runner.run(2)
        assert out["promotions"] >= 1
        for g in out["generations"]:
            if g["promoted"]:
                assert g["decision"]["eligible"]
                assert g["decision"]["gates"]["mean_ratio"] < 1.0
        if out["generations"][-1]["promoted"]:
            rb = out["rollback"]
            assert rb["rolled_back"]
            assert (rb["restored"]["digest"]
                    == out["generations"][-1]["parent"]["digest"])


class TestCLI:
    def test_status_on_empty_root(self, tmp_path, capsys):
        from ccka_tpu.cli import main

        assert main(["flywheel", "status",
                     "--root", str(tmp_path / "none")]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["incumbent"] == "rule" and doc["live"] is None

    def test_unknown_names_rejected_up_front(self, tmp_path):
        from ccka_tpu.cli import main

        root = str(tmp_path / "fw")
        with pytest.raises(SystemExit, match="unknown fault intensities"):
            main(["flywheel", "mine", "--root", root,
                  "--intensities", "off,catastrophic"])
        with pytest.raises(SystemExit, match="unknown teacher"):
            main(["flywheel", "distill", "--root", root,
                  "--teacher", "oracle"])

    def test_promote_without_recorded_decision_refused(self, tmp_path):
        from ccka_tpu.cli import main

        with pytest.raises(SystemExit, match="refused"):
            main(["flywheel", "promote",
                  "--root", str(tmp_path / "fw"), "--generation", "1"])

    def test_mine_prints_ranked_cells(self, tmp_path, capsys, ledgers):
        from ccka_tpu.cli import main

        assert main(["flywheel", "mine", "--root", str(tmp_path / "fw"),
                     "--decisions", ledgers["decisions"],
                     "--tournament", ledgers["tournament"],
                     "--incidents", ledgers["incidents"],
                     "--top-k", "3"]) == 0
        rows = json.loads(capsys.readouterr().out)
        assert len(rows) == 3
        assert rows[0]["workload_class"] == "inference"


# -- bench-history flywheel invariant gates ----------------------------------


def _good_flywheel_record():
    """The shape `bench.py --flywheel-only` emits (BENCH_r23.json)."""
    gen = {
        "generation": 1, "incumbent": "rule", "promoted": True,
        "eligible": True, "mean_ratio": 0.97,
        "gates": {"cells_improved": True, "class_regression_ok": True,
                  "shadow_ok": True, "provenance_ok": True,
                  "history_ok": True},
        "worst_class_rel_delta": {"inference": 0.0, "batch": 0.01,
                                  "background": 0.0},
        "shadow_outcome": "non_inferior",
    }
    return {
        "stage": "--flywheel-only",
        "provenance": {"platform": "cpu"},
        "generations": [gen,
                        dict(gen, generation=2, incumbent="gen-001",
                             mean_ratio=0.99)],
        "promotions": 2,
        "flywheel_gate_ok": True, "provenance_ok": True,
        "rollback_ok": True, "deterministic_ok": True,
    }


class TestBenchDiffFlywheelGates:
    def _diff_of(self, tmp_path, rec):
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        (tmp_path / "BENCH_r96.json").write_text(json.dumps(rec))
        return bench_diff(load_bench_history(str(tmp_path)))

    def _fw_regressions(self, diff):
        return [r for r in diff["regressions"]
                if r["kind"] == "flywheel_invariant"]

    def test_good_record_is_clean(self, tmp_path):
        diff = self._diff_of(tmp_path, _good_flywheel_record())
        assert diff["ok"], diff["regressions"]

    def test_promotion_without_gate_evidence_regresses_and_cli_exits_one(
            self, tmp_path, capsys):
        rec = _good_flywheel_record()
        rec["generations"][0]["gates"]["shadow_ok"] = False
        diff = self._diff_of(tmp_path, rec)
        assert any("without passing gate evidence" in r["detail"]
                   for r in self._fw_regressions(diff))
        from ccka_tpu.cli import main

        assert main(["bench-diff", "--root", str(tmp_path)]) == 1
        assert "REGRESSION" in capsys.readouterr().err

    def test_promotion_without_strict_improvement_regresses(
            self, tmp_path):
        rec = _good_flywheel_record()
        rec["generations"][1]["mean_ratio"] = 1.0
        diff = self._diff_of(tmp_path, rec)
        assert any("strict paired" in r["detail"]
                   for r in self._fw_regressions(diff))

    def test_class_regression_beyond_tolerance_regresses(self, tmp_path):
        rec = _good_flywheel_record()
        rec["generations"][0]["worst_class_rel_delta"]["batch"] = 0.12
        diff = self._diff_of(tmp_path, rec)
        assert any("regressed workload class batch" in r["detail"]
                   for r in self._fw_regressions(diff))

    def test_false_or_missing_flags_regress(self, tmp_path):
        for key in ("flywheel_gate_ok", "provenance_ok",
                    "rollback_ok", "deterministic_ok"):
            rec = _good_flywheel_record()
            rec[key] = False
            assert not self._diff_of(tmp_path, rec)["ok"], key
            rec = _good_flywheel_record()
            del rec[key]
            diff = self._diff_of(tmp_path, rec)
            assert any("partial" in r["detail"]
                       for r in self._fw_regressions(diff)), key

    def test_real_history_is_clean_and_round23_extracted(self):
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        history = load_bench_history(ROOT)
        rows = [r for r in history["records"]
                if r.get("flywheel_promotions") is not None]
        assert rows, "BENCH_r23.json lost its flywheel columns"
        assert rows[-1]["flywheel_promotions"] >= 2
        assert rows[-1]["flywheel_gate_ok"] is True
        assert rows[-1]["flywheel_rollback_ok"] is True
        assert rows[-1]["flywheel_deterministic_ok"] is True
        diff = bench_diff(history)
        assert diff["ok"], diff["regressions"]


class TestRunlogEvents:
    def test_flywheel_events_registered(self):
        from ccka_tpu.obs.runlog import RUNLOG_EVENTS

        assert {"flywheel_mine", "flywheel_distill", "flywheel_gate",
                "flywheel_promote",
                "flywheel_rollback"} <= RUNLOG_EVENTS
