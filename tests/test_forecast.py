"""Forecast subsystem tests: backends, metrics, MPC + controller wiring.

The subsystem's contract (ISSUE 1 / round 6): planning windows become
*predictions from observed history* while execution still bills against
the true trace; the oracle path survives as ``forecaster=None``. These
tests pin the backend math (seasonal-naive exact on periodic signals,
ridge recovering a known AR coefficient, persistence = last-value hold),
the batched/loop parity that makes fleet-scale forecasting one dispatch,
and the end-to-end jitted integration on CPU.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.cli import main
from ccka_tpu.config import default_config
from ccka_tpu.forecast import (Forecaster, PersistenceForecaster,
                               RidgeARForecaster, SeasonalNaiveForecaster,
                               evaluate_forecaster, fit_ar_coeffs,
                               forecast_errors, make_forecaster,
                               matrix_to_trace, trace_to_matrix)
from ccka_tpu.signals.base import ExogenousTrace, as_f32
from ccka_tpu.signals.synthetic import SyntheticSignalSource


@pytest.fixture(scope="module")
def cfg():
    return default_config()


@pytest.fixture(scope="module")
def synth(cfg):
    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals)


def _periodic_trace(period: int, reps: int, n_zones: int = 3) -> ExogenousTrace:
    """A strictly ``period``-periodic positive trace (seasonal-naive's
    exactness case; positivity keeps matrix_to_trace's clamps inert)."""
    t = np.arange(period * reps)
    phase = 2 * np.pi * (t % period) / period
    per_zone = np.stack([np.sin(phase + z) + 2.0 for z in range(n_zones)],
                        axis=-1)
    demand = np.stack([np.cos(phase) + 2.0, np.sin(2 * phase) + 2.0],
                      axis=-1)
    return ExogenousTrace(
        spot_price_hr=as_f32(0.03 * per_zone),
        od_price_hr=as_f32(0.10 * per_zone),
        carbon_g_kwh=as_f32(300.0 * per_zone),
        demand_pods=as_f32(20.0 * demand),
        is_peak=as_f32(((t % period) < period // 2).astype(np.float32)),
    )


# -- backend math --------------------------------------------------------


def test_seasonal_naive_exact_on_periodic_signal():
    """On a purely P-periodic signal, repeat-from-one-period-ago IS the
    true future — the forecast must match it exactly, every channel."""
    p, h = 96, 48
    trace = _periodic_trace(p, 3)
    history = trace.slice_steps(p, p)        # ticks [P, 2P) — one period
    future = trace.slice_steps(2 * p, h)     # ticks [2P, 2P+H)
    pred = SeasonalNaiveForecaster(period_steps=p).predict(history, h)
    for field in ExogenousTrace._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(pred, field)),
            np.asarray(getattr(future, field)), rtol=0, atol=1e-6,
            err_msg=field)


def test_seasonal_naive_short_history_falls_back_to_persistence():
    trace = _periodic_trace(96, 1)
    history = trace.slice_steps(0, 32)       # < one period of context
    pred = SeasonalNaiveForecaster(period_steps=96).predict(history, 8)
    last = np.asarray(history.spot_price_hr)[-1]
    np.testing.assert_allclose(np.asarray(pred.spot_price_hr),
                               np.broadcast_to(last, (8,) + last.shape))


def test_ridge_recovers_known_ar1_coefficient():
    """Closed-form normal equations on an AR(1) series recover rho —
    batched over series via vmap (the fleet-fit path)."""
    rng = np.random.default_rng(0)
    rhos = np.array([0.85, 0.6], np.float32)
    t_len = 4000
    ys = np.zeros((2, t_len), np.float32)
    for i, rho in enumerate(rhos):
        e = rng.normal(0, 1.0, t_len).astype(np.float32)
        for t in range(1, t_len):
            ys[i, t] = rho * ys[i, t - 1] + e[t]
    w, _mu, _sd = jax.vmap(
        lambda y: fit_ar_coeffs(y, lags=1, ridge=1e-6))(jnp.asarray(ys))
    np.testing.assert_allclose(np.asarray(w)[:, 0], rhos, atol=0.05)


def test_ridge_forecaster_runs_and_beats_trivial_scale(synth):
    """Sanity on real synthetic signals: finite forecasts in the right
    shape, error no worse than 10x persistence (it fits the same data)."""
    tr = synth.trace(700, seed=5)
    ridge = evaluate_forecaster(RidgeARForecaster(lags=8), tr,
                                horizon=16, history_steps=256, stride=64)
    pers = evaluate_forecaster(PersistenceForecaster(), tr,
                               horizon=16, history_steps=256, stride=64)
    assert np.isfinite(ridge["overall"]["mape_mean"])
    assert ridge["overall"]["mape_mean"] < 10 * pers["overall"]["mape_mean"]


def test_persistence_matches_live_source_hold_behavior(cfg):
    """Persistence IS the live default family: the live source's
    on-demand price forecast is a last-value hold, and the persistence
    backend reproduces exactly that behavior from the same history."""
    from ccka_tpu.signals.live import LiveSignalSource

    def no_network(url, headers):
        raise OSError("offline")

    live = LiveSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals,
                            fetch=no_network, start_unix_s=1_700_000_000.0)
    h = 16
    live_fc = live.forecast(0, h)
    pred = PersistenceForecaster().predict(live.history(0, 8), h)
    live_od = np.asarray(live_fc.od_price_hr)
    pred_od = np.asarray(pred.od_price_hr)
    # Both hold od price flat across the horizon...
    assert np.allclose(live_od, live_od[:1])
    assert np.allclose(pred_od, pred_od[:1])
    # ...at the same measured level (live holds the zone-mean scalar).
    np.testing.assert_allclose(pred_od.mean(), live_od.mean(), rtol=1e-5)


def test_predict_batch_matches_loop(synth):
    """Batched-vs-loop parity: vmapped predict over stacked histories is
    elementwise the per-history predict — the identity that lets the
    receding-horizon loop forecast every segment in one dispatch."""
    h = 12
    hists = [synth.trace(200, seed=s).slice_steps(50, 128)
             for s in (0, 1, 2)]
    stacked = ExogenousTrace(*[
        jnp.stack([getattr(t, f) for t in hists])
        for f in ExogenousTrace._fields])
    for fc in (PersistenceForecaster(),
               SeasonalNaiveForecaster(period_steps=96),
               RidgeARForecaster(lags=4)):
        batched = fc.predict_batch(stacked, h)
        for i, hist in enumerate(hists):
            single = fc.predict(hist, h)
            for field in ExogenousTrace._fields:
                np.testing.assert_allclose(
                    np.asarray(getattr(batched, field))[i],
                    np.asarray(getattr(single, field)),
                    rtol=2e-4, atol=1e-5,
                    err_msg=f"{fc.name}.{field}[{i}]")


def test_trace_matrix_round_trip(synth):
    tr = synth.trace(64, seed=2)
    back = matrix_to_trace(trace_to_matrix(tr), tr.n_zones,
                           tr.demand_pods.shape[-1])
    for field in ExogenousTrace._fields:
        np.testing.assert_allclose(np.asarray(getattr(back, field)),
                                   np.asarray(getattr(tr, field)),
                                   atol=1e-6)


def test_make_forecaster_factory(cfg):
    assert make_forecaster("oracle") is None
    assert make_forecaster("") is None
    assert isinstance(make_forecaster("persistence"), PersistenceForecaster)
    sn = make_forecaster("seasonal-naive", dt_s=cfg.sim.dt_s)
    assert isinstance(sn, SeasonalNaiveForecaster)
    assert sn.period_steps == int(round(86400 / cfg.sim.dt_s))
    assert isinstance(make_forecaster("ridge"), RidgeARForecaster)
    with pytest.raises(ValueError, match="unknown forecaster"):
        make_forecaster("prophet")


# -- metrics -------------------------------------------------------------


def test_forecast_errors_horizon_resolved():
    """Persistence error on a trending signal must GROW with horizon —
    the property horizon-resolved curves exist to expose."""
    t = np.arange(300, dtype=np.float32)
    trend = ExogenousTrace(
        spot_price_hr=as_f32(np.stack([t, t, t], -1) + 10.0),
        od_price_hr=as_f32(np.stack([t, t, t], -1) + 10.0),
        carbon_g_kwh=as_f32(np.stack([t, t, t], -1) + 10.0),
        demand_pods=as_f32(np.stack([t, t], -1) + 10.0),
        is_peak=as_f32(np.ones_like(t)),
    )
    out = evaluate_forecaster(PersistenceForecaster(), trend,
                              horizon=16, history_steps=8, stride=16)
    mape = out["spot_price_hr"]["mape"]
    assert len(mape) == 16
    assert mape[-1] > mape[0] > 0
    assert out["is_peak"]["mape"][0] == pytest.approx(0.0, abs=1e-6)


def test_gather_windows_rejects_out_of_range(synth):
    from ccka_tpu.forecast import gather_windows
    tr = synth.trace(100, seed=0)
    with pytest.raises(ValueError, match="anchors"):
        gather_windows(tr, [5], history_steps=10, horizon=4)
    with pytest.raises(ValueError, match="anchors"):
        gather_windows(tr, [98], history_steps=10, horizon=4)


# -- history windows -----------------------------------------------------


def test_source_history_alignment_and_left_pad(synth):
    """history(t, k) ends at tick t inclusive and left-pads by repeating
    the first tick — never touching ticks > t (no future leak)."""
    full = synth.trace(64, seed=0)
    h = synth.history(20, 8, seed=0)
    np.testing.assert_allclose(np.asarray(h.spot_price_hr),
                               np.asarray(full.spot_price_hr)[13:21])
    padded = synth.history(2, 8, seed=0)
    assert padded.steps == 8
    np.testing.assert_allclose(
        np.asarray(padded.spot_price_hr)[:6],
        np.broadcast_to(np.asarray(full.spot_price_hr)[0], (6, 3)))
    np.testing.assert_allclose(np.asarray(padded.spot_price_hr)[-1],
                               np.asarray(full.spot_price_hr)[2])
    assert padded.is_peak.shape == (8,)


def test_planning_window_current_tick_plus_predictions(synth):
    """The planner's window: tick 0 is the OBSERVED current tick, ticks
    1..H-1 are the forecaster's predictions — one time base for planner
    and executor, nothing future-dated."""
    from ccka_tpu.forecast import planning_window
    hist = synth.trace(64, seed=7)
    fc = PersistenceForecaster()
    w = planning_window(fc, hist, 8)
    assert w.steps == 8
    last = np.asarray(hist.spot_price_hr)[-1]
    np.testing.assert_allclose(np.asarray(w.spot_price_hr)[0], last)
    pred = fc.predict(hist, 7)
    np.testing.assert_allclose(np.asarray(w.spot_price_hr)[1:],
                               np.asarray(pred.spot_price_hr))
    np.testing.assert_allclose(np.asarray(w.is_peak)[0],
                               np.asarray(hist.is_peak)[-1])
    # Degenerate H=1: just the observed tick.
    w1 = planning_window(fc, hist, 1)
    assert w1.steps == 1
    np.testing.assert_allclose(np.asarray(w1.od_price_hr)[0],
                               np.asarray(hist.od_price_hr)[-1])


# -- MPC + controller integration ---------------------------------------


@pytest.mark.parametrize("fc_name", [
    "persistence",
    # ISSUE 14 lane-time rule (~21s for the pair): the three params run
    # the SAME jitted MPC composition and differ only in the forecaster
    # backend, whose math is pinned exactly by the exactness/AR-recovery
    # tests above — persistence stays as the fast-lane representative.
    pytest.param("seasonal-naive", marks=pytest.mark.slow),
    pytest.param("ridge", marks=pytest.mark.slow)])
def test_forecast_driven_mpc_jitted_end_to_end(cfg, synth, fc_name):
    """The tentpole contract: receding-horizon MPC planning against
    predicted windows runs fully jitted on CPU — no shape/tracer errors —
    and bills against the TRUE trace (finite, plausible KPIs)."""
    from ccka_tpu.sim.rollout import initial_state
    from ccka_tpu.train.mpc import MPCBackend

    fc = make_forecaster(fc_name, dt_s=cfg.sim.dt_s)
    # Small history keeps the seasonal gather CI-sized; correctness of
    # the period handling is pinned by the exactness test above.
    backend = MPCBackend(cfg, horizon=8, iters=2, replan_every=8,
                         forecaster=fc, history_steps=32)
    trace = synth.trace(32, seed=1)
    final, metrics = backend.evaluate(initial_state(cfg), trace,
                                      jax.random.key(0), stochastic=False)
    cost = np.asarray(metrics.cost_usd)
    assert cost.shape == (32,)
    assert np.all(np.isfinite(cost)) and cost.sum() > 0


@pytest.mark.slow  # ISSUE 16 lane-time rule: the oracle default is
# exercised by every non-forecast MPC test in the fast lane.
def test_oracle_path_unchanged_by_forecaster_arg(cfg, synth):
    """forecaster=None must be bit-identical to the pre-subsystem
    behavior (it IS the pre-subsystem code path)."""
    from ccka_tpu.sim.rollout import initial_state
    from ccka_tpu.train.mpc import MPCBackend

    trace = synth.trace(16, seed=3)
    runs = []
    for _ in range(2):
        b = MPCBackend(cfg, horizon=8, iters=2, replan_every=8,
                       forecaster=None)
        _, m = b.evaluate(initial_state(cfg), trace, jax.random.key(1),
                          stochastic=False)
        runs.append(np.asarray(m.cost_usd))
    np.testing.assert_array_equal(runs[0], runs[1])


class _SpyForecaster(Forecaster):
    """Persistence wrapper that counts host-side predict calls."""

    name = "spy"

    def __init__(self):
        self.inner = PersistenceForecaster()
        self.calls = 0

    def predict(self, history, horizon):
        self.calls += 1
        return self.inner.predict(history, horizon)

    def wanted_history(self, horizon):
        return 4


def test_controller_routes_replan_through_forecaster(cfg, synth):
    """harness/controller.py replan-window routing: a backend carrying a
    forecaster gets predicted windows (source.forecast untouched)."""
    from ccka_tpu.actuation.sink import DryRunSink
    from ccka_tpu.harness.controller import Controller
    from ccka_tpu.train.mpc import MPCBackend

    backend = MPCBackend(cfg, horizon=4, iters=1, replan_every=2,
                         forecaster=_SpyForecaster(), history_steps=4)
    oracle_windows = []
    orig_forecast = synth.forecast

    def recording_forecast(t, steps, **kw):
        oracle_windows.append((t, steps))
        return orig_forecast(t, steps, **kw)

    synth.forecast = recording_forecast
    try:
        ctrl = Controller(cfg, backend, synth, DryRunSink(),
                          interval_s=0, log_fn=lambda s: None)
        reports = ctrl.run(4)
    finally:
        synth.forecast = orig_forecast
    assert len(reports) == 4
    assert backend.forecaster.calls == 2          # replans at t=0 and t=2
    # The synthetic source's own tick() is forecast(t, 1) — those 1-step
    # scrapes remain; what must be GONE is any horizon-sized oracle
    # window feeding a replan.
    assert all(steps == 1 for _t, steps in oracle_windows)


# -- CLI -----------------------------------------------------------------


def test_cli_forecast_eval_on_replay_trace(capsys):
    rc = main(["forecast-eval", "--trace", "data/replay_2day.npz",
               "--forecasters", "persistence,ridge", "--horizon", "8",
               "--stride", "512"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc["forecasters"]) == {"persistence", "ridge"}
    row = doc["forecasters"]["persistence"]
    assert row["n_windows"] > 0
    assert row["carbon_g_kwh"]["mape_h1"] >= 0


def test_cli_forecaster_rejected_for_non_mpc_backends():
    with pytest.raises(SystemExit, match="mpc"):
        main(["simulate", "--days", "0.01", "--backend", "rule",
              "--forecaster", "persistence"])
    with pytest.raises(SystemExit, match="mpc"):
        main(["run", "--backend", "carbon", "--forecaster", "ridge",
              "--ticks", "1"])


def test_cli_forecast_eval_unknown_forecaster():
    with pytest.raises(SystemExit, match="unknown forecaster"):
        main(["forecast-eval", "--trace", "data/replay_2day.npz",
              "--forecasters", "prophet"])


@pytest.mark.slow  # ISSUE 16 lane-time rule: compile-cache hygiene,
# not math; the e2e persistence representative stays fast.
def test_forecaster_compile_cache_keys_on_config(cfg, synth):
    """ISSUE 4 satellite (ARCHITECTURE §8): forecasters hash by
    (type, config), so a FRESH same-config instance is a compile-cache
    HIT on the jitted receding-horizon program — two MPCBackend
    instances share ONE compile instead of silently recompiling the
    whole closed loop per instance (the hazard `obs/compile.py` was
    built to detect, now closed at the cache key itself)."""
    from ccka_tpu.obs.compile import stats_for
    from ccka_tpu.sim.rollout import initial_state
    from ccka_tpu.train.mpc import MPCBackend

    # The equality/hash contract itself (host-side).
    assert make_forecaster("ridge") == make_forecaster("ridge")
    assert hash(make_forecaster("ridge")) == hash(make_forecaster("ridge"))
    assert (make_forecaster("seasonal", dt_s=30.0)
            == make_forecaster("seasonal", dt_s=30.0))
    assert (make_forecaster("seasonal", dt_s=30.0)
            != make_forecaster("seasonal", dt_s=60.0))
    assert make_forecaster("persistence") != make_forecaster("ridge")
    assert RidgeARForecaster(lags=4) != RidgeARForecaster(lags=8)

    # Same statics as the jitted end-to-end test above, with two FRESH
    # ridge instances — in the full lane the first run is itself a
    # cache hit on that test's compile.
    trace = synth.trace(32, seed=1)

    def run():
        fc = make_forecaster("ridge", dt_s=cfg.sim.dt_s)
        backend = MPCBackend(cfg, horizon=8, iters=2, replan_every=8,
                             forecaster=fc, history_steps=32)
        backend.evaluate(initial_state(cfg), trace, jax.random.key(0),
                         stochastic=False)

    run()
    st = stats_for("mpc.receding_horizon_rollout")
    before = st.compiles
    run()
    assert st.compiles == before, (
        "a fresh same-config forecaster re-keyed the receding-horizon "
        "compile cache (instance-identity hashing is back)")
