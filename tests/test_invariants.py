"""Property-style fuzz tests: simulator and projection invariants.

SURVEY §4 prescribes "property tests on policy invariants" as part of the
test substrate the reference lacked. These fuzz randomized actions,
states and exogenous inputs through the dynamics and the feasibility
projection and assert the invariants that must hold for *any* input —
the safety net under the learned backends, whose outputs are arbitrary
before projection.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config, multi_region_config
from ccka_tpu.policy import project_feasible
from ccka_tpu.policy.constraints import CONSOLIDATE_AFTER_MAX_S
from ccka_tpu.sim import CT_OD, CT_SPOT, SimParams, initial_state, step
from ccka_tpu.sim.dynamics import ExoStep
from ccka_tpu.sim.types import Action

N_FUZZ = 64


def _random_action(key, n_pools, n_zones, scale=5.0):
    ks = jax.random.split(key, 5)
    # Deliberately out-of-domain magnitudes: the projection must tame them.
    return Action(
        zone_weight=scale * jax.random.normal(ks[0], (n_pools, n_zones)),
        ct_allow=scale * jax.random.normal(ks[1], (n_pools, 2)),
        consolidation_aggr=scale * jax.random.normal(ks[2], (n_pools,)),
        consolidate_after_s=1e4 * jax.random.normal(ks[3], (n_pools,)),
        hpa_scale=scale * jax.random.normal(ks[4], (2,)),
    )


def _random_exo(key, n_zones):
    ks = jax.random.split(key, 4)
    return ExoStep(
        spot_price_hr=jax.random.uniform(ks[0], (n_zones,), minval=0.005,
                                         maxval=0.09),
        od_price_hr=jnp.full((n_zones,), 0.096),
        carbon_g_kwh=jax.random.uniform(ks[1], (n_zones,), minval=50.0,
                                        maxval=900.0),
        demand_pods=jax.random.uniform(ks[2], (2,), minval=0.0,
                                       maxval=300.0),
        is_peak=(jax.random.uniform(ks[3], ()) > 0.5).astype(jnp.float32),
    )


@pytest.fixture(scope="module", params=["single", "multi"])
def cfg(request):
    return (default_config() if request.param == "single"
            else multi_region_config())


class TestProjectionInvariants:
    def test_any_action_projects_feasible(self, cfg):
        cl = cfg.cluster
        for i in range(N_FUZZ):
            a = project_feasible(
                _random_action(jax.random.key(i), cl.n_pools, cl.n_zones),
                cl)
            zw = np.asarray(a.zone_weight)
            assert ((0.0 <= zw) & (zw <= 1.0)).all()
            # Never an unsatisfiable zone requirement.
            assert (zw.sum(axis=-1) > 0).all()
            ct = np.asarray(a.ct_allow)
            assert ((0.0 <= ct) & (ct <= 1.0)).all()
            for p, pool in enumerate(cl.pools):
                # Intrinsic capacity types only (Kyverno guarantee):
                # the SLO pool can never offer spot...
                if "spot" not in pool.capacity_types:
                    assert ct[p, CT_SPOT] == 0.0
                # ...and SLO pools always offer on-demand.
                if pool.strategy == "slo":
                    assert ct[p, CT_OD] >= 1.0 - 1e-6
            after = np.asarray(a.consolidate_after_s)
            assert ((0.0 <= after)
                    & (after <= CONSOLIDATE_AFTER_MAX_S)).all()
            hpa = np.asarray(a.hpa_scale)
            assert ((0.1 <= hpa) & (hpa <= 4.0)).all()


class TestDynamicsInvariants:
    def test_step_preserves_physical_invariants(self, cfg):
        """For any projected action and any sane exogenous tick, one step
        must keep the state physical: non-negative fleet/pipeline,
        serving bounded by demand-target, finite accounting that only
        accumulates forward."""
        params = SimParams.from_config(cfg)
        cl = cfg.cluster
        jstep = jax.jit(lambda s, a, e, k: step(params, s, a, e, k,
                                                stochastic=True))
        state = initial_state(cfg)
        for i in range(N_FUZZ):
            k = jax.random.key(1000 + i)
            ka, ke, ks = jax.random.split(k, 3)
            action = project_feasible(
                _random_action(ka, cl.n_pools, cl.n_zones), cl)
            exo = _random_exo(ke, cl.n_zones)
            prev = state
            state, m = jstep(state, action, exo, ks)

            assert (np.asarray(state.nodes) >= 0).all()
            assert (np.asarray(state.pipeline) >= 0).all()
            assert (np.asarray(state.running) >= -1e-5).all()
            # Serving never exceeds the HPA-scaled target.
            target = np.asarray(exo.demand_pods) * np.asarray(
                action.hpa_scale)
            assert (np.asarray(state.running) <= target + 1e-3).all()
            # Pool caps respected (active + in-flight).
            pool_total = (np.asarray(state.nodes).sum(axis=(1, 2))
                          + np.asarray(state.pipeline).sum(axis=(0, 2, 3)))
            assert (pool_total <= np.asarray(params.max_nodes) + 1e-3).all()
            # Accounting is finite and monotone.
            for field in ("acc_cost_usd", "acc_carbon_g", "acc_requests",
                          "acc_slo_ok_s", "acc_evictions"):
                now = float(getattr(state, field))
                assert np.isfinite(now)
                assert now >= float(getattr(prev, field)) - 1e-6
            # Tick metrics are physical too.
            assert float(m.cost_usd) >= 0.0
            assert float(m.carbon_g) >= 0.0
            assert float(m.latency_p95_ms) >= 0.0
            assert 0.0 <= float(m.slo_ok) <= 1.0

    def test_workload_queue_conservation(self, cfg):
        """Per-family queue conservation (ISSUE 6): for every fuzzed
        tick, arrivals − served − dropped == Δqueue — EXACT in f32
        accounting for the inference queue (the test replays the step's
        own f32 op order bit-for-bit), and to f32-rounding tolerance
        for the bucketed batch pipeline / background backlog (their
        deltas sum across buckets, so only the per-op roundings
        differ)."""
        import dataclasses

        from ccka_tpu.config import WorkloadsConfig
        from ccka_tpu.workloads.types import WorkloadState, WorkloadStep

        wl_cfg = WorkloadsConfig(enabled=True, inference_queue_max=12.0,
                                 batch_deadline_ticks=5)
        params = SimParams.from_config(
            dataclasses.replace(cfg, workloads=wl_cfg))
        cl = cfg.cluster
        jstep = jax.jit(lambda s, a, e, w, ws, k: step(
            params, s, a, e, k, stochastic=True, workload=w, wl_state=ws))
        state = initial_state(cfg)
        ws = WorkloadState.zero(int(params.wl_batch_deadline_ticks))
        f32 = np.float32
        for i in range(N_FUZZ):
            k = jax.random.key(2000 + i)
            ka, ke, kw, ks = jax.random.split(k, 4)
            action = project_feasible(
                _random_action(ka, cl.n_pools, cl.n_zones), cl)
            exo = _random_exo(ke, cl.n_zones)
            r = jax.random.uniform(kw, (3,), minval=0.0, maxval=25.0)
            wl = WorkloadStep(inf_arrivals=r[0], batch_arrivals=r[1],
                              bg_arrivals=r[2])
            prev = ws
            state, m, ws = jstep(state, action, exo, wl, ws, ks)

            # Inference: EXACT f32 replay of the step's op order
            # q2 = ((q + a) − served) − dropped.
            in_q = f32(f32(prev.inf_queue) + f32(r[0]))
            q2 = f32(f32(in_q - f32(m.inf_served)) - f32(m.inf_dropped))
            assert q2 == f32(ws.inf_queue), i
            assert float(ws.inf_queue) <= 12.0 + 1e-4

            # Batch: arrivals − served − missed == Δbacklog (f64 over
            # the f32 bucket values; per-bucket roundings only).
            d_bl = (np.asarray(ws.batch_backlog, np.float64).sum()
                    - np.asarray(prev.batch_backlog, np.float64).sum())
            lhs = (float(r[1]) - float(m.batch_served)
                   - float(m.batch_deadline_miss))
            assert abs(lhs - d_bl) < 1e-3 * max(1.0, abs(lhs)), i
            # The aged-out slot is always drained (state invariant).
            assert float(np.asarray(ws.batch_backlog)[-1]) == 0.0

            # Background: backlog only ever grows by at most arrivals.
            d_bg = float(ws.bg_backlog) - float(prev.bg_backlog)
            assert d_bg <= float(r[2]) + 1e-4
            assert float(ws.bg_backlog) >= -1e-6

            # Counters physical and finite.
            for fname in ("inf_queue_depth", "inf_served", "inf_dropped",
                          "batch_backlog", "batch_served",
                          "batch_deadline_miss", "bg_backlog"):
                v = float(getattr(m, fname))
                assert np.isfinite(v) and v >= -1e-6, fname
            assert float(m.inf_slo_violation) in (0.0, 1.0)

    def test_no_nan_under_degenerate_inputs(self, cfg):
        """Zero demand, zero prices... the step must stay finite (guards
        against division blowups in utilization/latency/accounting)."""
        params = SimParams.from_config(cfg)
        cl = cfg.cluster
        z = cl.n_zones
        exo = ExoStep(
            spot_price_hr=jnp.zeros((z,)), od_price_hr=jnp.zeros((z,)),
            carbon_g_kwh=jnp.zeros((z,)), demand_pods=jnp.zeros((2,)),
            is_peak=jnp.float32(0.0))
        action = project_feasible(Action.neutral(cl.n_pools, z), cl)
        state, m = step(params, initial_state(cfg), action, exo,
                        jax.random.key(0), stochastic=True)
        for leaf in jax.tree.leaves(state) + jax.tree.leaves(m):
            assert np.isfinite(np.asarray(leaf)).all()
