"""Parity gate for the Pallas rollout megakernel (`sim/megakernel.py`).

VERDICT r3 #2's condition for the kernel becoming the bench path: parity
with the lax rollout on EVERY quality metric. Two tiers:

- **CPU lane (interpret mode, deterministic)**: the kernel's math is
  EXACTLY the lax dynamics (float-association tolerance ~1e-5) —
  per-cluster, every EpisodeSummary field, including with time-padding
  and multiple batch blocks.
- **TPU lane (`-m tpu`)**: on real Mosaic-compiled code, per-trajectory
  parity is impossible by construction — the dynamics are chaotic (sharp
  consolidation/SLO gates) and Mosaic's transcendental ULPs differ from
  XLA's, so individual threshold events flip. The gate is therefore
  distribution-level: batch-mean parity on every field, deterministic
  AND stochastic (the kernel's pltpu PRNG vs the lax threefry stream),
  with tolerances far below the effect sizes the scoreboard measures
  (measured round-4: means agree to ~0.05% core / ~1% on rare-event
  counters at B=8192 x one day).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.policy import RulePolicy
from ccka_tpu.policy.rule import offpeak_action, peak_action
from ccka_tpu.sim import SimParams, initial_state
from ccka_tpu.sim.megakernel import (carbon_megakernel_rollout_summary,
                                     kernel_numerics_action_fn,
                                     mean_parity_violations,
                                     megakernel_rollout_summary,
                                     neural_megakernel_rollout_summary)
from ccka_tpu.sim.rollout import batched_rollout_summary
from ccka_tpu.signals.synthetic import SyntheticSignalSource


def _perturbed_net_params(cfg, seed: int = 3, scale: float = 0.3):
    """ActorCritic params with non-trivial weights (a zero-init head
    would emit the same action everywhere and mask layout bugs)."""
    from ccka_tpu.models import ActorCritic, latent_dim
    from ccka_tpu.sim.megakernel import _obs_dim

    import zlib

    net = ActorCritic(act_dim=latent_dim(cfg.cluster))
    key = jax.random.key(seed)
    p0 = net.init(key, jnp.zeros(
        (_obs_dim(cfg.cluster.n_pools, cfg.cluster.n_zones),)))
    # crc32, not hash(): PYTHONHASHSEED would make the perturbation —
    # and thus the parity deltas — vary between pytest runs.
    return jax.tree_util.tree_map_with_path(
        lambda path, x: x + scale * jax.random.normal(
            jax.random.fold_in(key, zlib.crc32(str(path).encode())
                               % (2 ** 31)), x.shape),
        p0)


@pytest.fixture(scope="module")
def cfg():
    return default_config()


@pytest.fixture(scope="module")
def setup(cfg):
    params = SimParams.from_config(cfg)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    return params, src, offpeak_action(cfg.cluster), peak_action(cfg.cluster)


def _lax_summary(cfg, params, traces, *, stochastic):
    b = traces.is_peak.shape[0]
    states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                          initial_state(cfg))
    keys = jax.random.split(jax.random.key(0), b)
    _, summary = batched_rollout_summary(
        params, states, RulePolicy(cfg.cluster).action_fn(), traces, keys,
        stochastic=stochastic)
    return summary


def _field_rel(sk, sl, reduce=None):
    out = {}
    for f in sk._fields:
        a = np.asarray(getattr(sk, f)).astype(np.float64)
        b = np.asarray(getattr(sl, f)).astype(np.float64)
        if reduce == "mean":
            a, b = a.mean(), b.mean()
        out[f] = float(np.max(np.abs(a - b) / (np.abs(b) + 1e-6)))
    return out


class TestInterpretExactParity:
    """Kernel math == lax dynamics, bit-for-bit up to float association."""

    def test_every_field_exact(self, cfg, setup):
        params, src, off, peak = setup
        traces = src.batch_trace_device(96, jax.random.key(7), 128)
        sk = megakernel_rollout_summary(params, off, peak, traces,
                                        stochastic=False, b_block=128,
                                        t_chunk=32, interpret=True)
        sl = _lax_summary(cfg, params, traces, stochastic=False)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 2e-3}
        assert not bad, f"interpret parity broken: {bad}"

    @pytest.mark.slow  # ISSUE 16 lane-time rule: padding masking rides the
    # every-field interpret exactness proof that stays fast.
    def test_time_padding_masks_extra_ticks(self, cfg, setup):
        """T not divisible by t_chunk: padded ticks must contribute
        nothing (same result as the unpadded lax run)."""
        params, src, off, peak = setup
        traces = src.batch_trace_device(40, jax.random.key(3), 128)
        sk = megakernel_rollout_summary(params, off, peak, traces,
                                        stochastic=False, b_block=128,
                                        t_chunk=32, interpret=True)
        sl = _lax_summary(cfg, params, traces, stochastic=False)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 2e-3}
        assert not bad, f"padding corrupted the rollout: {bad}"
        # hours reflect the TRUE horizon, not the padded one.
        np.testing.assert_allclose(np.asarray(sk.hours),
                                   40 * cfg.sim.dt_s / 3600.0)

    @pytest.mark.slow  # ISSUE 14 lane-time rule (~9s): batch-block
    # independence is re-proven fast-lane by every multi-block parity
    # run and by the streaming chunked==unblocked bitwise gates, whose
    # cluster-chunk groups are exactly these blocks.
    def test_multiple_batch_blocks_are_independent(self, cfg, setup):
        """Scratch state must reset between batch blocks: running two
        blocks must equal each block run alone."""
        params, src, off, peak = setup
        traces = src.batch_trace_device(64, jax.random.key(5), 256)
        both = megakernel_rollout_summary(params, off, peak, traces,
                                          stochastic=False, b_block=128,
                                          t_chunk=32, interpret=True)
        second = jax.tree.map(lambda x: x[128:], traces)
        alone = megakernel_rollout_summary(params, off, peak, second,
                                           stochastic=False, b_block=128,
                                           t_chunk=32, interpret=True)
        np.testing.assert_allclose(np.asarray(both.cost_usd)[128:],
                                   np.asarray(alone.cost_usd), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(both.slo_attainment)[128:],
                                   np.asarray(alone.slo_attainment),
                                   rtol=1e-6)

    @pytest.mark.slow  # round 10 lane budget: a Z=4 topology repin of
    # the same numerics test_short_horizon_exact pins at Z=3 (~21s of
    # compiles); the multiregion neural kernel is additionally exercised
    # and recorded by bench_quality_mega / bench_faults.
    def test_multiregion_topology_exact(self):
        """Z=4 (multiregion preset): exo/action row offsets are computed
        from the topology, not hard-coded for the 3-zone default."""
        from ccka_tpu.config import multi_region_config

        mcfg = multi_region_config()
        params = SimParams.from_config(mcfg)
        src = SyntheticSignalSource(mcfg.cluster, mcfg.workload, mcfg.sim,
                                    mcfg.signals)
        traces = src.batch_trace_device(48, jax.random.key(2), 128)
        sk = megakernel_rollout_summary(
            params, offpeak_action(mcfg.cluster), peak_action(mcfg.cluster),
            traces, stochastic=False, b_block=128, t_chunk=16,
            interpret=True)
        sl = _lax_summary(mcfg, params, traces, stochastic=False)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 2e-3}
        assert not bad, f"Z=4 parity broken: {bad}"

    def test_rejects_misaligned_batch(self, cfg, setup):
        params, src, off, peak = setup
        traces = src.batch_trace_device(8, jax.random.key(1), 96)
        with pytest.raises(ValueError, match="B %"):
            megakernel_rollout_summary(params, off, peak, traces,
                                       b_block=128, interpret=True)


class TestCarbonKernelParity:
    """`policy="carbon"`: CarbonAwarePolicy fused in-kernel — all-f32
    formulas, so interpret mode is exact like the rule path."""

    def test_interpret_exact(self, cfg, setup):
        from ccka_tpu.policy import CarbonAwarePolicy

        params, src, off, peak = setup
        traces = src.batch_trace_device(96, jax.random.key(7), 128)
        sk = carbon_megakernel_rollout_summary(
            params, off, peak, traces, stochastic=False, b_block=128,
            t_chunk=32, interpret=True)
        b = 128
        states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                              initial_state(cfg))
        keys = jax.random.split(jax.random.key(0), b)
        _, sl = batched_rollout_summary(
            params, states, CarbonAwarePolicy(cfg.cluster).action_fn(),
            traces, keys, stochastic=False)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 2e-3}
        assert not bad, f"carbon kernel parity broken: {bad}"

    def test_policy_constants_thread_through(self, cfg, setup):
        """Non-default sharpness/stickiness must change the rollout (the
        statics actually reach the fused policy)."""
        params, src, off, peak = setup
        traces = src.batch_trace_device(64, jax.random.key(9), 128)
        a = carbon_megakernel_rollout_summary(
            params, off, peak, traces, stochastic=False, b_block=128,
            t_chunk=32, interpret=True)
        b = carbon_megakernel_rollout_summary(
            params, off, peak, traces, stochastic=False, b_block=128,
            t_chunk=32, interpret=True, sharpness=40.0, stickiness=0.0)
        assert float(np.max(np.abs(
            np.asarray(a.carbon_kg) - np.asarray(b.carbon_kg)))) > 0


class TestNeuralKernelParity:
    """`policy="mlp"`: the deterministic ActorCritic policy fused
    in-kernel. The MLP forward is bit-identical to the packed-weights
    lax helper (`kernel_numerics_action_fn`), but a FEEDBACK policy
    amplifies float-association noise through the state→obs→net loop,
    so exact parity holds only at short horizons; long horizons get the
    batch-mean gate (same structure as the on-chip contract)."""

    @pytest.mark.slow  # ISSUE 16 lane-time rule: neural parity keeps its
    # sharded + streaming representatives in the slow lane too.
    def test_short_horizon_exact(self, cfg, setup):
        params, src, _, _ = setup
        net_params = _perturbed_net_params(cfg)
        traces = src.batch_trace_device(32, jax.random.key(7), 128)
        sk = neural_megakernel_rollout_summary(
            params, cfg.cluster, net_params, traces, stochastic=False,
            b_block=128, t_chunk=16, interpret=True)
        b = 128
        states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                              initial_state(cfg))
        keys = jax.random.split(jax.random.key(0), b)
        _, sl = batched_rollout_summary(
            params, states,
            kernel_numerics_action_fn(net_params, cfg.cluster, params),
            traces, keys, stochastic=False)
        rel = _field_rel(sk, sl)
        # Threshold-gated counters divide by near-zero short-horizon
        # totals, so association noise reads as percents there; the
        # interruption-path aggregates (interruptions, and the spot
        # exposure/waste fractions it feeds) share that near-zero-
        # denominator sensitivity at 32 ticks — measured ~0.25% on a
        # CPU interpret-mode host, pure accumulation-order noise. Core
        # fields stay at 1e-3; the full-day tests keep these strict.
        loose = {"evictions": 2e-2, "queue_depth_mean": 2e-2,
                 "interruptions": 2e-2, "spot_exposure": 2e-2,
                 "waste_frac": 2e-2}
        bad = {f: r for f, r in rel.items() if r > loose.get(f, 1e-3)}
        assert not bad, f"neural kernel exact parity broken: {bad}"

    @pytest.mark.slow  # round 10 lane budget: the distribution-level
    # flax repin duplicates test_short_horizon_exact's deterministic
    # numeric anchor at ~32s of compiles; bench's quality gates re-check
    # the kernel against lax at run time. Slow lane keeps it.
    def test_full_day_batch_mean_vs_flax(self, cfg, setup):
        """Against the REAL flax PPOBackend forward (not the helper):
        batch-mean parity on every field under the shared tolerance
        table — the same standard the bench gate applies on-chip."""
        from ccka_tpu.train.ppo import PPOBackend

        params, src, _, _ = setup
        net_params = _perturbed_net_params(cfg)
        traces = src.batch_trace_device(288, jax.random.key(11), 256)
        sk = neural_megakernel_rollout_summary(
            params, cfg.cluster, net_params, traces, stochastic=False,
            b_block=128, t_chunk=32, interpret=True)
        b = 256
        states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                              initial_state(cfg))
        keys = jax.random.split(jax.random.key(0), b)
        backend = PPOBackend(cfg, net_params)
        _, sl = batched_rollout_summary(
            params, states, backend.action_fn(), traces, keys,
            stochastic=False)
        bad = mean_parity_violations(sk, sl)
        assert not bad, f"neural batch-mean parity broken: {bad}"

    @pytest.mark.slow  # round 10 lane budget: a Z=4 topology repin of
    # the same numerics test_short_horizon_exact pins at Z=3 (~21s of
    # compiles); the multiregion neural kernel is additionally exercised
    # and recorded by bench_quality_mega / bench_faults.
    def test_multiregion_topology(self):
        """Z=4, latent dim 18 (padded to 24): dims are computed from the
        topology, not hard-coded for the default."""
        from ccka_tpu.config import multi_region_config

        mcfg = multi_region_config()
        params = SimParams.from_config(mcfg)
        src = SyntheticSignalSource(mcfg.cluster, mcfg.workload, mcfg.sim,
                                    mcfg.signals)
        net_params = _perturbed_net_params(mcfg)
        traces = src.batch_trace_device(32, jax.random.key(2), 128)
        sk = neural_megakernel_rollout_summary(
            params, mcfg.cluster, net_params, traces, stochastic=False,
            b_block=128, t_chunk=16, interpret=True)
        b = 128
        states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                              initial_state(mcfg))
        keys = jax.random.split(jax.random.key(0), b)
        _, sl = batched_rollout_summary(
            params, states,
            kernel_numerics_action_fn(net_params, mcfg.cluster, params),
            traces, keys, stochastic=False)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 1e-3}
        assert not bad, f"Z=4 neural parity broken: {bad}"

    @pytest.mark.slow  # ISSUE 14 lane-time rule (~9s): the population
    # fan-out is re-proven fast-lane by the sharded neural entry parity
    # (test_sharded_kernel) and by every cem_refine-driven refinement
    # test, whose ES generations run THIS population kernel.
    def test_population_axis(self, cfg, setup):
        """Stacked candidates: one launch, [NP, B] fields; member 0
        equals the single-pytree run (paired worlds) and a genuinely
        different member produces different KPIs."""
        params, src, _, _ = setup
        p0 = _perturbed_net_params(cfg)
        p1 = jax.tree.map(lambda x: x * 0.5, p0)
        stacked = jax.tree.map(lambda a, b: jnp.stack([a, b]), p0, p1)
        traces = src.batch_trace_device(48, jax.random.key(5), 128)
        pop = neural_megakernel_rollout_summary(
            params, cfg.cluster, stacked, traces, stochastic=False,
            b_block=128, t_chunk=16, interpret=True)
        single = neural_megakernel_rollout_summary(
            params, cfg.cluster, p0, traces, stochastic=False,
            b_block=128, t_chunk=16, interpret=True)
        assert np.asarray(pop.cost_usd).shape[0] == 2
        np.testing.assert_allclose(np.asarray(pop.cost_usd)[0],
                                   np.asarray(single.cost_usd), rtol=1e-6)
        assert float(np.max(np.abs(np.asarray(pop.cost_usd)[1]
                                   - np.asarray(pop.cost_usd)[0]))) > 0

    def test_rejects_wrong_topology_net(self, cfg, setup):
        from ccka_tpu.config import multi_region_config

        params, src, _, _ = setup
        wrong = _perturbed_net_params(multi_region_config())
        traces = src.batch_trace_device(8, jax.random.key(1), 128)
        with pytest.raises(ValueError, match="obs dim"):
            neural_megakernel_rollout_summary(
                params, cfg.cluster, wrong, traces, b_block=128,
                interpret=True)


class TestPackedLayoutGeneration:
    """Traces generated DIRECTLY in the kernel's [T, rows, B] layout
    (`packed_trace_device`) — no [B, T] materialization, no transpose
    (ARCHITECTURE §6 lever)."""

    def test_packed_assembly_matches_pack_of_assemble(self, cfg):
        """Same noise through `_assemble_packed` and through
        `_assemble` + `_pack_exo` must agree exactly — the two layouts
        share their formulas by this pin, not by code."""
        from ccka_tpu.sim.megakernel import _pack_exo
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        T, B, Z = 96, 8, cfg.cluster.n_zones
        rng = np.random.default_rng(3)
        # [T, Z, B] noise for the packed path; transposed for the
        # batch-major assembler.
        n_spot = rng.standard_normal((T, Z, B)).astype(np.float32) * 0.04
        n_carb = rng.standard_normal((T, Z, B)).astype(np.float32) * 0.03
        n_dem = rng.standard_normal((T, B)).astype(np.float32) * 0.5
        packed = np.asarray(src._assemble_packed(
            T, 96, (jnp.asarray(n_spot), jnp.asarray(n_carb),
                    jnp.asarray(n_dem))))
        trace = src._assemble(
            T, (np.transpose(n_spot, (2, 0, 1)),
                np.transpose(n_carb, (2, 0, 1)),
                np.transpose(n_dem, (1, 0))), xp=np)
        via_pack = np.asarray(_pack_exo(
            jax.tree.map(jnp.asarray, trace), 96))
        np.testing.assert_allclose(packed, via_pack, rtol=1e-6, atol=1e-5)

    @pytest.mark.slow  # ISSUE 16 lane-time rule: duplicate of the
    # every-field interpret exactness proof that stays fast.
    def test_packed_kernel_path_matches_unpacked(self, cfg, setup):
        """`megakernel_summary_from_packed` on a packed stream equals
        the standard wrapper on its unpacked traces (deterministic,
        interpret mode)."""
        from ccka_tpu.sim.megakernel import (megakernel_summary_from_packed,
                                             unpack_exo)
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        params, _, off, peak = setup
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        T = 64
        packed = src.packed_trace_device(T, jax.random.key(9), 128,
                                         t_chunk=32)
        sk = megakernel_summary_from_packed(
            params, off, peak, packed, T, stochastic=False, b_block=128,
            t_chunk=32, interpret=True)
        traces = unpack_exo(packed, T, cfg.cluster.n_zones)
        ref = megakernel_rollout_summary(
            params, off, peak, traces, stochastic=False, b_block=128,
            t_chunk=32, interpret=True)
        rel = _field_rel(sk, ref)
        bad = {f: r for f, r in rel.items() if r > 1e-5}
        assert not bad, f"packed path diverged: {bad}"
        # And the unpacked traces drive the lax path to the same place.
        sl = _lax_summary(cfg, params, traces, stochastic=False)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 2e-3}
        assert not bad, f"packed-generated world diverged from lax: {bad}"

    def test_packed_rejects_mismatched_chunking(self, cfg, setup):
        from ccka_tpu.sim.megakernel import megakernel_summary_from_packed
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        params, _, off, peak = setup
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        packed = src.packed_trace_device(64, jax.random.key(1), 128,
                                         t_chunk=32)
        with pytest.raises(ValueError, match="t_chunk"):
            megakernel_summary_from_packed(params, off, peak, packed, 64,
                                           b_block=128, t_chunk=48,
                                           interpret=True)


@pytest.mark.tpu
class TestTPUDistributionParity:
    """Mosaic-compiled kernel vs lax path: batch-mean parity on every
    field, both modes (see module docstring for why per-trajectory
    parity is the wrong gate on-chip)."""

    @pytest.fixture(scope="class")
    def accel(self):
        devs = [d for d in jax.devices() if d.platform != "cpu"]
        if not devs:
            pytest.skip("no accelerator present")
        return devs[0]

    @pytest.mark.parametrize("stochastic", [False, True])
    def test_batch_mean_parity(self, cfg, setup, accel, stochastic):
        from ccka_tpu.sim.megakernel import mean_parity_violations

        params, src, off, peak = setup
        traces = src.batch_trace_device(960, jax.random.key(11), 2048)
        sk = megakernel_rollout_summary(params, off, peak, traces, seed=5,
                                        stochastic=stochastic)
        sl = _lax_summary(cfg, params, traces, stochastic=stochastic)
        bad = mean_parity_violations(sk, sl)   # the shared tolerance table
        assert not bad, f"distribution parity broken: {bad}"

    @pytest.mark.parametrize("stochastic", [False, True])
    def test_neural_batch_mean_parity(self, cfg, setup, accel, stochastic):
        """Mosaic-compiled mlp kernel vs the real flax PPOBackend on the
        lax path — the learned-policy variant of the pinned contract
        (fleet-shape diagnostics get the documented bf16-feedback
        latitude; every scoreboard field stays on the shared table)."""
        from ccka_tpu.sim.megakernel import NEURAL_MEAN_PARITY_TOLERANCES
        from ccka_tpu.train.ppo import PPOBackend

        params, src, _, _ = setup
        net_params = _perturbed_net_params(cfg)
        traces = src.batch_trace_device(960, jax.random.key(13), 2048)
        sk = neural_megakernel_rollout_summary(
            params, cfg.cluster, net_params, traces, seed=5,
            stochastic=stochastic)
        b = 2048
        states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                              initial_state(cfg))
        keys = jax.random.split(jax.random.key(0), b)
        _, sl = batched_rollout_summary(
            params, states, PPOBackend(cfg, net_params).action_fn(),
            traces, keys, stochastic=stochastic)
        bad = mean_parity_violations(sk, sl,
                                     NEURAL_MEAN_PARITY_TOLERANCES)
        assert not bad, f"neural distribution parity broken: {bad}"

    def test_carbon_batch_mean_parity(self, cfg, setup, accel):
        from ccka_tpu.policy import CarbonAwarePolicy

        params, src, off, peak = setup
        traces = src.batch_trace_device(960, jax.random.key(17), 2048)
        sk = carbon_megakernel_rollout_summary(
            params, off, peak, traces, seed=5, stochastic=True)
        b = 2048
        states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                              initial_state(cfg))
        keys = jax.random.split(jax.random.key(0), b)
        _, sl = batched_rollout_summary(
            params, states, CarbonAwarePolicy(cfg.cluster).action_fn(),
            traces, keys, stochastic=True)
        bad = mean_parity_violations(sk, sl)
        assert not bad, f"carbon distribution parity broken: {bad}"


class TestPlanPlaybackParity:
    """Plan-playback entry (ISSUE 4): a precomputed [T] / [B, T] action
    sequence executed instead of a policy — the MPC execution path. The
    contract is `rollout_actions` per cluster (interpret-exact here;
    the stochastic tier inherits the profile kernel's distribution gate
    through the bench's shared parity gate, which replays the rule
    profiles through this entry)."""

    @staticmethod
    def _decoded_plan(cfg, key, shape):
        from ccka_tpu.models import latent_dim, latent_to_action

        lat = 0.3 * jax.random.normal(
            key, shape + (latent_dim(cfg.cluster),))
        dec = lambda u: latent_to_action(u, cfg.cluster)  # noqa: E731
        for _ in shape:
            dec = jax.vmap(dec)
        return dec(lat)

    @pytest.mark.slow
    def test_broadcast_plan_matches_lax(self, cfg, setup):
        """Slow lane (840s budget): the per-cluster test below anchors
        the playback dynamics against lax; broadcast differs only in
        the act() source (SMEM scalars), and its sharded-vs-single
        consistency is pinned fast in test_sharded_kernel."""
        from ccka_tpu.sim.megakernel import plan_megakernel_rollout_summary

        params, src, _off, _peak = setup
        B, T = 128, 32
        traces = src.batch_trace_device(T, jax.random.key(5), B)
        acts = self._decoded_plan(cfg, jax.random.key(2), (T,))
        sk = plan_megakernel_rollout_summary(
            params, acts, traces, stochastic=False, b_block=128,
            t_chunk=32, interpret=True)
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (B,) + x.shape),
            initial_state(cfg))
        keys = jax.random.split(jax.random.key(0), B)
        afn = lambda state, exo, t: jax.tree.map(  # noqa: E731
            lambda a: a[t], acts)
        _, sl = batched_rollout_summary(params, states, afn, traces, keys,
                                        stochastic=False)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 2e-3}
        assert not bad, f"broadcast plan playback diverged: {bad}"

    def test_per_cluster_plan_matches_lax(self, cfg, setup):
        from ccka_tpu.sim.rollout import rollout_summary
        from ccka_tpu.sim.megakernel import plan_megakernel_rollout_summary

        params, src, _off, _peak = setup
        B, T = 128, 32
        traces = src.batch_trace_device(T, jax.random.key(7), B)
        acts = self._decoded_plan(cfg, jax.random.key(3), (B, T))
        sk = plan_megakernel_rollout_summary(
            params, acts, traces, stochastic=False, b_block=128,
            t_chunk=32, interpret=True)
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (B,) + x.shape),
            initial_state(cfg))
        keys = jax.random.split(jax.random.key(0), B)

        def run_one(s, a, tr, k):
            fn = lambda state, exo, t: jax.tree.map(  # noqa: E731
                lambda x: x[t], a)
            return rollout_summary(params, s, fn, tr, k,
                                   stochastic=False)[1]

        sl = jax.vmap(run_one)(states, acts, traces, keys)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 2e-3}
        assert not bad, f"per-cluster plan playback diverged: {bad}"
        # Distinct plans genuinely produce distinct outcomes (a zero
        # spread would mean the lane split never reached the kernel).
        assert float(np.std(np.asarray(sk.cost_usd))) > 0

    def test_rule_equivalent_plan_matches_profile_kernel(self, cfg, setup):
        """A per-cluster plan replaying the rule profile selection per
        (cluster, tick) is EXACTLY the profile kernel — same dynamics
        code, different action source; also pins the packed entry and
        the donation contract (exo consumed, plan NOT donated)."""
        import math

        from ccka_tpu.sim.megakernel import (
            _pack_exo, megakernel_summary_from_packed, pack_plan,
            plan_megakernel_summary_from_packed)

        params, src, off, peak = setup
        B, T, TC = 128, 32, 32
        traces = src.batch_trace_device(T, jax.random.key(11), B)
        is_peak = traces.is_peak > 0.5
        plan = jax.tree.map(
            lambda o, p: jnp.where(
                is_peak.reshape(is_peak.shape + (1,) * o.ndim), p, o),
            off, peak)
        T_pad = math.ceil(T / TC) * TC
        exo = _pack_exo(traces, T_pad)
        pp = pack_plan(plan, T_pad)
        kw = dict(stochastic=False, b_block=128, t_chunk=TC,
                  interpret=True)
        ref = megakernel_summary_from_packed(params, off, peak, exo, T,
                                             **kw)
        sk, stream = plan_megakernel_summary_from_packed(
            params, cfg.cluster, pp, exo, T, donate_stream=True, **kw)
        jax.block_until_ready(sk.cost_usd)
        assert exo.is_deleted(), "donated exo stream not consumed"
        assert not pp.is_deleted(), "plan stream must survive the launch"
        rel = _field_rel(sk, ref)
        bad = {f: r for f, r in rel.items() if r > 1e-6}
        assert not bad, f"rule-equivalent plan != profile kernel: {bad}"
        del stream

    def test_rejects_mismatched_plans(self, cfg, setup):
        import math

        from ccka_tpu.sim.megakernel import (
            _pack_exo, pack_plan, plan_megakernel_summary_from_packed,
            plan_megakernel_rollout_summary)

        params, src, _off, _peak = setup
        B, T, TC = 128, 32, 32
        traces = src.batch_trace_device(T, jax.random.key(13), B)
        acts_short = self._decoded_plan(cfg, jax.random.key(4), (T // 2,))
        with pytest.raises(ValueError, match="one action per tick"):
            plan_megakernel_rollout_summary(
                params, acts_short, traces, stochastic=False,
                b_block=128, t_chunk=TC, interpret=True)
        T_pad = math.ceil(T / TC) * TC
        exo = _pack_exo(traces, T_pad)
        acts = self._decoded_plan(cfg, jax.random.key(5), (B, T))
        good = pack_plan(acts, T_pad)
        with pytest.raises(ValueError, match="pack_plan"):
            plan_megakernel_summary_from_packed(
                params, cfg.cluster, good[:, :8], exo, T,
                stochastic=False, b_block=128, t_chunk=TC, interpret=True)
        with pytest.raises(ValueError, match="plan batch"):
            plan_megakernel_summary_from_packed(
                params, cfg.cluster, good[:, :, :64], exo, T,
                stochastic=False, b_block=128, t_chunk=TC, interpret=True)
