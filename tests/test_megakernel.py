"""Parity gate for the Pallas rollout megakernel (`sim/megakernel.py`).

VERDICT r3 #2's condition for the kernel becoming the bench path: parity
with the lax rollout on EVERY quality metric. Two tiers:

- **CPU lane (interpret mode, deterministic)**: the kernel's math is
  EXACTLY the lax dynamics (float-association tolerance ~1e-5) —
  per-cluster, every EpisodeSummary field, including with time-padding
  and multiple batch blocks.
- **TPU lane (`-m tpu`)**: on real Mosaic-compiled code, per-trajectory
  parity is impossible by construction — the dynamics are chaotic (sharp
  consolidation/SLO gates) and Mosaic's transcendental ULPs differ from
  XLA's, so individual threshold events flip. The gate is therefore
  distribution-level: batch-mean parity on every field, deterministic
  AND stochastic (the kernel's pltpu PRNG vs the lax threefry stream),
  with tolerances far below the effect sizes the scoreboard measures
  (measured round-4: means agree to ~0.05% core / ~1% on rare-event
  counters at B=8192 x one day).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.policy import RulePolicy
from ccka_tpu.policy.rule import offpeak_action, peak_action
from ccka_tpu.sim import SimParams, initial_state
from ccka_tpu.sim.megakernel import megakernel_rollout_summary
from ccka_tpu.sim.rollout import batched_rollout_summary
from ccka_tpu.signals.synthetic import SyntheticSignalSource


@pytest.fixture(scope="module")
def cfg():
    return default_config()


@pytest.fixture(scope="module")
def setup(cfg):
    params = SimParams.from_config(cfg)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    return params, src, offpeak_action(cfg.cluster), peak_action(cfg.cluster)


def _lax_summary(cfg, params, traces, *, stochastic):
    b = traces.is_peak.shape[0]
    states = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape),
                          initial_state(cfg))
    keys = jax.random.split(jax.random.key(0), b)
    _, summary = batched_rollout_summary(
        params, states, RulePolicy(cfg.cluster).action_fn(), traces, keys,
        stochastic=stochastic)
    return summary


def _field_rel(sk, sl, reduce=None):
    out = {}
    for f in sk._fields:
        a = np.asarray(getattr(sk, f)).astype(np.float64)
        b = np.asarray(getattr(sl, f)).astype(np.float64)
        if reduce == "mean":
            a, b = a.mean(), b.mean()
        out[f] = float(np.max(np.abs(a - b) / (np.abs(b) + 1e-6)))
    return out


class TestInterpretExactParity:
    """Kernel math == lax dynamics, bit-for-bit up to float association."""

    def test_every_field_exact(self, cfg, setup):
        params, src, off, peak = setup
        traces = src.batch_trace_device(96, jax.random.key(7), 128)
        sk = megakernel_rollout_summary(params, off, peak, traces,
                                        stochastic=False, b_block=128,
                                        t_chunk=32, interpret=True)
        sl = _lax_summary(cfg, params, traces, stochastic=False)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 2e-3}
        assert not bad, f"interpret parity broken: {bad}"

    def test_time_padding_masks_extra_ticks(self, cfg, setup):
        """T not divisible by t_chunk: padded ticks must contribute
        nothing (same result as the unpadded lax run)."""
        params, src, off, peak = setup
        traces = src.batch_trace_device(40, jax.random.key(3), 128)
        sk = megakernel_rollout_summary(params, off, peak, traces,
                                        stochastic=False, b_block=128,
                                        t_chunk=32, interpret=True)
        sl = _lax_summary(cfg, params, traces, stochastic=False)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 2e-3}
        assert not bad, f"padding corrupted the rollout: {bad}"
        # hours reflect the TRUE horizon, not the padded one.
        np.testing.assert_allclose(np.asarray(sk.hours),
                                   40 * cfg.sim.dt_s / 3600.0)

    def test_multiple_batch_blocks_are_independent(self, cfg, setup):
        """Scratch state must reset between batch blocks: running two
        blocks must equal each block run alone."""
        params, src, off, peak = setup
        traces = src.batch_trace_device(64, jax.random.key(5), 256)
        both = megakernel_rollout_summary(params, off, peak, traces,
                                          stochastic=False, b_block=128,
                                          t_chunk=32, interpret=True)
        second = jax.tree.map(lambda x: x[128:], traces)
        alone = megakernel_rollout_summary(params, off, peak, second,
                                           stochastic=False, b_block=128,
                                           t_chunk=32, interpret=True)
        np.testing.assert_allclose(np.asarray(both.cost_usd)[128:],
                                   np.asarray(alone.cost_usd), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(both.slo_attainment)[128:],
                                   np.asarray(alone.slo_attainment),
                                   rtol=1e-6)

    def test_multiregion_topology_exact(self):
        """Z=4 (multiregion preset): exo/action row offsets are computed
        from the topology, not hard-coded for the 3-zone default."""
        from ccka_tpu.config import multi_region_config

        mcfg = multi_region_config()
        params = SimParams.from_config(mcfg)
        src = SyntheticSignalSource(mcfg.cluster, mcfg.workload, mcfg.sim,
                                    mcfg.signals)
        traces = src.batch_trace_device(48, jax.random.key(2), 128)
        sk = megakernel_rollout_summary(
            params, offpeak_action(mcfg.cluster), peak_action(mcfg.cluster),
            traces, stochastic=False, b_block=128, t_chunk=16,
            interpret=True)
        sl = _lax_summary(mcfg, params, traces, stochastic=False)
        rel = _field_rel(sk, sl)
        bad = {f: r for f, r in rel.items() if r > 2e-3}
        assert not bad, f"Z=4 parity broken: {bad}"

    def test_rejects_misaligned_batch(self, cfg, setup):
        params, src, off, peak = setup
        traces = src.batch_trace_device(8, jax.random.key(1), 96)
        with pytest.raises(ValueError, match="B %"):
            megakernel_rollout_summary(params, off, peak, traces,
                                       b_block=128, interpret=True)


@pytest.mark.tpu
class TestTPUDistributionParity:
    """Mosaic-compiled kernel vs lax path: batch-mean parity on every
    field, both modes (see module docstring for why per-trajectory
    parity is the wrong gate on-chip)."""

    @pytest.fixture(scope="class")
    def accel(self):
        devs = [d for d in jax.devices() if d.platform != "cpu"]
        if not devs:
            pytest.skip("no accelerator present")
        return devs[0]

    @pytest.mark.parametrize("stochastic", [False, True])
    def test_batch_mean_parity(self, cfg, setup, accel, stochastic):
        from ccka_tpu.sim.megakernel import mean_parity_violations

        params, src, off, peak = setup
        traces = src.batch_trace_device(960, jax.random.key(11), 2048)
        sk = megakernel_rollout_summary(params, off, peak, traces, seed=5,
                                        stochastic=stochastic)
        sl = _lax_summary(cfg, params, traces, stochastic=stochastic)
        bad = mean_parity_violations(sk, sl)   # the shared tolerance table
        assert not bad, f"distribution parity broken: {bad}"
