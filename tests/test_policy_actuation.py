"""Golden tests: rule policy → rendered patches must byte-match the
reference's emitted JSON (the oracle format, SURVEY.md §4 "Implication"),
plus constraint projection and sink apply/verify/fallback semantics.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.actuation import (
    DryRunSink,
    KubectlSink,
    render_hpa_manifests,
    render_keda_scaledobject,
    render_nodepool_patches,
)
from ccka_tpu.actuation.patches import FALLBACK_PATH, PRIMARY_PATH
from ccka_tpu.config import default_config
from ccka_tpu.policy import (
    RulePolicy,
    offpeak_action,
    peak_action,
    project_feasible,
)
from ccka_tpu.sim import SimParams, initial_state, rollout, summarize
from ccka_tpu.signals import SyntheticSignalSource


@pytest.fixture(scope="module")
def cfg():
    return default_config()


# ---------------------------------------------------------------------------
# Golden patch JSON — oracle strings transcribed from the reference scripts.
# ---------------------------------------------------------------------------


def test_offpeak_disruption_merge_golden(cfg):
    ps = render_nodepool_patches(offpeak_action(cfg.cluster), cfg.cluster,
                                 op="replace")
    by_pool = {p.pool: p for p in ps}
    # demo_20_offpeak_configure.sh:59
    assert by_pool["spot-preferred"].disruption_merge == json.loads(
        '{"spec":{"disruption":{"consolidationPolicy":"WhenEmptyOrUnderutilized"}}}')
    # demo_20_offpeak_configure.sh:60
    assert by_pool["on-demand-slo"].disruption_merge == json.loads(
        '{"spec":{"disruption":{"consolidationPolicy":"WhenEmpty","consolidateAfter":"60s"}}}')


def test_offpeak_requirements_json_golden(cfg):
    ps = render_nodepool_patches(offpeak_action(cfg.cluster), cfg.cluster,
                                 op="replace")
    by_pool = {p.pool: p for p in ps}
    # demo_20_offpeak_configure.sh:69-79 with OFFPEAK_ZONES=us-east-2a
    # (demo_00_env.sh:22)
    assert by_pool["spot-preferred"].requirements_json == json.loads(
        '[{"op":"replace","path":"/spec/template/spec/requirements","value":['
        '{"key":"topology.kubernetes.io/zone","operator":"In","values":["us-east-2a"]},'
        '{"key":"karpenter.sh/capacity-type","operator":"In","values":["spot","on-demand"]}]}]')
    assert by_pool["on-demand-slo"].requirements_json == json.loads(
        '[{"op":"replace","path":"/spec/template/spec/requirements","value":['
        '{"key":"topology.kubernetes.io/zone","operator":"In","values":["us-east-2a"]},'
        '{"key":"karpenter.sh/capacity-type","operator":"In","values":["on-demand"]}]}]')


def test_peak_patches_golden(cfg):
    ps = render_nodepool_patches(peak_action(cfg.cluster), cfg.cluster,
                                 op="add")
    by_pool = {p.pool: p for p in ps}
    # demo_21_peak_configure.sh:56-57 — both pools WhenEmpty/120s
    for pool in ("spot-preferred", "on-demand-slo"):
        assert by_pool[pool].disruption_merge == json.loads(
            '{"spec":{"disruption":{"consolidationPolicy":"WhenEmpty","consolidateAfter":"120s"}}}')
    # demo_21:65-75 — op:add, PEAK_ZONES=us-east-2c (demo_00_env.sh:23)
    req = by_pool["spot-preferred"].requirements_json
    assert req[0]["op"] == "add"
    assert req[0]["path"] == "/spec/template/spec/requirements"
    assert req[0]["value"][0]["values"] == ["us-east-2c"]
    assert req[0]["value"][1]["values"] == ["spot", "on-demand"]
    assert by_pool["on-demand-slo"].requirements_json[0]["value"][1][
        "values"] == ["on-demand"]


def test_fallback_patch_path(cfg):
    ps = render_nodepool_patches(offpeak_action(cfg.cluster), cfg.cluster)
    assert ps[0].requirements_json_fallback[0]["path"] == \
        "/spec/template/requirements"  # demo_20:87,110


# ---------------------------------------------------------------------------
# Rule policy behavior
# ---------------------------------------------------------------------------


def test_rule_policy_switches_on_peak_signal(cfg):
    policy = RulePolicy(cfg.cluster)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim, cfg.signals)
    tr = src.trace(2880, seed=0)
    params = SimParams.from_config(cfg)
    final, metrics = rollout(params, initial_state(cfg), policy.action_fn(),
                             tr, jax.random.key(0))
    s = summarize(params, metrics)
    assert float(s.cost_usd) > 0
    assert np.all(np.isfinite(np.asarray(jax.tree.leaves(s)[0])))


def test_rule_policy_is_traceable_and_matches_profiles(cfg):
    policy = RulePolicy(cfg.cluster)
    from ccka_tpu.sim.dynamics import ExoStep
    z = cfg.cluster.n_zones

    def exo(is_peak):
        return ExoStep(
            spot_price_hr=jnp.zeros((z,)), od_price_hr=jnp.zeros((z,)),
            carbon_g_kwh=jnp.zeros((z,)), demand_pods=jnp.zeros((2,)),
            is_peak=jnp.float32(is_peak))

    decide = jax.jit(policy.decide)
    st = initial_state(cfg)
    a_off = decide(st, exo(0.0), jnp.int32(0))
    a_peak = decide(st, exo(1.0), jnp.int32(0))
    assert np.allclose(np.asarray(a_off.consolidate_after_s),
                       np.asarray(offpeak_action(cfg.cluster).consolidate_after_s))
    assert np.allclose(np.asarray(a_peak.zone_weight),
                       np.asarray(peak_action(cfg.cluster).zone_weight))


# ---------------------------------------------------------------------------
# Constraint projection (Kyverno guardrails, 04_kyverno.sh)
# ---------------------------------------------------------------------------


def test_project_feasible_od_pool_never_spot(cfg):
    a = offpeak_action(cfg.cluster)._replace(
        ct_allow=jnp.ones((2, 2), jnp.float32))  # try to allow spot everywhere
    p = project_feasible(a, cfg.cluster)
    od_idx = cfg.cluster.pool_index("on-demand-slo")
    assert float(p.ct_allow[od_idx, 0]) == 0.0  # spot stripped
    assert float(p.ct_allow[od_idx, 1]) == 1.0


def test_project_feasible_slo_pool_keeps_od(cfg):
    a = offpeak_action(cfg.cluster)._replace(
        ct_allow=jnp.zeros((2, 2), jnp.float32))  # try to disallow everything
    p = project_feasible(a, cfg.cluster)
    od_idx = cfg.cluster.pool_index("on-demand-slo")
    assert float(p.ct_allow[od_idx, 1]) == 1.0  # critical capacity guaranteed


def test_project_feasible_zone_collapse_resets(cfg):
    a = offpeak_action(cfg.cluster)._replace(
        zone_weight=jnp.zeros((2, 3), jnp.float32))
    p = project_feasible(a, cfg.cluster)
    assert np.all(np.asarray(p.zone_weight) == 1.0)


def test_project_feasible_hpa_bounded(cfg):
    a = offpeak_action(cfg.cluster)._replace(
        hpa_scale=jnp.asarray([0.0, 100.0], jnp.float32))
    p = project_feasible(a, cfg.cluster)
    assert float(p.hpa_scale[0]) == pytest.approx(0.1)
    assert float(p.hpa_scale[1]) == pytest.approx(4.0)


def test_projection_is_differentiable(cfg):
    def loss(x):
        a = offpeak_action(cfg.cluster)._replace(zone_weight=x)
        return project_feasible(a, cfg.cluster).zone_weight.sum()

    g = jax.grad(loss)(jnp.full((2, 3), 0.7, jnp.float32))
    assert np.all(np.isfinite(np.asarray(g)))


# ---------------------------------------------------------------------------
# Sinks
# ---------------------------------------------------------------------------


def test_dry_run_sink_applies_and_verifies(cfg):
    sink = DryRunSink()
    results = sink.apply_all(
        render_nodepool_patches(offpeak_action(cfg.cluster), cfg.cluster))
    assert all(r.ok for r in results)
    assert not any(r.used_fallback for r in results)
    # 2 pools × (merge + json) = 4 commands
    assert len(sink.commands) == 4
    assert sink.rendered()[0].startswith("kubectl patch nodepool spot-preferred")


def test_dry_run_sink_fallback_branch(cfg):
    sink = DryRunSink(schema_path=FALLBACK_PATH)
    results = sink.apply_all(
        render_nodepool_patches(peak_action(cfg.cluster), cfg.cluster, op="add"))
    assert all(r.ok for r in results)
    assert all(r.used_fallback for r in results)
    # merge + primary json (fails) + fallback json per pool
    assert len(sink.commands) == 6


def test_kubectl_sink_with_fake_runner(cfg):
    calls = []

    def runner(argv):
        calls.append(list(argv))
        if argv[:2] == ["kubectl", "get"]:
            return 0, "topology.kubernetes.io/zone=In:us-east-2a \n"
        return 0, "nodepool.karpenter.sh/spot-preferred patched"

    sink = KubectlSink(runner=runner)
    results = sink.apply_all(
        render_nodepool_patches(offpeak_action(cfg.cluster), cfg.cluster))
    assert all(r.ok and not r.used_fallback for r in results)
    patch_calls = [c for c in calls if c[:2] == ["kubectl", "patch"]]
    assert "--type=merge" in patch_calls[0]
    assert "--type=json" in patch_calls[1]


def test_kubectl_sink_fallback_on_empty_readback(cfg):
    state = {"applied_fallback": False}

    def runner(argv):
        if argv[:2] == ["kubectl", "get"]:
            # Primary jsonpath reads empty; fallback reads populated.
            if ".spec.template.spec." in argv[-1]:
                return 0, ""
            return 0, "karpenter.sh/capacity-type=In:on-demand \n"
        if "--type=json" in argv and "/spec/template/requirements" in argv[-1]:
            state["applied_fallback"] = True
        return 0, "ok"

    sink = KubectlSink(runner=runner)
    res = sink.apply_nodepool(
        render_nodepool_patches(offpeak_action(cfg.cluster), cfg.cluster)[0])
    assert res.ok and res.used_fallback
    assert state["applied_fallback"]


# ---------------------------------------------------------------------------
# HPA / KEDA gap-closers (§2.3)
# ---------------------------------------------------------------------------


def test_hpa_manifests(cfg):
    acts = offpeak_action(cfg.cluster)._replace(
        hpa_scale=jnp.asarray([2.0, 0.5], jnp.float32))
    hpas = render_hpa_manifests(acts, cfg.cluster, cfg.workload)
    assert len(hpas) == 2
    assert hpas[0]["kind"] == "HorizontalPodAutoscaler"
    assert hpas[0]["spec"]["maxReplicas"] == 60  # 30 per class × 2.0
    assert hpas[1]["spec"]["maxReplicas"] == 15  # 30 per class × 0.5
    assert hpas[0]["metadata"]["namespace"] == "nov-22"  # demo_00_env.sh:9


def test_keda_scaledobject(cfg):
    so = render_keda_scaledobject(offpeak_action(cfg.cluster), "burst-queue",
                              account_id="123456789012")
    assert so["kind"] == "ScaledObject"
    assert so["spec"]["triggers"][0]["type"] == "aws-sqs-queue"
    assert so["spec"]["triggers"][0]["metadata"]["awsRegion"] == "us-east-2"
    assert "123456789012" in so["spec"]["triggers"][0]["metadata"]["queueURL"]
    with pytest.raises(ValueError, match="account id"):
        render_keda_scaledobject(offpeak_action(cfg.cluster), "q", account_id="")


def test_reset_profile_never_grants_spot_to_slo_pool(cfg):
    # Live-cluster safety: even an unprojected all-ones action (the neutral
    # reset) must not patch the SLO pool to offer spot capacity
    # (04_kyverno.sh:47-75 critical-workload guarantee, enforced at render).
    from ccka_tpu.sim.types import Action
    ps = render_nodepool_patches(
        Action.neutral(cfg.cluster.n_pools, cfg.cluster.n_zones), cfg.cluster)
    by_pool = {p.pool: p for p in ps}
    cts = by_pool["on-demand-slo"].requirements_json[0]["value"][1]["values"]
    assert cts == ["on-demand"]


def test_lifecycle_verify_reads_back_from_sink(cfg):
    # A sink on the legacy schema path silently rejects primary-path-only
    # patches; verify() must catch that from the sink's observed state.
    from ccka_tpu.actuation.patches import FALLBACK_PATH as FB
    from ccka_tpu.harness import ConfigureObserve, Stage

    class DroppingSink(DryRunSink):
        """Accepts merges but silently drops requirements patches."""

        def _patch(self, cmd):
            if cmd.patch_type == "json":
                self.commands.append(cmd)
                return  # dropped on the floor
            super()._patch(cmd)

        def _readback_ok(self, pool, path_prefix):
            return True  # lies about apply success

    co = ConfigureObserve(DroppingSink())
    stage = Stage(
        name="offpeak",
        patchsets=render_nodepool_patches(offpeak_action(cfg.cluster),
                                          cfg.cluster),
        expect={"spot-preferred": ("WhenEmptyOrUnderutilized",
                                   ["spot", "on-demand"])})
    assert not co.run(stage)  # skeptical read-back catches the drop


def test_kubectl_sink_fails_when_merge_patch_rejected(cfg):
    # RBAC denial / admission rejection of the disruption merge must surface
    # as ok=False with detail, not a silent '[ok] applied'.
    def runner(argv):
        if "--type=merge" in argv:
            return 1, 'Error from server (Forbidden): nodepools "x" is forbidden'
        if argv[:2] == ["kubectl", "get"]:
            return 0, "karpenter.sh/capacity-type=In:on-demand \n"
        return 0, "ok"

    sink = KubectlSink(runner=runner)
    res = sink.apply_nodepool(
        render_nodepool_patches(offpeak_action(cfg.cluster), cfg.cluster)[0])
    assert not res.ok
    assert "merge patch rejected" in res.detail


class TestKyvernoGuardrailManifests:
    """04_kyverno.sh parity: the cluster-side ClusterPolicies themselves,
    matching the semantics the feasibility projection enforces client-side."""

    def test_require_requests_limits_shape(self):
        from ccka_tpu.actuation import render_require_requests_limits

        doc = render_require_requests_limits()
        assert doc["metadata"]["name"] == "require-requests-limits"
        assert doc["spec"]["validationFailureAction"] == "Enforce"
        pattern = doc["spec"]["rules"][0]["validate"]["pattern"]
        resources = pattern["spec"]["containers"][0]["resources"]
        assert set(resources["requests"]) == {"cpu", "memory"}
        assert set(resources["limits"]) == {"cpu", "memory"}

    def test_critical_no_spot_shape(self):
        from ccka_tpu.actuation import render_critical_no_spot
        from ccka_tpu.actuation.guardrails import EXCLUDED_NAMESPACES

        doc = render_critical_no_spot()
        rule = doc["spec"]["rules"][0]
        sel = rule["match"]["any"][0]["resources"]["selector"]
        assert sel["matchLabels"] == {"critical": "true"}
        excluded = rule["exclude"]["any"][0]["resources"]["namespaces"]
        assert set(excluded) == set(EXCLUDED_NAMESPACES)  # 04:66-69
        cond = rule["validate"]["deny"]["conditions"]["any"][0]
        assert "capacity-type" in cond["key"] and "spot" in cond["key"]

    def test_apply_and_burst_compliance(self, cfg):
        """Guardrails apply through the sink; the burst workload the
        framework generates satisfies both policies by construction."""
        from ccka_tpu.actuation import DryRunSink, apply_guardrails
        from ccka_tpu.actuation.burst import render_burst_deployments

        sink = DryRunSink()
        assert all(r.ok for r in apply_guardrails(sink))
        assert sink.get_object("ClusterPolicy", "require-requests-limits")

        for doc in render_burst_deployments(cfg.workload):
            pod = doc["spec"]["template"]["spec"]
            res = pod["containers"][0]["resources"]
            assert res["requests"] and res["limits"]  # policy 1
            labels = doc["spec"]["template"]["metadata"]["labels"]
            if labels.get("critical") == "true":      # policy 2 (vacuous
                assert all(t.get("key") != "karpenter.sh/capacity-type"
                           for t in pod["tolerations"])  # unless labeled)

    def test_cli_guardrails_json(self, capsys):
        from ccka_tpu.cli import main

        assert main(["guardrails", "--json"]) == 0
        docs = json.loads(capsys.readouterr().out)
        assert [d["metadata"]["name"] for d in docs] == [
            "require-requests-limits", "critical-no-spot-without-pdb"]
