"""TPU smoke lane: run with ``CCKA_TEST_TPU=1 python -m pytest -m tpu``.

The default CI lane never touches the accelerator (conftest forces CPU), so
bfloat16-torso numerics and real compile behavior would otherwise go
unexercised — the round-1 VERDICT called this out. These tests are skipped
unless the CCKA_TEST_TPU=1 lane is selected.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.models import ActorCritic, latent_dim
from ccka_tpu.policy import RulePolicy
from ccka_tpu.sim import SimParams, initial_state, rollout
from ccka_tpu.signals.synthetic import SyntheticSignalSource

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module")
def accel():
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if not devs:
        pytest.skip("no accelerator present")
    return devs[0]


def test_bfloat16_torso_forward(cfg, accel):
    """ActorCritic's bfloat16 torso runs on the chip and emits finite f32."""
    net = ActorCritic(act_dim=latent_dim(cfg.cluster))
    obs = jnp.ones((256, 29), jnp.float32)
    params = net.init(jax.random.key(0), obs[0])
    params, obs = jax.device_put((params, obs), accel)
    mean, log_std, value = jax.jit(net.apply)(params, obs)
    assert mean.dtype == jnp.float32 and value.dtype == jnp.float32
    for x in (mean, log_std, value):
        assert bool(jnp.isfinite(x).all())


def test_jitted_day_rollout_on_chip(cfg, accel):
    """One jitted rule-policy day rollout on the accelerator: finite, sane."""
    params = SimParams.from_config(cfg)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    trace = src.trace(2880)  # one day at 30s ticks
    action_fn = RulePolicy(cfg.cluster).action_fn()
    state0, key = jax.device_put(
        (initial_state(cfg), jax.random.key(0)), accel)
    final, _ = jax.jit(
        lambda s, k: rollout(params, s, action_fn, trace, k,
                             stochastic=True))(state0, key)
    cost = float(np.asarray(final.acc_cost_usd))
    assert np.isfinite(cost) and 1.0 < cost < 100.0
    assert float(final.acc_slo_ok_s) > 0.0


def test_fleet_summary_rollout_on_chip(cfg, accel):
    """The bench-headline path on the real chip: device-synthesized trace
    batch + summarize-in-scan fleet rollout, KPIs finite and sane."""
    from ccka_tpu.sim import batched_rollout_summary

    params = SimParams.from_config(cfg)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    b, t = 512, 2880
    traces = src.batch_trace_device(t, jax.random.key(7), b)
    states = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (b,) + x.shape), initial_state(cfg))
    keys = jax.random.split(jax.random.key(0), b)
    _, summary = jax.jit(
        lambda s, tr, k: batched_rollout_summary(
            params, s, RulePolicy(cfg.cluster).action_fn(), tr, k,
            stochastic=True))(states, traces, keys)
    cost = np.asarray(summary.cost_usd)
    assert cost.shape == (b,)
    assert np.isfinite(cost).all() and (cost > 0).all()
    slo = np.asarray(summary.slo_attainment)
    assert ((0.0 <= slo) & (slo <= 1.0 + 1e-6)).all()


def test_carbon_policy_on_chip(accel):
    """Multi-region carbon-aware decide + rollout on the accelerator."""
    from ccka_tpu.config import multi_region_config
    from ccka_tpu.policy import CarbonAwarePolicy
    from ccka_tpu.sim import rollout_summary

    mcfg = multi_region_config()
    params = SimParams.from_config(mcfg)
    src = SyntheticSignalSource(mcfg.cluster, mcfg.workload, mcfg.sim,
                                mcfg.signals)
    trace = src.forecast(1080, 720)  # daytime window
    fn = CarbonAwarePolicy(mcfg.cluster).action_fn()
    state0, key = jax.device_put(
        (initial_state(mcfg), jax.random.key(0)), accel)
    _, summary = jax.jit(
        lambda s, k: rollout_summary(params, s, fn, trace, k))(state0, key)
    assert np.isfinite(float(summary.g_co2_per_kreq))
    assert float(summary.slo_attainment) > 0.5


@pytest.mark.parametrize("preset", ["default", "multiregion"])
def test_flagship_checkpoints_decide_on_chip(accel, preset):
    """The SHIPPED flagship checkpoints drive decisions on the real chip:
    load the topology-keyed .npz, run one jitted decide, and assert the
    multiregion one's provenance records the dual win. Parametrized so a
    missing checkpoint skips only ITS topology, never the other's
    assertions."""
    import os

    from ccka_tpu.config import default_config, multi_region_config
    from ccka_tpu.sim.rollout import exo_steps
    from ccka_tpu.train.flagship import (flagship_checkpoint_path,
                                         load_flagship_backend)

    cfg = (default_config if preset == "default" else multi_region_config)()
    if not os.path.exists(flagship_checkpoint_path(cfg)):
        pytest.skip(f"no shipped checkpoint for {preset}")
    backend, meta = load_flagship_backend(cfg)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    exo = jax.tree.map(lambda x: x[0], exo_steps(src.trace(1)))
    state0, key = jax.device_put(
        (initial_state(cfg), jax.random.key(0)), accel)
    action = jax.jit(
        lambda s, e: backend.decide(s, e, jnp.int32(0)))(state0, exo)
    for leaf in jax.tree.leaves(action):
        assert bool(jnp.isfinite(leaf).all())
    if cfg.cluster.regions:
        assert meta["wins_both"] is True
        # Round-4 contract (VERDICT r3 #1): the shipped multiregion
        # flagship is a TRAINED winner — refinement moved it off the
        # distilled init before selection adopted it.
        assert meta["selected_iteration"] > 0
