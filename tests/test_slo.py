"""Latency/RPS/queue-depth SLO metrics (VERDICT row 16).

The reference advertises latency SLOs as autoscaler inputs (`README.md:21`,
proposal PDF p.1) but its pipeline scrapes only kube-state-metrics
(`06_opencost.sh:324-327`). These tests cover the realized version: the
simulator's queueing-curve p95 proxy + latency SLO gate, episode latency
KPIs, and the live PromQL client for measured p95/RPS/queue depth.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.sim import SimParams, initial_state, step, summarize
from ccka_tpu.sim.dynamics import ExoStep
from ccka_tpu.sim.types import Action


def _exo(cfg, demand):
    z = cfg.cluster.n_zones
    return ExoStep(
        spot_price_hr=jnp.full((z,), 0.035, jnp.float32),
        od_price_hr=jnp.full((z,), 0.096, jnp.float32),
        carbon_g_kwh=jnp.full((z,), 400.0, jnp.float32),
        demand_pods=jnp.asarray(demand, jnp.float32),
        is_peak=jnp.float32(0.0),
    )


def _neutral(cfg):
    return Action.neutral(cfg.cluster.n_pools, cfg.cluster.n_zones)


class TestLatencyProxy:
    def test_idle_near_base_overload_saturates(self):
        cfg = default_config()
        params = SimParams.from_config(cfg)
        s0 = initial_state(cfg)
        key = jax.random.key(0)

        # Near-idle: 2 pods on 27-pod base capacity → p95 ≈ base.
        _, light = step(params, s0, _neutral(cfg), _exo(cfg, [0.0, 2.0]), key)
        assert float(light.latency_p95_ms) < 1.3 * cfg.sim.latency_base_ms

        # Overload: demand far above capacity → saturated queueing curve,
        # far above base, and a deep pending backlog.
        _, heavy = step(params, s0, _neutral(cfg), _exo(cfg, [0.0, 200.0]),
                        key)
        assert float(heavy.latency_p95_ms) > 20 * cfg.sim.latency_base_ms
        assert float(heavy.queue_depth) > 150.0
        assert float(light.queue_depth) == pytest.approx(0.0)

    def test_latency_monotone_in_load(self):
        cfg = default_config()
        params = SimParams.from_config(cfg)
        s0 = initial_state(cfg)
        key = jax.random.key(0)
        p95s = [
            float(step(params, s0, _neutral(cfg), _exo(cfg, [0.0, d]),
                       key)[1].latency_p95_ms)
            for d in (2.0, 16.0, 24.0, 26.0)
        ]
        assert p95s == sorted(p95s)
        assert p95s[-1] > p95s[0]


class TestLatencySLOGate:
    def test_unenforceable_bound_rejected(self):
        """An SLO at/above the proxy's saturation ceiling (~145x base)
        could never trip — config validation must refuse it instead of
        silently disabling the gate."""
        from ccka_tpu.config import ConfigError
        with pytest.raises(ConfigError, match="saturation ceiling"):
            default_config().with_overrides(**{"sim.latency_slo_ms": 3000.0})
        # Just below the ceiling is allowed.
        default_config().with_overrides(**{"sim.latency_slo_ms": 2800.0})

    def test_disabled_by_default(self):
        cfg = default_config()
        assert cfg.sim.latency_slo_ms == 0.0
        params = SimParams.from_config(cfg)
        s0 = initial_state(cfg)
        # On-demand demand near base capacity (27): fully served, hot.
        _, m = step(params, s0, _neutral(cfg), _exo(cfg, [0.0, 26.0]),
                    jax.random.key(0))
        assert float(m.slo_ok) == 1.0  # served-fraction gate only

    def test_tight_bound_fails_hot_tick(self):
        cfg = default_config().with_overrides(**{"sim.latency_slo_ms": 40.0})
        params = SimParams.from_config(cfg)
        s0 = initial_state(cfg)
        key = jax.random.key(0)
        # Fully served but hot (ρ≈26/27 on base capacity): p95 breaches
        # the 40ms bound → SLO fails even though serving succeeded.
        _, hot = step(params, s0, _neutral(cfg), _exo(cfg, [0.0, 26.0]), key)
        assert float(hot.served_pods.sum()) == pytest.approx(26.0)
        assert float(hot.latency_p95_ms) > 40.0
        assert float(hot.slo_ok) == 0.0
        # Cool tick passes both gates.
        _, cool = step(params, s0, _neutral(cfg), _exo(cfg, [0.0, 2.0]), key)
        assert float(cool.slo_ok) == 1.0

    def test_episode_summary_carries_latency_kpis(self):
        cfg = default_config()
        params = SimParams.from_config(cfg)
        s0 = initial_state(cfg)
        key = jax.random.key(0)
        mets = []
        s = s0
        for d in (2.0, 26.0, 2.0):
            s, m = step(params, s, _neutral(cfg), _exo(cfg, [0.0, d]), key)
            mets.append(m)
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *mets)
        summ = summarize(params, stacked)
        assert float(summ.latency_p95_ms_max) >= float(
            summ.latency_p95_ms_mean) > 0.0
        assert float(summ.queue_depth_mean) >= 0.0


class TestSLOMetricsClient:
    def _client(self, responses):
        from ccka_tpu.signals.live import PrometheusClient, SLOMetricsClient

        def fetch(url, headers):
            for frag, payload in responses.items():
                if frag in url:
                    return json.dumps(payload).encode()
            return json.dumps({"status": "success",
                               "data": {"result": []}}).encode()

        return SLOMetricsClient(
            PrometheusClient("http://prom", fetch=fetch))

    @staticmethod
    def _instant(value):
        return {"status": "success", "data": {"result": [
            {"metric": {}, "value": [0, str(value)]}]}}

    def test_parses_all_three(self):
        client = self._client({
            "histogram_quantile": self._instant(0.042),
            "http_requests_total": self._instant(350.0),
            "kube_pod_status_phase": self._instant(7.0),
        })
        snap = client.snapshot()
        assert snap["latency_p95_ms"] == pytest.approx(42.0)
        assert snap["rps"] == pytest.approx(350.0)
        assert snap["queue_depth"] == pytest.approx(7.0)

    def test_absent_series_omitted(self):
        client = self._client({})  # empty result sets everywhere
        assert client.snapshot() == {}
        assert client.latency_p95_s() is None

    def test_nan_histogram_treated_absent(self):
        client = self._client({"histogram_quantile": self._instant("NaN")})
        assert client.latency_p95_s() is None

    def test_unreachable_endpoint_degrades(self):
        from ccka_tpu.signals.live import PrometheusClient, SLOMetricsClient

        def fetch(url, headers):
            raise OSError("no route to host")

        client = SLOMetricsClient(PrometheusClient("http://prom", fetch=fetch))
        assert client.snapshot() == {}


class TestControllerSLOReport:
    def test_report_carries_model_latency_and_measured_snapshot(self):
        from ccka_tpu.actuation.sink import DryRunSink
        from ccka_tpu.harness.controller import Controller
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.signals.synthetic import SyntheticSignalSource

        cfg = default_config()

        class SourceWithSLO(SyntheticSignalSource):
            def slo_snapshot(self):
                return {"latency_p95_ms": 35.0, "rps": 120.0}

        src = SourceWithSLO(cfg.cluster, cfg.workload, cfg.sim, cfg.signals)
        ctrl = Controller(cfg, RulePolicy(cfg.cluster), src, DryRunSink(),
                          interval_s=0.0, log_fn=lambda _line: None)
        report = ctrl.tick(0)
        assert report.latency_p95_ms > 0.0
        assert report.slo_metrics == {"latency_p95_ms": 35.0, "rps": 120.0}
        # JSON log line round-trips the new fields.
        rec = json.loads(report.to_json())
        assert "slo_metrics" in rec and "latency_p95_ms" in rec
