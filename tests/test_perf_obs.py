"""Device-time performance observatory (round 15): obs/costmodel +
obs/occupancy + the bench-diff perf gates + the scaling-curve artifact.

Coverage map:

- **Cost-model attribution**: real XLA cost/memory analysis on the CPU
  backend (flops/bytes are genuine numbers), the graceful
  ``flops=None`` path on backends where the analysis raises or returns
  nothing, the program-table join with `obs/compile` dispatch counters,
  the hand-count-vs-XLA byte cross-check's 2x warning band, and the
  achieved-roofline arithmetic.
- **Occupancy ledger**: fractions sum to 1 by construction, per-stage
  fencing on a real interpret-mode megakernel pipeline, per-shard
  timing via `parallel.shard_lane_blocks` (slicing is exactly the mesh
  split), max/mean imbalance >= 1, and the observatory-on/off bitwise
  non-interference gate.
- **bench-diff invariant gates**: achieved fraction outside (0, 1.25],
  occupancy fractions not summing to ~1, imbalance < 1, a PARTIAL perf
  record, and a broken bitwise flag each trip a `perf_invariant`
  regression — with the injected bad-occupancy record driving the CLI
  exit code non-zero (the CI contract), and the committed real history
  staying clean.
- **CLI**: `ccka perf` runs the probe pipeline and renders rows with
  unavailable analysis as '-', `ccka scaling-curve` writes the CSV
  artifact from the committed history.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccka_tpu.config import default_config
from ccka_tpu.obs import costmodel
from ccka_tpu.obs import occupancy as occ
from ccka_tpu.obs.trace import SpanTracer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cfg():
    return default_config()


@pytest.fixture(scope="module")
def tiny_pipeline(cfg):
    """A CI-sized packed pipeline: generation jit + rule-mode kernel
    closure (interpret, deterministic), compiled once per module."""
    from ccka_tpu.sim import SimParams
    from ccka_tpu.sim.megakernel import packed_mode_summary_fn
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    params = SimParams.from_config(cfg)
    src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals)
    steps, batch = 16, 32
    gen = jax.jit(src.packed_generate_fn(steps, batch, t_chunk=16))
    kfn = packed_mode_summary_fn(params, cfg.cluster, "rule", T=steps,
                                 b_block=32, t_chunk=16, interpret=True,
                                 stochastic=False)
    stream = gen(jax.random.key(7))
    jax.block_until_ready(kfn(stream, 0))  # compile once for the module
    return gen, kfn, stream, steps, batch


class TestCostModel:
    def test_attribute_real_program_on_cpu(self):
        """The CPU backend genuinely reports flops/bytes — attribution
        rows carry real numbers, joined with dispatch counters."""
        from ccka_tpu.obs.compile import watch_jit

        f = watch_jit(jax.jit(lambda x: (x * 2.0 + 1.0).sum()),
                      "test.costmodel_probe")
        x = jnp.ones((64, 64))
        f(x)
        f(x)
        rec = costmodel.attribute("test.costmodel_probe", f, x)
        assert rec.analysis == "xla"
        assert rec.flops and rec.flops > 0
        assert rec.bytes_accessed and rec.bytes_accessed >= x.size * 4
        assert rec.peak_memory_bytes and rec.peak_memory_bytes > 0
        row = {r["name"]: r for r in costmodel.program_table()}[
            "test.costmodel_probe"]
        assert row["dispatches"] == 2
        assert row["flops"] == rec.flops
        assert row["analysis"] == "xla"

    def test_unavailable_analysis_degrades_to_none(self):
        """Round-15 satellite: on backends where cost_analysis()
        raises/returns nothing, the registry still returns an
        ATTRIBUTED row — flops None, analysis 'unavailable', error
        recorded — instead of raising or omitting the program."""

        class NoAnalysisCompiled:
            def cost_analysis(self):
                raise NotImplementedError("backend reports nothing")

            def memory_analysis(self):
                return None

        class Lowered:
            def compile(self):
                return NoAnalysisCompiled()

        class FakeJit:
            def lower(self, *a, **k):
                return Lowered()

        rec = costmodel.attribute("test.unavailable", FakeJit())
        assert rec.analysis == "unavailable"
        assert rec.flops is None and rec.bytes_accessed is None
        assert "cost_analysis" in (rec.error or "")
        row = {r["name"]: r for r in costmodel.program_table()}[
            "test.unavailable"]
        assert row["flops"] is None
        # And the renderer survives the None row (the `ccka perf`
        # crash-free contract).
        text = costmodel.render_program_table([row])
        assert "test.unavailable" in text and "-" in text

    def test_lower_failure_is_recorded_not_raised(self):
        class Unlowerable:
            def lower(self, *a, **k):
                raise TypeError("no lowering on this backend")

        rec = costmodel.attribute("test.unlowerable", Unlowerable())
        assert rec.analysis == "unavailable"
        assert "lower/compile" in rec.error

    def test_empty_cost_analysis_list(self):
        """A backend returning an empty list (seen across jax
        versions) resolves to None, not an IndexError."""

        class EmptyCompiled:
            def cost_analysis(self):
                return []

            def memory_analysis(self):
                return None

        class Lowered:
            def compile(self):
                return EmptyCompiled()

        class FakeJit:
            def lower(self, *a, **k):
                return Lowered()

        rec = costmodel.attribute("test.emptylist", FakeJit())
        assert rec.flops is None and rec.analysis == "unavailable"

    def test_crosscheck_band(self):
        warned = []
        out = costmodel.crosscheck_bytes("p", 1000.0, 1500.0,
                                         warn=warned.append)
        assert out["agree"] is True and not warned
        out = costmodel.crosscheck_bytes("p", 1000.0, 2500.0,
                                         warn=warned.append)
        assert out["agree"] is False and out["ratio"] == 2.5
        assert warned and "disagreement" in warned[0]
        # XLA reporting LESS than the hand-counted lower bound is just
        # as wrong as reporting far more.
        out = costmodel.crosscheck_bytes("p", 1000.0, 400.0,
                                         warn=warned.append)
        assert out["agree"] is False
        # Unattributable bytes: recorded, no verdict, no warning.
        out = costmodel.crosscheck_bytes("p", 1000.0, None)
        assert out["agree"] is None and out["ratio"] is None

    def test_achieved_fraction_arithmetic(self):
        # 1 GB in 1 s over a 2 GB/s roofline = 0.5.
        f = costmodel.achieved_roofline_fraction(
            1.0, bytes_accessed=1e9, bandwidth_bytes_per_s=2e9)
        assert f == pytest.approx(0.5)
        # Compute-bound side wins when it is the larger fraction.
        f = costmodel.achieved_roofline_fraction(
            1.0, bytes_accessed=1e6, bandwidth_bytes_per_s=2e9,
            flops=9e11, peak_flops_per_s=1e12)
        assert f == pytest.approx(0.9)
        # Unknowable is None, not zero.
        assert costmodel.achieved_roofline_fraction(
            1.0, bytes_accessed=None) is None
        assert costmodel.achieved_roofline_fraction(
            0.0, bytes_accessed=1e9) is None

    def test_pipeline_snapshot_roundtrip(self):
        costmodel.publish_pipeline_snapshot(
            occupancy={"generation": 0.3, "kernel": 0.6, "host": 0.1},
            shard_imbalance=1.2, achieved_fraction=0.8)
        snap = costmodel.pipeline_snapshot()
        assert snap["occupancy"]["kernel"] == 0.6
        assert snap["shard_imbalance"] == 1.2
        assert snap["achieved_fraction"] == 0.8


class TestOccupancy:
    def test_fractions_sum_to_one(self):
        led = occ.OccupancyLedger()
        led.add("generation", 0.2)
        led.add("kernel", 0.7)
        led.add("host", 0.1)
        fr = led.fractions()
        assert sum(fr.values()) == pytest.approx(1.0)
        assert fr["kernel"] == pytest.approx(0.7)
        with pytest.raises(ValueError):
            led.add("mystery_stage", 1.0)
        assert occ.OccupancyLedger().fractions() == {}

    def test_shard_imbalance(self):
        assert occ.shard_imbalance([1.0, 1.0, 1.0]) == pytest.approx(1.0)
        assert occ.shard_imbalance([1.0, 1.0, 2.0]) == pytest.approx(1.5)
        assert occ.shard_imbalance([]) is None
        assert occ.shard_imbalance([0.0, 0.0]) is None
        # >= 1 on any positive measurement, by construction.
        rng = np.random.default_rng(0)
        for _ in range(16):
            ts = rng.uniform(0.1, 5.0, size=8)
            assert occ.shard_imbalance(ts) >= 1.0

    def test_measured_pipeline_fences_and_sums(self, tiny_pipeline):
        gen, kfn, _stream, _steps, _batch = tiny_pipeline
        tracer = SpanTracer()
        ledger, host_out = occ.measure_packed_pipeline(
            lambda i: gen(jax.random.key(50 + i)),
            lambda s, i: kfn(s, i + 1),
            lambda summary: float(np.asarray(summary.cost_usd).mean()),
            repeats=2, tracer=tracer, label="test.pipe")
        fr = ledger.fractions()
        assert set(fr) == set(occ.PIPELINE_STAGES)
        assert sum(fr.values()) == pytest.approx(1.0)
        assert all(v >= 0.0 for v in fr.values())
        assert ledger.repeats == 2
        assert isinstance(host_out, float)
        # The device stages really closed as fenced device spans.
        cats = {sp.name: sp.cat for sp in tracer.spans()}
        assert cats["test.pipe.generation"] == "device"
        assert cats["test.pipe.kernel"] == "device"

    def test_observatory_is_bitwise_noninterfering(self, tiny_pipeline):
        """The tentpole's non-interference gate: the SAME (stream,
        seed) produces bitwise-identical summaries with and without
        the observatory's spans in scope."""
        _gen, kfn, stream, _steps, _batch = tiny_pipeline
        tracer = SpanTracer()
        with tracer.device_span("test.bitwise") as sp:
            s_on = kfn(stream, 5)
            sp.fence(s_on)
        s_off = kfn(stream, 5)
        jax.block_until_ready(s_off)
        for a, b in zip(jax.tree_util.tree_leaves(s_on),
                        jax.tree_util.tree_leaves(s_off)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_shard_lane_blocks_are_the_mesh_split(self, tiny_pipeline):
        """Slicing parity: the per-shard observation blocks concatenate
        back to the exact stream, and a batch that does not divide is
        rejected outright (a silently truncated shard would fake
        balance)."""
        from ccka_tpu.config import ConfigError
        from ccka_tpu.parallel import shard_lane_blocks

        _gen, _kfn, stream, _steps, batch = tiny_pipeline
        blocks = shard_lane_blocks(stream, 4)
        assert len(blocks) == 4
        assert all(b.shape[2] == batch // 4 for b in blocks)
        assert np.array_equal(np.asarray(jnp.concatenate(blocks, axis=2)),
                              np.asarray(stream))
        with pytest.raises(ConfigError):
            shard_lane_blocks(stream, 7)

    def test_measure_shard_times(self, tiny_pipeline):
        _gen, kfn, stream, _steps, _batch = tiny_pipeline
        from ccka_tpu.parallel import shard_lane_blocks, shard_seed

        blocks = shard_lane_blocks(stream, 2)
        kfn16 = None
        from ccka_tpu.sim import SimParams
        from ccka_tpu.sim.megakernel import packed_mode_summary_fn

        cfg = default_config()
        kfn16 = packed_mode_summary_fn(
            SimParams.from_config(cfg), cfg.cluster, "rule", T=16,
            b_block=16, t_chunk=16, interpret=True, stochastic=False)
        jax.block_until_ready(kfn16(blocks[0], 0))  # compile

        times = occ.measure_shard_times(
            lambda i: kfn16(blocks[i], shard_seed(1, i, 1)).cost_usd, 2)
        assert len(times) == 2 and all(t > 0 for t in times)
        assert occ.shard_imbalance(times) >= 1.0


def _good_perf_record(**overrides) -> dict:
    """A minimal well-formed --perf-only record for the gate tests."""
    def mode(frac=0.4):
        return {
            "occupancy": {"seconds": {"generation": 0.3, "kernel": 0.6,
                                      "host": 0.1},
                          "fractions": {"generation": 0.3, "kernel": 0.6,
                                        "host": 0.1}, "repeats": 2},
            "achieved_roofline_fraction": frac,
            "bitwise_identical": True,
            "programs": [],
        }

    rec = {
        "metric": "perf", "round": 90, "stage": "--perf-only",
        "platform": "cpu", "virtual": True,
        "modes": {"rule": mode(), "carbon": mode(0.38),
                  "neural": mode(0.05), "plan": mode(0.35)},
        "mesh8": {"shards": 8, "shard_imbalance": 1.15,
                  "occupancy": {"fractions": {"generation": 0.3,
                                              "kernel": 0.65,
                                              "host": 0.05}}},
        "observatory": {"overhead_frac": 0.01,
                        "overhead_gate_frac": 0.05,
                        "overhead_gate_ok": True, "bitwise_all": True},
        "single_chip": {"cluster_days_per_sec": 450.0},
        "provenance": {"platform": "cpu"},
    }
    rec.update(overrides)
    return rec


def _diff_of(tmp_path, rec) -> dict:
    from ccka_tpu.obs.bench_history import bench_diff, load_bench_history

    (tmp_path / "BENCH_r90.json").write_text(json.dumps(rec))
    return bench_diff(load_bench_history(str(tmp_path)))


class TestBenchDiffPerfGates:
    def test_good_record_is_clean(self, tmp_path):
        diff = _diff_of(tmp_path, _good_perf_record())
        assert diff["ok"], diff["regressions"]

    def test_bad_occupancy_sum_regresses_and_cli_exits_nonzero(
            self, tmp_path, capsys):
        rec = _good_perf_record()
        rec["modes"]["rule"]["occupancy"]["fractions"] = {
            "generation": 0.6, "kernel": 0.6, "host": 0.2}  # sums 1.4
        diff = _diff_of(tmp_path, rec)
        kinds = [r["kind"] for r in diff["regressions"]]
        assert "perf_invariant" in kinds
        # The CI contract: the injected bad record makes the exit code
        # non-zero (pinned per the round-15 satellite).
        from ccka_tpu.cli import main

        assert main(["bench-diff", "--root", str(tmp_path)]) == 1
        out = capsys.readouterr()
        assert "REGRESSION" in out.err

    def test_achieved_fraction_out_of_band(self, tmp_path):
        rec = _good_perf_record()
        rec["modes"]["plan"]["achieved_roofline_fraction"] = 1.6
        diff = _diff_of(tmp_path, rec)
        assert any(r["kind"] == "perf_invariant"
                   and r.get("mode") == "plan"
                   for r in diff["regressions"])
        rec = _good_perf_record()
        rec["modes"]["rule"]["achieved_roofline_fraction"] = 0.0
        assert not _diff_of(tmp_path, rec)["ok"]

    def test_imbalance_below_one(self, tmp_path):
        rec = _good_perf_record()
        rec["mesh8"]["shard_imbalance"] = 0.8
        diff = _diff_of(tmp_path, rec)
        assert any("imbalance" in r["detail"]
                   for r in diff["regressions"])

    def test_partial_record_is_a_regression(self, tmp_path):
        # A declared mode with no occupancy...
        rec = _good_perf_record()
        del rec["modes"]["neural"]["occupancy"]
        assert not _diff_of(tmp_path, rec)["ok"]
        # ...a --perf-only record silently missing a whole mode...
        rec = _good_perf_record()
        del rec["modes"]["carbon"]
        diff = _diff_of(tmp_path, rec)
        assert any("carbon" in r["detail"] for r in diff["regressions"])
        # ...or missing the mesh section entirely.
        rec = _good_perf_record()
        del rec["mesh8"]
        assert not _diff_of(tmp_path, rec)["ok"]

    def test_bitwise_and_overhead_gates(self, tmp_path):
        rec = _good_perf_record()
        rec["observatory"]["bitwise_all"] = False
        assert not _diff_of(tmp_path, rec)["ok"]
        rec = _good_perf_record()
        rec["observatory"]["overhead_frac"] = 0.09
        diff = _diff_of(tmp_path, rec)
        assert any("overhead" in r["detail"]
                   for r in diff["regressions"])

    def test_unreadable_perf_record_is_a_regression(self, tmp_path):
        (tmp_path / "BENCH_r91.json").write_text("{torn json")
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        diff = bench_diff(load_bench_history(str(tmp_path)))
        assert any(r["kind"] == "unreadable_record"
                   for r in diff["regressions"])

    def test_committed_history_stays_clean(self):
        """The real repo history — including the round-15 record —
        must pass every gate this module adds (a PR that regresses its
        own sentinel ships a broken record)."""
        from ccka_tpu.obs.bench_history import (bench_diff,
                                                load_bench_history)

        diff = bench_diff(load_bench_history(ROOT))
        assert diff["ok"], diff["regressions"]


class TestScalingCurve:
    def test_real_history_renders(self):
        from ccka_tpu.obs.bench_history import scaling_curve

        curve = scaling_curve(ROOT)
        rounds = {p["round"] for p in curve["points"]}
        # The legacy skip-wrappers AND the measured r08 sweep are both
        # on the curve — the artifact must not hide that rounds 1-5
        # measured nothing.
        assert 1 in rounds and 8 in rounds
        r8 = [p for p in curve["points"]
              if p["round"] == 8 and p.get("devices") == 8
              and p["source"] == "multichip"]
        assert r8 and r8[0]["cluster_days_per_sec_per_device"] > 0
        legacy = [p for p in curve["points"] if p["round"] == 1]
        assert legacy and "skipped" in legacy[0]["note"]
        # The r09 sharded plan-playback row is a point too.
        assert any(p["source"] == "multichip_plan_playback"
                   and p["round"] == 9 for p in curve["points"])

    def test_csv_artifact(self, tmp_path):
        from ccka_tpu.obs.bench_history import (SCALING_CSV_COLUMNS,
                                                scaling_curve,
                                                write_scaling_csv)

        curve = scaling_curve(ROOT)
        path = write_scaling_csv(curve, str(tmp_path / "curve.csv"))
        lines = open(path, encoding="utf-8").read().splitlines()
        assert lines[0] == ",".join(SCALING_CSV_COLUMNS)
        assert len(lines) >= 1 + len(curve["points"])

    def test_cli(self, tmp_path, capsys):
        from ccka_tpu.cli import main

        out_csv = str(tmp_path / "sc.csv")
        assert main(["scaling-curve", "--root", ROOT,
                     "--out", out_csv]) == 0
        assert os.path.exists(out_csv)
        err = capsys.readouterr().err
        assert "scaling curve ->" in err
        with pytest.raises(SystemExit):
            main(["scaling-curve", "--root", str(tmp_path / "nowhere"),
                  "--out", out_csv])


class TestPerfCLI:
    @pytest.mark.slow  # ISSUE 16 lane-time rule: full probe sweep; the
    # render/unavailable CLI contract stays in the fast lane.
    def test_perf_probe_json(self, capsys):
        """`ccka perf` end to end on the CPU interpret path: the table
        carries a dispatch-joined, XLA-attributed row for the rule mode
        and the occupancy ledger sums to ~1."""
        from ccka_tpu.cli import main

        assert main(["perf", "--steps", "16", "--batch", "32",
                     "--repeats", "1", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        rule = doc["modes"]["rule"]
        assert sum(rule["occupancy"]["fractions"].values()) \
            == pytest.approx(1.0, abs=1e-4)
        names = {r["name"]: r for r in doc["programs"]}
        assert "megakernel.mode.rule" in names
        row = names["megakernel.mode.rule"]
        assert row["dispatches"] and row["dispatches"] > 0
        # On the CPU backend the analysis is genuinely available; the
        # unavailable path is covered below by forcing it.
        assert row["analysis"] == "xla"
        assert rule["achieved_roofline_fraction"] is not None
        assert 0.0 < rule["achieved_roofline_fraction"] <= 1.25

    @pytest.mark.slow  # ISSUE 16 lane-time rule:
    # perf CLI contract rides the slow lane with probe-json.
    def test_perf_renders_unavailable_rows(self, capsys, monkeypatch):
        """Round-15 satellite: when the backend reports no cost
        analysis, `ccka perf` still prints attributed rows (flops '-')
        without crashing."""
        monkeypatch.setattr(
            costmodel, "_cost_numbers",
            lambda compiled: (_ for _ in ()).throw(
                NotImplementedError("no analysis on this backend")))
        from ccka_tpu.cli import main

        assert main(["perf", "--steps", "16", "--batch", "32",
                     "--repeats", "1"]) == 0
        out = capsys.readouterr().out
        assert "megakernel.mode.rule" in out
        assert "unavailable" in out

    def test_perf_rejects_unknown_mode(self):
        from ccka_tpu.cli import main

        with pytest.raises(SystemExit):
            main(["perf", "--modes", "quantum"])


class TestServicePerfGauges:
    def test_service_obs_block_fills_perf_surfaces(self):
        """End-to-end wiring: with the obs layer ON, service ticks
        state the session dispatch counter, and once the observatory
        publishes a pipeline snapshot the measurement-backed gauges
        ride the next tick's report; with obs OFF all four skip."""
        from ccka_tpu.config import OBS_PRESETS, SERVICE_PRESETS
        from ccka_tpu.harness.promexport import render_exposition
        from ccka_tpu.harness.service import fleet_service_from_config
        from ccka_tpu.policy import RulePolicy

        cfg = default_config().with_overrides(**{"sim.horizon_steps": 16})
        costmodel.publish_pipeline_snapshot(
            occupancy={"generation": 0.3, "kernel": 0.6, "host": 0.1},
            shard_imbalance=1.1, achieved_fraction=0.5)
        svc = fleet_service_from_config(
            cfg, RulePolicy(cfg.cluster), 2,
            service=SERVICE_PRESETS["default"],
            obs=OBS_PRESETS["default"], horizon_ticks=8,
            log_fn=lambda _m: None)
        svc.warmup()
        reports = svc.run(2)
        svc.close()
        rep = reports[-1]
        assert rep.program_dispatches_total > 0
        assert rep.achieved_roofline_fraction == 0.5
        assert rep.pipeline_occupancy["kernel"] == 0.6
        assert rep.shard_imbalance == 1.1
        import dataclasses

        text = render_exposition(dataclasses.asdict(rep))
        assert "ccka_program_dispatches_total" in text
        assert "ccka_achieved_roofline_fraction 0.5" in text
        assert "ccka_shard_imbalance 1.1" in text

        # Hard "off" gate: no obs layer, no perf surfaces.
        costmodel.publish_pipeline_snapshot(
            occupancy={"kernel": 1.0}, shard_imbalance=1.0,
            achieved_fraction=0.9)
        svc_off = fleet_service_from_config(
            cfg, RulePolicy(cfg.cluster), 2,
            service=SERVICE_PRESETS["default"], obs=None,
            horizon_ticks=8, log_fn=lambda _m: None)
        svc_off.warmup()
        off_rep = svc_off.run(1)[-1]
        svc_off.close()
        assert off_rep.program_dispatches_total is None
        assert off_rep.shard_imbalance is None
