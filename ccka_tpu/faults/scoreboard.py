"""Paired robustness scoreboard: policies × fault intensities.

Scores {rule, flagship, MPC-playback} (plus optional carbon) on the SAME
``n_traces`` paired worlds at each named fault intensity
(`config.FAULT_PRESETS`) through the megakernel path, and reports
$/SLO-hour degradation curves + interruption/denial/stale counts. Three
pairing properties make the curves meaningful:

- **Across policies**: every row of one intensity shares one
  (stream, seed, b_block, t_chunk) — identical worlds AND identical
  fault realization (the lanes are part of the stream).
- **Across intensities**: all intensities are generated from one key, so
  the exo rows are bitwise identical and the fault latents are the same
  storms at rising severity (thresholded nested windows) — a genuine
  dose-response, not four different weather systems.
- **MPC plans on the calm world**: the planner sees its forecast (the
  clean exo trace — preemption storms are not forecastable), the kernel
  executes the plan on the faulted world. That asymmetry is the point:
  robustness is what survives planning for weather you didn't get.

On TPU this runs the Mosaic kernels in stochastic mode at full-day
horizons; elsewhere interpret-mode deterministic at CI sizes (labeled —
the degradation curve's shape is the result, not absolute wall-clock).
Used by `bench.py bench_faults` (records BASELINE round10) and the
`ccka chaos-eval` CLI.
"""

from __future__ import annotations

import dataclasses
import sys

import jax
import jax.numpy as jnp
import numpy as np

from ccka_tpu.config import FAULT_PRESETS, FrameworkConfig

_CURVE_FIELDS = ("usd_per_slo_hour", "g_co2_per_kreq", "slo_attainment",
                 "interruptions", "denials", "stale_ticks")


def _row(summary) -> dict:
    vals = {k: np.asarray(getattr(summary, k), np.float64)
            for k in _CURVE_FIELDS}
    out = {k: round(float(v.mean()), 4) for k, v in vals.items()}
    out["per_trace_usd_per_slo_hour"] = vals["usd_per_slo_hour"]
    return out


def _vs_calm(row: dict, calm_per_trace: np.ndarray) -> None:
    """Paired per-trace degradation vs the calm ('off') intensity of the
    SAME policy: mean ratio + se (same worlds, so the ratio cancels
    trace heterogeneity like every other paired gate here)."""
    r = (row.pop("per_trace_usd_per_slo_hour")
         / np.maximum(calm_per_trace, 1e-9))
    row["vs_calm_usd_per_slo_hour"] = round(float(r.mean()), 4)
    if r.size >= 2:
        row["vs_calm_usd_per_slo_hour_se"] = round(
            float(r.std(ddof=1) / np.sqrt(r.size)), 5)


def fault_scoreboard(cfg: FrameworkConfig, *,
                     intensities=("off", "mild", "moderate", "severe"),
                     policies=("rule", "flagship", "mpc"),
                     n_traces: int = 256,
                     eval_steps: int | None = None,
                     seed: int = 31,
                     trace_seed: int = 97) -> dict:
    """The robustness board (module docstring). ``intensities`` must
    include "off" (the calm denominator) and name `FAULT_PRESETS`
    entries; ``policies`` ⊆ {rule, carbon, flagship, mpc}."""
    from ccka_tpu.faults.process import unpack_fault_lanes
    from ccka_tpu.models import action_to_latent, latent_to_action
    from ccka_tpu.policy import CarbonAwarePolicy
    from ccka_tpu.policy.rule import (neutral_action, offpeak_action,
                                      peak_action)
    from ccka_tpu.signals.synthetic import SyntheticSignalSource
    from ccka_tpu.sim import SimParams, initial_state
    from ccka_tpu.sim.megakernel import (
        carbon_megakernel_summary_from_packed,
        megakernel_summary_from_packed,
        neural_megakernel_summary_from_packed, pack_plan,
        plan_megakernel_summary_from_packed, unpack_exo)
    from ccka_tpu.train.flagship import load_flagship_backend
    from ccka_tpu.train.mpc import receding_horizon_plan_batch

    bad = [i for i in intensities if i not in FAULT_PRESETS]
    if bad:
        raise ValueError(f"unknown fault intensities {bad}; presets: "
                         f"{sorted(FAULT_PRESETS)}")
    if "off" not in intensities:
        raise ValueError('intensities must include "off" (the calm '
                         "denominator of every degradation curve)")
    known_policies = ("rule", "carbon", "flagship", "mpc")
    bad = [p for p in policies if p not in known_policies]
    if bad:
        raise ValueError(f"unknown policies {bad}; known: "
                         f"{list(known_policies)} — a typo here would "
                         f"otherwise run the full sweep and emit a board "
                         f"missing that row")

    on_tpu = jax.default_backend() == "tpu"
    steps = eval_steps or (2880 if on_tpu else 96)
    t_chunk = 64 if on_tpu else 32
    b_block = min(256, n_traces)
    if n_traces % b_block:
        raise ValueError(f"n_traces={n_traces} must be a multiple of "
                         f"b_block={b_block}")
    kw = dict(seed=seed, stochastic=on_tpu, b_block=b_block,
              t_chunk=t_chunk, interpret=not on_tpu)
    params = SimParams.from_config(cfg)
    cluster = cfg.cluster
    Z = cluster.n_zones
    off_a, peak_a = offpeak_action(cluster), peak_action(cluster)
    key = jax.random.key(trace_seed)

    # One stream per intensity, all from ONE key: exo rows bitwise
    # shared, fault latents shared (nested windows at rising severity).
    streams = {}
    for name in intensities:
        src = SyntheticSignalSource(cluster, cfg.workload, cfg.sim,
                                    cfg.signals,
                                    faults=FAULT_PRESETS[name])
        streams[name] = src.packed_trace_device(steps, key, n_traces,
                                                t_chunk=t_chunk)

    out: dict = {
        "engine": "megakernel(fault lanes)",
        "n_traces": n_traces, "eval_steps": steps,
        "stochastic": on_tpu, "interpret": not on_tpu,
        "b_block": b_block, "t_chunk": t_chunk, "seed": seed,
        "policies": list(policies),
        "intensities": {},
    }

    flagship = None
    if "flagship" in policies:
        flagship, meta = load_flagship_backend(cfg)
        if flagship is None:
            out["flagship_source"] = ("omitted: no flagship checkpoint "
                                      "for this topology (no stand-ins)")
        else:
            out["flagship_source"] = {
                "checkpoint": "topology-keyed flagship",
                "selected_iteration": meta.get("selected_iteration")}

    plan_packed = None
    if "mpc" in policies:
        # Plan ONCE on the clean world (exo rows are shared across
        # intensities, so one plan serves every row): lax quick planner
        # per paired trace, kernel playback on the faulted worlds.
        quick = dict(horizon=8, replan_every=8, iters=2)
        out["mpc_planner"] = dict(
            quick, n_traces=n_traces,
            mode="lax_quick_plan(clean world)->kernel_playback(faulted)")
        traces = unpack_exo(streams["off"], steps, Z)
        base = jnp.zeros_like(action_to_latent(neutral_action(cluster),
                                               cluster))
        lat0 = jnp.broadcast_to(
            base, (n_traces, quick["horizon"]) + base.shape)
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n_traces,) + x.shape),
            initial_state(cfg))
        plans = receding_horizon_plan_batch(
            params, cluster, cfg.train, states, traces, lat0, **quick)
        plan_actions = jax.vmap(jax.vmap(
            lambda u: latent_to_action(u, cluster)))(plans)
        import math as _math
        t_pad = _math.ceil(steps / t_chunk) * t_chunk
        plan_packed = pack_plan(plan_actions, t_pad)

    cp = CarbonAwarePolicy(cluster)
    boards: dict[str, dict] = {}
    for name in intensities:
        stream = streams[name]
        rows: dict[str, dict] = {}
        if "rule" in policies:
            rows["rule"] = _row(megakernel_summary_from_packed(
                params, off_a, peak_a, stream, steps, **kw))
        if "carbon" in policies:
            rows["carbon"] = _row(carbon_megakernel_summary_from_packed(
                params, off_a, peak_a, stream, steps,
                sharpness=cp.sharpness, min_weight=cp.min_weight,
                stickiness=cp.stickiness, **kw))
        if flagship is not None:
            rows["flagship"] = _row(
                neural_megakernel_summary_from_packed(
                    params, cluster, flagship.params, stream, steps,
                    **kw))
        if plan_packed is not None:
            rows["mpc"] = _row(plan_megakernel_summary_from_packed(
                params, cluster, plan_packed, stream, steps, **kw))
        # Stream-level fault exposure (identical for every policy row —
        # the pairing, stated on the record).
        fs = unpack_fault_lanes(stream, steps, Z)
        exposure = {
            "stale_tick_frac": round(
                float(np.asarray(fs.signal_stale).mean()), 4),
            "ice_tick_frac": round(
                float((np.asarray(fs.deny_frac) > 0).mean()), 4),
            "mean_hazard": round(
                float(np.asarray(fs.preempt_hazard).mean()), 3),
        }
        boards[name] = {
            "faults": dataclasses.asdict(FAULT_PRESETS[name]),
            "exposure": exposure,
            "rows": rows,
        }
        print(f"# faults[{name}]: " + " ".join(
            f"{p}={r['usd_per_slo_hour']:.3f}$/slo-hr"
            f"@{r['slo_attainment']:.3f}" for p, r in rows.items()),
            file=sys.stderr)

    # Degradation curves: per policy, paired vs the calm intensity
    # (capture the calm per-trace arrays first — the off row's own ratio
    # is computed against itself, identically 1).
    calm_arrays = {p: row["per_trace_usd_per_slo_hour"]
                   for p, row in boards["off"]["rows"].items()}
    for name in intensities:
        for p, row in boards[name]["rows"].items():
            _vs_calm(row, calm_arrays[p])
    curves = {}
    for p in next(iter(boards.values()))["rows"]:
        curves[p] = {
            "intensities": list(intensities),
            "usd_per_slo_hour": [boards[i]["rows"][p]["usd_per_slo_hour"]
                                 for i in intensities],
            "vs_calm_usd_per_slo_hour": [
                boards[i]["rows"][p]["vs_calm_usd_per_slo_hour"]
                for i in intensities],
            "slo_attainment": [boards[i]["rows"][p]["slo_attainment"]
                               for i in intensities],
            "interruptions": [boards[i]["rows"][p]["interruptions"]
                              for i in intensities],
        }
    out["intensities"] = boards
    out["degradation_curves"] = curves
    return out
