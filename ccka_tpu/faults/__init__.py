"""Fault-injection subsystem: batched disturbance processes + scoreboard.

Three halves (ARCHITECTURE §12):

- **Processes** (`faults/process.py`): spot-preemption storms
  (optionally price-correlated), insufficient-capacity errors with a
  cooldown, provisioning-delay jitter, and signal-outage windows — all
  pure-jnp, synthesized as extra lanes in the packed exo stream and
  keyed by the same ``(seed, shard, block)`` PRNG scheme as the exo
  signals, so every policy being compared sees the bitwise-identical
  fault realization.
- **Consumption**: `sim/dynamics.step` (``fault=`` kwarg) and the fused
  Pallas megakernel (fault lanes auto-detected from the packed stream's
  row count) lose capacity, deny/delay provisioning, and serve stale
  observations; `harness/controller.py` degrades gracefully on stale
  signals (hold-last-action → rule-fallback state machine).
- **Scoreboard** (`faults/scoreboard.py`): paired robustness sweep over
  the named `config.FAULT_PRESETS` intensities — `bench.py bench_faults`
  and `ccka chaos-eval` both drive it.
"""

from ccka_tpu.config import FAULT_PRESETS, FaultsConfig  # noqa: F401
from ccka_tpu.faults.process import (  # noqa: F401
    fault_rows,
    has_fault_lanes,
    packed_fault_lanes,
    sample_fault_steps,
    unpack_fault_lanes,
)
from ccka_tpu.faults.types import FaultStep  # noqa: F401

__all__ = [
    "FAULT_PRESETS",
    "FaultsConfig",
    "FaultStep",
    "fault_rows",
    "has_fault_lanes",
    "packed_fault_lanes",
    "sample_fault_steps",
    "unpack_fault_lanes",
]
