"""Batched disturbance processes, synthesized as packed-stream lanes.

The fault subsystem's generation half: pure-jnp, scan/`associative_scan`-
compatible processes emitting ``[T_pad, fault_rows(Z), B]`` lane blocks
that ride the SAME packed exo stream the megakernel reads
(`sim/megakernel.py` layout table, ARCHITECTURE §12). Because the lanes
are part of stream synthesis they inherit every pairing property of the
exo signals for free: shard-local on a mesh (`parallel/sharded_kernel.
sharded_packed_trace` runs the generator per shard on ``fold_in(key,
shard)``), and bitwise identical for every policy scored on the stream —
rule, flagship and MPC-playback see the same preemption storm.

Lane layout, offsets relative to the fault block base ``_exo_rows(Z)``:

    row 0..Z-1   preempt_hazard[z]  multiplier on interrupt_p (1 = calm)
    row Z        deny_frac          spot provisioning denied this tick
    row Z+1      delay_frac         pipeline arrivals held back one tick
    row Z+2      signal_stale       {0,1} outage indicator
    rows pad to a sublane multiple of 8 (zeros)

Window processes (storms / ICE / outages) are thresholded stationary
AR(1) latents: the threshold for a stationary in-window fraction ``f``
is the Gaussian quantile ``Phi^-1(1-f)`` (computed HOST-side from the
static config — ``f=0`` maps to +inf, so a zero-rate process is exactly
never active), and persistence ``rho = exp(-1/mean_ticks)`` gives
geometric-ish windows with roughly that mean — the ICE "cooldown" and
outage-window length fall out of the same two-parameter family.

The neutral contract: with every intensity at 0 the emitted lanes are
EXACTLY (hazard=1, deny=0, delay=0, stale=0) — multiplying/adding them
into the simulator is bitwise a no-op, which is what lets the zero-fault
gate (`tests/test_faults.py`) pin the widened pipeline against the
pre-fault one even in stochastic mode.
"""

from __future__ import annotations

import math
from statistics import NormalDist

import jax
import jax.numpy as jnp

from ccka_tpu.config import FaultsConfig
from ccka_tpu.faults.types import FaultStep
from ccka_tpu.signals.synthetic import _ar1_device
from ccka_tpu.sim import lanes

# Key-domain tag separating the fault latents from the exo noise streams
# (the generator splits its key 3 ways for spot/carbon/demand; fault
# lanes fold this constant into the SAME generation key, so they are
# paired per (seed, shard) without disturbing the exo streams' draws —
# the exo rows of a widened stream stay bitwise identical to the
# un-widened generation). Canonical value lives in the lane-family
# registry (`sim/lanes.py` — ISSUE 14); re-exported here for the
# existing surface.
FAULT_KEY_TAG = lanes.LANE_FAMILIES["faults"].key_tag


# Layout arithmetic lives in the neutral `sim/lanes.py` (the one
# layout module — faults and workloads both import it DOWNWARD);
# re-exported here for the existing `faults.fault_rows` surface.
fault_rows = lanes.fault_rows


def _threshold(frac: float) -> float:
    """Host-side Gaussian threshold for a stationary in-window fraction
    ``frac`` of a unit-variance latent; ``frac<=0`` -> +inf (never)."""
    if frac <= 0.0:
        return float("inf")
    return float(NormalDist().inv_cdf(1.0 - frac))


def _window(key, shape, *, frac: float, mean_ticks: int) -> jnp.ndarray:
    """{0,1} window indicator: thresholded stationary AR(1) along axis 0."""
    rho = math.exp(-1.0 / max(mean_ticks, 1))
    latent = _ar1_device(key, shape, rho=rho, sigma=1.0, axis=0)
    return (latent > _threshold(frac)).astype(jnp.float32)


def _ar1_unit_p(key, shape, *, rho, scale, axis: int = 0):
    """`signals.synthetic._ar1_device` with sigma=1 and TRACED AR(1)
    coefficients — the window latent under the scenario-parameter axis
    (ISSUE 19). ``rho``/``scale`` arrive as f32 scalars precomputed by
    `search/params.ScenarioParams.derived` with exactly the baked path's
    host arithmetic (scale = f32(sqrt(1 - rho64^2)) — NOT re-derived
    in-trace from the f32 rho, which would differ by an ulp), so at any
    concrete parameter value this is bitwise `_ar1_device(key, shape,
    rho=rho, sigma=1.0, axis=axis)`: x0 = 1.0*normal is the identity,
    eps = (scale*1.0)*normal is one f32 multiply by the same value, and
    the scan/cumprod see identical element sequences."""
    k0, k1 = jax.random.split(key)
    x0_shape = shape[:axis] + (1,) + shape[axis + 1:]
    x0 = jax.random.normal(k0, x0_shape, jnp.float32)
    eps = scale * jax.random.normal(k1, shape, jnp.float32)
    a = jnp.full(shape, jnp.float32(rho))

    def combine(l, r):
        return (l[0] * r[0], l[1] * r[0] + r[1])

    _, b = jax.lax.associative_scan(combine, (a, eps), axis=axis)
    apow = jnp.cumprod(a, axis=axis)
    return apow * x0 + b


def _window_p(key, shape, *, thresh, rho, scale) -> jnp.ndarray:
    """:func:`_window` with TRACED derived coefficients (threshold /
    rho / noise scale from `ScenarioParams.derived`): one compiled
    program serves every window intensity, and a +inf threshold (frac
    0) yields exact zeros — the traced form of the baked path's
    "never"."""
    latent = _ar1_unit_p(key, shape, rho=rho, scale=scale, axis=0)
    return (latent > thresh).astype(jnp.float32)


# The generator's spot-price AR(1) sigma — the price-coupling unit
# ("+coupling x hazard per +1 sigma price anomaly"). Shared constant so
# the docstring in `config.FaultsConfig` can never drift from the math.
PRICE_DEV_SIGMA = 0.04


def packed_fault_lanes(faults: FaultsConfig, key, steps: int, t_pad: int,
                       Z: int, batch: int, *,
                       price_dev=None) -> jnp.ndarray:
    """``[T_pad, fault_rows(Z), B]`` lane block for one stream.

    ``price_dev``: the generator's spot-price AR(1) anomaly ``[T, Z, B]``
    (relative deviation from the diurnal mean) for the optional
    price-correlated hazard; None decouples regardless of config.
    Pure jnp — runs inside the (possibly shard_map'd) generation jit.
    """
    ks, ki, kd, ko = jax.random.split(jax.random.fold_in(key, FAULT_KEY_TAG),
                                      4)
    f32 = jnp.float32

    storm = _window(ks, (steps, batch), frac=faults.preempt_storm_frac,
                    mean_ticks=faults.preempt_storm_mean_ticks)  # [T, B]
    hazard = 1.0 + f32(faults.preempt_storm_hazard) * storm      # [T, B]
    hazard = jnp.broadcast_to(hazard[:, None, :], (steps, Z, batch))
    if faults.preempt_price_coupling > 0.0 and price_dev is not None:
        hazard = hazard * (1.0 + f32(faults.preempt_price_coupling)
                           * jnp.maximum(price_dev, 0.0) / PRICE_DEV_SIGMA)

    ice = _window(ki, (steps, batch), frac=faults.ice_frac,
                  mean_ticks=faults.ice_mean_ticks)
    deny = f32(faults.ice_deny_frac) * ice                       # [T, B]

    if faults.delay_jitter_frac > 0.0:
        burst = _ar1_device(kd, (steps, batch), rho=0.8, sigma=1.0, axis=0)
        delay = jnp.clip(f32(faults.delay_jitter_frac)
                         * (1.0 + 0.5 * burst), 0.0, 0.9)
    else:
        delay = jnp.zeros((steps, batch), f32)

    stale = _window(ko, (steps, batch), frac=faults.outage_frac,
                    mean_ticks=faults.outage_mean_ticks)

    block = jnp.concatenate(
        [hazard, deny[:, None, :], delay[:, None, :], stale[:, None, :]],
        axis=1).astype(f32)                          # [T, Z+3, B]
    return jnp.pad(block, ((0, t_pad - steps),
                           (0, fault_rows(Z) - block.shape[1]), (0, 0)))


def packed_fault_lanes_p(faults: FaultsConfig, derived: dict, key,
                         steps: int, t_pad: int, Z: int, batch: int, *,
                         price_dev=None) -> jnp.ndarray:
    """:func:`packed_fault_lanes` with the searchable intensities TRACED
    (ISSUE 19): ``derived`` is `ScenarioParams.derived()["faults"]` — f32
    scalars (window threshold/rho/scale triples, hazard, coupling, deny,
    delay fractions) — so one compiled program serves every fault
    parameterization, and `search/axis.ScenarioAxisSource` vmaps this
    over the ``[S]`` axis with the key CLOSED OVER (common random
    numbers: every candidate sees the same storm realization, the paired
    property CEM needs).

    Bitwise contract vs the baked path at any concrete value (pinned by
    `tests/test_search.py`): the host value-gates become unconditional
    arithmetic that is an exact f32 no-op at the neutral value —
    coupling 0 multiplies hazard by exactly 1.0, and the delay lane's
    ``jnp.abs`` collapses the one -0.0 edge (frac 0 times a negative
    burst) to the baked branch's +0.0 while being the identity on the
    active branch's non-negative clip output. Key consumption is
    identical (the baked path splits all four subkeys regardless of
    gating). ``faults`` itself is unused — every continuous field is
    searchable — but kept for the registry's uniform
    ``generate_p(config, derived, ...)`` signature."""
    del faults  # all continuous fields arrive via `derived`
    ks, ki, kd, ko = jax.random.split(jax.random.fold_in(key, FAULT_KEY_TAG),
                                      4)
    f32 = jnp.float32
    d = derived

    storm = _window_p(ks, (steps, batch), thresh=d["storm_thresh"],
                      rho=d["storm_rho"], scale=d["storm_scale"])
    hazard = 1.0 + d["storm_hazard"] * storm                     # [T, B]
    hazard = jnp.broadcast_to(hazard[:, None, :], (steps, Z, batch))
    if price_dev is not None:
        # Pre-divide the coupling by sigma: XLA constant-folds the baked
        # path's `c * max(dev,0) / SIGMA` into `(c/SIGMA) * max(dev,0)`
        # (c is a compile-time constant there); with a TRACED coupling
        # that reassociation can't happen, so do it by hand — the S=1
        # bitwise-parity pin holds with coupling > 0 on both layouts.
        hazard = hazard * (1.0 + (d["price_coupling"] / PRICE_DEV_SIGMA)
                           * jnp.maximum(price_dev, 0.0))

    ice = _window_p(ki, (steps, batch), thresh=d["ice_thresh"],
                    rho=d["ice_rho"], scale=d["ice_scale"])
    deny = d["ice_deny"] * ice                                   # [T, B]

    burst = _ar1_device(kd, (steps, batch), rho=0.8, sigma=1.0, axis=0)
    delay = jnp.abs(jnp.clip(d["delay_frac"] * (1.0 + 0.5 * burst),
                             0.0, 0.9))

    stale = _window_p(ko, (steps, batch), thresh=d["outage_thresh"],
                      rho=d["outage_rho"], scale=d["outage_scale"])

    block = jnp.concatenate(
        [hazard, deny[:, None, :], delay[:, None, :], stale[:, None, :]],
        axis=1).astype(f32)                          # [T, Z+3, B]
    return jnp.pad(block, ((0, t_pad - steps),
                           (0, fault_rows(Z) - block.shape[1]), (0, 0)))


def has_fault_lanes(exo_packed, Z: int) -> bool:
    """Whether a packed stream carries the fault lane block — inferred
    from the row count, so every kernel entry point auto-detects widened
    streams with zero API churn. Delegates to the one layout resolver
    (`sim.lanes.stream_layout`), which rejects any unknown row count
    outright (a half-widened stream would silently misread lanes as
    padding)."""
    return lanes.stream_layout(int(exo_packed.shape[1]), Z)[0]


def unpack_fault_lanes(exo_packed, T: int, Z: int) -> FaultStep:
    """Fault lanes of a widened stream → batched time-major
    :class:`FaultStep` (leaves ``[B, T, ...]``) for the lax rollout path
    — the parity-test/bench plumbing mirror of `megakernel.unpack_exo`
    (it pays the transpose the packed path exists to skip; hot paths
    never call it)."""
    if not has_fault_lanes(exo_packed, Z):
        raise ValueError("stream carries no fault lanes")
    base = lanes.exo_rows(Z)
    x = exo_packed[:T, base:]
    return FaultStep(
        preempt_hazard=jnp.transpose(x[:, 0:Z], (2, 0, 1)),   # [B, T, Z]
        deny_frac=jnp.transpose(x[:, Z], (1, 0)),             # [B, T]
        delay_frac=jnp.transpose(x[:, Z + 1], (1, 0)),
        signal_stale=jnp.transpose(x[:, Z + 2], (1, 0)),
    )


def sample_fault_steps(faults: FaultsConfig, key, steps: int,
                       Z: int) -> FaultStep:
    """Single-trace time-major FaultStep (leaves ``[T, ...]``) for
    standalone lax rollouts and controller tests — same processes, same
    key-tag scheme as the packed lanes (a batch=1 synthesis, squeezed)."""
    lanes = packed_fault_lanes(faults, key, steps, steps, Z, 1)
    return FaultStep(
        preempt_hazard=lanes[:steps, 0:Z, 0],      # [T, Z]
        deny_frac=lanes[:steps, Z, 0],             # [T]
        delay_frac=lanes[:steps, Z + 1, 0],
        signal_stale=lanes[:steps, Z + 2, 0],
    )


def _registry_generate(cfg: FaultsConfig, key, steps: int, t_pad: int,
                       z: int, batch: int, *, ctx: dict):
    """Lane-family registry adapter (`sim/lanes.provide_lane_generator`):
    the generic synthesis path the signal backends drive for every
    registered family — exactly :func:`packed_fault_lanes` on the
    stream key (the tag fold stays inside, so registry-driven and
    direct synthesis are bitwise identical)."""
    return packed_fault_lanes(cfg, key, steps, t_pad, z, batch,
                              price_dev=ctx.get("price_dev"))


def _registry_generate_p(cfg: FaultsConfig, derived: dict, key, steps: int,
                         t_pad: int, z: int, batch: int, *, ctx: dict):
    """Traced-parameter registry adapter
    (`sim/lanes.provide_lane_param_generator`): exactly
    :func:`packed_fault_lanes_p` on the stream key — the scenario-axis
    source drives this generically, so every engine gains the traced
    parameter axis with zero per-engine edits."""
    return packed_fault_lanes_p(cfg, derived, key, steps, t_pad, z, batch,
                                price_dev=ctx.get("price_dev"))


lanes.provide_lane_generator("faults", _registry_generate)
lanes.provide_lane_param_generator("faults", _registry_generate_p)
