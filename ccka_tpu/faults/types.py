"""Fault pytrees consumed by the simulator step.

Kept in their own leaf module (imports only jnp) so `sim/dynamics.py`
can take a :class:`FaultStep` without creating a cycle with the fault
*synthesis* side (`faults/process.py`, which imports the signal layer).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class FaultStep(NamedTuple):
    """One tick of disturbance inputs (a time-slice of the fault lanes).

    Shapes use Z = zones. A leading batch/time axis, when present, is
    handled by ``vmap``/``scan`` like :class:`~ccka_tpu.sim.dynamics.ExoStep`.

    Attributes:
      preempt_hazard: [Z] multiplier on the base per-step spot-
        interruption probability (1 = calm baseline; a preemption storm
        pushes it up, optionally price-correlated).
      deny_frac: [] fraction of this tick's SPOT provisioning request
        denied (insufficient-capacity error; denied capacity is simply
        not requested — Karpenter re-requests next tick from the pending
        backlog, which is exactly how ICE retry behaves).
      delay_frac: [] fraction of this tick's pipeline ARRIVALS held back
        one more tick (provisioning-delay jitter).
      signal_stale: [] {0,1} signal-outage indicator: policies observe
        held (last pre-outage) signals this tick; dynamics use true ones.
    """

    preempt_hazard: jnp.ndarray
    deny_frac: jnp.ndarray
    delay_frac: jnp.ndarray
    signal_stale: jnp.ndarray

    @classmethod
    def neutral(cls, n_zones: int) -> "FaultStep":
        """The no-op disturbance: consuming it is bitwise identical to
        ``fault=None`` (pinned by `tests/test_faults.py`)."""
        return cls(
            preempt_hazard=jnp.ones((n_zones,), jnp.float32),
            deny_frac=jnp.float32(0.0),
            delay_frac=jnp.float32(0.0),
            signal_stale=jnp.float32(0.0),
        )
