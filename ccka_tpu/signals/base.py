"""SignalSource interface and the ExogenousTrace tensor bundle.

The reference reads three signal families — service health via PromQL
(`demo_40_watch_observe.sh:106-110`), cost via OpenCost (`06_opencost.sh:436`),
and carbon intensity via a stubbed API (`.env:14-16`) — each on a 30s cadence
(`06_opencost.sh:323`). This module defines the common tensor format those
signals are lowered into before touching the device: a time-major bundle of
`float32` arrays with static shapes, ready for `lax.scan` over the horizon and
`vmap` over a cluster batch.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class ExogenousTrace(NamedTuple):
    """Time-major exogenous inputs to the cluster simulator.

    Shapes use T = steps, Z = zones. A batch dimension, when present, is
    prepended by ``vmap``; this type stays rank-stable either way.

    Attributes:
      spot_price_hr:  [T, Z] $/node-hr for spot capacity per zone (OpenCost's
        node pricing signal, `06_opencost.sh:404-429`).
      od_price_hr:    [T, Z] $/node-hr for on-demand capacity per zone.
      carbon_g_kwh:   [T, Z] grid carbon intensity per zone
        (ElectricityMaps-style; dummy fallback ~400 g/kWh, `.env:14-16`).
      demand_pods:    [T, C] desired pods per workload class. C=2 matches the
        reference's burst generator which alternates spot-targeted and
        on-demand-targeted deployments (`demo_30_burst_configure.sh:59-70`).
      is_peak:        [T] {0,1} peak-hours indicator — the signal the human
        operator acts on when choosing demo_20 vs demo_21 (`README.md:52-57`).
    """

    spot_price_hr: jnp.ndarray
    od_price_hr: jnp.ndarray
    carbon_g_kwh: jnp.ndarray
    demand_pods: jnp.ndarray
    is_peak: jnp.ndarray

    @property
    def steps(self) -> int:
        return self.spot_price_hr.shape[-2]

    @property
    def n_zones(self) -> int:
        return self.spot_price_hr.shape[-1]

    def slice_steps(self, start: int, length: int) -> "ExogenousTrace":
        return ExogenousTrace(
            spot_price_hr=self.spot_price_hr[..., start:start + length, :],
            od_price_hr=self.od_price_hr[..., start:start + length, :],
            carbon_g_kwh=self.carbon_g_kwh[..., start:start + length, :],
            demand_pods=self.demand_pods[..., start:start + length, :],
            is_peak=self.is_peak[..., start:start + length],
        )

    def validate_shapes(self) -> None:
        t, z = self.spot_price_hr.shape[-2:]
        checks = {
            "od_price_hr": self.od_price_hr.shape[-2:] == (t, z),
            "carbon_g_kwh": self.carbon_g_kwh.shape[-2:] == (t, z),
            "demand_pods": self.demand_pods.shape[-2] == t,
            "is_peak": self.is_peak.shape[-1] == t,
        }
        bad = [k for k, ok in checks.items() if not ok]
        if bad:
            shapes = {k: tuple(getattr(self, k).shape) for k in self._fields}
            raise ValueError(f"inconsistent trace shapes for {bad}: {shapes}")


@dataclasses.dataclass(frozen=True)
class TraceMeta:
    """Provenance for a trace — what the AMP workspace alias + region were to
    the reference (`demo_00_env.sh:11-15`)."""

    source: str  # "synthetic" | "replay" | "live"
    start_unix_s: float
    dt_s: float
    zones: tuple[str, ...]
    description: str = ""


class SignalSource(abc.ABC):
    """Common interface over synthetic/replay/live signal backends.

    ``trace`` produces a whole horizon at once (training, simulation); ``tick``
    produces the latest single-step observation (the live control loop's 30s
    scrape, `06_opencost.sh:323`). Both return device-ready arrays.
    """

    @abc.abstractmethod
    def trace(self, steps: int, *, seed: int = 0) -> ExogenousTrace:
        """Materialize ``steps`` ticks of exogenous signals."""

    @abc.abstractmethod
    def meta(self) -> TraceMeta:
        """Provenance of what :meth:`trace` returns."""

    def tick(self, t_index: int, *, seed: int = 0) -> ExogenousTrace:
        """A single-step trace at tick ``t_index`` (default: slice of trace)."""
        full = self.trace(t_index + 1, seed=seed)
        return full.slice_steps(t_index, 1)

    def forecast(self, t_index: int, steps: int, *,
                 seed: int = 0) -> ExogenousTrace:
        """``steps`` ticks of *forward-looking* signals from ``t_index`` —
        what a receding-horizon planner optimizes against.

        Default: the future slice of :meth:`trace` (exact for synthetic/
        replay worlds, where the trace IS the future). Live sources must
        override — their trace() is backfilled history, not a forecast
        (LiveSignalSource uses persistence forecasting).
        """
        return self.trace(t_index + steps, seed=seed).slice_steps(
            t_index, steps)

    def history(self, t_index: int, steps: int, *,
                seed: int = 0) -> ExogenousTrace:
        """The trailing ``steps`` *observed* ticks ending at ``t_index``
        inclusive — the forecaster input window (`ccka_tpu.forecast`).

        Only ticks <= ``t_index`` are ever touched (the current tick is
        scraped before the decide, so it is observable); early histories
        left-pad by repeating the first tick, keeping the returned shape
        static for jitted consumers. Live sources override: their
        trace() IS backfilled history.
        """
        avail = min(steps, t_index + 1)
        tr = self.trace(t_index + 1, seed=seed).slice_steps(
            t_index + 1 - avail, avail)
        pad = steps - avail
        if not pad:
            return tr

        def lead(x, taxis):
            first = jnp.repeat(jnp.take(x, jnp.array([0]), axis=taxis),
                               pad, axis=taxis)
            return jnp.concatenate([first, x], axis=taxis)

        return ExogenousTrace(
            spot_price_hr=lead(as_f32(tr.spot_price_hr), -2),
            od_price_hr=lead(as_f32(tr.od_price_hr), -2),
            carbon_g_kwh=lead(as_f32(tr.carbon_g_kwh), -2),
            demand_pods=lead(as_f32(tr.demand_pods), -2),
            is_peak=lead(as_f32(tr.is_peak), -1),
        )

    # Staleness protocol (`ccka_tpu/faults` degraded-mode path): a source
    # sets this True when the sample its latest tick() returned is stale
    # — scrapes failed/exhausted their retry budget and the tick fell
    # back to held/prior values. The controller reads it after every
    # scrape to drive its hold-last-action → rule-fallback state machine
    # instead of deciding on garbage. Synthetic/replay worlds are never
    # stale; LiveSignalSource maintains it per tick.
    last_scrape_stale = False

    # Capability flag for on-device trace synthesis (the `--device-traces`
    # fleet path). True only for sources whose batch_trace_device
    # *generates* traces on device under an arbitrary sharding (synthetic);
    # replay's same-named method samples windows from a finite store and
    # cannot honor sharding — a duck-typed hasattr check conflated the two
    # (the round-5 tier-1 regression).
    supports_device_traces = False

    def batch_trace(self, steps: int, seeds) -> ExogenousTrace:
        """[B, T, ...] traces for a batch of seeds (default: stack
        per-seed :meth:`trace` calls; synthetic overrides vectorized)."""
        import jax

        traces = [self.trace(steps, seed=int(s)) for s in seeds]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *traces)

    def slo_snapshot(self) -> dict:
        """Measured app-level SLO metrics (p95/RPS/queue depth) for the
        controller's KPI line. Default: none — only sources with an
        app-metrics path (live Prometheus) override; absent metrics are
        omitted rather than fabricated."""
        return {}


def as_f32(x) -> jnp.ndarray:
    """float32 device array; jax inputs stay on device (no numpy round-trip)."""
    if isinstance(x, jnp.ndarray):
        return x.astype(jnp.float32)
    return jnp.asarray(np.asarray(x), dtype=jnp.float32)
