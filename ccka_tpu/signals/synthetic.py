"""Synthetic signal backend: diurnal price/carbon + bursty demand.

Generalizes the reference's dummy-carbon fallback ("leave blank to use dummy
~400 g/kWh", `.env:14-16`) into a full synthetic exogenous world matching
BASELINE.json config #2 ("synthetic sinusoidal carbon + spot-price signal").

All generation is pure numpy on host (signals are I/O, not compute — the
reference likewise keeps ingestion out of the hot loop, `06_opencost.sh:323`),
then shipped to device once as a single batch of arrays.
"""

from __future__ import annotations

import numpy as np

from ccka_tpu.config import ClusterConfig, SignalsConfig, SimConfig, WorkloadConfig
from ccka_tpu.signals.base import ExogenousTrace, SignalSource, TraceMeta, as_f32

_DAY_S = 86400.0


class SyntheticSignalSource(SignalSource):
    """Sinusoidal diurnal spot price and carbon intensity, bursty pod demand.

    - Spot price: mean from the node type, ±35% diurnal swing (cheapest at
      night), small AR(1) noise, per-zone phase offsets — so zones genuinely
      differ and zone-selection actions (`demo_20_offpeak_configure.sh:71`)
      matter.
    - On-demand price: constant per the node type (on-demand pricing is
      stable), identical across zones.
    - Carbon: mean ``carbon_default_g_kwh`` with a solar-dip daytime profile
      (cleanest mid-day, dirtiest evening ramp — the CAISO duck curve for the
      default `US-CAL-CISO` zone, `.env:15`).
    - Demand: base load plus peak-hours burst reaching the reference's 60-pod
      burst scale (`demo_30_burst_configure.sh:7-8`), split across the two
      workload classes like the odd/even spot/on-demand deployments
      (`demo_30_burst_configure.sh:59-70`).
    - is_peak: 1 during 09:00-21:00 local, the regime in which the reference
      operator would run `demo_21_peak_configure.sh`.
    """

    def __init__(self,
                 cluster: ClusterConfig,
                 workload: WorkloadConfig,
                 sim: SimConfig,
                 signals: SignalsConfig,
                 *,
                 start_unix_s: float = 0.0):
        self.cluster = cluster
        self.workload = workload
        self.sim = sim
        self.signals = signals
        self.start_unix_s = start_unix_s
        # Longest trace generated so far, per seed. Generation is
        # prefix-stable (per-family RNG streams drawn step-sequentially), so
        # serving shorter requests as slices is exact, and tick-at-t costs
        # amortized O(1) instead of regenerating O(t) every scrape.
        self._cache: dict[int, ExogenousTrace] = {}

    def meta(self) -> TraceMeta:
        return TraceMeta(
            source="synthetic",
            start_unix_s=self.start_unix_s,
            dt_s=self.sim.dt_s,
            zones=self.cluster.zones,
            description="sinusoidal diurnal spot price + duck-curve carbon + bursty demand",
        )

    def trace(self, steps: int, *, seed: int = 0) -> ExogenousTrace:
        cached = self._cache.get(seed)
        if cached is not None and cached.steps >= steps:
            return cached.slice_steps(0, steps)
        # Geometric growth so a tick-by-tick caller regenerates rarely.
        gen_steps = max(steps, 2 * cached.steps if cached is not None else 0, 128)
        trace = self._generate(gen_steps, seed)
        self._cache[seed] = trace
        return trace.slice_steps(0, steps)

    def _generate(self, steps: int, seed: int) -> ExogenousTrace:
        # Independent streams per signal family; each draws step-sequentially,
        # so prefixes are stable across different requested lengths.
        rng_spot = np.random.default_rng([seed, 0])
        rng_carbon = np.random.default_rng([seed, 1])
        rng_demand = np.random.default_rng([seed, 2])
        z = self.cluster.n_zones
        dt = self.sim.dt_s
        t = self.start_unix_s + np.arange(steps) * dt  # [T]
        tod = (t % _DAY_S) / _DAY_S  # time-of-day in [0,1)
        tod_z = tod[:, None]  # [T, 1] broadcast against zones

        nt = self.cluster.node_type

        # Per-zone phase offsets (deterministic per zone index).
        phase = (np.arange(z) / max(z, 1)) * 0.15  # [Z] fraction of a day

        # Spot price: diurnal swing + AR(1) noise, clipped to [20%, 95%] of OD.
        diurnal = 1.0 + 0.35 * np.sin(2 * np.pi * (tod_z - 0.25 + phase))  # [T,Z]
        noise = _ar1(rng_spot, (steps, z), rho=0.97, sigma=0.04)
        spot = nt.spot_price_hr_mean * diurnal * (1.0 + noise)
        spot = np.clip(spot, 0.2 * nt.od_price_hr, 0.95 * nt.od_price_hr)

        od = np.full((steps, z), nt.od_price_hr)

        # Carbon duck curve: base − solar dip (centered 13:00) + evening ramp
        # (centered 19:30), small noise; clipped positive.
        base = self.signals.carbon_default_g_kwh
        solar = 0.45 * base * _bump(tod_z, center=13.5 / 24, width=3.5 / 24)
        evening = 0.25 * base * _bump(tod_z + phase, center=19.5 / 24, width=2.0 / 24)
        carbon = base - solar + evening
        carbon = carbon * (1.0 + 0.1 * (np.arange(z) / max(z, 1)))[None, :]
        carbon = carbon * (1.0 + _ar1(rng_carbon, (steps, z), rho=0.95, sigma=0.03))
        carbon = np.clip(carbon, 20.0, None)

        # Peak indicator 09:00-21:00.
        is_peak = ((tod >= 9 / 24) & (tod < 21 / 24)).astype(np.float32)

        # Demand: base 40% of burst scale off-peak, ramping to the full
        # 60-pod burst at peak, with bursty noise; split between the two
        # classes like the reference's odd/even deployments.
        total = float(self.workload.total_pods)
        level = total * (0.4 + 0.6 * _bump(tod, center=14.0 / 24, width=5.0 / 24))
        level = level * (1.0 + 0.15 * _ar1(rng_demand, (steps,), rho=0.9, sigma=0.5))
        level = np.clip(level, 0.0, 2.0 * total)
        demand = np.stack([np.ceil(level / 2.0), np.floor(level / 2.0)], axis=-1)

        trace = ExogenousTrace(
            spot_price_hr=as_f32(spot),
            od_price_hr=as_f32(od),
            carbon_g_kwh=as_f32(carbon),
            demand_pods=as_f32(demand),
            is_peak=as_f32(is_peak),
        )
        trace.validate_shapes()
        return trace


def _ar1(rng: np.random.Generator, shape, rho: float, sigma: float) -> np.ndarray:
    """Stationary AR(1) noise along axis 0."""
    steps = shape[0]
    rest = shape[1:]
    out = np.zeros(shape, dtype=np.float64)
    x = rng.normal(0.0, sigma, size=rest)
    scale = np.sqrt(1.0 - rho * rho)
    for i in range(steps):
        x = rho * x + scale * rng.normal(0.0, sigma, size=rest)
        out[i] = x
    return out


def _bump(x: np.ndarray, center: float, width: float) -> np.ndarray:
    """Smooth periodic bump in [0,1] centered at ``center`` (day fraction)."""
    d = np.minimum(np.abs(x - center), 1.0 - np.abs(x - center))
    return np.exp(-0.5 * (d / (width / 2.0)) ** 2)
