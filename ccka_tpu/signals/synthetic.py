"""Synthetic signal backend: diurnal price/carbon + bursty demand.

Generalizes the reference's dummy-carbon fallback ("leave blank to use dummy
~400 g/kWh", `.env:14-16`) into a full synthetic exogenous world matching
BASELINE.json config #2 ("synthetic sinusoidal carbon + spot-price signal").

All generation is pure numpy on host (signals are I/O, not compute — the
reference likewise keeps ingestion out of the hot loop, `06_opencost.sh:323`),
then shipped to device once as a single batch of arrays.
"""

from __future__ import annotations

import numpy as np

from ccka_tpu.config import ClusterConfig, SignalsConfig, SimConfig, WorkloadConfig
from ccka_tpu.signals.base import ExogenousTrace, SignalSource, TraceMeta, as_f32

_DAY_S = 86400.0


class SyntheticSignalSource(SignalSource):
    """Sinusoidal diurnal spot price and carbon intensity, bursty pod demand.

    - Spot price: mean from the node type, ±35% diurnal swing (cheapest at
      night), small AR(1) noise, per-zone phase offsets — so zones genuinely
      differ and zone-selection actions (`demo_20_offpeak_configure.sh:71`)
      matter.
    - On-demand price: constant per the node type (on-demand pricing is
      stable), identical across zones.
    - Carbon: mean ``carbon_default_g_kwh`` with a solar-dip daytime profile
      (cleanest mid-day, dirtiest evening ramp — the CAISO duck curve for the
      default `US-CAL-CISO` zone, `.env:15`).
    - Demand: base load plus peak-hours burst reaching the reference's 60-pod
      burst scale (`demo_30_burst_configure.sh:7-8`), split across the two
      workload classes like the odd/even spot/on-demand deployments
      (`demo_30_burst_configure.sh:59-70`).
    - is_peak: 1 during 09:00-21:00 local, the regime in which the reference
      operator would run `demo_21_peak_configure.sh`.
    """

    def __init__(self,
                 cluster: ClusterConfig,
                 workload: WorkloadConfig,
                 sim: SimConfig,
                 signals: SignalsConfig,
                 *,
                 start_unix_s: float = 0.0,
                 faults=None,
                 workloads=None,
                 extra_lanes: dict | None = None):
        self.cluster = cluster
        self.workload = workload
        self.sim = sim
        self.signals = signals
        # Fault-injection disturbances (`config.FaultsConfig`): when
        # enabled, the PACKED stream grows the fault lane block
        # (`faults/process.py`) — keyed off the same generation key, so
        # the exo rows stay bitwise identical to a no-faults source and
        # every policy scored on the stream sees one fault realization.
        # None/disabled emits the exact pre-fault stream (no lanes).
        self.faults = faults if (faults is not None
                                 and faults.enabled) else None
        # Workload families (`config.WorkloadsConfig`): when enabled,
        # the PACKED stream additionally grows the family-arrival lane
        # block (`workloads/process.py`), appended AFTER the fault block
        # and keyed by its own tag off the same generation key — exo
        # AND fault rows stay bitwise identical to a no-workloads
        # source. None/disabled emits the exact pre-workload stream.
        self.workloads = workloads if (workloads is not None
                                       and workloads.enabled) else None
        # Further registered lane families (`sim/lanes.py` registry,
        # ISSUE 14): {family name: family config}. Synthesis is fully
        # generic — a family registered with `register_lane_family` +
        # `provide_lane_generator` rides the packed stream with ZERO
        # edits here (the registry contract `tests/test_engine_registry`
        # pins). Unknown names are rejected up front.
        from ccka_tpu.sim import lanes as _lanes

        for name in (extra_lanes or {}):
            if name in ("faults", "workloads"):
                raise ValueError(
                    f"extra_lanes[{name!r}]: pass the built-in families "
                    "via the faults=/workloads= arguments")
            if name not in _lanes.LANE_FAMILIES:
                raise ValueError(
                    f"unknown lane family {name!r}; registered: "
                    f"{sorted(_lanes.LANE_FAMILIES)}")
        self.extra_lanes = dict(extra_lanes or {})
        self.start_unix_s = start_unix_s
        self._zp = self._zone_params()
        # Longest trace generated so far, per seed. Generation is
        # prefix-stable (per-family RNG streams drawn step-sequentially), so
        # serving shorter requests as slices is exact, and tick-at-t costs
        # amortized O(1) instead of regenerating O(t) every scrape.
        self._cache: dict[int, ExogenousTrace] = {}
        # Compiled device-generation programs per (steps, batch) shape.
        self._device_fns: dict = {}

    def meta(self) -> TraceMeta:
        return TraceMeta(
            source="synthetic",
            start_unix_s=self.start_unix_s,
            dt_s=self.sim.dt_s,
            zones=self.cluster.zones,
            description="sinusoidal diurnal spot price + duck-curve carbon + bursty demand",
        )

    def trace(self, steps: int, *, seed: int = 0) -> ExogenousTrace:
        self._ensure_cached(steps, seed)
        return self._cache[seed].slice_steps(0, steps)

    def _ensure_cached(self, steps: int, seed: int) -> None:
        cached = self._cache.get(seed)
        if cached is not None and cached.steps >= steps:
            return
        # Geometric growth so a tick-by-tick caller regenerates rarely.
        gen_steps = max(steps, 2 * cached.steps if cached is not None else 0, 128)
        self._cache[seed] = self._assemble(gen_steps,
                                           self._noise(gen_steps, seed))

    def tick(self, t_index: int, *, seed: int = 0) -> ExogenousTrace:
        """O(1) amortized per tick: slice straight out of the prefix-stable
        cache (the base default's trace(t+1) intermediate would copy O(t)
        device memory every scrape — unbounded growth for a long-lived
        controller daemon)."""
        return self.forecast(t_index, 1, seed=seed)

    def forecast(self, t_index: int, steps: int, *,
                 seed: int = 0) -> ExogenousTrace:
        self._ensure_cached(t_index + steps, seed)
        return self._cache[seed].slice_steps(t_index, steps)

    def batch_trace(self, steps: int, seeds) -> ExogenousTrace:
        """[B, T, ...] traces for a batch of seeds in one vectorized pass.

        Bitwise-identical to stacking ``trace(steps, seed=s)`` per seed (the
        per-seed RNG streams are the same; only the AR(1) filtering and the
        deterministic diurnal parts are computed batch-at-once), but ~50x
        faster at training scale — round 1 spent 15.6s of host time per
        B=256 batch in the per-step Python AR(1) loop, ~98% of wall clock.
        """
        noises = [self._noise(steps, int(s)) for s in seeds]
        stacked = tuple(np.stack(parts) for parts in zip(*noises))
        return self._assemble(steps, stacked)

    def _zone_params(self) -> dict[str, np.ndarray]:
        """Per-zone signal parameters, each a float32 [Z] array.

        Single-region: the classic demo profile — small per-zone phase
        offsets, one carbon base with a mild per-zone scale spread.
        Multi-region (`ClusterConfig.regions`, BASELINE config #4): each
        zone inherits its region's grid profile — carbon base, solar-dip
        depth, local-solar timezone offset, price scales — so regions'
        carbon curves genuinely diverge and cross over the day, which is
        what makes carbon-aware cross-region placement worth anything.
        """
        z = self.cluster.n_zones
        frac = np.arange(z, dtype=np.float32) / max(z, 1)
        default = np.float32(self.signals.carbon_default_g_kwh)
        zp = {
            "spot_phase": frac * 0.15,
            "solar_phase": np.zeros(z, np.float32),
            "evening_phase": frac * 0.15,
            "carbon_base": np.full(z, default, np.float32),
            "solar_frac": np.full(z, 0.45, np.float32),
            "carbon_scale": 1.0 + 0.1 * frac,
            "od_scale": np.ones(z, np.float32),
            "spot_scale": np.ones(z, np.float32),
        }
        if not self.cluster.regions:
            return zp
        i = 0
        for r in self.cluster.regions:
            nz = max(len(r.zones), 1)
            tzf = np.float32(r.tz_offset_hr / 24.0)
            for j in range(len(r.zones)):
                intra = np.float32(j / nz)
                zp["spot_phase"][i] = tzf + 0.05 * intra
                zp["solar_phase"][i] = tzf
                zp["evening_phase"][i] = tzf + 0.05 * intra
                zp["carbon_base"][i] = r.carbon_base_g_kwh or default
                zp["solar_frac"][i] = r.solar_frac
                zp["carbon_scale"][i] = 1.0 + 0.1 * intra
                zp["od_scale"][i] = r.od_price_scale
                zp["spot_scale"][i] = r.spot_price_scale
                i += 1
        return {k: v.astype(np.float32) for k, v in zp.items()}

    def _noise(self, steps: int, seed: int) -> tuple[np.ndarray, ...]:
        """Per-family AR(1) noise streams for one seed.

        Independent streams per signal family; each draws step-sequentially,
        so prefixes are stable across different requested lengths.
        """
        z = self.cluster.n_zones
        return (
            _ar1(np.random.default_rng([seed, 0]), (steps, z),
                 rho=0.97, sigma=0.04),
            _ar1(np.random.default_rng([seed, 1]), (steps, z),
                 rho=0.95, sigma=0.03),
            _ar1(np.random.default_rng([seed, 2]), (steps,),
                 rho=0.9, sigma=0.5),
        )

    # Real on-device generation incl. arbitrary output shardings — the
    # `--device-traces` capability (see SignalSource.supports_device_traces).
    supports_device_traces = True

    def batch_trace_device(self, steps: int, key, batch: int,
                           *, sharding=None) -> ExogenousTrace:
        """[B, T, ...] trace batch synthesized entirely on device.

        TPU-native path for training-scale generation: noise comes from
        `jax.random`, the AR(1) recurrences run as `associative_scan` (log-
        depth instead of a T-step loop), and assembly is the same formulas
        in jnp — zero host compute, zero host→device transfer. Statistically
        identical family to :meth:`batch_trace` (same diurnal structure,
        same AR(1) ρ/σ) but a different RNG stream, so use one or the other
        within an experiment; keyed reproducibly by ``key``.

        ``sharding`` (e.g. ``batch_sharding(mesh)``) makes the jitted
        program *produce* every leaf already distributed over the mesh's
        batch axis — at fleet scale the multi-GB trace batch must never
        materialize on one device just to be resharded afterwards.
        """
        import jax
        import jax.numpy as jnp

        fn = self._device_fns.get((steps, batch, sharding))
        if fn is None:
            z = self.cluster.n_zones

            def generate(k):
                ks, kc, kd = jax.random.split(k, 3)
                noise = (
                    _ar1_device(ks, (batch, steps, z), rho=0.97, sigma=0.04),
                    _ar1_device(kc, (batch, steps, z), rho=0.95, sigma=0.03),
                    _ar1_device(kd, (batch, steps), rho=0.9, sigma=0.5),
                )
                return self._assemble(steps, noise, xp=jnp)

            # One jitted program per shape: traced eagerly this would
            # dispatch every associative_scan stage as its own XLA program
            # (minutes of compile through the TPU tunnel); jitted it is one
            # fused program, ~1s to compile, ~ms to run.
            fn = jax.jit(generate, out_shardings=sharding)
            self._device_fns[(steps, batch, sharding)] = fn
        return fn(key)

    def packed_generate_fn(self, steps: int, batch: int,
                           *, t_chunk: int = 64):
        """Un-jitted ``key -> [T_pad, exo_rows(Z), B]`` packed-stream
        synthesis — the traceable core shared by
        :meth:`packed_trace_device` (which jits it) and the multi-chip
        wrapper (`parallel.sharded_kernel.sharded_packed_trace`, which
        runs it PER SHARD inside a `shard_map` body so each chip's exo
        block is born local and never crosses ICI)."""
        import jax
        import math as _math

        z = self.cluster.n_zones
        t_pad = _math.ceil(steps / t_chunk) * t_chunk
        lane_gens = self._lane_generators()

        def generate(k):
            ks, kc, kd = jax.random.split(k, 3)
            noise = (
                _ar1_device(ks, (steps, z, batch), rho=0.97,
                            sigma=0.04, axis=0),
                _ar1_device(kc, (steps, z, batch), rho=0.95,
                            sigma=0.03, axis=0),
                _ar1_device(kd, (steps, batch), rho=0.9, sigma=0.5,
                            axis=0),
            )
            packed = self._assemble_packed(steps, t_pad, noise)
            if not lane_gens:
                return packed
            import jax.numpy as _jnp

            # Registered lane families (ccka_tpu/sim/lanes registry):
            # appended AFTER the padded exo block in registration order
            # so existing row offsets never move; each family's
            # generator folds its OWN key tag off the same generation
            # key, so the exo streams' draws — and therefore the exo
            # rows — stay bitwise identical to an un-widened source on
            # the same key. The spot AR(1) anomaly rides the context
            # for the faults family's price-correlated hazard.
            ctx = dict(price_dev=noise[0], dt_s=self.sim.dt_s,
                       start_unix_s=self.start_unix_s)
            parts = [packed]
            for _name, cfg_f, gen_f in lane_gens:
                parts.append(gen_f(cfg_f, k, steps, t_pad, z, batch,
                                   ctx=ctx))
            return _jnp.concatenate(parts, axis=1)

        return generate

    def _lane_generators(self) -> list:
        """``(name, config, generate)`` per PRESENT lane family, in
        registration order — the generic synthesis plan both packed
        generators share (`sim/lanes.py` registry; generators resolve
        here, OUTSIDE the jitted trace)."""
        from ccka_tpu.sim import lanes as _lanes

        configs = {"faults": self.faults, "workloads": self.workloads,
                   **self.extra_lanes}
        plan = []
        for fam in _lanes.lane_families():
            cfg_f = configs.get(fam.name)
            if cfg_f is None:
                continue
            plan.append((fam.name, cfg_f, _lanes.lane_generator(fam.name)))
        return plan

    def packed_rows(self) -> int:
        """Row count of this source's packed stream layout — base exo
        block plus every present registered lane family's block."""
        from ccka_tpu.sim import lanes as _lanes

        z = self.cluster.n_zones
        rows = _lanes.exo_rows(z)
        for name, _cfg, _gen in self._lane_generators():
            rows += _lanes.LANE_FAMILIES[name].rows(z)
        return rows

    def packed_trace_device(self, steps: int, key, batch: int,
                            *, t_chunk: int = 64, recycle=None):
        """[T_pad, exo_rows(Z), B] feature-first exo stream synthesized
        DIRECTLY in the megakernel's packed layout (ARCHITECTURE §6
        lever): no [B, T, ...] trace ever materializes and no transpose
        runs — the AR(1) scans generate time-major [T, Z, B] and the
        diurnal assembly broadcasts in place, so the only HBM traffic is
        one write of the stream the kernel will read. Same generative
        family and parameters as :meth:`batch_trace_device` (a different
        RNG stream — statistically identical, not bitwise; use one or
        the other within an experiment). Feed the result to
        `sim.megakernel.megakernel_summary_from_packed`.

        ``recycle``: a dead stream buffer of the SAME shape (the second
        element of a ``donate_stream=True`` kernel return) — it is
        DONATED and the fresh stream is written into its memory, so a
        generate→rollout→generate loop holds one stream in HBM instead
        of allocating a second before freeing the first.
        """
        import jax

        recycled = recycle is not None
        cache_key = ("packed", steps, batch, t_chunk, recycled)
        fn = self._device_fns.get(cache_key)
        if fn is None:
            generate = self.packed_generate_fn(steps, batch,
                                               t_chunk=t_chunk)
            if recycled:
                # The buffer's VALUES are dead — only its memory is
                # reused, via donation aliased to the same-shaped output
                # (keep_unused: a pruned arg cannot donate).
                fn = jax.jit(lambda k, buf: generate(k),
                             donate_argnums=(1,), keep_unused=True)
            else:
                fn = jax.jit(generate)
            self._device_fns[cache_key] = fn
        return fn(key, recycle) if recycled else fn(key)

    def packed_block_generate_fn(self, block_T: int, batch: int,
                                 *, t_chunk: int = 64):
        """Un-jitted ``(key, t0_ticks) -> [block_T, exo_rows(Z), B]``
        BLOCK-wise packed synthesis — the streaming pipeline's
        generation unit (`sim/streaming.py`, ISSUE 13). ``key`` is the
        per-block world key (already folded by
        ``fold_in(fold_in(caller_key, lanes.BLOCK_KEY_TAG), j)`` — the
        caller owns the fold so sharded wrappers can fold the shard
        index on top, keeping blocked sharded generation bitwise the
        single-chip chunked one). ``t0_ticks`` is the block's traced
        global tick offset: diurnal/peak/workload phases anchor to the
        same wall clock the unblocked stream uses, and ONE compiled
        program serves every block.

        Each block is an independent same-family world segment (the
        AR(1) latents restart from their stationary draw at block
        boundaries — a new generative variant, statistically identical
        marginals, different stream; use blocked or unblocked within
        one experiment, the repo's standing RNG-family rule). Fault and
        workload lanes key off the BLOCK key via their own tags, so
        widening a blocked stream changes neither the exo nor the fault
        rows bitwise — per block, exactly the unblocked invariant."""
        import jax
        import jax.numpy as jnp

        from ccka_tpu.sim import lanes as _lanes

        _lanes.block_layout(block_T, block_T, t_chunk)  # divisibility
        z = self.cluster.n_zones
        dt_s, start_s = self.sim.dt_s, self.start_unix_s
        lane_gens = self._lane_generators()

        def generate(k, t0_ticks):
            ks, kc, kd = jax.random.split(k, 3)
            noise = (
                _ar1_device(ks, (block_T, z, batch), rho=0.97,
                            sigma=0.04, axis=0),
                _ar1_device(kc, (block_T, z, batch), rho=0.95,
                            sigma=0.03, axis=0),
                _ar1_device(kd, (block_T, batch), rho=0.9, sigma=0.5,
                            axis=0),
            )
            packed = self._assemble_packed(block_T, block_T, noise,
                                           t0_ticks=t0_ticks)
            if not lane_gens:
                return packed
            # Same generic registry iteration as `packed_generate_fn`;
            # the block's global tick offset rides the context so
            # families with a diurnal clock (workloads) stay anchored
            # to the same wall clock the unblocked stream uses.
            ctx = dict(
                price_dev=noise[0], dt_s=dt_s, start_unix_s=start_s,
                start_offset_s=jnp.full(
                    (batch,), jnp.asarray(t0_ticks, jnp.float32) * dt_s))
            parts = [packed]
            for _name, cfg_f, gen_f in lane_gens:
                parts.append(gen_f(cfg_f, k, block_T, block_T, z, batch,
                                   ctx=ctx))
            return jnp.concatenate(parts, axis=1)

        return generate

    def packed_block_trace_device(self, block_T: int, key, batch: int,
                                  block_index, *, t_chunk: int = 64,
                                  recycle=None, shard=None,
                                  total_steps: int | None = None):
        """One ``[block_T, exo_rows(Z), B]`` stream BLOCK on device:
        block ``block_index`` of the blocked stream family keyed by
        ``key`` (see :meth:`packed_block_generate_fn` — the per-block
        fold and the ``j * block_T`` tick offset are applied here, so
        callers hand the SAME caller key for every block). One compiled
        program serves all blocks: ``block_index`` is traced.
        ``recycle``: donate a dead same-shape block buffer (the aliased
        return of a ``donate_stream=True`` block launch) so the
        double-buffer holds exactly two blocks per chip. ``shard``:
        optional shard/cluster-chunk index folded AFTER the block fold
        — the cluster-axis chunking path generates chunk ``c``'s block
        bitwise as mesh shard ``c`` would (the sharded wrapper folds
        `lax.axis_index` at the same position). ``total_steps`` is
        accepted for signature uniformity with the replay backend
        (synthetic worlds need no horizon-length extension)."""
        import jax
        import jax.numpy as jnp

        from ccka_tpu.sim import lanes as _lanes

        del total_steps  # uniform signature; unused by synthesis
        recycled = recycle is not None
        sharded = shard is not None
        cache_key = ("packed_block", block_T, batch, t_chunk, recycled,
                     sharded)
        fn = self._device_fns.get(cache_key)
        if fn is None:
            generate = self.packed_block_generate_fn(block_T, batch,
                                                     t_chunk=t_chunk)

            def block(k, j, *shard_arg):
                kj = jax.random.fold_in(
                    jax.random.fold_in(k, _lanes.BLOCK_KEY_TAG), j)
                if shard_arg:
                    kj = jax.random.fold_in(kj, shard_arg[0])
                return generate(kj, j * jnp.int32(block_T))

            if recycled:
                fn = jax.jit(lambda k, j, *rest: block(k, j, *rest[:-1]),
                             donate_argnums=(2 + sharded,),
                             keep_unused=True)
            else:
                fn = jax.jit(block)
            self._device_fns[cache_key] = fn
        j = jnp.int32(block_index)
        args = (key, j) + ((jnp.int32(shard),) if sharded else ())
        return fn(*args, recycle) if recycled else fn(*args)

    def _assemble_packed(self, steps: int, t_pad: int, noise: tuple,
                         t0_ticks=None):
        """The `_assemble` formulas in time-major packed form: noise
        [T, Z, B]/[T, B] → [T_pad, exo_rows(Z), B] with the row order
        `sim.megakernel._pack_exo` defines (spot, od, carbon, demand,
        is_peak; zero padding). `tests/test_megakernel.py` pins this
        against `_assemble` on identical noise so the two layouts cannot
        drift.

        ``t0_ticks``: optional (traced) global tick offset of this
        stream's first row — the streaming pipeline generates block j
        at offset ``j * block_T`` so the diurnal/peak phases stay
        anchored to the SAME wall clock the unblocked stream uses. The
        day reduction of ``start_unix_s`` happens on host in float64
        BEFORE the f32 tick arithmetic (at unix-epoch scale the f32 ulp
        is 128 s — the workload lanes pin the same pitfall). ``None``
        keeps the exact host-numpy path existing callers compile."""
        import jax.numpy as jnp

        xp = jnp
        spot_noise, carbon_noise, demand_noise = noise
        B = demand_noise.shape[-1]
        dt = self.sim.dt_s
        if t0_ticks is None:
            t = self.start_unix_s + np.arange(steps) * dt       # [T]
            tod = xp.asarray((t % _DAY_S) / _DAY_S, dtype=xp.float32)
        else:
            base = np.float32(self.start_unix_s % _DAY_S)
            ticks = (xp.asarray(t0_ticks, xp.float32)
                     + xp.arange(steps, dtype=xp.float32))      # [T]
            tod = xp.mod(base + xp.mod(ticks * np.float32(dt),
                                       np.float32(_DAY_S)),
                         np.float32(_DAY_S)) / np.float32(_DAY_S)
        tod_zb = tod[:, None, None]                              # [T,1,1]
        nt = self.cluster.node_type
        zp = {k: xp.asarray(v)[None, :, None] for k, v in self._zp.items()}

        diurnal = 1.0 + 0.35 * xp.sin(
            2 * np.pi * (tod_zb - 0.25 + zp["spot_phase"]))      # [T,Z,1]
        spot = (nt.spot_price_hr_mean * zp["spot_scale"] * diurnal
                * (1.0 + spot_noise))                            # [T,Z,B]
        od_z = xp.float32(nt.od_price_hr) * zp["od_scale"]       # [1,Z,1]
        spot = xp.clip(spot, 0.2 * od_z, 0.95 * od_z)
        od = xp.broadcast_to(od_z, spot.shape)

        base = zp["carbon_base"]
        solar = zp["solar_frac"] * base * _bump(
            tod_zb + zp["solar_phase"], center=13.5 / 24,
            width=3.5 / 24, xp=xp)
        evening = 0.25 * base * _bump(
            tod_zb + zp["evening_phase"], center=19.5 / 24,
            width=2.0 / 24, xp=xp)
        carbon = (base - solar + evening) * zp["carbon_scale"]
        carbon = xp.clip(carbon * (1.0 + carbon_noise), 20.0, None)

        total = float(self.workload.total_pods)
        level = total * (0.4 + 0.6 * _bump(tod, center=14.0 / 24,
                                           width=5.0 / 24, xp=xp))[:, None]
        level = xp.clip(level * (1.0 + 0.15 * demand_noise),
                        0.0, 2.0 * total)                        # [T,B]
        demand = xp.stack([xp.ceil(level / 2.0),
                           xp.floor(level / 2.0)], axis=1)       # [T,2,B]

        is_peak = ((tod >= 9 / 24) & (tod < 21 / 24)).astype(xp.float32)
        peak_row = xp.broadcast_to(is_peak[:, None, None],
                                   (steps, 1, B))

        packed = xp.concatenate(
            [spot, od, carbon, demand, peak_row], axis=1
        ).astype(xp.float32)                           # [T, 3Z+3, B]
        # The kernel's own row-count helper, so a layout change there
        # cannot silently desynchronize this generator.
        from ccka_tpu.sim.megakernel import _exo_rows
        rows_pad = _exo_rows(self.cluster.n_zones)
        return xp.pad(packed, ((0, t_pad - steps),
                               (0, rows_pad - packed.shape[1]), (0, 0)))

    def _assemble(self, steps: int, noise: tuple, xp=np) -> ExogenousTrace:
        """Deterministic diurnal structure + noise → trace.

        ``noise`` arrays may carry a leading batch axis [B, T, ...]; the
        deterministic parts broadcast against it, and the returned trace
        then has batch-leading leaves ([B, T, Z] etc.). ``xp`` selects the
        array backend: numpy (host path) or jax.numpy (device path).
        """
        spot_noise, carbon_noise, demand_noise = noise
        batched = spot_noise.ndim == 3
        z = self.cluster.n_zones
        dt = self.sim.dt_s
        t = self.start_unix_s + np.arange(steps) * dt  # [T]
        # f32 from here on — everything downstream is f32, and at fleet
        # scale (B=8192) f64 intermediates double the assembly cost.
        tod = xp.asarray(((t % _DAY_S) / _DAY_S), dtype=xp.float32)  # [0,1)
        tod_z = tod[:, None]  # [T, 1] broadcast against zones

        nt = self.cluster.node_type
        # Per-zone grid/price profile [Z] arrays (region-aware; see
        # `_zone_params`). Deterministic given the cluster topology.
        zp = {k: xp.asarray(v) for k, v in self._zp.items()}

        # Spot price: diurnal swing + AR(1) noise, clipped to [20%, 95%] of OD.
        diurnal = 1.0 + 0.35 * xp.sin(
            2 * np.pi * (tod_z - 0.25 + zp["spot_phase"]))  # [T,Z]
        spot = (nt.spot_price_hr_mean * zp["spot_scale"] * diurnal
                * (1.0 + spot_noise))
        od_z = xp.float32(nt.od_price_hr) * zp["od_scale"]  # [Z]
        spot = xp.clip(spot, 0.2 * od_z, 0.95 * od_z)

        od = xp.broadcast_to(od_z, spot.shape)

        # Carbon duck curve per zone: base − solar dip (centered 13:00 local
        # solar time) + evening ramp (centered 19:30), small noise; clipped
        # positive. In multi-region mode base/dip-depth/phase come from the
        # region's grid profile, so e.g. CAISO-west dips deep mid-day while
        # MISO-east barely moves.
        base = zp["carbon_base"]  # [Z]
        solar = zp["solar_frac"] * base * _bump(
            tod_z + zp["solar_phase"], center=13.5 / 24, width=3.5 / 24, xp=xp)
        evening = 0.25 * base * _bump(tod_z + zp["evening_phase"],
                                      center=19.5 / 24, width=2.0 / 24, xp=xp)
        carbon = base - solar + evening
        carbon = carbon * zp["carbon_scale"]
        carbon = carbon * (1.0 + carbon_noise)
        carbon = xp.clip(carbon, 20.0, None)

        # Peak indicator 09:00-21:00.
        is_peak = ((tod >= 9 / 24) & (tod < 21 / 24)).astype(xp.float32)
        if batched:
            is_peak = xp.broadcast_to(is_peak, demand_noise.shape)

        # Demand: base 40% of burst scale off-peak, ramping to the full
        # 60-pod burst at peak, with bursty noise; split between the two
        # classes like the reference's odd/even deployments.
        total = float(self.workload.total_pods)
        level = total * (0.4 + 0.6 * _bump(tod, center=14.0 / 24,
                                           width=5.0 / 24, xp=xp))
        level = level * (1.0 + 0.15 * demand_noise)
        level = xp.clip(level, 0.0, 2.0 * total)
        demand = xp.stack([xp.ceil(level / 2.0), xp.floor(level / 2.0)], axis=-1)

        trace = ExogenousTrace(
            spot_price_hr=as_f32(spot),
            od_price_hr=as_f32(od),
            carbon_g_kwh=as_f32(carbon),
            demand_pods=as_f32(demand),
            is_peak=as_f32(is_peak),
        )
        trace.validate_shapes()
        return trace


def _ar1(rng: np.random.Generator, shape, rho: float, sigma: float) -> np.ndarray:
    """Stationary AR(1) noise along axis 0, vectorized.

    Same draw order as the recurrence ``x0 = N(0,σ); x_t = ρ·x_{t-1} +
    √(1-ρ²)·N(0,σ)`` stepped in Python (one ``normal`` stream, first draw is
    the initial condition), but the recursion runs in `scipy.signal.lfilter`
    — O(T) in C instead of O(T) Python iterations.
    """
    from scipy.signal import lfilter

    steps = shape[0]
    rest = shape[1:]
    # float32 end to end: the simulator consumes f32, and halving the noise
    # buffers matters at fleet scale (B=8192 x T=2880 is ~300MB per family).
    eps = rng.standard_normal(size=(steps + 1,) + rest, dtype=np.float32)
    eps *= np.float32(sigma)
    scale = np.float32(np.sqrt(1.0 - rho * rho))
    # y[0] = scale*eps[1] + rho*x0, y[t] = scale*eps[t+1] + rho*y[t-1].
    zi = (np.float32(rho) * eps[0])[None, ...]
    out, _ = lfilter(np.asarray([scale], np.float32),
                     np.asarray([1.0, -rho], np.float32), eps[1:],
                     axis=0, zi=zi)
    return out


def _bump(x, center: float, width: float, xp=np):
    """Smooth periodic bump in [0,1] centered at ``center`` (day fraction)."""
    d = xp.minimum(xp.abs(x - center), 1.0 - xp.abs(x - center))
    return xp.exp(-0.5 * (d / (width / 2.0)) ** 2)


def _ar1_device(key, shape, rho: float, sigma: float, axis: int | None = None):
    """Stationary AR(1) along the time axis (default: axis -2 of
    [..., T, Z] or axis -1 of [..., T]; the packed layout passes
    ``axis=0`` for [T, ...]), on device via log-depth `associative_scan`.

    Same recurrence as :func:`_ar1`: ``x_0 ~ N(0,σ)`` then
    ``x_t = ρ·x_{t-1} + √(1-ρ²)·N(0,σ)`` — expressed as the linear map
    composition ``(a,b)∘(a',b') = (aa', a'b + b')`` scanned associatively,
    so the TPU runs O(log T) passes of elementwise work instead of a
    T-iteration loop.
    """
    import jax
    import jax.numpy as jnp

    if axis is None:
        axis = len(shape) - 2 if len(shape) >= 3 else len(shape) - 1
    k0, k1 = jax.random.split(key)
    scale = np.float32(np.sqrt(1.0 - rho * rho))
    x0_shape = shape[:axis] + (1,) + shape[axis + 1:]
    x0 = sigma * jax.random.normal(k0, x0_shape, jnp.float32)
    eps = scale * sigma * jax.random.normal(k1, shape, jnp.float32)
    a = jnp.full(shape, np.float32(rho))

    def combine(l, r):
        return (l[0] * r[0], l[1] * r[0] + r[1])

    _, b = jax.lax.associative_scan(combine, (a, eps), axis=axis)
    # b_t composes all noise up to t; apow_t = ρ^(t+1) carries the initial
    # state forward: x_t = ρ^(t+1)·x_0 + Σ_i ρ^(t-i)·e_i.
    apow = jnp.cumprod(a, axis=axis)
    return apow * x0 + b
