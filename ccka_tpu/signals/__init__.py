"""Signal ingestion layer.

The reference's signal plane is a metrics pipeline — kube-state-metrics →
ADOT collector (30s scrape) → SigV4 → Amazon Managed Prometheus
(`06_opencost.sh:318-341`) — queried back by OpenCost and Grafana through a
SigV4 proxy (`06_opencost.sh:426`, `demo_40_watch_observe.sh:106-110`), plus a
carbon-intensity stub that falls back to a dummy ~400 g/kWh when no API key is
set (`.env:14-16`).

Here every signal is a :class:`~ccka_tpu.signals.base.SignalSource` with three
interchangeable backends:

- ``synthetic``  — sinusoidal diurnal price/carbon + bursty demand (the
  reference's dummy-carbon fallback, generalized);
- ``replay``     — replays stored traces (the AMP time-series store analog);
- ``live``       — real HTTP clients for Prometheus-compatible APIs, OpenCost
  and ElectricityMaps-style carbon APIs.

All backends emit the same device-ready :class:`ExogenousTrace` tensor bundle,
so the simulator, the rule policy and the learned policies are agnostic to
where signals come from.
"""

from ccka_tpu.signals.base import ExogenousTrace, SignalSource, TraceMeta  # noqa: F401
from ccka_tpu.signals.synthetic import SyntheticSignalSource  # noqa: F401
from ccka_tpu.signals.replay import ReplaySignalSource, save_trace, load_trace  # noqa: F401
from ccka_tpu.signals.live import (  # noqa: F401
    PrometheusClient,
    OpenCostClient,
    CarbonIntensityClient,
    LiveSignalSource,
)
