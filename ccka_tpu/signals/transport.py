"""Concurrent scrape transport: thread-pool fan-in with per-tenant
deadlines (round 21, the fleet-scale host loop's real-I/O half).

`harness/service.py` models tenant scrapes on a :class:`VirtualClock`
— deterministic, fast, and the deadline arithmetic is identical to
real time — but a real fleet scrapes N HTTP endpoints, and a
sequential walk over 10^4 sockets cannot fit any tick budget. This
module is the same ``_scrape`` contract (``(ok, timed_out)`` within a
budget) over a real concurrent transport, seeded by the in-process
HTTP round-trip idiom of ``tests/test_http_integration.py``:

- **fan-in, not fan-out-and-wait**: every ready tenant's fetch is
  submitted to one bounded thread pool at once; results are gathered
  until the budget edge and NOT ONE MICROSECOND past it.
- **stragglers abandoned, never awaited**: a fetch that misses the
  deadline is left to its own socket timeout and recorded as a
  timeout; the service defers/breakers it exactly like a virtual
  hung scrape. While a tenant's previous fetch is still hung, a new
  attempt fails fast instead of stacking a second request behind a
  dead endpoint.
- **deterministic tests keep the VirtualClock path**: the service
  only routes through a transport when one is injected.

Clock waits here are socket/pool waits on the REAL monotonic clock by
design — this module holds no device code (no jax anywhere), which is
exactly the condition the AST timing guard
(`tests/test_timing_guard.py`) enforces; it scans this file and finds
no un-fenced clock next to a device marker.
"""

from __future__ import annotations

import functools
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Callable, Sequence

Fetcher = Callable[[], bytes]


class ScrapeFanIn:
    """N per-tenant fetchers behind the FleetService scrape contract.

    ``fetchers[i]`` is a zero-arg callable performing tenant i's
    scrape (raising on failure); each should carry its OWN bounded
    socket timeout so an abandoned straggler eventually frees its
    worker thread. ``clock`` is injectable for tests; the pool is
    bounded (a 10^4-tenant fleet must not spawn 10^4 threads — ready
    tenants queue through the pool inside the same budget)."""

    def __init__(self, fetchers: Sequence[Fetcher], *,
                 workers: int | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self._fetchers = list(fetchers)
        self.n = len(self._fetchers)
        self._clock = clock
        self._pool = ThreadPoolExecutor(
            max_workers=workers or min(32, max(4, self.n)),
            thread_name_prefix="ccka-scrape")
        # tenant -> still-running Future from a previous budget window.
        self._stragglers: dict = {}
        self.completed_total = 0
        self.failed_total = 0
        self.abandoned_total = 0

    # -- the service contract -------------------------------------------

    def scrape(self, i: int, budget_s: float) -> tuple:
        """One tenant within ``budget_s`` → (ok, timed_out); the
        sequential `_scrape` surface (object host loop)."""
        return self.fan_in([i], budget_s)[i]

    def fan_in(self, tenants: Sequence[int], budget_s: float) -> dict:
        """Launch every tenant's fetch concurrently, gather until the
        budget edge; returns {tenant: (ok, timed_out)}. Stragglers are
        abandoned — their futures are never awaited again, only
        checked for doneness if the same tenant comes back."""
        deadline = self._clock() + max(budget_s, 0.0)
        pending: dict = {}
        results: dict = {}
        for i in tenants:
            prev = self._stragglers.pop(i, None)
            if prev is not None and not prev.done():
                # Previous scrape still hung: fail fast, keep tracking.
                self._stragglers[i] = prev
                results[i] = (False, True)
                continue
            pending[self._pool.submit(self._fetchers[i])] = i
        while pending:
            remaining = deadline - self._clock()
            if remaining <= 0.0:
                break
            done, _ = wait(set(pending), timeout=remaining,
                           return_when=FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                i = pending.pop(fut)
                try:
                    fut.result()
                except Exception:
                    results[i] = (False, False)
                    self.failed_total += 1
                else:
                    results[i] = (True, False)
                    self.completed_total += 1
        for fut, i in pending.items():
            # Abandoned at the budget edge: never awaited past here.
            results[i] = (False, True)
            self._stragglers[i] = fut
            self.abandoned_total += 1
        return results

    def stragglers(self) -> list:
        """Tenants whose last fetch is STILL in flight (hung sockets
        the pool is carrying; drains as their own timeouts fire)."""
        return sorted(i for i, f in self._stragglers.items()
                      if not f.done())

    def close(self) -> None:
        """Release the pool without awaiting stragglers (their own
        socket timeouts unwind the worker threads)."""
        self._pool.shutdown(wait=False, cancel_futures=True)


def http_scrape_fan_in(urls: Sequence[str], *, timeout_s: float = 5.0,
                       workers: int | None = None,
                       clock: Callable[[], float] = time.monotonic,
                       fetch=None) -> ScrapeFanIn:
    """Fan-in over per-tenant metric URLs through the signals-layer
    urllib transport (`signals/live.default_fetch`). ``timeout_s`` is
    each socket's own bound — the straggler drain above."""
    from ccka_tpu.signals.live import default_fetch
    f = fetch or default_fetch(timeout_s)
    return ScrapeFanIn(
        [functools.partial(f, url, {}) for url in urls],
        workers=workers, clock=clock)
