"""Replay signal backend: stored traces as the time-series store.

The reference durably stores metrics in Amazon Managed Prometheus
(`06_opencost.sh:153-163`) and queries them back over its API
(`demo_40_watch_observe.sh:106-110`). The replay backend is that store's
role in this framework: traces captured from live scraping (or generated
synthetically) are saved as compressed ``.npz`` files and replayed
deterministically for policy training and evaluation on held-out data
(BASELINE.json config #3: "replayed OpenCost/ElectricityMaps traces").
"""

from __future__ import annotations

import json
import math
import os
import warnings
from typing import Mapping

import numpy as np

from ccka_tpu.signals.base import ExogenousTrace, SignalSource, TraceMeta, as_f32

_FIELDS = ("spot_price_hr", "od_price_hr", "carbon_g_kwh", "demand_pods", "is_peak")


def save_trace(path: str, trace: ExogenousTrace, meta: TraceMeta) -> None:
    """Persist a trace + provenance to ``path`` (.npz)."""
    arrays = {k: np.asarray(getattr(trace, k)) for k in _FIELDS}
    arrays["__meta__"] = np.frombuffer(
        json.dumps({
            "source": meta.source,
            "start_unix_s": meta.start_unix_s,
            "dt_s": meta.dt_s,
            "zones": list(meta.zones),
            "description": meta.description,
        }).encode("utf-8"), dtype=np.uint8)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez_compressed(path, **arrays)


def load_trace(path: str) -> tuple[ExogenousTrace, TraceMeta]:
    with np.load(path) as data:
        trace = ExogenousTrace(**{k: as_f32(data[k]) for k in _FIELDS})
        raw = bytes(data["__meta__"].tobytes()) if "__meta__" in data else b"{}"
    md = json.loads(raw.decode("utf-8") or "{}")
    meta = TraceMeta(
        source=md.get("source", "replay"),
        start_unix_s=float(md.get("start_unix_s", 0.0)),
        dt_s=float(md.get("dt_s", 30.0)),
        zones=tuple(md.get("zones", ())),
        description=md.get("description", ""),
    )
    trace.validate_shapes()
    return trace, meta


class ReplaySignalSource(SignalSource):
    """Replays a stored trace; deterministic, seed-independent.

    ``trace(steps)`` tiles the stored trace if a longer horizon is requested
    (periodic extension — diurnal signals tile naturally) and slices if
    shorter. ``offset_steps`` selects held-out evaluation windows.
    """

    def __init__(self, trace: ExogenousTrace, meta: TraceMeta,
                 *, offset_steps: int = 0, faults=None, workloads=None):
        trace.validate_shapes()
        self._trace = trace
        self._meta = meta
        self.offset_steps = offset_steps
        # Fault-injection disturbances (`config.FaultsConfig`): replayed
        # worlds are recorded calm weather — the stored trace carries no
        # preemption storms/ICE/outages — so the fault lanes are
        # SYNTHESIZED on top of the replayed windows (packed path only),
        # keyed by the window-sampling key: same key → same windows AND
        # same faults, the pairing contract of the synthetic backend.
        self.faults = faults if (faults is not None
                                 and faults.enabled) else None
        # Workload families (`config.WorkloadsConfig`): same treatment —
        # the stored trace records only the primary demand, so family
        # arrivals are synthesized on top of the sampled windows,
        # appended after the fault block and keyed by the same
        # window-sampling key.
        self.workloads = workloads if (workloads is not None
                                       and workloads.enabled) else None

    @classmethod
    def from_file(cls, path: str, *, offset_steps: int = 0,
                  faults=None, workloads=None) -> "ReplaySignalSource":
        trace, meta = load_trace(path)
        return cls(trace, meta, offset_steps=offset_steps, faults=faults,
                   workloads=workloads)

    def meta(self) -> TraceMeta:
        return self._meta

    def trace(self, steps: int, *, seed: int = 0) -> ExogenousTrace:
        del seed  # replay is deterministic
        return self._trace_at(self.offset_steps, steps)

    def _trace_at(self, offset: int, steps: int) -> ExogenousTrace:
        stored = self._trace.steps
        need = offset + steps
        if need > stored:
            reps = -(-need // stored)  # ceil
            full = ExogenousTrace(*[
                np.concatenate([np.asarray(a)] * reps, axis=-2)
                if a.ndim >= 2 else np.concatenate([np.asarray(a)] * reps, axis=-1)
                for a in self._trace
            ])
            full = ExogenousTrace(*[as_f32(a) for a in full])
        else:
            full = self._trace
        return full.slice_steps(offset, steps)

    def batch_trace(self, steps: int, seeds) -> ExogenousTrace:
        """[B, T, ...] batch of *distinct windows* into the stored trace.

        The base default stacks ``trace(steps, seed=s)`` per seed, but
        replay ignores seeds — that would hand a PPO batch B identical
        clusters, silently collapsing BASELINE config #3 ("256 clusters
        vmap'd on replayed traces") to one. Instead seed ``s`` replays
        from offset ``s·step mod stored`` with ``step`` coprime to the
        stored length (≈ golden-ratio spacing): a bijection on offsets,
        so distinct seeds give distinct windows whenever that is possible
        at all (seeds colliding mod ``stored`` is pigeonhole — warned).
        """
        import jax
        import jax.numpy as jnp

        seeds = [int(s) for s in seeds]
        stored = self._trace.steps
        # Multiplier near stored/φ, nudged to coprimality → offset bijection.
        step = max(1, round(stored * 0.6180339887498949))
        while math.gcd(step, stored) != 1:
            step += 1
        if len({s % stored for s in seeds}) < len(seeds):
            warnings.warn(
                f"replay batch_trace: {len(seeds)} seeds over a "
                f"{stored}-step store must repeat windows (pigeonhole); "
                "capture a longer trace for a fully distinct batch",
                stacklevel=2)
        # Tile the periodic extension ONCE (every offset lies in
        # [0, stored)), so the per-seed work is pure slicing — not a
        # device round-trip + re-tile per element.
        ext = self._trace_at(0, stored + steps)
        windows = [ext.slice_steps((self.offset_steps + s * step) % stored,
                                   steps)
                   for s in seeds]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *windows)

    def _window_offsets(self, key, n: int):
        """The ONE per-window offset draw (traceable, [n] int32).

        Both `batch_trace_device` (the exo windows) and the packed
        workload-lane path (which phases each window's diurnal family
        shapes to the demand it replays) MUST consume these same draws
        from the same key — the lanes' phase alignment holds only
        because this is the single place the offsets are sampled.
        """
        import jax

        stored = self._trace.steps
        return (self.offset_steps
                + jax.random.randint(key, (n,), 0, stored)) % stored

    def batch_trace_device(self, steps: int, key, n: int,
                           *, sharding=None) -> ExogenousTrace:
        """[n, T, ...] window batch sampled ON DEVICE: offsets uniform
        over the stored length, fresh per ``key`` (the mega ES engine's
        fresh-traces-per-generation contract — `train/cem.py`), windows
        gathered from the device-resident periodic extension under vmap.
        Windows may overlap (the store is finite); for ES fitness that
        is sampling with replacement over the window population, not a
        collapse — paired candidates still see identical batches.

        Signature-aligned with the synthetic backend so batch-path
        callers can pass ``sharding=None`` uniformly; actually honoring
        a sharding would require resharding a host-resident store, which
        this backend does not do (``supports_device_traces`` stays
        False — the `--device-traces` CLI path refuses replay up front).
        """
        if sharding is not None:
            raise SystemExit(
                "ccka: replay traces are sampled from a host-resident "
                "store and cannot be synthesized into a device sharding; "
                "use the synthetic signals backend for sharded "
                "--device-traces fleets")
        import jax
        import jax.numpy as jnp

        stored = self._trace.steps
        if getattr(self, "_ext_steps", None) != steps:
            # Tile once per window length; reused across generations.
            self._ext_dev = jax.tree.map(
                jnp.asarray, self._trace_at(0, stored + steps))
            self._ext_steps = steps
        ext = self._ext_dev
        offs = self._window_offsets(key, n)

        def window(o):
            def sl(a):
                if a.ndim == 2:                              # [T, k]
                    return jax.lax.dynamic_slice(
                        a, (o, 0), (steps, a.shape[1]))
                return jax.lax.dynamic_slice(a, (o,), (steps,))
            return jax.tree.map(sl, ext)

        return jax.vmap(window)(offs)

    def packed_trace_device(self, steps: int, key, n: int,
                            *, t_chunk: int = 64, recycle=None):
        """``[T_pad, exo_rows(Z), n]`` kernel-layout stream of
        device-sampled replay windows: the window batch of
        :meth:`batch_trace_device` (SAME offsets for the same key)
        followed by the megakernel's pack. A replay store is batch-major
        at rest, so the pack transpose is paid here — but the stream
        then feeds the packed kernel entries and their donated-buffer
        chain uniformly with the synthetic backend (`train/cem.py` mega
        engine). ``recycle``: donate a dead same-shape stream buffer so
        the fresh pack reuses its memory (see the synthetic backend's
        docstring)."""
        import jax

        from ccka_tpu.sim.megakernel import _pack_exo

        t_pad = math.ceil(steps / t_chunk) * t_chunk
        recycled = recycle is not None
        if not hasattr(self, "_packed_fns"):
            self._packed_fns = {}
        ckey = (steps, n, t_chunk, recycled)
        fn = self._packed_fns.get(ckey)
        if fn is None:
            import jax.numpy as jnp

            faults = self.faults
            workloads = self.workloads
            Z = self._trace.n_zones
            dt_s = self._meta.dt_s or 30.0
            start_s = self._meta.start_unix_s

            def pack(tr, k):
                packed = _pack_exo(tr, t_pad)
                if faults is None and workloads is None:
                    return packed
                parts = [packed]
                if faults is not None:
                    # Fault lanes on replayed windows (see __init__):
                    # the stored trace is calm weather, so disturbances
                    # are synthesized here — appended after the padded
                    # exo block like the synthetic backend's, keyed by
                    # the same window-sampling key. No price_dev: the
                    # stored spot series carries no separable anomaly
                    # channel, so the price-correlated hazard term is
                    # synthetic-only.
                    from ccka_tpu.faults.process import packed_fault_lanes
                    parts.append(packed_fault_lanes(faults, k, steps,
                                                    t_pad, Z, n))
                if workloads is not None:
                    # Workload lanes on replayed windows: appended LAST
                    # like the synthetic backend's, same key. Each
                    # window replays from its own offset into the store
                    # (`_window_offsets` — the shared draw
                    # `batch_trace_device` consumes from this same key)
                    # so the diurnal/anti-diurnal family shapes are
                    # phased per window to the demand it actually sees.
                    from ccka_tpu.workloads.process import (
                        packed_workload_lanes)
                    offs = self._window_offsets(k, n)
                    parts.append(packed_workload_lanes(
                        workloads, k, steps, t_pad, Z, n, dt_s=dt_s,
                        start_unix_s=start_s,
                        start_offset_s=offs.astype(jnp.float32) * dt_s,
                        wrap_period_s=self._trace.steps * dt_s))
                return jnp.concatenate(parts, axis=1)

            if recycled:
                fn = jax.jit(lambda tr, k, buf: pack(tr, k),
                             donate_argnums=(2,), keep_unused=True)
            else:
                fn = jax.jit(pack)
            self._packed_fns[ckey] = fn
        trace = self.batch_trace_device(steps, key, n)
        return fn(trace, key, recycle) if recycled else fn(trace, key)

    def packed_block_trace_device(self, block_T: int, key, n: int,
                                  block_index, *, total_steps: int,
                                  t_chunk: int = 64, recycle=None,
                                  shard=None):
        """One ``[block_T, exo_rows(Z), n]`` stream BLOCK of replayed
        windows — the replay analog of the synthetic backend's
        :meth:`~ccka_tpu.signals.synthetic.SyntheticSignalSource.packed_block_trace_device`
        (ISSUE 13). Window offsets are drawn ONCE from ``key`` (the same
        `_window_offsets` draw every block of that key consumes), so
        block ``j`` replays ticks ``[j*block_T, (j+1)*block_T)`` of the
        exact windows the unblocked ``packed_trace_device(total_steps,
        key, n)`` replays — the exo rows of a blocked run concatenate
        bitwise to the unblocked stream's. Fault/workload lanes key off
        the per-block fold (``fold_in(fold_in(key, BLOCK_KEY_TAG), j)``
        via their own tags), the same blocked-lane family the synthetic
        backend emits. ``total_steps`` names the full horizon (for the
        periodic extension's length and the blocked-layout check);
        ``block_index`` is traced — one compiled program serves every
        block. ``recycle``: donate a dead same-shape block buffer.
        ``shard``: optional cluster-chunk index folded into the caller
        key (each chunk samples its own windows — replay supports no
        device mesh, so there is no mesh realization to pair with)."""
        import jax
        import jax.numpy as jnp

        from ccka_tpu.sim import lanes as _lanes
        from ccka_tpu.sim.megakernel import _pack_exo

        _lanes.block_layout(block_T, block_T, t_chunk)  # divisibility
        stored = self._trace.steps
        if getattr(self, "_blk_ext_steps", None) != (total_steps, block_T):
            # + block_T of slack: the final block covers the PADDED
            # horizon, which can run past total_steps by up to a block
            # (the kernel's valid gate masks those ticks; the extension
            # just has to keep the slice in bounds).
            self._blk_ext = jax.tree.map(
                jnp.asarray,
                self._trace_at(0, stored + total_steps + block_T))
            self._blk_ext_steps = (total_steps, block_T)
        recycled = recycle is not None
        if not hasattr(self, "_packed_fns"):
            self._packed_fns = {}
        ckey = ("block", block_T, n, t_chunk, recycled)
        fn = self._packed_fns.get(ckey)
        if fn is None:
            faults = self.faults
            workloads = self.workloads
            Z = self._trace.n_zones
            dt_s = self._meta.dt_s or 30.0
            start_s = self._meta.start_unix_s

            def block(ext, k, j):
                offs = self._window_offsets(k, n)            # [n]
                t0 = offs + j * jnp.int32(block_T)

                def window(o):
                    def sl(a):
                        if a.ndim == 2:                      # [T, k]
                            return jax.lax.dynamic_slice(
                                a, (o, 0), (block_T, a.shape[1]))
                        return jax.lax.dynamic_slice(a, (o,), (block_T,))
                    return jax.tree.map(sl, ext)

                tr = jax.vmap(window)(t0)                    # [n, bT, ..]
                packed = _pack_exo(tr, block_T)
                if faults is None and workloads is None:
                    return packed
                kj = jax.random.fold_in(
                    jax.random.fold_in(k, _lanes.BLOCK_KEY_TAG), j)
                parts = [packed]
                if faults is not None:
                    from ccka_tpu.faults.process import packed_fault_lanes
                    parts.append(packed_fault_lanes(
                        faults, kj, block_T, block_T, Z, n))
                if workloads is not None:
                    from ccka_tpu.workloads.process import (
                        packed_workload_lanes)
                    parts.append(packed_workload_lanes(
                        workloads, kj, block_T, block_T, Z, n,
                        dt_s=dt_s, start_unix_s=start_s,
                        start_offset_s=t0.astype(jnp.float32) * dt_s,
                        wrap_period_s=stored * dt_s))
                return jnp.concatenate(parts, axis=1)

            if recycled:
                fn = jax.jit(lambda ext, k, j, buf: block(ext, k, j),
                             donate_argnums=(3,), keep_unused=True)
            else:
                fn = jax.jit(block)
            self._packed_fns[ckey] = fn
        if shard is not None:
            key = jax.random.fold_in(key, shard)
        j = jnp.int32(block_index)
        return (fn(self._blk_ext, key, j, recycle) if recycled
                else fn(self._blk_ext, key, j))


def trace_from_arrays(arrays: Mapping[str, np.ndarray], dt_s: float,
                      zones: tuple[str, ...]) -> tuple[ExogenousTrace, TraceMeta]:
    """Build a replayable trace from raw arrays (e.g. parsed Prometheus
    query_range results)."""
    trace = ExogenousTrace(**{k: as_f32(arrays[k]) for k in _FIELDS})
    trace.validate_shapes()
    meta = TraceMeta(source="replay", start_unix_s=0.0, dt_s=dt_s, zones=zones)
    return trace, meta
