"""Live HTTP signal clients: Prometheus-compatible, OpenCost, carbon API.

The reference's live query path is PromQL over the AMP query API through a
SigV4 proxy — e.g. ``/api/v1/label/__name__/values`` and
``/api/v1/query?query=up`` (`demo_40_watch_observe.sh:106-110`), the same
endpoint OpenCost is pointed at as an "external Prometheus"
(`06_opencost.sh:404-429`). The carbon API is stubbed with an empty key and a
dummy fallback (`.env:14-16`).

These clients speak those same wire formats. Transport is injectable (any
``fetch(url, headers) -> bytes``) so tests run on canned JSON and a live
deployment can wrap SigV4 signing or bearer auth without changing parsing.
Every client degrades gracefully to its configured default when the endpoint
is unreachable — the reference's dummy-carbon behavior, generalized.
"""

from __future__ import annotations

import json
import re
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Mapping, Sequence

import numpy as np

from ccka_tpu.config import ClusterConfig, SignalsConfig, SimConfig, WorkloadConfig
from ccka_tpu.signals.base import ExogenousTrace, SignalSource, TraceMeta, as_f32
from ccka_tpu.signals.synthetic import SyntheticSignalSource

Fetch = Callable[[str, Mapping[str, str]], bytes]


def _default_fetch(timeout_s: float) -> Fetch:
    def fetch(url: str, headers: Mapping[str, str]) -> bytes:
        req = urllib.request.Request(url, headers=dict(headers))
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:  # noqa: S310
            return resp.read()
    return fetch


def default_fetch(timeout_s: float) -> Fetch:
    """Public alias of the module's urllib transport — the fetch the
    concurrent scrape fan-in (`signals/transport.py`) pools per tenant.
    Kept as a separate name so the private one can keep evolving with
    the retry stack without committing its signature."""
    return _default_fetch(timeout_s)


class RetryingFetch:
    """Jittered exponential-backoff retry around any ``fetch`` transport.

    One-shot fetches meant a transient 500/flaky LB wasted the whole
    control tick (the scrape falls back to the synthetic prior for 30s
    of real decisions). This wrapper retries transport-level failures
    (``OSError``/``TimeoutError`` — ``urllib.error.URLError`` is an
    OSError; malformed-body errors are NOT retried, they are the
    server's answer) with full-jitter exponential backoff:
    ``backoff_s * 2^attempt * U(0.5, 1.5)``. The retry budget is
    bounded by ``deadline_s`` (the tick's ``request_timeout_s``): sleeps
    never push past it and no NEW attempt starts once it is spent —
    each in-flight attempt is additionally bounded by the transport's
    own socket timeout, so one call takes at most ``deadline_s`` plus
    one transport timeout. When the budget is spent the LAST error is
    re-raised — callers (the per-family try/excepts in
    :class:`LiveSignalSource`) then mark the tick ``stale`` and fall
    back, feeding the controller's degraded-mode path instead of
    raising mid-controller.

    ``sleep``/``rand`` are injectable for tests (and ``rand`` defaults
    to a private PRNG so retry jitter never perturbs global
    ``random``)."""

    def __init__(self, fetch: Fetch, *, retries: int = 2,
                 backoff_s: float = 0.4, deadline_s: float = 10.0,
                 sleep=None, rand=None, clock=None):
        import random as _random
        import time as _time

        self.fetch = fetch
        self.retries = max(0, int(retries))
        self.backoff_s = backoff_s
        self.deadline_s = deadline_s
        self.sleep = sleep if sleep is not None else _time.sleep
        self.rand = rand if rand is not None else _random.Random(0x5e7)
        self.clock = clock if clock is not None else _time.monotonic

    def __call__(self, url: str, headers: Mapping[str, str]) -> bytes:
        t0 = self.clock()
        last: BaseException | None = None
        for attempt in range(self.retries + 1):
            try:
                return self.fetch(url, headers)
            except (OSError, TimeoutError) as e:
                last = e
            if attempt >= self.retries:
                break
            delay = (self.backoff_s * (2 ** attempt)
                     * (0.5 + self.rand.random()))
            remaining = self.deadline_s - (self.clock() - t0)
            if remaining <= 0.0:
                break  # budget spent — don't blow the tick deadline
            self.sleep(min(delay, remaining))
            if self.clock() - t0 >= self.deadline_s:
                break  # deadline hit mid-sleep — no new attempt
        assert last is not None
        raise last


class SignalUnavailable(RuntimeError):
    """A live endpoint could not be reached or returned malformed data."""


class PrometheusClient:
    """Minimal Prometheus HTTP API client (instant + range queries).

    Query path shape matches the reference's smoke queries against the AMP
    SigV4 proxy (`demo_40_watch_observe.sh:106-110`):
    ``{base}/api/v1/query?query=...`` and ``/api/v1/query_range``.
    """

    def __init__(self, base_url: str, *, fetch: Fetch | None = None,
                 timeout_s: float = 10.0, headers: Mapping[str, str] | None = None):
        self.base_url = base_url.rstrip("/")
        self.fetch = fetch or _default_fetch(timeout_s)
        self.headers = dict(headers or {})

    def _get(self, path: str, params: Mapping[str, str]) -> dict:
        url = f"{self.base_url}{path}?{urllib.parse.urlencode(params)}"
        try:
            raw = self.fetch(url, self.headers)
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise SignalUnavailable(f"prometheus fetch failed: {url}: {e}") from e
        try:
            doc = json.loads(raw)
        except json.JSONDecodeError as e:
            raise SignalUnavailable(f"prometheus returned non-JSON: {url}") from e
        if doc.get("status") != "success":
            raise SignalUnavailable(f"prometheus error response: {doc.get('error')}")
        return doc["data"]

    def query(self, promql: str) -> list[tuple[dict, float]]:
        """Instant query → list of (metric labels, value)."""
        data = self._get("/api/v1/query", {"query": promql})
        out = []
        for series in data.get("result", []):
            ts_val = series.get("value")
            if ts_val is None:
                continue
            out.append((series.get("metric", {}), float(ts_val[1])))
        return out

    def query_range(self, promql: str, start: float, end: float,
                    step_s: float) -> list[tuple[dict, np.ndarray, np.ndarray]]:
        """Range query → list of (labels, times[T], values[T])."""
        data = self._get("/api/v1/query_range", {
            "query": promql, "start": str(start), "end": str(end),
            "step": f"{step_s}s",
        })
        out = []
        for series in data.get("result", []):
            pts = series.get("values", [])
            times = np.array([float(t) for t, _ in pts])
            vals = np.array([float(v) for _, v in pts])
            out.append((series.get("metric", {}), times, vals))
        return out

    def label_values(self, label: str) -> list[str]:
        """`/api/v1/label/<name>/values` — the reference's first smoke query
        (`demo_40_watch_observe.sh:108`)."""
        data = self._get(f"/api/v1/label/{label}/values", {})
        return list(data) if isinstance(data, list) else list(data.get("result", []))


class OpenCostClient:
    """OpenCost allocation/cost API client (`06_opencost.sh:430-437`).

    Exposes per-namespace/pod cost and node pricing; endpoint shape follows
    OpenCost's ``/allocation`` and ``/assets`` APIs on :9090 (the UI/API port
    the reference port-forwards, `demo_40_watch_observe.sh:60-68`).
    """

    def __init__(self, base_url: str, *, fetch: Fetch | None = None,
                 timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.fetch = fetch or _default_fetch(timeout_s)

    def _get(self, path: str, params: Mapping[str, str]) -> dict:
        url = f"{self.base_url}{path}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        try:
            raw = self.fetch(url, {})
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise SignalUnavailable(f"opencost fetch failed: {url}: {e}") from e
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise SignalUnavailable(f"opencost returned non-JSON: {url}") from e

    def allocation(self, window: str = "1h",
                   aggregate: str = "namespace") -> dict[str, float]:
        """Total cost per aggregate over the window → {name: $}."""
        doc = self._get("/allocation", {"window": window, "aggregate": aggregate})
        out: dict[str, float] = {}
        for bucket in doc.get("data", []) or []:
            if not bucket:
                continue
            for name, alloc in bucket.items():
                out[name] = out.get(name, 0.0) + float(alloc.get("totalCost", 0.0))
        return out

    def node_prices_hr(self) -> dict[str, float]:
        """Per-node $/hr from the assets API → {node_name: $/hr}."""
        doc = self._get("/assets", {"window": "1h", "filterCategories": "Compute"})
        out: dict[str, float] = {}
        data = doc.get("data", {})
        items = data.items() if isinstance(data, dict) else []
        for name, asset in items:
            hourly = asset.get("hourlyCost") if isinstance(asset, dict) else None
            if hourly is not None:
                out[name] = float(hourly)
        return out


class SLOMetricsClient:
    """App-level SLO metrics: p95 latency, RPS, queue depth.

    The reference *advertises* these as the autoscaler's SLO inputs
    (`README.md:21` "latency SLOs", proposal PDF p.1) yet its pipeline
    scrapes only kube-state-metrics (`06_opencost.sh:324-327`) — no app
    latency, request-rate or queue metric is ever collected (§2.3). This
    client issues the standard PromQL for all three against the same
    Prometheus-compatible endpoint, degrading to ``None`` per metric when
    series are absent (a cluster without app instrumentation), so callers
    can log gaps instead of fabricating numbers.
    """

    def __init__(self, prom: PrometheusClient,
                 namespace: str = "nov-22"):
        self.prom = prom
        self.namespace = namespace

    def _scalar(self, promql: str) -> float | None:
        try:
            rows = self.prom.query(promql)
        except SignalUnavailable:
            return None
        if not rows:
            return None
        val = rows[0][1]
        return None if val != val else val  # NaN → absent histogram

    def latency_p95_s(self) -> float | None:
        """p95 request latency over 5m, histogram-quantile form."""
        return self._scalar(
            "histogram_quantile(0.95, sum(rate("
            f'http_request_duration_seconds_bucket{{namespace="{self.namespace}"}}'
            "[5m])) by (le))")

    def rps(self) -> float | None:
        """Served request rate over 5m."""
        return self._scalar(
            f'sum(rate(http_requests_total{{namespace="{self.namespace}"}}[5m]))')

    def queue_depth(self) -> float | None:
        """Scheduler queue depth: Pending pods in the workload namespace —
        the series the burst observer tabulates
        (`demo_30_burst_observe.sh:20-28`)."""
        return self._scalar(
            'sum(kube_pod_status_phase{phase="Pending",'
            f'namespace="{self.namespace}"}})')

    def snapshot(self) -> dict[str, float]:
        """All available metrics (absent ones omitted), ms-normalized."""
        out: dict[str, float] = {}
        p95 = self.latency_p95_s()
        if p95 is not None:
            out["latency_p95_ms"] = p95 * 1000.0
        rps = self.rps()
        if rps is not None:
            out["rps"] = rps
        q = self.queue_depth()
        if q is not None:
            out["queue_depth"] = q
        return out


class CarbonIntensityClient:
    """ElectricityMaps-style carbon intensity client.

    Implements the capability the reference stubbed: `.env:14-16` holds an
    empty ``CARBON_API_KEY``, a zone (`US-CAL-CISO`), and a comment promising
    a dummy ~400 g/kWh fallback; a `07_carbonexporter.sh` was named as future
    work (report PDF p.2). With no key or an unreachable endpoint this client
    returns the configured default, exactly as documented there.
    """

    def __init__(self, base_url: str, api_key: str, zone: str,
                 default_g_kwh: float, *, fetch: Fetch | None = None,
                 timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key
        self.zone = zone
        self.default_g_kwh = default_g_kwh
        self.fetch = fetch or _default_fetch(timeout_s)
        # Staleness marker for the degraded-mode path: False only when a
        # keyed fetch actually failed (the documented no-key fallback is
        # by-design, not stale).
        self.last_ok = True

    def latest(self, zone: str | None = None,
               default: float | None = None) -> float:
        """Latest gCO2eq/kWh for the zone; falls back to ``default`` (the
        configured global default if omitted) on any failure. Callers with
        a zone-specific prior pass it — a flat global fallback for one
        zone of a multi-region fleet could invert the cross-region carbon
        ordering a migration policy acts on."""
        zone = zone or self.zone
        fallback = self.default_g_kwh if default is None else default
        if not self.api_key:
            self.last_ok = True
            return fallback
        url = (f"{self.base_url}/carbon-intensity/latest?"
               f"{urllib.parse.urlencode({'zone': zone})}")
        try:
            raw = self.fetch(url, {"auth-token": self.api_key})
            doc = json.loads(raw)
            val = float(doc["carbonIntensity"])
        except Exception:  # noqa: BLE001 — documented graceful fallback
            self.last_ok = False
            return fallback
        self.last_ok = True
        return val


class SpotPriceClient:
    """Per-AZ spot prices from `aws ec2 describe-spot-price-history`.

    The reference has no spot feed at all — OpenCost reports realized node
    cost only — yet its whole Off-Peak profile is a bet on spot economics
    (`demo_20_offpeak_configure.sh:74-78`). This client closes that gap
    (VERDICT r2 missing #8): it shells the AWS CLI (the reference's only
    AWS transport, `00_common.sh:24`) with an injectable runner, parses the
    newest price per availability zone, and returns {} on any failure so
    the tick can keep its synthetic prior instead of fabricating numbers.
    """

    def __init__(self, region: str, instance_type: str, *,
                 runner=None, window_hr: float = 3.0,
                 cache_ttl_s: float = 300.0,
                 failure_ttl_s: float = 60.0, clock=None):
        self.region = region
        self.instance_type = instance_type
        self.window_hr = window_hr
        # TTL cache (successes AND failures): spot prices move on minutes,
        # but the CLI call sits inside the 30s control tick — uncached, an
        # AWS brownout would block the loop for the runner's full
        # timeout+retry budget every tick (round-3 review). 300s keeps at
        # most one CLI call per ~10 ticks. Failures re-probe sooner
        # (failure_ttl_s): an empty result marks the whole tick stale
        # (degraded-mode input), and caching a single transient hiccup
        # for the full TTL would hold the controller in rule-fallback
        # for ~10 ticks after the CLI already recovered.
        self.cache_ttl_s = cache_ttl_s
        self.failure_ttl_s = failure_ttl_s
        self._cache: dict[str, float] | None = None
        self._cache_at = float("-inf")
        import time as _time
        self._clock = clock or _time.monotonic
        if runner is None:
            from ccka_tpu.actuation.sink import _subprocess_runner
            runner = _subprocess_runner
        self.runner = runner

    def _argv(self) -> list[str]:
        import datetime
        start = (datetime.datetime.now(datetime.timezone.utc)
                 - datetime.timedelta(hours=self.window_hr))
        return ["aws", "ec2", "describe-spot-price-history",
                "--region", self.region,
                "--instance-types", self.instance_type,
                "--product-descriptions", "Linux/UNIX",
                "--start-time", start.strftime("%Y-%m-%dT%H:%M:%SZ"),
                "--output", "json"]

    def latest_by_zone(self) -> dict[str, float]:
        """{availability_zone: $/hr}, newest record per zone; {} if the
        CLI fails, returns junk, or reports no prices. Successes are
        cached for ``cache_ttl_s``; failures for the shorter
        ``failure_ttl_s`` — a broken CLI must not be re-tried every
        tick, but a transient hiccup must not pin the stale flag (and
        the controller's rule-fallback) for the full success TTL."""
        now = self._clock()
        if self._cache is not None:
            ttl = self.cache_ttl_s if self._cache else self.failure_ttl_s
            if now - self._cache_at < ttl:
                return dict(self._cache)
        prices = self._fetch()
        self._cache, self._cache_at = prices, now
        return dict(prices)

    def _fetch(self) -> dict[str, float]:
        rc, out = self.runner(self._argv())
        if rc != 0:
            return {}
        try:
            doc = json.loads(out)
        except json.JSONDecodeError:
            return {}
        best: dict[str, tuple[str, float]] = {}
        for rec in doc.get("SpotPriceHistory", []) or []:
            try:
                az = rec["AvailabilityZone"]
                price = float(rec["SpotPrice"])
                ts = str(rec.get("Timestamp", ""))
            except (KeyError, TypeError, ValueError):
                continue
            if price <= 0:
                continue
            if az not in best or ts > best[az][0]:  # ISO-8601 sorts
                best[az] = (ts, price)
        return {az: price for az, (_ts, price) in best.items()}


class InterruptionWarning:
    """One EC2 spot lifecycle event (EventBridge shape)."""

    __slots__ = ("instance_id", "action", "detail_type", "region")

    def __init__(self, instance_id: str, action: str, detail_type: str,
                 region: str = ""):
        self.instance_id = instance_id
        self.action = action              # "terminate" | "rebalance"
        self.detail_type = detail_type
        self.region = region

    def __repr__(self) -> str:  # diagnostics in controller logs
        return (f"InterruptionWarning({self.instance_id!r}, {self.action!r},"
                f" region={self.region!r})")


class SpotInterruptionFeed:
    """EC2 spot interruption/rebalance warnings from an SQS queue.

    This is the capability the reference explicitly disabled: Karpenter's
    ``settings.interruptionQueue=""`` (`05_karpenter.sh:136`) turns off the
    EventBridge→SQS interruption pipeline entirely, so a spot reclaim hits
    the demo cluster with zero notice. The simulator prices interruptions
    as a first-class stochastic process; this feed closes the live half:
    it polls the EventBridge-target SQS queue over the AWS CLI (the
    reference's only AWS transport, `00_common.sh:24`) with an injectable
    runner, parses `EC2 Spot Instance Interruption Warning` and
    `EC2 Instance Rebalance Recommendation` events, and acknowledges
    (deletes) consumed messages so a warning is acted on exactly once.

    Failures (CLI error, junk JSON, missing queue) return [] — the control
    loop keeps running on its stochastic prior, mirroring every other live
    client's graceful degradation.
    """

    _DETAIL_ACTIONS = {
        "EC2 Spot Instance Interruption Warning": "terminate",
        "EC2 Instance Rebalance Recommendation": "rebalance",
    }

    def __init__(self, queue_url: str, *, region: str = "",
                 runner=None, ack: bool = True, max_messages: int = 10):
        self.queue_url = queue_url
        self.region = region
        self.ack = ack
        self.max_messages = max(1, min(int(max_messages), 10))  # SQS cap
        if runner is None:
            from ccka_tpu.actuation.sink import _subprocess_runner
            runner = _subprocess_runner
        self.runner = runner

    def _region_args(self) -> list[str]:
        return ["--region", self.region] if self.region else []

    def poll(self) -> list[InterruptionWarning]:
        rc, out = self.runner([
            "aws", "sqs", "receive-message", *self._region_args(),
            "--queue-url", self.queue_url,
            "--max-number-of-messages", str(self.max_messages),
            "--wait-time-seconds", "0",
            "--output", "json"])
        if rc != 0:
            return []
        try:
            doc = json.loads(out) if out.strip() else {}
        except json.JSONDecodeError:
            return []
        messages = doc.get("Messages", []) or []
        # Ack every received message in ONE batch call FIRST (including
        # junk and non-spot events routed here by a broad EventBridge
        # rule): an unacked message would redeliver and double-drain next
        # tick, a junk body would redeliver forever, and per-message
        # delete-message subprocesses would cost the control tick up to
        # ten sequential CLI spawns.
        handles = [m.get("ReceiptHandle", "") for m in messages]
        handles = [h for h in handles if h]
        if self.ack and handles:
            entries = [{"Id": str(i), "ReceiptHandle": h}
                       for i, h in enumerate(handles)]
            self.runner(["aws", "sqs", "delete-message-batch",
                         *self._region_args(),
                         "--queue-url", self.queue_url,
                         "--entries", json.dumps(entries)])
        warnings: list[InterruptionWarning] = []
        for msg in messages:
            try:
                event = json.loads(msg.get("Body", ""))
            except (json.JSONDecodeError, TypeError):
                continue
            action = self._DETAIL_ACTIONS.get(event.get("detail-type", ""))
            instance = (event.get("detail") or {}).get("instance-id", "")
            if action and instance:
                warnings.append(InterruptionWarning(
                    instance_id=instance, action=action,
                    detail_type=event["detail-type"],
                    region=event.get("region", self.region)))
        return warnings


class LiveSignalSource(SignalSource):
    """Assembles live clients into the common trace format.

    For tick-level control this scrapes all three families and emits a 1-step
    trace; for multi-step ``trace()`` requests it backfills from Prometheus
    range queries where available and falls back to the synthetic model for
    anything missing (so a cold-started live loop can still warm-start a
    policy). Demand is read from pending+running pod counts, the same
    kube-state-metrics series the reference's pipeline scrapes
    (`06_opencost.sh:324-327`).
    """

    PENDING_QUERY = 'sum(kube_pod_status_phase{phase="Pending"})'
    RUNNING_QUERY = 'sum(kube_pod_status_phase{phase="Running"})'
    # Per-pod series scoped to the workload namespace: classification into
    # the simulator's two demand classes (class 0 spot / class 1 od — the
    # burst generator's odd/even split) happens host-side from the pod
    # name, since kube_pod_status_phase carries no nodeSelector labels.
    POD_QUERY_TMPL = ('kube_pod_status_phase{{phase=~"Pending|Running",'
                      'namespace="{ns}"}} > 0')

    def __init__(self, cluster: ClusterConfig, workload: WorkloadConfig,
                 sim: SimConfig, signals: SignalsConfig,
                 *, fetch: Fetch | None = None,
                 spot_runner=None,
                 start_unix_s: float | None = None):
        import time
        self.cluster = cluster
        self.sim = sim
        self.signals = signals
        # Anchor tick 0 at real wall-clock (UTC) so time-of-day-shaped priors
        # (is_peak 09:00-21:00, diurnal curves) and Prometheus range windows
        # refer to actual hours, not ticks-since-process-start.
        self.start_unix_s = time.time() if start_unix_s is None else start_unix_s
        # Retry/backoff transport (the fault subsystem's live satellite):
        # every HTTP family rides one RetryingFetch, so a transient 500
        # costs a sub-second retry instead of the whole tick; exhaustion
        # surfaces through the per-family fallbacks below as a
        # ``last_scrape_stale`` tick, not an exception mid-controller.
        base_fetch = fetch or _default_fetch(signals.request_timeout_s)
        rfetch: Fetch = RetryingFetch(
            base_fetch, retries=signals.fetch_retries,
            backoff_s=signals.fetch_backoff_s,
            deadline_s=signals.request_timeout_s)
        self.prom = PrometheusClient(signals.prometheus_url, fetch=rfetch,
                                     timeout_s=signals.request_timeout_s)
        self.opencost = OpenCostClient(signals.opencost_url, fetch=rfetch,
                                       timeout_s=signals.request_timeout_s)
        self.carbon = CarbonIntensityClient(
            signals.carbon_url, signals.carbon_api_key, signals.carbon_zone,
            signals.carbon_default_g_kwh, fetch=rfetch,
            timeout_s=signals.request_timeout_s)
        self._synth = SyntheticSignalSource(cluster, workload, sim, signals,
                                            start_unix_s=self.start_unix_s)
        self.namespace = workload.namespace
        self.slo = SLOMetricsClient(self.prom, namespace=workload.namespace)
        # Spot feed: enabled by signals.spot_feed="aws" (CLI transport) or
        # by injecting a runner directly (tests / alternate transports).
        # Multi-region fleets query each region's price history separately.
        self.spot_clients: list[SpotPriceClient] = []
        if spot_runner is not None or signals.spot_feed == "aws":
            region_names = ([r.name for r in cluster.regions]
                            or [cluster.region])
            self.spot_clients = [
                SpotPriceClient(name, cluster.node_type.name,
                                runner=spot_runner)
                for name in region_names]
        # Grid zone + fallback intensity per cluster zone: in a multi-region
        # fleet each zone carries its region's ElectricityMaps zone id and
        # its region's base intensity as the API-failure fallback, so the
        # live carbon tick preserves cross-region divergence (a flat global
        # fallback for one failed zone could invert the ordering the
        # carbon-aware policy migrates on). Single-region: every zone
        # shares signals.carbon_zone and the global default.
        if cluster.regions:
            regs = [cluster.regions[i] for i in cluster.zone_region_index]
            self._zone_grid = [r.carbon_zone or signals.carbon_zone
                               for r in regs]
            self._zone_default = [r.carbon_base_g_kwh
                                  or signals.carbon_default_g_kwh
                                  for r in regs]
        else:
            self._zone_grid = [signals.carbon_zone] * cluster.n_zones
            self._zone_default = ([signals.carbon_default_g_kwh]
                                  * cluster.n_zones)

    def slo_snapshot(self) -> dict[str, float]:
        """Measured app-level SLO metrics for the controller's KPI line
        (absent series omitted — see :class:`SLOMetricsClient`)."""
        return self.slo.snapshot()

    _BURST_POD = re.compile(r"^burst-web-(\d+)-")

    def _demand_by_class(self) -> np.ndarray | None:
        """[C] per-class pod demand from namespace-scoped per-pod series;
        None when the query returns nothing (caller falls back)."""
        rows = self.prom.query(
            self.POD_QUERY_TMPL.format(ns=self.namespace))
        # Only per-pod series count: an endpoint that answers every query
        # with one anonymous aggregate (recording rules, test fakes) has
        # no class information — fall back to the aggregate path.
        rows = [(labels, val) for labels, val in rows if labels.get("pod")]
        if not rows:
            return None
        by_class = np.zeros(2, dtype=np.float64)
        for labels, val in rows:
            m = self._BURST_POD.match(labels.get("pod", ""))
            if m:
                # Generator convention (`actuation/burst.py`): odd index →
                # spot nodeSelector (class 0), even → on-demand (class 1).
                cls = 0 if int(m.group(1)) % 2 == 1 else 1
                by_class[cls] += val
            else:
                # Non-burst namespace pods: no capacity-type pin; spread.
                by_class += val / 2.0
        return by_class

    def meta(self) -> TraceMeta:
        return TraceMeta(source="live", start_unix_s=self.start_unix_s,
                         dt_s=self.sim.dt_s, zones=self.cluster.zones,
                         description=f"live scrape of {self.signals.prometheus_url}")

    def tick(self, t_index: int, *, seed: int = 0) -> ExogenousTrace:
        z = self.cluster.n_zones
        nt = self.cluster.node_type
        base = self._synth.trace(t_index + 1, seed=seed).slice_steps(t_index, 0 + 1)
        # Staleness accounting for the degraded-mode controller: any
        # family whose (retried) scrape failed and fell back marks the
        # whole sample stale — the values are priors/held, not measured.
        stale = False

        od = np.asarray(base.od_price_hr).copy()
        demand = np.asarray(base.demand_pods).copy()

        # Spot prices: measured per-AZ history when the feed is enabled,
        # synthetic prior for any zone the feed doesn't cover. A feed
        # that is CONFIGURED but returned nothing at all (CLI failure or
        # empty history — latest_by_zone caches both as {}) is a stale
        # family too: every zone is then running on fabricated prices,
        # exactly what the degraded-mode machine must see.
        spot = np.asarray(base.spot_price_hr).copy()
        if self.spot_clients:
            by_az: dict[str, float] = {}
            for client in self.spot_clients:
                by_az.update(client.latest_by_zone())
            if not by_az:
                stale = True
            for i, zone in enumerate(self.cluster.zones):
                if zone in by_az:
                    spot[0, i] = by_az[zone]

        try:
            prices = self.opencost.node_prices_hr()
            if prices:
                mean_hr = float(np.mean(list(prices.values())))
                od[:] = max(mean_hr, nt.od_price_hr)
        except SignalUnavailable:
            stale = True

        # Demand: namespace-scoped per-pod series classified into the
        # simulator's spot/od demand classes (burst-web-<i> odd→spot,
        # even→od — the generator's own convention); falls back to the
        # round-2 whole-cluster aggregate with an even split when per-pod
        # series are unavailable (e.g. a stripped-down KSM).
        try:
            by_class = self._demand_by_class()
            if by_class is not None:
                demand[0, :] = by_class
            else:
                pending = self.prom.query(self.PENDING_QUERY)
                running = self.prom.query(self.RUNNING_QUERY)
                if pending or running:
                    total = (sum(v for _, v in pending)
                             + sum(v for _, v in running))
                    demand[0, :] = total / demand.shape[-1]
        except SignalUnavailable:
            stale = True

        # One API call per distinct grid zone (ElectricityMaps bills per
        # request; a 2-region 4-zone fleet makes 2 calls, not 4), each
        # falling back to its own region's base intensity.
        defaults = {g: d for g, d in zip(self._zone_grid,
                                         self._zone_default)}
        by_grid = {}
        for g in dict.fromkeys(self._zone_grid):
            by_grid[g] = self.carbon.latest(zone=g, default=defaults[g])
            if not self.carbon.last_ok:
                stale = True
        carbon = np.asarray([[by_grid[g] for g in self._zone_grid]],
                            dtype=np.float32)

        self.last_scrape_stale = stale
        return ExogenousTrace(
            spot_price_hr=as_f32(spot), od_price_hr=as_f32(od),
            carbon_g_kwh=as_f32(carbon), demand_pods=as_f32(demand),
            is_peak=base.is_peak,
        )

    def trace(self, steps: int, *, seed: int = 0) -> ExogenousTrace:
        # Backfill a *historical* window ending at the wall-clock anchor:
        # tick i covers [start + i·dt, start + (i+1)·dt) with
        # start = anchor − steps·dt. The synthetic prior is re-anchored to
        # that same past window so demand, prices, carbon and is_peak all
        # refer to the same wall-clock instants; Prometheus samples are
        # placed by their returned timestamps, not by array position.
        end = self.start_unix_s
        start = end - steps * self.sim.dt_s
        synth_past = SyntheticSignalSource(
            self.cluster, self._synth.workload, self.sim, self.signals,
            start_unix_s=start)
        base = synth_past.trace(steps, seed=seed)
        demand = np.asarray(base.demand_pods).copy()
        try:
            total: dict[int, float] = {}
            for q in (self.PENDING_QUERY, self.RUNNING_QUERY):
                series = self.prom.query_range(q, start=start, end=end,
                                               step_s=self.sim.dt_s)
                if series:
                    _, times, vals = series[0]
                    for t, v in zip(times, vals):
                        i = int(round((float(t) - start) / self.sim.dt_s))
                        if 0 <= i < steps:
                            total[i] = total.get(i, 0.0) + float(v)
            for i, v in total.items():
                demand[i, :] = v / demand.shape[-1]
        except SignalUnavailable:
            pass
        return ExogenousTrace(
            spot_price_hr=base.spot_price_hr, od_price_hr=base.od_price_hr,
            carbon_g_kwh=base.carbon_g_kwh, demand_pods=as_f32(demand),
            is_peak=base.is_peak,
        )

    def history(self, t_index: int, steps: int, *,
                seed: int = 0) -> ExogenousTrace:
        """Forecaster input window (`ccka_tpu.forecast`): ``trace()``
        already backfills the most recent ``steps`` ticks of measured
        history, so the base default's slice-of-trace indexing (built for
        tick-anchored synthetic/replay worlds) is skipped entirely."""
        del t_index  # live history always ends "now"
        return self.trace(steps, seed=seed)

    # The live planning default stays in the persistence family
    # (forecast.PersistenceForecaster is its zero-prior form): od price
    # below is exactly a last-value hold, demand/carbon hold the measured
    # *anomaly* against the diurnal prior. Controllers that want the
    # seasonal-naive or learned backends attach one to the MPC backend
    # (`MPCBackend(forecaster=...)`) — the controller then routes replans
    # through it instead of this method.
    default_forecaster = "persistence"

    def forecast(self, t_index: int, steps: int, *,
                 seed: int = 0) -> ExogenousTrace:
        """Forward window for receding-horizon planning: the synthetic
        diurnal prior shaped to NOW's measured levels (persistence-of-
        anomaly). The base default would slice ``trace()``, which for a
        live source is *backfilled history* frozen at the construction
        anchor — a planner fed that would optimize yesterday's window
        forever."""
        prior = self._synth.forecast(t_index, steps, seed=seed)
        now = self.tick(t_index, seed=seed)

        def _lvl(x) -> float:
            return float(np.asarray(x).mean())

        d_ratio = _lvl(now.demand_pods) / max(
            _lvl(prior.demand_pods[:1]), 1e-6)
        # Carbon anomaly is PER ZONE: tick() measures each region's grid
        # separately, and collapsing to one scalar would hand the planner
        # the synthetic prior's cross-region ordering even when live
        # measurements disagree with it.
        c_ratio = (np.asarray(now.carbon_g_kwh)[0]
                   / np.maximum(np.asarray(prior.carbon_g_kwh)[0], 1e-6))
        od_now = _lvl(now.od_price_hr)
        return ExogenousTrace(
            spot_price_hr=prior.spot_price_hr,
            od_price_hr=as_f32(np.full_like(
                np.asarray(prior.od_price_hr), od_now)),
            carbon_g_kwh=as_f32(
                np.asarray(prior.carbon_g_kwh) * c_ratio[None, :]),
            demand_pods=as_f32(np.asarray(prior.demand_pods) * d_ratio),
            is_peak=prior.is_peak,
        )


def make_signal_source(cluster: ClusterConfig, workload: WorkloadConfig,
                       sim: SimConfig, signals: SignalsConfig,
                       *, fetch: Fetch | None = None,
                       replay_path: str | None = None,
                       faults=None, workloads=None) -> SignalSource:
    """Factory keyed on ``signals.backend``.

    ``replay_path`` defaults to ``signals.replay_path``, so the replay
    backend is reachable purely through config/CCKA_* env overrides.

    ``faults`` (a ``config.FaultsConfig``) and ``workloads`` (a
    ``config.WorkloadsConfig``) reach the synthetic and replay
    backends, whose packed streams synthesize the disturbance/
    family-arrival lanes; the live backend ignores both — the live
    world supplies its own faults and its own tenant mix, and the
    degraded-mode machinery reacts to the REAL staleness flag instead.
    """
    from ccka_tpu.config import ConfigError
    if signals.backend == "synthetic":
        return SyntheticSignalSource(cluster, workload, sim, signals,
                                     faults=faults, workloads=workloads)
    if signals.backend == "replay":
        from ccka_tpu.signals.replay import ReplaySignalSource
        path = replay_path or signals.replay_path
        if not path:
            raise ConfigError("signals: replay backend requires replay_path")
        try:
            return ReplaySignalSource.from_file(path, faults=faults,
                                                workloads=workloads)
        except (OSError, KeyError, ValueError) as e:
            raise ConfigError(f"signals: cannot load replay trace "
                              f"{path!r}: {e}") from e
    if signals.backend == "live":
        return LiveSignalSource(cluster, workload, sim, signals, fetch=fetch)
    raise ConfigError(f"unknown signals backend {signals.backend!r}")
