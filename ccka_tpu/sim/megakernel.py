"""Pallas rollout megakernel: the whole day-rollout in one TPU kernel.

Why this exists (ARCHITECTURE.md §6, VERDICT r3 weak #7): the lax rollout
is fusion-boundary-bound — ~15 fused kernels per simulated tick, each
paying a kernel launch plus an HBM round trip for every intermediate, with
a measured ~9x gap to the HBM roofline (0.53s vs ~0.06s for a B=32k day).
This kernel keeps the ENTIRE cluster state resident in VMEM across the
scanned horizon and touches HBM only for the exogenous trace stream (the
irreducible traffic) and one final summary block per batch.

Design (the round-3 sketch, realized):

- **Feature-first layout**: every array is ``[rows, B_BLK]`` with the
  cluster batch in lanes — the VPU's 8x128 registers see 128 clusters per
  op, and all the simulator's tiny feature dims (P=2, Z=3, CT=2, C=2)
  become static row slices instead of trailing dims XLA must pad.
- **Grid (batch blocks x time chunks)**: the time dimension is innermost
  and sequential; the packed state lives in a VMEM scratch that persists
  across time chunks of the same batch block (zeroed at t==0, summarized
  into the output block at t==nT-1). Exogenous signals stream in as
  ``[T_CHUNK, 16, B_BLK]`` blocks, auto-double-buffered by pallas.
- **pltpu PRNG for interruptions**: the same truncated-CDF + rounded-
  Gaussian Poisson sampler as `dynamics._poisson_small`, fed by
  `pltpu.prng_random_bits` (a per-grid-cell seed) — statistically
  identical, not bitwise (threefry does not lower to Mosaic). The seed
  depends only on (user seed, batch block, time chunk) — NOT the policy
  or population index — so runs of different policies (and every
  candidate of an ES population) with the same seed/b_block/t_chunk see
  IDENTICAL interruption randomness: kernel-side comparisons are paired
  exactly like the lax path's shared world keys.
- **Three policies fused in** (VERDICT r4 next #1 — round 4's kernel
  served only the rule policy):
  * ``profiles`` — the bench headline's per-tick select between two
    constant profiles on the is_peak signal (`policy/rule.py`); both
    profiles enter as a tiny [2, 16] input, select in-register.
  * ``carbon`` — `policy/carbon.py`'s carbon-derived zone weight
    (sigmoid re-rank + occupancy hysteresis) over the profile base;
    the policy constants are compile-time statics.
  * ``mlp`` — the FULL learned policy: the ActorCritic deterministic
    forward (`models/nets.py`: log1p normalize → bf16 GELU torso →
    f32 actor head) plus the latent→Action codec and the Kyverno
    feasibility projection, all in-register per tick. Weights carry a
    leading population axis ridden by a third grid dimension, so an
    entire ES generation (pop × traces) is ONE kernel launch — CEM
    fitness, flagship selection and bench quality run at kernel speed.
  Everything else (dynamics, accounting) is the same code for all
  three, so learned-policy parity inherits the rule kernel's pinned
  contract. The general `PolicyBackend` path stays on the lax rollout
  (`sim/rollout.py`), which remains the reference implementation the
  parity suite pins this kernel against.
- **Plan playback** (round 9, ARCHITECTURE §11): a fourth mode executes
  a PRECOMPUTED action stream — broadcast ``[T_pad, rows]`` (SMEM
  scalars) or per-cluster ``[T_pad, rows, B]`` (VMEM, the exo stream's
  layout) — instead of deciding in-kernel. This is diff-MPC's execution
  path: plans come from the lax receding-horizon planner
  (`train/mpc.py`), the kernel scores them on paired stochastic worlds
  (`plan_megakernel_rollout_summary` / `..._summary_from_packed`).

Semantics contract: identical to
``batched_rollout_summary(params, zeros, RulePolicy(...).action_fn(),
traces, keys, stochastic=...)`` — exact (float-tolerance) in
deterministic mode, distribution-level in stochastic mode (different
PRNG streams). `tests/test_megakernel.py` enforces both, plus every
EpisodeSummary field. Fresh-state episodes only (the bench/fleet-scoring
path): warm starts stay on the lax path.

Simplification used (always true by construction, `SimParams.from_config`
builds ``class_ct = eye(2)``): workload class c consumes capacity type c,
so class-indexed and ct-indexed quantities coincide.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ccka_tpu.config import LATENCY_CURVE_COEF, LATENCY_RHO_CLIP
from ccka_tpu.sim import lanes
from ccka_tpu.sim.types import Action, ClusterState, SimParams
from ccka_tpu.signals.base import ExogenousTrace

# Fixed topology of the kernel (the default + multiregion presets both
# compile: P/Z/CT/C/K enter as static python ints).
_EPS = 1e-6

# pltpu PRNG stream spacing: the per-grid-cell seed is
# ``seed + b_idx * SEED_BLOCK_STRIDE + t_idx * SEED_CHUNK_STRIDE``.
# Exported constants (not inline literals) because the multi-chip wrapper
# (`parallel/sharded_kernel.py`) must reproduce the SAME per-(global
# block, chunk) streams by offsetting each shard's seed — the paired-
# comparison invariant only survives sharding if both sides agree on the
# stride arithmetic.
SEED_BLOCK_STRIDE = 131071
SEED_CHUNK_STRIDE = 8191

# Latent→Action codec constants — imported from the single source of
# truth so the fused squash can never drift from `latent_to_action`.
from ccka_tpu.models.nets import (  # noqa: E402
    AFTER_MAX_S as _AFTER_MAX_S,
    HPA_BIAS as _HPA_BIAS,
    HPA_HI as _HPA_HI,
    HPA_LO as _HPA_LO,
)

# ---- packed state rows (feature-first; [S, B] scratch) -------------------
# nodes[(ct, p, z)] = ct*P*Z + p*Z + z — spot rows contiguous first.


def _state_rows(P: int, Z: int, K: int, *, fault_obs: bool = False,
                wl_D: int = 0) -> dict:
    """``fault_obs``: reserve rows carrying the LAST-OBSERVED signals
    (spot/od/carbon [Z each] + demand [2]) for the signal-outage fault —
    observing policies (carbon/mlp) read these instead of the live exo
    rows while the outage lane is set. Appended after the accumulators so
    the pre-fault layout is unchanged byte-for-byte.

    ``wl_D``: nonzero reserves the workload-family rows
    (`ccka_tpu/workloads`): five per-family accumulators, the inference
    queue, a ``wl_D``-deep batch age-pipeline (D = batch_deadline_ticks)
    and the background backlog — appended LAST so every earlier layout
    is unchanged byte-for-byte."""
    n = P * Z * 2
    rows = {"nodes": (0, n)}
    off = n
    rows["pipe"] = (off, off + K * n)
    off += K * n
    rows["running"] = (off, off + 2)
    off += 2
    rows["timer"] = (off, off + P)
    off += P
    for name in ("acc_cost", "acc_carbon", "acc_requests", "acc_slo",
                 "acc_evict", "nct_spot", "nct_od", "served_sum",
                 "capacity_sum", "waste_sum", "latency_sum", "latency_max",
                 "queue_sum", "interrupts_sum", "denied_sum", "stale_sum"):
        rows[name] = (off, off + 1)
        off += 1
    if fault_obs:
        rows["last_exo"] = (off, off + 3 * Z + 2)
        off += 3 * Z + 2
    if wl_D:
        for name in ("inf_viol_sum", "inf_q_sum", "inf_drop_sum",
                     "batch_miss_sum", "batch_bl_sum", "wl_inf_q"):
            rows[name] = (off, off + 1)
            off += 1
        rows["wl_batch"] = (off, off + wl_D)
        off += wl_D
        rows["wl_bg"] = (off, off + 1)
        off += 1
    rows["_total"] = (0, off)
    return rows


# Exo rows inside the [T, rows, B] packed stream — offsets depend on the
# zone count (the multiregion preset has Z=4), so they are computed, not
# constants: spot[0:Z], od[Z:2Z], carbon[2Z:3Z], demand[3Z:3Z+2],
# is_peak[3Z+2]; padded to a sublane multiple. A FAULT-WIDENED stream
# (`ccka_tpu/faults`, ARCHITECTURE §12) appends the disturbance lane
# block after this padding — hazard[FB:FB+Z], deny[FB+Z], delay[FB+Z+1],
# stale[FB+Z+2] with FB = _exo_rows(Z), itself padded to a multiple of 8
# (`faults.process.fault_rows`) — so existing offsets never move. A
# WORKLOAD-WIDENED stream (`ccka_tpu/workloads`, ARCHITECTURE §12-13)
# appends the family-arrival block LAST — inf[WB], batch[WB+1],
# bg[WB+2] with WB = FB + (fault_rows(Z) if faulted else 0), the block
# sized fault_rows(Z)+8 so the four layouts stay distinguishable purely
# by row count; the launchers detect layouts via
# `sim.lanes.stream_layout` (the one layout module).

# The layout arithmetic lives in the neutral `sim/lanes.py` (faults and
# workloads import it downward); `_exo_rows` stays exported here for
# the long tail of existing callers.
_exo_rows = lanes.exo_rows


def _act_rows(P: int, Z: int) -> int:
    # zone_weight P*Z + ct_allow 2P + aggr P + after P + hpa 2.
    return P * Z + 2 * P + P + P + 2


def _plan_rows(P: int, Z: int) -> int:
    """Rows of a packed plan stream: the action coordinates padded to a
    sublane multiple (the per-cluster form is a VMEM-streamed
    ``[T_pad, rows, B]`` block exactly like the exo stream)."""
    return math.ceil(_act_rows(P, Z) / 8) * 8

# Packed scalar params (SMEM [1, NP]).
_PARAM_NAMES = (
    "dt_s", "ppn", "base_od", "maxn0", "maxn1",
    "sa00", "sa01", "sa10", "sa11",           # static_ct_allow[p, ct]
    "interrupt_p", "pdb", "frag", "underutil",
    "watts_idle", "watts_full", "rps", "slo_frac", "tau_s",
    "lat_base", "lat_slo",
    "wl_inf_qmax", "wl_inf_slo",              # workload families
)
_PI = {n: i for i, n in enumerate(_PARAM_NAMES)}


def _pack_params(params: SimParams) -> jnp.ndarray:
    sa = params.static_ct_allow
    vals = [params.dt_s, params.pods_per_node, params.base_od_nodes,
            params.max_nodes[0], params.max_nodes[1],
            sa[0, 0], sa[0, 1], sa[1, 0], sa[1, 1],
            params.interrupt_p_step, params.pdb_min_available,
            params.fragmentation, params.underutil_threshold,
            params.watts_idle, params.watts_full, params.rps_per_pod,
            params.slo_served_fraction, params.consolidate_tau_s,
            params.latency_base_ms, params.latency_slo_ms,
            params.wl_inference_queue_max, params.wl_inference_slo_ms]
    return jnp.asarray(vals, jnp.float32).reshape(1, -1)


def _pack_action(a: Action) -> jnp.ndarray:
    """One profile's Action -> [16] coordinate vector (kernel order)."""
    return jnp.concatenate([
        jnp.reshape(a.zone_weight, (-1,)),
        jnp.reshape(a.ct_allow, (-1,)),
        jnp.reshape(a.consolidation_aggr, (-1,)),
        jnp.reshape(a.consolidate_after_s, (-1,)),
        jnp.reshape(a.hpa_scale, (-1,)),
    ]).astype(jnp.float32)


def _uniform(shape) -> jnp.ndarray:
    """U(0,1) from the pltpu PRNG (never exactly 0): top 24 bits via a
    LOGICAL shift (the raw bits lower as int32 — an arithmetic shift
    would keep the sign and hand back negative 'uniforms')."""
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.int32)
    bits24 = jax.lax.shift_right_logical(bits, 8)
    return (bits24.astype(jnp.float32) * (1.0 / (1 << 24))
            + (0.5 / (1 << 24)))


def _poisson_small_kernel(lam: jnp.ndarray, cap: jnp.ndarray) -> jnp.ndarray:
    """`dynamics._poisson_small`, on the in-kernel PRNG: truncated CDF
    inversion below lambda=0.5, rounded moment-matched Gaussian above."""
    u = _uniform(lam.shape)
    t = jnp.exp(-lam)
    cdf = t
    count = jnp.zeros_like(lam)
    for k in (1, 2, 3, 4):
        count = count + (u > cdf)
        t = t * lam / k
        cdf = cdf + t
    # Box-Muller normal from two fresh uniforms.
    u1 = jnp.maximum(_uniform(lam.shape), 1e-7)
    u2 = _uniform(lam.shape)
    normal = jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(2.0 * jnp.pi * u2)
    gauss = jnp.round(lam + jnp.sqrt(lam) * normal)
    sample = jnp.where(lam < 0.5, count, jnp.maximum(gauss, 0.0))
    return jnp.minimum(sample, cap)


def _make_kernel(P: int, Z: int, K: int, T_CHUNK: int, n_chunks: int,
                 stochastic: bool, *,
                 policy: str = "profiles",
                 carbon: tuple | None = None,
                 slo_mask: tuple | None = None,
                 mlp_dims: tuple | None = None,
                 plan_batched: bool = False,
                 faults: bool = False,
                 workloads: int = 0,
                 carry: bool = False):
    """``policy``: "profiles" | "carbon" | "mlp" | "plan" (module
    docstring; "plan" executes a precomputed per-tick action stream —
    the diff-MPC playback entry — instead of deciding in-kernel).

    ``carbon``: (sharpness, min_weight, stickiness) compile-time floats.
    ``slo_mask``: per-pool SLO flags (mlp feasibility projection rule 3).
    ``mlp_dims``: (F, F_pad, H, A) — obs/hidden/latent dims, static.
    ``plan_batched``: plan streams are ``[T_pad, rows, B]`` (per-cluster
    plans, VMEM-streamed like the exo block) rather than ``[T_pad,
    rows]`` (one broadcast plan, SMEM scalars).
    ``faults``: the exo stream carries the fault lane block
    (`ccka_tpu/faults`, rows at base ``_exo_rows(Z)``: hazard[Z], deny,
    delay, stale — ARCHITECTURE §12): interruption hazard scales per
    zone, spot provisioning is denied during ICE windows, arrivals are
    delay-jittered, and observing policies (carbon/mlp) read held
    signals during outages via the ``last_exo`` state rows. Static: the
    False kernel is the pre-fault program, untouched (zero-fault gate).

    ``workloads``: nonzero means the stream carries the workload lane
    block (`ccka_tpu/workloads`, rows after the fault block: inference/
    batch/background arrivals) and names the STATIC batch-deadline
    depth D — per-family queues ride the VMEM state scratch and drain
    from the post-step fleet's headroom exactly as `dynamics.step`'s
    workload path does. 0 is the pre-workload program, untouched
    (zero-workload gate).

    ``carry``: the CARRIED-STATE variant (ISSUE 13, the streaming
    pipeline): the launch covers one time BLOCK of a longer rollout —
    the packed state loads from a ``state_in`` input at the block's
    first chunk (instead of zeroing) and writes back to a ``state_out``
    output at its last, so a rollout resumes bitwise across block
    boundaries (the state rows carry the SummaryAcc accumulators, the
    held-signal policy rows and the workload queues — everything a
    resume needs). The block's global tick offset rides ``meta[0, 3]``
    (the ``valid`` horizon gate and the tod clock stay global); the
    PRNG needs no new plumbing because the caller folds the block's
    first chunk index into the seed (`block_chunk_seed`), making the
    per-(block, chunk) streams globally identical to one unblocked
    launch. False is the pre-streaming program, untouched.
    """
    ROWS = _state_rows(P, Z, K,
                       fault_obs=faults and policy in ("carbon", "mlp"),
                       wl_D=workloads)
    FB = _exo_rows(Z)    # fault lane base row
    if workloads:
        WB = FB + (lanes.fault_rows(Z) if faults else 0)  # workload base
    NPZ = P * Z * 2  # nodes rows
    # Unpacked here: `carbon` would otherwise be shadowed by the tick
    # body's carbon accumulator local.
    if policy == "carbon":
        c_sharp, c_minw, c_stick = carbon

    def rows(state, name):
        lo, hi = ROWS[name]
        return state[lo:hi]

    def kernel(meta_ref, params_ref, *rest):
        rest = list(rest)
        if policy == "mlp":
            w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref = rest[:6]
            rest = rest[6:]
            # Grid (pop, batch, time): weights per population member.
            b_idx = pl.program_id(1)
            t_idx = pl.program_id(2)
        elif policy == "plan":
            plan_ref = rest.pop(0)
            b_idx = pl.program_id(0)
            t_idx = pl.program_id(1)
        else:
            actions_ref = rest.pop(0)
            b_idx = pl.program_id(0)
            t_idx = pl.program_id(1)
        if carry:
            state_in_ref, exo_ref, out_ref, state_out_ref, s_ref = rest
        else:
            exo_ref, out_ref, s_ref = rest

        @pl.when(t_idx == 0)
        def _init():
            if carry:
                # Resume: the previous block's carried state (the mlp
                # grid's state block carries a leading pop axis).
                s_ref[:] = (state_in_ref[0] if policy == "mlp"
                            else state_in_ref[:])
            else:
                s_ref[:] = jnp.zeros_like(s_ref)

        # Independent stream per (batch block, time chunk) — deliberately
        # NOT per policy/population member, so same-seed runs are paired
        # (module docstring). Static gate: deterministic kernels never
        # touch the PRNG (and plain interpret mode on CPU can then run
        # them).
        if stochastic:
            pltpu.prng_seed(meta_ref[0, 2] + b_idx * SEED_BLOCK_STRIDE
                            + t_idx * SEED_CHUNK_STRIDE)

        p = {n: params_ref[0, i] for n, i in _PI.items()}
        dt_hr = p["dt_s"] / 3600.0
        T_total = meta_ref[0, 0]
        # Global tick of this launch's first row (nonzero only for
        # carried-state block launches): the valid gate and the tod
        # clock stay anchored to the FULL horizon, not the block's.
        t_base = meta_ref[0, 3]

        if policy == "mlp":
            # Hoisted out of the time loop: one VMEM read per weight per
            # grid cell (the index map pins the same block across t, so
            # pallas does not re-copy it from HBM either).
            w1 = w1_ref[0]                         # [F_pad, H] bf16
            b1 = b1_ref[0]                         # [H, B]    bf16
            w2 = w2_ref[0]                         # [H, H]    bf16
            b2 = b2_ref[0]                         # [H, B]    bf16
            w3 = w3_ref[0]                         # [H, A_pad] f32
            b3 = b3_ref[0]                         # [A_pad, B] f32

        state0 = s_ref[:]
        B = state0.shape[1]

        def tick(i, state):
            exo = exo_ref[i]                       # [exo_rows, B]
            tglob = t_base + t_idx * T_CHUNK + i
            valid = (tglob < T_total).astype(jnp.float32)

            is_peak = exo[3 * Z + 2] > 0.5         # [B] bool

            # PRE-step state reads: the policy observes the state the
            # lax path's `action_fn(state, exo, t)` sees.
            nodes = rows(state, "nodes")           # [NPZ, B]
            pipe = rows(state, "pipe")             # [K*NPZ, B]
            running = rows(state, "running")       # [2, B]
            timer = rows(state, "timer")           # [P, B]

            if faults:
                haz = [exo[FB + z] for z in range(Z)]    # hazard mult [B]
                deny = exo[FB + Z]                       # ICE denial [B]
                delay = exo[FB + Z + 1]                  # arrival hold [B]
                stale = exo[FB + Z + 2]                  # outage flag [B]
            if faults and policy in ("carbon", "mlp"):
                # Signal outage: observing policies read the HELD
                # last-pre-outage signals instead of the live rows; tick
                # 0 observes fresh (the zeroed scratch is never served —
                # tglob > 0 gates the hold, mirroring the lax path's
                # last0 = exo[0] carry init).
                last = rows(state, "last_exo")           # [3Z+2, B]
                cur = exo[0:3 * Z + 2]
                hold = jnp.logical_and(stale > 0.5, tglob > 0)
                obs_sig = jnp.where(hold[None, :], last, cur)

                def obs(j):
                    """Policy-observed signal row j (< 3Z+2: prices,
                    carbon, demand; is_peak is clock-derived — read it
                    from exo directly)."""
                    return obs_sig[j]
            else:
                obs_sig = None

                def obs(j):
                    return exo[j]

            if policy in ("profiles", "carbon", "plan"):
                if policy == "plan":
                    if plan_batched:
                        prow = plan_ref[i]        # [plan_rows, B]

                        def act(j):
                            """Action coordinate j of this tick's
                            per-cluster plan row."""
                            return prow[j]
                    else:
                        def act(j):
                            """Coordinate j of the broadcast plan's tick
                            row (SMEM scalar → all lanes)."""
                            return jnp.broadcast_to(plan_ref[i, j], (B,))
                else:
                    def act(j):
                        """Action coordinate j: per-cluster select of the
                        two constant profiles on is_peak."""
                        return jnp.where(is_peak, actions_ref[1, j],
                                         actions_ref[0, j])

                zw = [[act(pp * Z + z) for z in range(Z)]
                      for pp in range(P)]
                ct_allow = [[act(P * Z + pp * 2 + ct) for ct in range(2)]
                            for pp in range(P)]
                aggr = [act(P * Z + P * 2 + pp) for pp in range(P)]
                after = [act(P * Z + P * 2 + P + pp) for pp in range(P)]
                hpa = [act(P * Z + P * 2 + 2 * P + c) for c in range(2)]

            if policy == "carbon":
                # CarbonAwarePolicy.decide (policy/carbon.py:84-101):
                # zone weight = sigmoid(sharpness * carbon-rank +
                # stickiness * occupancy), floored at min_weight; the
                # profile base keeps every other coordinate. Observed
                # carbon — stale under a signal outage (fault mode).
                carbon_z = [obs(2 * Z + z) for z in range(Z)]
                cmean = sum(carbon_z) / Z
                nodes_z = [
                    sum(nodes[ct * P * Z + pp * Z + z]
                        for ct in range(2) for pp in range(P))
                    for z in range(Z)]
                ntot = sum(nodes_z) + 1e-6
                w_z = []
                for z in range(Z):
                    occ = jnp.clip(nodes_z[z] / ntot * Z - 1.0, -1.0, 1.0)
                    rel = (cmean - carbon_z[z]) / (cmean + 1e-6)
                    w_z.append(jnp.maximum(
                        jax.nn.sigmoid(c_sharp * rel + c_stick * occ),
                        c_minw))
                zw = [[w_z[z] for z in range(Z)] for pp in range(P)]

            if policy == "mlp":
                F, F_pad, H, A = mlp_dims
                # Observation, exactly `observe(...).flatten()` order
                # (policy/base.py:46-57): nodes [P,Z,CT] row-major, then
                # pipeline per ct, running, demand, spot/od/carbon
                # prices, is_peak, tod_frac.
                ob = []
                for pp in range(P):
                    for z in range(Z):
                        for ct in range(2):
                            ob.append(nodes[ct * P * Z + pp * Z + z])
                for ct in range(2):
                    ob.append(sum(
                        pipe[k * NPZ + ct * P * Z:
                             k * NPZ + (ct + 1) * P * Z].sum(axis=0)
                        for k in range(K)))
                ob.extend([running[0], running[1]])
                # Signal features via obs(): held (stale) under a fault
                # outage; is_peak is clock-derived and stays live.
                ob.extend([obs(3 * Z), obs(3 * Z + 1)])          # demand
                ob.extend([obs(z) for z in range(Z)])            # spot $
                ob.extend([obs(Z + z) for z in range(Z)])        # od $
                ob.extend([obs(2 * Z + z) for z in range(Z)])    # carbon
                ob.append(exo[3 * Z + 2])                        # is_peak
                time_s = tglob.astype(jnp.float32) * p["dt_s"]
                ob.append(jnp.broadcast_to(
                    jnp.mod(time_s, 86400.0) / 86400.0, (B,)))   # tod
                obs = jnp.stack(ob)                              # [F, B]
                if F_pad > F:
                    obs = jnp.concatenate(
                        [obs, jnp.zeros((F_pad - F, B), jnp.float32)])
                # models/nets.py numerics: log1p normalize, bf16 GELU
                # torso (f32 MXU accumulation, rounded to bf16 like the
                # flax Dense's bf16 output), f32 head.
                x = (jnp.sign(obs) * jnp.log1p(jnp.abs(obs))
                     ).astype(jnp.bfloat16)
                dn = (((0,), (0,)), ((), ()))  # contract rows: W^T @ x
                h = jax.nn.gelu(jax.lax.dot_general(
                    w1, x, dn, preferred_element_type=jnp.float32
                ).astype(jnp.bfloat16) + b1)
                h = jax.nn.gelu(jax.lax.dot_general(
                    w2, h, dn, preferred_element_type=jnp.float32
                ).astype(jnp.bfloat16) + b2)
                u = jax.lax.dot_general(
                    w3, h.astype(jnp.float32), dn,
                    preferred_element_type=jnp.float32) + b3     # [A_pad,B]

                # latent→Action codec + Kyverno projection
                # (models/nets.py latent_to_action ∘ project_feasible),
                # coordinate-for-coordinate.
                sig = jax.nn.sigmoid
                zw_raw = [[sig(u[pp * Z + z]) for z in range(Z)]
                          for pp in range(P)]
                zw = []
                for pp in range(P):
                    mass = sum(zw_raw[pp])
                    zw.append([jnp.where(mass < 1e-3, 1.0, zw_raw[pp][z])
                               for z in range(Z)])
                ct_allow = []
                for pp in range(P):
                    row = []
                    for ct in range(2):
                        v = sig(u[P * Z + pp * 2 + ct]) * p[f"sa{pp}{ct}"]
                        if ct == 1:  # SLO pools always offer on-demand
                            v = jnp.maximum(v, slo_mask[pp])
                        row.append(v)
                    ct_allow.append(row)
                aggr = [sig(u[P * Z + 2 * P + pp]) for pp in range(P)]
                after = [_AFTER_MAX_S * sig(u[P * Z + 3 * P + pp])
                         for pp in range(P)]
                hpa = [_HPA_LO + (_HPA_HI - _HPA_LO)
                       * sig(u[P * Z + 4 * P + c] + _HPA_BIAS)
                       for c in range(2)]

            # 1. desired pods (HPA lever).
            demand = exo[3 * Z:3 * Z + 2]                      # [2, B]
            desired = demand * jnp.stack(hpa)                   # [2, B]

            # 2. provisioning arrivals + pipeline shift. Fault delay
            # jitter holds back a fraction of the arrivals one tick
            # (re-queued at the shifted pipeline's head).
            arr = pipe[0:NPZ]
            tail = jnp.concatenate(
                [pipe[NPZ:], jnp.zeros((NPZ, B), jnp.float32)], axis=0)
            if faults:
                held = arr * delay
                nodes = nodes + (arr - held)
                pipe = jnp.concatenate([tail[0:NPZ] + held, tail[NPZ:]],
                                       axis=0)
            else:
                nodes = nodes + arr
                pipe = tail

            # 3. spot interruptions — per-zone hazard multiplier under a
            # fault preemption storm, clipped at 1 (a storm can at most
            # reclaim the whole pool).
            spot = nodes[0:P * Z]
            if faults:
                haz_block = jnp.stack([haz[z] for pp in range(P)
                                       for z in range(Z)])    # [P*Z, B]
                lam = spot * jnp.minimum(p["interrupt_p"] * haz_block,
                                         1.0)
            else:
                lam = spot * p["interrupt_p"]
            if stochastic:
                interrupted = _poisson_small_kernel(lam, spot)
            else:
                interrupted = lam
            nodes = jnp.concatenate([spot - interrupted, nodes[P * Z:]],
                                    axis=0)
            interrupted_total = interrupted.sum(axis=0)         # [B]

            # 4. scheduling (class c <-> capacity type c).
            spot_n = nodes[0:P * Z].sum(axis=0)                 # [B]
            od_n = nodes[P * Z:].sum(axis=0)
            cap_spot = spot_n * p["ppn"]
            cap_od = (od_n + p["base_od"]) * p["ppn"]
            cap_ct = jnp.stack([cap_spot, cap_od])              # [2, B]
            running = jnp.minimum(desired, cap_ct)
            pending = desired - running                         # [2, B]

            # 5. provisioning split.
            inc_spot = sum(pipe[k * NPZ:k * NPZ + P * Z].sum(axis=0)
                           for k in range(K))
            inc_od = sum(pipe[k * NPZ + P * Z:(k + 1) * NPZ].sum(axis=0)
                         for k in range(K))
            incoming = jnp.stack([inc_spot, inc_od])
            need_ct = jnp.maximum(pending / p["ppn"] - incoming, 0.0)

            price = [exo[0:Z],                                   # ct=0 [Z,B]
                     exo[Z:2 * Z]]                               # ct=1
            price_mean = (price[0].sum(axis=0) + price[1].sum(axis=0)) \
                / (2.0 * Z)
            tau = 0.1 * price_mean + _EPS
            cheap = []
            for ct in range(2):
                e = jnp.exp(-price[ct] / tau)
                cheap.append(e / (e.sum(axis=0) + _EPS) * 1.0)
            # NOTE: dynamics' softmax normalizes over zones per ct — same.

            w_rows = []
            for ct in range(2):
                for pp in range(P):
                    allow = ct_allow[pp][ct] * p[f"sa{pp}{ct}"]
                    for z in range(Z):
                        w_rows.append(zw[pp][z] * allow * cheap[ct][z])
            w = jnp.stack(w_rows)                               # [NPZ, B]
            wsum = [w[0:P * Z].sum(axis=0), w[P * Z:].sum(axis=0)]
            frac_rows = []
            for ct in range(2):
                s = wsum[ct]
                blk = w[ct * P * Z:(ct + 1) * P * Z]
                frac_rows.append(jnp.where(s > _EPS, blk / (s + _EPS), 0.0)
                                 * need_ct[ct])
            new_nodes = jnp.concatenate(frac_rows, axis=0)      # [NPZ, B]

            # Per-pool cap.
            def pool_rows(arr, pp):  # rows of pool pp across cts, [2Z, B]
                return jnp.concatenate(
                    [arr[pp * Z:(pp + 1) * Z],
                     arr[P * Z + pp * Z:P * Z + (pp + 1) * Z]], axis=0)

            scale = []
            for pp in range(P):
                pool_now = pool_rows(nodes, pp).sum(axis=0)
                for k in range(K):
                    pool_now = pool_now + pool_rows(
                        pipe[k * NPZ:(k + 1) * NPZ], pp).sum(axis=0)
                pool_new = pool_rows(new_nodes, pp).sum(axis=0)
                headroom = jnp.maximum(p[f"maxn{pp}"] - pool_now, 0.0)
                scale.append(jnp.where(
                    pool_new > _EPS,
                    jnp.minimum(headroom / (pool_new + _EPS), 1.0), 1.0))
            scaled_rows = []
            for ct in range(2):
                for pp in range(P):
                    blk = new_nodes[ct * P * Z + pp * Z:
                                    ct * P * Z + (pp + 1) * Z]
                    scaled_rows.append(blk * scale[pp])
            new_nodes = jnp.concatenate(scaled_rows, axis=0)
            # Insufficient-capacity errors (fault): the spot share of
            # this tick's request is denied — not requested, so pending
            # pods drive a re-request next tick (dynamics.py order).
            if faults:
                spot_new = new_nodes[0:P * Z]
                denied_b = spot_new.sum(axis=0) * deny
                new_nodes = jnp.concatenate(
                    [spot_new * (1.0 - deny), new_nodes[P * Z:]], axis=0)
            else:
                denied_b = jnp.zeros((B,), jnp.float32)
            pipe = jnp.concatenate(
                [pipe[0:(K - 1) * NPZ], pipe[(K - 1) * NPZ:] + new_nodes],
                axis=0)

            # 6. consolidation.
            used_ct = running                                   # [2, B]
            used_karp_od = jnp.maximum(
                used_ct[1] - p["base_od"] * p["ppn"], 0.0)
            used_karp = jnp.stack([used_ct[0], used_karp_od])
            repack = used_karp / p["ppn"]
            nodes_ct = jnp.stack([spot_n, od_n])                # [2, B]
            slack = jnp.maximum(nodes_ct - repack, 0.0)
            empty = jnp.maximum(nodes_ct - repack * (1.0 + p["frag"]), 0.0)
            util = used_karp / (nodes_ct * p["ppn"] + _EPS)
            under_gate = jax.nn.sigmoid((p["underutil"] - util) / 0.05)
            evict_budget = (1.0 - p["pdb"]) * used_karp
            aggr_ct = jnp.minimum(
                slack, empty + under_gate * evict_budget / p["ppn"])

            removable_rows = []
            for ct in range(2):
                denom = nodes_ct[ct] + _EPS
                for pp in range(P):
                    blk = nodes[ct * P * Z + pp * Z:
                                ct * P * Z + (pp + 1) * Z]
                    share = blk / denom
                    removable_rows.append(
                        share * (empty[ct] * (1.0 - aggr[pp])
                                 + aggr_ct[ct] * aggr[pp]))
            removable = jnp.concatenate(removable_rows, axis=0)  # [NPZ, B]

            gate = []
            new_timer_rows = []
            for pp in range(P):
                removable_p = pool_rows(removable, pp).sum(axis=0)
                has_slack = removable_p > 1e-3
                t_new = jnp.where(has_slack, timer[pp] + p["dt_s"], 0.0)
                g = jax.nn.sigmoid((t_new - after[pp]) / p["tau_s"])
                gate.append(g)
                new_timer_rows.append(jnp.where(g > 0.5, 0.0, t_new))
            timer = jnp.stack(new_timer_rows)

            removed_rows = []
            for ct in range(2):
                for pp in range(P):
                    blk = removable[ct * P * Z + pp * Z:
                                    ct * P * Z + (pp + 1) * Z]
                    removed_rows.append(blk * gate[pp])
            removed = jnp.concatenate(removed_rows, axis=0)
            nodes = jnp.maximum(nodes - removed, 0.0)
            removed_ct = jnp.stack([removed[0:P * Z].sum(axis=0),
                                    removed[P * Z:].sum(axis=0)])
            evicted = jnp.maximum(removed_ct - empty, 0.0).sum(axis=0) \
                * p["ppn"] * 0.5

            # 7. accounting on the post-step fleet.
            base_z = p["base_od"] / Z
            nodes_zc = []   # [ct][z] -> [B]
            for ct in range(2):
                per_z = []
                for z in range(Z):
                    v = sum(nodes[ct * P * Z + pp * Z + z]
                            for pp in range(P))
                    if ct == 1:
                        v = v + base_z
                    per_z.append(v)
                nodes_zc.append(per_z)
            cost = sum(nodes_zc[ct][z] * price[ct][z]
                       for ct in range(2) for z in range(Z)) * dt_hr

            total_ct = [sum(nodes_zc[ct][z] for z in range(Z))
                        for ct in range(2)]
            carbon_z = exo[2 * Z:3 * Z]
            carbon = jnp.zeros((B,), jnp.float32)
            for ct in range(2):
                t_ct = total_ct[ct]
                u = jnp.where(t_ct > _EPS,
                              jnp.minimum(
                                  used_ct[ct] / (t_ct * p["ppn"] + _EPS),
                                  1.0), 0.0)
                watts = p["watts_idle"] + (p["watts_full"]
                                           - p["watts_idle"]) * u
                for z in range(Z):
                    carbon = carbon + (nodes_zc[ct][z] * watts / 1000.0
                                       * dt_hr) * carbon_z[z]

            effective = jnp.minimum(running, demand)
            requests = effective.sum(axis=0) * p["rps"] * p["dt_s"]

            load = demand.sum(axis=0) / (cap_ct.sum(axis=0) + _EPS)
            rho = jnp.clip(load, 0.0, LATENCY_RHO_CLIP)
            lat = p["lat_base"] * (
                1.0 + LATENCY_CURVE_COEF * rho * rho / (1.0 - rho))
            queue = pending.sum(axis=0)

            met = jnp.logical_and(
                running[0] >= p["slo_frac"] * demand[0] - _EPS,
                running[1] >= p["slo_frac"] * demand[1] - _EPS)
            lat_ok = jnp.where(p["lat_slo"] > 0,
                               (lat <= p["lat_slo"]).astype(jnp.float32),
                               1.0)
            slo_ok = met.astype(jnp.float32) * lat_ok

            # 8. accumulators (SummaryAcc + episode totals).
            nodes_total = total_ct[0] + total_ct[1] - p["base_od"]
            # total_ct includes base in od; SummaryAcc counts
            # Karpenter-owned nodes only (metrics.nodes_by_ct).
            nct_spot_now = total_ct[0]
            nct_od_now = total_ct[1] - p["base_od"]
            capacity = (nodes_total + p["base_od"]) * p["ppn"]
            served = running.sum(axis=0)

            # 7b. workload families (ccka_tpu/workloads): per-family
            # queues drained from the post-step fleet's headroom —
            # inference first (queue cap + latency-proxy SLO), then
            # batch EDF over the D-deep age pipeline, then best-effort
            # background. Mirrors dynamics.step's workload path
            # line-for-line in feature-first form.
            if workloads:
                D = workloads
                inf_arr = exo[WB]
                bat_arr = exo[WB + 1]
                bg_arr = exo[WB + 2]
                headroom = jnp.maximum(capacity - served, 0.0)
                inf_q = rows(state, "wl_inf_q")[0]          # [B]
                inf_in = inf_q + inf_arr
                inf_served = jnp.minimum(inf_in, headroom)
                inf_after = inf_in - inf_served
                inf_dropped = jnp.maximum(inf_after - p["wl_inf_qmax"],
                                          0.0)
                inf_q2 = inf_after - inf_dropped
                rem = headroom - inf_served
                inf_rho = jnp.clip(inf_in / (headroom + _EPS),
                                   0.0, LATENCY_RHO_CLIP)
                inf_lat = p["lat_base"] * (
                    1.0 + LATENCY_CURVE_COEF * inf_rho * inf_rho
                    / (1.0 - inf_rho))
                inf_viol = jnp.maximum(
                    (inf_lat > p["wl_inf_slo"]).astype(jnp.float32),
                    (inf_dropped > 0.0).astype(jnp.float32))
                wbat = rows(state, "wl_batch")              # [D, B]
                pool = [bat_arr] + [wbat[kk] for kk in range(D - 1)]
                rem_b = rem
                batch_leftover = [None] * D
                for kk in range(D - 1, -1, -1):             # oldest first
                    take = jnp.minimum(pool[kk], rem_b)
                    rem_b = rem_b - take
                    batch_leftover[kk] = pool[kk] - take
                batch_missed = batch_leftover[D - 1]
                keep = batch_leftover[:D - 1]
                batch_bl = (sum(keep) if keep
                            else jnp.zeros((B,), jnp.float32))
                new_wbat = jnp.stack(
                    keep + [jnp.zeros((B,), jnp.float32)])  # [D, B]
                bg_q = rows(state, "wl_bg")[0]
                bg_in = bg_q + bg_arr
                bg_served = jnp.minimum(bg_in, rem_b)
                bg_q2 = bg_in - bg_served

            def bump(name, delta):
                return rows(state, name) + valid * delta[None, :]

            stale_b = stale if faults else jnp.zeros((B,), jnp.float32)
            new_state_parts = [
                nodes, pipe, running, timer,
                bump("acc_cost", cost),
                bump("acc_carbon", carbon),
                bump("acc_requests", requests),
                bump("acc_slo", slo_ok * p["dt_s"]),
                bump("acc_evict", evicted),
                bump("nct_spot", nct_spot_now),
                bump("nct_od", nct_od_now),
                bump("served_sum", served),
                bump("capacity_sum", capacity),
                bump("waste_sum", jnp.maximum(capacity - served, 0.0)),
                bump("latency_sum", lat),
                jnp.maximum(rows(state, "latency_max"),
                            valid * lat[None, :]),
                bump("queue_sum", queue),
                bump("interrupts_sum", interrupted_total),
                bump("denied_sum", denied_b),
                bump("stale_sum", stale_b),
            ]
            if obs_sig is not None:
                # Held-signal carry: during an outage obs_sig IS the old
                # last row block, so the hold persists across the window.
                new_state_parts.append(obs_sig)
            if workloads:
                # Row order matches _state_rows' workload block; the
                # final valid-gate below reverts queue rows (like all
                # dynamic state) on padding ticks.
                new_state_parts += [
                    bump("inf_viol_sum", inf_viol),
                    bump("inf_q_sum", inf_q2),
                    bump("inf_drop_sum", inf_dropped),
                    bump("batch_miss_sum", batch_missed),
                    bump("batch_bl_sum", batch_bl),
                    inf_q2[None, :],
                    new_wbat,
                    bg_q2[None, :],
                ]
            pad = state.shape[0] - ROWS["_total"][1]
            if pad:
                new_state_parts.append(jnp.zeros((pad, B), jnp.float32))
            new_state = jnp.concatenate(new_state_parts, axis=0)
            # Ticks beyond T_total leave the dynamic state untouched too.
            return jnp.where(valid > 0, new_state, state)

        state = jax.lax.fori_loop(0, T_CHUNK, tick, state0)
        s_ref[:] = state

        @pl.when(t_idx == n_chunks - 1)
        def _emit():
            names = ("acc_cost", "acc_carbon", "acc_requests", "acc_slo",
                     "acc_evict", "nct_spot", "nct_od", "served_sum",
                     "capacity_sum", "waste_sum", "latency_sum",
                     "latency_max", "queue_sum", "interrupts_sum",
                     "denied_sum", "stale_sum")
            if workloads:
                names += ("inf_viol_sum", "inf_q_sum", "inf_drop_sum",
                          "batch_miss_sum", "batch_bl_sum")
            vals = [state[ROWS[n][0]] for n in names]
            pad = out_ref.shape[-2] - len(vals)
            out = jnp.stack(vals + [jnp.zeros_like(vals[0])] * pad)
            if policy == "mlp":   # population out block carries a lead 1
                out_ref[0] = out
            else:
                out_ref[:] = out
            if carry:
                # Hand the block's final packed state back for the next
                # block's resume (aliased onto state_in by the donating
                # launchers — one state buffer per chip).
                if policy == "mlp":
                    state_out_ref[0] = state
                else:
                    state_out_ref[:] = state

    return kernel, ROWS


# Output block rows: 16 shared accumulators + 5 workload-family ones
# (zero-padded by kernels without workload lanes), padded to a sublane
# multiple.
_OUT_ROWS = 24

# Batch-mean parity tolerances — the ONE table both gates use
# (`tests/test_megakernel.py` and bench.py's inline gate), so the bench
# can never admit the kernel under a different standard than the pinned
# contract. Core KPIs tight; rare-event counters and threshold-gated slo
# fields looser (chaotic event flips are unbiased but noisy) — all far
# below scoreboard effect sizes.
MEAN_PARITY_TOLERANCES = {
    "interruptions": 0.03, "evictions": 0.05, "queue_depth_mean": 0.05,
    "slo_hours": 0.01, "slo_attainment": 0.01, "usd_per_slo_hour": 0.01,
    "latency_p95_ms_max": 0.02,
    # Fault counters (ccka_tpu/faults): rare-event totals like
    # interruptions/evictions; identically 0 (rel diff 0) off the fault
    # path, so the pre-fault gates are untouched.
    "denials": 0.05, "stale_ticks": 0.01,
    # Workload-family counters (ccka_tpu/workloads): threshold-gated
    # (violation/miss flips) and queue-depth means amplify small fleet
    # differences; identically 0 (rel diff 0) off the workload path.
    "inf_slo_violations": 0.02, "inf_queue_mean": 0.05,
    "inf_dropped": 0.05, "batch_deadline_misses": 0.05,
    "batch_backlog_mean": 0.05,
}
DEFAULT_MEAN_PARITY_TOL = 0.005

# The mlp policy's extra latitude, ON TOP of the shared table: a bf16
# FEEDBACK policy amplifies Mosaic-vs-XLA rounding differences in the
# net forward (measured on-chip: jitted flax vs the kernel's numeric
# model agree to ~0.03 in latent units ≈ ~0.7% per action coordinate)
# into a small systematic fleet-size offset. The scoreboard fields
# (cost/carbon/SLO/headline ratios) stay under the SHARED tolerances —
# only the two fleet-shape diagnostics widen, and candidate-vs-candidate
# comparisons inside one kernel run are unaffected (common-mode).
NEURAL_MEAN_PARITY_TOLERANCES = {
    "mean_nodes": 0.02, "waste_frac": 0.02,
}


def mean_parity_violations(kernel_summary, lax_summary,
                           overrides: dict | None = None) -> dict:
    """{field: batch-mean rel diff} for every field whose diff exceeds
    its tolerance AND is statistically significant; empty == parity.

    Significance matters for the rare-event counters: the two paths use
    independent PRNG families, so their batch means differ by shot noise
    — at B=2048 over part of a day, interruptions (~0.65/cluster) carry
    ~4% relative se, and a tolerance-only gate false-fires on pure noise
    (measured round 4: 3-7% across seeds, all within 2σ of zero;
    full-day B=8k gives 0.9%). The se is PAIRED (both summaries come
    from the same per-cluster traces, so d = kernel − lax cancels trace
    heterogeneity and retains only the genuine kernel-vs-lax noise) —
    an independent-samples se would be dominated by cross-cluster trace
    spread and let real systematic biases hide under it. A REAL kernel
    bias shifts mean(d) across the whole batch and clears the z-gate
    easily."""
    tol = dict(MEAN_PARITY_TOLERANCES, **(overrides or {}))
    bad = {}
    for f in kernel_summary._fields:
        ka = np.asarray(getattr(kernel_summary, f), np.float64).ravel()
        la = np.asarray(getattr(lax_summary, f), np.float64).ravel()
        b = la.mean()
        d = ka - la
        rel = abs(d.mean()) / (abs(b) + 1e-9)
        if rel <= tol.get(f, DEFAULT_MEAN_PARITY_TOL):
            continue
        if d.size < 2:
            bad[f] = round(rel, 5)   # no variance estimate: rel decides
            continue
        se = d.std(ddof=1) / math.sqrt(d.size)
        z = abs(d.mean()) / (se + 1e-12)
        if z > 4.0:
            bad[f] = round(rel, 5)
    return bad


def _pack_exo(traces: ExogenousTrace, T_pad: int) -> jnp.ndarray:
    """[B, T, ...] trace pytree -> [T_pad, exo_rows(Z), B] feature-first
    stream (row offsets: see the comment above `_exo_rows`)."""
    def tb(x):  # [B, T, k] -> [T, k, B]
        return jnp.moveaxis(x, 0, -1)

    T = traces.is_peak.shape[1]
    Z = traces.spot_price_hr.shape[-1]
    parts = [
        tb(traces.spot_price_hr), tb(traces.od_price_hr),
        tb(traces.carbon_g_kwh), tb(traces.demand_pods),
        tb(traces.is_peak[:, :, None]),
    ]
    packed = jnp.concatenate(parts, axis=1).astype(jnp.float32)
    rows = packed.shape[1]
    packed = jnp.pad(packed,
                     ((0, T_pad - T), (0, _exo_rows(Z) - rows), (0, 0)))
    return packed


@functools.partial(jax.jit, static_argnames=("P", "Z", "K", "WD",
                                             "stochastic", "b_block",
                                             "t_chunk", "interpret",
                                             "carbon"))
def _run(params_packed, actions_packed, exo_packed, meta, state_in=None,
         *, P, Z, K, WD, stochastic, b_block, t_chunk, interpret=False,
         carbon=None):
    # Lane auto-detect: widened streams (`ccka_tpu/faults` /
    # `ccka_tpu/workloads`) carry extra row blocks past _exo_rows(Z),
    # resolved purely from the static row count. Shapes are static at
    # trace time, so this is a compile-time switch — the plain-stream
    # program is the pre-fault/pre-workload kernel, untouched.
    # ``state_in`` (the streaming pipeline's carried state, [s_rows, B])
    # selects the carry variant: the launch then ALSO returns the
    # block's final state (see `_make_kernel`'s ``carry``).
    T_pad, exo_rows_total, B = exo_packed.shape
    faults, wl = lanes.stream_layout(exo_rows_total, Z)
    carry = state_in is not None
    n_b = B // b_block
    n_t = T_pad // t_chunk
    kernel, ROWS = _make_kernel(
        P, Z, K, t_chunk, n_t, stochastic,
        policy="carbon" if carbon is not None else "profiles",
        carbon=carbon, faults=faults, workloads=WD if wl else 0,
        carry=carry)
    s_rows = math.ceil(ROWS["_total"][1] / 8) * 8
    if carry and tuple(state_in.shape) != (s_rows, B):
        raise ValueError(
            f"carried state shape {tuple(state_in.shape)} does not "
            f"match this mode/layout's ({s_rows}, {B}) — build it with "
            "init_block_state for the SAME stream layout")

    in_specs = [
        pl.BlockSpec((1, 4), lambda b, t: (0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, len(_PARAM_NAMES)), lambda b, t: (0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((2, _act_rows(P, Z)), lambda b, t: (0, 0),
                     memory_space=pltpu.SMEM),
    ]
    state_spec = pl.BlockSpec((s_rows, b_block), lambda b, t: (0, b),
                              memory_space=pltpu.VMEM)
    if carry:
        in_specs.append(state_spec)
    in_specs.append(
        pl.BlockSpec((t_chunk, exo_rows_total, b_block),
                     lambda b, t: (t, 0, b), memory_space=pltpu.VMEM))
    out_spec = pl.BlockSpec((_OUT_ROWS, b_block), lambda b, t: (0, b),
                            memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((_OUT_ROWS, B), jnp.float32)
    if carry:
        out_specs = (out_spec, state_spec)
        out_shape = (out_shape,
                     jax.ShapeDtypeStruct((s_rows, B), jnp.float32))
    else:
        out_specs = out_spec
    args = ((meta, params_packed, actions_packed, state_in, exo_packed)
            if carry else
            (meta, params_packed, actions_packed, exo_packed))
    return pl.pallas_call(
        kernel,
        interpret=interpret,
        grid=(n_b, n_t),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((s_rows, b_block), jnp.float32)],
    )(*args)


def _obs_dim(P: int, Z: int) -> int:
    """`observe(...).flatten()` length: nodes P*Z*2 + pipeline_ct 2 +
    running 2 + demand 2 + 3 price/carbon vectors [Z] + is_peak + tod."""
    return 2 * P * Z + 3 * Z + 8


def _mlp_dims(net_params, *, P: int, Z: int):
    """Validate an ActorCritic params pytree against the topology and
    return ``(dims, was_single)`` with dims = (F, F_pad, H, A). Shape
    reads only — no device work (the tensor build is jitted,
    `_pack_mlp_tensors`)."""
    pp = net_params["params"]
    extra = sorted(k for k in pp
                   if k.startswith("Dense_") and k not in ("Dense_0",
                                                           "Dense_1"))
    if extra:
        # Silently truncating a deeper torso would score a DIFFERENT
        # policy than the lax PPOBackend runs.
        raise ValueError(f"kernel supports exactly two torso layers; net "
                         f"has extra {extra}")
    w1 = pp["Dense_0"]["kernel"]
    was_single = w1.ndim == 2
    F, H = w1.shape[-2:]
    A = pp["actor_mean"]["kernel"].shape[-1]
    if F != _obs_dim(P, Z):
        raise ValueError(f"net expects obs dim {F}, topology gives "
                         f"{_obs_dim(P, Z)}")
    if A != _act_rows(P, Z):
        raise ValueError(f"net emits latent dim {A}, topology needs "
                         f"{_act_rows(P, Z)}")
    F_pad = math.ceil(F / 16) * 16       # bf16 sublane multiple
    A_pad = math.ceil(A / 8) * 8         # f32 sublane multiple
    return (F, F_pad, H, A), was_single


def _pack_mlp_tensors(net_params, dims, b_block: int):
    """Stacked ActorCritic params → the kernel's weight tensors:
    (w1 [NP,F_pad,H] bf16, b1 [NP,H,b_block] bf16, w2 [NP,H,H] bf16,
    b2 [NP,H,b_block] bf16, w3 [NP,H,A_pad] f32, b3 [NP,A_pad,b_block]
    f32). Weights keep flax's natural [in, out] layout — the kernel
    contracts on dim 0 (W^T @ x) so no transposes are materialized;
    biases replicate across lanes so the in-kernel add is elementwise.
    Pure jnp (runs inside the fused jit)."""
    F, F_pad, H, A = dims
    pp = net_params["params"]
    w1, b1 = pp["Dense_0"]["kernel"], pp["Dense_0"]["bias"]
    w2, b2 = pp["Dense_1"]["kernel"], pp["Dense_1"]["bias"]
    w3, b3 = pp["actor_mean"]["kernel"], pp["actor_mean"]["bias"]
    NP = w1.shape[0]
    A_pad = math.ceil(A / 8) * 8

    def rep(b, rows, dtype):             # [NP, rows] -> [NP, rows, b_block]
        return jnp.broadcast_to(b.astype(dtype)[:, :, None],
                                (NP, rows, b_block))

    return (
        jnp.pad(w1, ((0, 0), (0, F_pad - F), (0, 0))).astype(jnp.bfloat16),
        rep(b1, H, jnp.bfloat16),
        w2.astype(jnp.bfloat16),
        rep(b2, H, jnp.bfloat16),
        jnp.pad(w3, ((0, 0), (0, 0), (0, A_pad - A))).astype(jnp.float32),
        rep(jnp.pad(b3, ((0, 0), (0, A_pad - A))), A_pad, jnp.float32),
    )


@functools.partial(jax.jit, static_argnames=(
    "P", "Z", "K", "WD", "stochastic", "b_block", "t_chunk", "interpret",
    "slo_mask", "mlp_dims"))
def _run_mlp(params_packed, weights, exo_packed, meta, state_in=None,
             *, P, Z, K, WD, stochastic, b_block, t_chunk, slo_mask,
             mlp_dims, interpret=False):
    T_pad, exo_rows_total, B = exo_packed.shape
    faults, wl = lanes.stream_layout(exo_rows_total, Z)   # see _run
    carry = state_in is not None
    n_b = B // b_block
    n_t = T_pad // t_chunk
    NP = weights[0].shape[0]
    F, F_pad, H, A = mlp_dims
    A_pad = weights[4].shape[-1]
    kernel, ROWS = _make_kernel(P, Z, K, t_chunk, n_t, stochastic,
                                policy="mlp", slo_mask=slo_mask,
                                mlp_dims=mlp_dims, faults=faults,
                                workloads=WD if wl else 0, carry=carry)
    s_rows = math.ceil(ROWS["_total"][1] / 8) * 8
    if carry and tuple(state_in.shape) != (NP, s_rows, B):
        raise ValueError(
            f"carried state shape {tuple(state_in.shape)} does not "
            f"match the population kernel's ({NP}, {s_rows}, {B}) — "
            "build it with init_block_state for the SAME stream layout")

    def wspec(rows, cols):
        return pl.BlockSpec((1, rows, cols), lambda n, b, t: (n, 0, 0),
                            memory_space=pltpu.VMEM)

    in_specs = [
        pl.BlockSpec((1, 4), lambda n, b, t: (0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, len(_PARAM_NAMES)), lambda n, b, t: (0, 0),
                     memory_space=pltpu.SMEM),
        wspec(F_pad, H), wspec(H, b_block),      # w1, b1
        wspec(H, H), wspec(H, b_block),          # w2, b2
        wspec(H, A_pad), wspec(A_pad, b_block),  # w3, b3
    ]
    state_spec = pl.BlockSpec((1, s_rows, b_block),
                              lambda n, b, t: (n, 0, b),
                              memory_space=pltpu.VMEM)
    if carry:
        in_specs.append(state_spec)
    in_specs.append(
        pl.BlockSpec((t_chunk, exo_rows_total, b_block),
                     lambda n, b, t: (t, 0, b), memory_space=pltpu.VMEM))
    out_spec = pl.BlockSpec((1, _OUT_ROWS, b_block),
                            lambda n, b, t: (n, 0, b),
                            memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((NP, _OUT_ROWS, B), jnp.float32)
    if carry:
        out_specs = (out_spec, state_spec)
        out_shape = (out_shape,
                     jax.ShapeDtypeStruct((NP, s_rows, B), jnp.float32))
    else:
        out_specs = out_spec
    args = ((meta, params_packed, *weights, state_in, exo_packed)
            if carry else (meta, params_packed, *weights, exo_packed))
    return pl.pallas_call(
        kernel,
        interpret=interpret,
        grid=(NP, n_b, n_t),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((s_rows, b_block), jnp.float32)],
    )(*args)


def megakernel_rollout_summary(params: SimParams,
                               off_action: Action,
                               peak_action: Action,
                               traces: ExogenousTrace,
                               seed: int | jnp.ndarray = 0,
                               *,
                               stochastic: bool = True,
                               b_block: int = 512,
                               t_chunk: int = 64,
                               interpret: bool = False):
    """EpisodeSummary batch for a fresh-state rule-profile rollout.

    Drop-in for the bench/fleet-scoring path:
    ``batched_rollout_summary(params, zeros, RulePolicy(cfg).action_fn(),
    traces, keys, stochastic=...)`` — see module docstring for the parity
    contract. ``traces`` leading axes are [B, T]; B must be a multiple of
    ``b_block`` (the bench's power-of-two batches are).
    """
    B, T = traces.is_peak.shape
    if B % b_block:
        raise ValueError(f"megakernel needs B % {b_block} == 0, got {B}")
    P = int(off_action.zone_weight.shape[0])
    Z = int(off_action.zone_weight.shape[1])
    K = int(params.provision_pipeline_k)

    return _fused_profile_summary(
        params, off_action, peak_action, traces, jnp.int32(seed),
        T=T, P=P, Z=Z, K=K, WD=int(params.wl_batch_deadline_ticks),
        stochastic=stochastic, b_block=b_block,
        t_chunk=t_chunk, interpret=interpret, carbon=None)


@functools.partial(jax.jit, static_argnames=(
    "T", "P", "Z", "K", "WD", "stochastic", "b_block", "t_chunk",
    "interpret", "carbon"))
def _fused_profile_summary(params, off_action, peak_action, traces, seed,
                           *, T, P, Z, K, WD, stochastic, b_block,
                           t_chunk, interpret, carbon):
    """pack → kernel → finalize as ONE jitted program: the eager path
    paid a tunnel round-trip per pack/finalize op (~17ms of dispatch for
    a ~11ms kernel at B=32k — measured round 5), which the fusion
    removes along with the intermediate HBM round trips XLA can now
    elide. Delegates to the packed-stream path after the exo pack, so
    the two can never diverge."""
    T_pad = math.ceil(T / t_chunk) * t_chunk
    return _fused_packed_summary(
        params, off_action, peak_action, _pack_exo(traces, T_pad), seed,
        T=T, P=P, Z=Z, K=K, WD=WD, stochastic=stochastic,
        b_block=b_block, t_chunk=t_chunk, interpret=interpret,
        carbon=carbon)


def _meta(T: int, stochastic: bool, seed, t0=0) -> jnp.ndarray:
    """[1, 4] SMEM scalars: total horizon, stochastic flag, seed, and
    the launch's global tick offset (``t0`` — nonzero only for the
    streaming pipeline's carried-state block launches)."""
    meta = jnp.asarray([[T, 0, 0, 0]], jnp.int32)
    meta = meta.at[0, 1].set(int(stochastic))
    meta = meta.at[0, 2].set(jnp.int32(seed))
    return meta.at[0, 3].set(jnp.int32(t0))


def _finalize(params: SimParams, out: jnp.ndarray, T: int):
    """Kernel output rows [OUT_ROWS, B] → EpisodeSummary batch (fields
    [B]); the SAME reduction code as the lax path (`finalize_summary`
    under vmap), so the KPI formulas cannot drift."""
    from ccka_tpu.sim.metrics import SummaryAcc, finalize_summary

    (cost, carbon, requests, slo_s, evict, nct_spot, nct_od, served,
     capacity, waste, lat_sum, lat_max, queue, interrupts, denied,
     stale) = out[:16]
    # Workload-family accumulator rows (zeros from kernels without
    # workload lanes — matching the lax path's identically-zero fields).
    inf_viol, inf_q, inf_drop, b_miss, b_bl = out[16:21]
    B = cost.shape[0]

    zeros = jnp.zeros((B,), jnp.float32)
    mk_state = lambda c, g, r, s, e: ClusterState(   # noqa: E731
        nodes=zeros, pipeline=zeros, running=zeros, consol_timer_s=zeros,
        time_s=zeros, acc_cost_usd=c, acc_carbon_g=g, acc_requests=r,
        acc_slo_ok_s=s, acc_evictions=e)
    acc = SummaryAcc(
        nodes_ct_sum=jnp.stack([nct_spot, nct_od], axis=-1),
        served_sum=served, capacity_sum=capacity, waste_sum=waste,
        latency_sum=lat_sum, latency_max=lat_max, queue_sum=queue,
        interrupts_sum=interrupts, denied_sum=denied, stale_sum=stale,
        inf_viol_sum=inf_viol, inf_queue_sum=inf_q, inf_drop_sum=inf_drop,
        batch_miss_sum=b_miss, batch_bl_sum=b_bl)
    return jax.vmap(
        lambda init, fin, a: finalize_summary(params, init, fin, a, T)
    )(mk_state(zeros, zeros, zeros, zeros, zeros),
      mk_state(cost, carbon, requests, slo_s, evict), acc)


def carbon_megakernel_rollout_summary(params: SimParams,
                                      off_action: Action,
                                      peak_action: Action,
                                      traces: ExogenousTrace,
                                      seed: int | jnp.ndarray = 0,
                                      *,
                                      sharpness: float = 10.0,
                                      min_weight: float = 0.05,
                                      stickiness: float = 1.0,
                                      stochastic: bool = True,
                                      b_block: int = 512,
                                      t_chunk: int = 64,
                                      interpret: bool = False):
    """EpisodeSummary batch for a fresh-state CarbonAwarePolicy rollout
    (`policy/carbon.py`) — the carbon teacher at kernel speed. Keyword
    defaults mirror CarbonAwarePolicy's. Same-seed runs are PAIRED with
    the other kernel entry points (module docstring)."""
    B, T = traces.is_peak.shape
    if B % b_block:
        raise ValueError(f"megakernel needs B % {b_block} == 0, got {B}")
    P = int(off_action.zone_weight.shape[0])
    Z = int(off_action.zone_weight.shape[1])
    K = int(params.provision_pipeline_k)
    return _fused_profile_summary(
        params, off_action, peak_action, traces, jnp.int32(seed),
        T=T, P=P, Z=Z, K=K, WD=int(params.wl_batch_deadline_ticks),
        stochastic=stochastic, b_block=b_block,
        t_chunk=t_chunk, interpret=interpret,
        carbon=(float(sharpness), float(min_weight), float(stickiness)))


def neural_megakernel_rollout_summary(params: SimParams,
                                      cluster,
                                      net_params,
                                      traces: ExogenousTrace,
                                      seed: int | jnp.ndarray = 0,
                                      *,
                                      stochastic: bool = True,
                                      b_block: int = 256,
                                      t_chunk: int = 64,
                                      interpret: bool = False):
    """EpisodeSummary batch for fresh-state rollouts of the DETERMINISTIC
    learned policy ``latent_to_action(actor_mean(obs))`` — PPOBackend's
    decide (`train/ppo.py:385-389`) fused into the kernel.

    ``net_params``: an ActorCritic params pytree; a leading population
    axis on every leaf (e.g. ES candidates stacked by ``jax.vmap`` over
    `cem._unflatten`) makes this ONE launch over a (pop, batch, time)
    grid returning fields ``[NP, B]`` (single pytree → fields ``[B]``).
    All candidates see identical per-(trace, tick) world randomness —
    paired exactly like the lax path's shared world keys — and the same
    ``seed``/``b_block``/``t_chunk`` pairs them with the rule/carbon
    kernels, so ES fitness comparisons carry no cross-policy noise.
    NOTE the ``b_block`` DEFAULT here (256 — measured faster for the
    matmul tick, and it divides the natural ES trace-batch sizes)
    differs from the rule/carbon kernels' 512: paired cross-policy
    comparisons must pass one explicit b_block to every call (the cem
    mega engine does).
    ``cluster``: the ClusterConfig (SLO-pool mask for the fused Kyverno
    projection, `policy/constraints.py` rule 3).
    """
    from ccka_tpu.policy.constraints import slo_pool_mask

    B, T = traces.is_peak.shape
    if B % b_block:
        raise ValueError(f"megakernel needs B % {b_block} == 0, got {B}")
    P, Z = cluster.n_pools, cluster.n_zones
    K = int(params.provision_pipeline_k)
    dims, was_single = _mlp_dims(net_params, P=P, Z=Z)
    if was_single:
        net_params = jax.tree.map(lambda x: jnp.asarray(x)[None],
                                  net_params)
    slo = tuple(float(x) for x in np.asarray(slo_pool_mask(cluster)))
    summary = _fused_neural_summary(
        params, net_params, traces, jnp.int32(seed), T=T, P=P, Z=Z, K=K,
        WD=int(params.wl_batch_deadline_ticks),
        stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
        slo_mask=slo, mlp_dims=dims, interpret=interpret)
    if was_single:
        summary = jax.tree.map(lambda x: x[0], summary)
    return summary


def _neural_packed_impl(params, net_params, exo_packed, seed, *, T, P, Z,
                        K, WD, stochastic, b_block, t_chunk, slo_mask,
                        mlp_dims, interpret):
    """Weight pack → population kernel → finalize on an ALREADY-PACKED
    exo stream — the shared body of both neural fused entries."""
    weights = _pack_mlp_tensors(net_params, mlp_dims, b_block)
    out = _run_mlp(_pack_params(params), weights, exo_packed,
                   _meta(T, stochastic, seed), P=P, Z=Z, K=K, WD=WD,
                   stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
                   slo_mask=slo_mask, mlp_dims=mlp_dims,
                   interpret=interpret)
    return jax.vmap(lambda o: _finalize(params, o, T))(out)


@functools.partial(jax.jit, static_argnames=(
    "T", "P", "Z", "K", "WD", "stochastic", "b_block", "t_chunk",
    "interpret", "slo_mask", "mlp_dims"))
def _fused_neural_summary(params, net_params, traces, seed, *, T, P, Z,
                          K, WD, stochastic, b_block, t_chunk, slo_mask,
                          mlp_dims, interpret):
    """Weight pack → exo pack → population kernel → finalize, one jitted
    program (same dispatch-fusion rationale as
    `_fused_profile_summary`). Delegates to the packed-stream body after
    the exo pack, so the two can never diverge."""
    T_pad = math.ceil(T / t_chunk) * t_chunk
    return _neural_packed_impl(
        params, net_params, _pack_exo(traces, T_pad), seed, T=T, P=P, Z=Z,
        K=K, WD=WD, stochastic=stochastic, b_block=b_block,
        t_chunk=t_chunk, slo_mask=slo_mask, mlp_dims=mlp_dims,
        interpret=interpret)


_NEURAL_PACKED_STATICS = ("T", "P", "Z", "K", "WD", "stochastic",
                          "b_block", "t_chunk", "interpret", "slo_mask",
                          "mlp_dims")

_fused_neural_packed_summary = functools.partial(
    jax.jit, static_argnames=_NEURAL_PACKED_STATICS)(_neural_packed_impl)


def _neural_packed_donate_impl(params, net_params, exo_packed, seed, *,
                               T, P, Z, K, WD, stochastic, b_block,
                               t_chunk, slo_mask, mlp_dims, interpret):
    """Donating variant: consumes the packed exo stream and weights
    buffers and returns them aliased (ping-pong), so back-to-back ES
    generations hold ONE stream in HBM instead of two — the caller
    threads the returned stream into the next generation's synthesis
    (`SyntheticSignalSource.packed_trace_device(recycle=...)`). The
    identity returns are what make the donation USABLE (warning-free):
    jax donation is input→output aliasing, and a donated buffer with no
    same-shaped output is ignored with a warning."""
    s = _neural_packed_impl(
        params, net_params, exo_packed, seed, T=T, P=P, Z=Z, K=K, WD=WD,
        stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
        slo_mask=slo_mask, mlp_dims=mlp_dims, interpret=interpret)
    return s, exo_packed, net_params


_fused_neural_packed_donate = functools.partial(
    jax.jit, static_argnames=_NEURAL_PACKED_STATICS,
    donate_argnums=(1, 2))(_neural_packed_donate_impl)


def _check_chunking(T_pad: int, T: int, t_chunk: int) -> None:
    """Shared by the single-chip and sharded packed entries (one copy of
    the contract and its message)."""
    if T_pad % t_chunk or T > T_pad:
        raise ValueError(f"packed stream T_pad={T_pad} must be a "
                         f"t_chunk={t_chunk} multiple covering T={T} — "
                         "generate with the same t_chunk")


def _check_packed(exo_packed, T: int, b_block: int, t_chunk: int,
                  Z: int | None = None) -> None:
    T_pad, _rows, B = exo_packed.shape
    if B % b_block:
        raise ValueError(f"megakernel needs B % {b_block} == 0, got {B}")
    _check_chunking(T_pad, T, t_chunk)
    if Z is not None:
        # Row-count contract: exactly the plain layout or the fault-
        # widened one (`ccka_tpu/faults`) — anything else would misread
        # lanes. Raises on mismatch; the bool itself is re-derived from
        # the static shape inside the launchers.
        from ccka_tpu.faults.process import has_fault_lanes

        has_fault_lanes(exo_packed, Z)


def megakernel_summary_from_packed(params: SimParams,
                                   off_action: Action,
                                   peak_action: Action,
                                   exo_packed: jnp.ndarray,
                                   T: int,
                                   seed: int | jnp.ndarray = 0,
                                   *,
                                   stochastic: bool = True,
                                   b_block: int = 512,
                                   t_chunk: int = 64,
                                   interpret: bool = False,
                                   carbon: tuple | None = None,
                                   donate_stream: bool = False):
    """Rule-profile EpisodeSummary from an ALREADY-PACKED
    ``[T_pad, exo_rows, B]`` stream
    (`signals.synthetic.packed_trace_device`): the exo pack — the
    transpose that is most of the kernel's non-essential HBM traffic
    (ARCHITECTURE §6) — never runs, because the stream was generated in
    this layout. ``T`` is the true horizon (rows beyond it are padding).

    ``carbon``: optional (sharpness, min_weight, stickiness) statics —
    the CarbonAwarePolicy kernel on the same stream (see
    `carbon_megakernel_summary_from_packed` for keyword defaults).
    ``donate_stream``: donate the stream buffer into the launch and
    return ``(summary, stream)`` with the stream ALIASED in place —
    thread it into the next generation's synthesis
    (``packed_trace_device(recycle=...)``) so back-to-back generations
    never hold two streams in HBM.
    """
    P = int(off_action.zone_weight.shape[0])
    Z = int(off_action.zone_weight.shape[1])
    _check_packed(exo_packed, T, b_block, t_chunk, Z)
    fn = _fused_packed_donate if donate_stream else _fused_packed_summary
    return fn(
        params, off_action, peak_action, exo_packed, jnp.int32(seed),
        T=T, P=P, Z=Z, K=int(params.provision_pipeline_k),
        WD=int(params.wl_batch_deadline_ticks),
        stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
        interpret=interpret, carbon=carbon)


def carbon_megakernel_summary_from_packed(params: SimParams,
                                          off_action: Action,
                                          peak_action: Action,
                                          exo_packed: jnp.ndarray,
                                          T: int,
                                          seed: int | jnp.ndarray = 0,
                                          *,
                                          sharpness: float = 10.0,
                                          min_weight: float = 0.05,
                                          stickiness: float = 1.0,
                                          stochastic: bool = True,
                                          b_block: int = 512,
                                          t_chunk: int = 64,
                                          interpret: bool = False,
                                          donate_stream: bool = False):
    """CarbonAwarePolicy EpisodeSummary from a packed stream — the
    packed-layout analog of `carbon_megakernel_rollout_summary` (keyword
    defaults mirror CarbonAwarePolicy's). Same-seed/-stream runs are
    PAIRED with the other packed entry points."""
    return megakernel_summary_from_packed(
        params, off_action, peak_action, exo_packed, T, seed,
        stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
        interpret=interpret, donate_stream=donate_stream,
        carbon=(float(sharpness), float(min_weight), float(stickiness)))


def neural_megakernel_summary_from_packed(params: SimParams,
                                          cluster,
                                          net_params,
                                          exo_packed: jnp.ndarray,
                                          T: int,
                                          seed: int | jnp.ndarray = 0,
                                          *,
                                          stochastic: bool = True,
                                          b_block: int = 256,
                                          t_chunk: int = 64,
                                          interpret: bool = False,
                                          donate_stream: bool = False):
    """Population-MLP EpisodeSummary from a packed stream — the
    packed-layout analog of `neural_megakernel_rollout_summary` (same
    population-axis and pairing contract; same b_block=256 default and
    caveat). ``donate_stream=True`` donates BOTH the stream and the
    stacked weights pytree and returns ``(summary, stream)`` — the ES
    mega engine's per-generation tensors are single-use, so the launch
    reclaims them instead of double-peaking HBM."""
    from ccka_tpu.policy.constraints import slo_pool_mask

    P, Z = cluster.n_pools, cluster.n_zones
    _check_packed(exo_packed, T, b_block, t_chunk, Z)
    K = int(params.provision_pipeline_k)
    dims, was_single = _mlp_dims(net_params, P=P, Z=Z)
    if was_single:
        net_params = jax.tree.map(lambda x: jnp.asarray(x)[None],
                                  net_params)
    slo = tuple(float(x) for x in np.asarray(slo_pool_mask(cluster)))
    kw = dict(T=T, P=P, Z=Z, K=K,
              WD=int(params.wl_batch_deadline_ticks),
              stochastic=stochastic, b_block=b_block,
              t_chunk=t_chunk, slo_mask=slo, mlp_dims=dims,
              interpret=interpret)
    if donate_stream:
        summary, stream, _weights = _fused_neural_packed_donate(
            params, net_params, exo_packed, jnp.int32(seed), **kw)
    else:
        summary = _fused_neural_packed_summary(
            params, net_params, exo_packed, jnp.int32(seed), **kw)
        stream = exo_packed
    if was_single:
        summary = jax.tree.map(lambda x: x[0], summary)
    return (summary, stream) if donate_stream else summary


def _packed_summary_impl(params, off_action, peak_action, exo_packed,
                         seed, *, T, P, Z, K, WD, stochastic, b_block,
                         t_chunk, interpret, carbon=None):
    out = _run(_pack_params(params),
               jnp.stack([_pack_action(off_action),
                          _pack_action(peak_action)]),
               exo_packed, _meta(T, stochastic, seed),
               P=P, Z=Z, K=K, WD=WD, stochastic=stochastic,
               b_block=b_block, t_chunk=t_chunk, interpret=interpret,
               carbon=carbon)
    return _finalize(params, out, T)


_PACKED_STATICS = ("T", "P", "Z", "K", "WD", "stochastic", "b_block",
                   "t_chunk", "interpret", "carbon")

_fused_packed_summary = functools.partial(
    jax.jit, static_argnames=_PACKED_STATICS)(_packed_summary_impl)


def _packed_summary_donate_impl(params, off_action, peak_action,
                                exo_packed, seed, *, T, P, Z, K, WD,
                                stochastic, b_block, t_chunk, interpret,
                                carbon=None):
    """Donating variant of the packed entry: the stream buffer is
    consumed and returned aliased (see `_neural_packed_donate_impl` for
    why the identity return is load-bearing)."""
    s = _packed_summary_impl(
        params, off_action, peak_action, exo_packed, seed, T=T, P=P, Z=Z,
        K=K, WD=WD, stochastic=stochastic, b_block=b_block,
        t_chunk=t_chunk, interpret=interpret, carbon=carbon)
    return s, exo_packed


_fused_packed_donate = functools.partial(
    jax.jit, static_argnames=_PACKED_STATICS,
    donate_argnums=(3,))(_packed_summary_donate_impl)


# ---- plan playback: execute a precomputed action sequence ---------------


def pack_plan(actions: Action, T_pad: int) -> jnp.ndarray:
    """Action pytree with a leading time axis → packed plan stream.

    ``[T, ...]`` leaves (ONE plan broadcast to every cluster) →
    ``[T_pad, plan_rows]``; ``[B, T, ...]`` leaves (per-cluster plans —
    diff-MPC's receding-horizon output, one plan per trace) →
    ``[T_pad, plan_rows, B]`` in the exo stream's feature-first layout.
    Coordinate order is `_pack_action`'s (the kernel's action order);
    rows pad to a sublane multiple and ticks beyond T pad zero (the
    kernel's ``valid`` gate never executes them). Pure jnp — runs inside
    the fused jit."""
    per_cluster = actions.zone_weight.ndim == 4
    P = int(actions.zone_weight.shape[-2])
    Z = int(actions.zone_weight.shape[-1])
    rows, pr = _act_rows(P, Z), _plan_rows(P, Z)
    if per_cluster:
        packed = jax.vmap(jax.vmap(_pack_action))(actions)   # [B, T, rows]
        packed = jnp.moveaxis(packed, 0, -1)                 # [T, rows, B]
        return jnp.pad(packed, ((0, T_pad - packed.shape[0]),
                                (0, pr - rows), (0, 0)))
    packed = jax.vmap(_pack_action)(actions)                 # [T, rows]
    return jnp.pad(packed, ((0, T_pad - packed.shape[0]), (0, pr - rows)))


@functools.partial(jax.jit, static_argnames=(
    "P", "Z", "K", "WD", "stochastic", "b_block", "t_chunk", "interpret",
    "plan_batched"))
def _run_plan(params_packed, plan_packed, exo_packed, meta,
              state_in=None, *, P, Z, K, WD, stochastic, b_block,
              t_chunk, plan_batched, interpret=False):
    T_pad, exo_rows_total, B = exo_packed.shape
    faults, wl = lanes.stream_layout(exo_rows_total, Z)   # see _run
    carry = state_in is not None
    n_b = B // b_block
    n_t = T_pad // t_chunk
    kernel, ROWS = _make_kernel(P, Z, K, t_chunk, n_t, stochastic,
                                policy="plan", plan_batched=plan_batched,
                                faults=faults, workloads=WD if wl else 0,
                                carry=carry)
    s_rows = math.ceil(ROWS["_total"][1] / 8) * 8
    if carry and tuple(state_in.shape) != (s_rows, B):
        raise ValueError(
            f"carried state shape {tuple(state_in.shape)} does not "
            f"match this mode/layout's ({s_rows}, {B}) — build it with "
            "init_block_state for the SAME stream layout")
    pr = _plan_rows(P, Z)
    if plan_batched:
        # Per-cluster plans stream through VMEM exactly like the exo
        # block (same chunking, same lane split).
        plan_spec = pl.BlockSpec((t_chunk, pr, b_block),
                                 lambda b, t: (t, 0, b),
                                 memory_space=pltpu.VMEM)
    else:
        # One broadcast plan: t_chunk×rows scalars per chunk in SMEM
        # (~4 KB at the defaults) — no lane traffic at all.
        plan_spec = pl.BlockSpec((t_chunk, pr), lambda b, t: (t, 0),
                                 memory_space=pltpu.SMEM)

    in_specs = [
        pl.BlockSpec((1, 4), lambda b, t: (0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, len(_PARAM_NAMES)), lambda b, t: (0, 0),
                     memory_space=pltpu.SMEM),
        plan_spec,
    ]
    state_spec = pl.BlockSpec((s_rows, b_block), lambda b, t: (0, b),
                              memory_space=pltpu.VMEM)
    if carry:
        in_specs.append(state_spec)
    in_specs.append(
        pl.BlockSpec((t_chunk, exo_rows_total, b_block),
                     lambda b, t: (t, 0, b), memory_space=pltpu.VMEM))
    out_spec = pl.BlockSpec((_OUT_ROWS, b_block), lambda b, t: (0, b),
                            memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((_OUT_ROWS, B), jnp.float32)
    if carry:
        out_specs = (out_spec, state_spec)
        out_shape = (out_shape,
                     jax.ShapeDtypeStruct((s_rows, B), jnp.float32))
    else:
        out_specs = out_spec
    args = ((meta, params_packed, plan_packed, state_in, exo_packed)
            if carry else
            (meta, params_packed, plan_packed, exo_packed))
    return pl.pallas_call(
        kernel,
        interpret=interpret,
        grid=(n_b, n_t),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((s_rows, b_block), jnp.float32)],
    )(*args)


def _check_plan(plan_packed, exo_packed, P: int, Z: int) -> bool:
    """Shape contract of a packed plan vs its exo stream; returns
    ``plan_batched``."""
    T_pad, _rows, B = exo_packed.shape
    pr = _plan_rows(P, Z)
    if plan_packed.ndim not in (2, 3) or \
            plan_packed.shape[0] != T_pad or plan_packed.shape[1] != pr:
        raise ValueError(
            f"plan stream shape {tuple(plan_packed.shape)} does not "
            f"match the exo stream's T_pad={T_pad} / plan_rows={pr} for "
            f"this topology — pack with pack_plan(actions, T_pad)")
    if plan_packed.ndim == 3 and plan_packed.shape[2] != B:
        raise ValueError(
            f"per-cluster plan batch {plan_packed.shape[2]} != stream "
            f"batch {B}")
    return plan_packed.ndim == 3


def _plan_packed_impl(params, plan_packed, exo_packed, seed, *, T, P, Z,
                      K, WD, stochastic, b_block, t_chunk, interpret,
                      plan_batched):
    out = _run_plan(_pack_params(params), plan_packed, exo_packed,
                    _meta(T, stochastic, seed), P=P, Z=Z, K=K, WD=WD,
                    stochastic=stochastic, b_block=b_block,
                    t_chunk=t_chunk, plan_batched=plan_batched,
                    interpret=interpret)
    return _finalize(params, out, T)


_PLAN_STATICS = ("T", "P", "Z", "K", "WD", "stochastic", "b_block",
                 "t_chunk", "interpret", "plan_batched")

_fused_plan_packed_summary = functools.partial(
    jax.jit, static_argnames=_PLAN_STATICS)(_plan_packed_impl)


def _plan_packed_donate_impl(params, plan_packed, exo_packed, seed, *, T,
                             P, Z, K, WD, stochastic, b_block, t_chunk,
                             interpret, plan_batched):
    """Donating variant: the EXO stream is consumed and returned aliased
    (``(summary, stream)`` — recycle via ``packed_trace_device``). The
    PLAN stream is deliberately NOT donated: a scoreboard scores one
    plan against many fresh worlds, so the plan buffer outlives the
    launch by design."""
    s = _plan_packed_impl(
        params, plan_packed, exo_packed, seed, T=T, P=P, Z=Z, K=K, WD=WD,
        stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
        interpret=interpret, plan_batched=plan_batched)
    return s, exo_packed


_fused_plan_packed_donate = functools.partial(
    jax.jit, static_argnames=_PLAN_STATICS,
    donate_argnums=(2,))(_plan_packed_donate_impl)


@functools.partial(jax.jit, static_argnames=_PLAN_STATICS)
def _fused_plan_summary(params, plan_actions, traces, seed, *, T, P, Z,
                        K, WD, stochastic, b_block, t_chunk, interpret,
                        plan_batched):
    """Plan pack → exo pack → playback kernel → finalize, one jitted
    program (same dispatch-fusion rationale as `_fused_profile_summary`).
    Delegates to the packed-stream body after the packs, so the two can
    never diverge."""
    T_pad = math.ceil(T / t_chunk) * t_chunk
    return _plan_packed_impl(
        params, pack_plan(plan_actions, T_pad), _pack_exo(traces, T_pad),
        seed, T=T, P=P, Z=Z, K=K, WD=WD, stochastic=stochastic,
        b_block=b_block, t_chunk=t_chunk, interpret=interpret,
        plan_batched=plan_batched)


def plan_megakernel_rollout_summary(params: SimParams,
                                    plan_actions: Action,
                                    traces: ExogenousTrace,
                                    seed: int | jnp.ndarray = 0,
                                    *,
                                    stochastic: bool = True,
                                    b_block: int = 512,
                                    t_chunk: int = 64,
                                    interpret: bool = False):
    """EpisodeSummary batch for fresh-state PLAN-PLAYBACK rollouts: a
    precomputed action sequence executed tick-for-tick instead of a
    policy — the diff-MPC execution path at kernel speed (ISSUE 4).

    ``plan_actions``: an Action pytree with leading ``[T]`` axes (one
    plan broadcast to every cluster) or ``[B, T]`` axes (per-cluster
    plans, e.g. `train.mpc.receding_horizon_plan_batch` output decoded
    through ``latent_to_action``). Semantics contract: identical to
    ``rollout_actions(params, zeros, plan, trace, key, stochastic=...)``
    per cluster — exact (float-tolerance) in deterministic mode,
    distribution-level in stochastic mode. Same ``seed``/``b_block``/
    ``t_chunk`` pairs runs with the rule/carbon/mlp kernels (the kernel
    PRNG is policy-independent — module docstring), which is what lets
    MPC execution be scored against the rule baseline on IDENTICAL
    worlds AND identical interruption draws."""
    B, T = traces.is_peak.shape
    if B % b_block:
        raise ValueError(f"megakernel needs B % {b_block} == 0, got {B}")
    per_cluster = plan_actions.zone_weight.ndim == 4
    t_axis = plan_actions.zone_weight.shape[1 if per_cluster else 0]
    if t_axis != T:
        raise ValueError(f"plan covers {t_axis} ticks, traces cover {T} "
                         "— plan playback needs one action per tick")
    if per_cluster and plan_actions.zone_weight.shape[0] != B:
        raise ValueError(
            f"per-cluster plan batch {plan_actions.zone_weight.shape[0]} "
            f"!= trace batch {B}")
    P = int(plan_actions.zone_weight.shape[-2])
    Z = int(plan_actions.zone_weight.shape[-1])
    return _fused_plan_summary(
        params, plan_actions, traces, jnp.int32(seed), T=T, P=P, Z=Z,
        K=int(params.provision_pipeline_k),
        WD=int(params.wl_batch_deadline_ticks), stochastic=stochastic,
        b_block=b_block, t_chunk=t_chunk, interpret=interpret,
        plan_batched=per_cluster)


def plan_megakernel_summary_from_packed(params: SimParams,
                                        cluster,
                                        plan_packed: jnp.ndarray,
                                        exo_packed: jnp.ndarray,
                                        T: int,
                                        seed: int | jnp.ndarray = 0,
                                        *,
                                        stochastic: bool = True,
                                        b_block: int = 512,
                                        t_chunk: int = 64,
                                        interpret: bool = False,
                                        donate_stream: bool = False):
    """Plan-playback EpisodeSummary from ALREADY-PACKED plan + exo
    streams (`pack_plan` / `packed_trace_device`) — the packed-layout
    analog of `plan_megakernel_rollout_summary`, matching the rule/
    carbon packed entries' contract. ``cluster``: the ClusterConfig
    (topology — P/Z are not recoverable from padded streams).
    ``donate_stream=True`` donates the EXO stream and returns
    ``(summary, stream)`` aliased; the plan stream is never donated
    (one plan is typically scored against many fresh worlds — see
    `_plan_packed_donate_impl`)."""
    P, Z = cluster.n_pools, cluster.n_zones
    _check_packed(exo_packed, T, b_block, t_chunk, Z)
    plan_batched = _check_plan(plan_packed, exo_packed, P, Z)
    fn = (_fused_plan_packed_donate if donate_stream
          else _fused_plan_packed_summary)
    return fn(params, plan_packed, exo_packed, jnp.int32(seed), T=T, P=P,
              Z=Z, K=int(params.provision_pipeline_k),
              WD=int(params.wl_batch_deadline_ticks),
              stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
              interpret=interpret, plan_batched=plan_batched)


# ---- carried-state block entries (ISSUE 13: the streaming pipeline) ------
#
# One time BLOCK of a longer rollout per launch: the packed state enters
# and leaves the kernel (`_make_kernel`'s ``carry``), the block's global
# tick offset rides meta[0, 3], and the per-(batch block, time chunk)
# PRNG streams stay GLOBAL via `block_chunk_seed` — so a blocked rollout
# is bitwise the unblocked launch on the concatenated stream, in both
# deterministic and stochastic modes. The donating fused entries alias
# BOTH the consumed stream block (recycle it into the next block's
# synthesis, `packed_block_trace_device(recycle=...)`) and the carried
# state (ping-pong: one state buffer per chip), which is what bounds the
# streaming pipeline's memory at two stream blocks + one state.


def block_chunk_seed(seed, block_index, block_T: int, t_chunk: int):
    """Kernel seed for time block ``block_index`` making per-chunk PRNG
    streams GLOBAL — the time-axis analog of
    `parallel.sharded_kernel.shard_seed`:

    ``block_chunk_seed(s, j, bT, tc) + t_loc * SEED_CHUNK_STRIDE
      == s + (j * bT // tc + t_loc) * SEED_CHUNK_STRIDE``

    — i.e. local chunk ``t_loc`` of block ``j`` draws exactly the
    stream the unblocked kernel gives the same GLOBAL chunk.
    Traced-arithmetic-safe (``block_index`` is traced in the streaming
    loop's one compiled step program)."""
    return seed + block_index * (block_T // t_chunk) * SEED_CHUNK_STRIDE


def block_state_rows(params: SimParams, cluster, mode: str,
                     stream_rows: int) -> int:
    """Padded row count of the carried state for ``mode`` on a stream
    with ``stream_rows`` rows — the state layout depends on the lane
    layout (fault-observing policies carry held-signal rows; workload
    lanes carry queue rows), so the stream decides."""
    P, Z = cluster.n_pools, cluster.n_zones
    faults, wl = lanes.stream_layout(stream_rows, Z)
    policy = {"rule": "profiles"}.get(mode, mode)
    if policy == "neural":
        policy = "mlp"
    ROWS = _state_rows(P, Z, int(params.provision_pipeline_k),
                       fault_obs=faults and policy in ("carbon", "mlp"),
                       wl_D=(int(params.wl_batch_deadline_ticks)
                             if wl else 0))
    return math.ceil(ROWS["_total"][1] / 8) * 8


def init_block_state(params: SimParams, cluster, mode: str,
                     stream_rows: int, batch: int, *,
                     n_pop: int | None = None) -> jnp.ndarray:
    """Fresh-episode carried state (all zeros — exactly the state the
    non-carry kernel's ``_init`` builds): ``[s_rows, B]``, or
    ``[NP, s_rows, B]`` for the population ("neural") kernel."""
    s_rows = block_state_rows(params, cluster, mode, stream_rows)
    shape = ((n_pop, s_rows, batch) if n_pop is not None
             else (s_rows, batch))
    return jnp.zeros(shape, jnp.float32)


def _packed_block_impl(params, off_action, peak_action, exo_block,
                       state, seed, block_index, *, T, block_T, P, Z, K,
                       WD, stochastic, b_block, t_chunk, interpret,
                       carbon=None):
    t0 = block_index * block_T
    meta = _meta(T, stochastic,
                 block_chunk_seed(seed, block_index, block_T, t_chunk),
                 t0)
    out, state2 = _run(_pack_params(params),
                       jnp.stack([_pack_action(off_action),
                                  _pack_action(peak_action)]),
                       exo_block, meta, state, P=P, Z=Z, K=K, WD=WD,
                       stochastic=stochastic, b_block=b_block,
                       t_chunk=t_chunk, interpret=interpret,
                       carbon=carbon)
    # Identity stream return = the donation alias (recycle it).
    return out, state2, exo_block


_BLOCK_STATICS = ("T", "block_T", "P", "Z", "K", "WD", "stochastic",
                  "b_block", "t_chunk", "interpret", "carbon")

_fused_packed_block = functools.partial(
    jax.jit, static_argnames=_BLOCK_STATICS,
    donate_argnums=(3, 4))(_packed_block_impl)


def _neural_block_impl(params, weights, exo_block, state, seed,
                       block_index, *, T, block_T, P, Z, K, WD,
                       stochastic, b_block, t_chunk, slo_mask, mlp_dims,
                       interpret):
    """``weights``: the PRE-PACKED kernel tensors (`_pack_mlp_tensors`
    — packed once per factory; repacking per block would re-dispatch
    the pack every block). NOT donated: the same weights score every
    block of the rollout."""
    t0 = block_index * block_T
    meta = _meta(T, stochastic,
                 block_chunk_seed(seed, block_index, block_T, t_chunk),
                 t0)
    out, state2 = _run_mlp(_pack_params(params), weights, exo_block,
                           meta, state, P=P, Z=Z, K=K, WD=WD,
                           stochastic=stochastic, b_block=b_block,
                           t_chunk=t_chunk, slo_mask=slo_mask,
                           mlp_dims=mlp_dims, interpret=interpret)
    return out, state2, exo_block


_NEURAL_BLOCK_STATICS = ("T", "block_T", "P", "Z", "K", "WD",
                         "stochastic", "b_block", "t_chunk", "slo_mask",
                         "mlp_dims", "interpret")

_fused_neural_block = functools.partial(
    jax.jit, static_argnames=_NEURAL_BLOCK_STATICS,
    donate_argnums=(2, 3))(_neural_block_impl)


def _plan_block_impl(params, plan_packed, exo_block, state, seed,
                     block_index, *, T, block_T, P, Z, K, WD, stochastic,
                     b_block, t_chunk, interpret, plan_batched):
    """``plan_packed`` is the FULL-horizon packed plan; the block's rows
    slice off here (traced offset, static size) so one program serves
    every block. The plan is never donated — a plan is scored against
    many worlds and outlives every block launch by design."""
    t0 = block_index * block_T
    plan_block = jax.lax.dynamic_slice_in_dim(plan_packed, t0, block_T,
                                              axis=0)
    meta = _meta(T, stochastic,
                 block_chunk_seed(seed, block_index, block_T, t_chunk),
                 t0)
    out, state2 = _run_plan(_pack_params(params), plan_block, exo_block,
                            meta, state, P=P, Z=Z, K=K, WD=WD,
                            stochastic=stochastic, b_block=b_block,
                            t_chunk=t_chunk, plan_batched=plan_batched,
                            interpret=interpret)
    return out, state2, exo_block


_PLAN_BLOCK_STATICS = ("T", "block_T", "P", "Z", "K", "WD", "stochastic",
                       "b_block", "t_chunk", "interpret", "plan_batched")

_fused_plan_block = functools.partial(
    jax.jit, static_argnames=_PLAN_BLOCK_STATICS,
    donate_argnums=(2, 3))(_plan_block_impl)


class BlockSummaryFns(tuple):
    """(step, init_state, finalize, n_blocks, T_pad) with named access —
    the per-mode carried-state closure bundle
    (`packed_mode_block_summary_fn`)."""

    __slots__ = ()

    def __new__(cls, step, init_state, finalize, n_blocks, T_pad):
        return tuple.__new__(cls, (step, init_state, finalize, n_blocks,
                                   T_pad))

    step = property(lambda self: self[0])
    init_state = property(lambda self: self[1])
    finalize = property(lambda self: self[2])
    n_blocks = property(lambda self: self[3])
    T_pad = property(lambda self: self[4])


def packed_mode_block_summary_fn(params: SimParams, cluster, mode: str,
                                 *, T: int, block_T: int,
                                 b_block: int = 512, t_chunk: int = 64,
                                 interpret: bool = False,
                                 stochastic: bool = True,
                                 net_params=None, plan_packed=None,
                                 carbon: tuple | None = None
                                 ) -> BlockSummaryFns:
    """The per-mode ``*_block_summary`` closures of the streaming
    pipeline (ISSUE 13): a rollout resumable across time blocks, one
    closure bundle per REGISTERED packed policy mode (the `sim/lanes.py`
    mode registry — the same modes `packed_mode_summary_fn` serves
    synchronously). Since ISSUE 14 this is a registry dispatcher: each
    mode's bundle builder is registered once (`lanes.register_mode`)
    and every engine — this one, the mesh wrapper, the lax reference —
    resolves it from the one vocabulary.

    - ``step(stream_block, state, j, seed) -> (out, state', stream')``
      runs block ``j`` ([block_T, rows, B] stream slice) from carried
      ``state``; the stream block AND the state are DONATED — ``state'``
      aliases ``state``'s buffer (ping-pong) and ``stream'`` aliases the
      consumed block (recycle it into the next block's synthesis via
      ``packed_block_trace_device(recycle=...)``). ``out`` is the raw
      accumulator row block — meaningful only after the LAST block.
    - ``init_state(stream_rows, batch)`` → the fresh-episode state for
      the stream's lane layout.
    - ``finalize(out)`` → the EpisodeSummary batch (identical reduction
      to the synchronous entries' — same `_finalize`).

    Blocked == unblocked is bitwise by construction: same per-tick
    arithmetic, same global valid/tod clocks (meta t0), same global
    PRNG streams (`block_chunk_seed`), and the carried state crosses
    blocks through exact f32 HBM round trips. `tests/test_streaming.py`
    pins it for all four modes with fault+workload lanes on.

    ``plan_packed`` (mode "plan"): the full-horizon packed plan
    (`pack_plan(actions, T_pad)`); None plays the neutral broadcast
    plan (bench's content-independent throughput convention).
    ``carbon`` (mode "carbon"): policy statics, defaulting to
    CarbonAwarePolicy's. ``net_params`` (mode "neural"): ActorCritic
    pytree, population axis supported ([NP, B] fields).
    """
    builder = lanes.mode_engine(mode, "block_summary")
    return builder(params, cluster, T=T, block_T=block_T,
                   b_block=b_block, t_chunk=t_chunk, interpret=interpret,
                   stochastic=stochastic, net_params=net_params,
                   plan_packed=plan_packed, carbon=carbon)


def _block_check(block_T: int):
    def check_block(stream_block):
        if stream_block.shape[0] != block_T:
            raise ValueError(
                f"stream block covers {stream_block.shape[0]} ticks, "
                f"the blocked layout needs exactly block_T={block_T} — "
                "generate with packed_block_trace_device(block_T, ...)")
    return check_block


def _block_statics(params, cluster, *, T, block_T, t_chunk, b_block,
                   stochastic, interpret):
    n_blocks, T_pad = lanes.block_layout(T, block_T, t_chunk)
    P, Z = cluster.n_pools, cluster.n_zones
    kw = dict(T=T, block_T=block_T, P=P, Z=Z,
              K=int(params.provision_pipeline_k),
              WD=int(params.wl_batch_deadline_ticks),
              stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
              interpret=interpret)
    return n_blocks, T_pad, P, Z, kw


def _profile_block_fns(mode, params, cluster, *, T, block_T, b_block,
                       t_chunk, interpret, stochastic, net_params=None,
                       plan_packed=None, carbon=None) -> BlockSummaryFns:
    """rule/carbon carried-state bundle (registered builder)."""
    from ccka_tpu.policy.rule import offpeak_action, peak_action

    n_blocks, T_pad, _P, _Z, kw = _block_statics(
        params, cluster, T=T, block_T=block_T, t_chunk=t_chunk,
        b_block=b_block, stochastic=stochastic, interpret=interpret)
    check_block = _block_check(block_T)
    off, peak = offpeak_action(cluster), peak_action(cluster)
    if mode == "carbon" and carbon is None:
        carbon = (10.0, 0.05, 1.0)   # CarbonAwarePolicy defaults
    cstat = carbon if mode == "carbon" else None

    def step(stream_block, state, j, seed):
        check_block(stream_block)
        return _fused_packed_block(
            params, off, peak, stream_block, state, jnp.int32(seed),
            jnp.int32(j), carbon=cstat, **kw)

    def init_state(stream_rows, batch):
        return init_block_state(params, cluster, mode, stream_rows,
                                batch)

    def finalize(out):
        return _finalize(params, out, T)

    return BlockSummaryFns(step, init_state, finalize, n_blocks, T_pad)


def _neural_block_fns(params, cluster, *, T, block_T, b_block, t_chunk,
                      interpret, stochastic, net_params=None,
                      plan_packed=None, carbon=None) -> BlockSummaryFns:
    """Population-MLP carried-state bundle (registered builder)."""
    if net_params is None:
        raise ValueError("packed_mode_block_summary_fn: mode "
                         "'neural' needs net_params")
    from ccka_tpu.policy.constraints import slo_pool_mask

    n_blocks, T_pad, P, Z, kw = _block_statics(
        params, cluster, T=T, block_T=block_T, t_chunk=t_chunk,
        b_block=b_block, stochastic=stochastic, interpret=interpret)
    check_block = _block_check(block_T)
    dims, was_single = _mlp_dims(net_params, P=P, Z=Z)
    if was_single:
        net_params = jax.tree.map(lambda x: jnp.asarray(x)[None],
                                  net_params)
    slo = tuple(float(x) for x in np.asarray(slo_pool_mask(cluster)))
    weights = _pack_mlp_tensors(net_params, dims, b_block)
    n_pop = int(weights[0].shape[0])
    nkw = dict(kw, slo_mask=slo, mlp_dims=dims)

    def step(stream_block, state, j, seed):
        check_block(stream_block)
        return _fused_neural_block(
            params, weights, stream_block, state, jnp.int32(seed),
            jnp.int32(j), **nkw)

    def init_state(stream_rows, batch):
        return init_block_state(params, cluster, "neural", stream_rows,
                                batch, n_pop=n_pop)

    def finalize(out):
        s = jax.vmap(lambda o: _finalize(params, o, T))(out)
        return jax.tree.map(lambda x: x[0], s) if was_single else s

    return BlockSummaryFns(step, init_state, finalize, n_blocks, T_pad)


def _plan_block_fns(params, cluster, *, T, block_T, b_block, t_chunk,
                    interpret, stochastic, net_params=None,
                    plan_packed=None, carbon=None) -> BlockSummaryFns:
    """Plan-playback carried-state bundle (registered builder)."""
    from ccka_tpu.policy.rule import neutral_action

    n_blocks, T_pad, P, Z, kw = _block_statics(
        params, cluster, T=T, block_T=block_T, t_chunk=t_chunk,
        b_block=b_block, stochastic=stochastic, interpret=interpret)
    check_block = _block_check(block_T)
    if plan_packed is None:
        base = neutral_action(cluster)
        actions = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (T_pad,) + x.shape), base)
        plan_packed = pack_plan(actions, T_pad)
    pr = _plan_rows(P, Z)
    if plan_packed.shape[0] != T_pad or plan_packed.shape[1] != pr:
        raise ValueError(
            f"plan stream shape {tuple(plan_packed.shape)} does not "
            f"match T_pad={T_pad} / plan_rows={pr} — pack with "
            "pack_plan(actions, T_pad)")
    plan_batched = plan_packed.ndim == 3
    pkw = dict(kw, plan_batched=plan_batched)

    def step(stream_block, state, j, seed):
        check_block(stream_block)
        return _fused_plan_block(
            params, plan_packed, stream_block, state,
            jnp.int32(seed), jnp.int32(j), **pkw)

    def init_state(stream_rows, batch):
        return init_block_state(params, cluster, "plan", stream_rows,
                                batch)

    def finalize(out):
        return _finalize(params, out, T)

    return BlockSummaryFns(step, init_state, finalize, n_blocks, T_pad)


# Dispatch/recompile watch (obs/compile.py) on the fused jit entry
# points — the only places a megakernel launch actually dispatches
# (`_run`/`_run_mlp` live inside these traces). A sweep legitimately
# compiles one program per (B, T, mode) combination, so the warmup
# budget is wider than the controller's; anything beyond it means a
# param-shape or static-arg leak is recompiling ~10s Mosaic programs
# mid-run.
from ccka_tpu.obs.compile import watch_jit  # noqa: E402

_fused_profile_summary = watch_jit(
    _fused_profile_summary, "megakernel.profile_summary", hot=True,
    warmup_compiles=6)
_fused_neural_summary = watch_jit(
    _fused_neural_summary, "megakernel.neural_summary", hot=True,
    warmup_compiles=6)
_fused_packed_summary = watch_jit(
    _fused_packed_summary, "megakernel.packed_summary", hot=True,
    warmup_compiles=6)
_fused_packed_donate = watch_jit(
    _fused_packed_donate, "megakernel.packed_summary_donate", hot=True,
    warmup_compiles=6)
_fused_neural_packed_summary = watch_jit(
    _fused_neural_packed_summary, "megakernel.neural_packed_summary",
    hot=True, warmup_compiles=6)
_fused_neural_packed_donate = watch_jit(
    _fused_neural_packed_donate, "megakernel.neural_packed_summary_donate",
    hot=True, warmup_compiles=6)
_fused_plan_summary = watch_jit(
    _fused_plan_summary, "megakernel.plan_summary", hot=True,
    warmup_compiles=6)
_fused_plan_packed_summary = watch_jit(
    _fused_plan_packed_summary, "megakernel.plan_packed_summary",
    hot=True, warmup_compiles=6)
_fused_plan_packed_donate = watch_jit(
    _fused_plan_packed_donate, "megakernel.plan_packed_summary_donate",
    hot=True, warmup_compiles=6)
# Wider warmup than the other fused entries: the streaming bench's
# paired sweep legitimately compiles TWO programs per geometry (the
# blocked program and the one-launch unblocked reference) across
# several geometries plus the chunked row's.
_fused_packed_block = watch_jit(
    _fused_packed_block, "megakernel.packed_block", hot=True,
    warmup_compiles=12)
_fused_neural_block = watch_jit(
    _fused_neural_block, "megakernel.neural_packed_block", hot=True,
    warmup_compiles=12)
_fused_plan_block = watch_jit(
    _fused_plan_block, "megakernel.plan_packed_block", hot=True,
    warmup_compiles=12)

def _profile_packed_fn(mode, params, cluster, *, T, b_block, t_chunk,
                       interpret, stochastic, net_params=None,
                       plan_packed=None):
    """rule/carbon sync packed closure (registered builder)."""
    from ccka_tpu.policy.rule import offpeak_action, peak_action

    kw = dict(stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
              interpret=interpret)
    off, peak = offpeak_action(cluster), peak_action(cluster)
    entry = (carbon_megakernel_summary_from_packed if mode == "carbon"
             else megakernel_summary_from_packed)

    def fn(stream, seed):
        return entry(params, off, peak, stream, T, seed, **kw)
    return fn


def _neural_packed_fn(params, cluster, *, T, b_block, t_chunk,
                      interpret, stochastic, net_params=None,
                      plan_packed=None):
    """Population-MLP sync packed closure (registered builder) — hoists
    the wrapper's host-side prep (slo mask via numpy, population
    detection) OUT of the closure so the whole thing stays traceable
    under an outer jit."""
    if net_params is None:
        raise ValueError("packed_mode_summary_fn: mode 'neural' "
                         "needs net_params")
    from ccka_tpu.policy.constraints import slo_pool_mask

    P, Z = cluster.n_pools, cluster.n_zones
    dims, was_single = _mlp_dims(net_params, P=P, Z=Z)
    if was_single:
        net_params = jax.tree.map(lambda x: jnp.asarray(x)[None],
                                  net_params)
    slo = tuple(float(x) for x in np.asarray(slo_pool_mask(cluster)))
    nkw = dict(T=T, P=P, Z=Z, K=int(params.provision_pipeline_k),
               WD=int(params.wl_batch_deadline_ticks),
               stochastic=stochastic, b_block=b_block,
               t_chunk=t_chunk, slo_mask=slo, mlp_dims=dims,
               interpret=interpret)

    def fn(stream, seed):
        s = _fused_neural_packed_summary(params, net_params, stream,
                                         jnp.int32(seed), **nkw)
        return (jax.tree.map(lambda x: x[0], s) if was_single
                else s)
    return fn


def _plan_packed_fn(params, cluster, *, T, b_block, t_chunk, interpret,
                    stochastic, net_params=None, plan_packed=None):
    """Plan-playback sync packed closure (registered builder).
    ``plan_packed=None`` plays the broadcast neutral plan (bench's
    content-independent throughput convention); the distillation
    factory passes its per-cluster packed plans instead."""
    from ccka_tpu.policy.rule import neutral_action

    kw = dict(stochastic=stochastic, b_block=b_block, t_chunk=t_chunk,
              interpret=interpret)
    if plan_packed is None:
        T_pad = math.ceil(T / t_chunk) * t_chunk
        base = neutral_action(cluster)
        actions = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (T_pad,) + x.shape), base)
        plan_packed = pack_plan(actions, T_pad)

    def fn(stream, seed):
        return plan_megakernel_summary_from_packed(
            params, cluster, plan_packed, stream, T, seed, **kw)
    return fn


def packed_mode_summary_fn(params: SimParams, cluster, mode: str, *,
                           T: int, b_block: int = 512,
                           t_chunk: int = 64, interpret: bool = False,
                           stochastic: bool = True, net_params=None,
                           plan_packed=None):
    """One JITTED ``(stream, seed) -> EpisodeSummary`` closure per packed
    policy mode — the device-time observatory's unit of timing and XLA
    attribution (`obs/costmodel.attribute` lowers exactly this callable,
    `bench.py --perf-only` and `ccka perf` both drive it, so the program
    the table names is the program the pipeline dispatches). All modes
    consume the SAME packed stream layout, making their occupancy
    ledgers directly comparable.

    Since ISSUE 14 a registry dispatcher (`sim/lanes.py` mode registry;
    unknown names rejected with the registered vocabulary): "rule"/
    "carbon" close over the profile actions; "plan" plays ``plan_packed``
    (or a broadcast neutral-action plan when None — playback throughput
    is content-independent); "neural" requires ``net_params``."""
    builder = lanes.mode_engine(mode, "packed_summary")
    fn = builder(params, cluster, T=T, b_block=b_block, t_chunk=t_chunk,
                 interpret=interpret, stochastic=stochastic,
                 net_params=net_params, plan_packed=plan_packed)
    # Watched under the MODE's name (shared_stats: one closure per
    # geometry, one hot path to the reader) so `ccka perf`'s program
    # table joins dispatch counters and cost attribution on one row —
    # the inner fused entries inline under this jit and count nothing.
    return watch_jit(jax.jit(fn), f"megakernel.mode.{mode}", hot=True,
                     warmup_compiles=4, shared_stats=True)


# ---- mode registration (the `sim/lanes.py` registry — ISSUE 14) ----------
#
# The four built-in packed policy modes register HERE, once: their fused
# sync entries and carried-state streaming bundles. The lax reference
# engines arrive from `sim/rollout.py` and the mesh engines from
# `parallel/sharded_kernel.py` (each module provides its slot at import
# — `lanes.provide_mode_engine`), so a NEW policy mode is one
# `register_mode` call plus its engine closures, not a five-site edit.
# "rule" and "carbon" share a fused entry (the carbon statics re-key
# the same program family) — the observatory's per-mode attribution
# names disambiguate them.

lanes.register_mode(
    "rule", watch_name="megakernel.packed_summary",
    packed_summary=functools.partial(_profile_packed_fn, "rule"),
    block_summary=functools.partial(_profile_block_fns, "rule"))
lanes.register_mode(
    "carbon", watch_name="megakernel.packed_summary",
    packed_summary=functools.partial(_profile_packed_fn, "carbon"),
    block_summary=functools.partial(_profile_block_fns, "carbon"))
lanes.register_mode(
    "neural", watch_name="megakernel.neural_packed_summary",
    packed_summary=_neural_packed_fn,
    block_summary=_neural_block_fns)
lanes.register_mode(
    "plan", watch_name="megakernel.plan_packed_summary",
    packed_summary=_plan_packed_fn,
    block_summary=_plan_block_fns)


def packed_mode_watch_names() -> dict:
    """mode → compile-watch name, derived LIVE from the mode registry so
    the observatory's vocabulary (`bench.py --perf-only`, `ccka perf`,
    `obs/occupancy.py`) can never drift from the registered modes."""
    return {m: mode.watch_name for m, mode in lanes.MODES.items()}


# Import-time snapshot kept for the existing surface; prefer the
# function (a mode registered later — e.g. by a test — appears there).
PACKED_MODE_WATCH_NAMES = packed_mode_watch_names()


def unpack_exo(exo_packed: jnp.ndarray, T: int, Z: int) -> ExogenousTrace:
    """Inverse of `_pack_exo` — [T_pad, rows, B] → [B, T, ...] traces.
    Gate/test plumbing only: it pays exactly the transpose the packed
    path exists to skip, so the hot paths never call it."""
    x = exo_packed[:T]

    def bt(a):  # [T, k, B] -> [B, T, k]
        return jnp.transpose(a, (2, 0, 1))

    return ExogenousTrace(
        spot_price_hr=bt(x[:, 0:Z]),
        od_price_hr=bt(x[:, Z:2 * Z]),
        carbon_g_kwh=bt(x[:, 2 * Z:3 * Z]),
        demand_pods=bt(x[:, 3 * Z:3 * Z + 2]),
        is_peak=jnp.transpose(x[:, 3 * Z + 2], (1, 0)),
    )


def kernel_numerics_action_fn(net_params, cluster, params_sim: SimParams):
    """A lax-path ``action_fn`` reproducing the mlp kernel's EXACT
    numeric path (f32-accumulated bf16 matmuls rounded once, f32 head,
    same codec) — the deterministic interpret-mode parity anchor for
    `tests/test_megakernel.py`. Differs from PPOBackend only in bf16
    rounding placement (distribution-level parity with the real flax
    forward is asserted separately)."""
    from ccka_tpu.models import latent_to_action
    from ccka_tpu.policy.base import observe

    pp = net_params["params"]
    extra = sorted(k for k in pp
                   if k.startswith("Dense_") and k not in ("Dense_0",
                                                           "Dense_1"))
    if extra:
        raise ValueError(f"kernel numerics cover exactly two torso "
                         f"layers; net has extra {extra}")
    w1 = jnp.asarray(pp["Dense_0"]["kernel"], jnp.bfloat16)
    b1 = jnp.asarray(pp["Dense_0"]["bias"], jnp.bfloat16)
    w2 = jnp.asarray(pp["Dense_1"]["kernel"], jnp.bfloat16)
    b2 = jnp.asarray(pp["Dense_1"]["bias"], jnp.bfloat16)
    w3 = jnp.asarray(pp["actor_mean"]["kernel"], jnp.float32)
    b3 = jnp.asarray(pp["actor_mean"]["bias"], jnp.float32)

    def action_fn(state, exo, t):
        obs = observe(params_sim, state, exo).flatten()
        x = (jnp.sign(obs) * jnp.log1p(jnp.abs(obs))).astype(jnp.bfloat16)
        h = jax.nn.gelu(jnp.dot(
            x, w1, preferred_element_type=jnp.float32
        ).astype(jnp.bfloat16) + b1)
        h = jax.nn.gelu(jnp.dot(
            h, w2, preferred_element_type=jnp.float32
        ).astype(jnp.bfloat16) + b2)
        u = jnp.dot(h.astype(jnp.float32), w3,
                    preferred_element_type=jnp.float32) + b3
        return latent_to_action(u, cluster)

    return action_fn
