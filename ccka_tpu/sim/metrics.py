"""Episode summaries: the KPIs the reference never measured.

BASELINE.md: the reference publishes no $/SLO-hour or gCO2/req numbers; this
module *defines* them so the rule baseline and learned policies are scored
identically (SURVEY.md §7 hard part (2)). Dashboards planned in the proposal
("$/1k req, gCO2e/1k req, waste%, Spot exposure", proposal PDF p.5) map to
fields here.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ccka_tpu.sim.types import CT_SPOT, ClusterState, N_CT, SimParams, StepMetrics

_EPS = 1e-9


class EpisodeSummary(NamedTuple):
    cost_usd: jnp.ndarray            # [] total spend
    carbon_kg: jnp.ndarray           # [] total emissions
    requests: jnp.ndarray            # [] served requests (proxy)
    slo_hours: jnp.ndarray           # [] hours meeting the served-fraction SLO
    hours: jnp.ndarray               # [] episode length
    usd_per_slo_hour: jnp.ndarray    # [] headline metric 1
    g_co2_per_kreq: jnp.ndarray      # [] headline metric 2 (grams per 1k req)
    usd_per_kreq: jnp.ndarray        # [] proposal's "$/1k req"
    slo_attainment: jnp.ndarray      # [] fraction of ticks meeting SLO
    mean_nodes: jnp.ndarray          # [] average fleet size (incl. base? no — Karpenter-owned)
    spot_exposure: jnp.ndarray       # [] fraction of Karpenter node-hours on spot
    waste_frac: jnp.ndarray          # [] unused capacity fraction (proposal "waste%")
    evictions: jnp.ndarray           # [] total consolidation evictions
    interruptions: jnp.ndarray       # [] total spot reclaims
    latency_p95_ms_mean: jnp.ndarray  # [] mean p95 proxy over the episode
    latency_p95_ms_max: jnp.ndarray   # [] worst tick p95
    queue_depth_mean: jnp.ndarray     # [] mean pending-pod backlog
    # Fault-injection counters (ccka_tpu/faults): identically 0 on the
    # pre-fault pipeline, so every recorded BASELINE/BENCH number keeps
    # its meaning (the zero-fault bitwise gate pins the shared fields).
    denials: jnp.ndarray              # [] total spot nodes denied (ICE)
    stale_ticks: jnp.ndarray          # [] ticks policies saw stale signals
    # Workload-family columns (ccka_tpu/workloads): identically 0 on the
    # pre-workload pipeline — same contract as the fault counters.
    inf_slo_violations: jnp.ndarray   # [] inference SLO-violation ticks
    inf_queue_mean: jnp.ndarray       # [] mean inference queue depth
    inf_dropped: jnp.ndarray          # [] inference work load-shed, total
    batch_deadline_misses: jnp.ndarray  # [] batch work aged out, total
    batch_backlog_mean: jnp.ndarray   # [] mean batch backlog


class SummaryAcc(NamedTuple):
    """Sufficient statistics for :class:`EpisodeSummary`, carried through a
    scan so fleet-scale rollouts never materialize per-tick metrics
    (O(B) memory instead of O(B·T) — see
    :func:`ccka_tpu.sim.rollout.rollout_summary`). The episode totals the
    dynamics already fold into :class:`ClusterState` accumulators (cost,
    carbon, requests, SLO seconds, evictions) are not duplicated here."""

    nodes_ct_sum: jnp.ndarray    # [T_CT] Σ_t active nodes per capacity type
    served_sum: jnp.ndarray      # [] Σ_t served pods
    capacity_sum: jnp.ndarray    # [] Σ_t whole-fleet pod capacity
    waste_sum: jnp.ndarray       # [] Σ_t max(capacity − served, 0)
    latency_sum: jnp.ndarray     # [] Σ_t p95 proxy
    latency_max: jnp.ndarray     # [] max_t p95 proxy
    queue_sum: jnp.ndarray       # [] Σ_t pending backlog
    interrupts_sum: jnp.ndarray  # [] Σ_t spot reclaims
    denied_sum: jnp.ndarray      # [] Σ_t spot nodes denied (faults)
    stale_sum: jnp.ndarray       # [] Σ_t stale-signal ticks (faults)
    # Workload-family sufficient statistics (ccka_tpu/workloads).
    inf_viol_sum: jnp.ndarray    # [] Σ_t inference SLO-violation ticks
    inf_queue_sum: jnp.ndarray   # [] Σ_t inference queue depth
    inf_drop_sum: jnp.ndarray    # [] Σ_t inference work load-shed
    batch_miss_sum: jnp.ndarray  # [] Σ_t batch deadline misses
    batch_bl_sum: jnp.ndarray    # [] Σ_t batch backlog

    @classmethod
    def zero(cls) -> "SummaryAcc":
        z = jnp.float32(0.0)
        return cls(nodes_ct_sum=jnp.zeros((N_CT,), jnp.float32),
                   served_sum=z, capacity_sum=z, waste_sum=z,
                   latency_sum=z, latency_max=z, queue_sum=z,
                   interrupts_sum=z, denied_sum=z, stale_sum=z,
                   inf_viol_sum=z, inf_queue_sum=z, inf_drop_sum=z,
                   batch_miss_sum=z, batch_bl_sum=z)

    def update(self, params: SimParams,
               metrics: StepMetrics) -> "SummaryAcc":
        nodes_total = metrics.nodes_by_ct.sum()
        capacity = (nodes_total + params.base_od_nodes) * params.pods_per_node
        served = metrics.served_pods.sum()
        return SummaryAcc(
            nodes_ct_sum=self.nodes_ct_sum + metrics.nodes_by_ct,
            served_sum=self.served_sum + served,
            capacity_sum=self.capacity_sum + capacity,
            waste_sum=self.waste_sum + jnp.maximum(capacity - served, 0.0),
            latency_sum=self.latency_sum + metrics.latency_p95_ms,
            latency_max=jnp.maximum(self.latency_max,
                                    metrics.latency_p95_ms),
            queue_sum=self.queue_sum + metrics.queue_depth,
            interrupts_sum=self.interrupts_sum + metrics.interrupted_nodes,
            denied_sum=self.denied_sum + metrics.denied_nodes,
            stale_sum=self.stale_sum + metrics.signal_stale,
            inf_viol_sum=self.inf_viol_sum + metrics.inf_slo_violation,
            inf_queue_sum=self.inf_queue_sum + metrics.inf_queue_depth,
            inf_drop_sum=self.inf_drop_sum + metrics.inf_dropped,
            batch_miss_sum=(self.batch_miss_sum
                            + metrics.batch_deadline_miss),
            batch_bl_sum=self.batch_bl_sum + metrics.batch_backlog,
        )


def finalize_summary(params: SimParams, initial: ClusterState,
                     final: ClusterState, acc: SummaryAcc,
                     n_ticks: int) -> EpisodeSummary:
    """Episode KPIs from the state accumulators + scan-carried sufficient
    statistics — field-for-field identical to :func:`summarize` over the
    stacked metrics (asserted by `tests/test_sim.py`'s parity test).

    The :class:`ClusterState` accumulators are *lifetime* totals, so the
    episode's share is the delta against ``initial`` — a warm-started
    rollout (state carried over from a previous episode) must not inherit
    the prior episode's cost/SLO/request totals.
    """
    dt_hr = params.dt_s / 3600.0
    t = jnp.float32(n_ticks)
    cost = final.acc_cost_usd - initial.acc_cost_usd
    carbon_g = final.acc_carbon_g - initial.acc_carbon_g
    requests = final.acc_requests - initial.acc_requests
    slo_ok_s = final.acc_slo_ok_s - initial.acc_slo_ok_s
    slo_hours = slo_ok_s / 3600.0
    hours = t * dt_hr
    node_hours = acc.nodes_ct_sum.sum() * dt_hr
    spot_hours = acc.nodes_ct_sum[CT_SPOT] * dt_hr
    return EpisodeSummary(
        cost_usd=cost,
        carbon_kg=carbon_g / 1000.0,
        requests=requests,
        slo_hours=slo_hours,
        hours=hours,
        usd_per_slo_hour=cost / (slo_hours + _EPS),
        g_co2_per_kreq=carbon_g / (requests / 1000.0 + _EPS),
        usd_per_kreq=cost / (requests / 1000.0 + _EPS),
        slo_attainment=slo_ok_s / (t * params.dt_s),
        mean_nodes=acc.nodes_ct_sum.sum() / t,
        spot_exposure=spot_hours / (node_hours + _EPS),
        waste_frac=acc.waste_sum / (acc.capacity_sum + _EPS),
        evictions=final.acc_evictions - initial.acc_evictions,
        interruptions=acc.interrupts_sum,
        latency_p95_ms_mean=acc.latency_sum / t,
        latency_p95_ms_max=acc.latency_max,
        queue_depth_mean=acc.queue_sum / t,
        denials=acc.denied_sum,
        stale_ticks=acc.stale_sum,
        inf_slo_violations=acc.inf_viol_sum,
        inf_queue_mean=acc.inf_queue_sum / t,
        inf_dropped=acc.inf_drop_sum,
        batch_deadline_misses=acc.batch_miss_sum,
        batch_backlog_mean=acc.batch_bl_sum / t,
    )


def summarize(params: SimParams, metrics: StepMetrics) -> EpisodeSummary:
    """Reduce per-tick metrics (leading axis T; optional batch axes after
    vmap) to episode KPIs. All reductions are over the time axis only, so a
    batched input yields batched summaries."""
    dt_hr = params.dt_s / 3600.0
    cost = metrics.cost_usd.sum(axis=-1)
    carbon_g = metrics.carbon_g.sum(axis=-1)
    # Requests only exist where raw demand exists (same clamp as dynamics).
    effective = jnp.minimum(metrics.served_pods, metrics.demand_pods)
    requests = (effective.sum(axis=-1) * params.rps_per_pod
                * params.dt_s).sum(axis=-1)
    slo_ticks = metrics.slo_ok.sum(axis=-1)
    n_ticks = jnp.float32(metrics.slo_ok.shape[-1])
    slo_hours = slo_ticks * dt_hr
    hours = n_ticks * dt_hr

    nodes_total = metrics.nodes_by_ct.sum(axis=-1)          # [..., T]
    node_hours = nodes_total.sum(axis=-1) * dt_hr
    spot_hours = metrics.nodes_by_ct[..., CT_SPOT].sum(axis=-1) * dt_hr

    served_total = metrics.served_pods.sum(axis=-1)         # [..., T]
    # Whole-fleet capacity: Karpenter nodes plus the managed base nodegroup
    # (pods bind to base capacity first, so excluding it zeroes real waste).
    capacity_proxy = (nodes_total + params.base_od_nodes) * params.pods_per_node
    waste = jnp.maximum(capacity_proxy - served_total, 0.0).sum(axis=-1)
    waste_frac = waste / (capacity_proxy.sum(axis=-1) + _EPS)

    return EpisodeSummary(
        cost_usd=cost,
        carbon_kg=carbon_g / 1000.0,
        requests=requests,
        slo_hours=slo_hours,
        hours=hours,
        usd_per_slo_hour=cost / (slo_hours + _EPS),
        g_co2_per_kreq=carbon_g / (requests / 1000.0 + _EPS),
        usd_per_kreq=cost / (requests / 1000.0 + _EPS),
        slo_attainment=slo_ticks / n_ticks,
        mean_nodes=nodes_total.mean(axis=-1),
        spot_exposure=spot_hours / (node_hours + _EPS),
        waste_frac=waste_frac,
        evictions=metrics.evicted_pods.sum(axis=-1),
        interruptions=metrics.interrupted_nodes.sum(axis=-1),
        latency_p95_ms_mean=metrics.latency_p95_ms.mean(axis=-1),
        latency_p95_ms_max=metrics.latency_p95_ms.max(axis=-1),
        queue_depth_mean=metrics.queue_depth.mean(axis=-1),
        denials=metrics.denied_nodes.sum(axis=-1),
        stale_ticks=metrics.signal_stale.sum(axis=-1),
        inf_slo_violations=metrics.inf_slo_violation.sum(axis=-1),
        inf_queue_mean=metrics.inf_queue_depth.mean(axis=-1),
        inf_dropped=metrics.inf_dropped.sum(axis=-1),
        batch_deadline_misses=metrics.batch_deadline_miss.sum(axis=-1),
        batch_backlog_mean=metrics.batch_backlog.mean(axis=-1),
    )
