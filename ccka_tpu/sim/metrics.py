"""Episode summaries: the KPIs the reference never measured.

BASELINE.md: the reference publishes no $/SLO-hour or gCO2/req numbers; this
module *defines* them so the rule baseline and learned policies are scored
identically (SURVEY.md §7 hard part (2)). Dashboards planned in the proposal
("$/1k req, gCO2e/1k req, waste%, Spot exposure", proposal PDF p.5) map to
fields here.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ccka_tpu.sim.types import CT_SPOT, SimParams, StepMetrics

_EPS = 1e-9


class EpisodeSummary(NamedTuple):
    cost_usd: jnp.ndarray            # [] total spend
    carbon_kg: jnp.ndarray           # [] total emissions
    requests: jnp.ndarray            # [] served requests (proxy)
    slo_hours: jnp.ndarray           # [] hours meeting the served-fraction SLO
    hours: jnp.ndarray               # [] episode length
    usd_per_slo_hour: jnp.ndarray    # [] headline metric 1
    g_co2_per_kreq: jnp.ndarray      # [] headline metric 2 (grams per 1k req)
    usd_per_kreq: jnp.ndarray        # [] proposal's "$/1k req"
    slo_attainment: jnp.ndarray      # [] fraction of ticks meeting SLO
    mean_nodes: jnp.ndarray          # [] average fleet size (incl. base? no — Karpenter-owned)
    spot_exposure: jnp.ndarray       # [] fraction of Karpenter node-hours on spot
    waste_frac: jnp.ndarray          # [] unused capacity fraction (proposal "waste%")
    evictions: jnp.ndarray           # [] total consolidation evictions
    interruptions: jnp.ndarray       # [] total spot reclaims
    latency_p95_ms_mean: jnp.ndarray  # [] mean p95 proxy over the episode
    latency_p95_ms_max: jnp.ndarray   # [] worst tick p95
    queue_depth_mean: jnp.ndarray     # [] mean pending-pod backlog


def summarize(params: SimParams, metrics: StepMetrics) -> EpisodeSummary:
    """Reduce per-tick metrics (leading axis T; optional batch axes after
    vmap) to episode KPIs. All reductions are over the time axis only, so a
    batched input yields batched summaries."""
    dt_hr = params.dt_s / 3600.0
    cost = metrics.cost_usd.sum(axis=-1)
    carbon_g = metrics.carbon_g.sum(axis=-1)
    # Requests only exist where raw demand exists (same clamp as dynamics).
    effective = jnp.minimum(metrics.served_pods, metrics.demand_pods)
    requests = (effective.sum(axis=-1) * params.rps_per_pod
                * params.dt_s).sum(axis=-1)
    slo_ticks = metrics.slo_ok.sum(axis=-1)
    n_ticks = jnp.float32(metrics.slo_ok.shape[-1])
    slo_hours = slo_ticks * dt_hr
    hours = n_ticks * dt_hr

    nodes_total = metrics.nodes_by_ct.sum(axis=-1)          # [..., T]
    node_hours = nodes_total.sum(axis=-1) * dt_hr
    spot_hours = metrics.nodes_by_ct[..., CT_SPOT].sum(axis=-1) * dt_hr

    served_total = metrics.served_pods.sum(axis=-1)         # [..., T]
    # Whole-fleet capacity: Karpenter nodes plus the managed base nodegroup
    # (pods bind to base capacity first, so excluding it zeroes real waste).
    capacity_proxy = (nodes_total + params.base_od_nodes) * params.pods_per_node
    waste = jnp.maximum(capacity_proxy - served_total, 0.0).sum(axis=-1)
    waste_frac = waste / (capacity_proxy.sum(axis=-1) + _EPS)

    return EpisodeSummary(
        cost_usd=cost,
        carbon_kg=carbon_g / 1000.0,
        requests=requests,
        slo_hours=slo_hours,
        hours=hours,
        usd_per_slo_hour=cost / (slo_hours + _EPS),
        g_co2_per_kreq=carbon_g / (requests / 1000.0 + _EPS),
        usd_per_kreq=cost / (requests / 1000.0 + _EPS),
        slo_attainment=slo_ticks / n_ticks,
        mean_nodes=nodes_total.mean(axis=-1),
        spot_exposure=spot_hours / (node_hours + _EPS),
        waste_frac=waste_frac,
        evictions=metrics.evicted_pods.sum(axis=-1),
        interruptions=metrics.interrupted_nodes.sum(axis=-1),
        latency_p95_ms_mean=metrics.latency_p95_ms.mean(axis=-1),
        latency_p95_ms_max=metrics.latency_p95_ms.max(axis=-1),
        queue_depth_mean=metrics.queue_depth.mean(axis=-1),
    )
