"""Rollouts: `lax.scan` over the horizon, `vmap` over the cluster batch.

This is the device-resident replacement for the reference's operational loop
(`demo_18 → demo_20|21 → demo_30 → demo_40`, `README.md:52-57`): instead of
one live cluster stepped by hand, thousands of simulated clusters advance a
full control horizon per XLA dispatch.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ccka_tpu.config import FrameworkConfig
from ccka_tpu.sim.dynamics import ExoStep, step
from ccka_tpu.sim.types import Action, ClusterState, N_CT, SimParams, StepMetrics
from ccka_tpu.signals.base import ExogenousTrace

# action_fn(state, exo_step, t_index) -> Action
ActionFn = Callable[[ClusterState, ExoStep, jnp.ndarray], Action]

# Scan unroll factor for the horizon loop: the per-step tensors are tiny
# ([B, P, Z, CT] and smaller), so per-iteration loop overhead dominates;
# unrolling 8 steps per scan iteration lets XLA fuse across ticks (~1.8x
# rollout throughput on v5e; flat beyond 8).
_UNROLL = 8


def initial_state(cfg: FrameworkConfig) -> ClusterState:
    """Fresh cluster: only the managed base nodegroup, nothing pending."""
    p, z = cfg.cluster.n_pools, cfg.cluster.n_zones
    c = 2
    k = cfg.sim.provision_delay_steps
    zero = jnp.float32(0.0)
    return ClusterState(
        nodes=jnp.zeros((p, z, N_CT), jnp.float32),
        pipeline=jnp.zeros((k, p, z, N_CT), jnp.float32),
        running=jnp.zeros((c,), jnp.float32),
        consol_timer_s=jnp.zeros((p,), jnp.float32),
        time_s=zero,
        acc_cost_usd=zero,
        acc_carbon_g=zero,
        acc_requests=zero,
        acc_slo_ok_s=zero,
        acc_evictions=zero,
    )


def exo_steps(trace: ExogenousTrace) -> ExoStep:
    """Repack a time-major trace as scan-consumable xs (leading axis = T)."""
    return ExoStep(
        spot_price_hr=trace.spot_price_hr,
        od_price_hr=trace.od_price_hr,
        carbon_g_kwh=trace.carbon_g_kwh,
        demand_pods=trace.demand_pods,
        is_peak=trace.is_peak,
    )


def rollout(params: SimParams,
            state0: ClusterState,
            action_fn: ActionFn,
            trace: ExogenousTrace,
            key: jax.Array,
            *,
            stochastic: bool = False) -> tuple[ClusterState, StepMetrics]:
    """Scan the closed loop decide→act→step over the trace horizon.

    ``action_fn`` is the PolicyBackend's jittable decide(); it sees the
    current state and tick signals — exactly the observation surface the
    reference's operator has when choosing demo_20 vs demo_21.
    """
    xs = exo_steps(trace)
    t0 = jnp.arange(xs.is_peak.shape[0], dtype=jnp.int32)

    def body(carry, inp):
        state, k = carry
        exo, t = inp
        k, sub = jax.random.split(k)
        action = action_fn(state, exo, t)
        state, metrics = step(params, state, action, exo, sub,
                              stochastic=stochastic)
        return (state, k), metrics

    (final, _), metrics = jax.lax.scan(body, (state0, key), (xs, t0),
                                       unroll=_UNROLL)
    return final, metrics


def rollout_actions(params: SimParams,
                    state0: ClusterState,
                    actions: Action,
                    trace: ExogenousTrace,
                    key: jax.Array,
                    *,
                    stochastic: bool = False) -> tuple[ClusterState, StepMetrics]:
    """Rollout under a precomputed action sequence (leading axis = T).

    This is the diff-MPC path: gradients flow from episode objectives back
    through `scan` into every action of the plan.
    """
    xs = exo_steps(trace)

    def body(carry, inp):
        state, k = carry
        exo, action = inp
        k, sub = jax.random.split(k)
        state, metrics = step(params, state, action, exo, sub,
                              stochastic=stochastic)
        return (state, k), metrics

    (final, _), metrics = jax.lax.scan(body, (state0, key), (xs, actions),
                                       unroll=_UNROLL)
    return final, metrics


def rollout_summary(params: SimParams,
                    state0: ClusterState,
                    action_fn: ActionFn,
                    trace: ExogenousTrace,
                    key: jax.Array,
                    *,
                    stochastic: bool = False):
    """Closed-loop rollout that reduces to episode KPIs *inside* the scan.

    :func:`rollout` materializes per-tick :class:`StepMetrics` stacked over
    the horizon — O(B·T·fields) HBM writes, which caps the fleet batch
    (B=32k × one day OOMs a v5e chip on metric stacking alone). This
    variant carries the summary sufficient statistics in the scan state
    and emits no per-tick output, so memory is O(B) regardless of horizon
    — the fleet-scoring path. Returns ``(final_state, EpisodeSummary)``
    identical (same keys, same dynamics) to
    ``summarize(params, rollout(...)[1])``.
    """
    from ccka_tpu.sim.metrics import SummaryAcc, finalize_summary

    xs = exo_steps(trace)
    steps = xs.is_peak.shape[0]
    t0 = jnp.arange(steps, dtype=jnp.int32)
    acc0 = SummaryAcc.zero()

    def body(carry, inp):
        state, k, acc = carry
        exo, t = inp
        k, sub = jax.random.split(k)
        action = action_fn(state, exo, t)
        state, metrics = step(params, state, action, exo, sub,
                              stochastic=stochastic)
        return (state, k, acc.update(params, metrics)), None

    (final, _, acc), _ = jax.lax.scan(body, (state0, key, acc0), (xs, t0),
                                      unroll=_UNROLL)
    return final, finalize_summary(params, state0, final, acc, steps)


def batched_rollout_summary(params: SimParams,
                            states0: ClusterState,
                            action_fn: ActionFn,
                            traces: ExogenousTrace,
                            keys: jax.Array,
                            *,
                            stochastic: bool = False):
    """`vmap` of :func:`rollout_summary` — per-cluster KPI summaries for
    fleet batches too large to stack per-tick metrics for."""
    fn = jax.vmap(
        lambda s, tr, k: rollout_summary(params, s, action_fn, tr, k,
                                         stochastic=stochastic),
        in_axes=(0, 0, 0))
    return fn(states0, traces, keys)


def batched_rollout(params: SimParams,
                    states0: ClusterState,
                    action_fn: ActionFn,
                    traces: ExogenousTrace,
                    keys: jax.Array,
                    *,
                    stochastic: bool = False) -> tuple[ClusterState, StepMetrics]:
    """`vmap` of :func:`rollout` over a leading cluster-batch axis.

    ``states0``/``traces``/``keys`` carry a leading batch dim B; params and
    the policy are shared. This is BASELINE.json config #3/#5: hundreds to
    10k clusters advanced in lockstep on one chip or a mesh.
    """
    fn = jax.vmap(
        lambda s, tr, k: rollout(params, s, action_fn, tr, k,
                                 stochastic=stochastic),
        in_axes=(0, 0, 0))
    return fn(states0, traces, keys)
