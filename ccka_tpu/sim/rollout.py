"""Rollouts: `lax.scan` over the horizon, `vmap` over the cluster batch.

This is the device-resident replacement for the reference's operational loop
(`demo_18 → demo_20|21 → demo_30 → demo_40`, `README.md:52-57`): instead of
one live cluster stepped by hand, thousands of simulated clusters advance a
full control horizon per XLA dispatch.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ccka_tpu.config import FrameworkConfig
from ccka_tpu.sim.dynamics import ExoStep, step
from ccka_tpu.sim.types import Action, ClusterState, N_CT, SimParams, StepMetrics
from ccka_tpu.signals.base import ExogenousTrace

# action_fn(state, exo_step, t_index) -> Action
ActionFn = Callable[[ClusterState, ExoStep, jnp.ndarray], Action]

# Scan unroll factor for the horizon loop: the per-step tensors are tiny
# ([B, P, Z, CT] and smaller), so per-iteration loop overhead dominates;
# unrolling 8 steps per scan iteration lets XLA fuse across ticks (~1.8x
# rollout throughput on v5e; flat beyond 8).
_UNROLL = 8


def initial_state(cfg: FrameworkConfig) -> ClusterState:
    """Fresh cluster: only the managed base nodegroup, nothing pending."""
    p, z = cfg.cluster.n_pools, cfg.cluster.n_zones
    c = 2
    k = cfg.sim.provision_delay_steps
    zero = jnp.float32(0.0)
    return ClusterState(
        nodes=jnp.zeros((p, z, N_CT), jnp.float32),
        pipeline=jnp.zeros((k, p, z, N_CT), jnp.float32),
        running=jnp.zeros((c,), jnp.float32),
        consol_timer_s=jnp.zeros((p,), jnp.float32),
        time_s=zero,
        acc_cost_usd=zero,
        acc_carbon_g=zero,
        acc_requests=zero,
        acc_slo_ok_s=zero,
        acc_evictions=zero,
    )


def exo_steps(trace: ExogenousTrace) -> ExoStep:
    """Repack a time-major trace as scan-consumable xs (leading axis = T)."""
    return ExoStep(
        spot_price_hr=trace.spot_price_hr,
        od_price_hr=trace.od_price_hr,
        carbon_g_kwh=trace.carbon_g_kwh,
        demand_pods=trace.demand_pods,
        is_peak=trace.is_peak,
    )


def observed_exo(last_obs: ExoStep, exo: ExoStep, stale) -> ExoStep:
    """Policy-observed signals under a possible outage (`ccka_tpu/faults`):
    prices/carbon/demand hold the last pre-outage values while ``stale``
    is set; ``is_peak`` is clock-derived and stays true. Dynamics always
    consume the true ``exo`` — only the decide's view goes stale (the
    same split the megakernel's fault mode implements in-register)."""
    hold = stale > 0.5
    return ExoStep(
        spot_price_hr=jnp.where(hold, last_obs.spot_price_hr,
                                exo.spot_price_hr),
        od_price_hr=jnp.where(hold, last_obs.od_price_hr, exo.od_price_hr),
        carbon_g_kwh=jnp.where(hold, last_obs.carbon_g_kwh,
                               exo.carbon_g_kwh),
        demand_pods=jnp.where(hold, last_obs.demand_pods, exo.demand_pods),
        is_peak=exo.is_peak,
    )


def _wl_zero(params: SimParams):
    """Fresh per-family queue state (ccka_tpu/workloads)."""
    from ccka_tpu.workloads.types import WorkloadState

    return WorkloadState.zero(int(params.wl_batch_deadline_ticks))


def rollout(params: SimParams,
            state0: ClusterState,
            action_fn: ActionFn,
            trace: ExogenousTrace,
            key: jax.Array,
            *,
            stochastic: bool = False,
            faults=None,
            workloads=None) -> tuple[ClusterState, StepMetrics]:
    """Scan the closed loop decide→act→step over the trace horizon.

    ``action_fn`` is the PolicyBackend's jittable decide(); it sees the
    current state and tick signals — exactly the observation surface the
    reference's operator has when choosing demo_20 vs demo_21.

    ``faults``: optional time-major :class:`ccka_tpu.faults.FaultStep`
    pytree (leaves ``[T, ...]``). When given, each tick's disturbances
    feed the dynamics and the policy observes STALE signals during
    outage windows (held at the last pre-outage tick; tick 0 observes
    its own fresh signals, matching the kernel's ``tglob > 0`` gate).

    ``workloads``: optional time-major
    :class:`ccka_tpu.workloads.WorkloadStep` pytree (leaves ``[T]``).
    When given, per-family queue state (zero-initialized) is carried
    through the scan and each tick's arrivals drain from the fleet's
    headroom (`sim/dynamics.step` workload path); policies do not
    observe the queues — families are tenant load the fleet's slack
    either absorbs or doesn't.

    ``None`` for both takes the exact pre-fault/pre-workload path — a
    Python-level branch, so existing rollouts stay bitwise identical.
    """
    xs = exo_steps(trace)
    t0 = jnp.arange(xs.is_peak.shape[0], dtype=jnp.int32)

    if faults is None and workloads is None:
        def body(carry, inp):
            state, k = carry
            exo, t = inp
            k, sub = jax.random.split(k)
            action = action_fn(state, exo, t)
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic)
            return (state, k), metrics

        (final, _), metrics = jax.lax.scan(body, (state0, key), (xs, t0),
                                           unroll=_UNROLL)
        return final, metrics

    hf, hw = faults is not None, workloads is not None

    def body(carry, inp):
        state, k = carry[0], carry[1]
        rest = list(carry[2:])
        last = rest.pop(0) if hf else None
        ws = rest.pop(0) if hw else None
        exo, t = inp[0], inp[1]
        extra = list(inp[2:])
        f = extra.pop(0) if hf else None
        w = extra.pop(0) if hw else None
        k, sub = jax.random.split(k)
        obs = observed_exo(last, exo, f.signal_stale) if hf else exo
        action = action_fn(state, obs, t)
        if hw:
            state, metrics, ws = step(params, state, action, exo, sub,
                                      stochastic=stochastic, fault=f,
                                      workload=w, wl_state=ws)
        else:
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic, fault=f)
        carry2 = (state, k) + ((obs,) if hf else ()) + ((ws,) if hw else ())
        return carry2, metrics

    carry0 = (state0, key)
    if hf:
        carry0 += (jax.tree.map(lambda x: x[0], xs),)
    if hw:
        carry0 += (_wl_zero(params),)
    inps = (xs, t0) + ((faults,) if hf else ()) + (
        (workloads,) if hw else ())
    (final, *_), metrics = jax.lax.scan(body, carry0, inps, unroll=_UNROLL)
    return final, metrics


def rollout_actions(params: SimParams,
                    state0: ClusterState,
                    actions: Action,
                    trace: ExogenousTrace,
                    key: jax.Array,
                    *,
                    stochastic: bool = False,
                    faults=None,
                    workloads=None) -> tuple[ClusterState, StepMetrics]:
    """Rollout under a precomputed action sequence (leading axis = T).

    This is the diff-MPC path: gradients flow from episode objectives back
    through `scan` into every action of the plan. ``faults``/
    ``workloads``: optional time-major pytrees — a plan observes
    nothing, so only the dynamics-side disturbances/queues apply (the
    playback kernel's contract).
    """
    xs = exo_steps(trace)

    if faults is None and workloads is None:
        def body(carry, inp):
            state, k = carry
            exo, action = inp
            k, sub = jax.random.split(k)
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic)
            return (state, k), metrics

        (final, _), metrics = jax.lax.scan(body, (state0, key),
                                           (xs, actions), unroll=_UNROLL)
        return final, metrics

    hf, hw = faults is not None, workloads is not None

    def body(carry, inp):
        state, k = carry[0], carry[1]
        ws = carry[2] if hw else None
        exo, action = inp[0], inp[1]
        extra = list(inp[2:])
        f = extra.pop(0) if hf else None
        w = extra.pop(0) if hw else None
        k, sub = jax.random.split(k)
        if hw:
            state, metrics, ws = step(params, state, action, exo, sub,
                                      stochastic=stochastic, fault=f,
                                      workload=w, wl_state=ws)
        else:
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic, fault=f)
        return (state, k) + ((ws,) if hw else ()), metrics

    carry0 = (state0, key) + ((_wl_zero(params),) if hw else ())
    inps = (xs, actions) + ((faults,) if hf else ()) + (
        (workloads,) if hw else ())
    (final, *_), metrics = jax.lax.scan(body, carry0, inps, unroll=_UNROLL)
    return final, metrics


def rollout_summary(params: SimParams,
                    state0: ClusterState,
                    action_fn: ActionFn,
                    trace: ExogenousTrace,
                    key: jax.Array,
                    *,
                    stochastic: bool = False,
                    faults=None,
                    workloads=None):
    """Closed-loop rollout that reduces to episode KPIs *inside* the scan.

    :func:`rollout` materializes per-tick :class:`StepMetrics` stacked over
    the horizon — O(B·T·fields) HBM writes, which caps the fleet batch
    (B=32k × one day OOMs a v5e chip on metric stacking alone). This
    variant carries the summary sufficient statistics in the scan state
    and emits no per-tick output, so memory is O(B) regardless of horizon
    — the fleet-scoring path. Returns ``(final_state, EpisodeSummary)``
    identical (same keys, same dynamics) to
    ``summarize(params, rollout(...)[1])``. ``faults``/``workloads``:
    per :func:`rollout`.
    """
    from ccka_tpu.sim.metrics import SummaryAcc, finalize_summary

    xs = exo_steps(trace)
    steps = xs.is_peak.shape[0]
    t0 = jnp.arange(steps, dtype=jnp.int32)
    acc0 = SummaryAcc.zero()

    if faults is None and workloads is None:
        def body(carry, inp):
            state, k, acc = carry
            exo, t = inp
            k, sub = jax.random.split(k)
            action = action_fn(state, exo, t)
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic)
            return (state, k, acc.update(params, metrics)), None

        (final, _, acc), _ = jax.lax.scan(body, (state0, key, acc0),
                                          (xs, t0), unroll=_UNROLL)
        return final, finalize_summary(params, state0, final, acc, steps)

    hf, hw = faults is not None, workloads is not None

    def body(carry, inp):
        state, k, acc = carry[0], carry[1], carry[2]
        rest = list(carry[3:])
        last = rest.pop(0) if hf else None
        ws = rest.pop(0) if hw else None
        exo, t = inp[0], inp[1]
        extra = list(inp[2:])
        f = extra.pop(0) if hf else None
        w = extra.pop(0) if hw else None
        k, sub = jax.random.split(k)
        obs = observed_exo(last, exo, f.signal_stale) if hf else exo
        action = action_fn(state, obs, t)
        if hw:
            state, metrics, ws = step(params, state, action, exo, sub,
                                      stochastic=stochastic, fault=f,
                                      workload=w, wl_state=ws)
        else:
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic, fault=f)
        carry2 = (state, k, acc.update(params, metrics))
        carry2 += ((obs,) if hf else ()) + ((ws,) if hw else ())
        return carry2, None

    carry0 = (state0, key, acc0)
    if hf:
        carry0 += (jax.tree.map(lambda x: x[0], xs),)
    if hw:
        carry0 += (_wl_zero(params),)
    inps = (xs, t0) + ((faults,) if hf else ()) + (
        (workloads,) if hw else ())
    (final, _, acc, *_), _ = jax.lax.scan(body, carry0, inps,
                                          unroll=_UNROLL)
    return final, finalize_summary(params, state0, final, acc, steps)


def batched_rollout_summary(params: SimParams,
                            states0: ClusterState,
                            action_fn: ActionFn,
                            traces: ExogenousTrace,
                            keys: jax.Array,
                            *,
                            stochastic: bool = False,
                            faults=None,
                            workloads=None):
    """`vmap` of :func:`rollout_summary` — per-cluster KPI summaries for
    fleet batches too large to stack per-tick metrics for. ``faults``/
    ``workloads``: optional batched pytrees (leaves ``[B, T, ...]``,
    e.g. from `faults.unpack_fault_lanes` /
    `workloads.unpack_workload_lanes`)."""
    if faults is None and workloads is None:
        fn = jax.vmap(
            lambda s, tr, k: rollout_summary(params, s, action_fn, tr, k,
                                             stochastic=stochastic),
            in_axes=(0, 0, 0))
        return fn(states0, traces, keys)
    hf, hw = faults is not None, workloads is not None

    def one(s, tr, k, f, w):
        return rollout_summary(params, s, action_fn, tr, k,
                               stochastic=stochastic, faults=f,
                               workloads=w)

    fn = jax.vmap(one, in_axes=(0, 0, 0, 0 if hf else None,
                                0 if hw else None))
    return fn(states0, traces, keys, faults, workloads)


def batched_rollout(params: SimParams,
                    states0: ClusterState,
                    action_fn: ActionFn,
                    traces: ExogenousTrace,
                    keys: jax.Array,
                    *,
                    stochastic: bool = False) -> tuple[ClusterState, StepMetrics]:
    """`vmap` of :func:`rollout` over a leading cluster-batch axis.

    ``states0``/``traces``/``keys`` carry a leading batch dim B; params and
    the policy are shared. This is BASELINE.json config #3/#5: hundreds to
    10k clusters advanced in lockstep on one chip or a mesh.
    """
    fn = jax.vmap(
        lambda s, tr, k: rollout(params, s, action_fn, tr, k,
                                 stochastic=stochastic),
        in_axes=(0, 0, 0))
    return fn(states0, traces, keys)
