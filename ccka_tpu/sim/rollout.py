"""Rollouts: `lax.scan` over the horizon, `vmap` over the cluster batch.

This is the device-resident replacement for the reference's operational loop
(`demo_18 → demo_20|21 → demo_30 → demo_40`, `README.md:52-57`): instead of
one live cluster stepped by hand, thousands of simulated clusters advance a
full control horizon per XLA dispatch.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ccka_tpu.config import FrameworkConfig
from ccka_tpu.sim.dynamics import ExoStep, step
from ccka_tpu.sim.types import Action, ClusterState, N_CT, SimParams, StepMetrics
from ccka_tpu.signals.base import ExogenousTrace

# action_fn(state, exo_step, t_index) -> Action
ActionFn = Callable[[ClusterState, ExoStep, jnp.ndarray], Action]

# Scan unroll factor for the horizon loop: the per-step tensors are tiny
# ([B, P, Z, CT] and smaller), so per-iteration loop overhead dominates;
# unrolling 8 steps per scan iteration lets XLA fuse across ticks (~1.8x
# rollout throughput on v5e; flat beyond 8).
_UNROLL = 8


def _fresh_state(p: int, z: int, k: int) -> ClusterState:
    """The one fresh-cluster constructor `initial_state` and
    `zero_state` share — a change to the start-state invariant must not
    be able to diverge the config and registry-engine paths."""
    zero = jnp.float32(0.0)
    return ClusterState(
        nodes=jnp.zeros((p, z, N_CT), jnp.float32),
        pipeline=jnp.zeros((k, p, z, N_CT), jnp.float32),
        running=jnp.zeros((2,), jnp.float32),
        consol_timer_s=jnp.zeros((p,), jnp.float32),
        time_s=zero,
        acc_cost_usd=zero,
        acc_carbon_g=zero,
        acc_requests=zero,
        acc_slo_ok_s=zero,
        acc_evictions=zero,
    )


def initial_state(cfg: FrameworkConfig) -> ClusterState:
    """Fresh cluster: only the managed base nodegroup, nothing pending."""
    return _fresh_state(cfg.cluster.n_pools, cfg.cluster.n_zones,
                        cfg.sim.provision_delay_steps)


def exo_steps(trace: ExogenousTrace) -> ExoStep:
    """Repack a time-major trace as scan-consumable xs (leading axis = T)."""
    return ExoStep(
        spot_price_hr=trace.spot_price_hr,
        od_price_hr=trace.od_price_hr,
        carbon_g_kwh=trace.carbon_g_kwh,
        demand_pods=trace.demand_pods,
        is_peak=trace.is_peak,
    )


def observed_exo(last_obs: ExoStep, exo: ExoStep, stale) -> ExoStep:
    """Policy-observed signals under a possible outage (`ccka_tpu/faults`):
    prices/carbon/demand hold the last pre-outage values while ``stale``
    is set; ``is_peak`` is clock-derived and stays true. Dynamics always
    consume the true ``exo`` — only the decide's view goes stale (the
    same split the megakernel's fault mode implements in-register)."""
    hold = stale > 0.5
    return ExoStep(
        spot_price_hr=jnp.where(hold, last_obs.spot_price_hr,
                                exo.spot_price_hr),
        od_price_hr=jnp.where(hold, last_obs.od_price_hr, exo.od_price_hr),
        carbon_g_kwh=jnp.where(hold, last_obs.carbon_g_kwh,
                               exo.carbon_g_kwh),
        demand_pods=jnp.where(hold, last_obs.demand_pods, exo.demand_pods),
        is_peak=exo.is_peak,
    )


def _wl_zero(params: SimParams):
    """Fresh per-family queue state (ccka_tpu/workloads)."""
    from ccka_tpu.workloads.types import WorkloadState

    return WorkloadState.zero(int(params.wl_batch_deadline_ticks))


def rollout(params: SimParams,
            state0: ClusterState,
            action_fn: ActionFn,
            trace: ExogenousTrace,
            key: jax.Array,
            *,
            stochastic: bool = False,
            faults=None,
            workloads=None) -> tuple[ClusterState, StepMetrics]:
    """Scan the closed loop decide→act→step over the trace horizon.

    ``action_fn`` is the PolicyBackend's jittable decide(); it sees the
    current state and tick signals — exactly the observation surface the
    reference's operator has when choosing demo_20 vs demo_21.

    ``faults``: optional time-major :class:`ccka_tpu.faults.FaultStep`
    pytree (leaves ``[T, ...]``). When given, each tick's disturbances
    feed the dynamics and the policy observes STALE signals during
    outage windows (held at the last pre-outage tick; tick 0 observes
    its own fresh signals, matching the kernel's ``tglob > 0`` gate).

    ``workloads``: optional time-major
    :class:`ccka_tpu.workloads.WorkloadStep` pytree (leaves ``[T]``).
    When given, per-family queue state (zero-initialized) is carried
    through the scan and each tick's arrivals drain from the fleet's
    headroom (`sim/dynamics.step` workload path); policies do not
    observe the queues — families are tenant load the fleet's slack
    either absorbs or doesn't.

    ``None`` for both takes the exact pre-fault/pre-workload path — a
    Python-level branch, so existing rollouts stay bitwise identical.
    """
    xs = exo_steps(trace)
    t0 = jnp.arange(xs.is_peak.shape[0], dtype=jnp.int32)

    if faults is None and workloads is None:
        def body(carry, inp):
            state, k = carry
            exo, t = inp
            k, sub = jax.random.split(k)
            action = action_fn(state, exo, t)
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic)
            return (state, k), metrics

        (final, _), metrics = jax.lax.scan(body, (state0, key), (xs, t0),
                                           unroll=_UNROLL)
        return final, metrics

    hf, hw = faults is not None, workloads is not None

    def body(carry, inp):
        state, k = carry[0], carry[1]
        rest = list(carry[2:])
        last = rest.pop(0) if hf else None
        ws = rest.pop(0) if hw else None
        exo, t = inp[0], inp[1]
        extra = list(inp[2:])
        f = extra.pop(0) if hf else None
        w = extra.pop(0) if hw else None
        k, sub = jax.random.split(k)
        obs = observed_exo(last, exo, f.signal_stale) if hf else exo
        action = action_fn(state, obs, t)
        if hw:
            state, metrics, ws = step(params, state, action, exo, sub,
                                      stochastic=stochastic, fault=f,
                                      workload=w, wl_state=ws)
        else:
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic, fault=f)
        carry2 = (state, k) + ((obs,) if hf else ()) + ((ws,) if hw else ())
        return carry2, metrics

    carry0 = (state0, key)
    if hf:
        carry0 += (jax.tree.map(lambda x: x[0], xs),)
    if hw:
        carry0 += (_wl_zero(params),)
    inps = (xs, t0) + ((faults,) if hf else ()) + (
        (workloads,) if hw else ())
    (final, *_), metrics = jax.lax.scan(body, carry0, inps, unroll=_UNROLL)
    return final, metrics


def rollout_actions(params: SimParams,
                    state0: ClusterState,
                    actions: Action,
                    trace: ExogenousTrace,
                    key: jax.Array,
                    *,
                    stochastic: bool = False,
                    faults=None,
                    workloads=None) -> tuple[ClusterState, StepMetrics]:
    """Rollout under a precomputed action sequence (leading axis = T).

    This is the diff-MPC path: gradients flow from episode objectives back
    through `scan` into every action of the plan. ``faults``/
    ``workloads``: optional time-major pytrees — a plan observes
    nothing, so only the dynamics-side disturbances/queues apply (the
    playback kernel's contract).
    """
    xs = exo_steps(trace)

    if faults is None and workloads is None:
        def body(carry, inp):
            state, k = carry
            exo, action = inp
            k, sub = jax.random.split(k)
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic)
            return (state, k), metrics

        (final, _), metrics = jax.lax.scan(body, (state0, key),
                                           (xs, actions), unroll=_UNROLL)
        return final, metrics

    hf, hw = faults is not None, workloads is not None

    def body(carry, inp):
        state, k = carry[0], carry[1]
        ws = carry[2] if hw else None
        exo, action = inp[0], inp[1]
        extra = list(inp[2:])
        f = extra.pop(0) if hf else None
        w = extra.pop(0) if hw else None
        k, sub = jax.random.split(k)
        if hw:
            state, metrics, ws = step(params, state, action, exo, sub,
                                      stochastic=stochastic, fault=f,
                                      workload=w, wl_state=ws)
        else:
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic, fault=f)
        return (state, k) + ((ws,) if hw else ()), metrics

    carry0 = (state0, key) + ((_wl_zero(params),) if hw else ())
    inps = (xs, actions) + ((faults,) if hf else ()) + (
        (workloads,) if hw else ())
    (final, *_), metrics = jax.lax.scan(body, carry0, inps, unroll=_UNROLL)
    return final, metrics


def rollout_summary(params: SimParams,
                    state0: ClusterState,
                    action_fn: ActionFn,
                    trace: ExogenousTrace,
                    key: jax.Array,
                    *,
                    stochastic: bool = False,
                    faults=None,
                    workloads=None):
    """Closed-loop rollout that reduces to episode KPIs *inside* the scan.

    :func:`rollout` materializes per-tick :class:`StepMetrics` stacked over
    the horizon — O(B·T·fields) HBM writes, which caps the fleet batch
    (B=32k × one day OOMs a v5e chip on metric stacking alone). This
    variant carries the summary sufficient statistics in the scan state
    and emits no per-tick output, so memory is O(B) regardless of horizon
    — the fleet-scoring path. Returns ``(final_state, EpisodeSummary)``
    identical (same keys, same dynamics) to
    ``summarize(params, rollout(...)[1])``. ``faults``/``workloads``:
    per :func:`rollout`.
    """
    from ccka_tpu.sim.metrics import SummaryAcc, finalize_summary

    xs = exo_steps(trace)
    steps = xs.is_peak.shape[0]
    t0 = jnp.arange(steps, dtype=jnp.int32)
    acc0 = SummaryAcc.zero()

    if faults is None and workloads is None:
        def body(carry, inp):
            state, k, acc = carry
            exo, t = inp
            k, sub = jax.random.split(k)
            action = action_fn(state, exo, t)
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic)
            return (state, k, acc.update(params, metrics)), None

        (final, _, acc), _ = jax.lax.scan(body, (state0, key, acc0),
                                          (xs, t0), unroll=_UNROLL)
        return final, finalize_summary(params, state0, final, acc, steps)

    hf, hw = faults is not None, workloads is not None

    def body(carry, inp):
        state, k, acc = carry[0], carry[1], carry[2]
        rest = list(carry[3:])
        last = rest.pop(0) if hf else None
        ws = rest.pop(0) if hw else None
        exo, t = inp[0], inp[1]
        extra = list(inp[2:])
        f = extra.pop(0) if hf else None
        w = extra.pop(0) if hw else None
        k, sub = jax.random.split(k)
        obs = observed_exo(last, exo, f.signal_stale) if hf else exo
        action = action_fn(state, obs, t)
        if hw:
            state, metrics, ws = step(params, state, action, exo, sub,
                                      stochastic=stochastic, fault=f,
                                      workload=w, wl_state=ws)
        else:
            state, metrics = step(params, state, action, exo, sub,
                                  stochastic=stochastic, fault=f)
        carry2 = (state, k, acc.update(params, metrics))
        carry2 += ((obs,) if hf else ()) + ((ws,) if hw else ())
        return carry2, None

    carry0 = (state0, key, acc0)
    if hf:
        carry0 += (jax.tree.map(lambda x: x[0], xs),)
    if hw:
        carry0 += (_wl_zero(params),)
    inps = (xs, t0) + ((faults,) if hf else ()) + (
        (workloads,) if hw else ())
    (final, _, acc, *_), _ = jax.lax.scan(body, carry0, inps,
                                          unroll=_UNROLL)
    return final, finalize_summary(params, state0, final, acc, steps)


def batched_rollout_summary(params: SimParams,
                            states0: ClusterState,
                            action_fn: ActionFn,
                            traces: ExogenousTrace,
                            keys: jax.Array,
                            *,
                            stochastic: bool = False,
                            faults=None,
                            workloads=None):
    """`vmap` of :func:`rollout_summary` — per-cluster KPI summaries for
    fleet batches too large to stack per-tick metrics for. ``faults``/
    ``workloads``: optional batched pytrees (leaves ``[B, T, ...]``,
    e.g. from `faults.unpack_fault_lanes` /
    `workloads.unpack_workload_lanes`)."""
    if faults is None and workloads is None:
        fn = jax.vmap(
            lambda s, tr, k: rollout_summary(params, s, action_fn, tr, k,
                                             stochastic=stochastic),
            in_axes=(0, 0, 0))
        return fn(states0, traces, keys)
    hf, hw = faults is not None, workloads is not None

    def one(s, tr, k, f, w):
        return rollout_summary(params, s, action_fn, tr, k,
                               stochastic=stochastic, faults=f,
                               workloads=w)

    fn = jax.vmap(one, in_axes=(0, 0, 0, 0 if hf else None,
                                0 if hw else None))
    return fn(states0, traces, keys, faults, workloads)


def batched_rollout(params: SimParams,
                    states0: ClusterState,
                    action_fn: ActionFn,
                    traces: ExogenousTrace,
                    keys: jax.Array,
                    *,
                    stochastic: bool = False) -> tuple[ClusterState, StepMetrics]:
    """`vmap` of :func:`rollout` over a leading cluster-batch axis.

    ``states0``/``traces``/``keys`` carry a leading batch dim B; params and
    the policy are shared. This is BASELINE.json config #3/#5: hundreds to
    10k clusters advanced in lockstep on one chip or a mesh.
    """
    fn = jax.vmap(
        lambda s, tr, k: rollout(params, s, action_fn, tr, k,
                                 stochastic=stochastic),
        in_axes=(0, 0, 0))
    return fn(states0, traces, keys)


# ---- the unified LAX reference engine (ISSUE 14: the mode registry) -------
#
# One lax-path engine per registered packed policy mode, consuming the
# SAME ``[T_pad, rows, B]`` packed stream the kernels consume: the lane
# layout resolves through the `sim/lanes.py` registry, fault/workload
# lane blocks unpack into the pytrees `rollout_summary` already
# threads, and any further registered (passive) lane families ride the
# stream untouched — so a new lane family reaches this engine with zero
# edits here (the registry contract test pins it). This is the
# reference implementation the kernel parity suite pins the megakernel
# against, now reachable through the one mode vocabulary
# (`lax_mode_summary`), and the distillation factory's "naive lax"
# baseline engine.


def zero_state(params: SimParams, cluster) -> ClusterState:
    """`initial_state` from (params, cluster) — the registry engines
    carry SimParams + ClusterConfig, not a full FrameworkConfig."""
    return _fresh_state(cluster.n_pools, cluster.n_zones,
                        int(params.provision_pipeline_k))


def lax_summary_from_packed(params: SimParams, cluster, stream, T: int,
                            key, *, action_fn=None, plan_latents=None,
                            stochastic: bool = False):
    """EpisodeSummary batch for a packed stream on the LAX path — the
    shared body of every registered mode's ``lax_summary`` engine.

    Exactly one of ``action_fn`` (a shared jittable decide) or
    ``plan_latents`` (``[B, T, A]`` per-cluster latent plans, decoded
    and executed tick-for-tick — the playback kernel's contract: a plan
    observes nothing) must be given. Pays the unpack transposes the
    packed pipeline exists to skip — this is the reference/labeling
    engine, never the hot path.
    """
    from ccka_tpu.models import latent_to_action
    from ccka_tpu.sim.megakernel import unpack_exo

    if (action_fn is None) == (plan_latents is None):
        raise ValueError("lax_summary_from_packed: pass exactly one of "
                         "action_fn or plan_latents")
    Z = cluster.n_zones
    lay = lanes.resolve_layout(int(stream.shape[1]), Z)
    traces = unpack_exo(stream, T, Z)
    faults = None
    workloads = None
    if lay.has("faults"):
        from ccka_tpu.faults.process import unpack_fault_lanes

        faults = unpack_fault_lanes(stream, T, Z)
    if lay.has("workloads"):
        from ccka_tpu.workloads.process import unpack_workload_lanes

        workloads = unpack_workload_lanes(stream, T, Z)
    B = int(traces.is_peak.shape[0])
    states0 = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (B,) + x.shape),
        zero_state(params, cluster))
    keys = jax.random.split(key, B)
    if action_fn is not None:
        return batched_rollout_summary(
            params, states0, action_fn, traces, keys,
            stochastic=stochastic, faults=faults, workloads=workloads)[1]

    def one(s, tr, k, pl, f, w):
        def plan_action(_state, _exo, t):
            # Tick t of THIS cluster's plan — content-identical to
            # `rollout_actions` (a plan observes nothing, so the
            # faulted observation path is a no-op through it).
            return latent_to_action(jnp.take(pl, t, axis=0), cluster)

        return rollout_summary(params, s, plan_action, tr, k,
                               stochastic=stochastic, faults=f,
                               workloads=w)[1]

    hf, hw = faults is not None, workloads is not None
    fn = jax.vmap(one, in_axes=(0, 0, 0, 0, 0 if hf else None,
                                0 if hw else None))
    return fn(states0, traces, keys, plan_latents, faults, workloads)


def lax_mode_summary(params: SimParams, cluster, mode: str, stream,
                     T: int, key, *, stochastic: bool = False,
                     net_params=None, plan_latents=None):
    """Registry dispatcher: the lax reference engine of a registered
    packed policy mode (`sim/lanes.py`; unknown modes rejected with the
    registered vocabulary). ``net_params`` (mode "neural"): a SINGLE
    ActorCritic pytree (no population axis — the lax reference scores
    one policy). ``plan_latents`` (mode "plan"): ``[B, T, A]``."""
    engine = lanes.mode_engine(mode, "lax_summary")
    return engine(params, cluster, stream, T, key, stochastic=stochastic,
                  net_params=net_params, plan_latents=plan_latents)


def _rule_lax_summary(params, cluster, stream, T, key, *,
                      stochastic=False, net_params=None,
                      plan_latents=None):
    from ccka_tpu.policy.rule import RulePolicy

    return lax_summary_from_packed(
        params, cluster, stream, T, key, stochastic=stochastic,
        action_fn=RulePolicy(cluster).action_fn())


def _carbon_lax_summary(params, cluster, stream, T, key, *,
                        stochastic=False, net_params=None,
                        plan_latents=None):
    from ccka_tpu.policy.carbon import CarbonAwarePolicy

    return lax_summary_from_packed(
        params, cluster, stream, T, key, stochastic=stochastic,
        action_fn=CarbonAwarePolicy(cluster).action_fn())


def _neural_lax_summary(params, cluster, stream, T, key, *,
                        stochastic=False, net_params=None,
                        plan_latents=None):
    if net_params is None:
        raise ValueError("lax_mode_summary: mode 'neural' needs "
                         "net_params (a single ActorCritic pytree)")
    from ccka_tpu.models import ActorCritic, latent_dim, latent_to_action
    from ccka_tpu.policy.base import observe

    net = ActorCritic(act_dim=latent_dim(cluster))

    def action_fn(state, exo, t):
        # PPOBackend.decide's deterministic forward (train/ppo.py).
        obs = observe(params, state, exo).flatten()
        mean, _, _ = net.apply(net_params, obs)
        return latent_to_action(mean, cluster)

    return lax_summary_from_packed(
        params, cluster, stream, T, key, stochastic=stochastic,
        action_fn=action_fn)


def _plan_lax_summary(params, cluster, stream, T, key, *,
                      stochastic=False, net_params=None,
                      plan_latents=None):
    if plan_latents is None:
        raise ValueError("lax_mode_summary: mode 'plan' needs "
                         "plan_latents [B, T, A]")
    return lax_summary_from_packed(
        params, cluster, stream, T, key, stochastic=stochastic,
        plan_latents=plan_latents)


from ccka_tpu.sim import lanes  # noqa: E402

for _m, _fn in (("rule", _rule_lax_summary),
                ("carbon", _carbon_lax_summary),
                ("neural", _neural_lax_summary),
                ("plan", _plan_lax_summary)):
    lanes.provide_mode_engine(_m, "lax_summary", _fn)
del _m, _fn
