"""Batched, differentiable cluster simulator (JAX).

The reference's "hot loop" is not in its scripts at all — it is Karpenter's
reconcile loop reacting to Pending pods and consolidating per NodePool
disruption policy, plus the kube-scheduler placing pods and the 30s metrics
scrape (`SURVEY.md` §3.3). This package models that loop as a pure function

    step(params, state, action, exogenous, key) -> (state', metrics)

over flat feature tensors, so that:

- `vmap` batches thousands of independent clusters (BASELINE.json config #3/#5),
- `lax.scan` runs the control horizon on-device with no host round-trips,
- `jax.grad` differentiates episode cost/carbon/SLO w.r.t. actions (diff-MPC),
- `pjit`/`shard_map` shard the cluster batch over a TPU mesh.

Modeled dynamics (all branch-free, static shapes):
- pod scheduling against capacity-type capacity (nodeSelector semantics of
  `demo_30_burst_configure.sh:104-106`),
- Karpenter-style provisioning with a delay pipeline, weighted by zone/
  capacity-type requirements (`demo_20_offpeak_configure.sh:69-79`) and spot
  pricing (Karpenter's cheapest-fit),
- consolidation per `{WhenEmpty | WhenEmptyOrUnderutilized, consolidateAfter}`
  (`demo_20_offpeak_configure.sh:59-60`, `demo_21_peak_configure.sh:56-57`)
  with PDB eviction budget (`demo_10_setup_configure.sh:46-57`),
- spot interruptions as a first-class stochastic process — the thing the
  reference explicitly disabled (`05_karpenter.sh:136`),
- cost and carbon accounting per node-step, and an SLO/latency proxy.
"""

from ccka_tpu.sim.types import (  # noqa: F401
    Action,
    ClusterState,
    SimParams,
    StepMetrics,
    CT_SPOT,
    CT_OD,
)
from ccka_tpu.sim.dynamics import step  # noqa: F401
from ccka_tpu.sim.rollout import (  # noqa: F401
    batched_rollout,
    batched_rollout_summary,
    initial_state,
    rollout,
    rollout_actions,
    rollout_summary,
)
from ccka_tpu.sim.metrics import (  # noqa: F401
    EpisodeSummary,
    finalize_summary,
    summarize,
)
