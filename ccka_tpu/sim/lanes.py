"""Packed exo-stream lane-layout arithmetic — the ONE layout module.

Every consumer of the packed ``[T_pad, rows, B]`` exo stream (the
megakernel's entry points, the fault and workload lane synthesizers,
the sharded wrappers, bench's roofline byte counts) keys off the same
row arithmetic: the base exo block, the optional fault block appended
after it, and the optional workload block appended after that. This
module is the neutral home for that arithmetic so the subsystems import
it DOWNWARD — `faults/` and `workloads/` both depend on it, never on
each other (earlier drafts had `faults.has_fault_lanes` reach up into
`workloads.process` for the resolver and everyone lazy-importing
`megakernel._exo_rows`, inverting or tangling the layering). It imports
nothing but the stdlib, so it can never join a cycle.

Block sizes (all padded to the f32 sublane multiple of 8):

    exo_rows(Z)       3Z+3 signal rows (ARCHITECTURE §6)
    fault_rows(Z)     hazard[Z] + deny + delay + stale   (§12)
    workload_rows(Z)  3 family-arrival rows, sized fault_rows(Z)+8 so
                      the four layouts below stay mutually
                      distinguishable for ANY zone count (§13)

Layout detection is purely row-count-based (`stream_layout`): a stream
has exactly ``exo_rows(Z)`` rows (plain), ``+fault_rows`` (+faults),
``+workload_rows`` (+workloads) or ``+both`` — anything else is
rejected outright, because a half-widened stream would silently misread
lanes as padding. ROADMAP item 5's unified rollout-engine refactor
grows this module into the full packed-stream layout registry.
"""

from __future__ import annotations

import math


def exo_rows(Z: int) -> int:
    """Rows of the base exo-signal block: spot[Z] + od[Z] + carbon[Z] +
    demand + is_peak + pad, padded to a sublane multiple."""
    return math.ceil((3 * Z + 3) / 8) * 8


def fault_rows(Z: int) -> int:
    """Rows of the fault lane block: hazard[Z] + deny + delay + stale,
    padded to a sublane multiple (mirrors :func:`exo_rows`)."""
    return math.ceil((Z + 3) / 8) * 8


def workload_rows(Z: int) -> int:
    """Rows of the workload lane block. Sized ``fault_rows(Z) + 8`` (not
    the minimal sublane multiple) so row-count layout detection stays
    unambiguous — see the module docstring."""
    return fault_rows(Z) + 8


def stream_layout(rows: int, Z: int) -> tuple[bool, bool]:
    """``(has_faults, has_workloads)`` of a packed stream, inferred from
    its row count — the zero-API-churn detection every kernel entry
    point uses. Rejects any other row count outright (a half-widened
    stream would silently misread lanes as padding)."""
    base, f, w = exo_rows(Z), fault_rows(Z), workload_rows(Z)
    layouts = {base: (False, False),
               base + f: (True, False),
               base + w: (False, True),
               base + f + w: (True, True)}
    got = layouts.get(int(rows))
    if got is None:
        raise ValueError(
            f"packed stream has {rows} rows; this topology (Z={Z}) "
            f"expects {base} (plain), {base + f} (+faults), {base + w} "
            f"(+workloads) or {base + f + w} (+both)")
    return got


def workload_base(rows: int, Z: int) -> int:
    """Row offset of the workload block inside a widened stream (after
    the fault block when one is present)."""
    has_faults, has_wl = stream_layout(rows, Z)
    if not has_wl:
        raise ValueError("stream carries no workload lanes")
    return exo_rows(Z) + (fault_rows(Z) if has_faults else 0)
