"""Packed exo-stream lane-layout arithmetic — the ONE layout module.

Every consumer of the packed ``[T_pad, rows, B]`` exo stream (the
megakernel's entry points, the fault and workload lane synthesizers,
the sharded wrappers, bench's roofline byte counts) keys off the same
row arithmetic: the base exo block, the optional fault block appended
after it, and the optional workload block appended after that. This
module is the neutral home for that arithmetic so the subsystems import
it DOWNWARD — `faults/` and `workloads/` both depend on it, never on
each other (earlier drafts had `faults.has_fault_lanes` reach up into
`workloads.process` for the resolver and everyone lazy-importing
`megakernel._exo_rows`, inverting or tangling the layering). It imports
nothing but the stdlib, so it can never join a cycle.

Block sizes (all padded to the f32 sublane multiple of 8):

    exo_rows(Z)       3Z+3 signal rows (ARCHITECTURE §6)
    fault_rows(Z)     hazard[Z] + deny + delay + stale   (§12)
    workload_rows(Z)  3 family-arrival rows, sized fault_rows(Z)+8 so
                      the four layouts below stay mutually
                      distinguishable for ANY zone count (§13)

Layout detection is purely row-count-based (`stream_layout`): a stream
has exactly ``exo_rows(Z)`` rows (plain), ``+fault_rows`` (+faults),
``+workload_rows`` (+workloads) or ``+both`` — anything else is
rejected outright, because a half-widened stream would silently misread
lanes as padding. ROADMAP item 5's unified rollout-engine refactor
grows this module into the full packed-stream layout registry.
"""

from __future__ import annotations

import math


def exo_rows(Z: int) -> int:
    """Rows of the base exo-signal block: spot[Z] + od[Z] + carbon[Z] +
    demand + is_peak + pad, padded to a sublane multiple."""
    return math.ceil((3 * Z + 3) / 8) * 8


def fault_rows(Z: int) -> int:
    """Rows of the fault lane block: hazard[Z] + deny + delay + stale,
    padded to a sublane multiple (mirrors :func:`exo_rows`)."""
    return math.ceil((Z + 3) / 8) * 8


def workload_rows(Z: int) -> int:
    """Rows of the workload lane block. Sized ``fault_rows(Z) + 8`` (not
    the minimal sublane multiple) so row-count layout detection stays
    unambiguous — see the module docstring."""
    return fault_rows(Z) + 8


def stream_layout(rows: int, Z: int) -> tuple[bool, bool]:
    """``(has_faults, has_workloads)`` of a packed stream, inferred from
    its row count — the zero-API-churn detection every kernel entry
    point uses. Rejects any other row count outright (a half-widened
    stream would silently misread lanes as padding)."""
    base, f, w = exo_rows(Z), fault_rows(Z), workload_rows(Z)
    layouts = {base: (False, False),
               base + f: (True, False),
               base + w: (False, True),
               base + f + w: (True, True)}
    got = layouts.get(int(rows))
    if got is None:
        raise ValueError(
            f"packed stream has {rows} rows; this topology (Z={Z}) "
            f"expects {base} (plain), {base + f} (+faults), {base + w} "
            f"(+workloads) or {base + f + w} (+both)")
    return got


def workload_base(rows: int, Z: int) -> int:
    """Row offset of the workload block inside a widened stream (after
    the fault block when one is present)."""
    has_faults, has_wl = stream_layout(rows, Z)
    if not has_wl:
        raise ValueError("stream carries no workload lanes")
    return exo_rows(Z) + (fault_rows(Z) if has_faults else 0)


# ---- time-axis block layout (ISSUE 13: the streaming pipeline) ------------
#
# The streaming rollout engine (`sim/streaming.py`) splits the packed
# stream's TIME axis into fixed blocks so generation of block k+1 can
# overlap kernel consumption of block k. The arithmetic lives here for
# the same reason the row arithmetic does: the generators (`signals/`),
# the kernel's carried-state entries (`sim/megakernel.py`), the sharded
# wrappers and bench's memory-bound bookkeeping must all agree on block
# boundaries, and a half-agreed split would silently misalign lanes.
# Per-block worlds are keyed ``fold_in(fold_in(key, BLOCK_KEY_TAG), j)``
# — the folding itself lives with the jax-importing generators, but the
# tag is declared here so every backend folds the SAME stream family.
# Fault/workload lanes then key off the BLOCK key exactly as they key
# off the whole-stream key today (fold_in(FAULT/WORKLOAD_KEY_TAG)), so
# widening a blocked stream with lanes changes neither the exo nor the
# fault rows bitwise — per block, the same invariant the unblocked
# layouts pin.

BLOCK_KEY_TAG = 0x5B10C  # per-block world fold tag (see above)


def block_layout(T: int, block_T: int, t_chunk: int) -> tuple[int, int]:
    """``(n_blocks, T_pad)`` of a time-blocked stream covering ``T``
    true ticks in fixed ``block_T``-tick blocks of ``t_chunk``-sized
    kernel chunks. Rejects any split the kernel grid cannot honor:
    a block must be a whole number of time chunks, and the padded
    horizon must be a whole number of blocks (a ragged tail block would
    need its own compiled program AND its own buffer shape — the
    double-buffer holds exactly two same-shape blocks per chip)."""
    if block_T <= 0 or t_chunk <= 0:
        raise ValueError(f"block_T={block_T} / t_chunk={t_chunk} must "
                         "be positive")
    if block_T % t_chunk:
        raise ValueError(
            f"block_T={block_T} is not a t_chunk={t_chunk} multiple — "
            "the kernel grid advances whole time chunks")
    T_pad = math.ceil(T / t_chunk) * t_chunk
    if T_pad % block_T:
        raise ValueError(
            f"block_T={block_T} does not divide the padded horizon "
            f"T_pad={T_pad} (T={T}, t_chunk={t_chunk}) — streaming "
            "blocks must tile the horizon exactly")
    return T_pad // block_T, T_pad


def chunk_layout(batch: int, chunk: int) -> int:
    """Number of cluster-axis chunks when a ``batch``-wide fleet streams
    through the mesh ``chunk`` clusters at a time (bench's 10^4–10^5
    rows). Rejects a chunk that does not tile the batch — a ragged tail
    chunk would silently change the per-launch geometry mid-sweep."""
    if chunk <= 0:
        raise ValueError(f"cluster chunk={chunk} must be positive")
    if batch % chunk:
        raise ValueError(
            f"cluster chunk={chunk} does not divide batch={batch} — "
            "cluster-axis chunking needs equal-width chunks")
    return batch // chunk


def block_bytes(block_T: int, rows: int, batch: int) -> int:
    """f32 bytes of ONE stream block — the unit of the streaming
    pipeline's memory bound (2 blocks x lanes x chunk live per chip)."""
    return 4 * block_T * rows * batch
