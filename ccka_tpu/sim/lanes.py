"""Packed exo-stream layout + rollout-engine registry — the ONE registry.

Every consumer of the packed ``[T_pad, rows, B]`` exo stream (the
megakernel's entry points, the fault and workload lane synthesizers,
the sharded wrappers, bench's roofline byte counts) keys off the same
row arithmetic: the base exo block, then one optional row block per
REGISTERED LANE FAMILY, appended in registration order. This module is
the neutral home for that arithmetic so the subsystems import it
DOWNWARD — `faults/` and `workloads/` both depend on it, never on each
other (earlier drafts had `faults.has_fault_lanes` reach up into
`workloads.process` for the resolver and everyone lazy-importing
`megakernel._exo_rows`, inverting or tangling the layering). At module
level it imports nothing but the stdlib, so it can never join a cycle;
the engine resolvers below lazily import their provider modules at
CALL time only.

ISSUE 14 grew this module from row arithmetic into the full registry
the ROADMAP item 2 refactor promised, with two registries:

- **Lane families** (:func:`register_lane_family`): a family is a named
  row block (``rows(Z)``), a PRNG key tag, and an optional synthesis
  closure (registered by the jax-importing provider module,
  :func:`provide_lane_generator`). The base exo block plus the present
  families' blocks must resolve UNIQUELY from the total row count
  (:func:`resolve_layout`) — registration rejects any family whose row
  arithmetic would make two different lane combinations collide for any
  plausible zone count, because a half-resolved stream would silently
  misread lanes as padding. Adding a lane family here is the ONLY edit:
  the synthetic source synthesizes registered families generically, the
  layout resolver accepts the widened stream, and every engine (lax,
  all four kernel modes, streaming, the sharded wrappers) consumes it
  with zero per-engine edits (`tests/test_engine_registry.py` pins
  this contract with a test-only family).

- **Policy modes** (:func:`register_mode`): a mode is a named bundle of
  engine closures — the fused packed entry (``packed_summary``), the
  carried-state streaming bundle (``block_summary``), the mesh variant
  (``sharded_block_summary``) and the lax reference engine
  (``lax_summary``). `sim/megakernel.py` registers the four kernel
  modes {rule, carbon, neural, plan} and dispatches its own
  ``packed_mode_summary_fn`` / ``packed_mode_block_summary_fn``
  through here; `sim/rollout.py` provides the lax engines;
  `parallel/sharded_kernel.py` provides the mesh engines;
  `sim/streaming.py` consumes whichever the mesh argument selects. A
  new policy mode is ONE registration, not five edits — the
  quintuplication tax (kernel out rows grew 14→16→21 across rounds
  10–11, each a five-site thread-through) ends here.

Block sizes (all padded to the f32 sublane multiple of 8):

    exo_rows(Z)       3Z+3 signal rows (ARCHITECTURE §6)
    fault_rows(Z)     hazard[Z] + deny + delay + stale   (§12)
    workload_rows(Z)  3 family-arrival rows, sized fault_rows(Z)+8 so
                      the layouts stay mutually distinguishable for ANY
                      zone count (§13)
"""

from __future__ import annotations

import importlib
import itertools
import math


def exo_rows(Z: int) -> int:
    """Rows of the base exo-signal block: spot[Z] + od[Z] + carbon[Z] +
    demand + is_peak + pad, padded to a sublane multiple."""
    return math.ceil((3 * Z + 3) / 8) * 8


def fault_rows(Z: int) -> int:
    """Rows of the fault lane block: hazard[Z] + deny + delay + stale,
    padded to a sublane multiple (mirrors :func:`exo_rows`)."""
    return math.ceil((Z + 3) / 8) * 8


def workload_rows(Z: int) -> int:
    """Rows of the workload lane block. Sized ``fault_rows(Z) + 8`` (not
    the minimal sublane multiple) so row-count layout detection stays
    unambiguous — see the module docstring."""
    return fault_rows(Z) + 8


def region_rows(Z: int) -> int:
    """Rows of the per-region geo lane block (`ccka_tpu/regions`,
    ISSUE 16): six Z-row sub-blocks (price deviation, carbon deviation,
    migratable capacity, and the three migratable-family arrival rows,
    each broadcast region→zone). Sized ``4*fault_rows(Z) + 32`` —
    strictly greater than the SUM of every other registrable block
    (faults + workloads + the test family total ``4*fault_rows(Z)+24``),
    so any subset containing this family out-counts any subset without
    it and row-count layout detection stays unambiguous at every zone
    count, even while the registry test's throwaway family is live."""
    return 4 * fault_rows(Z) + 32


# ---- lane-family registry -------------------------------------------------

# Zone counts the ambiguity check sweeps at registration time: every
# preset topology (default Z=3, multiregion Z=4) plus the plausible
# spread a scenario suite could configure. A family whose rows collide
# with another combination at ANY of these is rejected up front.
_AMBIGUITY_ZS = (1, 2, 3, 4, 5, 6, 8)


class LaneFamily:
    """One registered packed-stream lane family (see module docstring).

    ``generate(config, key, steps, t_pad, z, batch, *, ctx)`` is the
    synthesis closure — registered separately by the family's
    jax-importing provider module (:func:`provide_lane_generator`) so
    this module stays import-light. ``ctx`` carries the generation
    context the built-in families need (``price_dev`` AR(1) spot noise,
    ``dt_s``, ``start_unix_s``, optional ``start_offset_s``); closures
    take what they use. The closure receives the UNFOLDED stream key
    and owns its tag fold — exactly how `faults.packed_fault_lanes` /
    `workloads.packed_workload_lanes` always keyed, so registering them
    here changed no bits.

    ``generate_p(config, derived, key, steps, t_pad, z, batch, *, ctx)``
    is the optional TRACED-PARAMETER synthesis closure (ISSUE 19): the
    same lane block, but with the family's searchable knobs arriving as
    ``derived`` — a dict of (possibly traced, possibly vmapped) f32
    scalars precomputed host-side by `search/params.ScenarioParams.
    derived()` — instead of baked Python constants. A family that
    registers one rides the batched scenario-parameter axis
    (`search/axis.ScenarioAxisSource`) with zero per-engine edits; a
    family without one is synthesized by its plain closure, constant
    across the S axis.
    """

    __slots__ = ("name", "rows", "key_tag", "provider", "generate",
                 "generate_p")

    def __init__(self, name, rows, key_tag, provider=None):
        self.name = name
        self.rows = rows
        self.key_tag = key_tag
        self.provider = provider
        self.generate = None
        self.generate_p = None


LANE_FAMILIES: dict[str, LaneFamily] = {}


def lane_families() -> tuple[LaneFamily, ...]:
    """Registered families in registration order — the packed stream's
    block order after the base exo block."""
    return tuple(LANE_FAMILIES.values())


def _subset_sums(families, Z: int) -> dict[int, tuple[str, ...]]:
    """{total rows: family-name subset} over all present/absent
    combinations of ``families`` at zone count ``Z``."""
    base = exo_rows(Z)
    sums: dict[int, tuple[str, ...]] = {}
    for r in range(len(families) + 1):
        for combo in itertools.combinations(families, r):
            total = base + sum(f.rows(Z) for f in combo)
            if total in sums:
                raise ValueError(
                    f"ambiguous lane layout at Z={Z}: families "
                    f"{tuple(f.name for f in combo)} and "
                    f"{sums[total]} both total {total} rows — a stream "
                    "could not be resolved from its row count")
            sums[total] = tuple(f.name for f in combo)
    return sums


def register_lane_family(name: str, *, rows, key_tag: int,
                         provider: str | None = None) -> LaneFamily:
    """Register a packed-stream lane family. ``rows`` is a
    ``Z -> row count`` callable; ``key_tag`` the family's PRNG fold tag
    (must be unique — two families folding the same tag would draw
    correlated lanes); ``provider`` an optional dotted module path
    imported lazily when the family's generator is first needed.
    Rejects duplicates and any registration that would make row-count
    layout resolution ambiguous (checked across ``_AMBIGUITY_ZS``)."""
    if name in LANE_FAMILIES:
        raise ValueError(f"lane family {name!r} already registered")
    tags = {f.key_tag for f in LANE_FAMILIES.values()}
    if key_tag in tags:
        raise ValueError(f"lane family {name!r}: key tag {key_tag:#x} "
                         "already registered to another family")
    fam = LaneFamily(name, rows, key_tag, provider)
    trial = list(LANE_FAMILIES.values()) + [fam]
    for z in _AMBIGUITY_ZS:
        _subset_sums(trial, z)   # raises on a collision
    LANE_FAMILIES[name] = fam
    return fam


def unregister_lane_family(name: str) -> None:
    """Remove a registered family — TEST plumbing only (the registry
    contract test registers a throwaway family and must leave the
    process-global registry exactly as it found it)."""
    LANE_FAMILIES.pop(name, None)


def provide_lane_generator(name: str, generate) -> None:
    """Attach the synthesis closure to a registered family (called by
    the family's jax-importing provider module at import time).
    Re-providing a filled slot is rejected — two modules silently
    fighting over one family's generator is a bug (the
    `provide_mode_engine` rule); re-register the family to replace it."""
    if name not in LANE_FAMILIES:
        raise ValueError(f"unknown lane family {name!r}; registered: "
                         f"{sorted(LANE_FAMILIES)}")
    fam = LANE_FAMILIES[name]
    if fam.generate is not None and fam.generate is not generate:
        raise ValueError(f"lane family {name!r} already has a "
                         "generator; unregister + re-register the "
                         "family to replace it")
    fam.generate = generate


def lane_generator(name: str):
    """The family's synthesis closure, importing its provider module on
    first use (the registry itself never imports jax)."""
    fam = LANE_FAMILIES.get(name)
    if fam is None:
        raise ValueError(f"unknown lane family {name!r}; registered: "
                         f"{sorted(LANE_FAMILIES)}")
    if fam.generate is None and fam.provider:
        importlib.import_module(fam.provider)
    if fam.generate is None:
        raise ValueError(f"lane family {name!r} has no registered "
                         "generator (provide_lane_generator)")
    return fam.generate


def provide_lane_param_generator(name: str, generate_p) -> None:
    """Attach the TRACED-PARAMETER synthesis closure to a registered
    family (see :class:`LaneFamily`). Same discipline as
    :func:`provide_lane_generator`: called by the family's jax-importing
    provider module at import time, and re-providing a filled slot is
    rejected — two modules silently fighting over one family's traced
    core is a bug."""
    if name not in LANE_FAMILIES:
        raise ValueError(f"unknown lane family {name!r}; registered: "
                         f"{sorted(LANE_FAMILIES)}")
    fam = LANE_FAMILIES[name]
    if fam.generate_p is not None and fam.generate_p is not generate_p:
        raise ValueError(f"lane family {name!r} already has a "
                         "param generator; unregister + re-register "
                         "the family to replace it")
    fam.generate_p = generate_p


def lane_param_generator(name: str):
    """The family's traced-parameter synthesis closure, importing its
    provider module on first use. Returns ``None`` (rather than
    raising) when the family registers no param generator — the
    scenario-axis source falls back to the plain closure, synthesizing
    that family constant across the S axis. Unknown family names are
    still rejected up front."""
    fam = LANE_FAMILIES.get(name)
    if fam is None:
        raise ValueError(f"unknown lane family {name!r}; registered: "
                         f"{sorted(LANE_FAMILIES)}")
    if fam.generate_p is None and fam.provider:
        importlib.import_module(fam.provider)
    return fam.generate_p


# The built-in families. Their tags are canonical HERE; the process
# modules re-export them (`faults.process.FAULT_KEY_TAG` /
# `workloads.process.WORKLOAD_KEY_TAG` /
# `regions.process.REGION_KEY_TAG`) and register the generators.
register_lane_family("faults", rows=fault_rows, key_tag=0xFA117,
                     provider="ccka_tpu.faults.process")
register_lane_family("workloads", rows=workload_rows, key_tag=0x301AD,
                     provider="ccka_tpu.workloads.process")
register_lane_family("regions", rows=region_rows, key_tag=0x6E0,
                     provider="ccka_tpu.regions.process")


class StreamLayout:
    """The resolved lane layout of one packed stream: which registered
    families are present and the row offsets of each block."""

    __slots__ = ("Z", "rows", "families", "offsets")

    def __init__(self, Z, rows, families, offsets):
        self.Z = Z
        self.rows = rows
        self.families = families   # tuple of present family names
        self.offsets = offsets     # name -> (lo, hi); "" = base exo

    def has(self, name: str) -> bool:
        return name in self.families

    def block(self, name: str) -> tuple[int, int]:
        if name not in self.offsets:
            raise ValueError(f"stream carries no {name} lanes")
        return self.offsets[name]


def resolve_layout(rows: int, Z: int) -> StreamLayout:
    """Resolve a packed stream's lane layout from its row count — the
    zero-API-churn detection every engine uses, generalized over the
    registered families. Rejects any other row count outright (a
    half-widened stream would silently misread lanes as padding)."""
    sums = _subset_sums(lane_families(), Z)
    names = sums.get(int(rows))
    if names is None:
        valid = ", ".join(
            f"{total} ({'+'.join(combo) or 'plain'})"
            for total, combo in sorted(sums.items()))
        raise ValueError(
            f"packed stream has {rows} rows; this topology (Z={Z}) "
            f"expects one of: {valid}")
    offsets = {}
    off = exo_rows(Z)
    for fam in lane_families():
        if fam.name in names:
            offsets[fam.name] = (off, off + fam.rows(Z))
            off += fam.rows(Z)
    return StreamLayout(Z, int(rows), names, offsets)


def stream_layout(rows: int, Z: int) -> tuple[bool, bool]:
    """``(has_faults, has_workloads)`` of a packed stream, inferred from
    its row count via :func:`resolve_layout`. The long-standing
    two-tuple form every kernel launcher consumes — lane families
    beyond the built-in two resolve (and ride the stream) without
    appearing here, because no engine consumes them in-kernel."""
    lay = resolve_layout(rows, Z)
    return lay.has("faults"), lay.has("workloads")


def workload_base(rows: int, Z: int) -> int:
    """Row offset of the workload block inside a widened stream (after
    the fault block when one is present)."""
    lay = resolve_layout(rows, Z)
    if not lay.has("workloads"):
        raise ValueError("stream carries no workload lanes")
    return lay.block("workloads")[0]


# ---- policy-mode registry -------------------------------------------------

# Engine slots and the provider module that registers each — imported
# lazily at resolution time so this module's import graph stays empty.
_ENGINE_PROVIDERS = {
    "packed_summary": "ccka_tpu.sim.megakernel",
    "block_summary": "ccka_tpu.sim.megakernel",
    "sharded_block_summary": "ccka_tpu.parallel.sharded_kernel",
    "lax_summary": "ccka_tpu.sim.rollout",
}

_MODE_REGISTRAR = "ccka_tpu.sim.megakernel"


class EngineMode:
    """One registered packed policy mode and its engine closures (see
    module docstring). Slots default to None and are provided by their
    engine modules (:func:`provide_mode_engine`); `mode_engine` imports
    the declared provider on first use."""

    __slots__ = ("name", "watch_name", "packed_summary", "block_summary",
                 "sharded_block_summary", "lax_summary")

    def __init__(self, name, watch_name):
        self.name = name
        self.watch_name = watch_name
        for slot in _ENGINE_PROVIDERS:
            setattr(self, slot, None)


MODES: dict[str, EngineMode] = {}

# Engines provided before their mode registers (engine modules and the
# mode registrar import in either order — e.g. `sim/rollout.py` provides
# the lax engines whether or not the kernel module has imported yet).
# Drained by `register_mode`.
_PENDING_ENGINES: list[tuple[str, str, object]] = []


def _attach_engine(mode: EngineMode, slot: str, fn) -> None:
    if getattr(mode, slot) is not None:
        raise ValueError(f"mode {mode.name!r} already has a {slot} "
                         "engine")
    setattr(mode, slot, fn)


def register_mode(name: str, *, watch_name: str, **engines) -> EngineMode:
    """Register a packed policy mode (duplicates rejected). ``engines``
    may provide any of the engine slots inline; the rest arrive via
    :func:`provide_mode_engine` from their own modules (in either import
    order — early provisions queue until the mode registers)."""
    if name in MODES:
        raise ValueError(f"packed mode {name!r} already registered")
    mode = EngineMode(name, watch_name)
    MODES[name] = mode
    for slot, fn in engines.items():
        provide_mode_engine(name, slot, fn)
    for pending in [p for p in _PENDING_ENGINES if p[0] == name]:
        _PENDING_ENGINES.remove(pending)
        _attach_engine(mode, pending[1], pending[2])
    return mode


def unregister_mode(name: str) -> None:
    """TEST plumbing only — see :func:`unregister_lane_family`."""
    MODES.pop(name, None)
    for pending in [p for p in _PENDING_ENGINES if p[0] == name]:
        _PENDING_ENGINES.remove(pending)


def provide_mode_engine(name: str, slot: str, fn) -> None:
    """Attach one engine closure to a registered mode (called by the
    engine's own module at import time; queued when the mode has not
    registered yet). Re-providing a filled slot is rejected — two
    modules silently fighting over one engine is a bug."""
    if slot not in _ENGINE_PROVIDERS:
        raise ValueError(f"unknown engine slot {slot!r}; have "
                         f"{sorted(_ENGINE_PROVIDERS)}")
    mode = MODES.get(name)
    if mode is None:
        _PENDING_ENGINES.append((name, slot, fn))
        return
    _attach_engine(mode, slot, fn)


def mode_names() -> tuple[str, ...]:
    """Registered mode names (importing the canonical registrar first so
    an early caller sees the built-in four)."""
    if not MODES:
        importlib.import_module(_MODE_REGISTRAR)
    return tuple(MODES)


def resolve_mode(name: str) -> EngineMode:
    if name not in MODES:
        # The built-in modes register when the kernel module imports;
        # resolve for an early caller rather than erroring on ordering.
        importlib.import_module(_MODE_REGISTRAR)
    if name not in MODES:
        raise ValueError(f"unknown packed mode {name!r} — have "
                         f"{tuple(MODES)}")
    return MODES[name]


def mode_engine(name: str, slot: str):
    """The mode's engine closure for ``slot``, importing the slot's
    provider module on first use. Raises (naming the mode and slot)
    when the provider registers nothing — a mode genuinely missing an
    engine must fail loudly, not fall back to a different engine."""
    mode = resolve_mode(name)
    fn = getattr(mode, slot, None)
    if fn is None:
        provider = _ENGINE_PROVIDERS.get(slot)
        if provider is None:
            raise ValueError(f"unknown engine slot {slot!r}; have "
                             f"{sorted(_ENGINE_PROVIDERS)}")
        importlib.import_module(provider)
        fn = getattr(mode, slot, None)
    if fn is None:
        raise ValueError(f"packed mode {name!r} has no {slot} engine "
                         "registered")
    return fn


# ---- time-axis block layout (ISSUE 13: the streaming pipeline) ------------
#
# The streaming rollout engine (`sim/streaming.py`) splits the packed
# stream's TIME axis into fixed blocks so generation of block k+1 can
# overlap kernel consumption of block k. The arithmetic lives here for
# the same reason the row arithmetic does: the generators (`signals/`),
# the kernel's carried-state entries (`sim/megakernel.py`), the sharded
# wrappers and bench's memory-bound bookkeeping must all agree on block
# boundaries, and a half-agreed split would silently misalign lanes.
# Per-block worlds are keyed ``fold_in(fold_in(key, BLOCK_KEY_TAG), j)``
# — the folding itself lives with the jax-importing generators, but the
# tag is declared here so every backend folds the SAME stream family.
# Fault/workload lanes then key off the BLOCK key exactly as they key
# off the whole-stream key today (their registered family tags), so
# widening a blocked stream with lanes changes neither the exo nor the
# fault rows bitwise — per block, the same invariant the unblocked
# layouts pin.

BLOCK_KEY_TAG = 0x5B10C  # per-block world fold tag (see above)


def block_layout(T: int, block_T: int, t_chunk: int) -> tuple[int, int]:
    """``(n_blocks, T_pad)`` of a time-blocked stream covering ``T``
    true ticks in fixed ``block_T``-tick blocks of ``t_chunk``-sized
    kernel chunks. Rejects any split the kernel grid cannot honor:
    a block must be a whole number of time chunks, and the padded
    horizon must be a whole number of blocks (a ragged tail block would
    need its own compiled program AND its own buffer shape — the
    double-buffer holds exactly two same-shape blocks per chip)."""
    if block_T <= 0 or t_chunk <= 0:
        raise ValueError(f"block_T={block_T} / t_chunk={t_chunk} must "
                         "be positive")
    if block_T % t_chunk:
        raise ValueError(
            f"block_T={block_T} is not a t_chunk={t_chunk} multiple — "
            "the kernel grid advances whole time chunks")
    T_pad = math.ceil(T / t_chunk) * t_chunk
    if T_pad % block_T:
        raise ValueError(
            f"block_T={block_T} does not divide the padded horizon "
            f"T_pad={T_pad} (T={T}, t_chunk={t_chunk}) — streaming "
            "blocks must tile the horizon exactly")
    return T_pad // block_T, T_pad


def chunk_layout(batch: int, chunk: int) -> int:
    """Number of cluster-axis chunks when a ``batch``-wide fleet streams
    through the mesh ``chunk`` clusters at a time (bench's 10^4–10^5
    rows). Rejects a chunk that does not tile the batch — a ragged tail
    chunk would silently change the per-launch geometry mid-sweep.

    Round 21 reuses this as the TENANT-axis layout for the fleet
    service's chunked dispatch (`harness/service.py`): N=10^3–10^4
    tenants ride ``N // chunk`` launches of ONE compiled chunk-sized
    tick program in bounded memory, with the same equal-width
    contract — the chunked run must be bitwise the unchunked one, and
    a ragged tail would be a second program shape."""
    if chunk <= 0:
        raise ValueError(f"cluster chunk={chunk} must be positive")
    if batch % chunk:
        raise ValueError(
            f"cluster chunk={chunk} does not divide batch={batch} — "
            "cluster-axis chunking needs equal-width chunks")
    return batch // chunk


def block_bytes(block_T: int, rows: int, batch: int) -> int:
    """f32 bytes of ONE stream block — the unit of the streaming
    pipeline's memory bound (2 blocks x lanes x chunk live per chip)."""
    return 4 * block_T * rows * batch
