"""Simulator pytrees: parameters, state, actions, per-step metrics.

Dimension glossary (all static at trace time):
  P = number of NodePools (2: `spot-preferred`, `on-demand-slo`,
      `demo_00_env.sh:18-19`)
  Z = number of zones (3 in us-east-2, `demo_20_offpeak_configure.sh:41`)
  T_CT = capacity types (2: spot=0, on-demand=1, `karpenter.sh/capacity-type`)
  C = workload classes (2: spot-targeted, od-targeted — the odd/even
      deployments of `demo_30_burst_configure.sh:59-70`)
  K = provisioning-delay pipeline depth (provision_delay_s / dt_s)

Node counts are float32 throughout: the simulator is a continuous relaxation
so `jax.grad` flows through provisioning/consolidation magnitudes (SURVEY.md
§7 "hard parts (1)"); stochastic mode adds sampled integer-like jumps for
spot interruptions without breaking the relaxation.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ccka_tpu.config import FrameworkConfig

CT_SPOT = 0
CT_OD = 1
N_CT = 2


class SimParams(NamedTuple):
    """Static-per-run physical parameters, derived from FrameworkConfig.

    Kept as a pytree of scalars/arrays (not a static arg) so one compiled
    step serves many configs of identical shape.
    """

    dt_s: jnp.ndarray                 # [] seconds per control tick
    pods_per_node: jnp.ndarray        # [] schedulable pods per node
    base_od_nodes: jnp.ndarray        # [] managed-nodegroup floor (.env:7-8)
    max_nodes: jnp.ndarray            # [P] per-pool node cap
    static_ct_allow: jnp.ndarray      # [P, T_CT] pool's intrinsic capacity types
    class_ct: jnp.ndarray             # [C, T_CT] one-hot: class c needs ct
    provision_pipeline_k: int         # static python int: pipeline depth
    interrupt_p_step: jnp.ndarray     # [] P(spot node interrupted per step)
    pdb_min_available: jnp.ndarray    # [] PDB floor (demo_10:52-57)
    fragmentation: jnp.ndarray        # [] stranded-capacity factor for WhenEmpty
    underutil_threshold: jnp.ndarray  # [] utilization gate for Underutilized
    watts_idle: jnp.ndarray           # [] per node
    watts_full: jnp.ndarray           # [] per node
    rps_per_pod: jnp.ndarray          # [] request throughput proxy
    slo_served_fraction: jnp.ndarray  # [] served/desired to count SLO-met
    consolidate_tau_s: jnp.ndarray    # [] softness of the consolidate-after gate
    latency_base_ms: jnp.ndarray      # [] idle p95 of the latency proxy
    latency_slo_ms: jnp.ndarray       # [] p95 SLO bound (0 = disabled)
    # Workload-family parameters (ccka_tpu/workloads; unused — but still
    # present — when the step runs without a WorkloadStep, so one
    # compiled step serves both modes). The deadline is a STATIC python
    # int like provision_pipeline_k: it sizes the batch age-pipeline.
    wl_inference_queue_max: jnp.ndarray  # [] drop inference work beyond
    wl_inference_slo_ms: jnp.ndarray     # [] inference p95 violation bound
    wl_batch_deadline_ticks: int         # static: batch age-pipeline depth

    @classmethod
    def from_config(cls, cfg: FrameworkConfig) -> "SimParams":
        cl, wl, sm = cfg.cluster, cfg.workload, cfg.sim
        nt = cl.node_type
        ppn = float(np.floor(min(
            (nt.vcpu - nt.system_reserved_vcpu) / wl.pod_cpu_request,
            (nt.mem_gib - nt.system_reserved_mem_gib) / wl.pod_mem_request_gib,
        )))
        static_allow = np.zeros((cl.n_pools, N_CT), np.float32)
        for i, pool in enumerate(cl.pools):
            static_allow[i, CT_SPOT] = float("spot" in pool.capacity_types)
            static_allow[i, CT_OD] = float("on-demand" in pool.capacity_types)
        # class 0 → spot nodeSelector, class 1 → on-demand nodeSelector
        class_ct = np.eye(N_CT, dtype=np.float32)
        return cls(
            dt_s=jnp.float32(sm.dt_s),
            pods_per_node=jnp.float32(ppn),
            base_od_nodes=jnp.float32(cl.base_nodes),
            max_nodes=jnp.asarray([p.max_nodes for p in cl.pools], jnp.float32),
            static_ct_allow=jnp.asarray(static_allow),
            class_ct=jnp.asarray(class_ct),
            provision_pipeline_k=sm.provision_delay_steps,
            interrupt_p_step=jnp.float32(
                sm.spot_interruption_rate_hr * sm.dt_s / 3600.0),
            pdb_min_available=jnp.float32(wl.pdb_min_available),
            fragmentation=jnp.float32(sm.fragmentation),
            underutil_threshold=jnp.float32(sm.underutil_threshold),
            watts_idle=jnp.float32(nt.watts_idle),
            watts_full=jnp.float32(nt.watts_full),
            rps_per_pod=jnp.float32(sm.rps_per_pod),
            slo_served_fraction=jnp.float32(sm.slo_served_fraction),
            consolidate_tau_s=jnp.float32(0.25 * sm.dt_s),
            latency_base_ms=jnp.float32(sm.latency_base_ms),
            latency_slo_ms=jnp.float32(sm.latency_slo_ms),
            wl_inference_queue_max=jnp.float32(
                cfg.workloads.inference_queue_max),
            wl_inference_slo_ms=jnp.float32(cfg.workloads.inference_slo_ms),
            wl_batch_deadline_ticks=int(cfg.workloads.batch_deadline_ticks),
        )


class ClusterState(NamedTuple):
    """The evolving cluster, one batch element = one simulated cluster."""

    nodes: jnp.ndarray          # [P, Z, T_CT] active Karpenter-owned nodes
    pipeline: jnp.ndarray       # [K, P, Z, T_CT] provisioning in flight
    running: jnp.ndarray        # [C] running pods per class
    consol_timer_s: jnp.ndarray  # [P] seconds of continuous reclaimable slack
    time_s: jnp.ndarray         # [] simulated wall-clock
    # Episode accumulators (folded here so scan carries everything).
    acc_cost_usd: jnp.ndarray   # []
    acc_carbon_g: jnp.ndarray   # []
    acc_requests: jnp.ndarray   # [] served requests (proxy)
    acc_slo_ok_s: jnp.ndarray   # [] seconds meeting the served-fraction SLO
    acc_evictions: jnp.ndarray  # [] pods evicted by consolidation (PDB audit)


class Action(NamedTuple):
    """Continuous canonical action — the §3.2 action surface, relaxed.

    The rule profiles map onto this exactly:
      off-peak (`demo_20_offpeak_configure.sh:59-60,69-79`):
        spot pool: consolidation_aggr=1 (WhenEmptyOrUnderutilized),
        od pool:   consolidation_aggr=0, consolidate_after_s=60,
        zone_weight one-hot on OFFPEAK_ZONES, ct_allow per write_req_patch.
      peak (`demo_21_peak_configure.sh:56-57,65-75`):
        both pools aggr=0, after=120s, zones=PEAK_ZONES.
    ``hpa_scale`` closes the reference's HPA gap (§2.3: prometheus-adapter
    installed but no HPA object): per-class multiplier on desired replicas.
    """

    zone_weight: jnp.ndarray          # [P, Z] in [0,1]
    ct_allow: jnp.ndarray             # [P, T_CT] in [0,1]
    consolidation_aggr: jnp.ndarray   # [P] in [0,1]: 0=WhenEmpty, 1=+Underutilized
    consolidate_after_s: jnp.ndarray  # [P] seconds
    hpa_scale: jnp.ndarray            # [C] multiplier on demanded pods

    @classmethod
    def neutral(cls, n_pools: int, n_zones: int, n_classes: int = 2) -> "Action":
        """The `demo_19_reset_policies.sh:22-29` reset: all zones, intrinsic
        capacity types, WhenEmpty/30s."""
        return cls(
            zone_weight=jnp.ones((n_pools, n_zones), jnp.float32),
            ct_allow=jnp.ones((n_pools, N_CT), jnp.float32),
            consolidation_aggr=jnp.zeros((n_pools,), jnp.float32),
            consolidate_after_s=jnp.full((n_pools,), 30.0, jnp.float32),
            hpa_scale=jnp.ones((n_classes,), jnp.float32),
        )


class StepMetrics(NamedTuple):
    """Per-tick observables — what the KSM→ADOT→AMP pipeline would scrape."""

    cost_usd: jnp.ndarray        # [] this tick
    carbon_g: jnp.ndarray        # [] this tick
    served_pods: jnp.ndarray     # [C]
    pending_pods: jnp.ndarray    # [C]
    desired_pods: jnp.ndarray    # [C] HPA-scaled scheduling target
    demand_pods: jnp.ndarray     # [C] raw exogenous demand (SLO/req basis)
    nodes_by_ct: jnp.ndarray     # [T_CT] active node totals
    nodes_by_zone: jnp.ndarray   # [Z] active node totals (region placement)
    slo_ok: jnp.ndarray          # [] {0,1} SLO met this tick (served fraction
                                 #    and, when configured, the p95 bound)
    interrupted_nodes: jnp.ndarray  # [] spot nodes reclaimed this tick
    evicted_pods: jnp.ndarray    # [] consolidation evictions this tick
    latency_p95_ms: jnp.ndarray  # [] queueing-curve p95 proxy (app latency)
    queue_depth: jnp.ndarray     # [] pending-pod backlog (scheduler queue)
    # Fault-injection counters (ccka_tpu/faults; all 0 when the step runs
    # without a FaultStep — the pre-fault pipeline's exact values).
    denied_nodes: jnp.ndarray    # [] spot provisioning denied (ICE), nodes
    delayed_nodes: jnp.ndarray   # [] arrivals held back (delay jitter)
    signal_stale: jnp.ndarray    # [] {0,1} policies saw stale signals
    # Workload-family counters (ccka_tpu/workloads; all 0 when the step
    # runs without a WorkloadStep — the pre-workload pipeline's exact
    # values). Units: pod-equivalents of work (1 pod = 1 unit/tick).
    inf_queue_depth: jnp.ndarray     # [] inference queue after this tick
    inf_served: jnp.ndarray          # [] inference work served this tick
    inf_dropped: jnp.ndarray         # [] load-shed beyond the queue cap
    inf_slo_violation: jnp.ndarray   # [] {0,1} inference SLO violated
    batch_backlog: jnp.ndarray       # [] total batch backlog after tick
    batch_served: jnp.ndarray        # [] batch work served this tick
    batch_deadline_miss: jnp.ndarray  # [] work aged past its deadline
    bg_backlog: jnp.ndarray          # [] best-effort backlog after tick
