"""The cluster dynamics step — pure, branch-free, differentiable.

Models one 30s control tick of the loop the reference delegates to Karpenter,
kube-scheduler and Kyverno (`SURVEY.md` §3.3): pod scheduling, provisioning
with delay, spot interruption, consolidation, and cost/carbon/SLO accounting.

Every operation is a static-shape `jnp` expression: `vmap`-able over a
cluster batch, `lax.scan`-able over the horizon, and differentiable w.r.t.
the continuous :class:`~ccka_tpu.sim.types.Action` relaxation. Discrete
events (consolidation firing, SLO gating) use sharp-but-smooth sigmoid gates
so diff-MPC gradients see the timers; stochastic spot interruption draws from
a binomial-moment Gaussian approximation to stay shape-static under `vmap`.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ccka_tpu.config import LATENCY_CURVE_COEF, LATENCY_RHO_CLIP
from ccka_tpu.sim.types import (
    CT_OD,
    CT_SPOT,
    Action,
    ClusterState,
    SimParams,
    StepMetrics,
)

_EPS = 1e-6


def _poisson_small(key: jax.Array, lam: jnp.ndarray,
                   cap: jnp.ndarray) -> jnp.ndarray:
    """Elementwise Poisson(λ) sample (no rejection loop), capped by ``cap``.

    Two branch-free regimes, blended by `where`:
    - λ < 0.5 — truncated CDF inversion over the first five terms: one
      uniform counted against F(0..3); exact to P[K>4 | λ=0.5] ≈ 1.7e-4
      mass, and the simulator's default per-tick reclaim rates sit far
      below that (λ ≈ 0.03 at 0.05/hr/node on a ≤64-node pool).
    - λ ≥ 0.5 — moment-matched rounded Gaussian (mean λ, var λ), the
      standard large-λ approximation; by λ=5 it is within a few percent on
      all low moments.

    Replaces `jax.random.poisson`, whose rejection sampler's while_loop
    cost ~45% of rollout wall-clock under vmap.
    """
    ku, kn = jax.random.split(key)
    u = jax.random.uniform(ku, lam.shape)
    t = jnp.exp(-lam)
    cdf = t
    count = jnp.zeros_like(lam)
    for k in (1, 2, 3, 4):
        count = count + (u > cdf)
        t = t * lam / k
        cdf = cdf + t
    gauss = jnp.round(lam + jnp.sqrt(lam) * jax.random.normal(kn, lam.shape))
    sample = jnp.where(lam < 0.5, count, jnp.maximum(gauss, 0.0))
    return jnp.minimum(sample, cap)


class ExoStep(NamedTuple):
    """One tick of exogenous signals (a time-slice of ExogenousTrace)."""

    spot_price_hr: jnp.ndarray  # [Z]
    od_price_hr: jnp.ndarray    # [Z]
    carbon_g_kwh: jnp.ndarray   # [Z]
    demand_pods: jnp.ndarray    # [C]
    is_peak: jnp.ndarray        # []


def step(params: SimParams,
         state: ClusterState,
         action: Action,
         exo: ExoStep,
         key: jax.Array,
         *,
         stochastic: bool = False,
         fault=None,
         workload=None,
         wl_state=None):
    """``fault``: optional :class:`ccka_tpu.faults.FaultStep` disturbance
    inputs (preemption-hazard multiplier, ICE denial, delay jitter,
    outage flag). ``None`` — the default everywhere outside the fault
    subsystem — takes the exact pre-fault code path (the Python-level
    branch keeps it BITWISE identical, pinned by `tests/test_faults.py`;
    a neutral FaultStep is bitwise identical too). Signal staleness is
    an *observation* effect: callers (rollout/controller) feed policies
    held signals; this step always consumes true ``exo``.

    ``workload``/``wl_state``: optional
    :class:`ccka_tpu.workloads.WorkloadStep` arrivals +
    :class:`~ccka_tpu.workloads.WorkloadState` queues (pass both or
    neither). When given, the per-family queues drain from the
    post-step fleet's headroom — inference first (queueing-curve
    latency + SLO-violation accounting, drops beyond the queue cap),
    then batch EDF over a deadline-deep age pipeline (work aging past
    ``wl_batch_deadline_ticks`` is a deadline miss), then best-effort
    background — and the step RETURNS A TRIPLE ``(state, metrics,
    wl_state')``. ``None`` (the default) takes the exact pre-workload
    path and the classic ``(state, metrics)`` pair (Python-level
    branch, bitwise — pinned by `tests/test_workloads.py`). The
    families consume only slack: the primary demand's scheduling,
    pricing and SLO accounting are untouched, so policies differ on the
    per-family columns exactly through the headroom their fleets carry.
    """
    if (workload is None) != (wl_state is None):
        raise ValueError("step: pass both workload= and wl_state=, or "
                         "neither")
    ppn = params.pods_per_node
    dt_hr = params.dt_s / 3600.0

    # ---- 1. Desired pods: demand scaled by the HPA lever (closes §2.3 gap:
    # prometheus-adapter installed but no HPA object in the reference).
    desired = exo.demand_pods * action.hpa_scale  # [C]

    # ---- 2. Provisioning pipeline arrivals (NodeClaim → Registered).
    # Delay jitter (fault): a fraction of the arrivals is held back one
    # more tick — re-queued at the head of the shifted pipeline.
    arrivals = state.pipeline[0]                        # [P, Z, T_CT]
    if fault is not None:
        held = arrivals * fault.delay_frac
        nodes = state.nodes + (arrivals - held)
    else:
        nodes = state.nodes + arrivals
    pipeline = jnp.concatenate(
        [state.pipeline[1:], jnp.zeros_like(state.pipeline[:1])], axis=0)
    if fault is not None:
        pipeline = pipeline.at[0].add(held)

    # ---- 3. Spot interruptions — stochastic reclaim, the process the
    # reference disabled (`05_karpenter.sh:136`). Gaussian moment-match of
    # Binomial(n, p) keeps shapes static and vmap-friendly. The fault
    # hazard lane scales the per-zone probability (preemption storms),
    # clipped at 1 — a storm can at most reclaim the whole pool.
    p = params.interrupt_p_step
    if fault is not None:
        p = jnp.minimum(p * fault.preempt_hazard, 1.0)  # [Z]
    spot_nodes = nodes[..., CT_SPOT]
    mean_int = spot_nodes * p
    if stochastic:
        # Poisson thinning: exact for the rare-event regime (n·p ≪ 1 at 30s
        # ticks) where a clipped-Gaussian binomial approximation is badly
        # positively biased; capped by the actual fleet. Sampled by
        # truncated CDF inversion rather than `jax.random.poisson` — the
        # rejection sampler's while_loop cost ~45% of rollout wall-clock
        # under vmap, and for λ ≤ ~0.2 the K≤4 truncation error
        # (P[K>4] ≈ λ⁵/120) is far below float32 resolution.
        interrupted = _poisson_small(key, mean_int, spot_nodes)
    else:
        interrupted = mean_int
    nodes = nodes.at[..., CT_SPOT].add(-interrupted)
    interrupted_total = interrupted.sum()

    # ---- 4. Scheduling: pods bind to nodes matching their capacity-type
    # nodeSelector (`demo_30_burst_configure.sh:104-106`). Base managed
    # nodegroup (`.env:7-8`) contributes on-demand capacity.
    nodes_ct = nodes.sum(axis=(0, 1))                   # [T_CT]
    cap_ct = nodes_ct * ppn
    cap_ct = cap_ct.at[CT_OD].add(params.base_od_nodes * ppn)
    cap_class = params.class_ct @ cap_ct                # [C]
    running = jnp.minimum(desired, cap_class)
    pending = desired - running

    # ---- 5. Provisioning: Karpenter reacts to Pending pods, discounted by
    # capacity already in flight, split over (pool, zone, ct) by the action's
    # requirements (`demo_20:69-79`) × cheapest-fit zone preference.
    incoming_ct = pipeline.sum(axis=(0, 1, 2))          # [T_CT] nodes in flight
    shortage_ct = params.class_ct.T @ pending           # [T_CT] pods
    need_nodes_ct = jnp.maximum(shortage_ct / ppn - incoming_ct, 0.0)

    price_zc = jnp.stack([exo.spot_price_hr, exo.od_price_hr], axis=-1)  # [Z, T_CT]
    # Cheapest-fit: softmin over zones per capacity type (Karpenter picks the
    # lowest-price offering satisfying requirements).
    cheap = jax.nn.softmax(-price_zc / (0.1 * price_zc.mean() + _EPS), axis=0)
    allow = action.ct_allow * params.static_ct_allow    # [P, T_CT]
    w = action.zone_weight[:, :, None] * allow[:, None, :] * cheap[None, :, :]
    wsum = w.sum(axis=(0, 1), keepdims=True)
    frac = jnp.where(wsum > _EPS, w / (wsum + _EPS), 0.0)
    new_nodes = frac * need_nodes_ct[None, None, :]     # [P, Z, T_CT]

    # Per-pool cap (PoolSpec.max_nodes): scale down a pool's share if the
    # active + in-flight + new total would exceed its limit.
    pool_now = nodes.sum(axis=(1, 2)) + pipeline.sum(axis=(0, 2, 3))  # [P]
    pool_new = new_nodes.sum(axis=(1, 2))
    headroom = jnp.maximum(params.max_nodes - pool_now, 0.0)
    scale = jnp.where(pool_new > _EPS,
                      jnp.minimum(headroom / (pool_new + _EPS), 1.0), 1.0)
    new_nodes = new_nodes * scale[:, None, None]
    # Insufficient-capacity errors (fault): the spot share of this tick's
    # provisioning request is denied. Denied capacity is *not requested*
    # — the pods stay pending and Karpenter re-requests next tick, which
    # is exactly how ICE retry behaves (the window's AR(1) persistence is
    # the cooldown). On-demand is never denied.
    if fault is not None:
        denied = new_nodes[..., CT_SPOT].sum() * fault.deny_frac
        new_nodes = new_nodes.at[..., CT_SPOT].multiply(
            1.0 - fault.deny_frac)
    else:
        denied = jnp.float32(0.0)
    pipeline = pipeline.at[-1].add(new_nodes)

    # ---- 6. Consolidation per disruption policy (`demo_20:59-60`,
    # `demo_21:56-57`). Pods prefer base capacity, so Karpenter-owned
    # on-demand usage is the residual above the base nodegroup.
    used_ct = params.class_ct.T @ running               # [T_CT] pods per ct
    used_karp_od = jnp.maximum(used_ct[CT_OD] - params.base_od_nodes * ppn, 0.0)
    used_karp = jnp.stack([used_ct[CT_SPOT], used_karp_od])  # [T_CT]
    repack = used_karp / ppn                            # optimal node count
    nodes_ct = nodes.sum(axis=(0, 1))
    slack_ct = jnp.maximum(nodes_ct - repack, 0.0)
    # WhenEmpty reclaims only truly-empty nodes; fragmentation strands
    # partially-filled ones (SimConfig.fragmentation).
    empty_ct = jnp.maximum(nodes_ct - repack * (1.0 + params.fragmentation), 0.0)
    # WhenEmptyOrUnderutilized additionally repacks, evicting pods — bounded
    # by the PDB budget (`demo_10_setup_configure.sh:52-57`: minAvailable 50%)
    # and gated on the fleet actually being underutilized: repack beyond
    # empty-node reclaim only engages while utilization sits below
    # ``underutil_threshold`` (smooth gate so grads see the margin).
    util_karp_ct = used_karp / (nodes_ct * ppn + _EPS)
    under_gate = jax.nn.sigmoid(
        (params.underutil_threshold - util_karp_ct) / 0.05)
    evict_budget_ct = (1.0 - params.pdb_min_available) * used_karp
    aggr_ct = jnp.minimum(slack_ct,
                          empty_ct + under_gate * evict_budget_ct / ppn)

    share = nodes / (nodes_ct[None, None, :] + _EPS)    # [P, Z, T_CT]
    aggr_p = action.consolidation_aggr[:, None, None]
    removable = share * (empty_ct * (1.0 - aggr_p) + aggr_ct * aggr_p)

    removable_p = removable.sum(axis=(1, 2))            # [P]
    has_slack = removable_p > 1e-3
    timer = jnp.where(has_slack, state.consol_timer_s + params.dt_s, 0.0)
    gate = jax.nn.sigmoid(
        (timer - action.consolidate_after_s) / params.consolidate_tau_s)
    removed = removable * gate[:, None, None]
    nodes = jnp.maximum(nodes - removed, 0.0)
    # Evictions: removals beyond the empty-only reclaim displace running pods
    # (approximated at half occupancy on the displaced nodes).
    removed_ct = removed.sum(axis=(0, 1))
    evicted = jnp.maximum(removed_ct - empty_ct, 0.0).sum() * ppn * 0.5
    timer = jnp.where(gate > 0.5, 0.0, timer)

    # ---- 7. Accounting on post-step fleet. Base nodes are spread evenly
    # over zones at on-demand price.
    z = exo.spot_price_hr.shape[-1]
    base_z = params.base_od_nodes / z
    nodes_zc = nodes.sum(axis=0)                        # [Z, T_CT]
    nodes_zc = nodes_zc.at[:, CT_OD].add(base_z)
    cost = (nodes_zc * price_zc).sum() * dt_hr

    total_ct = nodes_zc.sum(axis=0)
    util_ct = jnp.where(total_ct > _EPS,
                        jnp.minimum(used_ct / (total_ct * ppn + _EPS), 1.0), 0.0)
    watts_ct = params.watts_idle + (params.watts_full - params.watts_idle) * util_ct
    kwh_zc = nodes_zc * watts_ct[None, :] / 1000.0 * dt_hr
    carbon = (kwh_zc * exo.carbon_g_kwh[:, None]).sum()

    # Served requests only exist where real demand exists: pods running above
    # raw demand (hpa_scale > 1 headroom) serve no extra requests, so the
    # $/req and gCO2/req denominators can't be inflated by overscaling.
    effective = jnp.minimum(running, exo.demand_pods)     # [C]
    requests = effective.sum() * params.rps_per_pod * params.dt_s

    # Latency proxy — the app-level p95 the reference named as an SLO input
    # (README.md:21) but never scraped (§2.3: the pipeline carries only
    # kube-state-metrics). An M/M/1-shaped queueing curve over the fleet
    # load factor: p95 ≈ base · (1 + c·ρ²/(1−ρ)), ρ = demand/capacity
    # clipped below 1 so overload saturates (~145× base) instead of
    # diverging. Constants shared with the config-level SLO-bound
    # validation (`LATENCY_SATURATION_FACTOR`) so the ceiling check can
    # never drift from the curve. Smooth in capacity, so diff-MPC
    # gradients see latency.
    load = exo.demand_pods.sum() / (cap_ct.sum() + _EPS)
    rho = jnp.clip(load, 0.0, LATENCY_RHO_CLIP)
    latency_p95_ms = params.latency_base_ms * (
        1.0 + LATENCY_CURVE_COEF * rho * rho / (1.0 - rho))
    queue_depth = pending.sum()

    # SLO is judged per class against *raw* demand, not the HPA-scaled
    # target — otherwise a policy could "meet" SLO by zeroing its own target
    # (hpa_scale=0) or by overserving one class while starving the other.
    # With a configured p95 bound, the latency gate must hold too.
    # ---- 7b. Workload families (ccka_tpu/workloads): per-family queues
    # drained from the post-step fleet's HEADROOM (capacity incl. the
    # base nodegroup minus the primary demand's running pods), priority
    # inference -> batch EDF -> background. Python-level branch: the
    # None path is the exact pre-workload program.
    if workload is not None:
        cap_total = nodes_zc.sum() * ppn
        headroom = jnp.maximum(cap_total - running.sum(), 0.0)
        # Inference: served first; queue bounded (excess = load-shed).
        inf_in = wl_state.inf_queue + workload.inf_arrivals
        inf_served = jnp.minimum(inf_in, headroom)
        inf_after = inf_in - inf_served
        inf_dropped = jnp.maximum(
            inf_after - params.wl_inference_queue_max, 0.0)
        inf_queue2 = inf_after - inf_dropped
        rem = headroom - inf_served
        inf_rho = jnp.clip(inf_in / (headroom + _EPS),
                           0.0, LATENCY_RHO_CLIP)
        inf_latency = params.latency_base_ms * (
            1.0 + LATENCY_CURVE_COEF * inf_rho * inf_rho / (1.0 - inf_rho))
        inf_viol = jnp.maximum(
            (inf_latency > params.wl_inference_slo_ms).astype(jnp.float32),
            (inf_dropped > 0.0).astype(jnp.float32))
        # Batch: EDF over the age pipeline. pool[k] = work that has
        # waited k ticks (k=0 arrived now); the state's slot D-1 is 0 by
        # invariant (it was dropped as missed last tick), so the shift
        # discards nothing.
        w_prev = wl_state.batch_backlog                   # [D]
        D = w_prev.shape[0]
        pool = jnp.concatenate(
            [jnp.reshape(workload.batch_arrivals, (1,)), w_prev[:D - 1]])
        leftover = []
        batch_served = jnp.float32(0.0)
        for k in range(D - 1, -1, -1):                    # oldest first
            take = jnp.minimum(pool[k], rem)
            rem = rem - take
            batch_served = batch_served + take
            leftover.append(pool[k] - take)
        leftover = jnp.stack(leftover[::-1])              # [D], age order
        batch_missed = leftover[D - 1]
        batch_backlog2 = jnp.concatenate(
            [leftover[:D - 1], jnp.zeros((1,), jnp.float32)])
        # Background: best-effort, whatever headroom remains.
        bg_in = wl_state.bg_backlog + workload.bg_arrivals
        bg_served = jnp.minimum(bg_in, rem)
        bg_backlog2 = bg_in - bg_served
        wl_state2 = wl_state._replace(inf_queue=inf_queue2,
                                      batch_backlog=batch_backlog2,
                                      bg_backlog=bg_backlog2)
        wl_metrics = dict(
            inf_queue_depth=inf_queue2,
            inf_served=inf_served,
            inf_dropped=inf_dropped,
            inf_slo_violation=inf_viol,
            batch_backlog=batch_backlog2.sum(),
            batch_served=batch_served,
            batch_deadline_miss=batch_missed,
            bg_backlog=bg_backlog2,
        )
    else:
        zero = jnp.float32(0.0)
        wl_metrics = dict(
            inf_queue_depth=zero, inf_served=zero, inf_dropped=zero,
            inf_slo_violation=zero, batch_backlog=zero, batch_served=zero,
            batch_deadline_miss=zero, bg_backlog=zero)

    met_c = running >= params.slo_served_fraction * exo.demand_pods - _EPS
    latency_ok = jnp.where(
        params.latency_slo_ms > 0,
        (latency_p95_ms <= params.latency_slo_ms).astype(jnp.float32),
        1.0)
    slo_ok = met_c.all().astype(jnp.float32) * latency_ok

    new_state = ClusterState(
        nodes=nodes,
        pipeline=pipeline,
        running=running,
        consol_timer_s=timer,
        time_s=state.time_s + params.dt_s,
        acc_cost_usd=state.acc_cost_usd + cost,
        acc_carbon_g=state.acc_carbon_g + carbon,
        acc_requests=state.acc_requests + requests,
        acc_slo_ok_s=state.acc_slo_ok_s + slo_ok * params.dt_s,
        acc_evictions=state.acc_evictions + evicted,
    )
    metrics = StepMetrics(
        cost_usd=cost,
        carbon_g=carbon,
        served_pods=running,
        pending_pods=pending,
        desired_pods=desired,
        demand_pods=exo.demand_pods,
        nodes_by_ct=nodes.sum(axis=(0, 1)),
        nodes_by_zone=nodes.sum(axis=(0, 2)),
        slo_ok=slo_ok,
        interrupted_nodes=interrupted_total,
        evicted_pods=evicted,
        latency_p95_ms=latency_p95_ms,
        queue_depth=queue_depth,
        denied_nodes=denied,
        delayed_nodes=(held.sum() if fault is not None
                       else jnp.float32(0.0)),
        signal_stale=(fault.signal_stale if fault is not None
                      else jnp.float32(0.0)),
        **wl_metrics,
    )
    if workload is not None:
        return new_state, metrics, wl_state2
    return new_state, metrics
