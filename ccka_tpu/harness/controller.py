"""The live closed-loop controller — the component the reference never built.

The reference's proposal describes a "Cost & Carbon Aware Controller …
computing the cheapest/cleanest configuration that meets SLOs" every few
seconds (proposal PDF p.4), but in code the decision step is the *operator
manually running* `demo_20_offpeak_configure.sh` or `demo_21_peak_configure.sh`
(`README.md:52-57`). This module closes that §2.3 gap: a daemon composing
the pieces the framework already has, on the reference's 30s metrics cadence
(`06_opencost.sh:323`):

    scrape (SignalSource.tick) → decide (PolicyBackend) → render
    (NodePool patches) → apply (ActuationSink) → verify (observed_state
    read-back) → account (simulator state estimate) → KPI log line

State estimation: the controller carries a :class:`ClusterState` estimate
advanced through the simulator dynamics with the applied action each tick
(model-based dead reckoning). Policies therefore see the same observation
surface in live operation as in training; scraped signals (prices, carbon,
demand, is_peak) are the measured inputs, exactly the quantities the
KSM→ADOT→AMP pipeline carried in the reference.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import tempfile
import time
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ccka_tpu.actuation.patches import render_region_nodepool_patches
from ccka_tpu.actuation.reconcile import Reconciler
from ccka_tpu.actuation.sink import ActuationSink
from ccka_tpu.config import FrameworkConfig
from ccka_tpu.policy.base import PolicyBackend
from ccka_tpu.sim.dynamics import step as sim_step
from ccka_tpu.sim.rollout import exo_steps, initial_state
from ccka_tpu.sim.types import CT_SPOT, Action, ClusterState, SimParams
from ccka_tpu.signals.base import SignalSource


@functools.lru_cache(maxsize=16)
def _compiled_steps(cfg: FrameworkConfig):
    """Jitted estimate steps shared across Controller instances of one
    config. Pre-round-12 every Controller jitted its own lambda, so a
    crash-resume (or the recovery scoreboard's hundreds of paired runs)
    paid a fresh XLA compile per construction — the same
    instance-vs-config keying hazard the forecaster cache fix closed
    (ARCHITECTURE §8). FrameworkConfig is frozen/hashable and SimParams
    derives from it deterministically, so config-keying is sound;
    `shared_stats=True` accumulates all instances into one watch entry."""
    from ccka_tpu.obs.compile import watch_jit

    params = SimParams.from_config(cfg)
    step = watch_jit(
        jax.jit(lambda s, a, e, k: sim_step(params, s, a, e, k,
                                            stochastic=False)),
        "controller.step", hot=True, shared_stats=True)
    step_wl = watch_jit(
        jax.jit(lambda s, ws, a, e, w, k: sim_step(
            params, s, a, e, k, stochastic=False, workload=w,
            wl_state=ws)),
        "controller.step_wl", hot=True, shared_stats=True)
    return step, step_wl


@dataclasses.dataclass
class TickReport:
    """One control tick's structured record (the KPI log line payload)."""

    t: int
    is_peak: bool
    profile: str               # backend-reported mode, e.g. "peak"/"offpeak"
    applied: bool              # all pool patches accepted
    verified: bool             # read-back matches the rendered intent
    fallbacks: int             # pools that needed the legacy schema path
    cost_usd_hr: float         # estimated fleet $/hr after this tick
    carbon_g_hr: float         # estimated gCO2/hr
    nodes_spot: float
    nodes_od: float
    pending_pods: float
    slo_ok: bool
    detail: str = ""
    # Model-estimated app p95 (queueing-curve proxy, `sim/dynamics.py`).
    latency_p95_ms: float = 0.0
    # Tick-rate KPI gauges (the dashboard's $/1k-req, gCO2e/1k-req and
    # waste% panels, proposal PDF p.5). Episode-level versions live in
    # EpisodeSummary; these are the instantaneous rates a live scrape sees.
    usd_per_kreq: float = 0.0
    g_co2_per_kreq: float = 0.0
    waste_frac: float = 0.0
    # Spot interruption warnings consumed this tick and nodes drained in
    # response (the live half of the capability the reference disabled at
    # `05_karpenter.sh:136`; 0/0 when no feed is wired).
    interruption_warnings: int = 0
    nodes_drained: int = 0
    # Measured app-level SLO metrics when the signal source scrapes them
    # (live Prometheus: p95/RPS/queue depth — the §2.3 inputs the
    # reference advertised but never collected). Empty for sources
    # without an app-metrics path.
    slo_metrics: dict = dataclasses.field(default_factory=dict)
    # Per-phase wall timings (ms) of the scrape→decide→render→apply→verify→
    # estimate pipeline — the structured-timing requirement of SURVEY §5.
    timings_ms: dict = dataclasses.field(default_factory=dict)
    # Degraded-mode state machine (ccka_tpu/faults; ARCHITECTURE §12):
    # signal outages drive ok → hold-last-action → rule-fallback instead
    # of deciding on garbage. ``degraded_level`` is the numeric export
    # (0 ok / 1 hold / 2 fallback); ``degraded_ticks_total`` is the
    # session's cumulative non-ok tick count (the promexport counter).
    signal_stale: bool = False
    degraded: str = "ok"
    degraded_level: int = 0
    degraded_ticks_total: int = 0
    # Fault-model estimate counters (0 outside fault-aware simulation).
    denied_nodes: float = 0.0
    delayed_nodes: float = 0.0
    # Workload-family estimate gauges (ccka_tpu/workloads; 0 unless
    # cfg.workloads is enabled): the per-family queue state of the
    # model-based estimate, and session-cumulative violation/miss
    # counters (kube-state-metrics style — each tick re-states the
    # running total, like degraded_ticks_total).
    inference_queue_depth: float = 0.0
    batch_backlog: float = 0.0
    inference_slo_violations_total: float = 0.0
    batch_deadline_misses_total: float = 0.0
    # Crash-safety surfaces (ARCHITECTURE §14). The reconciler turns the
    # apply stage into convergence: ``reconcile_retries`` counts this
    # tick's re-apply attempts, ``reconcile_diverged`` the pools still
    # diverged at give-up (0 = converged), and ``actuation_failures``
    # the failed applies + failed read-backs this tick. The _total
    # fields are session-cumulative (kube-state-metrics style, like
    # degraded_ticks_total) and survive snapshot/resume.
    reconcile_retries: int = 0
    reconcile_retries_total: int = 0
    reconcile_diverged: int = 0
    actuation_failures: int = 0
    actuation_failures_total: int = 0
    # Ticks since the last durable snapshot write (0 right after one;
    # stays 0 when snapshotting is disabled) and how many times this
    # logical run has been resumed from a snapshot.
    snapshot_age_ticks: int = 0
    resumes_total: int = 0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)


# Ticks an acked-but-unmatched terminate warning is retried before being
# dropped (covers transient node-listing failures and registration lag;
# at the 30s cadence, 4 ticks = the 2-minute interruption notice window).
_PENDING_WARNING_TTL = 4


class ControllerLockHeld(RuntimeError):
    """Another controller daemon holds this cluster's single-writer lock."""


class ControllerLock:
    """Advisory single-writer lock per cluster — the race guard.

    The reference's concurrency discipline is ad hoc: port-collision
    preflight (`demo_18_preroll_check.sh:58-65`) and killing stale
    port-forwards (`demo_19_reset_policies.sh:39-55`); nothing stops two
    operators applying demo_20 and demo_21 simultaneously, which would
    ping-pong the NodePool disruption settings and churn real nodes. Two
    controller daemons on one cluster are the same hazard, so the
    controller takes an exclusive `flock` on a per-cluster lockfile; a
    second instance fails fast (:class:`ControllerLockHeld`, with the
    holder's pid) instead of silently interleaving patches.

    The lockfile is never unlinked: removing it on release would let a
    waiter that already opened the old inode lock it while a third opener
    locks a fresh file at the same path — two "exclusive" holders (the
    classic flock-unlink race). The default lock dir is per-uid so a
    second user's daemon gets the lock-held diagnostic, not an
    unrelated PermissionError on another user's directory.
    """

    def __init__(self, cluster_name: str, *, lock_dir: str | None = None):
        d = lock_dir or os.path.join(tempfile.gettempdir(),
                                     f"ccka-locks-{os.getuid()}")
        os.makedirs(d, exist_ok=True)
        self.path = os.path.join(d, f"controller-{cluster_name}.lock")
        self._fh = None

    def acquire(self) -> None:
        import fcntl

        fh = open(self.path, "a+")
        try:
            fcntl.flock(fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            fh.seek(0)
            holder = fh.read().strip() or "unknown pid"
            fh.close()
            raise ControllerLockHeld(
                f"another controller holds {self.path} ({holder}); two "
                "control loops on one cluster would ping-pong NodePool "
                "patches — stop the other instance first")
        fh.truncate(0)
        fh.write(f"pid={os.getpid()}\n")
        fh.flush()
        self._fh = fh

    def release(self) -> None:
        if self._fh is not None:
            import fcntl

            fcntl.flock(self._fh, fcntl.LOCK_UN)
            self._fh.close()
            self._fh = None


def _workload_clock_anchor(source: SignalSource, dt_s: float) -> float:
    """Unix-seconds anchor for the workload-family arrival track: the
    source's own clock when it carries one (synthetic/live expose
    ``start_unix_s``; replay keeps its recorded clock in ``meta()`` and
    replays from ``offset_steps`` into the store), wall clock otherwise.
    A timestamp, not a timing measurement — kept in this host-only scope
    so the diurnal phase anchor stays out of the device-touching
    ``__init__`` the AST timing guard polices."""
    start = getattr(source, "start_unix_s", None)
    if start is None:
        try:
            m = source.meta()
            start = (m.start_unix_s
                     + getattr(source, "offset_steps", 0) * (m.dt_s or dt_s))
        except Exception:
            start = time.time()
    return float(start)




class Controller:
    """Scrape→decide→act loop over pluggable backend/source/sink.

    ``interval_s`` defaults to the signals scrape cadence (30s, matching
    `06_opencost.sh:323`); tests inject ``sleep_fn``/``log_fn`` and run with
    interval 0.
    """

    def __init__(self,
                 cfg: FrameworkConfig,
                 backend: PolicyBackend,
                 source: SignalSource,
                 sink: "ActuationSink | dict[str, ActuationSink]",
                 *,
                 interval_s: float | None = None,
                 seed: int = 0,
                 apply_hpa: bool = False,
                 apply_keda: bool = False,
                 lock: bool = False,
                 lock_dir: str | None = None,
                 degraded_fallback_after: int = 3,
                 reconcile_rounds: int = 3,
                 reconcile_backoff_s: float = 0.05,
                 reconcile_deadline_s: float = 5.0,
                 snapshot_path: str = "",
                 snapshot_every: int = 1,
                 telemetry_path: str = "",
                 exporter=None,
                 tracer=None,
                 interruption_feed=None,
                 incident_log=None,
                 decision_ledger=None,
                 log_fn: Callable[[str], None] | None = None,
                 sleep_fn: Callable[[float], None] = time.sleep):
        self.cfg = cfg
        # Spot interruption/rebalance warning source (SpotInterruptionFeed
        # or any object with poll() -> [InterruptionWarning]); None
        # disables the drain path.
        self.interruption_feed = interruption_feed
        # insertion-ordered: oldest evicted first (see _remember_drained)
        self._drained_instances: dict[str, None] = {}
        # Terminate warnings whose instance no node-listing resolved yet.
        # The SQS ack happens at poll time (before processing), so an
        # unresolved warning would otherwise be lost forever — e.g. a
        # transient apiserver blip making list_objects return [] — and
        # the 2-minute notice wasted. Bounded retry: {instance_id:
        # (warning, remaining_ticks)}.
        self._pending_warnings: dict[str, tuple] = {}
        # Prometheus exposition of the tick KPIs (harness.promexport);
        # None disables. Updated after every tick.
        self.exporter = exporter
        # Shared span tracer (obs/trace.py): when given, every tick's
        # phase spans accumulate here and the owner can export one
        # Perfetto-loadable Chrome trace for the whole session (`ccka run
        # --trace-out`). None keeps per-tick private timers (old shape).
        self.tracer = tracer
        self.backend = backend
        self.source = source
        # Multi-region fleets (BASELINE config #4) run one Karpenter per
        # regional cluster, so actuation needs one sink per region. A bare
        # sink serves the single-region topology; a dict must cover every
        # configured region.
        if isinstance(sink, dict):
            missing = [r.name for r in cfg.cluster.regions
                       if r.name not in sink] if cfg.cluster.regions else (
                [cfg.cluster.region] if cfg.cluster.region not in sink else [])
            if missing:
                raise ValueError(f"no sink for region(s) {missing}")
            self.region_sinks = dict(sink)
        else:
            names = ([r.name for r in cfg.cluster.regions]
                     or [cfg.cluster.region])
            self.region_sinks = {name: sink for name in names}
        # Home-region sink: workload-scoped objects (HPA) live here.
        self.sink = self.region_sinks.get(
            cfg.cluster.region, next(iter(self.region_sinks.values())))
        # Desired-state reconciliation (actuation/reconcile.py): the
        # apply stage converges each region's sink onto the rendered
        # intent with deadline-bounded retries + read-back verification
        # instead of firing apply_all once and hoping. One reconciler
        # per DISTINCT sink object: regions sharing a sink share its
        # retry state, and the AST guard (tests/test_timing_guard.py)
        # pins that harness code never bypasses this path.
        by_sink: dict[int, Reconciler] = {}
        self._reconcilers: dict[str, Reconciler] = {}
        for region, snk in self.region_sinks.items():
            rec = by_sink.get(id(snk))
            if rec is None:
                rec = by_sink[id(snk)] = Reconciler(
                    snk, max_rounds=reconcile_rounds,
                    backoff_s=reconcile_backoff_s,
                    deadline_s=reconcile_deadline_s,
                    seed=seed ^ 0x5EC0)
            self._reconcilers[region] = rec
        # Incident log (round 14, `obs/incidents.py`; None disables):
        # the degraded machine's hold→rule-fallback escalation and
        # every reconciler give-up stamp ONE structured incident each,
        # joined to RunLog lines and trace spans on the tick key by
        # `ccka incidents timeline`. The give-up trigger rides the
        # reconciler's OWN hook (`actuation/reconcile.on_giveup`), at
        # the layer that defines "gave up".
        self.incident_log = incident_log
        # Decision-provenance ledger (round 18, `obs/decisions.py`;
        # None disables): one structured row per tick — the observed
        # exo, the state estimate, the chosen action's objective terms
        # and the RULE SHADOW stepped on the same inputs. Unlike the
        # batched fleet/service ticks (where the shadow rides extra
        # lanes of the one dispatch), the single-cluster loop pays two
        # extra small dispatches per tick when a ledger is attached —
        # noise against its 30s scrape cadence, and the REAL estimate
        # path is untouched either way (same compiled step, same
        # inputs), so attaching a ledger cannot steer a decision.
        self.decision_ledger = decision_ledger
        self._obs_tick = 0
        # Regions may SHARE a reconciler (one per distinct sink), so
        # the give-up's region is stamped from the converge call site
        # (`self._obs_region`, set by the apply loop), not baked into
        # the hook.
        self._obs_region = ""
        if incident_log is not None:
            for rec in by_sink.values():
                rec.on_giveup = self._stamp_giveup
        self.interval_s = (cfg.signals.scrape_interval_s
                           if interval_s is None else interval_s)
        self.apply_hpa = apply_hpa
        self.apply_keda = apply_keda
        if apply_keda and not (cfg.workload.sqs_queue_name
                               and cfg.workload.aws_account_id):
            raise ValueError(
                "apply_keda requires workload.sqs_queue_name and "
                "workload.aws_account_id (the reference's CREATE_SQS/"
                "SQS_QUEUE_NAME stub, `.env:10-12`)")
        self.seed = seed
        # Degraded-mode state machine (ARCHITECTURE §12): when the source
        # flags its scrape stale (`SignalSource.last_scrape_stale` — live
        # retry budgets exhausted), the controller stops trusting the
        # sample: first HOLD the last applied action (fresh garbage must
        # not move the fleet), and after ``degraded_fallback_after``
        # consecutive stale ticks FALL BACK to the rule policy — its only
        # signal input is the clock-derived is_peak, so it stays sound
        # with every scrape down. Recovery (a fresh scrape) returns to
        # the primary backend immediately.
        self.degraded_fallback_after = max(1, int(degraded_fallback_after))
        from ccka_tpu.policy import RulePolicy
        self._fallback_policy = RulePolicy(cfg.cluster)
        self._degraded = "ok"
        self._stale_streak = 0
        # Actuation divergence feeds the SAME state machine (round 12):
        # a reconciler give-up increments this streak, and a cluster
        # that will not converge drives hold → rule-fallback exactly
        # like a stale signal — stop pushing fresh complex intents at
        # an edge that is not accepting them.
        self._diverge_streak = 0
        self._last_action: Action | None = None
        self.degraded_ticks_total = 0
        # Crash-safety session counters + durable snapshot wiring
        # (harness/snapshot.py; "" disables). Snapshots are written at
        # the END of a tick (next_tick = t+1), so a kill between writes
        # resumes at the last completed tick boundary and the decision
        # stream replays bitwise.
        self.reconcile_retries_total = 0
        self.actuation_failures_total = 0
        self.resumes_total = 0
        self.snapshot_path = snapshot_path
        self.snapshot_every = max(1, int(snapshot_every))
        self._last_snapshot_tick: int | None = None
        self._last_verified_desired: dict = {}
        self._force_replan = False
        self.log_fn = log_fn if log_fn is not None else (
            lambda line: print(line, flush=True))
        self.sleep_fn = sleep_fn
        self.params = SimParams.from_config(cfg)
        self.state: ClusterState = initial_state(cfg)
        self.key = jax.random.key(seed)
        # Single-writer guard (see ControllerLock): on for daemons, off for
        # in-process test harnesses that drive ticks directly. Acquired
        # FIRST so a lock-held refusal is side-effect-free — no telemetry
        # file created or fd leaked by a half-constructed controller.
        self._lock = None
        if lock:
            self._lock = ControllerLock(cfg.cluster.name, lock_dir=lock_dir)
            self._lock.acquire()
        # Durable JSONL telemetry (the remote-write analog); "" disables.
        self.telemetry = None
        if telemetry_path:
            from ccka_tpu.harness.telemetry import TelemetryWriter
            self.telemetry = TelemetryWriter(telemetry_path)
        # Watched jit (obs/compile.py): the state-estimate step is the
        # controller's hot device path — after the warmup compile, a
        # recompile mid-run means a static-arg leak and gets warned.
        # Config-keyed and shared across instances (`_compiled_steps`),
        # so a crash-resumed controller reuses the dead one's compile.
        self._step, self._step_wl = _compiled_steps(cfg)
        # Workload-family track (ccka_tpu/workloads): when the config
        # enables families, the state estimate also carries per-family
        # queues fed by a deterministic arrival sample (seed-keyed, one
        # horizon pre-sampled and tiled) — the live analog of the
        # simulator's workload lanes, surfaced through promexport as
        # ccka_inference_queue_depth / *_slo_violations_total /
        # ccka_batch_deadline_misses_total.
        self._wl_steps = None
        wl_cfg = getattr(cfg, "workloads", None)
        self.inference_slo_violations_total = 0.0
        self.batch_deadline_misses_total = 0.0
        if wl_cfg is not None and wl_cfg.enabled:
            from ccka_tpu.workloads.process import (WORKLOAD_KEY_TAG,
                                                    sample_workload_steps)
            from ccka_tpu.workloads.types import WorkloadState
            # Whole-day horizon (the `t % horizon` tile must wrap at a
            # day boundary or the diurnal arrival process jumps mid-day)
            # anchored to the source's clock: synthetic and live carry
            # `.start_unix_s` directly (live = wall clock at source
            # construction, so the 14:00 inference peak lands at real
            # 14:00); replay keeps its recorded clock in `meta()` and
            # replays from `offset_steps` into the store, so the track
            # stays phased to the window the estimate actually sees.
            day = max(1, int(round(86400.0 / cfg.sim.dt_s)))
            self._wl_horizon = -(-max(int(cfg.sim.horizon_steps), day)
                                 // day) * day
            # The anchor is snapshot state: a resumed run must re-sample
            # the SAME arrival track, not re-anchor to its own clock.
            self._wl_anchor = _workload_clock_anchor(source, cfg.sim.dt_s)
            self._wl_cfg = wl_cfg
            self._wl_steps = sample_workload_steps(
                wl_cfg, jax.random.key(seed ^ WORKLOAD_KEY_TAG),
                self._wl_horizon,
                cfg.cluster.n_zones, dt_s=cfg.sim.dt_s,
                start_unix_s=self._wl_anchor)
            self._wl_state = WorkloadState.zero(
                int(self.params.wl_batch_deadline_ticks))
        # MPC-style backends replan against a forecast window. The window
        # provider is the SAME protocol the jitted evaluation loop uses
        # (`forecast.Forecaster`): a backend carrying a forecaster plans
        # against predictions from observed history; without one it falls
        # back to the source's own forecast (exact future for synthetic/
        # replay — the oracle reference — persistence-of-anomaly for live).
        self._replan_every = getattr(backend, "replan_every", 0)
        self._horizon = getattr(backend, "horizon", 0)
        self._forecaster = getattr(backend, "forecaster", None)
        self._hist_steps = 0
        if self._forecaster is not None:
            self._hist_steps = (getattr(backend, "history_steps", 0)
                                or self._forecaster.wanted_history(
                                    self._horizon))

    # -- spot interruption response -----------------------------------------

    def _drain_for_warnings(self, warnings) -> int:
        """Cordon+drain the spot nodes named by interruption warnings and
        fold the capacity loss into the state estimate immediately.

        Instance-ids map to nodes via ``spec.providerID`` (AWS shape:
        ``aws:///us-east-2a/i-0abc...``) over each region sink's spot-node
        listing. Only ``terminate`` warnings drain — a rebalance
        recommendation is advisory (Karpenter itself treats it as
        optional) and is surfaced in the report count without action.
        The estimate decrement means the very next decide sees the lost
        capacity instead of discovering it a scrape-cadence later."""
        from ccka_tpu.config import ConfigError

        drained = 0
        by_instance: dict[str, tuple[dict, ActuationSink]] = {}
        for sink in dict.fromkeys(self.region_sinks.values()):
            try:
                nodes = sink.list_objects(
                    "node", selector="karpenter.sh/capacity-type=spot")
            except NotImplementedError:
                continue
            for node in nodes:
                provider = str(node.get("spec", {}).get("providerID", ""))
                if provider:
                    by_instance[provider.rsplit("/", 1)[-1]] = (node, sink)
        zones = list(self.cfg.cluster.zones)
        prev_pending = self._pending_warnings
        next_pending: dict[str, tuple] = {}
        for w in warnings:
            if w.action != "terminate":
                self.log_fn(f"# rebalance recommendation: {w!r} (no action)")
                continue
            # SQS standard queues deliver at-least-once (and the ack can
            # fail): a redelivered warning for an instance already drained
            # must not drain/decrement twice.
            if w.instance_id in self._drained_instances:
                self.log_fn(f"# duplicate interruption warning for "
                            f"{w.instance_id} (already drained)")
                continue
            # Both not-yet-matched and failed-to-drain warnings share ONE
            # bounded retry buffer: the warning was already acked at poll
            # time, so the controller is its only memory — losing it
            # wastes the 2-minute notice (ADVICE r4 medium).
            def carry(reason: str) -> None:
                _w, ttl = prev_pending.get(w.instance_id,
                                           (w, _PENDING_WARNING_TTL + 1))
                if ttl - 1 > 0:
                    next_pending[w.instance_id] = (w, ttl - 1)
                    self.log_fn(f"# {reason} — retrying {ttl - 1} more "
                                f"tick(s)")
                else:
                    self.log_fn(f"# {reason} — dropped (TTL exhausted)")

            hit = by_instance.get(w.instance_id)
            if hit is None:
                carry(f"interruption warning for unresolved instance "
                      f"{w.instance_id}")
                continue
            node, sink = hit
            name = node.get("metadata", {}).get("name", "")
            if not name or not sink.drain_node(name):
                carry(f"drain of {name or w.instance_id} failed")
                continue
            self._remember_drained(w.instance_id)
            drained += 1
            labels = node.get("metadata", {}).get("labels", {})
            zone = labels.get("topology.kubernetes.io/zone", "")
            pool = labels.get("karpenter.sh/nodepool", "")
            try:
                zi = zones.index(zone)
                pi = self.cfg.cluster.pool_index(pool)
            except (ValueError, ConfigError):
                # A freshly-registered node may not carry zone/pool labels
                # yet; decrementing an arbitrary cell would misattribute
                # the loss — skip the estimate adjustment (the drain
                # itself still happened; dynamics reconcile via demand).
                self.log_fn(f"# drained {name} but cannot attribute "
                            f"zone={zone!r} pool={pool!r} — estimate "
                            f"unchanged")
                continue
            new_nodes = self.state.nodes.at[pi, zi, CT_SPOT].add(-1.0)
            self.state = self.state._replace(
                nodes=jnp.maximum(new_nodes, 0.0))
        self._pending_warnings = next_pending
        return drained

    def _remember_drained(self, instance_id: str) -> None:
        """Bounded already-drained memory (dedupe across redeliveries)."""
        self._drained_instances[instance_id] = None
        while len(self._drained_instances) > 256:
            self._drained_instances.pop(
                next(iter(self._drained_instances)))

    # -- incident stamps (round 14; no-ops without an incident_log) --------

    def _stamp_giveup(self, outcome) -> None:
        """`actuation/reconcile.on_giveup` hook: one incident per
        give-up, keyed on the tick/region the apply loop is in."""
        self.incident_log.stamp(
            "reconcile_giveup", t=self._obs_tick,
            region=self._obs_region,
            diverged=list(outcome.diverged),
            retries=int(outcome.retries))

    # -- one tick ----------------------------------------------------------

    def tick(self, t: int) -> TickReport:
        from ccka_tpu.harness.telemetry import StageTimer

        self._obs_tick = t
        timer = StageTimer(self.tracer)
        # 1. scrape the latest signals (the 30s AMP pipeline analog).
        with timer.stage("scrape"):
            tick_trace = self.source.tick(t, seed=self.seed)
            exo = jax.tree.map(lambda x: x[0], exo_steps(tick_trace))
            is_peak = bool(float(exo.is_peak) > 0.5)

        # 1a. degraded-mode state machine (see __init__): classify this
        #     tick BEFORE deciding, on the source's staleness flag AND
        #     the previous tick's actuation-divergence streak (round 12:
        #     a reconciler give-up means the cluster is not accepting
        #     patches — hold the last intent instead of thrashing it,
        #     and after the threshold fall back to the simple rule
        #     profile a flaky edge is most likely to converge on).
        stale = bool(getattr(self.source, "last_scrape_stale", False))
        self._stale_streak = self._stale_streak + 1 if stale else 0
        streak = max(self._stale_streak, self._diverge_streak)
        prev_mode = self._degraded
        if streak == 0:
            self._degraded = "ok"
        elif (streak >= self.degraded_fallback_after
              or self._last_action is None):
            # No held action to trust yet → straight to the fallback.
            self._degraded = "fallback"
        else:
            self._degraded = "hold"
        if self._degraded != "ok":
            self.degraded_ticks_total += 1
        if prev_mode != self._degraded:
            self.log_fn(f"# degraded-mode: {prev_mode} -> "
                        f"{self._degraded} (stale streak "
                        f"{self._stale_streak}, diverge streak "
                        f"{self._diverge_streak})")
            if self._degraded == "fallback" and \
                    self.incident_log is not None:
                # The single-cluster analog of the service's lane
                # escalation: the loop stopped trusting fresh intent
                # entirely — an incident, not just a log line.
                self.incident_log.stamp(
                    "hold_fallback", t=t, prev_mode=prev_mode,
                    stale_streak=int(self._stale_streak),
                    diverge_streak=int(self._diverge_streak))

        # 1b. spot interruption warnings → cordon+drain BEFORE the decide,
        #     so displaced pods go Pending under the profile this tick is
        #     about to apply and Karpenter reprovisions under it (the
        #     response loop `settings.interruptionQueue=""` disabled,
        #     `05_karpenter.sh:136`).
        n_warnings = n_drained = 0
        if self.interruption_feed is not None:
            with timer.stage("interruptions"):
                warnings = self.interruption_feed.poll()
                n_warnings = len(warnings)
                # All fresh warnings pass through as-is (one instance can
                # carry both a rebalance and a terminate); carried-over
                # unresolved ones are re-offered unless a fresh warning
                # for the same instance supersedes them.
                fresh_ids = {w.instance_id for w in warnings}
                carried = [w for iid, (w, _t)
                           in self._pending_warnings.items()
                           if iid not in fresh_ids]
                batch = list(warnings) + carried
                if batch:
                    n_drained = self._drain_for_warnings(batch)

        # 2. decide. Receding-horizon backends periodically re-optimize
        #    against the source's forward-looking window (exact future for
        #    synthetic/replay, persistence forecast for live).
        with timer.stage("decide") as sp_decide:
            sp_decide.args["degraded"] = self._degraded
            if self._degraded == "hold":
                # Fresh-but-stale signals must not move the fleet: keep
                # the last action that was decided on measured data.
                action = self._last_action
            elif self._degraded == "fallback":
                # Rule policy on the clock-derived is_peak — sound with
                # every scrape down (its only signal input survives).
                action = self._fallback_policy.decide(self.state, exo,
                                                      jnp.int32(t))
            else:
                # Replans are skipped while degraded (a window forecast
                # anchored on stale measurements is garbage squared).
                # `_force_replan` re-plans once right after a snapshot
                # resume: receding-horizon plan state does not survive a
                # crash, so the first resumed decide must not execute a
                # stale segment of the dead process's plan.
                if self._replan_every and (
                        t % self._replan_every == 0 or self._force_replan):
                    self._force_replan = False
                    if self._forecaster is not None:
                        from ccka_tpu.forecast.base import planning_window
                        hist = self.source.history(t, self._hist_steps,
                                                   seed=self.seed)
                        window = planning_window(self._forecaster, hist,
                                                 self._horizon)
                    else:
                        window = self.source.forecast(t, self._horizon,
                                                      seed=self.seed)
                    self.backend.replan(self.state, window)
                action = self.backend.decide(self.state, exo, jnp.int32(t))
                self._last_action = action
            # Device fence: without it the stage times the dispatch, not
            # the decide (the VERDICT r5 weak-#2 footgun).
            sp_decide.fence(action)

        # 3. render: op mirrors the reference's profile split — peak uses
        #    op:add (demo_21:65), off-peak op:replace (demo_20:69). The
        #    global zone selection is split per region (one Karpenter per
        #    regional cluster); single-region topologies get one entry.
        with timer.stage("render"):
            per_region = render_region_nodepool_patches(
                action, self.cfg.cluster, op="add" if is_peak else "replace")

        # 4. apply through each region's RECONCILER (round 12): the
        #    one-shot apply became convergence — deadline-bounded retries
        #    with read-back verification per round, so a kubectl timeout
        #    or a dropped patch is re-applied instead of silently lost.
        #    With apply_hpa, the tick also realizes the HPA lever as
        #    actual HorizontalPodAutoscaler objects in the home region —
        #    the §2.3 capability the reference installed
        #    prometheus-adapter for but never created.
        with timer.stage("apply") as sp_apply:
            results = []
            tick_retries = tick_failures = diverged_pools = 0
            pools_converged = True
            for region, patches in per_region.items():
                self._obs_region = region
                outcome = self._reconcilers[region].converge(patches)
                results += outcome.results
                tick_retries += outcome.retries
                tick_failures += outcome.failures
                diverged_pools += len(outcome.diverged)
                pools_converged &= outcome.converged
            n_pool_results = len(results)
            if self.apply_hpa:
                from ccka_tpu.actuation.patches import render_hpa_manifests
                results += self.sink.apply_manifests(
                    render_hpa_manifests(action, self.cfg.cluster,
                                         self.cfg.workload,
                                         namespace=self.cfg.workload.namespace))
            if self.apply_keda:
                from ccka_tpu.actuation.patches import render_keda_scaledobject
                wl = self.cfg.workload
                results.append(self.sink.apply_manifest(
                    render_keda_scaledobject(
                        action, wl.sqs_queue_name, wl.aws_account_id,
                        namespace=wl.namespace,
                        region=self.cfg.cluster.region)))
            applied = all(r.ok for r in results)
            fallbacks = sum(1 for r in results if r.used_fallback)
            self.reconcile_retries_total += tick_retries
            # Manifest (HPA/KEDA) failures only: the reconciler's own
            # failed applies are already inside outcome.failures, so
            # counting the pool results again would double-book them.
            tick_failures += sum(
                1 for r in results[n_pool_results:] if not r.ok)
            self.actuation_failures_total += tick_failures
            sp_apply.args["retries"] = tick_retries
            sp_apply.args["diverged"] = diverged_pools

        # 5. verify: the reconciler already read back every pool against
        #    the rendered intent (actuation/reconcile.verify_pool — ONE
        #    definition of converged); a verified tick is one where every
        #    pool converged AND every manifest applied. A give-up feeds
        #    the degraded-mode streak the NEXT tick classifies on.
        with timer.stage("verify"):
            verified = applied and pools_converged
            self._diverge_streak = (0 if pools_converged
                                    else self._diverge_streak + 1)
            if verified:
                self._last_verified_desired = {
                    region: {ps.pool: {
                        "consolidationPolicy": ps.disruption_merge["spec"]
                        ["disruption"]["consolidationPolicy"],
                        "requirements": {
                            r["key"]: r["values"]
                            for r in ps.requirements_json[0]["value"]},
                    } for ps in patches}
                    for region, patches in per_region.items()}

        # 6. advance the model-based state estimate (expectation dynamics;
        #    with workload families enabled, the per-family queue track
        #    advances in the same fused step).
        with timer.stage("estimate") as sp_est:
            self.key, sub = jax.random.split(self.key)
            state_pre = self.state
            wl_state_pre = (self._wl_state if self._wl_steps is not None
                            else None)
            w = None
            if self._wl_steps is not None:
                w = jax.tree.map(lambda x: x[t % self._wl_horizon],
                                 self._wl_steps)
                self.state, metrics, self._wl_state = self._step_wl(
                    self.state, self._wl_state, action, exo, w, sub)
            else:
                self.state, metrics = self._step(self.state, action, exo,
                                                 sub)
            # Fence on the step outputs: the report pulls these to host
            # floats below anyway, so the estimate stage must carry the
            # device time, not leak it into whatever blocks first.
            sp_est.fence((self.state, metrics))
        if self._wl_steps is not None:
            self.inference_slo_violations_total += float(
                metrics.inf_slo_violation)
            self.batch_deadline_misses_total += float(
                metrics.batch_deadline_miss)

        # 6a. decision provenance (round 18; no-op without a ledger):
        #     the rule shadow stepped on the SAME pre-step state,
        #     observed exo and key — strictly after this tick's real
        #     decide/apply/estimate, so recording can never steer them.
        if self.decision_ledger is not None:
            self._observe_decision(t, action, exo, metrics, state_pre,
                                   wl_state_pre, w, sub, stale)

        # 7. measured app-level SLO metrics, when the source scrapes them
        #    (live Prometheus p95/RPS/queue depth; {} for sources without
        #    an app-metrics path). Timed as its own stage: on a slow
        #    endpoint these three blocking queries are the tick's dominant
        #    cost and must show up in timings_ms.
        with timer.stage("slo_scrape"):
            slo_metrics = self.source.slo_snapshot()

        dt_hr = float(self.params.dt_s) / 3600.0
        profile = ""
        if self._degraded == "fallback":
            profile = ("degraded-fallback:"
                       + self._fallback_policy.profile_name(is_peak))
        elif self._degraded == "hold":
            profile = "degraded-hold"
        elif hasattr(self.backend, "profile_name"):
            profile = self.backend.profile_name(is_peak)
        # Tick-rate KPIs (same formulas as EpisodeSummary, one-tick window;
        # requests clamp at raw demand exactly like the simulator does).
        effective = float(np.minimum(np.asarray(metrics.served_pods),
                                     np.asarray(metrics.demand_pods)).sum())
        kreq = effective * float(self.params.rps_per_pod) \
            * float(self.params.dt_s) / 1000.0
        served_total = float(np.asarray(metrics.served_pods).sum())
        capacity = ((float(np.asarray(metrics.nodes_by_ct).sum())
                     + float(self.params.base_od_nodes))
                    * float(self.params.pods_per_node))
        report = TickReport(
            t=t,
            is_peak=is_peak,
            profile=profile or self.backend.name,
            applied=applied,
            verified=verified,
            fallbacks=fallbacks,
            cost_usd_hr=float(metrics.cost_usd) / dt_hr,
            carbon_g_hr=float(metrics.carbon_g) / dt_hr,
            nodes_spot=float(metrics.nodes_by_ct[0]),
            nodes_od=float(metrics.nodes_by_ct[1]),
            pending_pods=float(np.asarray(metrics.pending_pods).sum()),
            slo_ok=bool(float(metrics.slo_ok) > 0.5),
            detail="; ".join(r.detail for r in results if r.detail)[:500],
            latency_p95_ms=float(metrics.latency_p95_ms),
            usd_per_kreq=float(metrics.cost_usd) / max(kreq, 1e-9),
            g_co2_per_kreq=float(metrics.carbon_g) / max(kreq, 1e-9),
            waste_frac=max(capacity - served_total, 0.0) / max(capacity,
                                                               1e-9),
            interruption_warnings=n_warnings,
            nodes_drained=n_drained,
            slo_metrics=slo_metrics,
            timings_ms=timer.timings_ms(),
            signal_stale=stale,
            degraded=self._degraded,
            degraded_level={"ok": 0, "hold": 1,
                            "fallback": 2}[self._degraded],
            degraded_ticks_total=self.degraded_ticks_total,
            denied_nodes=float(metrics.denied_nodes),
            delayed_nodes=float(metrics.delayed_nodes),
            inference_queue_depth=float(metrics.inf_queue_depth),
            batch_backlog=float(metrics.batch_backlog),
            inference_slo_violations_total=(
                self.inference_slo_violations_total),
            batch_deadline_misses_total=self.batch_deadline_misses_total,
            reconcile_retries=tick_retries,
            reconcile_retries_total=self.reconcile_retries_total,
            reconcile_diverged=diverged_pools,
            actuation_failures=tick_failures,
            actuation_failures_total=self.actuation_failures_total,
            resumes_total=self.resumes_total,
        )
        # 8. durable snapshot (harness/snapshot.py; "" disables): written
        #    at the END of the tick with next_tick=t+1, atomically, so a
        #    kill at any point resumes at the last completed boundary.
        if self.snapshot_path:
            if t % self.snapshot_every == 0:
                self.write_snapshot(t + 1)
                self._last_snapshot_tick = t
            report.snapshot_age_ticks = (
                t - self._last_snapshot_tick
                if self._last_snapshot_tick is not None else t + 1)
        self.log_fn(report.to_json())
        if self.telemetry is not None:
            self.telemetry.write(dataclasses.asdict(report))
        if self.exporter is not None:
            self.exporter.update(report)
        return report

    # -- decision provenance (round 18; obs/decisions.py) -------------------

    def _observe_decision(self, t: int, action, exo, metrics, state_pre,
                          wl_state_pre, w, sub, stale: bool) -> None:
        """One ledger row: the chosen step's metrics vs the rule
        shadow's on identical inputs (same pre-step state, same
        observed exo, same key, same compiled step — no new compile).
        The degraded machine maps onto the service's decision lanes:
        ok→fresh, hold→hold, fallback→fallback (a fallback tick's
        divergence is 0 by construction — the chosen action IS the
        rule's)."""
        lane = {"ok": "fresh", "hold": "hold",
                "fallback": "fallback"}[self._degraded]
        shadow_action = self._fallback_policy.decide(state_pre, exo,
                                                     jnp.int32(t))
        if self._wl_steps is not None:
            _s, sh_metrics, _ws = self._step_wl(
                state_pre, wl_state_pre, shadow_action, exo, w, sub)
        else:
            _s, sh_metrics = self._step(state_pre, shadow_action, exo,
                                        sub)

        def decomp(m) -> dict:
            pend = np.maximum(np.asarray(m.demand_pods)
                              - np.asarray(m.served_pods), 0.0)
            return {"cost_usd": float(m.cost_usd),
                    "carbon_g": float(m.carbon_g),
                    "pend_c0": float(pend[0]),
                    "pend_c1": float(pend[1]),
                    "slo_ok": float(m.slo_ok)}

        def flat(a) -> np.ndarray:
            return np.concatenate(
                [np.asarray(leaf, np.float64).reshape(-1) for leaf in a])

        surfaces = self.decision_ledger.observe_single(
            t, lane=lane, action=flat(action),
            shadow_action=flat(shadow_action),
            exo={
                "spot_price_hr": float(
                    np.asarray(exo.spot_price_hr).mean()),
                "od_price_hr": float(np.asarray(exo.od_price_hr).mean()),
                "carbon_g_kwh": float(
                    np.asarray(exo.carbon_g_kwh).mean()),
                "demand_pods": float(np.asarray(exo.demand_pods).sum()),
                "is_peak": bool(float(exo.is_peak) > 0.5),
                "stale": bool(stale),
            },
            state={"nodes_spot": float(metrics.nodes_by_ct[0]),
                   "nodes_od": float(metrics.nodes_by_ct[1])},
            chosen=decomp(metrics), shadow=decomp(sh_metrics))
        # A windowed divergence spike is an incident here exactly as on
        # the service path (the trigger vocabulary promises it without
        # scoping to the fleet): one edge-triggered stamp, re-armed
        # below the bar. No-op without an incident log.
        spike = surfaces.get("spike")
        if spike is not None and self.incident_log is not None:
            self.incident_log.stamp("policy_divergence", t=t, **spike)

    # -- durable snapshot / resume (ARCHITECTURE §14) -----------------------

    def snapshot_body(self, next_tick: int) -> dict:
        """Everything a fresh process needs to continue this run bitwise:
        tick index, PRNG key data (the (split) key path), the state
        estimate, the degraded-mode machine, session counters, and the
        last applied+verified desired state (the audit record that makes
        re-applying after a mid-tick kill provably idempotent)."""
        from ccka_tpu.harness import snapshot as snap

        body: dict = {
            "kind": "controller",
            "next_tick": int(next_tick),
            "seed": int(self.seed),
            "backend": getattr(self.backend, "name",
                               type(self.backend).__name__),
            "config_sha256": snap.config_digest(self.cfg),
            "prng_key": snap.encode_key(self.key),
            "state": snap.encode_tree(self.state),
            "degraded": self._degraded,
            "stale_streak": int(self._stale_streak),
            "diverge_streak": int(self._diverge_streak),
            "degraded_ticks_total": int(self.degraded_ticks_total),
            "reconcile_retries_total": int(self.reconcile_retries_total),
            "actuation_failures_total": int(self.actuation_failures_total),
            "resumes_total": int(self.resumes_total),
            "drained_instances": list(self._drained_instances),
            # Carried-over unresolved interruption warnings: the SQS ack
            # happened at poll time, so this buffer is the warning's ONLY
            # memory — losing it across a crash would waste the 2-minute
            # notice (the drained-instances sibling above has the same
            # property for dedupe).
            "pending_warnings": [
                {"instance_id": w.instance_id, "action": w.action,
                 "detail_type": w.detail_type, "region": w.region,
                 "ttl": int(ttl)}
                for w, ttl in self._pending_warnings.values()],
            "desired": self._last_verified_desired,
            "last_action": (snap.encode_tree(self._last_action)
                            if self._last_action is not None else None),
            "wl": None,
        }
        # Receding-horizon backend plan state (MPCBackend._plan): with it
        # in the snapshot, a resumed MPC run continues executing the SAME
        # optimized plan at the same cadence — bitwise, like the
        # stateless-decide backends.
        plan = getattr(self.backend, "_plan", None)
        if plan is not None:
            body["backend_plan"] = snap.encode_tree(plan)
            body["backend_plan_age"] = int(
                getattr(self.backend, "_plan_age", 0))
        if self._wl_steps is not None:
            body["wl"] = {
                "state": snap.encode_tree(self._wl_state),
                "anchor_unix_s": float(self._wl_anchor),
                "inference_slo_violations_total": float(
                    self.inference_slo_violations_total),
                "batch_deadline_misses_total": float(
                    self.batch_deadline_misses_total),
            }
        return body

    def write_snapshot(self, next_tick: int) -> str:
        from ccka_tpu.harness.snapshot import save_snapshot
        return save_snapshot(self.snapshot_path, self.snapshot_body(
            next_tick))

    def restore(self, body: dict) -> int:
        """Restore from a snapshot body (`snapshot.load_snapshot`);
        returns the tick to resume at. Refuses identity mismatches —
        config, backend, seed — loudly: resuming another run's snapshot
        would not crash, it would silently corrupt the estimate."""
        from ccka_tpu.harness import snapshot as snap

        if body.get("kind") != "controller":
            raise snap.SnapshotError(
                f"snapshot kind {body.get('kind')!r} is not a controller "
                "snapshot")
        digest = snap.config_digest(self.cfg)
        if body.get("config_sha256") != digest:
            raise snap.SnapshotError(
                "snapshot was taken under a different config "
                f"(stored {body.get('config_sha256', '')[:12]}…, running "
                f"{digest[:12]}…) — resuming across configs would corrupt "
                "the state estimate; rerun with the original config")
        want_backend = getattr(self.backend, "name",
                               type(self.backend).__name__)
        if body.get("backend") != want_backend:
            raise snap.SnapshotError(
                f"snapshot was taken with backend {body.get('backend')!r}, "
                f"this controller runs {want_backend!r} — the decision "
                "stream would silently change policy mid-run")
        if int(body.get("seed", -1)) != int(self.seed):
            raise snap.SnapshotError(
                f"snapshot seed {body.get('seed')} != controller seed "
                f"{self.seed} — the PRNG path would fork")
        self.key = snap.decode_key(body["prng_key"])
        self.state = snap.decode_like(self.state, body["state"])
        la = body.get("last_action")
        template = Action.neutral(self.cfg.cluster.n_pools,
                                  self.cfg.cluster.n_zones)
        self._last_action = (snap.decode_like(template, la)
                             if la is not None else None)
        self._degraded = body.get("degraded", "ok")
        self._stale_streak = int(body.get("stale_streak", 0))
        self._diverge_streak = int(body.get("diverge_streak", 0))
        self.degraded_ticks_total = int(body.get("degraded_ticks_total", 0))
        self.reconcile_retries_total = int(
            body.get("reconcile_retries_total", 0))
        self.actuation_failures_total = int(
            body.get("actuation_failures_total", 0))
        self.resumes_total = int(body.get("resumes_total", 0)) + 1
        self._drained_instances = dict.fromkeys(
            body.get("drained_instances", []))
        if body.get("pending_warnings"):
            from ccka_tpu.signals.live import InterruptionWarning
            self._pending_warnings = {
                rec["instance_id"]: (
                    InterruptionWarning(rec["instance_id"], rec["action"],
                                        rec["detail_type"],
                                        rec.get("region", "")),
                    int(rec["ttl"]))
                for rec in body["pending_warnings"]}
        self._last_verified_desired = body.get("desired", {})
        wl = body.get("wl")
        if wl is not None and self._wl_steps is not None:
            self._wl_state = snap.decode_like(self._wl_state, wl["state"])
            self.inference_slo_violations_total = float(
                wl["inference_slo_violations_total"])
            self.batch_deadline_misses_total = float(
                wl["batch_deadline_misses_total"])
            if wl["anchor_unix_s"] != self._wl_anchor:
                # Re-sample the arrival track on the ORIGINAL clock
                # anchor: a live resume must not re-phase the diurnal
                # arrivals to its own (later) start time.
                from ccka_tpu.workloads.process import (
                    WORKLOAD_KEY_TAG, sample_workload_steps)
                self._wl_anchor = float(wl["anchor_unix_s"])
                self._wl_steps = sample_workload_steps(
                    self._wl_cfg,
                    jax.random.key(self.seed ^ WORKLOAD_KEY_TAG),
                    self._wl_horizon, self.cfg.cluster.n_zones,
                    dt_s=self.cfg.sim.dt_s,
                    start_unix_s=self._wl_anchor)
        next_tick = int(body["next_tick"])
        self._last_snapshot_tick = next_tick - 1
        # Receding-horizon plan state: restored from the snapshot when
        # the backend carries it (resume stays bitwise — the plan and
        # its replan cadence both survive); only a snapshot from before
        # plan-state capture falls back to an immediate replan, so the
        # first resumed decide never executes a plan that died with the
        # old process.
        bp = body.get("backend_plan")
        if bp is not None and getattr(self.backend, "_plan",
                                      None) is not None:
            self.backend._plan = snap.decode_like(self.backend._plan, bp)
            if hasattr(self.backend, "_plan_age"):
                self.backend._plan_age = int(
                    body.get("backend_plan_age", 0))
            self._force_replan = False
        else:
            self._force_replan = bool(self._replan_every)
        self.log_fn(f"# resumed from snapshot at tick {next_tick} "
                    f"(resume #{self.resumes_total})")
        return next_tick

    # -- the loop ----------------------------------------------------------

    def run(self, ticks: int | None = None,
            start_tick: int = 0) -> list[TickReport]:
        """Drive the loop for ``ticks`` iterations (None = forever).

        Sleeps ``interval_s`` between ticks — the operator cadence the
        reference left to a human. Returns the collected reports (for a
        bounded run; an unbounded run only logs).
        """
        reports: list[TickReport] = []
        t = start_tick
        while ticks is None or t < start_tick + ticks:
            report = self.tick(t)
            if ticks is not None:  # unbounded daemons only log (no
                reports.append(report)  # unbounded in-memory accumulation)
            t += 1
            more = ticks is None or t < start_tick + ticks
            if more and self.interval_s > 0:
                self.sleep_fn(self.interval_s)
        return reports

    def close(self) -> None:
        """Release the telemetry writer. Owned by the *controller's* owner,
        not by run(): resumed runs (``run(start_tick=...)``) and direct
        tick() calls must keep appending — every write is flushed, so an
        unclosed writer loses nothing on process exit."""
        if self.telemetry is not None:
            self.telemetry.close()
            self.telemetry = None
        if self._lock is not None:
            self._lock.release()
            self._lock = None


def controller_from_config(cfg: FrameworkConfig, backend: PolicyBackend,
                           *, live: bool = False,
                           runner=None, region_runners=None,
                           interruption_runner=None,
                           **kwargs) -> Controller:
    """Wire a controller with the configured signal source and a sink:
    DryRunSink by default, KubectlSink with ``live=True`` (runner
    injectable for tests).

    Live multi-region requires a kubectl path per region: either
    ``region_runners`` (``{region_name: runner}``, tests) or
    ``RegionSpec.kube_context`` set on every region (operators — the CLI
    reaches this via config). Sharing one context would apply both regions'
    NodePool patches (same pool names, different zone sets) to ONE cluster
    each tick — requirements ping-ponging that only surfaces at verify
    time — so that wiring is refused outright, like the controller's
    ``--keda`` config gate.
    """
    from ccka_tpu.actuation.sink import (DryRunSink, KubectlSink,
                                         context_runner)
    from ccka_tpu.signals.live import make_signal_source

    source = make_signal_source(cfg.cluster, cfg.workload, cfg.sim,
                                cfg.signals, faults=cfg.faults,
                                workloads=cfg.workloads)

    # Spot interruption feed: configured queue URL enables it (live AWS
    # CLI transport by default; tests inject interruption_runner).
    if cfg.signals.interruption_queue_url and "interruption_feed" not in kwargs:
        from ccka_tpu.signals.live import SpotInterruptionFeed
        kwargs["interruption_feed"] = SpotInterruptionFeed(
            cfg.signals.interruption_queue_url, region=cfg.cluster.region,
            runner=interruption_runner)

    chaos_on = cfg.chaos.enabled and (
        cfg.chaos.timeout_prob + cfg.chaos.transient_exit_prob
        + cfg.chaos.drop_prob + cfg.chaos.rewrite_prob) > 0.0
    if chaos_on and live:
        raise ValueError(
            "chaos injection (cfg.chaos) is a dry-run recovery-harness "
            "tool; injecting failures into a live kubectl path would "
            "fight a real cluster — drop --live or disable chaos")

    def wrap(s, idx=0):
        if not chaos_on:
            return s
        from ccka_tpu.actuation.chaos import ChaosSink
        # Per-region seed derivation (the fleet's per-sink idiom): one
        # shared seed would draw IDENTICAL fate sequences in every
        # region — region-asymmetric failure, the case the per-region
        # reconciler + divergence streak exist for, would never occur.
        return ChaosSink(s, cfg.chaos,
                         seed=kwargs.get("seed", 0) ^ (0xC4A05 + idx))

    if cfg.cluster.regions:
        # One sink per regional cluster.
        if live:
            runners = dict(region_runners or {})
            for r in cfg.cluster.regions:
                if r.name not in runners and r.kube_context:
                    runners[r.name] = context_runner(r.kube_context)
            missing = [r.name for r in cfg.cluster.regions
                       if r.name not in runners]
            if missing:
                raise ValueError(
                    "live multi-region controller requires one kubectl "
                    f"runner per region; missing for {missing}. Set "
                    "RegionSpec.kube_context on every region (e.g. "
                    'CCKA_CLUSTER_REGIONS=\'[{"name": ..., '
                    '"kube_context": ...}]\') or pass region_runners= — a '
                    "shared kube-context would ping-pong the same "
                    "NodePools between the regions' zone sets every tick.")
            sink = {r.name: KubectlSink(runners[r.name])
                    for r in cfg.cluster.regions}
        else:
            sink = {r.name: wrap(DryRunSink(), i)
                    for i, r in enumerate(cfg.cluster.regions)}
    else:
        if live:
            sink = KubectlSink(runner) if runner else KubectlSink()
        else:
            sink = wrap(DryRunSink())
    return Controller(cfg, backend, source, sink, **kwargs)
