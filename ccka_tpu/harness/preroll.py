"""Preroll assertion gate — `demo_18_preroll_check.sh` as a component.

The reference asserts, each with exit-1 + remediation hint (`:23-81`):
namespace exists, zero leftover burst workloads, NodePools in the neutral
profile, dashboard ports free, and the Karpenter node role mapped in
aws-auth. The framework analog checks the pieces *this* stack depends on,
in two tiers:

- always: config validity, JAX backend present, simulator compiles a step,
  signal source produces a sane tick;
- --live additionally: both NodePools exist and are neutral
  (`demo_18:42-55`), zero leftover burst workloads (`demo_18:30-39`), the
  Karpenter node role is mapped in aws-auth (`demo_18:67-81`), and the
  operator dashboard ports are free (`demo_18:58-65` — a stale
  port-forward squatting 3000/8005/9090 breaks the observe session).

Each check returns (ok, detail) and the runner prints a pass/fail table —
the same contract as the bash gate, machine-checkable from pytest.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

from ccka_tpu.config import ConfigError, FrameworkConfig


@dataclass
class PrerollCheck:
    name: str
    ok: bool
    detail: str = ""
    hint: str = ""


def check_config(cfg: FrameworkConfig) -> PrerollCheck:
    try:
        cfg.validate()
        return PrerollCheck("config-valid", True)
    except ConfigError as e:
        return PrerollCheck("config-valid", False, str(e),
                            hint="fix the flagged field or CCKA_* override")


def check_jax_backend() -> PrerollCheck:
    try:
        import jax
        devices = jax.devices()
        kinds = {d.platform for d in devices}
        return PrerollCheck("jax-backend", True,
                            f"{len(devices)} device(s): {sorted(kinds)}")
    except Exception as e:  # noqa: BLE001 — any backend failure blocks
        return PrerollCheck("jax-backend", False, str(e),
                            hint="check JAX_PLATFORMS / TPU runtime")


def check_simulator_compiles(cfg: FrameworkConfig) -> PrerollCheck:
    try:
        import jax

        from ccka_tpu.policy.rule import neutral_action
        from ccka_tpu.sim import SimParams, initial_state, rollout
        from ccka_tpu.signals import SyntheticSignalSource

        params = SimParams.from_config(cfg)
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        tr = src.trace(4)
        act = neutral_action(cfg.cluster)
        final, _ = jax.jit(
            lambda s, k: rollout(params, s, lambda st, e, t: act, tr, k)
        )(initial_state(cfg), jax.random.key(0))
        jax.block_until_ready(final)
        return PrerollCheck("simulator-compiles", True)
    except Exception as e:  # noqa: BLE001
        return PrerollCheck("simulator-compiles", False, repr(e)[:300],
                            hint="simulator/XLA regression — run pytest tests/test_sim.py")


def check_signals(cfg: FrameworkConfig) -> PrerollCheck:
    try:
        import numpy as np

        from ccka_tpu.signals.live import make_signal_source
        src = make_signal_source(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals, faults=cfg.faults,
                                 workloads=cfg.workloads)
        tick = src.tick(0)
        arr = np.asarray(tick.carbon_g_kwh)
        if not np.all(np.isfinite(arr)) or np.any(arr <= 0):
            return PrerollCheck("signals-sane", False,
                                f"carbon tick {arr.tolist()}",
                                hint="check signal backend configuration")
        return PrerollCheck("signals-sane", True,
                            f"backend={cfg.signals.backend}")
    except Exception as e:  # noqa: BLE001
        return PrerollCheck("signals-sane", False, repr(e)[:300],
                            hint="check signals.* config / endpoints")


def _local_ports(cfg: FrameworkConfig) -> list[int]:
    """Ports the observe session will port-forward onto this host — derived
    from the SAME tunnel plan `ccka watch` opens (`harness.watch.
    watch_plan`), so the preroll port gate can never drift from the
    session it protects (the framework analog of demo_18's hardcoded
    3000/8005/9090 list)."""
    from ccka_tpu.harness.watch import watch_plan

    return sorted({fw.local_port for fw in watch_plan(cfg)})


def check_ports_free(cfg: FrameworkConfig,
                     ports: Sequence[int] | None = None) -> list[PrerollCheck]:
    """Dashboard ports are bindable (`demo_18_preroll_check.sh:58-65`).

    A port already bound almost always means a stale `kubectl port-forward`
    from a previous observe session — the reference's remediation (kill the
    PF, `demo_19_reset_policies.sh:39-55`) is the hint here.
    """
    import socket

    out = []
    for port in (ports if ports is not None else _local_ports(cfg)):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.bind(("127.0.0.1", port))
            out.append(PrerollCheck(f"port-{port}-free", True))
        except OSError as e:
            out.append(PrerollCheck(
                f"port-{port}-free", False, str(e),
                hint="stale port-forward? run `ccka reset` or kill the "
                     f"process listening on {port} (demo_19:39-55)"))
        finally:
            s.close()
    return out


def check_nodepools_live(cfg: FrameworkConfig, runner) -> list[PrerollCheck]:
    """Live-cluster checks (demo_18:42-55): pools exist and are neutral."""
    out = []
    for pool in cfg.cluster.pools:
        rc, got = runner(["kubectl", "get", "nodepool", pool.name, "-o",
                          "jsonpath={.spec.disruption.consolidationPolicy}"])
        if rc != 0:
            out.append(PrerollCheck(f"nodepool-{pool.name}", False, got,
                                    hint="create the NodePool (ccka bootstrap)"))
        elif got.strip() not in ("WhenEmpty", ""):
            out.append(PrerollCheck(
                f"nodepool-{pool.name}", False,
                f"consolidationPolicy={got.strip()!r} not neutral",
                hint="run `ccka reset` first (demo_19 analog)"))
        else:
            out.append(PrerollCheck(f"nodepool-{pool.name}", True))
    return out


def check_no_leftover_burst(cfg: FrameworkConfig, runner) -> PrerollCheck:
    """Zero leftover burst workloads (demo_18:30-39) — a stale burst set
    would contaminate the scale-out the new run is about to measure."""
    from ccka_tpu.actuation.burst import BURST_GROUP
    ns = cfg.workload.namespace
    rc, got = runner(["kubectl", "get", "deploy", "-n", ns,
                      "-l", f"group={BURST_GROUP}", "-o", "name"])
    if rc != 0:
        # A missing namespace is genuinely clean; any other kubectl failure
        # (no binary, unreachable API server) must fail the gate — "can't
        # see the cluster" is not "the cluster is clean".
        if "NotFound" in got:
            return PrerollCheck("no-leftover-burst", True,
                                "namespace absent")
        return PrerollCheck("no-leftover-burst", False, got[:200],
                            hint="kubectl unreachable — fix cluster access")
    leftovers = [ln for ln in got.strip().splitlines() if ln.strip()]
    if leftovers:
        return PrerollCheck("no-leftover-burst", False,
                            f"{len(leftovers)} burst deployment(s) present",
                            hint="run `ccka burst --delete` (demo_50 subset)")
    return PrerollCheck("no-leftover-burst", True)


def check_aws_auth(cfg: FrameworkConfig, runner) -> PrerollCheck:
    """Karpenter node role mapped in aws-auth (demo_18:67-81) — without it
    provisioned nodes never join and every burst pod stays Pending."""
    from ccka_tpu.actuation.bootstrap import karpenter_node_role, role_mapped
    role = karpenter_node_role(cfg.cluster)
    rc, got = runner(["kubectl", "get", "configmap", "aws-auth",
                      "-n", "kube-system",
                      "-o", "jsonpath={.data.mapRoles}"])
    if rc != 0:
        return PrerollCheck("aws-auth-mapping", False, got[:200],
                            hint="is this an EKS cluster with kubectl access?")
    # Shared matcher with ensure_node_role_mapping: exact rolearn entries
    # only (no prefix collisions, no username/groups false positives).
    if not role_mapped(got, role_name=role):
        return PrerollCheck("aws-auth-mapping", False,
                            f"{role} not in mapRoles",
                            hint="run `ccka map-nodes --account-id ...` "
                                 "(demo_15 analog)")
    return PrerollCheck("aws-auth-mapping", True)


def run_preroll(cfg: FrameworkConfig, *, live: bool = False,
                runner=None, echo: bool = True) -> int:
    """Run all checks; returns 0 iff all pass (exit-code contract of
    demo_18_preroll_check.sh)."""
    checks: list[PrerollCheck] = [
        check_config(cfg),
        check_jax_backend(),
        check_simulator_compiles(cfg),
        check_signals(cfg),
    ]
    if live:
        from ccka_tpu.actuation.sink import _subprocess_runner
        r = runner or _subprocess_runner
        checks.extend(check_nodepools_live(cfg, r))
        checks.append(check_no_leftover_burst(cfg, r))
        checks.append(check_aws_auth(cfg, r))
        checks.extend(check_ports_free(cfg))

    ok = True
    for c in checks:
        ok &= c.ok
        if echo:
            mark = "PASS" if c.ok else "FAIL"
            line = f"[{mark}] {c.name}"
            if c.detail:
                line += f" — {c.detail}"
            if not c.ok and c.hint:
                line += f"  (hint: {c.hint})"
            print(line)
    if echo:
        print(f"[{'ok' if ok else 'err'}] preroll "
              f"{'passed' if ok else 'FAILED'}")
    return 0 if ok else 1
