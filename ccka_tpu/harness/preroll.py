"""Preroll assertion gate — `demo_18_preroll_check.sh` as a component.

The reference asserts, each with exit-1 + remediation hint (`:23-81`):
namespace exists, zero leftover burst workloads, NodePools in the neutral
profile, dashboard ports free, and the Karpenter node role mapped in
aws-auth. The framework analog checks the pieces *this* stack depends on,
in two tiers:

- always: config validity, JAX backend present, simulator compiles a step,
  signal source produces a sane tick;
- --live additionally: kubectl reachable, both NodePools exist, NodePools
  currently neutral (consolidationPolicy WhenEmpty, `demo_18:42-55`).

Each check returns (ok, detail) and the runner prints a pass/fail table —
the same contract as the bash gate, machine-checkable from pytest.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

from ccka_tpu.config import ConfigError, FrameworkConfig


@dataclass
class PrerollCheck:
    name: str
    ok: bool
    detail: str = ""
    hint: str = ""


def check_config(cfg: FrameworkConfig) -> PrerollCheck:
    try:
        cfg.validate()
        return PrerollCheck("config-valid", True)
    except ConfigError as e:
        return PrerollCheck("config-valid", False, str(e),
                            hint="fix the flagged field or CCKA_* override")


def check_jax_backend() -> PrerollCheck:
    try:
        import jax
        devices = jax.devices()
        kinds = {d.platform for d in devices}
        return PrerollCheck("jax-backend", True,
                            f"{len(devices)} device(s): {sorted(kinds)}")
    except Exception as e:  # noqa: BLE001 — any backend failure blocks
        return PrerollCheck("jax-backend", False, str(e),
                            hint="check JAX_PLATFORMS / TPU runtime")


def check_simulator_compiles(cfg: FrameworkConfig) -> PrerollCheck:
    try:
        import jax

        from ccka_tpu.policy.rule import neutral_action
        from ccka_tpu.sim import SimParams, initial_state, rollout
        from ccka_tpu.signals import SyntheticSignalSource

        params = SimParams.from_config(cfg)
        src = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                    cfg.signals)
        tr = src.trace(4)
        act = neutral_action(cfg.cluster)
        final, _ = jax.jit(
            lambda s, k: rollout(params, s, lambda st, e, t: act, tr, k)
        )(initial_state(cfg), jax.random.key(0))
        jax.block_until_ready(final)
        return PrerollCheck("simulator-compiles", True)
    except Exception as e:  # noqa: BLE001
        return PrerollCheck("simulator-compiles", False, repr(e)[:300],
                            hint="simulator/XLA regression — run pytest tests/test_sim.py")


def check_signals(cfg: FrameworkConfig) -> PrerollCheck:
    try:
        import numpy as np

        from ccka_tpu.signals.live import make_signal_source
        src = make_signal_source(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals)
        tick = src.tick(0)
        arr = np.asarray(tick.carbon_g_kwh)
        if not np.all(np.isfinite(arr)) or np.any(arr <= 0):
            return PrerollCheck("signals-sane", False,
                                f"carbon tick {arr.tolist()}",
                                hint="check signal backend configuration")
        return PrerollCheck("signals-sane", True,
                            f"backend={cfg.signals.backend}")
    except Exception as e:  # noqa: BLE001
        return PrerollCheck("signals-sane", False, repr(e)[:300],
                            hint="check signals.* config / endpoints")


def check_nodepools_live(cfg: FrameworkConfig, runner) -> list[PrerollCheck]:
    """Live-cluster checks (demo_18:42-55): pools exist and are neutral."""
    out = []
    for pool in cfg.cluster.pools:
        rc, got = runner(["kubectl", "get", "nodepool", pool.name, "-o",
                          "jsonpath={.spec.disruption.consolidationPolicy}"])
        if rc != 0:
            out.append(PrerollCheck(f"nodepool-{pool.name}", False, got,
                                    hint="create the NodePool (ccka bootstrap)"))
        elif got.strip() not in ("WhenEmpty", ""):
            out.append(PrerollCheck(
                f"nodepool-{pool.name}", False,
                f"consolidationPolicy={got.strip()!r} not neutral",
                hint="run `ccka reset` first (demo_19 analog)"))
        else:
            out.append(PrerollCheck(f"nodepool-{pool.name}", True))
    return out


def run_preroll(cfg: FrameworkConfig, *, live: bool = False,
                runner=None, echo: bool = True) -> int:
    """Run all checks; returns 0 iff all pass (exit-code contract of
    demo_18_preroll_check.sh)."""
    checks: list[PrerollCheck] = [
        check_config(cfg),
        check_jax_backend(),
        check_simulator_compiles(cfg),
        check_signals(cfg),
    ]
    if live:
        from ccka_tpu.actuation.sink import _subprocess_runner
        checks.extend(check_nodepools_live(cfg, runner or _subprocess_runner))

    ok = True
    for c in checks:
        ok &= c.ok
        if echo:
            mark = "PASS" if c.ok else "FAIL"
            line = f"[{mark}] {c.name}"
            if c.detail:
                line += f" — {c.detail}"
            if not c.ok and c.hint:
                line += f"  (hint: {c.hint})"
            print(line)
    if echo:
        print(f"[{'ok' if ok else 'err'}] preroll "
              f"{'passed' if ok else 'FAILED'}")
    return 0 if ok else 1
