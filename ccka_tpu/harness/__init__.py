"""Demo/ops harness: preroll checks, paired configure/observe, lifecycle.

The reference's operational discipline (SURVEY.md §4) — preroll assertion
gates (`demo_18_preroll_check.sh`), paired `*_configure.sh`/`*_observe.sh`
stages, reset (`demo_19`) and cleanup (`demo_50`) — re-expressed as Python
components usable both as a pytest fixture layer and from the CLI.
"""

from ccka_tpu.harness.preroll import PrerollCheck, run_preroll  # noqa: F401
from ccka_tpu.harness.lifecycle import Stage, ConfigureObserve  # noqa: F401
from ccka_tpu.harness.controller import (  # noqa: F401
    Controller,
    TickReport,
    controller_from_config,
)
from ccka_tpu.harness.telemetry import (  # noqa: F401
    StageTimer,
    TelemetryWriter,
    profile_trace,
    read_telemetry,
    summarize_telemetry,
)
from ccka_tpu.harness.service import (  # noqa: F401
    CircuitBreaker,
    FleetService,
    ServiceTickReport,
    TENANT_PROFILES,
    fleet_service_from_config,
)
