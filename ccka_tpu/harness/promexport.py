"""Prometheus exposition of controller telemetry — the missing fabric half.

The reference's metrics fabric is scrape -> SigV4 remote-write -> AMP
(`06_opencost.sh:318-341`); the dashboards then query AMP. Round 2 shipped
the dashboards (`harness/dashboard.py`) and a durable JSONL stream
(`harness/telemetry.py`) but nothing *served* the `ccka_*` series the
panels query — on a live stack every panel was empty (VERDICT r2
missing #3). This module is the exposition side:

- :data:`SERIES` — the registry mapping every exported gauge to its
  TickReport field. The dashboard's panel expressions are written against
  exactly this vocabulary; `tests/test_telemetry.py` pins the parity both
  ways, so a panel can never reference an unexported series again.
- :func:`render_exposition` — Prometheus text format 0.0.4 for one tick.
- :class:`MetricsExporter` — holds the latest TickReport and publishes it:
  a `/metrics` HTTP endpoint (daemon thread, stdlib http.server — scrape
  target for any Prometheus/ADOT agent) and/or a node-exporter
  textfile-collector `.prom` file (written atomically each tick).

Gauges-not-counters: each tick fully re-states the fleet's instantaneous
rates (the controller's 30s cadence IS the scrape interval), matching how
kube-state-metrics — the reference's sole scrape target — models cluster
state.
"""

from __future__ import annotations

import os
import re
import tempfile
import threading
from dataclasses import asdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Mapping

# series name -> (TickReport field spec, help text). Booleans export as
# 0/1. A spec may be dotted into the per-phase timing dict the span
# tracer fills (`TickReport.timings_ms`): "timings_ms.a+b" sums the
# named phases, "timings_ms.*" sums them all — see `resolve_field`.
SERIES: dict[str, tuple[str, str]] = {
    "ccka_cost_usd_hr": ("cost_usd_hr", "Estimated fleet spend rate, $/hr"),
    "ccka_carbon_g_hr": ("carbon_g_hr", "Estimated emission rate, gCO2e/hr"),
    "ccka_slo_ok": ("slo_ok", "1 if this tick met the SLO gate, else 0"),
    "ccka_usd_per_kreq": ("usd_per_kreq", "Dollars per 1k served requests"),
    "ccka_g_co2_per_kreq": ("g_co2_per_kreq",
                            "gCO2e per 1k served requests"),
    "ccka_waste_frac": ("waste_frac",
                        "Unused fraction of fleet pod capacity"),
    "ccka_nodes_spot": ("nodes_spot", "Karpenter-owned spot nodes"),
    "ccka_nodes_od": ("nodes_od", "Karpenter-owned on-demand nodes"),
    "ccka_latency_p95_ms": ("latency_p95_ms",
                            "App p95 latency proxy, milliseconds"),
    "ccka_pending_pods": ("pending_pods", "Unschedulable pod backlog"),
    "ccka_is_peak": ("is_peak", "1 during configured peak hours"),
    "ccka_interruption_warnings": (
        "interruption_warnings",
        "Spot interruption/rebalance warnings consumed this tick"),
    "ccka_nodes_drained": (
        "nodes_drained", "Nodes cordoned+drained for interruption warnings"),
    # Degraded-mode + fault-event series (ccka_tpu/faults; ARCHITECTURE
    # §12): the controller's stale-signal state machine and the fault
    # model's provisioning counters, on the wire next to the KPIs they
    # explain. The _total counter is controller-cumulative (each tick
    # re-states the running total, kube-state-metrics style).
    "ccka_degraded": (
        "degraded_level",
        "Degraded-mode level: 0 ok, 1 hold-last-action, 2 rule-fallback"),
    "ccka_degraded_ticks_total": (
        "degraded_ticks_total",
        "Cumulative ticks spent in a degraded mode this session"),
    "ccka_signal_stale": (
        "signal_stale",
        "1 if this tick's signal scrape was stale (retries exhausted)"),
    "ccka_nodes_denied": (
        "denied_nodes",
        "Spot provisioning denied this tick (fault model), nodes"),
    "ccka_nodes_delayed": (
        "delayed_nodes",
        "Provisioning arrivals held back this tick (fault model), nodes"),
    # Workload-family series (ccka_tpu/workloads): the per-family queue
    # estimate and session-cumulative SLO accounting, next to the fleet
    # KPIs they trade against. The _total counters re-state the running
    # total each tick (kube-state-metrics style).
    "ccka_inference_queue_depth": (
        "inference_queue_depth",
        "Inference work queued after this tick (pod-equivalents)"),
    "ccka_inference_slo_violations_total": (
        "inference_slo_violations_total",
        "Cumulative inference SLO-violation ticks this session"),
    "ccka_batch_deadline_misses_total": (
        "batch_deadline_misses_total",
        "Cumulative batch work missing its deadline this session"),
    # Crash-safety series (round 12; ARCHITECTURE §14): the reconciler's
    # convergence counters, the actuation failure budget, and the
    # snapshot/resume health of the loop itself. The _total counters are
    # session-cumulative (kube-state-metrics style) and survive
    # snapshot/resume — a resumed controller re-states the dead one's
    # running totals instead of resetting the wire to zero.
    "ccka_reconcile_retries_total": (
        "reconcile_retries_total",
        "Cumulative reconciler re-apply attempts this session"),
    "ccka_reconcile_diverged": (
        "reconcile_diverged",
        "Pools still diverged from intent after this tick's "
        "reconciliation (0 = converged)"),
    "ccka_actuation_failures_total": (
        "actuation_failures_total",
        "Cumulative failed applies + failed read-backs this session"),
    "ccka_snapshot_age_ticks": (
        "snapshot_age_ticks",
        "Ticks since the last durable snapshot write (0 = fresh)"),
    "ccka_resumes_total": (
        "resumes_total",
        "Times this logical run was resumed from a snapshot"),
    # Multi-tenant service series (round 13; ARCHITECTURE §15): the
    # overload-control surfaces of `harness/service.py`. These resolve
    # from a ServiceTickReport (the fleet service's per-tick record);
    # single-cluster TickReports skip them. The breaker gauge sums the
    # per-tenant levels (0 closed, 1 half-open, 2 open) via the dotted
    # dict spec, so one number states the fleet's breaker pressure.
    "ccka_tenant_breaker_state": (
        "breaker_states.*",
        "Sum of per-tenant circuit-breaker levels "
        "(0 closed, 1 half-open, 2 open)"),
    "ccka_ticks_shed_total": (
        "sheds_total",
        "Cumulative tenant decides shed by admission backpressure "
        "this session"),
    "ccka_admission_queue_depth": (
        "admission_queue_depth",
        "Tenant decides wanting admission this tick (pre-cap)"),
    "ccka_tick_latency_ms": (
        "tick_latency_ms",
        "Service tick latency (admission+decide+fanout), milliseconds"),
    # Incident-grade obs series (round 14; `ccka_tpu/obs`): the SLO
    # burn-rate engine's fast window, the incident-active flag
    # (two-window burn OR a fresh trigger stamp), and the flight
    # recorder's session dump counter. Service-only: the fleet service
    # carries the burn engine; a single-cluster controller's scrape
    # legitimately omits them.
    "ccka_slo_burn_rate": (
        "slo_burn_rate",
        "Fast-window fleet SLO burn rate (violating tenant-ticks per "
        "tenant-tick)"),
    "ccka_incident_active": (
        "incident_active",
        "1 while the burn-rate engine is burning or an incident "
        "trigger fired within the fast window"),
    "ccka_recorder_dumps_total": (
        "recorder_dumps_total",
        "Cumulative checksummed flight-recorder dumps this session"),
    # Device-time observatory series (round 15; obs/costmodel +
    # obs/occupancy): the compile registry's dispatch counter and the
    # observatory's last published pipeline measurement — achieved
    # roofline fraction, kernel-stage occupancy, and the mesh's
    # max/mean shard imbalance. Service-only: the fleet service's obs
    # block fills them; a single-cluster controller's scrape
    # legitimately omits them, and absent measurements SKIP rather
    # than export fake zeros.
    "ccka_program_dispatches_total": (
        "program_dispatches_total",
        "Cumulative watched-program device dispatches this session"),
    "ccka_achieved_roofline_fraction": (
        "achieved_roofline_fraction",
        "Achieved fraction of the memory roofline for the last "
        "attributed kernel-stage measurement"),
    "ccka_pipeline_occupancy": (
        "pipeline_occupancy.kernel",
        "Kernel-stage fraction of the last measured packed-pipeline "
        "occupancy ledger"),
    "ccka_shard_imbalance": (
        "shard_imbalance",
        "Max/mean per-shard kernel time across the mesh "
        "(1.0 = perfectly balanced)"),
    # Decision-provenance series (round 18; obs/decisions.py): the
    # windowed shadow-disagreement rate, the cost-term share of the
    # fleet's step-objective attribution (dotted term spec into the
    # per-term share dict), and the tick's projected chosen-minus-
    # rule-shadow SLO delta. Service-only, and skipped (never fake
    # zeros) when the decision ledger is off.
    "ccka_policy_divergence_rate": (
        "policy_divergence_rate",
        "Fraction of decides whose action departed from the rule "
        "shadow beyond obs.divergence_threshold over the trailing "
        "obs.decision_window ticks"),
    "ccka_objective_term_share": (
        "objective_term_shares.cost",
        "Cost-term share of the fleet's per-tick objective "
        "attribution (terms sum to 1; carbon/SLO shares ride the "
        "same dict)"),
    "ccka_shadow_slo_delta": (
        "shadow_slo_delta",
        "Chosen-minus-rule-shadow SLO-ok tenant count this tick "
        "(projected on identical observed inputs)"),
    # Shadow-tournament series (round 20; obs/tournament.py): the
    # summed windowed win rate over every roster candidate (the
    # challenger-pressure gauge — 0 means nothing on the roster is
    # beating the primary anywhere) and the current board leader's
    # roster index. Service-only, skipped (never fake zeros) when no
    # tournament ledger runs.
    "ccka_policy_candidate_win_rate": (
        "candidate_win_rate.*",
        "Summed windowed win rate over the tournament roster's "
        "candidates vs the live primary (per-candidate and per-class "
        "splits ride the board JSONL)"),
    "ccka_tournament_leader": (
        "tournament_leader",
        "Roster index of the candidate currently leading the shadow "
        "tournament's windowed board"),
    # Geo-arbitrage series (ISSUE 16; regions/geo.py publish/read
    # snapshot): the mean applied inter-region migration rate of the
    # last geo rollout and the sum of the per-region carbon
    # intensities its lanes saw. Service-only, skipped (never fake
    # zeros) before any geo rollout has published.
    "ccka_region_migration_rate": (
        "region_migration_rate.mean",
        "Mean applied off-diagonal inter-region migration rate of the "
        "last published geo rollout (0 = no mass moving)"),
    "ccka_region_carbon_intensity": (
        "region_carbon_intensity.*",
        "Sum of per-region grid carbon intensities (g/kWh) the last "
        "published geo rollout's lanes saw"),
    # Fleet-scale host-loop series (round 21; the vectorized admission
    # machine): real host microseconds per tenant spent in the
    # admission + accounting windows (virtual scrape delays excluded
    # by the offset-subtracting gauge) and the tenants that entered
    # the scrape/dispatch phase this tick. Service-only, and skipped
    # (never fake zeros) on pre-round-21 reports that don't carry the
    # fields.
    "ccka_host_loop_us_per_tenant": (
        "host_loop_us_per_tenant",
        "Real host-loop microseconds per tenant this tick (admission "
        "machine + masked accounting; scrape waits and device "
        "dispatch excluded)"),
    "ccka_active_tenants": (
        "active_tenants",
        "Tenants admitted into the scrape/dispatch phase this tick "
        "(post cadence/bulkhead/cap)"),
    "ccka_applied": ("applied", "1 if every patch applied this tick"),
    "ccka_verified": ("verified", "1 if read-back matched intent"),
    "ccka_tick": ("t", "Controller tick counter"),
    # Per-stage tick timing, sourced from the span tracer's fenced phase
    # spans (obs/trace.py via StageTimer): the scrape→decide→act loop's
    # structured timing, now on the wire and not only in JSONL.
    "ccka_tick_scrape_ms": (
        "timings_ms.scrape+slo_scrape",
        "Signal + SLO scrape time this tick, milliseconds"),
    "ccka_tick_decide_ms": (
        "timings_ms.decide",
        "Policy decide time this tick (device-fenced), milliseconds"),
    "ccka_tick_act_ms": (
        "timings_ms.render+apply+verify",
        "Render + apply + verify time this tick, milliseconds"),
    "ccka_tick_total_ms": (
        "timings_ms.*", "Total instrumented tick time, milliseconds"),
}

# Series that resolve only from the fleet service's ServiceTickReport
# (`harness/service.py`): a single-cluster controller's scrape
# legitimately omits them (resolve_field -> None skips the series), and
# the telemetry parity test checks them against a service tick instead.
SERVICE_ONLY_SERIES = frozenset({
    "ccka_tenant_breaker_state", "ccka_ticks_shed_total",
    "ccka_admission_queue_depth", "ccka_tick_latency_ms",
    "ccka_slo_burn_rate", "ccka_incident_active",
    "ccka_recorder_dumps_total",
    "ccka_program_dispatches_total", "ccka_achieved_roofline_fraction",
    "ccka_pipeline_occupancy", "ccka_shard_imbalance",
    "ccka_policy_divergence_rate", "ccka_objective_term_share",
    "ccka_shadow_slo_delta",
    "ccka_region_migration_rate", "ccka_region_carbon_intensity",
    "ccka_policy_candidate_win_rate", "ccka_tournament_leader",
    "ccka_host_loop_us_per_tenant", "ccka_active_tenants",
})

_LABEL = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*")


def exported_series_names() -> set[str]:
    return set(SERIES)


def referenced_series(expr: str) -> set[str]:
    """The `ccka_*` tokens a PromQL expression reads (for parity tests)."""
    return {tok for tok in _LABEL.findall(expr) if tok.startswith("ccka_")}


def resolve_field(rec: Mapping, spec: str):
    """A SERIES field spec against one tick record: a plain TickReport
    field, or a dotted reach into a sub-dict — "timings_ms.a+b" sums the
    named phases (absent phases count 0), "timings_ms.*" sums all. An
    absent/empty sub-dict resolves to None so the series is skipped, not
    exported as a fake 0."""
    if "." not in spec:
        return rec.get(spec)
    base, _, sub = spec.partition(".")
    d = rec.get(base)
    if not isinstance(d, Mapping) or not d:
        return None
    if sub == "*":
        return sum(float(v) for v in d.values())
    return sum(float(d.get(k, 0.0)) for k in sub.split("+"))


def _escape_label_value(value: str) -> str:
    """Escape per the text exposition format: backslash, double-quote and
    newline must be escaped inside label values or scrapers reject the
    whole exposition."""
    return (value.replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def render_exposition(report, *, cluster: str = "") -> str:
    """One TickReport (or its dict) as Prometheus text format 0.0.4."""
    rec: Mapping = report if isinstance(report, Mapping) else asdict(report)
    label = (f'{{cluster="{_escape_label_value(cluster)}"}}'
             if cluster else "")
    lines = []
    for name, (field, help_text) in SERIES.items():
        value = resolve_field(rec, field)
        if value is None:
            continue
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{label} {float(value):g}")
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """Publishes the latest tick as Prometheus gauges.

    ``port``: serve GET /metrics on 127.0.0.1:port (0 picks a free port —
    read it back from ``.port``). ``textfile``: additionally write a
    `.prom` file atomically each update (node-exporter textfile collector).
    Both are optional; with neither this is an in-memory holder (tests).
    """

    def __init__(self, *, port: int | None = None, textfile: str = "",
                 cluster: str = ""):
        self.cluster = cluster
        self.textfile = textfile
        self._latest: dict | None = None
        self._lock = threading.Lock()
        self._httpd = None
        self.port = None
        if port is not None:
            exporter = self

            class Handler(BaseHTTPRequestHandler):
                def do_GET(self):  # noqa: N802 (stdlib API)
                    if self.path.rstrip("/") not in ("", "/metrics"):
                        self.send_error(404)
                        return
                    body = exporter.exposition().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

                def log_message(self, *args):  # silence per-scrape stderr
                    pass

            self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="ccka-metrics")
            self._thread.start()

    def update(self, report) -> None:
        rec = report if isinstance(report, Mapping) else asdict(report)
        with self._lock:
            self._latest = dict(rec)
        if self.textfile:
            self._write_textfile()

    def exposition(self) -> str:
        with self._lock:
            rec = self._latest
        if rec is None:
            return "# no ticks yet\n"
        return render_exposition(rec, cluster=self.cluster)

    def _write_textfile(self) -> None:
        """Atomic replace: the textfile collector must never read a torn
        half-written file (same discipline as checkpoint writes)."""
        body = self.exposition()
        d = os.path.dirname(os.path.abspath(self.textfile)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".prom.tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(body)
            os.replace(tmp, self.textfile)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
