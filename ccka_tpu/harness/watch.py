"""The observability session — `demo_40_watch_observe.sh` as a component.

The reference's watch stage (`demo_40_watch_observe.sh:50-110`): kill stale
port-forwards, spawn background `kubectl port-forward` tunnels for Grafana
(:3000), OpenCost (:9090) and the AMP SigV4 proxy (:8005), wait for the
sockets, then smoke-query the metrics API (`/api/v1/label/__name__/values`
and `query?query=up`). This module is that session with the framework's
discipline: the plan is a pure function of config (printable in dry-run),
the process spawner and HTTP fetch are injectable (testable without a
cluster), and teardown is owned by the session object.
"""

from __future__ import annotations

import socket
import subprocess
import time
from dataclasses import dataclass
from typing import Callable, Sequence
from urllib.parse import urlparse

from ccka_tpu.config import FrameworkConfig


@dataclass(frozen=True)
class ForwardSpec:
    """One `kubectl port-forward` tunnel."""

    name: str          # human label, e.g. "grafana"
    target: str        # e.g. "svc/ccka-grafana"
    namespace: str
    local_port: int
    remote_port: int

    def argv(self) -> list[str]:
        return ["kubectl", "port-forward", "-n", self.namespace,
                self.target, f"{self.local_port}:{self.remote_port}"]


# Grafana's operator port (`demo_40_watch_observe.sh:56`).
GRAFANA_PORT = 3000


def watch_plan(cfg: FrameworkConfig) -> list[ForwardSpec]:
    """The tunnels a watch session needs, derived from config: Grafana
    (the stack `ccka dashboard` deploys), plus any localhost endpoint the
    signals config points at (Prometheus-compatible store, OpenCost) —
    the generalization of the reference's hardcoded 3000/8005/9090.
    This is THE source of the local observability ports: the preroll port
    gate (`harness.preroll._local_ports`) derives from it."""
    ns = cfg.workload.namespace
    plan = [ForwardSpec("grafana", "svc/ccka-grafana", ns,
                        GRAFANA_PORT, 3000)]
    prom = urlparse(cfg.signals.prometheus_url)
    if prom.hostname in ("localhost", "127.0.0.1") and prom.port:
        plan.append(ForwardSpec("prometheus", "svc/amp-sigv4-proxy",
                                "opencost", prom.port, 8005))
    oc = urlparse(cfg.signals.opencost_url)
    if oc.hostname in ("localhost", "127.0.0.1") and oc.port:
        plan.append(ForwardSpec("opencost", "svc/opencost", "opencost",
                                oc.port, 9090))
    return plan


def _wait_socket(port: int, *, timeout_s: float, sleep) -> bool:
    """demo_40_watch_observe.sh:93-96 (`/dev/tcp` poll) as a function."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(0.5)
        try:
            s.connect(("127.0.0.1", port))
            return True
        except OSError:
            sleep(0.25)
        finally:
            s.close()
    return False


class WatchSession:
    """Spawns the planned tunnels, waits for sockets, smoke-queries.

    ``spawner(argv) -> handle`` must return an object with ``terminate()``
    (subprocess.Popen by default); ``fetch`` is the signals-layer HTTP
    transport (injectable, like every live client).
    """

    def __init__(self, cfg: FrameworkConfig, *,
                 spawner: Callable[[Sequence[str]], object] | None = None,
                 fetch=None,
                 sleep: Callable[[float], None] = time.sleep,
                 socket_timeout_s: float = 15.0):
        self.cfg = cfg
        self.plan = watch_plan(cfg)
        self.spawner = spawner or (lambda argv: subprocess.Popen(
            list(argv), stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL))
        self.fetch = fetch
        self.sleep = sleep
        self.socket_timeout_s = socket_timeout_s
        self._children: list = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> dict[str, bool]:
        """Spawn every tunnel; returns {name: tunnel_ready}.

        Ready means OUR child's socket: ports already occupied are
        reported not-ready up front (a stale port-forward squatting 3000
        would otherwise answer the socket probe and smoke() would query
        the wrong service — the demo_19 stale-PF hazard), and a child
        that died (e.g. kubectl exiting on 'address already in use')
        fails readiness even if something is listening.
        """
        from ccka_tpu.harness.preroll import check_ports_free

        ports = [fw.local_port for fw in self.plan]
        # check_ports_free returns one check per requested port, in order.
        free = {port: check.ok
                for port, check in zip(
                    ports, check_ports_free(self.cfg, ports=ports))}
        ready = {}
        children_by_name = {}
        for fw in self.plan:
            if not free.get(fw.local_port, False):
                ready[fw.name] = False
                continue
            try:
                child = self.spawner(fw.argv())
            except OSError as e:  # no kubectl binary, exec failure
                raise RuntimeError(
                    f"watch: cannot spawn tunnel {fw.name!r} "
                    f"({' '.join(fw.argv()[:2])}): {e}") from e
            self._children.append(child)
            children_by_name[fw.name] = child
        for fw in self.plan:
            child = children_by_name.get(fw.name)
            if child is None:
                continue
            ok = _wait_socket(fw.local_port,
                              timeout_s=self.socket_timeout_s,
                              sleep=self.sleep)
            # A dead child means the socket (if any) is someone else's.
            poll = getattr(child, "poll", None)
            if ok and poll is not None and poll() is not None:
                ok = False
            ready[fw.name] = ok
        return ready

    def stop(self) -> None:
        for child in self._children:
            try:
                child.terminate()
                wait = getattr(child, "wait", None)
                if wait is not None:
                    try:
                        wait(timeout=5)
                    except Exception:  # noqa: BLE001 — escalate to kill
                        kill = getattr(child, "kill", None)
                        if kill is not None:
                            kill()
                            wait(timeout=5)
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        self._children = []

    def __enter__(self) -> "WatchSession":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- smoke queries ------------------------------------------------------

    def smoke(self) -> dict:
        """The reference's two smoke queries against the metrics store
        (`demo_40_watch_observe.sh:106-110`): metric-name listing and
        `up`. Degrades to reachable=False per endpoint, never raises."""
        from ccka_tpu.signals.live import PrometheusClient, SignalUnavailable

        prom = PrometheusClient(self.cfg.signals.prometheus_url,
                                fetch=self.fetch,
                                timeout_s=self.cfg.signals.request_timeout_s)
        out: dict = {"prometheus_url": self.cfg.signals.prometheus_url}
        try:
            names = prom.label_values("__name__")
            out["metric_names"] = len(names)
            out["has_ccka_series"] = any(n.startswith("ccka_")
                                         for n in names)
            up = prom.query("up")
            out["up_series"] = len(up)
            out["reachable"] = True
        except SignalUnavailable as e:
            out["reachable"] = False
            out["detail"] = str(e)[:200]
        return out
