"""FlywheelRunner: generations of the continual-learning loop on the
fleet service.

`train/flywheel.py` owns the artifacts (mined cells, curricula,
checksummed challenger checkpoints, the atomic live swap); this module
owns the PRODUCTION half of each generation — the VirtualClock-driven
fleet-service runs that (a) record the ledgers the mine stage consumes
and (b) ride the distilled challenger as a tournament shadow lane on
the incumbent's own dispatch before any promotion:

1. **record**: serve ``record_ticks`` with the CURRENT incumbent,
   decision ledger + incident log + a carbon shadow lane enabled — the
   production evidence window (all JSONL, all under one scratch dir).
2. **mine → label → distill**: `Flywheel.mine` over the recorded
   window, `Flywheel.distill` into generation N's challenger.
3. **shadow**: slot the challenger checkpoint
   (`set_challenger_checkpoint`) and re-serve with the
   ``flywheel-challenger`` roster lane riding the incumbent's ticks —
   the challenger's per-workload-class win ledger against the live
   policy on live traffic, the round-20 safety construction.
4. **gate → promote**: `promotion_gates` over the paired cell
   evaluation + the shadow board + the verified provenance + the bench
   history; an eligible decision swaps the live checkpoint atomically,
   anything else leaves the incumbent untouched.
5. **watch → roll back** (`divergence_rollback`): a post-promotion
   watch window with the divergence trigger armed; an edge-triggered
   ``policy_divergence`` incident demotes the challenger and restores
   the parent digest bitwise.

Determinism: every service run uses a fresh deterministic VirtualClock
(the bench_tournament ``det_clock`` construction) and the one seed the
runner was built with; reruns with the same seed reproduce the same
mined cells, the same challenger digests and the same board counts.

A note on compiled-tick caching: `_compiled_service_tick` is keyed on
(cfg, backend, n, horizon) with BACKENDS HASHED BY IDENTITY, and the
roster lanes are built inside it from ``cfg.obs.tournament_roster`` —
so the runner constructs a FRESH incumbent backend object per service
run. A reused object could hit a cache entry whose challenger lane was
built from a PREVIOUS generation's slotted checkpoint.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ccka_tpu.config import SERVICE_PRESETS, FrameworkConfig, ObsConfig
from ccka_tpu.train.checkpoint import load_params_npz
from ccka_tpu.train.flywheel import (Flywheel, load_provenance,
                                     promotion_gates,
                                     set_challenger_checkpoint)

# The roster lane name the shadow stage rides (registered in
# obs/tournament.py; its builder reads the runner-slotted checkpoint).
CHALLENGER_LANE = "flywheel-challenger"


class FlywheelRunner:
    """Drive ``Flywheel`` generations on the fleet service loop."""

    def __init__(self, cfg: FrameworkConfig, flywheel: Flywheel, *,
                 scratch: str, n_tenants: int = 6,
                 record_ticks: int = 20, shadow_ticks: int = 24,
                 watch_ticks: int = 12, top_k: int = 3,
                 seed: int = 211, shadow_win_rate: float = 0.5,
                 history_regressions=None, runlog=None):
        self.cfg = cfg
        self.fw = flywheel
        self.scratch = os.path.abspath(scratch)
        self.n_tenants = int(n_tenants)
        self.record_ticks = int(record_ticks)
        self.shadow_ticks = int(shadow_ticks)
        self.watch_ticks = int(watch_ticks)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.shadow_win_rate = float(shadow_win_rate)
        self.history_regressions = history_regressions
        self.runlog = runlog
        self._run_idx = 0
        os.makedirs(self.scratch, exist_ok=True)
        # The tenant mix keeps every workload class on the board with
        # real comparisons (the bench_tournament construction): batch
        # tenants map to the batch class, slow ones to background.
        n_b = max(1, self.n_tenants // 3)
        self.profiles = (["healthy"] * (self.n_tenants - 2 * n_b)
                         + ["batch"] * n_b
                         + ["slow"] * n_b)[:self.n_tenants]

    # -- service plumbing ----------------------------------------------------

    def _clock(self):
        from ccka_tpu.harness.service import VirtualClock

        state = {"s": 0.0}

        def base():
            state["s"] += 1e-4
            return state["s"]
        return VirtualClock(base=base)

    def _incumbent_backend(self):
        """A FRESH backend object for the live policy (see the module
        docstring's caching note): the rule profile until a promotion
        lands, the promoted checkpoint's PPOBackend after."""
        from ccka_tpu.policy import RulePolicy
        from ccka_tpu.train.ppo import PPOBackend

        name, params = self.fw.incumbent()
        if params is None:
            return name, RulePolicy(self.cfg.cluster)
        return name, PPOBackend(self.cfg, params)

    def _serve(self, roster: tuple, ticks: int, *, backend=None,
               decisions: bool = False, **obs_kw) -> dict:
        from ccka_tpu.harness.service import fleet_service_from_config

        self._run_idx += 1
        tag = f"run-{self._run_idx:02d}"
        paths = {
            "decisions": (os.path.join(self.scratch,
                                       f"{tag}-decisions.jsonl")
                          if decisions else ""),
            "tournament": (os.path.join(self.scratch,
                                        f"{tag}-tournament.jsonl")
                           if roster else ""),
            "incidents": os.path.join(self.scratch,
                                      f"{tag}-incidents.jsonl"),
        }
        run_cfg = self.cfg.with_overrides(**{
            "sim.horizon_steps": max(ticks + 8, 16),
            "obs.tournament_roster": roster,
        })
        obs = ObsConfig(
            enabled=True,
            decisions_enabled=decisions,
            decision_log_path=paths["decisions"],
            tournament_enabled=bool(roster),
            tournament_log_path=paths["tournament"],
            incident_log_path=paths["incidents"], **obs_kw)
        inc_name, inc_backend = (("custom", backend) if backend is not None
                                 else self._incumbent_backend())
        svc = fleet_service_from_config(
            run_cfg, inc_backend, self.n_tenants,
            profiles=self.profiles,
            service=SERVICE_PRESETS["default"], obs=obs,
            horizon_ticks=ticks + 4, seed=self.seed,
            clock=self._clock())
        svc.warmup()
        svc.run(ticks)
        led = svc.tournament
        dl = svc.decisions
        out = {
            "paths": paths, "ticks": ticks, "incumbent": inc_name,
            "board": led._board() if led is not None else {},
            "decision_rows": dl.rows_total if dl is not None else 0,
            "diverged_total": (dl.diverged_total
                               if dl is not None else 0),
            "incidents": svc.incidents.counts(),
            "incident_records": list(svc.incidents.incidents),
            "usd_per_slo_hr": [round(float(v), 6)
                               for v in np.asarray(
                                   svc.tenant_usd_per_slo_hr())],
        }
        svc.close()
        return out

    # -- the generation ------------------------------------------------------

    def record(self) -> dict:
        """Stage 1: the production evidence window — incumbent serving
        with the decision ledger, incident log and one carbon shadow
        lane (the board needs a candidate to ledger per-class wins
        against the live policy; carbon is checkpoint-free)."""
        return self._serve(("carbon",), self.record_ticks,
                           decisions=True)

    def shadow(self, checkpoint: str) -> dict:
        """Stage 3: the challenger rides the incumbent's dispatch as
        the ``flywheel-challenger`` lane, tight sliding window (the
        bench_tournament challenger-scenario settings)."""
        set_challenger_checkpoint(checkpoint)
        return self._serve((CHALLENGER_LANE,), self.shadow_ticks,
                           tournament_window=8,
                           tournament_sustain_ticks=4,
                           tournament_win_rate=0.6)

    def generation(self, gen: int) -> dict:
        """One full mine → distill → shadow → gate → maybe-promote
        turn. Returns the JSON-serializable generation record; the
        live checkpoint changes ONLY if every gate passed."""
        rec = self.record()
        cells = self.fw.mine(
            decisions_path=rec["paths"]["decisions"],
            tournament_path=rec["paths"]["tournament"],
            incidents_path=rec["paths"]["incidents"],
            top_k=self.top_k)
        # Paths out of the ledger window: the provenance digest must be
        # reproducible across reruns in fresh scratch dirs.
        window = {"ticks": rec["ticks"], "rows": rec["decision_rows"],
                  "diverged": rec["diverged_total"],
                  "incidents": rec["incidents"], "seed": self.seed}
        rep = self.fw.distill(cells, generation=gen,
                              ledger_window=window)
        ch_params, _meta = load_params_npz(rep["checkpoint"])
        eval_rows = self.fw.evaluate(ch_params, rep["produced"])
        sh = self.shadow(rep["checkpoint"])
        prov = load_provenance(
            os.path.join(self.fw.gen_dir(gen), "provenance.json"))
        decision = promotion_gates(
            eval_rows, shadow_board=sh["board"].get(CHALLENGER_LANE),
            provenance=prov,
            history_regressions=self.history_regressions,
            win_rate=self.shadow_win_rate)
        if self.runlog is not None:
            self.runlog.event("flywheel_gate", generation=gen,
                              eligible=decision["eligible"],
                              gates={k: v for k, v in
                                     decision["gates"].items()
                                     if isinstance(v, bool)})
        out = {
            "generation": gen,
            "incumbent": rec["incumbent"],
            "mined_cells": [{"scenario": c.scenario,
                             "intensity": c.intensity,
                             "class": c.workload_class,
                             "regime": c.tenant_regime,
                             "score": c.score} for c in cells],
            "curriculum": rep["curriculum"],
            "curriculum_digest": rep["curriculum_digest"],
            "checkpoint_digest": rep["checkpoint_digest"],
            "parent": rep["parent"],
            "ledger_window": window,
            "eval": eval_rows,
            "shadow_board": sh["board"].get(CHALLENGER_LANE),
            "shadow_incidents": sh["incidents"],
            "decision": decision,
            "promoted": False,
        }
        if decision["eligible"]:
            live = self.fw.promote(gen, decision)
            out["promoted"] = True
            out["live"] = {"name": live["name"],
                           "digest": live["digest"]}
        return out

    # -- the rollback demo ---------------------------------------------------

    def divergence_rollback(self) -> dict:
        """Stage 5: serve a post-promotion watch window with the
        divergence trigger armed (the promoted challenger vs its rule
        shadow — a learned policy disagrees with the hand rule nearly
        every tick, so the windowed rate crosses the spike bar and
        stamps ONE edge-triggered ``policy_divergence`` incident),
        then demote and restore the parent digest bitwise."""
        name, backend = self._incumbent_backend()
        watch = self._serve((), self.watch_ticks, backend=backend,
                            decisions=True, decision_window=4,
                            divergence_spike_rate=0.5)
        watch["incumbent"] = name
        div = [r for r in watch["incident_records"]
               if r.trigger == "policy_divergence"]
        if not div:
            return {"watch": {k: watch[k] for k in
                              ("incidents", "decision_rows",
                               "diverged_total", "incumbent")},
                    "rolled_back": False,
                    "reason": "no policy_divergence incident in the "
                              "watch window — nothing to demote"}
        new_live = self.fw.rollback(
            trigger="policy_divergence",
            incident={"id": div[0].id, "t": div[0].t})
        return {"watch": {k: watch[k] for k in
                          ("incidents", "decision_rows",
                           "diverged_total", "incumbent")},
                "incident": {"id": div[0].id, "t": div[0].t},
                "rolled_back": True,
                "demoted": name,
                "restored": {"name": new_live.get("name"),
                             "digest": new_live.get("digest", "")}}

    def run(self, generations: int = 2, *,
            rollback_demo: bool = True) -> dict:
        """The full arc: N generations, then (optionally) the forced
        post-promotion divergence → rollback demonstration."""
        gens = [self.generation(g) for g in
                range(1, int(generations) + 1)]
        out = {"generations": gens,
               "promotions": sum(g["promoted"] for g in gens),
               "status": self.fw.status()}
        if rollback_demo and any(g["promoted"] for g in gens):
            out["rollback"] = self.divergence_rollback()
            out["status_after_rollback"] = self.fw.status()
        return out


def flywheel_snapshot(path: str, result: dict) -> str:
    """Persist a run's JSON record (CLI + bench artifact)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(result, fh, indent=1, sort_keys=True, default=str)
    return path
