"""Metrics-pipeline rendering — the reference's collector deployment as
declarative manifests.

The reference's largest script closes its metrics loop with two deployed
halves this framework previously only *consumed* or *exported*
(VERDICT r4 missing #1):

- an ADOT collector that scrapes kube-state-metrics and remote-writes to
  AMP through SigV4 (`/root/reference/06_opencost.sh:277-387`: RBAC for
  Kubernetes SD, a ConfigMap carrying the OTel pipeline
  ``prometheus receiver → sigv4auth → prometheusremotewrite``, and a
  hardened Deployment);
- an aws-sigv4-proxy Deployment + Service fronting the AMP query API so
  Prometheus-API clients (Grafana, the demo observes) can read without
  SigV4-signing themselves (`06_opencost.sh:204-264`).

This module renders both halves the way `harness/dashboard.py` renders
the demo_40 Grafana stack: pure functions returning manifest dicts that
apply through any ActuationSink, so ``ccka pipeline --live`` is the
whole deploy stage and dry-run prints reviewable kubectl-equivalents.

Framework-first differences from the reference (not a port):

- the scrape pool includes the CONTROLLER's own exposition
  (`harness/promexport.py` serves the ``ccka_*`` series the dashboards
  chart) alongside kube-state-metrics — the reference never scraped its
  own decision loop;
- the remote-write target is ANY Prometheus-compatible endpoint; SigV4
  auth is an option (``region=...``), not an assumption, so the same
  pipeline lands on AMP, Mimir, Thanos or a plain Prometheus;
- every pod passes this framework's own Kyverno guardrails
  (`actuation/guardrails.py`): requests+limits on all containers,
  non-root, no privilege escalation, dropped capabilities — the
  reference's pods carry these too (`06_opencost.sh:227-236`), and the
  parity is kept.
"""

from __future__ import annotations

import json

from ccka_tpu.actuation.guardrails import (
    HARDENED_CONTAINER_SECURITY_CONTEXT,
    hardened_pod_security_context,
)

# Image pins mirror the reference's choices (06_opencost.sh:237,358) —
# pinned rather than :latest so the rendered manifests are reproducible.
COLLECTOR_IMAGE = "public.ecr.aws/aws-observability/aws-otel-collector:v0.40.0"
SIGV4_PROXY_IMAGE = "public.ecr.aws/aws-observability/aws-sigv4-proxy:1.8"

# nobody:nobody with fsGroup — the reference's NONROOT_UID analog.
_HARDENED_POD = hardened_pod_security_context(uid=65534, gid=65534,
                                              fs_group=65534)
_HARDENED_CONTAINER = HARDENED_CONTAINER_SECURITY_CONTEXT


def default_scrape_targets(namespace: str) -> list[dict]:
    """The framework's scrape pool: the controller's own ``ccka_*``
    exposition plus kube-state-metrics (the reference's one known-good
    target, `06_opencost.sh:322-326`)."""
    return [
        {"job_name": "ccka-controller",
         "static_configs": [{"targets": [
             f"ccka-controller.{namespace}.svc.cluster.local:9464"]}]},
        {"job_name": "ksm-static",
         "static_configs": [{"targets": [
             f"kube-state-metrics.{namespace}.svc.cluster.local:8080"]}]},
    ]


def render_collector_config(remote_write_url: str,
                            scrape_configs: list[dict],
                            *, region: str = "",
                            scrape_interval: str = "30s") -> dict:
    """The OTel collector pipeline document
    (`06_opencost.sh:316-341`): prometheus receiver over the scrape
    pool → prometheusremotewrite exporter, with the sigv4auth extension
    threaded in exactly when a ``region`` is given."""
    exporter: dict = {"endpoint": remote_write_url}
    service: dict = {"pipelines": {"metrics": {
        "receivers": ["prometheus"],
        "exporters": ["prometheusremotewrite"],
    }}}
    config: dict = {
        "receivers": {"prometheus": {"config": {
            "global": {"scrape_interval": scrape_interval},
            "scrape_configs": scrape_configs,
        }}},
        "exporters": {"prometheusremotewrite": exporter},
        "service": service,
    }
    if region:
        exporter["auth"] = {"authenticator": "sigv4auth"}
        config["extensions"] = {"sigv4auth": {"region": region}}
        service["extensions"] = ["sigv4auth"]
    return config


def render_collector_rbac(namespace: str) -> list[dict]:
    """ClusterRole + binding for Kubernetes service discovery
    (`06_opencost.sh:277-301`) — read-only on the SD surfaces."""
    return [
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRole",
         "metadata": {"name": "ccka-collector-k8ssd"},
         "rules": [{"apiGroups": [""],
                    "resources": ["nodes", "nodes/proxy", "services",
                                  "endpoints", "pods", "namespaces"],
                    "verbs": ["get", "list", "watch"]}]},
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRoleBinding",
         "metadata": {"name": "ccka-collector-k8ssd-binding"},
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "ClusterRole",
                     "name": "ccka-collector-k8ssd"},
         "subjects": [{"kind": "ServiceAccount",
                       "name": "ccka-collector",
                       "namespace": namespace}]},
    ]


def render_collector_deployment(remote_write_url: str,
                                namespace: str,
                                *, region: str = "",
                                writer_role_arn: str = "",
                                scrape_configs: list[dict] | None = None
                                ) -> list[dict]:
    """ServiceAccount + config ConfigMap + Deployment for the collector
    (`06_opencost.sh:302-387`), hardened to pass the framework's own
    admission guardrails."""
    if scrape_configs is None:
        scrape_configs = default_scrape_targets(namespace)
    sa: dict = {
        "apiVersion": "v1", "kind": "ServiceAccount",
        "metadata": {"name": "ccka-collector", "namespace": namespace},
    }
    if writer_role_arn:
        # IRSA: the pod identity the remote-write SigV4 signs with.
        sa["metadata"]["annotations"] = {
            "eks.amazonaws.com/role-arn": writer_role_arn}
    config_cm = {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "ccka-collector-config",
                     "namespace": namespace},
        "data": {"collector.yaml": json.dumps(
            render_collector_config(remote_write_url, scrape_configs,
                                    region=region), indent=2)},
    }
    deployment = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "ccka-collector", "namespace": namespace,
                     "labels": {"app": "ccka-collector"}},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": "ccka-collector"}},
            "template": {
                "metadata": {"labels": {"app": "ccka-collector"}},
                "spec": {
                    "serviceAccountName": "ccka-collector",
                    "terminationGracePeriodSeconds": 10,
                    "securityContext": dict(_HARDENED_POD),
                    "containers": [{
                        "name": "collector",
                        "image": COLLECTOR_IMAGE,
                        "imagePullPolicy": "IfNotPresent",
                        "args": ["--config=/conf/collector.yaml"],
                        "securityContext": dict(_HARDENED_CONTAINER),
                        "volumeMounts": [{"name": "conf",
                                          "mountPath": "/conf"}],
                        "resources": {
                            "requests": {"cpu": "200m",
                                         "memory": "256Mi"},
                            "limits": {"cpu": "1", "memory": "512Mi"},
                        },
                    }],
                    "volumes": [{
                        "name": "conf",
                        "configMap": {
                            "name": "ccka-collector-config",
                            "items": [{"key": "collector.yaml",
                                       "path": "collector.yaml"}]},
                    }],
                },
            },
        },
    }
    return [sa, config_cm, deployment]


def render_query_proxy(namespace: str,
                       *, region: str,
                       host: str = "",
                       query_role_arn: str = "",
                       port: int = 8005) -> list[dict]:
    """The SigV4 query proxy (`06_opencost.sh:204-264`): ServiceAccount
    (IRSA query role) + Deployment + Service. ``host`` defaults to the
    AMP workspace API for ``region``; the Service is what Grafana's
    datasource (and `ccka watch`'s port-forward plan) point at."""
    host = host or f"aps-workspaces.{region}.amazonaws.com"
    sa: dict = {
        "apiVersion": "v1", "kind": "ServiceAccount",
        "metadata": {"name": "ccka-query-proxy", "namespace": namespace},
    }
    if query_role_arn:
        sa["metadata"]["annotations"] = {
            "eks.amazonaws.com/role-arn": query_role_arn}
    deployment = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "ccka-query-proxy", "namespace": namespace,
                     "labels": {"app": "ccka-query-proxy"}},
        "spec": {
            "replicas": 1,
            "strategy": {"type": "Recreate"},
            "selector": {"matchLabels": {"app": "ccka-query-proxy"}},
            "template": {
                "metadata": {"labels": {"app": "ccka-query-proxy"}},
                "spec": {
                    "serviceAccountName": "ccka-query-proxy",
                    "terminationGracePeriodSeconds": 10,
                    "securityContext": dict(_HARDENED_POD),
                    "containers": [{
                        "name": "sigv4-proxy",
                        "image": SIGV4_PROXY_IMAGE,
                        "imagePullPolicy": "IfNotPresent",
                        "args": ["--name=aps", f"--region={region}",
                                 f"--host={host}", f"--port=:{port}"],
                        "ports": [{"containerPort": port}],
                        "securityContext": dict(_HARDENED_CONTAINER),
                        "resources": {
                            "requests": {"cpu": "100m",
                                         "memory": "128Mi"},
                            "limits": {"cpu": "500m",
                                       "memory": "256Mi"},
                        },
                    }],
                },
            },
        },
    }
    service = {
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": "ccka-query-proxy", "namespace": namespace},
        "spec": {
            "selector": {"app": "ccka-query-proxy"},
            "ports": [{"name": "http", "port": port, "targetPort": port,
                       "protocol": "TCP"}],
        },
    }
    return [sa, deployment, service]


def render_metrics_pipeline(remote_write_url: str,
                            namespace: str,
                            *, region: str = "",
                            writer_role_arn: str = "",
                            query_role_arn: str = "",
                            proxy: bool = False,
                            scrape_configs: list[dict] | None = None
                            ) -> list[dict]:
    """The whole deploy stage, apply-ordered: RBAC, collector stack,
    and (when ``proxy``) the SigV4 query proxy. ``proxy`` requires a
    ``region`` — the proxy exists only to SigV4-sign."""
    if proxy and not region:
        raise ValueError("the query proxy is SigV4-specific: pass "
                         "region= to render it")
    docs = render_collector_rbac(namespace)
    docs += render_collector_deployment(
        remote_write_url, namespace, region=region,
        writer_role_arn=writer_role_arn, scrape_configs=scrape_configs)
    if proxy:
        docs += render_query_proxy(namespace, region=region,
                                   query_role_arn=query_role_arn)
    return docs
