"""Overload-safe multi-tenant fleet service (ROADMAP item 4, host half).

`harness/fleet.py` scaled the decide sideways: one batched device
dispatch over N homogeneous clusters, host fan-out to N sinks. But the
host loop it wraps is only as healthy as its worst tenant — a hung
scrape blocks the tick, a chaos-ridden kubectl edge burns the whole
fan-out budget in retries, and nothing bounds queue growth when arrival
rate exceeds dispatch rate. This module is the fleet loop rebuilt with
robustness as the design axis (KIS-S and NeuroScaler both stress that an
autoscaling control plane must stay responsive *under the load it
manages*):

- **bounded batched ticks** — each tick has a hard deadline
  (`ServiceConfig.tick_deadline_ms`), split between a scrape/admission
  budget and a fan-out budget. Tenant scrapes that would run past the
  scrape budget are abandoned at the budget edge and DEFERRED to the
  next tick (a straggler is never awaited); all admitted decides still
  pack into ONE device dispatch per tick through the config-keyed
  shared jit (`fleet._compiled_fleet_tick` idiom), with held/fallback
  lanes selected per tenant *inside* the same dispatch so a degraded
  fleet never pays a second device round trip.
- **per-tenant bulkheads + circuit breakers** — scrape timeouts/stale
  samples and reconcile give-ups feed a per-tenant
  closed→open→half-open :class:`CircuitBreaker` (seeded-jitter
  exponential probe schedule, the `RetryingFetch` idiom). While open,
  the tenant's scrape AND actuation are skipped outright — no tick
  budget is spent on a known-bad edge — and its decision lane degrades
  to hold-last-action, escalating to the rule fallback after
  ``hold_fallback_after`` open ticks (the single-cluster degraded
  machine's ok→hold→fallback shape, per tenant). Healthy tenants
  proceed untouched: their decide rows are bitwise the calm run's.
- **backpressure + load shedding** — `ServiceConfig.admission_queue_cap`
  bounds admitted decides per tick; overflow is shed by EXPLICIT
  priority (stale-tolerant tenants first), every shed/deferral is
  counted on the report, and sustained saturation degrades
  stale-tolerant tenants' decide cadence (bounded divisor) instead of
  growing unbounded backlog.

Time is read through an injectable :class:`VirtualClock` so the
dry-run overload harness (`harness/overload.py`) models slow/hung
scrapes by advancing the clock instead of sleeping — deterministic,
fast, and the deadline arithmetic is identical to real time. All
host timing here rides inside tracer spans (the AST timing guard in
`tests/test_timing_guard.py` scans this hot loop, `time.monotonic`
included).

The ``off`` preset (`config.SERVICE_PRESETS`) is a hard gate in the
ChaosSink-"off" idiom: every tick delegates verbatim to the wrapped
pre-service :class:`FleetController`, byte-identical packed actions and
per-sink command streams (pinned by `tests/test_service.py`).
"""

from __future__ import annotations

import dataclasses
import functools
import math
import time
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ccka_tpu.actuation.patches import render_nodepool_patches
from ccka_tpu.actuation.sink import ActuationSink
from ccka_tpu.config import FrameworkConfig, ServiceConfig
from ccka_tpu.harness.fleet import (FleetController, action_layout,
                                    unpack_action_row)
from ccka_tpu.policy.base import PolicyBackend
from ccka_tpu.sim.dynamics import step as sim_step
from ccka_tpu.sim.types import Action, SimParams
from ccka_tpu.signals.base import SignalSource

# Decision lanes, selected per tenant INSIDE the one batched dispatch.
LANE_FRESH = 0      # admitted scrape → the backend's fresh decide
LANE_HOLD = 1       # shed/deferred/breaker-open → hold last fresh action
LANE_FALLBACK = 2   # breaker open past hold_fallback_after → rule profile


@dataclasses.dataclass(frozen=True)
class TenantProfile:
    """A tenant's behavioral archetype for the dry-run service harness.

    ``scrape_delay_ms`` is virtual host time one scrape consumes
    (advanced on the service's :class:`VirtualClock`); a delay larger
    than the remaining scrape budget models the hung scrape that times
    out at the budget edge. ``chaos`` names a `config.CHAOS_PRESETS`
    intensity wrapped onto the tenant's sink (its kubectl edge).
    ``priority`` orders admission AND shedding: lower numbers scrape
    first, higher numbers shed first; ``stale_tolerant`` additionally
    opts the tenant into cadence degradation under sustained saturation.
    """

    name: str
    scrape_delay_ms: float = 0.0
    scrape_fail_prob: float = 0.0
    chaos: str = ""
    priority: int = 1
    stale_tolerant: bool = False


# The named tenant archetypes `bench_overload` / `ccka overload-eval`
# compose into fleets; unknown names are rejected up front (the
# chaos-eval convention).
TENANT_PROFILES: dict[str, TenantProfile] = {
    # The well-behaved tenant: instant scrape, honest kubectl edge.
    "healthy": TenantProfile("healthy"),
    # Stale-tolerant batch tenant: first to shed, cadence-degradable.
    "batch": TenantProfile("batch", priority=2, stale_tolerant=True),
    # Slow-but-bounded scrape: consumes real budget, never times out on
    # a default-posture budget (deferral pressure without breaker trips).
    "jittery": TenantProfile("jittery", scrape_delay_ms=20.0),
    # The hung scrape from the issue: always exceeds any sane scrape
    # budget, so every attempt times out at the budget edge.
    "slow": TenantProfile("slow", scrape_delay_ms=400.0),
    # Byzantine edge: failing scrapes AND severe kubectl chaos.
    "flaky": TenantProfile("flaky", scrape_fail_prob=0.35,
                           chaos="severe"),
}


def resolve_profiles(names: Sequence) -> list[TenantProfile]:
    """Profile names (or explicit TenantProfile instances, e.g. the
    overload grid's chaos-composed derivatives) -> profiles, rejecting
    unknown names up front — a typo must fail fast, not produce an
    empty/meaningless board."""
    out: list[TenantProfile] = []
    bad: set[str] = set()
    for p in names:
        if isinstance(p, TenantProfile):
            out.append(p)
        elif p in TENANT_PROFILES:
            out.append(TENANT_PROFILES[p])
        else:
            bad.add(str(p))
    if bad:
        raise ValueError(f"unknown tenant profiles {sorted(bad)}; known: "
                         f"{sorted(TENANT_PROFILES)}")
    return out


class VirtualClock:
    """Monotonic clock plus injectable virtual delay.

    The overload harness models slow/hung tenant scrapes by calling
    :meth:`advance` instead of sleeping, so stress runs are
    deterministic and wall-clock-fast while every deadline comparison
    is arithmetically identical to real time. The base clock is
    injectable for fully-virtual tests."""

    def __init__(self, base: Callable[[], float] = time.monotonic):
        self._base = base
        self._offset = 0.0

    def __call__(self) -> float:
        return self._base() + self._offset

    def advance(self, seconds: float) -> None:
        self._offset += float(seconds)

    @property
    def offset(self) -> float:
        """Cumulative virtual seconds injected so far. The host-loop
        µs/tenant gauge reads real host time as (clock delta) minus
        (offset delta), so simulated scrape delays never inflate it."""
        return self._offset


_BREAKER_LEVEL = {"closed": 0, "half-open": 1, "open": 2}
_BREAKER_STATE = ("closed", "half-open", "open")

# ---- counter-based per-tenant RNG streams (round 21) ----------------------
#
# The fleet's draw machinery at 10^4 tenants cannot afford N
# `random.Random` objects walked one tenant at a time: every draw is
# instead ADDRESSED as (stream seed, draw index) through a stateless
# splitmix64-style hash, so the object breaker, the vectorized breaker
# bank and the vectorized scrape phase all read the SAME streams —
# identical probe schedules and scrape-fail draws whichever host loop
# runs (pinned by the paired parity test in tests/test_service.py).

_U64 = np.uint64
_GOLD = _U64(0x9E3779B97F4A7C15)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (vectorized, uint64 wraparound)."""
    with np.errstate(over="ignore"):
        x = (x ^ (x >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> _U64(27))) * _U64(0x94D049BB133111EB)
        return x ^ (x >> _U64(31))


def counter_u01(seed, counter) -> np.ndarray:
    """Uniform draw(s) in [0, 1) addressed by (stream seed, draw
    index) — one seeded generator for the whole fleet, no per-tenant
    RNG objects. Accepts scalars or equal-length arrays; float64 out."""
    s = np.asarray(seed, _U64)
    c = np.asarray(counter, _U64)
    with np.errstate(over="ignore"):
        z = _mix64(_mix64(s + _GOLD)
                   ^ _mix64(c * _GOLD + _U64(0xD1B54A32D192ED03)))
    return (z >> _U64(11)).astype(np.float64) * (2.0 ** -53)


class CircuitBreaker:
    """Per-tenant closed→open→half-open breaker.

    ``breaker_failures`` consecutive failures (scrape timeout/stale or
    reconcile give-up) OPEN the breaker; while open, :meth:`allow`
    refuses work until the seeded-jittered probe tick arrives, at which
    point ONE half-open probe is allowed through — success re-closes,
    failure re-opens with the probe delay doubled (capped at
    ``breaker_max_probe_ticks``). Jitter draws come from the
    counter-based stream addressed by (seed, ``draws``) — no RNG
    object, and the draw index is exposed so paired runs (and the
    vectorized breaker bank) can prove they consumed the identical
    schedule (`RetryingFetch` idiom, round-21 form)."""

    def __init__(self, svc: ServiceConfig, seed: int = 0):
        self._svc = svc
        self._seed = _U64(seed & 0xFFFFFFFFFFFFFFFF)
        self.draws = 0  # jitter draws consumed (one per open)
        self.state = "closed"
        self._fails = 0          # consecutive failures while closed
        self._opens = 0          # consecutive opens (probe backoff expo)
        self._probe_at = 0
        self._opened_at: int | None = None
        self.transitions = {"opened": 0, "half_open": 0, "closed": 0}

    @property
    def level(self) -> int:
        return _BREAKER_LEVEL[self.state]

    def open_ticks(self, t: int) -> int:
        """Ticks since the breaker first left closed (0 when closed)."""
        return 0 if self._opened_at is None else max(0, t - self._opened_at)

    def allow(self, t: int) -> bool:
        """May this tenant's scrape/actuation be attempted at tick t?
        Transitions open→half-open when the probe is due."""
        if self.state == "closed":
            return True
        if self.state == "open" and t >= self._probe_at:
            self.state = "half-open"
            self.transitions["half_open"] += 1
            return True
        return self.state == "half-open"

    def record_success(self) -> None:
        if self.state != "closed":
            self.transitions["closed"] += 1
        self.state = "closed"
        self._fails = 0
        self._opens = 0
        self._opened_at = None

    def record_failure(self, t: int) -> None:
        self._fails += 1
        if self.state == "half-open" or self._fails >= \
                self._svc.breaker_failures:
            self._open(t)

    def _open(self, t: int) -> None:
        svc = self._svc
        if self.state != "open":
            self.transitions["opened"] += 1
        if self._opened_at is None:
            self._opened_at = t
        self.state = "open"
        self._opens += 1
        self._fails = 0
        base = svc.breaker_probe_ticks * (2.0 ** min(self._opens - 1, 8))
        u = float(counter_u01(self._seed, self.draws))
        self.draws += 1
        jit = 1.0 + svc.breaker_probe_jitter * (2.0 * u - 1.0)
        delay = int(round(base * jit))
        self._probe_at = t + max(1, min(delay, svc.breaker_max_probe_ticks))


class _ObjectBreakerBank:
    """The pre-round-21 per-tenant breaker OBJECTS, kept as the paired
    baseline the fleet-scale bench measures the vectorized machine
    against. Same stream seeds, same draw addressing — `host_loop=
    "object"` must produce bitwise the vectorized path's schedules."""

    kind = "object"

    def __init__(self, svc: ServiceConfig, seed: int, n: int):
        self.breakers = [CircuitBreaker(svc, seed=seed ^ (0xB4EA + i))
                         for i in range(n)]

    def views(self):
        return self.breakers

    def level_of(self, i: int) -> int:
        return self.breakers[i].level

    def is_open(self, i: int) -> bool:
        return self.breakers[i].state == "open"

    def open_ticks(self, i: int, t: int) -> int:
        return self.breakers[i].open_ticks(t)

    def record_success(self, i: int) -> None:
        self.breakers[i].record_success()

    def record_failure(self, i: int, t: int) -> None:
        self.breakers[i].record_failure(t)

    def levels(self) -> np.ndarray:
        return np.asarray([b.level for b in self.breakers], np.int8)

    def opened_counts(self) -> list:
        return [b.transitions["opened"] for b in self.breakers]

    def transitions_total(self) -> int:
        return sum(sum(b.transitions.values()) for b in self.breakers)

    def transition_counts(self) -> dict:
        out = {"opened": 0, "half_open": 0, "closed": 0}
        for b in self.breakers:
            for k, v in b.transitions.items():
                out[k] += v
        return out

    def states_dict(self) -> dict:
        return {str(i): b.level for i, b in enumerate(self.breakers)}


class _BreakerView:
    """Read-only object facade over ONE tenant's row of the vectorized
    breaker bank — the ``svc.breakers[i]`` surface the board accessors
    and pinned tests read (state/level/transitions/open_ticks), without
    resurrecting N stateful objects."""

    __slots__ = ("_bank", "_i")

    def __init__(self, bank: "_VectorBreakerBank", i: int):
        self._bank = bank
        self._i = i

    @property
    def state(self) -> str:
        return _BREAKER_STATE[int(self._bank.level[self._i])]

    @property
    def level(self) -> int:
        return int(self._bank.level[self._i])

    @property
    def draws(self) -> int:
        return int(self._bank.draws[self._i])

    @property
    def transitions(self) -> dict:
        b, i = self._bank, self._i
        return {"opened": int(b.tr_opened[i]),
                "half_open": int(b.tr_half[i]),
                "closed": int(b.tr_closed[i])}

    def open_ticks(self, t: int) -> int:
        oa = int(self._bank.opened_at[self._i])
        return 0 if oa < 0 else max(0, t - oa)


class _VectorBreakerBank:
    """All N breakers as flat arrays: level/probe-deadline vectors,
    counter-based jitter streams, masked transitions. Scalar methods
    mirror :class:`_ObjectBreakerBank` for the shared fan-out loop;
    the float arithmetic per element is EXACTLY the object breaker's
    (``np.rint`` is half-to-even like Python ``round`` — the parity
    test pins the probe schedules bitwise)."""

    kind = "vectorized"

    def __init__(self, svc: ServiceConfig, seed: int, n: int):
        self._svc = svc
        self.n = n
        self.level = np.zeros(n, np.int8)       # 0 closed/1 half/2 open
        self.fails = np.zeros(n, np.int64)
        self.opens = np.zeros(n, np.int64)
        self.probe_at = np.zeros(n, np.int64)
        self.opened_at = np.full(n, -1, np.int64)   # -1 = closed epoch
        self.tr_opened = np.zeros(n, np.int64)
        self.tr_half = np.zeros(n, np.int64)
        self.tr_closed = np.zeros(n, np.int64)
        # Identical per-tenant seed derivation to the object bank.
        idx = np.arange(n, dtype=np.int64)
        self.seeds = ((_U64(seed & 0xFFFFFFFFFFFFFFFF)
                       ^ (idx + 0xB4EA).astype(_U64))
                      if n else np.zeros(0, _U64))
        self.draws = np.zeros(n, np.int64)
        # O(1) count of not-closed breakers: the calm-fleet fast paths
        # (no probe gate, no escalation scan) key off this instead of
        # scanning N levels every tick.
        self.n_tripped = 0
        self._state_keys = [str(i) for i in range(n)]

    @property
    def all_closed(self) -> bool:
        return self.n_tripped == 0

    def views(self) -> list:
        return [_BreakerView(self, i) for i in range(self.n)]

    # -- vectorized admission interface ---------------------------------

    def allow_due(self, due: np.ndarray, t: int):
        """Vectorized :meth:`CircuitBreaker.allow` over the due set:
        returns (allowed mask, probing mask) aligned with ``due``,
        flipping open→half-open exactly where the probe is due."""
        lv = self.level[due]
        flip = (lv == 2) & (t >= self.probe_at[due])
        idx = due[flip]
        self.level[idx] = 1
        self.tr_half[idx] += 1
        allowed = (lv != 2) | flip
        probing = (lv == 1) | flip
        return allowed, probing

    def record_success_idx(self, idx: np.ndarray) -> None:
        if idx.size == 0:
            return
        was_tripped = self.level[idx] != 0
        self.tr_closed[idx] += was_tripped
        self.n_tripped -= int(was_tripped.sum())
        self.level[idx] = 0
        self.fails[idx] = 0
        self.opens[idx] = 0
        self.opened_at[idx] = -1

    def record_failure_idx(self, idx: np.ndarray, t: int) -> None:
        if idx.size == 0:
            return
        self.fails[idx] += 1
        opening = (self.level[idx] == 1) | (
            self.fails[idx] >= self._svc.breaker_failures)
        self._open_idx(idx[opening], t)

    def _open_idx(self, idx: np.ndarray, t: int) -> None:
        if idx.size == 0:
            return
        svc = self._svc
        self.tr_opened[idx] += (self.level[idx] != 2)
        self.n_tripped += int((self.level[idx] == 0).sum())
        fresh = self.opened_at[idx] < 0
        self.opened_at[idx] = np.where(fresh, t, self.opened_at[idx])
        self.level[idx] = 2
        self.opens[idx] += 1
        self.fails[idx] = 0
        base = svc.breaker_probe_ticks * np.power(
            2.0, np.minimum(self.opens[idx] - 1, 8).astype(np.float64))
        u = counter_u01(self.seeds[idx], self.draws[idx])
        self.draws[idx] += 1
        jit = 1.0 + svc.breaker_probe_jitter * (2.0 * u - 1.0)
        delay = np.clip(np.rint(base * jit), 1,
                        svc.breaker_max_probe_ticks).astype(np.int64)
        self.probe_at[idx] = t + delay

    def open_ticks_vec(self, t: int) -> np.ndarray:
        return np.where(self.opened_at >= 0,
                        np.maximum(0, t - self.opened_at), 0)

    # -- scalar interface (shared fan-out loop) -------------------------

    def level_of(self, i: int) -> int:
        return int(self.level[i])

    def is_open(self, i: int) -> bool:
        return self.level[i] == 2

    def open_ticks(self, i: int, t: int) -> int:
        oa = int(self.opened_at[i])
        return 0 if oa < 0 else max(0, t - oa)

    def record_success(self, i: int) -> None:
        self.record_success_idx(np.asarray([i], np.int64))

    def record_failure(self, i: int, t: int) -> None:
        self.record_failure_idx(np.asarray([i], np.int64), t)

    # -- reporting ------------------------------------------------------

    def levels(self) -> np.ndarray:
        return self.level.astype(np.int8, copy=True)

    def opened_counts(self) -> list:
        return self.tr_opened.tolist()

    def transitions_total(self) -> int:
        return int(self.tr_opened.sum() + self.tr_half.sum()
                   + self.tr_closed.sum())

    def transition_counts(self) -> dict:
        return {"opened": int(self.tr_opened.sum()),
                "half_open": int(self.tr_half.sum()),
                "closed": int(self.tr_closed.sum())}

    def states_dict(self) -> dict:
        # tolist() yields python ints — same values, ~6x cheaper than
        # per-element int() at fleet scale (this dict is per tick).
        return dict(zip(self._state_keys, self.level.tolist()))


def _build_service_tick(cfg: FrameworkConfig, backend,
                        n: int, horizon_ticks: int,
                        precomputed_keys: bool):
    """The lane-selecting batched tick, jitted once per (config,
    backend, fleet size, horizon) — `fleet._compiled_fleet_tick` with
    the service's three decision lanes folded into the SAME single
    dispatch: the backend's fresh decide and the rule fallback are both
    computed batched, then selected per row by the host-built lane
    vector, with held actions supplied as an input buffer. One device
    round trip per tick regardless of how degraded the fleet is. Keyed
    on the backend INSTANCE (identity hash), so the overload board's
    paired stressed/calm services share one XLA program.

    Round 21 (``precomputed_keys``): the chunked tenant-axis variant
    takes the per-tenant PRNG keys as an INPUT instead of deriving
    them from (key, t) inside the program — the caller derives keys
    for the FULL fleet once (`_tick_keys`, bit-identical to the
    in-program derivation) and feeds each k-tenant chunk its slice,
    so chunking the tenant axis can never change any tenant's key
    stream. One compiled program per chunk width, reused across every
    chunk and every tick.

    Round 18: the per-cluster rows widen past the slo_ok/cost/carbon/
    pending block with the decision-provenance columns and the rule
    SHADOW action (`obs/decisions.decision_row_layout`): the fallback
    lane's rule decide — already computed batched for the lane select
    — doubles as the counterfactual, stepped through the same
    expectation dynamics on the same pre-step states and observed exo.
    Extra lanes on the existing dispatch, computed whether or not a
    ledger reads them — toggling the ledger can never select a
    different XLA program, which is the construction behind the
    ledger-on/off bitwise gate."""
    from ccka_tpu.obs.compile import watch_jit
    from ccka_tpu.obs.decisions import shadow_decision_columns
    from ccka_tpu.obs.tournament import (TournamentRoster,
                                         add_candidate_lanes)
    from ccka_tpu.policy.rule import RulePolicy

    from ccka_tpu.harness.fleet import (exo_at, flatten_actions,
                                        pack_rows, per_cluster_metrics)

    action_fn = backend.action_fn()
    params = SimParams.from_config(cfg)
    fallback_fn = RulePolicy(cfg.cluster).action_fn()
    shapes, sizes = action_layout(cfg.cluster)
    # Shadow-tournament lanes (round 20): roster names come from
    # cfg.obs (program-shaping, part of this builder's cache key);
    # candidates are constructed INSIDE the builder like the rule
    # fallback. Empty roster = exactly the round-18 program.
    cand_fns = TournamentRoster(
        cfg, cfg.obs.tournament_roster).action_fns()
    zone_region_index = cfg.cluster.zone_region_index
    n_regions = cfg.cluster.n_regions

    def _unflatten(flat: jnp.ndarray) -> Action:
        leaves, off = [], 0
        for shape, size in zip(shapes, sizes):
            leaves.append(jnp.reshape(flat[:, off:off + size],
                                      (n,) + shape))
            off += size
        return Action(*leaves)

    def _tick_core(states, xs_all, t, keys, lanes, held):
        exo_n = exo_at(xs_all, t, horizon_ticks)
        fresh = jax.vmap(lambda s, e: action_fn(s, e, t))(states, exo_n)
        fb = jax.vmap(lambda s, e: fallback_fn(s, e, t))(states, exo_n)
        flat_fb = flatten_actions(fb, n)
        lane_col = lanes[:, None]
        flat_sel = jnp.where(
            lane_col == LANE_FRESH, flatten_actions(fresh, n),
            jnp.where(lane_col == LANE_HOLD, held, flat_fb))
        actions = _unflatten(flat_sel)
        step_n = jax.vmap(
            functools.partial(sim_step, params, stochastic=False))
        new_states, metrics = step_n(states, actions, exo_n, keys)
        # Rule-shadow counterfactual: same pre-step states, exo and
        # keys; only the action differs. Shadow next-states are
        # discarded — the real estimate chain must not fork.
        _sh_states, sh_metrics = step_n(states, _unflatten(flat_fb),
                                        exo_n, keys)
        packed = pack_rows(flat_sel, exo_n)
        blocks = [
            per_cluster_metrics(metrics),
            shadow_decision_columns(metrics, sh_metrics, exo_n,
                                    flat_sel, flat_fb),
            flat_fb,
        ]
        if cand_fns:
            # Unconditional K-candidate lanes (obs/tournament.py): the
            # tournament ledger toggling on/off can never select a
            # different XLA program.
            blocks.append(add_candidate_lanes(
                states, exo_n, t, keys, flat_sel, cand_fns, step_n, n,
                zone_region_index, n_regions))
        per = jnp.concatenate(blocks, axis=-1)
        return packed, new_states, per

    if precomputed_keys:
        @jax.jit
        def service_tick(states, xs_all, t, keys, lanes, held):
            return _tick_core(states, xs_all, t, keys, lanes, held)
        return watch_jit(service_tick, "service.tick_chunk", hot=True,
                         shared_stats=True)

    @jax.jit
    def service_tick(states, xs_all, t, key, lanes, held):
        keys = jax.random.split(jax.random.fold_in(key, t), n)
        return _tick_core(states, xs_all, t, keys, lanes, held)

    return watch_jit(service_tick, "service.tick", hot=True,
                     shared_stats=True)


@functools.lru_cache(maxsize=32)
def _compiled_service_tick(cfg: FrameworkConfig, backend,
                           n: int, horizon_ticks: int):
    return _build_service_tick(cfg, backend, n, horizon_ticks,
                               precomputed_keys=False)


@functools.lru_cache(maxsize=32)
def _compiled_service_tick_chunk(cfg: FrameworkConfig, backend,
                                 k: int, horizon_ticks: int):
    """The k-tenant chunk program (precomputed keys). Cached separately
    from the unchunked tick so a chunked N=10240 fleet compiles exactly
    ONE chunk program, not one per chunk index."""
    return _build_service_tick(cfg, backend, k, horizon_ticks,
                               precomputed_keys=True)


@functools.lru_cache(maxsize=32)
def _tick_keys(n: int):
    """Jitted full-fleet key derivation, bit-identical to the
    in-program `split(fold_in(key, t), n)` of the unchunked tick."""
    @jax.jit
    def derive(key, t):
        return jax.random.split(jax.random.fold_in(key, t), n)

    return derive


@dataclasses.dataclass
class ServiceTickReport:
    """One service tick: fleet KPIs + the overload-control surfaces."""

    t: int
    n_tenants: int
    admitted: int              # tenants whose fresh decide was used
    deferred: int              # scrape stragglers abandoned at the budget
    shed: int                  # decides shed by admission backpressure
    cadence_skipped: int       # stale-tolerant tenants skipped by backoff
    bulkhead_skipped: int      # open-breaker tenants not even attempted
    scrape_failed: int         # scrapes attempted but timed out / failed
    probes: int                # half-open probes attempted this tick
    applied: int               # tenants whose reconcile converged
    fanout_deferred: int       # tenants un-actuated at the tick deadline
    slo_ok: int                # tenants meeting the SLO gate
    cost_usd_hr: float
    carbon_g_hr: float
    pending_pods: float
    tick_latency_ms: float     # admission+decide+fanout on the clock
    admission_queue_depth: int  # decides wanting in this tick (pre-cap)
    sheds_total: int           # session-cumulative (promexport counter)
    deferrals_total: int
    breaker_transitions_total: int
    cadence_divisor: int       # 1 = full cadence for stale-tolerant rows
    decide_ms: float
    fanout_ms: float
    # Per-tenant breaker levels {tenant index as str: 0|1|2}; promexport
    # sums this dict ("breaker_states.*") into the fleet's aggregate
    # breaker-pressure gauge.
    breaker_states: dict = dataclasses.field(default_factory=dict)
    # Incident-grade observability surfaces (round 14, `ccka_tpu/obs`;
    # all 0 with the obs layer off — the gauges then read as a quiet
    # fleet, exactly like the degraded/fault gauges on a calm run).
    slo_burn_rate: float = 0.0        # fast-window fleet SLO burn
    slo_burn_rate_slow: float = 0.0   # slow-window (the flap damper)
    incident_active: int = 0          # burning OR a fresh incident
    incidents_total: int = 0          # session incident stamps
    recorder_dumps_total: int = 0     # session checksummed captures
    # Device-time observatory surfaces (round 15; obs/costmodel +
    # obs/occupancy): the compile registry's session dispatch count,
    # plus whatever the observatory last PUBLISHED (`ccka perf` /
    # bench --perf-only writes a pipeline snapshot). None/{} means no
    # measurement exists — the exporter then SKIPS the series, the
    # established never-fake-zeros contract.
    program_dispatches_total: "int | None" = None
    achieved_roofline_fraction: "float | None" = None
    pipeline_occupancy: dict = dataclasses.field(default_factory=dict)
    shard_imbalance: "float | None" = None
    # Decision-provenance surfaces (round 18; obs/decisions.py): the
    # windowed shadow-disagreement rate, the fleet's objective-term
    # attribution shares (promexport reads the dotted "cost" share),
    # and the tick's projected chosen-minus-rule-shadow SLO/$ deltas.
    # None/{} when the ledger is off — the exporter SKIPS the series
    # (never-fake-zeros), same as the perf surfaces above.
    policy_divergence_rate: "float | None" = None
    objective_term_shares: dict = dataclasses.field(default_factory=dict)
    shadow_slo_delta: "float | None" = None
    shadow_usd_delta: "float | None" = None
    # Geo-arbitrage surfaces (ISSUE 16; regions/geo.py): whatever the
    # geo overlay last PUBLISHED (`publish_geo_snapshot` — the round-15
    # costmodel publish/read idiom; the tick never threads geo state).
    # {} when no geo rollout has run — the exporter SKIPS the series.
    region_migration_rate: dict = dataclasses.field(default_factory=dict)
    region_carbon_intensity: dict = dataclasses.field(default_factory=dict)
    # Shadow-tournament surfaces (round 20; obs/tournament.py): the
    # per-candidate windowed win rates (promexport sums the dict — the
    # "challenger pressure" gauge) and the current board leader's
    # roster index. {}/None when no tournament ledger runs — the
    # exporter SKIPS both series (never-fake-zeros).
    candidate_win_rate: dict = dataclasses.field(default_factory=dict)
    tournament_leader: "int | None" = None
    # Fleet-scale host-loop surfaces (round 21): real host microseconds
    # the admission/accounting machine spent per tenant this tick
    # (virtual scrape delay subtracted via the clock offset; dispatch
    # and fan-out excluded — they are common to both host loops), and
    # the tenant count that entered the scrape/dispatch phase. None on
    # reports that never measured them — the exporter SKIPS the series
    # (never-fake-zeros).
    host_loop_us_per_tenant: "float | None" = None
    active_tenants: "int | None" = None


class FleetService:
    """N tenant clusters behind one bounded, bulkheaded batched tick.

    Construction mirrors :class:`FleetController` (which it wraps for
    the device machinery and per-tenant reconcilers) plus per-tenant
    ``profiles`` (names into :data:`TENANT_PROFILES`; default all
    "healthy") and a ``service`` posture (default ``cfg.service``).
    Tenants whose profile names a chaos intensity get their sink wrapped
    in a seeded `ChaosSink` (per-tenant seed derivation, the fleet
    idiom), so the breaker's actuation-failure signal is driven by the
    same injected kubectl edge the recovery scoreboard uses.

    With ``service.enabled`` False every tick delegates verbatim to the
    wrapped FleetController — the zero-overhead "off" gate.
    """

    def __init__(self, cfg: FrameworkConfig, backend: PolicyBackend,
                 source: SignalSource, sinks: Sequence[ActuationSink],
                 *, profiles: Sequence[str] | None = None,
                 service: ServiceConfig | None = None,
                 obs=None,
                 horizon_ticks: int = 2880, seed: int = 0,
                 clock: VirtualClock | None = None, tracer=None,
                 host_loop: str = "vectorized",
                 dispatch_chunk: "int | None" = None,
                 transport=None,
                 log_fn: Callable[[str], None] | None = None):
        svc = cfg.service if service is None else service
        svc.validate()
        if host_loop not in ("vectorized", "object"):
            raise ValueError(f"host_loop={host_loop!r} — expected "
                             "'vectorized' or 'object'")
        self.svc = svc
        self.cfg = cfg
        n = len(sinks)
        names = list(profiles) if profiles is not None else ["healthy"] * n
        if len(names) != n:
            raise ValueError(f"{len(names)} profiles for {n} sinks — one "
                             "profile per tenant")
        self.profiles = resolve_profiles(names)
        self.profile_names = [p.name for p in self.profiles]
        # Per-tenant kubectl-edge chaos per the profile (seed derivation
        # per tenant: one shared seed would fail every tenant in
        # lockstep, hiding exactly the asymmetric-failure case bulkheads
        # exist for).
        wrapped: list[ActuationSink] = []
        for i, (snk, prof) in enumerate(zip(sinks, self.profiles)):
            if prof.chaos:
                from ccka_tpu.actuation.chaos import make_chaos_sink
                snk = make_chaos_sink(snk, prof.chaos,
                                      seed=seed ^ (0xC4A05 + i))
            wrapped.append(snk)
        self.ctrl = FleetController(
            cfg, backend, source, wrapped, horizon_ticks=horizon_ticks,
            seed=seed, fanout_workers=1, tracer=tracer, log_fn=log_fn)
        self.n = n
        self.sinks = self.ctrl.sinks
        self.tracer = self.ctrl.tracer
        self.log_fn = log_fn or (lambda s: None)
        self._seed = seed
        if not svc.enabled:
            return  # hard gate: tick()/run() delegate to the controller

        self.clock = clock if clock is not None else VirtualClock()
        self._host_loop = host_loop
        self._transport = transport
        # Chunked tenant-axis dispatch (round 21): N=10^3-10^4 fleets
        # ride `sim/lanes.chunk_layout`-validated chunks through ONE
        # compiled k-tenant program (keys precomputed for the full
        # fleet, so chunking never changes a tenant's key stream).
        if dispatch_chunk is not None and dispatch_chunk < n:
            from ccka_tpu.sim.lanes import chunk_layout
            self._n_chunks = chunk_layout(n, dispatch_chunk)
            self._chunk = int(dispatch_chunk)
            self._tick_fn = _compiled_service_tick_chunk(
                cfg, backend, self._chunk, horizon_ticks)
            self._keys_fn = _tick_keys(n)
        else:
            self._n_chunks = 1
            self._chunk = n
            self._tick_fn = _compiled_service_tick(cfg, backend, n,
                                                   horizon_ticks)
            self._keys_fn = None
        # Service-tuned reconcilers over the (chaos-wrapped) sinks: the
        # fleet controller's defaults carry a 2s internal deadline and
        # 10ms backoffs — one converge started just before the tick
        # deadline would blow through it. Each converge is budgeted to
        # a small slice of the fan-out share, and the fan-out loop only
        # STARTS a converge whose worst case still fits the remaining
        # tick budget, so the deadline is a guarantee, not a hope.
        from ccka_tpu.actuation.reconcile import Reconciler
        if svc.tick_deadline_ms > 0.0:
            fan_budget_s = (svc.tick_deadline_ms
                            * (1.0 - svc.scrape_budget_frac) / 1e3)
            self._converge_budget_s = min(0.05, fan_budget_s / 4.0)
        else:
            self._converge_budget_s = 2.0
        self._reconcilers = [
            Reconciler(snk, max_rounds=2, backoff_s=0.002,
                       deadline_s=self._converge_budget_s,
                       seed=seed ^ (0x5EC0 + i))
            for i, snk in enumerate(self.ctrl.sinks)]
        # Breaker machinery: flat arrays by default; the object bank is
        # the paired baseline the fleet-scale bench measures against
        # (same stream seeds → identical probe schedules either way).
        self._brk = (_VectorBreakerBank(svc, seed, n)
                     if host_loop == "vectorized"
                     else _ObjectBreakerBank(svc, seed, n))
        # Per-tenant scrape-fail streams, counter-addressed (replaces
        # the N `random.Random` objects): draw order across tenants is
        # irrelevant by construction, which is what lets the vectorized
        # scrape phase batch the zero-delay tenants' draws.
        idx_n = np.arange(n, dtype=np.int64)
        self._scrape_seeds = (_U64(seed & 0xFFFFFFFFFFFFFFFF)
                              ^ (idx_n + 0x5C12A9).astype(_U64))
        self._scrape_draws = np.zeros(n, np.int64)
        # Flat per-profile vectors for the vectorized admission machine.
        self._stale_arr = np.asarray(
            [p.stale_tolerant for p in self.profiles], bool)
        self._delay_s_arr = np.asarray(
            [p.scrape_delay_ms / 1e3 for p in self.profiles], np.float64)
        self._failp_arr = np.asarray(
            [p.scrape_fail_prob for p in self.profiles], np.float64)
        # Static profile facts the admission fast path keys off: a
        # fleet with no budget-consuming and no fallible scrapes skips
        # the whole scrape walk (profiles are fixed per service).
        self._any_delay = bool((self._delay_s_arr > 0.0).any())
        self._any_failp = bool((self._failp_arr > 0.0).any())
        # Held action rows [N, A] (packed layout minus the is_peak
        # column); neutral until a tenant's first fresh decide lands.
        neutral = np.concatenate(
            [np.asarray(leaf, np.float32).reshape(-1)
             for leaf in Action.neutral(cfg.cluster.n_pools,
                                        cfg.cluster.n_zones)])
        self._held = np.tile(neutral[None, :], (n, 1))
        # Admission order: priority ascending, index-stable — critical
        # tenants scrape (and actuate) inside the budget first.
        self._order = sorted(range(n),
                             key=lambda i: (self.profiles[i].priority, i))
        # The argsort-once form of the same order (lexsort is stable on
        # its last key, so ties break by index exactly like the tuple
        # sort above) — computed once, reused by every vectorized tick.
        self._order_arr = np.lexsort((
            np.arange(n, dtype=np.int64),
            np.asarray([p.priority for p in self.profiles], np.int64)))
        # Session counters + per-tenant accounting (the overload board's
        # isolation evidence reads these).
        self.sheds_total = 0
        self.deferrals_total = 0
        self.cadence_skips_total = 0
        self.bulkhead_skips_total = 0
        self.scrape_timeouts_total = 0
        self.scrape_failures_total = 0
        self.actuation_giveups_total = 0
        self.tenant_cost_usd = np.zeros(n, np.float64)
        self.tenant_slo_ticks = np.zeros(n, np.float64)
        self.tenant_fresh_ticks = np.zeros(n, np.int64)
        # Retention-bounded like the fleet's default tracer: a service
        # daemon ticks forever, and an unbounded per-tick float list on
        # the hot loop is a slow leak. 4096 covers any overload-board
        # run; long-lived owners wanting full history can drain it.
        from collections import deque
        self.latencies_ms: "deque[float]" = deque(maxlen=4096)
        self._sat_streak = 0
        self._cadence_divisor = 1
        # Incident-grade observability (round 14, `ccka_tpu/obs`):
        # flight recorder + trigger stamps + burn-rate engine, all
        # host-side and all AFTER each tick's decisions — the paired
        # recorder-on/recorder-off run in tests/test_incidents.py pins
        # that enabling this changes no decision and no patch byte.
        ob = cfg.obs if obs is None else obs
        ob.validate()
        self.obs = ob
        self.recorder = None
        self.incidents = None
        self.burn = None
        self.decisions = None
        self.tournament = None
        if ob.enabled:
            from ccka_tpu.obs.burnrate import BurnRateEngine
            from ccka_tpu.obs.incidents import IncidentLog
            from ccka_tpu.obs.recorder import FLEET_KEY, FlightRecorder
            self._fleet_key = FLEET_KEY
            self.recorder = FlightRecorder(ob)
            self.incidents = IncidentLog(ob.incident_log_path,
                                         recorder=self.recorder)
            self.burn = BurnRateEngine(ob.burn_fast_window,
                                       ob.burn_slow_window,
                                       ob.burn_threshold)
            # Trigger bookkeeping: breaker opens are counted off the
            # breakers' own transition tallies (one stamp per open, by
            # construction), lane escalations off the previous tick's
            # lane vector, give-ups off the reconciler's OWN hook (the
            # layer that defines "gave up" — actuation/reconcile.py).
            self._prev_opened = [0] * n
            self._prev_lanes = None
            self._giveups_this_tick: list[int] = []
            for i, rec in enumerate(self._reconcilers):
                rec.on_giveup = functools.partial(self._note_giveup, i)
            # Decision-provenance ledger (round 18, obs/decisions.py):
            # host-side recording of the shadow lanes the compiled
            # tick already emits. Disabled-but-obs-on is the
            # bench_decisions off-arm — the device program is the
            # same either way.
            if ob.decisions_enabled:
                from ccka_tpu.obs.decisions import DecisionLedger
                self.decisions = DecisionLedger(
                    ob, cfg.train,
                    policy=getattr(backend, "name",
                                   type(backend).__name__))
            # Shadow tournament (round 20, obs/tournament.py): the
            # host-side win ledger over the candidate lanes the
            # compiled tick already emits. The roster is cfg.obs's
            # (program truth); an obs override naming a DIFFERENT
            # roster would score columns that don't exist — refuse.
            roster = tuple(cfg.obs.tournament_roster)
            if obs is not None and tuple(ob.tournament_roster) not in (
                    (), roster):
                raise ValueError(
                    "obs override names tournament roster "
                    f"{ob.tournament_roster} but the compiled tick "
                    f"carries cfg.obs.tournament_roster={roster} — "
                    "the roster is program-shaping and must be set on "
                    "the FrameworkConfig, not the override")
            if roster and ob.tournament_enabled:
                from ccka_tpu.obs.tournament import (TournamentLedger,
                                                     workload_class)
                self.tournament = TournamentLedger(
                    ob, cfg.train, roster,
                    classes=[workload_class(p.name)
                             for p in self.profiles],
                    policy=getattr(backend, "name",
                                   type(backend).__name__))
        # ONE row layout for both host ledgers, widened by the
        # program's roster (K=0 -> exactly the round-18 layout).
        from ccka_tpu.obs.decisions import decision_row_layout
        self._dec_layout = decision_row_layout(
            cfg.cluster, candidates=cfg.obs.tournament_roster)

    def _note_giveup(self, tenant: int, _outcome) -> None:
        """Reconciler give-up hook (`actuation/reconcile.on_giveup`):
        collected per tick, stamped in the tick's obs block with the
        tick key the incident timeline joins on."""
        self._giveups_this_tick.append(tenant)

    # -- delegation surface --------------------------------------------------

    @property
    def states(self):
        return self.ctrl.states

    @property
    def breakers(self) -> list:
        """Per-tenant breaker surface (objects in ``host_loop="object"``
        mode, read-only row views over the vectorized bank otherwise).
        Raises AttributeError when the service is disabled — the off
        preset carries no breaker machinery (``hasattr`` gate pinned in
        tests/test_service.py)."""
        bank = self.__dict__.get("_brk")
        if bank is None:
            raise AttributeError("breakers (service disabled)")
        return bank.views()

    def close(self) -> None:
        if getattr(self, "incidents", None) is not None:
            self.incidents.close()
        if getattr(self, "decisions", None) is not None:
            self.decisions.close()
        if getattr(self, "tournament", None) is not None:
            self.tournament.close()
        self.ctrl.close()

    def warmup(self) -> None:
        """Trigger (or reuse) the XLA compile without advancing any
        state: a cold service's first tick would otherwise spend its
        entire deadline inside the compile and defer its whole fan-out.
        The overload harness calls this before measuring latencies; a
        daemon may skip it and simply eat one deferred first tick."""
        if not self.svc.enabled:
            return
        if self._n_chunks > 1:
            k = self._chunk
            keys = self._keys_fn(self.ctrl.key, jnp.int32(0))
            st = jax.tree_util.tree_map(lambda x: x[:k],
                                        self.ctrl.states)
            xs = jax.tree_util.tree_map(lambda x: x[:k],
                                        self.ctrl._xs_all)
            out = self._tick_fn(st, xs, jnp.int32(0), keys[:k],
                                jnp.zeros(k, jnp.int32),
                                jnp.asarray(self._held[:k]))
        else:
            out = self._tick_fn(
                self.ctrl.states, self.ctrl._xs_all, jnp.int32(0),
                self.ctrl.key, jnp.zeros(self.n, jnp.int32),
                jnp.asarray(self._held))
        jax.block_until_ready(out[0])

    # -- scrape simulation ---------------------------------------------------

    def _scrape(self, i: int, budget_s: float) -> tuple[bool, bool]:
        """Attempt tenant i's scrape within ``budget_s``; returns
        (ok, timed_out). A profile delay larger than the remaining
        budget consumes the WHOLE remaining budget and times out — the
        straggler is abandoned at the budget edge, exactly what a
        scrape-with-timeout does to a hung endpoint. With a concurrent
        ``transport`` injected (signals/transport.py) the real fetch
        replaces the VirtualClock profile simulation behind the same
        contract."""
        if self._transport is not None:
            return self._transport.scrape(i, budget_s)
        prof = self.profiles[i]
        delay_s = prof.scrape_delay_ms / 1e3
        if delay_s > 0.0:
            if delay_s > budget_s:
                self.clock.advance(max(budget_s, 0.0))
                return False, True
            self.clock.advance(delay_s)
        if prof.scrape_fail_prob > 0.0 and \
                self._scrape_fail_draw(i) < prof.scrape_fail_prob:
            return False, False
        return True, False

    def _scrape_fail_draw(self, i: int) -> float:
        """One draw from tenant i's counter-addressed scrape stream."""
        u = float(counter_u01(self._scrape_seeds[i],
                              int(self._scrape_draws[i])))
        self._scrape_draws[i] += 1
        return u

    # -- admission machine (steps 1-5 of the tick) ---------------------------

    def _admit_object(self, t: int, scrape_end: float):
        """The pre-round-21 per-tenant admission loop (cadence →
        bulkheads → cap/shed → bounded scrape → lanes), kept verbatim
        as the paired baseline the fleet-scale bench measures the
        vectorized machine against. Returns the admission tuple shared
        with :meth:`_admit_vectorized`."""
        svc = self.svc
        brs = self._brk.breakers

        # 1. arrivals: every tenant is due unless cadence-degraded
        #    (stale-tolerant tenants decide every `divisor` ticks
        #    while the queue has been saturating). Tenants whose
        #    breaker is not closed are NEVER cadence-skipped: the
        #    seeded probe schedule must not silently depend on
        #    admission outcomes.
        due: list[int] = []
        cadence_skipped = 0
        div = self._cadence_divisor
        for i in self._order:
            if (div > 1 and self.profiles[i].stale_tolerant
                    and brs[i].state == "closed"
                    and (t + i) % div != 0):
                cadence_skipped += 1
                continue
            due.append(i)

        # 2. bulkheads BEFORE the cap: an open breaker must not
        #    consume an admission slot (known-bad tenants filling
        #    the queue would starve healthy ones into being shed —
        #    the inverse of the isolation contract). allow() is the
        #    probe gate: it flips open→half-open exactly when the
        #    seeded schedule says so.
        live: list[int] = []
        probing: set[int] = set()
        bulkhead_skipped = 0
        for i in due:
            br = brs[i]
            if not br.allow(t):
                # Bulkheaded for the WHOLE tick (scrape and fan-out
                # both skipped); the fan-out loop must not count it
                # again.
                bulkhead_skipped += 1
                continue
            live.append(i)
            if br.state == "half-open":
                probing.add(i)
        queue_depth = len(live)

        # 3. admission cap: shed overflow from the BACK of the
        #    priority order (stale-tolerant/low-priority first).
        #    Due half-open probes are EXEMPT from the cap — the
        #    seeded probe schedule must not be shed by backpressure
        #    — but they keep their priority position in the scrape
        #    order, so a probe never burns the budget ahead of a
        #    healthier tenant.
        cap = svc.admission_queue_cap or self.n
        non_probing = [i for i in live if i not in probing]
        shed = max(0, len(non_probing) - cap)
        keep = set(non_probing[:cap]) | probing
        ready = [i for i in live if i in keep]

        # 4. bounded scrape loop: stragglers defer when the budget
        #    runs out — abandoned at the budget edge, never awaited.
        admitted: list[int] = []
        scraped_ok = np.zeros(self.n, bool)
        deferred = scrape_failed = probes = 0
        for pos, i in enumerate(ready):
            now = self.clock()
            if now >= scrape_end:
                deferred += len(ready) - pos
                self.deferrals_total += len(ready) - pos
                break
            if brs[i].state == "half-open":
                probes += 1
            ok, timed_out = self._scrape(i, scrape_end - now)
            if ok:
                admitted.append(i)
                scraped_ok[i] = True
            else:
                scrape_failed += 1
                self.scrape_timeouts_total += int(timed_out)
                self.scrape_failures_total += int(not timed_out)
                brs[i].record_failure(t)

        # 5. lanes: fresh for admitted; open breakers escalate
        #    hold → rule-fallback after hold_fallback_after ticks.
        lanes = np.full(self.n, LANE_HOLD, np.int32)
        if admitted:
            lanes[np.asarray(admitted, int)] = LANE_FRESH
        for i in range(self.n):
            if lanes[i] == LANE_HOLD and brs[i].open_ticks(
                    t) >= svc.hold_fallback_after:
                lanes[i] = LANE_FALLBACK
        return (cadence_skipped, bulkhead_skipped, queue_depth, shed,
                len(ready), np.asarray(admitted, np.int64), scraped_ok,
                deferred, scrape_failed, probes, lanes)

    def _admit_vectorized(self, t: int, scrape_end: float):
        """Steps 1-5 as flat array ops: masked cadence/shed accounting
        over the argsort-once admission order, the breaker bank's
        vectorized probe gate, batched counter-stream fail draws for
        zero-delay tenants, and a sequential walk over ONLY the tenants
        whose scrapes consume budget (their VirtualClock advances are
        order-dependent by design — the budget edge is a shared
        resource). Decisions, patch streams and report counters are
        bitwise `_admit_object`'s on the det clock."""
        svc = self.svc
        bank = self._brk
        n = self.n
        order = self._order_arr

        # 1. cadence (closed breakers only — the probe schedule must
        #    not depend on admission outcomes).
        div = self._cadence_divisor
        if div > 1:
            skip = (self._stale_arr[order]
                    & (bank.level[order] == 0)
                    & ((t + order) % div != 0))
            cadence_skipped = int(skip.sum())
        else:
            cadence_skipped = 0
        due = order[~skip] if cadence_skipped else order

        # 2. bulkheads BEFORE the cap (vectorized probe gate). With
        #    every breaker closed (the calm-fleet common case, O(1) via
        #    the bank's tripped count) the gate trivially allows all
        #    and probes none — same outputs, no mask machinery.
        if bank.all_closed:
            bulkhead_skipped = 0
            live = due
            probing = None
        else:
            allowed, probing_all = bank.allow_due(due, t)
            bulkhead_skipped = int(due.size) - int(allowed.sum())
            live = due[allowed]
            probing = probing_all[allowed]
        queue_depth = int(live.size)

        # 3. admission cap: probes exempt, overflow shed from the back
        #    of the priority order (rank among non-probing rows; with
        #    no probes the kept set is exactly the first `cap` rows).
        cap = svc.admission_queue_cap or n
        if probing is None:
            shed = max(0, queue_depth - cap)
            ready = live[:cap] if shed else live
        else:
            non_probing = ~probing
            shed = max(0, int(non_probing.sum()) - cap)
            rank = np.cumsum(non_probing) - 1
            keep = probing | (non_probing & (rank < cap))
            ready = live[keep]

        # 4. bounded scrape phase. Zero-delay tenants never move the
        #    clock, so their fail draws batch through the counter
        #    streams; only budget-consuming tenants walk sequentially
        #    (stragglers abandoned at the budget edge, never awaited).
        nr = int(ready.size)
        if self._transport is None and not self._any_delay \
                and not self._any_failp:
            # Every scrape is free and cannot fail: all ready rows
            # admit, nothing defers, no draws are consumed — exactly
            # what the general walk below computes, without building
            # its masks.
            probes = (0 if probing is None
                      else int((bank.level[ready] == 1).sum()))
            admitted = ready
            scraped_ok = np.zeros(n, bool)
            lanes = np.full(n, LANE_HOLD, np.int32)
            if admitted.size:
                a0 = int(admitted.min())
                a1 = int(admitted.max())
                if a1 - a0 + 1 == admitted.size:
                    # Pigeonhole: distinct indices spanning their
                    # range ARE the range — strided stores, no
                    # scatter.
                    scraped_ok[a0:a1 + 1] = True
                    lanes[a0:a1 + 1] = LANE_FRESH
                else:
                    scraped_ok[admitted] = True
                    lanes[admitted] = LANE_FRESH
            if not bank.all_closed:
                esc = (lanes == LANE_HOLD) & (
                    bank.open_ticks_vec(t) >= svc.hold_fallback_after)
                lanes[esc] = LANE_FALLBACK
            return (cadence_skipped, bulkhead_skipped, queue_depth,
                    shed, nr, admitted.astype(np.int64, copy=False),
                    scraped_ok, 0, 0, probes, lanes)
        half_open_before = bank.level[ready] == 1
        ok_mask = np.zeros(nr, bool)
        fail_mask = np.zeros(nr, bool)
        timeout_mask = np.zeros(nr, bool)
        cut = nr
        if self._transport is not None:
            # Concurrent fan-in: every ready tenant's fetch launches
            # at once, each bounded by the remaining scrape budget.
            budget = max(scrape_end - self.clock(), 0.0)
            res = self._transport.fan_in(
                [int(i) for i in ready], budget)
            for q in range(nr):
                ok, timed_out = res[int(ready[q])]
                ok_mask[q] = ok
                if not ok:
                    fail_mask[q] = True
                    timeout_mask[q] = timed_out
        else:
            delays = self._delay_s_arr[ready]
            failp = self._failp_arr[ready]
            clk = self.clock
            for q in np.flatnonzero(delays > 0.0):
                q = int(q)
                rem = scrape_end - clk()
                if rem <= 0.0:
                    cut = q
                    break
                i = int(ready[q])
                if delays[q] > rem:
                    clk.advance(max(rem, 0.0))
                    fail_mask[q] = True
                    timeout_mask[q] = True
                    cut = q + 1
                    break
                clk.advance(delays[q])
                if failp[q] > 0.0 and \
                        self._scrape_fail_draw(i) < failp[q]:
                    fail_mask[q] = True
                else:
                    ok_mask[q] = True
                if clk() >= scrape_end:
                    cut = q + 1
                    break
            free = delays == 0.0
            free[cut:] = False
            free_pos = np.flatnonzero(free)
            drawp = free_pos[failp[free_pos] > 0.0]
            if drawp.size:
                ids = ready[drawp]
                u = counter_u01(self._scrape_seeds[ids],
                                self._scrape_draws[ids])
                self._scrape_draws[ids] += 1
                f = u < failp[drawp]
                fail_mask[drawp] = f
                ok_mask[drawp] = ~f
            ok_mask[free_pos[failp[free_pos] == 0.0]] = True
        deferred = nr - cut
        if deferred:
            self.deferrals_total += deferred
        probes = int(half_open_before[:cut].sum())
        scrape_failed = int(fail_mask.sum())
        self.scrape_timeouts_total += int(timeout_mask.sum())
        self.scrape_failures_total += int(
            (fail_mask & ~timeout_mask).sum())
        bank.record_failure_idx(ready[fail_mask], t)
        admitted = ready[ok_mask]
        scraped_ok = np.zeros(n, bool)
        scraped_ok[admitted] = True

        # 5. lanes (masked hold→fallback escalation; with every
        #    breaker closed — checked AFTER this tick's failures
        #    recorded — no opened_at stamp exists and the scan is
        #    vacuous).
        lanes = np.full(n, LANE_HOLD, np.int32)
        lanes[admitted] = LANE_FRESH
        if not bank.all_closed:
            esc = (lanes == LANE_HOLD) & (bank.open_ticks_vec(t)
                                          >= svc.hold_fallback_after)
            lanes[esc] = LANE_FALLBACK
        return (cadence_skipped, bulkhead_skipped, queue_depth, shed,
                nr, admitted.astype(np.int64), scraped_ok,
                deferred, scrape_failed, probes, lanes)

    # -- one bounded tick ----------------------------------------------------

    def tick(self, t: int) -> "ServiceTickReport | object":
        if not self.svc.enabled:
            # The "off" gate: verbatim pre-service fleet behavior.
            return self.ctrl.tick(t)
        svc = self.svc
        with self.tracer.span("service.tick", t=t):
            t0 = self.clock()
            has_deadline = svc.tick_deadline_ms > 0.0
            deadline = (t0 + svc.tick_deadline_ms / 1e3
                        if has_deadline else math.inf)
            scrape_end = (t0 + svc.tick_deadline_ms
                          * svc.scrape_budget_frac / 1e3
                          if has_deadline else math.inf)

            # 1-5. the admission machine (cadence → bulkheads →
            #    cap/shed → bounded scrape → lanes): flat-array
            #    vectorized by default, the pre-round-21 object loop
            #    kept as the paired host_loop="object" baseline —
            #    bitwise-identical decisions on the det clock (pinned
            #    by tests/test_service.py).
            off0 = self.clock.offset
            admit = (self._admit_object if self._host_loop == "object"
                     else self._admit_vectorized)
            (cadence_skipped, bulkhead_skipped, queue_depth, shed,
             n_ready, admitted, scraped_ok, deferred, scrape_failed,
             probes, lanes) = admit(t, scrape_end)
            self.sheds_total += shed
            self.last_lanes = lanes.copy()
            # Real host seconds the admission machine consumed: clock
            # delta minus the virtual scrape delay injected into it.
            host_adm_s = ((self.clock() - t0)
                          - (self.clock.offset - off0))

            # 6. ONE batched dispatch, lanes selected on device — or,
            #    chunked on the tenant axis (round 21), the SAME
            #    program over k-tenant slices with full-fleet
            #    precomputed keys, per-chunk rows gathered on host so
            #    device output stays bounded by the chunk width.
            with self.tracer.span("service.dispatch", t=t) as sp_d:
                if self._n_chunks > 1:
                    k = self._chunk
                    keys = self._keys_fn(self.ctrl.key, jnp.int32(t))
                    lanes_j = jnp.asarray(lanes)
                    held_j = jnp.asarray(self._held)
                    packed_parts, state_parts, per_parts = [], [], []
                    for c in range(self._n_chunks):
                        sl = slice(c * k, (c + 1) * k)
                        st = jax.tree_util.tree_map(
                            lambda x: x[sl], self.ctrl.states)
                        xs = jax.tree_util.tree_map(
                            lambda x: x[sl], self.ctrl._xs_all)
                        p, s, m = self._tick_fn(
                            st, xs, jnp.int32(t), keys[sl],
                            lanes_j[sl], held_j[sl])
                        packed_parts.append(np.asarray(p))
                        per_parts.append(np.asarray(m))
                        state_parts.append(s)
                    self.ctrl.states = jax.tree_util.tree_map(
                        lambda *leaves: jnp.concatenate(leaves, axis=0),
                        *state_parts)
                    packed = np.concatenate(packed_parts, axis=0)
                    per = np.concatenate(per_parts, axis=0)
                else:
                    packed, new_states, per = self._tick_fn(
                        self.ctrl.states, self.ctrl._xs_all,
                        jnp.int32(t), self.ctrl.key,
                        jnp.asarray(lanes), jnp.asarray(self._held))
                    self.ctrl.states = new_states
                    for arr in (packed, per):
                        if hasattr(arr, "copy_to_host_async"):
                            arr.copy_to_host_async()

            # 7. bounded fan-out through the per-tenant reconcilers
            #    (priority order; open breakers bulkheaded; stragglers
            #    deferred at the tick deadline).
            with self.tracer.span("service.fanout", t=t) as sp_f:
                packed_np = np.asarray(packed)
                per_np = np.asarray(per)
                bank = self._brk
                applied = fanout_deferred = 0
                for pos, i in enumerate(self._order):
                    if bank.is_open(i):
                        # Not re-counted: either it was bulkheaded at
                        # scrape time (already in bulkhead_skipped) or
                        # it opened on THIS tick's scrape/probe failure
                        # (already in scrape_failed) — one tenant, one
                        # bucket per tick.
                        continue
                    # Only START a converge whose worst case (its own
                    # bounded deadline) still fits the tick budget,
                    # with one further converge-budget of headroom for
                    # host noise and post-loop accounting — stragglers
                    # defer rather than overshooting the deadline.
                    if self.clock() + 2.0 * self._converge_budget_s \
                            >= deadline:
                        rest = len(self._order) - pos
                        fanout_deferred += rest
                        self.deferrals_total += rest
                        break
                    a_i = unpack_action_row(
                        packed_np[i, :-1], self.ctrl._action_shapes,
                        self.ctrl._action_sizes)
                    is_peak = packed_np[i, -1] > 0.5
                    patches = render_nodepool_patches(
                        a_i, self.cfg.cluster,
                        op="add" if is_peak else "replace")
                    outcome = self._reconcilers[i].converge(patches)
                    if outcome.converged:
                        applied += 1
                        # A probe (or a plain tick) closes the breaker
                        # only when scrape AND actuation both held.
                        if scraped_ok[i]:
                            bank.record_success(i)
                    else:
                        self.actuation_giveups_total += 1
                        bank.record_failure(i, t)

            # 8. held rows advance for fresh lanes; accounting (masked
            #    — part of the host-loop window the µs/tenant gauge
            #    measures, like the admission machine above).
            acct0 = self.clock()
            aoff0 = self.clock.offset
            if admitted.size:
                a0 = int(admitted.min())
                a1 = int(admitted.max())
                if a1 - a0 + 1 == admitted.size:
                    # Distinct indices spanning exactly their range ARE
                    # that range (pigeonhole) — a strided copy instead
                    # of a gather/scatter pair. Uniform-priority fleets
                    # admit a contiguous prefix every calm tick.
                    sl = slice(a0, a1 + 1)
                    self._held[sl] = packed_np[sl, :-1]
                    self.tenant_fresh_ticks[sl] += 1
                else:
                    self._held[admitted] = packed_np[admitted, :-1]
                    self.tenant_fresh_ticks[admitted] += 1
            # In-place += casts f32 rows without materializing a f64
            # temporary (bitwise the old astype-then-add).
            self.tenant_cost_usd += per_np[:, 1]
            self.tenant_slo_ticks += per_np[:, 0]

            # 9. cadence degradation: sustained shedding doubles the
            #    stale-tolerant divisor (bounded); relief halves it.
            if shed > 0:
                self._sat_streak += 1
                if self._sat_streak >= svc.shed_backoff_after:
                    self._cadence_divisor = min(
                        self._cadence_divisor * 2, svc.cadence_backoff_max)
            else:
                self._sat_streak = 0
                if self._cadence_divisor > 1:
                    self._cadence_divisor //= 2
            self.cadence_skips_total += cadence_skipped
            self.bulkhead_skips_total += bulkhead_skipped
            host_loop_s = host_adm_s + ((self.clock() - acct0)
                                        - (self.clock.offset - aoff0))
            host_loop_us = max(host_loop_s, 0.0) * 1e6 / max(self.n, 1)

            # 10. incident-grade observation (round 14, `ccka_tpu/obs`):
            #     burn windows, ring recording, trigger stamps and
            #     recorder dumps — host-side, strictly AFTER every
            #     decision this tick made (bitwise non-interference is
            #     pinned by the paired recorder-on/off test). Inside
            #     the span and before the final clock read, so the
            #     recorder's cost shows up in tick_latency_ms honestly
            #     instead of hiding between ticks.
            slo_burn = slo_burn_slow = 0.0
            incident_active = 0
            dec = tour = None
            if self.burn is not None:
                slo_burn, slo_burn_slow, incident_active, dec, tour = \
                    self._observe_tick(t, t0, lanes, shed, scraped_ok,
                                       per_np, packed_np, applied,
                                       deadline if has_deadline
                                       else None)

            latency_ms = (self.clock() - t0) * 1e3
        self.latencies_ms.append(latency_ms)
        # KPI aggregates come from the base metric block only — the
        # round-18 decision-provenance tail (shadow metrics + shadow
        # actions) must never leak into fleet sums (fleet.py idiom).
        agg = per_np[:, :4].sum(axis=0)
        dt_hr = float(self.ctrl.params.dt_s) / 3600.0
        report = ServiceTickReport(
            t=t,
            n_tenants=self.n,
            admitted=int(admitted.size),
            deferred=deferred,
            shed=shed,
            cadence_skipped=cadence_skipped,
            bulkhead_skipped=bulkhead_skipped,
            scrape_failed=scrape_failed,
            probes=probes,
            applied=applied,
            fanout_deferred=fanout_deferred,
            slo_ok=int(agg[0]),
            cost_usd_hr=float(agg[1]) / dt_hr,
            carbon_g_hr=float(agg[2]) / dt_hr,
            pending_pods=float(agg[3]),
            tick_latency_ms=round(latency_ms, 3),
            admission_queue_depth=queue_depth,
            sheds_total=self.sheds_total,
            deferrals_total=self.deferrals_total,
            breaker_transitions_total=self._brk.transitions_total(),
            cadence_divisor=self._cadence_divisor,
            decide_ms=round(sp_d.dur_ms, 3),
            fanout_ms=round(sp_f.dur_ms, 3),
            breaker_states=self._brk.states_dict(),
            host_loop_us_per_tenant=round(host_loop_us, 4),
            active_tenants=int(n_ready),
            slo_burn_rate=round(slo_burn, 6),
            slo_burn_rate_slow=round(slo_burn_slow, 6),
            incident_active=int(incident_active),
            incidents_total=(self.incidents.total
                             if self.incidents is not None else 0),
            recorder_dumps_total=(self.recorder.dumps_total
                                  if self.recorder is not None else 0),
            policy_divergence_rate=(dec or {}).get(
                "policy_divergence_rate"),
            objective_term_shares=(dec or {}).get(
                "objective_term_shares") or {},
            shadow_slo_delta=(dec or {}).get("shadow_slo_delta"),
            shadow_usd_delta=(dec or {}).get("shadow_usd_delta"),
            candidate_win_rate=(tour or {}).get("candidate_win_rate")
            or {},
            tournament_leader=(tour or {}).get("tournament_leader"),
            **self._perf_surfaces(),
            **self._geo_surfaces(),
        )
        self.log_fn(
            f"service t={t}: {report.admitted}/{self.n} fresh, "
            f"{report.shed} shed, {report.deferred} deferred, "
            f"{report.bulkhead_skipped} bulkheaded, "
            f"latency {report.tick_latency_ms:.1f}ms")
        return report

    def _perf_surfaces(self) -> dict:
        """The round-15 observatory gauges' tick fields: dict lookups
        only (no device work, no probes) — the obs layer's budget rules
        here exactly as they rule the recorder. With the obs layer off
        every field stays at its skip value."""
        if self.burn is None:  # the obs layer's hard "off" gate
            return {}
        from ccka_tpu.obs import costmodel

        snap = costmodel.pipeline_snapshot() or {}
        return {
            "program_dispatches_total": costmodel.total_dispatches(),
            "achieved_roofline_fraction": snap.get("achieved_fraction"),
            "pipeline_occupancy": snap.get("occupancy") or {},
            "shard_imbalance": snap.get("shard_imbalance"),
        }

    def _geo_surfaces(self) -> dict:
        """Geo-arbitrage gauges (ISSUE 16): read whatever rollout
        snapshot `regions/geo.publish_geo_snapshot` last published —
        dict lookups only, same budget rule and "off" gate as the perf
        surfaces. No snapshot (geo never ran) → {} fields → the
        exporter skips both series (never-fake-zeros)."""
        if self.burn is None:  # the obs layer's hard "off" gate
            return {}
        from ccka_tpu.regions import geo as geo_dyn

        snap = geo_dyn.geo_snapshot() or {}
        return {
            "region_migration_rate": snap.get("migration_rate") or {},
            "region_carbon_intensity": snap.get("carbon_intensity") or {},
        }

    def _observe_tick(self, t: int, t0: float, lanes, shed: int,
                      scraped_ok, per_np, packed_np, applied: int,
                      deadline: "float | None"):
        """The tick's obs pass: update burn windows, append ring rows,
        record the decision ledger's rows, stamp one incident per
        trigger occurrence (breaker open, lane escalation, reconcile
        give-up, deadline overshoot, shed spike, divergence spike) and
        return the (fast burn, slow burn, incident_active, decision
        surfaces) report tuple. Every value recorded is a native host
        scalar — the recorder must never force a device transfer, and
        the dump codec (canonical JSON) would refuse numpy scalars
        anyway."""
        ob = self.obs
        n = self.n
        lat_pre_ms = (self.clock() - t0) * 1e3
        slo_ok_n = float(per_np[:, 0].sum())
        overshoot = deadline is not None and self.clock() > deadline
        self.burn.update("slo", n - slo_ok_n, n)
        self.burn.update("deadline", 1.0 if overshoot else 0.0, 1.0)
        self.burn.update("shed", float(shed), float(n))

        # Ring rows: one fleet-loop row + one per-tenant row per tick.
        # Flat scalars only — the rows are serialized 3x per dump
        # (canonical digest + envelope), so nesting here is dump cost.
        self.recorder.record(self._fleet_key, {
            "t": int(t), "shed": int(shed), "applied": int(applied),
            "latency_ms": round(lat_pre_ms, 3),
            "burn_slo_fast": round(self.burn.rate("slo", "fast"), 4),
            "burn_slo_slow": round(self.burn.rate("slo", "slow"), 4),
        })
        lvls = self._brk.levels()
        for i in range(n):
            self.recorder.record(i, {
                "t": int(t), "lane": int(lanes[i]),
                "breaker": int(lvls[i]),
                "scraped": bool(scraped_ok[i]),
            })

        # Triggers — exactly ONE stamp per occurrence (the
        # tests/test_incidents.py counting contract). Breaker opens
        # come off the breakers' own transition tallies; both the
        # scrape phase and the fan-out phase already happened, so the
        # tallies are final for this tick.
        opened = self._brk.opened_counts()
        for i in range(n):
            while self._prev_opened[i] < opened[i]:
                self._prev_opened[i] += 1
                self.incidents.stamp(
                    "breaker_open", t=t, tenant=i,
                    open_number=self._prev_opened[i],
                    state=_BREAKER_STATE[int(lvls[i])],
                    profile=self.profile_names[i])
        prev = self._prev_lanes
        for i in range(n):
            if lanes[i] == LANE_FALLBACK and (
                    prev is None or prev[i] != LANE_FALLBACK):
                self.incidents.stamp(
                    "hold_fallback", t=t, tenant=i,
                    open_ticks=int(self._brk.open_ticks(i, t)),
                    profile=self.profile_names[i])
        self._prev_lanes = lanes.copy()
        for i in self._giveups_this_tick:
            self.incidents.stamp("reconcile_giveup", t=t, tenant=i,
                                 profile=self.profile_names[i])
        self._giveups_this_tick.clear()
        if overshoot:
            self.incidents.stamp(
                "deadline_overshoot", t=t,
                latency_ms=round(lat_pre_ms, 3),
                deadline_ms=float(self.svc.tick_deadline_ms))
        if shed >= max(1, math.ceil(ob.shed_spike_frac * n)):
            self.incidents.stamp("shed_spike", t=t, shed=int(shed),
                                 n_tenants=n)

        # Decision provenance (round 18): record every tenant's row
        # from the shadow lanes the dispatch already computed; an
        # edge-triggered divergence spike stamps ONE policy_divergence
        # incident carrying its flight-recorder dump like every other
        # trigger. Host floats only — same budget discipline as the
        # recorder rows above.
        dec = None
        if self.decisions is not None:
            dec = self.decisions.observe_tick(
                t, per_np, packed_np, self._dec_layout, lanes=lanes)
            spike = dec.pop("spike", None)
            if spike is not None:
                self.incidents.stamp("policy_divergence", t=t, **spike)

        # Shadow tournament (round 20): score the candidate lanes the
        # dispatch already computed; a sustained challenger stamps ONE
        # edge-triggered challenger_sustained_win with its dump and
        # the signed promotion audit's evidence. Host floats only.
        tour = None
        if self.tournament is not None:
            tour = self.tournament.observe_tick(
                t, per_np, self._dec_layout, lanes=lanes)
            for ch in tour.get("challengers", ()):
                self.incidents.stamp("challenger_sustained_win", t=t,
                                     **ch)

        slo_burn = self.burn.rate("slo", "fast")
        slo_burn_slow = self.burn.rate("slo", "slow")
        last = self.incidents.last_tick()
        incident_active = int(
            self.burn.any_burning
            or (last is not None and t - last < ob.burn_fast_window))
        return slo_burn, slo_burn_slow, incident_active, dec, tour

    def run(self, ticks: int, start_tick: int = 0) -> list:
        """Sequential bounded ticks (the deadline is a per-tick host
        contract, so the fleet controller's dispatch pipelining does not
        apply — the dispatch itself is still a single async device
        round trip under the fan-out)."""
        return [self.tick(t) for t in range(start_tick,
                                            start_tick + ticks)]

    # -- board accessors -----------------------------------------------------

    def breaker_transition_counts(self) -> dict:
        return self._brk.transition_counts()

    def chaos_injected(self) -> dict:
        """Summed injected-failure stats over chaos-wrapped tenant
        sinks (zeros when no tenant profile carries chaos)."""
        out = {"commands": 0, "timeouts": 0, "transient_exits": 0,
               "dropped": 0, "rewrites": 0}
        for snk in self.sinks:
            stats = getattr(snk, "stats", None)
            if stats:
                for k in out:
                    out[k] += stats.get(k, 0)
        return out

    def tenant_usd_per_slo_hr(self) -> np.ndarray:
        """Per-tenant $/SLO-hour over the run so far (the paired-ratio
        numerator/denominator of the overload board)."""
        dt_hr = float(self.ctrl.params.dt_s) / 3600.0
        slo_hr = self.tenant_slo_ticks * dt_hr
        return self.tenant_cost_usd / np.maximum(slo_hr, 1e-9)


def fleet_service_from_config(cfg: FrameworkConfig,
                              backend: PolicyBackend, n_tenants: int,
                              *, profiles: Sequence[str] | None = None,
                              service: ServiceConfig | None = None,
                              obs=None,
                              horizon_ticks: int = 2880, seed: int = 0,
                              clock: VirtualClock | None = None,
                              host_loop: str = "vectorized",
                              dispatch_chunk: "int | None" = None,
                              transport=None,
                              log_fn=None) -> FleetService:
    """Dry-run service wiring: N in-memory sinks over the synthetic
    source (per-tenant chaos wraps ride the profiles)."""
    from ccka_tpu.actuation.sink import DryRunSink
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    source = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                   cfg.signals)
    sinks = [DryRunSink() for _ in range(n_tenants)]
    return FleetService(cfg, backend, source, sinks, profiles=profiles,
                        service=service, obs=obs,
                        horizon_ticks=horizon_ticks,
                        seed=seed, clock=clock, host_loop=host_loop,
                        dispatch_chunk=dispatch_chunk,
                        transport=transport, log_fn=log_fn)
