"""Telemetry: structured timing, JSONL export, and JAX profiler capture.

The reference's observability is a metrics pipeline it configures but never
instruments itself with — KSM→ADOT→AMP on a 30s cadence
(`06_opencost.sh:318-341`) plus port-forwarded dashboards
(`demo_40_watch_observe.sh:50-110`); the scripts themselves emit only
colored log lines (`00_common.sh:12-14`). SURVEY §5 calls for the new
build to carry "JAX profiler traces of the simulator/policy step +
structured timing of the scrape→decide→act loop". This module is that:

- :class:`StageTimer` — named-phase wall timing for one control tick (as
  of the obs subsystem, a re-export of `ccka_tpu.obs.trace.StageTimer`:
  every stage is now a span, so controller phases land in the same trace
  model — and the same Chrome trace files — as bench stages and training
  generations; the round-2 API is unchanged);
- :class:`TelemetryWriter` — append-only JSONL export of tick reports (the
  remote-write analog: durable, machine-parseable, replayable);
- :func:`profile_trace` — gated `jax.profiler` capture around any block
  (simulate/bench/controller), viewable in TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Iterator, Mapping

from ccka_tpu.obs.trace import StageTimer  # noqa: F401  (re-export)


class TelemetryWriter:
    """Append-only JSONL sink for structured tick records.

    One JSON object per line, flushed per write — the controller daemon's
    counterpart of the reference's Prometheus remote-write stream (durable
    history that dashboards and replays read back). ``path`` parents are
    created on demand; writer doubles as a context manager.
    """

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")

    def write(self, record: Mapping) -> None:
        self._fh.write(json.dumps(dict(record), sort_keys=True) + "\n")
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()

    def __enter__(self) -> "TelemetryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_telemetry(path: str) -> list[dict]:
    """Load a JSONL telemetry file back into records (skips blank lines)."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def summarize_telemetry(records: list[dict]) -> dict:
    """Reduce controller tick records to a session scoreboard — the
    demo_40 watch dashboard (`demo_40_watch_observe.sh:50-110`) as a
    machine-readable report: SLO attainment, cost/carbon rates, latency,
    apply/verify health, and per-phase timing distribution.
    """
    if not records:
        return {"ticks": 0}

    def _vals(key):
        return [float(r[key]) for r in records if key in r]

    def _frac(key):
        vals = [bool(r.get(key)) for r in records]
        return sum(vals) / len(vals)

    def _stats(vals):
        if not vals:
            return {}
        arr = sorted(vals)
        # Nearest-rank p95: ceil(0.95·n)−1. The naive int(0.95·n) is one
        # rank high and collapses to max for n ≤ 20 — every short session.
        rank = max(0, -(-95 * len(arr) // 100) - 1)
        return {"mean": round(sum(arr) / len(arr), 3),
                "p95": round(arr[rank], 3),
                "max": round(arr[-1], 3)}

    phases: dict[str, list[float]] = {}
    for r in records:
        for phase, ms in (r.get("timings_ms") or {}).items():
            phases.setdefault(phase, []).append(float(ms))

    peak_ticks = sum(1 for r in records if r.get("is_peak"))
    return {
        "ticks": len(records),
        "peak_ticks": peak_ticks,
        "slo_attainment": round(_frac("slo_ok"), 4),
        "applied_frac": round(_frac("applied"), 4),
        "verified_frac": round(_frac("verified"), 4),
        "fallbacks": int(sum(_vals("fallbacks"))),
        "cost_usd_hr": _stats(_vals("cost_usd_hr")),
        "carbon_g_hr": _stats(_vals("carbon_g_hr")),
        # Proposal-p.5 KPI rates (tick-level gauges exported to Prometheus
        # by harness.promexport; summarized here for `ccka report`).
        "usd_per_kreq": _stats(_vals("usd_per_kreq")),
        "g_co2_per_kreq": _stats(_vals("g_co2_per_kreq")),
        "waste_frac": _stats(_vals("waste_frac")),
        "latency_p95_ms": _stats(_vals("latency_p95_ms")),
        "pending_pods": _stats(_vals("pending_pods")),
        "nodes_spot": _stats(_vals("nodes_spot")),
        "nodes_od": _stats(_vals("nodes_od")),
        "timings_ms": {k: _stats(v) for k, v in sorted(phases.items())},
        "profiles": sorted({r.get("profile", "") for r in records} - {""}),
    }


@contextlib.contextmanager
def profile_trace(log_dir: str | None) -> Iterator[None]:
    """JAX profiler capture around a block, gated on ``log_dir``.

    With a falsy ``log_dir`` this is a no-op, so call sites can thread a
    CLI flag straight through. The captured trace lands under
    ``log_dir/plugins/profile/...`` for TensorBoard's profile plugin /
    XProf — device timelines, XLA op breakdown, fusion inspection.
    """
    if not log_dir:
        yield
        return
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield
