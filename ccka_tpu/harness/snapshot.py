"""Durable controller snapshots: versioned, checksummed, atomic.

The reference's only persistence was idempotent re-runnable scripts plus
state left in the cluster; a controller daemon that dies mid-run lost
its tick index, PRNG path, state estimate and degraded-mode machine —
everything `ccka run --resume` needs to continue *bitwise* where it
stopped. This module is the codec + disk discipline:

- **versioned**: every snapshot carries ``format``/``version``; a reader
  refuses formats it does not understand instead of mis-decoding them;
- **checksummed**: the body's canonical JSON is SHA-256'd at write time
  and re-verified at load — a torn or hand-edited file is refused with
  a :class:`SnapshotError`, never half-restored;
- **atomic**: write-temp-then-rename in the target directory (the same
  discipline as promexport's textfile and orbax checkpoints), so a
  crash mid-write leaves the previous good snapshot in place;
- **pytree-faithful**: device arrays round-trip through base64-encoded
  raw bytes with dtype/shape, keyed by their `jax.tree_util` key paths,
  so restore rebuilds the exact leaves (PRNG key data included) —
  `tests/test_recovery.py` pins save→load→tree-equality.

The body schema is owned by the writers (`harness/controller.py`,
`harness/fleet.py`); this module only guarantees integrity + fidelity.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import tempfile
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ccka_tpu.train.checkpoint import _path_part

SNAPSHOT_FORMAT = "ccka-snapshot"
SNAPSHOT_VERSION = 1


class SnapshotError(RuntimeError):
    """Unreadable, corrupt, or incompatible snapshot."""


# -- pytree <-> JSON-safe encoding ------------------------------------------


def encode_tree(tree: Any) -> dict:
    """Flatten a pytree of arrays to {key-path: {dtype, shape, b64}}."""
    out: dict[str, dict] = {}
    for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(jax.device_get(leaf))
        out["/".join(_path_part(p) for p in kp) or "."] = {
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
        }
    return out


def decode_like(template: Any, enc: dict) -> Any:
    """Rebuild a pytree shaped like ``template`` from :func:`encode_tree`
    output. Leaves are matched by key path; a missing or shape-mismatched
    leaf is a :class:`SnapshotError` (schema drift must fail loudly, not
    restore a half-right state)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for kp, leaf in flat:
        key = "/".join(_path_part(p) for p in kp) or "."
        rec = enc.get(key)
        if rec is None:
            raise SnapshotError(f"snapshot missing leaf {key!r}")
        raw = base64.b64decode(rec["b64"])
        arr = np.frombuffer(raw, dtype=np.dtype(rec["dtype"])).reshape(
            rec["shape"])
        want = tuple(np.shape(leaf))
        if tuple(arr.shape) != want:
            raise SnapshotError(
                f"snapshot leaf {key!r} has shape {tuple(arr.shape)}, "
                f"expected {want}")
        leaves.append(jnp.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def encode_key(key: jax.Array) -> dict:
    """A typed PRNG key as its raw key data (impl-stable uint32 words)."""
    return encode_tree(jax.random.key_data(key))


def decode_key(enc: dict) -> jax.Array:
    rec = enc.get(".")
    if rec is None:
        raise SnapshotError("snapshot missing PRNG key data")
    raw = np.frombuffer(base64.b64decode(rec["b64"]),
                        dtype=np.dtype(rec["dtype"])).reshape(rec["shape"])
    return jax.random.wrap_key_data(jnp.asarray(raw))


# -- disk format -------------------------------------------------------------


def _canonical(body: dict) -> str:
    return json.dumps(body, sort_keys=True, separators=(",", ":"))


def save_snapshot(path: str, body: dict) -> str:
    """Atomically write ``body`` with integrity envelope; returns path."""
    return save_snapshot_with_digest(path, body)[0]


def save_snapshot_with_digest(path: str, body: dict) -> tuple[str, str]:
    """:func:`save_snapshot`, also returning the envelope's SHA-256 —
    for writers that record the digest next to a reference to the file
    (the flight recorder's incident records); recomputing it would
    re-serialize the whole body."""
    sha = hashlib.sha256(_canonical(body).encode()).hexdigest()
    doc = {
        "format": SNAPSHOT_FORMAT,
        "version": SNAPSHOT_VERSION,
        "sha256": sha,
        "body": body,
    }
    path = os.path.abspath(path)
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".snap.tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path, sha


def load_snapshot(path: str) -> dict:
    """Read + verify a snapshot; returns the body. Raises SnapshotError
    on any integrity/compatibility problem."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise SnapshotError(f"cannot read snapshot {path!r}: {e}")
    except json.JSONDecodeError as e:
        raise SnapshotError(f"snapshot {path!r} is not valid JSON "
                            f"(torn write?): {e}")
    if not isinstance(doc, dict) or doc.get("format") != SNAPSHOT_FORMAT:
        raise SnapshotError(f"{path!r} is not a {SNAPSHOT_FORMAT} file")
    if doc.get("version") != SNAPSHOT_VERSION:
        raise SnapshotError(
            f"snapshot {path!r} has version {doc.get('version')!r}; this "
            f"reader understands version {SNAPSHOT_VERSION} only")
    body = doc.get("body")
    want = doc.get("sha256")
    got = hashlib.sha256(_canonical(body).encode()).hexdigest()
    if got != want:
        raise SnapshotError(
            f"snapshot {path!r} failed its checksum (stored {want!r}, "
            f"recomputed {got!r}) — refusing to restore corrupt state")
    return body


def config_digest(cfg) -> str:
    """Identity digest of a FrameworkConfig — resumed runs must refuse a
    snapshot taken under a different config (silently mixing topologies
    would corrupt the state estimate)."""
    return hashlib.sha256(cfg.to_json().encode()).hexdigest()
