"""Dashboard rendering — the demo_40 observability stage as manifests.

The reference deploys a namespace-local Grafana and provisions it with an
AMP datasource ConfigMap (`demo_40_watch_config.sh:51-72,75-138`), then
port-forwards dashboards for the operator (`demo_40_watch_observe.sh`).
The proposal names the dashboards it wanted: "SLO burn, $/1k req,
gCO2e/1k req, waste%, Spot exposure" (proposal PDF p.5) — none were built.

This module renders both halves as declarative objects:

- :func:`render_datasource_configmap` — the Grafana datasource provisioning
  ConfigMap pointed at any Prometheus-compatible endpoint (the SigV4-proxy
  AMP URL in the reference's case);
- :func:`render_dashboard` — a Grafana dashboard JSON with exactly the
  proposal's panels, fed by the controller's exported metric names (the
  telemetry JSONL fields double as the metric vocabulary).

Both apply through any ActuationSink (`kubectl apply -f` equivalents), so
`ccka dashboard --live` is the whole demo_40 configure stage.
"""

from __future__ import annotations

import json

_PANEL_DEFS = (
    # (title, expr, unit) — expr uses the controller's exported series
    # names; on a live stack these come from scraping the telemetry JSONL
    # (or remote-writing TickReports) into Prometheus.
    ("Cost rate", "ccka_cost_usd_hr", "currencyUSD"),
    ("Carbon rate", "ccka_carbon_g_hr", "massg"),
    ("SLO burn", "1 - ccka_slo_ok", "percentunit"),
    ("$ per 1k requests", "ccka_usd_per_kreq", "currencyUSD"),
    ("gCO2e per 1k requests", "ccka_g_co2_per_kreq", "massg"),
    ("Waste %", "ccka_waste_frac", "percentunit"),
    ("Spot exposure", "ccka_nodes_spot / clamp_min(ccka_nodes_spot + "
     "ccka_nodes_od, 1)", "percentunit"),
    ("p95 latency", "ccka_latency_p95_ms", "ms"),
    ("Pending pods", "ccka_pending_pods", "short"),
)


def render_dashboard(title: str = "CCKA autoscaler") -> dict:
    """Grafana dashboard JSON: the proposal's planned panels, realized."""
    panels = []
    for i, (name, expr, unit) in enumerate(_PANEL_DEFS):
        panels.append({
            "id": i + 1,
            "title": name,
            "type": "timeseries",
            "gridPos": {"h": 8, "w": 8, "x": (i % 3) * 8,
                        "y": (i // 3) * 8},
            "fieldConfig": {"defaults": {"unit": unit}},
            "targets": [{"expr": expr, "refId": "A"}],
        })
    return {
        "title": title,
        "uid": "ccka-autoscaler",
        "timezone": "utc",
        "refresh": "30s",  # the scrape cadence, 06_opencost.sh:323
        "panels": panels,
        "schemaVersion": 39,
    }


def render_datasource_configmap(prometheus_url: str,
                                namespace: str = "nov-22") -> dict:
    """Grafana datasource provisioning ConfigMap —
    `demo_40_watch_config.sh:51-72` with the AMP-via-SigV4-proxy URL
    generalized to any Prometheus-compatible endpoint."""
    datasource = {
        "apiVersion": 1,
        "datasources": [{
            "name": "ccka-prometheus",
            "type": "prometheus",
            "access": "proxy",
            "url": prometheus_url,
            "isDefault": True,
            "jsonData": {"timeInterval": "30s"},
        }],
    }
    return {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "ccka-grafana-datasource",
                     "namespace": namespace,
                     "labels": {"grafana_datasource": "1"}},
        "data": {"ccka-datasource.yaml": json.dumps(datasource, indent=2)},
    }


def render_dashboard_configmap(prometheus_url: str,
                               namespace: str = "nov-22") -> list[dict]:
    """Both provisioning objects: datasource + dashboard ConfigMaps (the
    dashboard rides the standard `grafana_dashboard: "1"` sidecar label)."""
    return [
        render_datasource_configmap(prometheus_url, namespace),
        {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "ccka-grafana-dashboard",
                         "namespace": namespace,
                         "labels": {"grafana_dashboard": "1"}},
            "data": {"ccka-dashboard.json":
                     json.dumps(render_dashboard(), indent=2)},
        },
    ]
