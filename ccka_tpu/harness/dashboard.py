"""Dashboard rendering — the demo_40 observability stage as manifests.

The reference deploys a namespace-local Grafana and provisions it with an
AMP datasource ConfigMap (`demo_40_watch_config.sh:51-72,75-138`), then
port-forwards dashboards for the operator (`demo_40_watch_observe.sh`).
The proposal names the dashboards it wanted: "SLO burn, $/1k req,
gCO2e/1k req, waste%, Spot exposure" (proposal PDF p.5) — none were built.

This module renders both halves as declarative objects:

- :func:`render_datasource_configmap` — the Grafana datasource provisioning
  ConfigMap pointed at any Prometheus-compatible endpoint (the SigV4-proxy
  AMP URL in the reference's case);
- :func:`render_dashboard` — a Grafana dashboard JSON with exactly the
  proposal's panels, fed by the controller's exported metric names (the
  telemetry JSONL fields double as the metric vocabulary).

Both apply through any ActuationSink (`kubectl apply -f` equivalents), so
`ccka dashboard --live` is the whole demo_40 configure stage.
"""

from __future__ import annotations

import json

from ccka_tpu.actuation.guardrails import (
    HARDENED_CONTAINER_SECURITY_CONTEXT,
    hardened_pod_security_context,
)

_PANEL_DEFS = (
    # (title, expr, unit) — expr uses the controller's exported series
    # names, served by `harness.promexport` (`ccka run --metrics-port` /
    # --metrics-textfile); `tests/test_telemetry.py::TestPromExport` pins
    # panel-expr <-> exported-series parity both ways.
    ("Cost rate", "ccka_cost_usd_hr", "currencyUSD"),
    ("Carbon rate", "ccka_carbon_g_hr", "massg"),
    ("SLO burn", "1 - ccka_slo_ok", "percentunit"),
    ("$ per 1k requests", "ccka_usd_per_kreq", "currencyUSD"),
    ("gCO2e per 1k requests", "ccka_g_co2_per_kreq", "massg"),
    ("Waste %", "ccka_waste_frac", "percentunit"),
    ("Spot exposure", "ccka_nodes_spot / clamp_min(ccka_nodes_spot + "
     "ccka_nodes_od, 1)", "percentunit"),
    ("p95 latency", "ccka_latency_p95_ms", "ms"),
    ("Pending pods", "ccka_pending_pods", "short"),
    # Controller self-observation (the obs subsystem): per-stage tick
    # timing from the span tracer, so a slow scrape endpoint or a
    # recompiling decide shows up on the SAME board as the KPIs it skews.
    ("Tick time by stage", "ccka_tick_scrape_ms + ccka_tick_decide_ms + "
     "ccka_tick_act_ms", "ms"),
    ("Tick total", "ccka_tick_total_ms", "ms"),
    # Robustness panels (ccka_tpu/faults): the degraded-mode state
    # machine and fault events, next to the KPIs they explain — an
    # operator must see "rule-fallback since 14:02" on the same board
    # as the cost spike it prevented from being worse.
    ("Degraded mode", "ccka_degraded", "short"),
    ("Stale scrapes", "ccka_signal_stale", "short"),
    ("Degraded ticks (session)", "ccka_degraded_ticks_total", "short"),
    ("Fault events", "ccka_nodes_denied + ccka_nodes_delayed + "
     "ccka_nodes_drained", "short"),
    # Crash-safety panels (round 12; ARCHITECTURE §14): reconciler
    # convergence pressure, actuation failure budget, and the snapshot/
    # resume health of the control loop itself — an operator must see
    # "3 pools diverged, snapshot 40 ticks old" BEFORE restarting the
    # daemon, not find out after.
    ("Reconcile retries (session)", "ccka_reconcile_retries_total",
     "short"),
    ("Actuation divergence", "ccka_reconcile_diverged", "short"),
    ("Actuation failures (session)", "ccka_actuation_failures_total",
     "short"),
    ("Snapshot age", "ccka_snapshot_age_ticks", "short"),
    ("Resumes (session)", "ccka_resumes_total", "short"),
    # Multi-tenant service panels (round 13; ARCHITECTURE §15): the
    # overload-control surfaces — an operator must see "4 breakers
    # open, shedding, 180ms ticks" on the SAME board as the fleet KPIs
    # the bulkheads are protecting.
    ("Breaker pressure", "ccka_tenant_breaker_state", "short"),
    ("Decides shed (session)", "ccka_ticks_shed_total", "short"),
    ("Admission queue depth", "ccka_admission_queue_depth", "short"),
    ("Service tick latency", "ccka_tick_latency_ms", "ms"),
    # Incident panels (round 14; ccka_tpu/obs): the burn-rate view and
    # the incident/recorder state — the operator sees "SLO budget
    # burning, incident active, 3 captures taken" on the SAME board as
    # the breaker pressure that explains it.
    ("SLO burn rate", "ccka_slo_burn_rate", "percentunit"),
    ("Incident active", "ccka_incident_active", "short"),
    ("Recorder dumps (session)", "ccka_recorder_dumps_total", "short"),
    # Device-time observatory panels (round 15; obs/costmodel +
    # obs/occupancy): where device time goes and how close to the
    # roofline the measured kernel stage runs — the operator sees
    # "kernel 60% occupied, 0.9 of roofline, shard 3 lagging" on the
    # SAME board as the fleet KPIs that throughput serves.
    ("Program dispatches (session)", "ccka_program_dispatches_total",
     "short"),
    ("Achieved roofline", "ccka_achieved_roofline_fraction",
     "percentunit"),
    ("Kernel occupancy", "ccka_pipeline_occupancy", "percentunit"),
    ("Shard imbalance", "ccka_shard_imbalance", "short"),
    # Decision-provenance panels (round 18; obs/decisions.py): how far
    # the flagship departs from the rule shadow, which objective term
    # is buying the decisions, and what the departure is projected to
    # cost in SLO — the "why" next to the KPIs it explains.
    ("Policy divergence", "ccka_policy_divergence_rate", "percentunit"),
    ("Objective cost share", "ccka_objective_term_share", "percentunit"),
    ("Shadow SLO delta", "ccka_shadow_slo_delta", "short"),
    # Workload-family panels (ccka_tpu/workloads): per-family queue
    # pressure and the session's SLO accounting, on the same board as
    # the fleet cost/SLO panels the families trade against.
    ("Inference queue", "ccka_inference_queue_depth", "short"),
    ("Inference SLO violations (session)",
     "ccka_inference_slo_violations_total", "short"),
    ("Batch deadline misses (session)",
     "ccka_batch_deadline_misses_total", "short"),
    # Geo-arbitrage panel (ISSUE 16; ccka_tpu/regions): how much work
    # is moving between regions and how dirty the regional grids are —
    # the migration rate next to the carbon intensity it arbitrages.
    ("Geo migration vs grid carbon",
     "ccka_region_migration_rate + ccka_region_carbon_intensity / 1000",
     "short"),
    # Shadow-tournament panels (round 20; obs/tournament.py): how hard
    # the roster is pressing on the live primary (summed windowed win
    # rate) and which candidate currently leads the board — the
    # operator's cue to go read `ccka tournament explain`.
    ("Tournament challenger pressure",
     "ccka_policy_candidate_win_rate", "short"),
    ("Tournament leader", "ccka_tournament_leader", "short"),
    # Fleet-scale panels (round 21; harness/fleetscale.py): the host
    # loop's real cost per tenant and the admitted-tenant count, on the
    # same board as the shed/latency panels they explain — the operator
    # sees "10k tenants, 0.1us each" next to the queue-depth spike.
    ("Host loop cost per tenant", "ccka_host_loop_us_per_tenant",
     "short"),
    ("Active tenants", "ccka_active_tenants", "short"),
)


def render_dashboard(title: str = "CCKA autoscaler") -> dict:
    """Grafana dashboard JSON: the proposal's planned panels, realized."""
    panels = []
    for i, (name, expr, unit) in enumerate(_PANEL_DEFS):
        panels.append({
            "id": i + 1,
            "title": name,
            "type": "timeseries",
            "gridPos": {"h": 8, "w": 8, "x": (i % 3) * 8,
                        "y": (i // 3) * 8},
            "fieldConfig": {"defaults": {"unit": unit}},
            "targets": [{"expr": expr, "refId": "A"}],
        })
    return {
        "title": title,
        "uid": "ccka-autoscaler",
        "timezone": "utc",
        "refresh": "30s",  # the scrape cadence, 06_opencost.sh:323
        "panels": panels,
        "schemaVersion": 39,
    }


def render_datasource_configmap(prometheus_url: str,
                                namespace: str = "nov-22") -> dict:
    """Grafana datasource provisioning ConfigMap —
    `demo_40_watch_config.sh:51-72` with the AMP-via-SigV4-proxy URL
    generalized to any Prometheus-compatible endpoint."""
    datasource = {
        "apiVersion": 1,
        "datasources": [{
            "name": "ccka-prometheus",
            "type": "prometheus",
            "access": "proxy",
            "url": prometheus_url,
            "isDefault": True,
            "jsonData": {"timeInterval": "30s"},
        }],
    }
    return {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "ccka-grafana-datasource",
                     "namespace": namespace,
                     "labels": {"grafana_datasource": "1"}},
        "data": {"ccka-datasource.yaml": json.dumps(datasource, indent=2)},
    }


def render_dashboard_configmap(prometheus_url: str,
                               namespace: str = "nov-22") -> list[dict]:
    """Both provisioning objects: datasource + dashboard ConfigMaps (the
    dashboard rides the standard `grafana_dashboard: "1"` sidecar label)."""
    return [
        render_datasource_configmap(prometheus_url, namespace),
        {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "ccka-grafana-dashboard",
                         "namespace": namespace,
                         "labels": {"grafana_dashboard": "1"}},
            "data": {"ccka-dashboard.json":
                     json.dumps(render_dashboard(), indent=2)},
        },
    ]


GRAFANA_IMAGE = "grafana/grafana:10.4.2"  # demo_40_watch_config.sh:94


def render_grafana_admin_secret(namespace: str = "nov-22",
                                password: str | None = None) -> dict:
    """Grafana admin Secret (`demo_40_watch_config.sh:36-48`). A random
    password is generated unless supplied (supply one for golden tests);
    stringData keeps the manifest reviewable in dry-run output."""
    if password is None:
        import secrets
        password = secrets.token_urlsafe(12)
    return {
        "apiVersion": "v1", "kind": "Secret",
        "metadata": {"name": "ccka-grafana-admin", "namespace": namespace,
                     "labels": {"app": "ccka-grafana"}},
        "type": "Opaque",
        "stringData": {"admin-user": "admin", "admin-password": password},
    }


def render_grafana_deployment(namespace: str = "nov-22") -> list[dict]:
    """Namespace-local Grafana Deployment + Service + dashboard-provider
    ConfigMap (`demo_40_watch_config.sh:75-138`), redesigned to pass this
    framework's own guardrails:

    - every container carries requests+limits (the `require-requests-limits`
      ClusterPolicy in `actuation/guardrails.py` would reject the
      reference's Grafana pod, which has none);
    - non-root + no privilege escalation + dropped caps, like the burst
      workload's hardened pod spec;
    - unlike the reference (datasources only), the committed dashboard is
      provisioned too, via a file provider — no manual import step.
    """
    provider = {
        "apiVersion": 1,
        "providers": [{
            "name": "ccka",
            "type": "file",
            "options": {"path": "/var/lib/grafana/dashboards"},
        }],
    }
    provider_cm = {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "ccka-grafana-dashboard-provider",
                     "namespace": namespace,
                     "labels": {"app": "ccka-grafana"}},
        "data": {"provider.yaml": json.dumps(provider, indent=2)},
    }
    secret_env = [
        {"name": f"GF_SECURITY_ADMIN_{k.upper()}",
         "valueFrom": {"secretKeyRef": {"name": "ccka-grafana-admin",
                                        "key": f"admin-{k}"}}}
        for k in ("user", "password")]
    deployment = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "ccka-grafana", "namespace": namespace,
                     "labels": {"app": "ccka-grafana"}},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": "ccka-grafana"}},
            "template": {
                "metadata": {"labels": {"app": "ccka-grafana"}},
                "spec": {
                    # Shared hardening (actuation/guardrails.py) with the
                    # grafana image's baked-in uid.
                    "securityContext": hardened_pod_security_context(
                        uid=472),
                    "containers": [{
                        "name": "grafana",
                        "image": GRAFANA_IMAGE,
                        "imagePullPolicy": "IfNotPresent",
                        "ports": [{"containerPort": 3000, "name": "http"}],
                        "env": secret_env + [
                            {"name": "GF_AUTH_ANONYMOUS_ENABLED",
                             "value": "false"},
                        ],
                        "resources": {
                            "requests": {"cpu": "100m", "memory": "128Mi"},
                            "limits": {"cpu": "500m", "memory": "256Mi"},
                        },
                        "securityContext": dict(
                            HARDENED_CONTAINER_SECURITY_CONTEXT),
                        "readinessProbe": {
                            "httpGet": {"path": "/login", "port": 3000},
                            "initialDelaySeconds": 5, "periodSeconds": 5},
                        "livenessProbe": {
                            "httpGet": {"path": "/api/health", "port": 3000},
                            "initialDelaySeconds": 10, "periodSeconds": 10},
                        "volumeMounts": [
                            {"name": "datasources",
                             "mountPath":
                                 "/etc/grafana/provisioning/datasources"},
                            {"name": "dashboard-provider",
                             "mountPath":
                                 "/etc/grafana/provisioning/dashboards"},
                            {"name": "dashboards",
                             "mountPath": "/var/lib/grafana/dashboards"},
                        ],
                    }],
                    "volumes": [
                        {"name": "datasources",
                         "configMap": {"name": "ccka-grafana-datasource"}},
                        {"name": "dashboard-provider",
                         "configMap":
                             {"name": "ccka-grafana-dashboard-provider"}},
                        {"name": "dashboards",
                         "configMap": {"name": "ccka-grafana-dashboard"}},
                    ],
                },
            },
        },
    }
    service = {
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": "ccka-grafana", "namespace": namespace,
                     "labels": {"app": "ccka-grafana"}},
        "spec": {
            "selector": {"app": "ccka-grafana"},
            "ports": [{"name": "http", "port": 3000, "targetPort": 3000}],
        },
    }
    return [provider_cm, deployment, service]


def render_observability_stack(prometheus_url: str,
                               namespace: str = "nov-22",
                               *, admin_password: str | None = None
                               ) -> list[dict]:
    """The WHOLE demo_40 configure stage as manifests, apply-ordered:
    provisioning ConfigMaps, admin Secret, then Deployment + Service."""
    return (render_dashboard_configmap(prometheus_url, namespace)
            + [render_grafana_admin_secret(namespace, admin_password)]
            + render_grafana_deployment(namespace))
