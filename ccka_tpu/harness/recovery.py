"""Kill/restart chaos harness: the end-to-end recovery scoreboard.

Round 10 measured how policies degrade when the *world* misbehaves
(`faults/scoreboard.py`); this board measures whether the control loop
itself survives dying. Each cell of {policy} x {actuation intensity}
runs paired controller sessions over the SAME seeded world, signal-fault
schedule and chaos realization:

- **baseline**: an uninterrupted run of ``ticks`` control ticks through
  a `ChaosSink`-wrapped dry-run cluster with a `Reconciler` converging
  every tick;
- **killed**: the same run murdered at a seeded random tick — the
  controller object is discarded (the process-death analog; the sink
  lives on, as a real cluster would), a fresh controller is constructed,
  restored from the durable snapshot, and driven to the end.

Recovery metrics per pair, aggregated per cell:

- ``duplicate_patches`` / ``lost_patches`` — multiset diff of the
  kubectl-equivalent command streams; both MUST be zero (snapshots are
  written at tick boundaries, so resume replays nothing and skips
  nothing);
- ``resume_bitwise`` — the killed run's decision fingerprints (cost/
  carbon/node/profile per tick) match the baseline's exactly;
- ``ticks_to_reconverge`` — post-kill ticks until the fingerprint
  streams agree and stay agreed (0 under the bitwise invariant);
- ``usd_per_slo_hr_vs_baseline`` — paired $/SLO-hour ratio killed vs
  uninterrupted (1.0 under the invariant; the board states it rather
  than assuming it).

Signal-side faults ride along: each intensity pairs its `CHAOS_PRESETS`
actuation preset with a stale-scrape fraction driven through the
degraded-mode state machine — the "combined signal+actuation" stress the
round-12 issue asks for. Used by `bench.py bench_recovery` (BASELINE
round12) and the `ccka recover-eval` CLI.
"""

from __future__ import annotations

import dataclasses
import os
import random
import shutil
import sys
import tempfile
from collections import Counter

import numpy as np

from ccka_tpu.config import CHAOS_PRESETS, FrameworkConfig

# Stale-scrape fraction paired with each actuation intensity: the signal
# half of "combined signal+actuation fault intensities". Kept mild —
# the degraded-mode machine (not the scoreboard) is what a stale tick
# exercises; heavy outage sweeps live on the round-10 board.
SIGNAL_STALE_FRAC = {"off": 0.0, "mild": 0.04, "moderate": 0.08,
                     "severe": 0.15}

_KNOWN_POLICIES = ("rule", "carbon", "flagship")


def _fingerprint(report) -> tuple:
    """The per-tick decision/estimate identity used for bitwise
    comparison: everything here derives deterministically from (state,
    action, exo), so equality across a kill is equality of the decision
    stream. Timings and snapshot ages are deliberately excluded."""
    return (report.t, report.profile, report.is_peak,
            report.cost_usd_hr, report.carbon_g_hr, report.nodes_spot,
            report.nodes_od, report.pending_pods, report.slo_ok)


def _usd_per_slo_hr(reports, dt_s: float) -> float:
    dt_hr = dt_s / 3600.0
    cost = sum(r.cost_usd_hr for r in reports) * dt_hr
    slo_hr = sum(1.0 for r in reports if r.slo_ok) * dt_hr
    return cost / max(slo_hr, 1e-9)


class _FlakyStaleSource:
    """Wrap a SignalSource with a seeded stale-scrape schedule.

    Staleness is a pure function of (tick, seed), so baseline and killed
    runs sharing a controller seed see the SAME outage realization —
    including a resumed controller, whose source object is brand new."""

    def __init__(self, inner, stale_frac: float):
        self._inner = inner
        self.stale_frac = float(stale_frac)
        self.last_scrape_stale = False

    def tick(self, t_index: int, *, seed: int = 0):
        out = self._inner.tick(t_index, seed=seed)
        if self.stale_frac > 0.0:
            r = np.random.default_rng(
                [0x57A1E, int(seed), int(t_index)]).random()
            self.last_scrape_stale = bool(r < self.stale_frac)
        else:
            self.last_scrape_stale = False
        return out

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _make_controller(cfg, backend, source, sink, *, seed: int,
                     snapshot_path: str = ""):
    from ccka_tpu.harness.controller import Controller

    return Controller(cfg, backend, source, sink, interval_s=0.0,
                      seed=seed, log_fn=lambda s: None,
                      snapshot_path=snapshot_path,
                      reconcile_backoff_s=0.0)


def _run_pair(cfg, backend, preset, stale_frac: float, *,
              ticks: int, seed: int, kill_tick: int,
              snap_path: str) -> dict:
    """One paired (baseline, killed+resumed) run; returns its metrics."""
    from ccka_tpu.actuation.chaos import ChaosSink
    from ccka_tpu.actuation.sink import DryRunSink
    from ccka_tpu.harness.snapshot import load_snapshot
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    def make_source():
        return _FlakyStaleSource(
            SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                  cfg.signals),
            stale_frac)

    # Baseline: uninterrupted.
    sink_b = DryRunSink()
    ctrl = _make_controller(cfg, backend, make_source(),
                            ChaosSink(sink_b, preset, seed=seed),
                            seed=seed)
    base_reports = ctrl.run(ticks)
    ctrl.close()
    base_fp = [_fingerprint(r) for r in base_reports]
    base_cmds = [c.render() for c in sink_b.commands]

    # Killed: run to kill_tick, discard the controller (the process
    # dies; the cluster — sink + chaos RNG — survives), construct a
    # fresh one, restore, finish. The source is rebuilt too: a new
    # process would re-create it from config exactly like this.
    sink_k = DryRunSink()
    chaos_k = ChaosSink(sink_k, preset, seed=seed)
    ctrl1 = _make_controller(cfg, backend, make_source(), chaos_k,
                             seed=seed, snapshot_path=snap_path)
    pre = ctrl1.run(kill_tick)
    ctrl1.close()
    del ctrl1
    ctrl2 = _make_controller(cfg, backend, make_source(), chaos_k,
                             seed=seed, snapshot_path=snap_path)
    start = ctrl2.restore(load_snapshot(snap_path))
    post = ctrl2.run(ticks - start, start_tick=start)
    ctrl2.close()
    kill_reports = pre + post
    kill_fp = [_fingerprint(r) for r in kill_reports]
    kill_cmds = [c.render() for c in sink_k.commands]

    dup = sum((Counter(kill_cmds) - Counter(base_cmds)).values())
    lost = sum((Counter(base_cmds) - Counter(kill_cmds)).values())
    bitwise = kill_fp == base_fp and kill_cmds == base_cmds
    # Ticks past the kill point until the fingerprint streams agree and
    # STAY agreed (0 when the resume is bitwise). Never-reconverged —
    # the LAST tick still disagrees — reports ticks-kill_tick+1, one
    # past any genuine convergence, so a permanent divergence can never
    # masquerade as late convergence on the board.
    reconverge = ticks - kill_tick + 1
    for i in range(kill_tick, ticks):
        if kill_fp[i:] == base_fp[i:]:
            reconverge = i - kill_tick
            break
    dt_s = float(cfg.sim.dt_s)
    base_usd = _usd_per_slo_hr(base_reports, dt_s)
    kill_usd = _usd_per_slo_hr(kill_reports, dt_s)
    return {
        "kill_tick": kill_tick,
        "duplicate_patches": dup,
        "lost_patches": lost,
        "resume_bitwise": bitwise,
        "ticks_to_reconverge": reconverge,
        "usd_ratio": kill_usd / max(base_usd, 1e-9),
        "reconcile_retries": kill_reports[-1].reconcile_retries_total,
        "actuation_failures": kill_reports[-1].actuation_failures_total,
        "degraded_ticks": kill_reports[-1].degraded_ticks_total,
        "resumes": kill_reports[-1].resumes_total,
        "chaos": dict(chaos_k.stats),
    }


def recovery_scoreboard(cfg: FrameworkConfig, *,
                        policies=("rule", "flagship"),
                        intensities=("off", "mild", "moderate", "severe"),
                        runs_per_cell: int = 8,
                        ticks: int = 32,
                        seed: int = 101,
                        snapshot_dir: str | None = None) -> dict:
    """The round-12 recovery board (module docstring). ``intensities``
    must name `config.CHAOS_PRESETS` entries; ``policies`` is a subset
    of {rule, carbon, flagship} — unknown names are rejected up front,
    matching the chaos-eval/scenario-eval convention."""
    from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy
    from ccka_tpu.train.flagship import load_flagship_backend

    bad = [i for i in intensities if i not in CHAOS_PRESETS]
    if bad:
        raise ValueError(f"unknown chaos intensities {bad}; presets: "
                         f"{sorted(CHAOS_PRESETS)}")
    bad = [p for p in policies if p not in _KNOWN_POLICIES]
    if bad:
        raise ValueError(f"unknown policies {bad}; known: "
                         f"{list(_KNOWN_POLICIES)} — a typo here would "
                         f"otherwise run the full sweep and emit a board "
                         f"missing that row")
    if ticks < 4:
        raise ValueError("recovery runs need ticks >= 4 (a kill point "
                         "strictly inside the run)")

    backends: dict[str, object] = {}
    out: dict = {
        "engine": "controller(dry-run chaos harness, reconciler, "
                  "snapshot/resume)",
        "ticks_per_run": ticks,
        "runs_per_cell": runs_per_cell,
        "seed": seed,
        "policies": list(policies),
        # "intensities" lists the names; "cells" holds the per-
        # {intensity x policy} rows — the SAME schema BASELINE round12
        # embeds and test_doc_sync parses, so the record path is
        # paste-through (no hand restructuring between bench and record).
        "intensities": list(intensities),
        "cells": {},
    }
    for p in policies:
        if p == "rule":
            backends[p] = RulePolicy(cfg.cluster)
        elif p == "carbon":
            backends[p] = CarbonAwarePolicy(cfg.cluster)
        else:
            flagship, meta = load_flagship_backend(cfg)
            if flagship is None:
                out["flagship_source"] = (
                    "omitted: no flagship checkpoint for this topology "
                    "(no stand-ins)")
                continue
            out["flagship_source"] = {
                "checkpoint": "topology-keyed flagship",
                "selected_iteration": meta.get("selected_iteration")}
            backends[p] = flagship

    tmp = snapshot_dir or tempfile.mkdtemp(prefix="ccka-recovery-")
    owns_tmp = snapshot_dir is None
    n_paired = 0
    try:
        for name in intensities:
            preset = CHAOS_PRESETS[name]
            stale_frac = SIGNAL_STALE_FRAC.get(name, 0.0)
            rows: dict[str, dict] = {}
            for pname, backend in backends.items():
                rng = random.Random((seed, name, pname).__repr__())
                pairs = []
                for i in range(runs_per_cell):
                    run_seed = seed + 7919 * i
                    kill_tick = rng.randrange(1, ticks - 1)
                    snap_path = os.path.join(
                        tmp, f"{name}-{pname}-{i}.snap")
                    pairs.append(_run_pair(
                        cfg, backend, preset, stale_frac, ticks=ticks,
                        seed=run_seed, kill_tick=kill_tick,
                        snap_path=snap_path))
                    n_paired += 1
                ratios = np.asarray([p["usd_ratio"] for p in pairs])
                rows[pname] = {
                    "n_pairs": len(pairs),
                    "duplicate_patches_total": int(
                        sum(p["duplicate_patches"] for p in pairs)),
                    "lost_patches_total": int(
                        sum(p["lost_patches"] for p in pairs)),
                    "resume_bitwise_frac": round(
                        float(np.mean([p["resume_bitwise"]
                                       for p in pairs])), 4),
                    "ticks_to_reconverge_mean": round(float(np.mean(
                        [p["ticks_to_reconverge"] for p in pairs])), 4),
                    "ticks_to_reconverge_max": int(max(
                        p["ticks_to_reconverge"] for p in pairs)),
                    "usd_per_slo_hr_vs_baseline": round(
                        float(ratios.mean()), 6),
                    "usd_per_slo_hr_vs_baseline_se": round(
                        float(ratios.std(ddof=1) / np.sqrt(ratios.size))
                        if ratios.size >= 2 else 0.0, 6),
                    "reconcile_retries_mean": round(float(np.mean(
                        [p["reconcile_retries"] for p in pairs])), 3),
                    "actuation_failures_mean": round(float(np.mean(
                        [p["actuation_failures"] for p in pairs])), 3),
                    "degraded_ticks_mean": round(float(np.mean(
                        [p["degraded_ticks"] for p in pairs])), 3),
                    "kill_ticks": [p["kill_tick"] for p in pairs],
                    "chaos_injected": {
                        k: int(sum(p["chaos"][k] for p in pairs))
                        for k in ("timeouts", "transient_exits",
                                  "dropped", "rewrites")},
                }
                print(f"# recovery[{name}/{pname}]: "
                      f"bitwise={rows[pname]['resume_bitwise_frac']:.2f} "
                      f"dup={rows[pname]['duplicate_patches_total']} "
                      f"lost={rows[pname]['lost_patches_total']} "
                      f"usd_ratio="
                      f"{rows[pname]['usd_per_slo_hr_vs_baseline']:.4f}",
                      file=sys.stderr)
            out["cells"][name] = {
                "chaos": dataclasses.asdict(preset),
                "signal_stale_frac": stale_frac,
                "rows": rows,
            }
    finally:
        if owns_tmp:
            shutil.rmtree(tmp, ignore_errors=True)
    out["n_paired_runs"] = n_paired
    out["invariants"] = {
        "duplicate_patches_total": int(sum(
            r["duplicate_patches_total"]
            for sec in out["cells"].values()
            for r in sec["rows"].values())),
        "lost_patches_total": int(sum(
            r["lost_patches_total"]
            for sec in out["cells"].values()
            for r in sec["rows"].values())),
        "resume_bitwise_frac": round(float(np.mean([
            r["resume_bitwise_frac"]
            for sec in out["cells"].values()
            for r in sec["rows"].values()])), 4),
    }
    return out
