"""Fleet-scale control: ONE batched on-device decide, N per-cluster sinks.

BASELINE.json config #5 / report PDF p.4 §9: the reference's productization
story is per-region clusters sustaining 25k req/min — a *fleet* of control
loops. Round 2 had fleet-scale *simulation* (10k clusters × a day in
0.23s) but the controller itself was single-fleet (VERDICT r2 missing #5).
This module is the control half: the policy network / rule logic runs once
per tick as a single `vmap`-batched, jitted function over all N cluster
states (one MXU-shaped [N, F]×[F, H] matmul instead of N dispatches), and
only the rendered per-cluster NodePool patches fan out host-side to each
cluster's ActuationSink — the same host/device split the single-cluster
controller uses, scaled sideways.

TPU mapping (round-4 rework, VERDICT r3 weak #5/#6): the profiled cost of
a fleet tick was never the decide math — it was host↔device round trips
(a tunneled chip pays ~100ms per dispatch/transfer; round-3 spent ~8 of
them per tick on eager exo slicing, a host-side PRNG split, and one
device→host pull per aggregate metric). Now one tick is:

- ONE dispatch: trace slicing (`dynamic_index_in_dim` on the traced tick
  index), PRNG fold-in, batched decide, expectation-dynamics estimate and
  fleet-aggregate reduction all live inside the jitted ``fleet_tick``;
- ONE device→host transfer: actions + is_peak pack into a single
  [N, A+1] array, aggregates into one [4] vector, and the copy starts
  asynchronously (`copy_to_host_async`) the moment the dispatch is queued;
- pipelined ticks: `run()` dispatches tick t+1 *before* harvesting and
  fanning out tick t, so the device round trip rides under the host
  render+apply work (sound because sink results never feed the
  on-device state estimate — the loop is open at the actuation edge);
- thread-pooled fan-out: per-sink render+apply goes through a worker
  pool in contiguous chunks — pure-Python dry-run sinks stay GIL-bound,
  but live kubectl sinks block in subprocesses, which is exactly where
  threads buy wall-clock.
"""

from __future__ import annotations

import dataclasses
import functools
from concurrent.futures import ThreadPoolExecutor
from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ccka_tpu.actuation.patches import render_nodepool_patches
from ccka_tpu.actuation.reconcile import Reconciler
from ccka_tpu.actuation.sink import ActuationSink
from ccka_tpu.config import FrameworkConfig
from ccka_tpu.policy.base import PolicyBackend
from ccka_tpu.sim.dynamics import step as sim_step
from ccka_tpu.sim.rollout import exo_steps, initial_state
from ccka_tpu.sim.types import Action, ClusterState, N_CT, SimParams
from ccka_tpu.signals.base import SignalSource


@dataclasses.dataclass
class FleetTickReport:
    """One fleet tick: aggregate KPIs + per-cluster apply health."""

    t: int
    n_clusters: int
    applied: int               # clusters whose patches all applied
    slo_ok: int                # clusters meeting the SLO gate this tick
    cost_usd_hr: float         # fleet-total spend rate
    carbon_g_hr: float         # fleet-total emission rate
    pending_pods: float        # fleet-total backlog
    decide_ms: float           # host time blocked on device work
    fanout_ms: float           # host render + sink apply


@dataclasses.dataclass
class _Dispatched:
    """In-flight device work for one tick (double-buffer slot)."""

    t: int
    packed: jax.Array          # [N, A+1] actions ++ is_peak column
    # [N, W] per-cluster rows: slo_ok, cost, carbon, pending in the
    # first four columns (the pre-round-18 block every consumer
    # indexes), then the decision-provenance columns + rule-shadow
    # action (`obs/decisions.decision_row_layout`).
    per_metrics: jax.Array
    dispatch_ms: float


def action_layout(cluster) -> tuple[list[tuple], list[int]]:
    """Host-side (shapes, sizes) unpack plan for a packed action row,
    derived from a template Action so it tracks the NamedTuple's field
    order and leaf shapes by construction. Shared by the fleet
    controller and the multi-tenant service (`harness/service.py`)."""
    template = Action.neutral(cluster.n_pools, cluster.n_zones)
    shapes = [tuple(leaf.shape) for leaf in template]
    sizes = [int(np.prod(s)) for s in shapes]
    return shapes, sizes


def unpack_action_row(row: np.ndarray, shapes, sizes) -> Action:
    """One packed [A] row (is_peak column already stripped) -> Action."""
    leaves, off = [], 0
    for shape, size in zip(shapes, sizes):
        leaves.append(row[off:off + size].reshape(shape))
        off += size
    return Action(*leaves)


# -- shared device-side tick pieces (used by this module's batched tick
# AND the service layer's lane-selecting variant, so the packed-row and
# per-metrics layouts cannot drift apart between the two builders) ------


def exo_at(xs_all, t, horizon_ticks: int):
    """Slice every [N, T, ...] trace leaf at tick t (mod horizon)."""
    t_mod = jnp.mod(t, horizon_ticks)
    return jax.tree.map(
        lambda x: jax.lax.dynamic_index_in_dim(
            x, t_mod, axis=1, keepdims=False), xs_all)


def flatten_actions(actions, n: int) -> jnp.ndarray:
    """Batched Action pytree -> [N, A] packed rows (field order)."""
    return jnp.concatenate(
        [jnp.reshape(a, (n, -1)) for a in actions], axis=-1)


def pack_rows(flat: jnp.ndarray, exo_n) -> jnp.ndarray:
    """[N, A] action rows ++ the is_peak column -> [N, A+1]."""
    return jnp.concatenate(
        [flat, (exo_n.is_peak > 0.5).astype(jnp.float32)[:, None]],
        axis=-1)


def per_cluster_metrics(metrics) -> jnp.ndarray:
    """StepMetrics -> [N, 4] rows: slo_ok, cost, carbon, pending."""
    return jnp.stack([
        metrics.slo_ok.astype(jnp.float32),
        metrics.cost_usd,
        metrics.carbon_g,
        metrics.pending_pods.sum(axis=tuple(range(
            1, metrics.pending_pods.ndim))),
    ], axis=-1)


@functools.lru_cache(maxsize=32)
def _compiled_fleet_tick(cfg: FrameworkConfig, backend,
                         n: int, horizon_ticks: int):
    """The batched fleet tick, jitted ONCE per (config, backend, fleet
    size, horizon) — the config-keyed shared-compile idiom from the
    round-12 `_compiled_steps` fix. Pre-round-13 every FleetController
    closed a fresh lambda over its own traces, so the overload
    scoreboard's paired stressed/calm services (and any resumed fleet)
    would each pay a full XLA compile; keying on the BACKEND instance
    (identity-hashed, like the forecaster cache keys on config) keeps
    the cache sound — `backend.action_fn()` mints a fresh closure per
    call and must therefore be resolved INSIDE the cached builder —
    while trace arrays move to arguments. Returns (packed [N, A+1],
    new_states, per_metrics [N, W]) — per-CLUSTER metric rows whose
    FIRST FOUR columns are the pre-round-18 slo_ok/cost/carbon/pending
    block (every existing consumer indexes those positions), followed
    by the decision-provenance columns and the rule-shadow action
    (`obs/decisions.decision_row_layout`): the rule profile evaluated
    on the SAME states and observed exo inside the SAME dispatch, so
    callers that need per-tenant accounting OR decision provenance
    read both without a second transfer. The shadow lanes run
    UNCONDITIONALLY — a ledger toggling on can never select a
    different XLA program, which is what makes ledger-on/off bitwise
    non-interference hold by construction. Fleet aggregates are a
    host-side sum over the first four columns.

    Round 21: the fleet-service tick (`service._build_service_tick`)
    shares these helpers and this cache discipline, and its chunked
    tenant-axis dispatch keys the cache on the CHUNK size — a 10^4
    tenant sweep whose cells all dispatch 256-wide chunks under one
    uniform horizon compiles exactly one program, which is why the
    fleet-scale record's upper cells carry no per-N recompile cost."""
    from ccka_tpu.obs.compile import watch_jit
    from ccka_tpu.obs.decisions import shadow_decision_columns
    from ccka_tpu.obs.tournament import (TournamentRoster,
                                         add_candidate_lanes)
    from ccka_tpu.policy.rule import RulePolicy

    action_fn = backend.action_fn()
    shadow_fn = RulePolicy(cfg.cluster).action_fn()
    params = SimParams.from_config(cfg)
    # Shadow-tournament lanes (round 20): the roster rides cfg.obs —
    # program-shaping names resolved INSIDE the cached builder like
    # the rule shadow, so the cache key stays (config, backend, n,
    # horizon). An empty roster (the default) compiles EXACTLY the
    # round-18 program.
    cand_fns = TournamentRoster(
        cfg, cfg.obs.tournament_roster).action_fns()
    zone_region_index = cfg.cluster.zone_region_index
    n_regions = cfg.cluster.n_regions

    @jax.jit
    def fleet_tick(states, xs_all, t, key):
        """One dispatch: slice exo, decide (+ rule shadow + tournament
        candidates), estimate all, pack per-cluster."""
        exo_n = exo_at(xs_all, t, horizon_ticks)
        actions = jax.vmap(lambda s, e: action_fn(s, e, t))(states, exo_n)
        shadow = jax.vmap(lambda s, e: shadow_fn(s, e, t))(states, exo_n)
        keys = jax.random.split(jax.random.fold_in(key, t), n)
        step_n = jax.vmap(partial(sim_step, params, stochastic=False))
        new_states, metrics = step_n(states, actions, exo_n, keys)
        # Counterfactual one-step projection: same pre-step states,
        # same exo, same keys — only the action differs. The shadow's
        # next state is discarded (the real estimate chain must not
        # fork); only its step metrics ride out.
        _sh_states, sh_metrics = step_n(states, shadow, exo_n, keys)
        flat = flatten_actions(actions, n)
        flat_sh = flatten_actions(shadow, n)
        packed = pack_rows(flat, exo_n)
        blocks = [
            per_cluster_metrics(metrics),
            shadow_decision_columns(metrics, sh_metrics, exo_n,
                                    flat, flat_sh),
            flat_sh,
        ]
        if cand_fns:
            # K candidate lanes through the SAME expectation dynamics
            # on the SAME inputs — computed unconditionally, so the
            # host-side tournament ledger toggling can never select a
            # different program (obs/tournament.py).
            blocks.append(add_candidate_lanes(
                states, exo_n, t, keys, flat, cand_fns, step_n, n,
                zone_region_index, n_regions))
        per = jnp.concatenate(blocks, axis=-1)
        return packed, new_states, per

    # Watched jit (obs/compile.py): the batched decide is THE fleet
    # hot path — one warmup compile is expected; any recompile after
    # it (a leaked static-arg rebind) warns loudly. shared_stats: every
    # fleet/service instance of one config accumulates into one entry.
    return watch_jit(fleet_tick, "fleet.tick", hot=True,
                     shared_stats=True)


class FleetController:
    """N homogeneous clusters, one batched decide, N sinks.

    ``sinks`` is one ActuationSink per cluster (dry-run in tests; kubectl
    with per-cluster contexts live — `actuation.sink.context_runner`).
    Traces are pre-synthesized on device for ``horizon_ticks``; each
    cluster gets an independent stream (distinct PRNG fold per index).

    ``fanout_workers``: thread-pool width for the per-sink render+apply
    fan-out (the sinks must be thread-safe for concurrent *distinct-sink*
    use, which both DryRunSink and subprocess-backed KubectlSink are;
    no sink is ever driven from two workers at once).
    """

    def __init__(self, cfg: FrameworkConfig, backend: PolicyBackend,
                 source: SignalSource, sinks: Sequence[ActuationSink],
                 *, horizon_ticks: int = 2880, seed: int = 0,
                 fanout_workers: int = 8, tracer=None, ledger=None,
                 incident_log=None,
                 log_fn: Callable[[str], None] | None = None):
        from ccka_tpu.obs.trace import SpanTracer
        if not hasattr(source, "batch_trace_device"):
            raise ValueError(
                "FleetController needs a device-batched signal source "
                "(synthetic); replay/live fleets should shard per-cluster "
                "sources onto per-cluster controllers instead")
        self.cfg = cfg
        self.backend = backend
        self.sinks = list(sinks)
        self.n = len(self.sinks)
        # Desired-state reconciliation per cluster (round 12): the
        # fan-out converges each sink onto its rendered patches (retry +
        # read-back) instead of one-shot apply_all — same discipline as
        # the single-cluster controller, and the AST guard pins that no
        # harness code bypasses it. Backoff is kept tiny: a fleet tick
        # has a 30s budget and the worker pool already parallelizes
        # per-sink stalls.
        self._reconcilers = [
            Reconciler(s, max_rounds=2, backoff_s=0.01, deadline_s=2.0,
                       seed=seed ^ (0x5EC0 + i))
            for i, s in enumerate(self.sinks)]
        self.params = SimParams.from_config(cfg)
        self.log_fn = log_fn or (lambda s: None)
        # Shared span tracer (obs/trace.py): dispatch/harvest/fanout spans
        # per tick, exportable as one Chrome trace. The default is
        # retention-bounded: a fleet daemon ticks forever and its owner
        # may never export, so unbounded span accumulation on the hot
        # loop would be a slow leak; pass an unbounded tracer to keep a
        # full-session trace.
        self.tracer = tracer or SpanTracer(max_spans=4096)
        n = self.n

        self._traces = source.batch_trace_device(
            horizon_ticks, jax.random.key(seed), n)
        self.horizon_ticks = horizon_ticks
        self._seed = seed
        base = initial_state(cfg)
        self.states: ClusterState = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n,) + x.shape), base)
        self.key = jax.random.key(seed + 1)

        # Host-side unpack plan for the packed action row (the device
        # pack iterates the same fields; trailing column is is_peak).
        self._action_shapes, self._action_sizes = action_layout(
            cfg.cluster)
        self._pool = (ThreadPoolExecutor(max_workers=fanout_workers,
                                         thread_name_prefix="ccka-fanout")
                      if fanout_workers > 1 else None)
        self._workers = max(1, fanout_workers)

        self._xs_all = exo_steps(self._traces)    # [N, T, ...] device pytree
        # Config-keyed shared compile (see `_compiled_fleet_tick`):
        # traces are an argument, so every fleet/service of this
        # (config, backend, N, horizon) shares ONE XLA program.
        self._tick_fn = _compiled_fleet_tick(cfg, backend, n,
                                             horizon_ticks)
        # Decision-provenance ledger (obs/decisions.py; None disables
        # recording — the shadow lanes ride the compiled tick either
        # way, so attaching one later never triggers a recompile).
        # Owned by the caller (the service closes its own). With an
        # incident_log attached too, a windowed divergence spike
        # stamps the same policy_divergence incident the service and
        # single-cluster paths stamp — the spikes==incidents 1:1
        # invariant must hold from every entry point.
        self.ledger = ledger
        self.incident_log = incident_log
        from ccka_tpu.obs.decisions import decision_row_layout
        self._dec_layout = decision_row_layout(
            cfg.cluster, candidates=cfg.obs.tournament_roster)

    def _fleet_tick(self, states, t, key):
        """The batched tick over this fleet's traces (kept as a bound
        3-arg entry point: tests probe it directly)."""
        return self._tick_fn(states, self._xs_all, t, key)

    # -- device side --------------------------------------------------------

    def _dispatch(self, t: int) -> _Dispatched:
        """Queue tick t's device work; start its host copy; don't block."""
        # Deliberately UNFENCED span: this measures host time to *queue*
        # the tick (the pipelining design point), never device execution
        # — the device chain is timed as its own fenced region by
        # bench_fleet. A fence here would serialize the pipeline.
        with self.tracer.span("fleet.dispatch", t=t) as sp:
            packed, new_states, per = self._fleet_tick(
                self.states, jnp.int32(t), self.key)
            self.states = new_states
            # Start the device→host copy immediately so it overlaps the
            # previous tick's fan-out (harvest then finds it already
            # local).
            for arr in (packed, per):
                if hasattr(arr, "copy_to_host_async"):
                    arr.copy_to_host_async()
        return _Dispatched(t=t, packed=packed, per_metrics=per,
                           dispatch_ms=sp.dur_ms)

    # -- host side ----------------------------------------------------------

    def _unpack_action(self, row: np.ndarray) -> Action:
        return unpack_action_row(row, self._action_shapes,
                                 self._action_sizes)

    def _fanout(self, packed: np.ndarray) -> int:
        """Render + apply every cluster's patches; returns #applied-ok."""
        def chunk(lo: int, hi: int) -> int:
            ok = 0
            for i in range(lo, hi):
                a_i = self._unpack_action(packed[i, :-1])
                is_peak = packed[i, -1] > 0.5
                patches = render_nodepool_patches(
                    a_i, self.cfg.cluster,
                    op="add" if is_peak else "replace")
                ok += self._reconcilers[i].converge(patches).converged
            return ok

        # Width adapts to the fleet: a 12-cluster live fleet still spreads
        # its (subprocess-blocking) kubectl applies over 12 workers.
        w = min(self._workers, self.n)
        if self._pool is None or w <= 1:
            return chunk(0, self.n)
        bounds = np.linspace(0, self.n, w + 1).astype(int)
        futures = [self._pool.submit(chunk, int(lo), int(hi))
                   for lo, hi in zip(bounds[:-1], bounds[1:])]
        return sum(f.result() for f in futures)

    def _harvest_and_fanout(self, disp: _Dispatched) -> FleetTickReport:
        # The harvest span DOES block (np.asarray pulls the device
        # arrays), so decide_ms = dispatch + harvest is host time blocked
        # on device work — near zero when pipelining hides the chain.
        with self.tracer.span("fleet.harvest", t=disp.t) as sp_h:
            packed = np.asarray(disp.packed)  # no-op if async copy landed
            per_np = np.asarray(disp.per_metrics)
            # Fleet aggregates are a host sum over the per-cluster
            # KPI block (columns 0..3; the decision-provenance tail
            # feeds the ledger, not the KPI line).
            agg = per_np[:, :4].sum(axis=0)
        # Decision provenance (round 18): host-side recording strictly
        # AFTER the tick's decisions, before fan-out — the rows explain
        # the patches about to go out. A bare fleet tick has no lane
        # machinery, so every row records as the fresh lane.
        if self.ledger is not None:
            surfaces = self.ledger.observe_tick(disp.t, per_np, packed,
                                                self._dec_layout)
            spike = surfaces.get("spike")
            if spike is not None and self.incident_log is not None:
                self.incident_log.stamp("policy_divergence", t=disp.t,
                                        **spike)
        with self.tracer.span("fleet.fanout", t=disp.t) as sp_f:
            applied = self._fanout(packed)

        dt_hr = float(self.params.dt_s) / 3600.0
        report = FleetTickReport(
            t=disp.t,
            n_clusters=self.n,
            applied=applied,
            slo_ok=int(agg[0]),
            cost_usd_hr=float(agg[1]) / dt_hr,
            carbon_g_hr=float(agg[2]) / dt_hr,
            pending_pods=float(agg[3]),
            decide_ms=round(disp.dispatch_ms + sp_h.dur_ms, 3),
            fanout_ms=round(sp_f.dur_ms, 3),
        )
        self.log_fn(
            f"fleet t={report.t}: {report.applied}/{self.n} applied, "
            f"{report.slo_ok}/{self.n} slo-ok, "
            f"${report.cost_usd_hr:.2f}/hr, decide {report.decide_ms}ms, "
            f"fanout {report.fanout_ms}ms")
        return report

    def tick(self, t: int) -> FleetTickReport:
        """Synchronous single tick (tests / cadenced live loops)."""
        return self._harvest_and_fanout(self._dispatch(t))

    # -- durable snapshot / resume (ARCHITECTURE §14) -----------------------
    #
    # The fleet's device state is the [N, ...] ClusterState batch plus a
    # CONSTANT key (ticks fold t in, the key never advances), so resume
    # is states + tick index; traces regenerate deterministically from
    # (source, seed) at construction. Same codec + identity checks as
    # the single-cluster controller.

    def snapshot_body(self, next_tick: int) -> dict:
        from ccka_tpu.harness import snapshot as snap

        return {
            "kind": "fleet",
            "next_tick": int(next_tick),
            "n_clusters": int(self.n),
            "seed": int(self._seed),
            "horizon_ticks": int(self.horizon_ticks),
            "config_sha256": snap.config_digest(self.cfg),
            "prng_key": snap.encode_key(self.key),
            "states": snap.encode_tree(self.states),
        }

    def write_snapshot(self, path: str, next_tick: int) -> str:
        from ccka_tpu.harness.snapshot import save_snapshot
        return save_snapshot(path, self.snapshot_body(next_tick))

    def restore(self, body: dict) -> int:
        """Restore device state from a snapshot body; returns the resume
        tick. Identity mismatches (config, fleet size, seed) are refused
        — see Controller.restore for why loudness matters here."""
        from ccka_tpu.harness import snapshot as snap

        if body.get("kind") != "fleet":
            raise snap.SnapshotError(
                f"snapshot kind {body.get('kind')!r} is not a fleet "
                "snapshot")
        if body.get("config_sha256") != snap.config_digest(self.cfg):
            raise snap.SnapshotError(
                "fleet snapshot was taken under a different config")
        if int(body.get("n_clusters", -1)) != self.n:
            raise snap.SnapshotError(
                f"fleet snapshot holds {body.get('n_clusters')} clusters, "
                f"this controller drives {self.n}")
        if (int(body.get("seed", -1)) != self._seed
                or int(body.get("horizon_ticks", -1))
                != self.horizon_ticks):
            raise snap.SnapshotError(
                "fleet snapshot seed/horizon mismatch — the exo streams "
                "would fork from the run being resumed")
        self.key = snap.decode_key(body["prng_key"])
        self.states = snap.decode_like(self.states, body["states"])
        return int(body["next_tick"])

    def run(self, ticks: int, start_tick: int = 0, *,
            pipeline_depth: int = 2) -> list[FleetTickReport]:
        """Pipelined loop: up to ``pipeline_depth`` ticks of device work
        stay in flight ahead of the host harvest+fanout, so the device
        compute/copy chain rides under host actuation (sound because
        actuation results never feed the on-device estimate — the loop is
        open at the sink edge; state estimates chain purely on device).
        Depth 2 fully hides a ~30ms device chain under a ~70ms fan-out on
        a tunneled chip; deeper only defers reporting. Peak in-flight is
        briefly ``depth + 1`` (the new dispatch is issued before the
        oldest is harvested — harvesting first would serialize
        ``depth=1`` into the unpipelined tick loop)."""
        from collections import deque

        depth = max(1, pipeline_depth)
        reports: list[FleetTickReport] = []
        inflight: deque[_Dispatched] = deque()
        for t in range(start_tick, start_tick + ticks):
            inflight.append(self._dispatch(t))
            if len(inflight) > depth:
                reports.append(self._harvest_and_fanout(inflight.popleft()))
        while inflight:
            reports.append(self._harvest_and_fanout(inflight.popleft()))
        return reports

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None


def fleet_controller_from_config(cfg: FrameworkConfig,
                                 backend: PolicyBackend, n_clusters: int,
                                 *, horizon_ticks: int = 2880,
                                 seed: int = 0, fanout_workers: int = 8,
                                 log_fn=None) -> FleetController:
    """Dry-run fleet wiring: N in-memory sinks over the synthetic source.
    Live fleets construct FleetController directly with per-cluster
    KubectlSinks (`context_runner` per kube-context)."""
    from ccka_tpu.actuation.sink import DryRunSink
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    source = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                   cfg.signals)
    sinks = [DryRunSink() for _ in range(n_clusters)]
    return FleetController(cfg, backend, source, sinks,
                           horizon_ticks=horizon_ticks, seed=seed,
                           fanout_workers=fanout_workers, log_fn=log_fn)
