"""Fleet-scale control: ONE batched on-device decide, N per-cluster sinks.

BASELINE.json config #5 / report PDF p.4 §9: the reference's productization
story is per-region clusters sustaining 25k req/min — a *fleet* of control
loops. Round 2 had fleet-scale *simulation* (10k clusters × a day in
0.23s) but the controller itself was single-fleet (VERDICT r2 missing #5).
This module is the control half: the policy network / rule logic runs once
per tick as a single `vmap`-batched, jitted function over all N cluster
states (one MXU-shaped [N, F]×[F, H] matmul instead of N dispatches), and
only the rendered per-cluster NodePool patches fan out host-side to each
cluster's ActuationSink — the same host/device split the single-cluster
controller uses, scaled sideways.

TPU mapping: decide+estimate is one jitted call on [N, ...] pytrees;
exogenous traces are synthesized on device up front (`batch_trace_device`)
and sliced per tick, so the steady-state loop moves one [N, A] action
tensor device→host per tick and nothing host→device at all.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ccka_tpu.actuation.patches import render_nodepool_patches
from ccka_tpu.actuation.sink import ActuationSink
from ccka_tpu.config import FrameworkConfig
from ccka_tpu.policy.base import PolicyBackend
from ccka_tpu.sim.dynamics import step as sim_step
from ccka_tpu.sim.rollout import exo_steps, initial_state
from ccka_tpu.sim.types import Action, ClusterState, SimParams
from ccka_tpu.signals.base import SignalSource


@dataclasses.dataclass
class FleetTickReport:
    """One fleet tick: aggregate KPIs + per-cluster apply health."""

    t: int
    n_clusters: int
    applied: int               # clusters whose patches all applied
    slo_ok: int                # clusters meeting the SLO gate this tick
    cost_usd_hr: float         # fleet-total spend rate
    carbon_g_hr: float         # fleet-total emission rate
    pending_pods: float        # fleet-total backlog
    decide_ms: float           # batched decide+estimate (device)
    fanout_ms: float           # host render + sink apply


class FleetController:
    """N homogeneous clusters, one batched decide, N sinks.

    ``sinks`` is one ActuationSink per cluster (dry-run in tests; kubectl
    with per-cluster contexts live — `actuation.sink.context_runner`).
    Traces are pre-synthesized on device for ``horizon_ticks``; each
    cluster gets an independent stream (distinct PRNG fold per index).
    """

    def __init__(self, cfg: FrameworkConfig, backend: PolicyBackend,
                 source: SignalSource, sinks: Sequence[ActuationSink],
                 *, horizon_ticks: int = 2880, seed: int = 0,
                 log_fn: Callable[[str], None] | None = None):
        if not hasattr(source, "batch_trace_device"):
            raise ValueError(
                "FleetController needs a device-batched signal source "
                "(synthetic); replay/live fleets should shard per-cluster "
                "sources onto per-cluster controllers instead")
        self.cfg = cfg
        self.backend = backend
        self.sinks = list(sinks)
        self.n = len(self.sinks)
        self.params = SimParams.from_config(cfg)
        self.log_fn = log_fn or (lambda s: None)
        n = self.n

        self._traces = source.batch_trace_device(
            horizon_ticks, jax.random.key(seed), n)
        self.horizon_ticks = horizon_ticks
        base = initial_state(cfg)
        self.states: ClusterState = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n,) + x.shape), base)
        self.key = jax.random.key(seed + 1)

        action_fn = backend.action_fn()

        @jax.jit
        def fleet_tick(states, exo_n, t, key):
            """Batched decide + expectation-dynamics estimate: [N, ...]."""
            actions = jax.vmap(lambda s, e: action_fn(s, e, t))(states,
                                                                exo_n)
            keys = jax.random.split(key, states.nodes.shape[0])
            new_states, metrics = jax.vmap(
                partial(sim_step, self.params, stochastic=False)
            )(states, actions, exo_n, keys)
            return actions, new_states, metrics

        self._fleet_tick = fleet_tick

    def _exo_at(self, t: int):
        xs = exo_steps(self._traces)  # [N, T, ...]
        return jax.tree.map(lambda x: x[:, t % self.horizon_ticks], xs)

    def tick(self, t: int) -> FleetTickReport:
        t0 = time.perf_counter()
        exo_n = self._exo_at(t)
        self.key, sub = jax.random.split(self.key)
        actions, self.states, metrics = self._fleet_tick(
            self.states, exo_n, jnp.int32(t), sub)
        jax.block_until_ready(actions)
        t1 = time.perf_counter()

        # Host fan-out: ONE device→host transfer of the stacked actions,
        # then per-cluster render + apply.
        host_actions = jax.device_get(actions)
        is_peak = np.asarray(exo_n.is_peak) > 0.5
        applied = 0
        for i, sink in enumerate(self.sinks):
            a_i = Action(*[np.asarray(leaf[i]) for leaf in host_actions])
            patches = render_nodepool_patches(
                a_i, self.cfg.cluster,
                op="add" if bool(is_peak[i]) else "replace")
            results = sink.apply_all(patches)
            applied += all(r.ok for r in results)
        t2 = time.perf_counter()

        report = FleetTickReport(
            t=t,
            n_clusters=self.n,
            applied=applied,
            slo_ok=int(np.asarray(metrics.slo_ok).sum()),
            cost_usd_hr=float(np.asarray(metrics.cost_usd).sum())
            / (float(self.params.dt_s) / 3600.0),
            carbon_g_hr=float(np.asarray(metrics.carbon_g).sum())
            / (float(self.params.dt_s) / 3600.0),
            pending_pods=float(np.asarray(metrics.pending_pods).sum()),
            decide_ms=round((t1 - t0) * 1000.0, 3),
            fanout_ms=round((t2 - t1) * 1000.0, 3),
        )
        self.log_fn(
            f"fleet t={t}: {report.applied}/{self.n} applied, "
            f"{report.slo_ok}/{self.n} slo-ok, "
            f"${report.cost_usd_hr:.2f}/hr, decide {report.decide_ms}ms, "
            f"fanout {report.fanout_ms}ms")
        return report

    def run(self, ticks: int, start_tick: int = 0) -> list[FleetTickReport]:
        return [self.tick(t) for t in range(start_tick, start_tick + ticks)]


def fleet_controller_from_config(cfg: FrameworkConfig,
                                 backend: PolicyBackend, n_clusters: int,
                                 *, horizon_ticks: int = 2880,
                                 seed: int = 0,
                                 log_fn=None) -> FleetController:
    """Dry-run fleet wiring: N in-memory sinks over the synthetic source.
    Live fleets construct FleetController directly with per-cluster
    KubectlSinks (`context_runner` per kube-context)."""
    from ccka_tpu.actuation.sink import DryRunSink
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    source = SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                   cfg.signals)
    sinks = [DryRunSink() for _ in range(n_clusters)]
    return FleetController(cfg, backend, source, sinks,
                           horizon_ticks=horizon_ticks, seed=seed,
                           log_fn=log_fn)
