"""Paired configure/observe stages — the reference's test discipline as code.

Every mutating stage in the reference ships with a read-only observer that
asserts post-state (`demo_10/20/21/30/40/50_{configure,observe}.sh`,
SURVEY.md §4 pattern 1). :class:`ConfigureObserve` makes that a first-class
object: ``apply()`` mutates through a sink, ``verify()`` reads back and
compares against the expected oracle (the printed expectation of
`demo_21_peak_observe.sh:18`), and ``run()`` does both with the reference's
apply→verify→fallback contract.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from ccka_tpu.actuation.patches import NodePoolPatchSet
from ccka_tpu.actuation.reconcile import Reconciler
from ccka_tpu.actuation.sink import ActuationSink, ApplyResult


@dataclass
class Stage:
    """A named lifecycle stage with its expected post-state oracle."""

    name: str
    patchsets: Sequence[NodePoolPatchSet]
    # oracle: pool name -> expected (consolidationPolicy, capacity-type values)
    expect: dict[str, tuple[str, list[str]]] = field(default_factory=dict)


class ConfigureObserve:
    """apply() + verify() over a sink, demo_2X_{configure,observe} style.

    ``rounds`` > 1 upgrades apply() from the reference's one-shot to
    reconciled convergence (actuation/reconcile.py) — the default stays
    1 so stage semantics (one apply pass, then the oracle check) are
    unchanged; either way actuation routes through the Reconciler, which
    the harness-wide AST guard requires.
    """

    def __init__(self, sink: ActuationSink, *, rounds: int = 1):
        self.sink = sink
        self._reconciler = Reconciler(sink, max_rounds=rounds,
                                      backoff_s=0.01)

    def apply(self, stage: Stage) -> list[ApplyResult]:
        return self._reconciler.converge(stage.patchsets).results

    def verify(self, stage: Stage) -> list[tuple[str, bool, str]]:
        """Read back each pool FROM THE SINK against the stage oracle —
        never from the intended patches, so a sink that silently dropped or
        mangled a mutation (mismatched schema path, admission webhook
        rewrite) fails verification. The same skepticism as the reference's
        jsonpath re-reads (`demo_20_offpeak_observe.sh:8-27`)."""
        out = []
        for ps in stage.patchsets:
            want = stage.expect.get(ps.pool)
            if want is None:
                out.append((ps.pool, True, "no oracle"))
                continue
            policy_want, cts_want = want
            observed = self.sink.observed_state(ps.pool)
            got_policy = observed.get("consolidationPolicy", "")
            got_cts = observed.get("capacity_types", [])
            ok = got_policy == policy_want and got_cts == cts_want
            detail = (f"observed policy={got_policy!r} cts={got_cts}"
                      if not ok else "")
            out.append((ps.pool, ok, detail))
        return out

    def run(self, stage: Stage) -> bool:
        applied = self.apply(stage)
        verified = self.verify(stage)
        return all(r.ok for r in applied) and all(ok for _, ok, _ in verified)
