"""Overload scoreboard: paired stressed/calm multi-tenant service runs.

Round 10 measured how policies degrade when the *world* misbehaves and
round 12 measured whether the loop survives dying; this board measures
whether the CONTROL PLANE stays responsive and fair when some of its
tenants misbehave — the property KIS-S/NeuroScaler demand of a control
loop that manages the very load stressing it. Each cell of
{tenant count x chaos intensity x slow-tenant fraction} runs the
:class:`~ccka_tpu.harness.service.FleetService` twice over the SAME
seeded world:

- **stressed**: the last ``slow_frac`` of the fleet runs a composed
  stress profile (the hung-scrape ``slow_profile`` archetype + the
  cell's `CHAOS_PRESETS` intensity on its kubectl edge, shed-eligible
  priority), behind an admission cap at ``cap_frac`` of the fleet;
- **calm**: the same fleet, same seed, same service posture, every
  tenant healthy.

Isolation metrics per cell (the acceptance surface):

- ``healthy_usd_ratio_{mean,max}`` — per-tenant paired $/SLO-hour,
  stressed vs calm, over the HEALTHY tenants only. Bulkheads working =
  ratio 1.0 bitwise (healthy decide rows are vmap-row-independent);
  the board states the measured ratio rather than assuming it.
- ``latency_ms`` p50/p99/max on the service's (virtual) clock, next to
  the configured ``tick_deadline_ms`` and a count of deadline
  violations — bounded ticks proven on the record.
- shed/deferral/bulkhead/cadence counters, breaker transition counts,
  and the injected chaos tally (every dropped decide is accounted,
  never silent).

The ``slow_frac == 0`` cells are the null-stress control: stressed and
calm runs are then literally identical configurations, so their ratio
pins the service-layer overhead at exactly 1.0. Used by `bench.py
bench_overload` (BASELINE round13) and the `ccka overload-eval` CLI;
unknown intensity/profile/policy names are rejected up front (the
chaos-eval convention).
"""

from __future__ import annotations

import dataclasses
import sys

import numpy as np

from ccka_tpu.config import (CHAOS_PRESETS, SERVICE_PRESETS,
                             FrameworkConfig)

_KNOWN_POLICIES = ("rule", "carbon", "flagship")


def _latency_stats(lats_ms) -> dict:
    arr = np.asarray(lats_ms, np.float64)
    return {
        "p50": round(float(np.percentile(arr, 50)), 3),
        "p99": round(float(np.percentile(arr, 99)), 3),
        "max": round(float(arr.max()), 3),
        "mean": round(float(arr.mean()), 3),
    }


def _run_service(cfg, backend, n, profiles, svc, *, ticks, seed,
                 horizon) -> dict:
    """One warmed service run; returns its board-relevant surfaces."""
    from ccka_tpu.harness.service import fleet_service_from_config

    service = fleet_service_from_config(
        cfg, backend, n, profiles=profiles, service=svc,
        horizon_ticks=horizon, seed=seed)
    service.warmup()
    reports = service.run(ticks)
    out = {
        "usd_per_slo_hr": service.tenant_usd_per_slo_hr(),
        "fresh_ticks": service.tenant_fresh_ticks.copy(),
        "latencies_ms": list(service.latencies_ms),
        "sheds_total": service.sheds_total,
        "deferrals_total": service.deferrals_total,
        "cadence_skips_total": service.cadence_skips_total,
        "bulkhead_skips_total": service.bulkhead_skips_total,
        "scrape_timeouts_total": service.scrape_timeouts_total,
        "scrape_failures_total": service.scrape_failures_total,
        "actuation_giveups_total": service.actuation_giveups_total,
        "breaker_transitions": service.breaker_transition_counts(),
        "chaos_injected": service.chaos_injected(),
        "cadence_divisor_last": reports[-1].cadence_divisor,
        "queue_depth_last": reports[-1].admission_queue_depth,
    }
    service.close()
    return out


def overload_scoreboard(cfg: FrameworkConfig, *,
                        policies=("rule", "flagship"),
                        tenants=(16, 64),
                        intensities=("off", "moderate", "severe"),
                        slow_fracs=(0.0, 0.25, 0.5),
                        slow_profile: str = "slow",
                        service_preset: str = "default",
                        cap_frac: float = 0.75,
                        ticks: int = 48,
                        seed: int = 211) -> dict:
    """The round-13 overload board (module docstring). ``intensities``
    must name `config.CHAOS_PRESETS` entries, ``slow_profile`` a
    `service.TENANT_PROFILES` archetype, ``service_preset`` a
    `config.SERVICE_PRESETS` posture, and ``policies`` a subset of
    {rule, carbon, flagship} — all rejected up front."""
    from ccka_tpu.harness.service import TENANT_PROFILES
    from ccka_tpu.policy import CarbonAwarePolicy, RulePolicy
    from ccka_tpu.train.flagship import load_flagship_backend

    bad = [i for i in intensities if i not in CHAOS_PRESETS]
    if bad:
        raise ValueError(f"unknown chaos intensities {bad}; presets: "
                         f"{sorted(CHAOS_PRESETS)}")
    if slow_profile not in TENANT_PROFILES:
        raise ValueError(f"unknown tenant profile {slow_profile!r}; "
                         f"known: {sorted(TENANT_PROFILES)}")
    if service_preset not in SERVICE_PRESETS:
        raise ValueError(f"unknown service preset {service_preset!r}; "
                         f"presets: {sorted(SERVICE_PRESETS)}")
    if not SERVICE_PRESETS[service_preset].enabled:
        raise ValueError(f"service preset {service_preset!r} is the off "
                         "gate — an overload board over the delegating "
                         "path would measure nothing")
    bad = [p for p in policies if p not in _KNOWN_POLICIES]
    if bad:
        raise ValueError(f"unknown policies {bad}; known: "
                         f"{list(_KNOWN_POLICIES)}")
    bad = [f for f in slow_fracs if not 0.0 <= f < 1.0]
    if bad:
        raise ValueError(f"slow_fracs out of [0, 1): {bad}")
    if not tenants or not intensities or not slow_fracs or not policies:
        raise ValueError("empty grid axis — tenants, intensities, "
                         "slow_fracs and policies all need at least "
                         "one entry")
    bad = [n for n in tenants if int(n) < 1]
    if bad:
        raise ValueError(f"tenant counts must be >= 1: {bad}")
    if not 0.0 < cap_frac <= 1.0:
        raise ValueError("cap_frac out of (0, 1]")
    if ticks < 4:
        raise ValueError("overload runs need ticks >= 4")

    base_svc = SERVICE_PRESETS[service_preset]
    slow_base = TENANT_PROFILES[slow_profile]
    horizon = max(int(ticks) + 4, 8)

    backends: dict[str, object] = {}
    out: dict = {
        "engine": "fleet service(bounded batched ticks, per-tenant "
                  "breakers/bulkheads, priority shed)",
        "ticks_per_run": int(ticks),
        "seed": int(seed),
        "policies": list(policies),
        "tenants": [int(n) for n in tenants],
        "intensities": list(intensities),
        "slow_fracs": [float(f) for f in slow_fracs],
        "slow_profile": slow_profile,
        "service_preset": service_preset,
        "service": dataclasses.asdict(base_svc),
        "cap_frac": float(cap_frac),
        "cells": {},
    }
    for p in policies:
        if p == "rule":
            backends[p] = RulePolicy(cfg.cluster)
        elif p == "carbon":
            backends[p] = CarbonAwarePolicy(cfg.cluster)
        else:
            flagship, meta = load_flagship_backend(cfg)
            if flagship is None:
                out["flagship_source"] = (
                    "omitted: no flagship checkpoint for this topology "
                    "(no stand-ins)")
                continue
            out["flagship_source"] = {
                "checkpoint": "topology-keyed flagship",
                "selected_iteration": meta.get("selected_iteration")}
            backends[p] = flagship
    # The record's policy list reflects the rows that actually ran —
    # a requested-but-omitted flagship must not read as having run.
    out["policies_requested"] = list(policies)
    out["policies"] = list(backends)
    if not backends:
        # Fail BEFORE the grid runs, not in the invariant summary after
        # minutes of compute (the up-front-rejection contract).
        raise ValueError(
            "no runnable policy rows — every requested policy was "
            "omitted (e.g. 'flagship' without a committed checkpoint "
            "for this topology); add 'rule' or train a flagship first")

    # Calm baselines, ONE per (policy, fleet size): every cell of that
    # column pairs against the same unstressed run (same seed, same
    # capped service posture — slow_frac 0 cells are then literally the
    # same configuration, the zero-overhead control).
    calm: dict[tuple, dict] = {}
    null_runs: dict[tuple, dict] = {}
    for n in tenants:
        svc_n = dataclasses.replace(
            base_svc,
            admission_queue_cap=max(1, int(np.ceil(cap_frac * n))))
        for pname, backend in backends.items():
            calm[(pname, n)] = _run_service(
                cfg, backend, n, ["healthy"] * n, svc_n,
                ticks=ticks, seed=seed, horizon=horizon)

    for n in tenants:
        svc_n = dataclasses.replace(
            base_svc,
            admission_queue_cap=max(1, int(np.ceil(cap_frac * n))))
        for intensity in intensities:
            # The stressed archetype composes the hung-scrape profile
            # with this cell's kubectl-edge chaos, shed-eligible.
            stressed = dataclasses.replace(
                slow_base,
                name=f"{slow_base.name}+{intensity}",
                chaos=(intensity if intensity != "off" else ""),
                priority=max(slow_base.priority, 2),
                stale_tolerant=True)
            for frac in slow_fracs:
                # At least one healthy tenant always remains: the
                # paired ratio needs a non-empty healthy set, and
                # frac < 1 already promises one.
                n_slow = min(int(round(float(frac) * n)), n - 1)
                profiles = (["healthy"] * (n - n_slow)
                            + [stressed] * n_slow)
                rows: dict[str, dict] = {}
                for pname, backend in backends.items():
                    if n_slow == 0:
                        # A slow-frac-0 cell is the same all-healthy
                        # configuration whatever the intensity: run
                        # the null control ONCE per (policy, n) — an
                        # INDEPENDENT run from the calm baseline, so
                        # its ratio measures harness determinism
                        # rather than comparing a run to itself — and
                        # reuse it across intensities.
                        if (pname, n) not in null_runs:
                            null_runs[(pname, n)] = _run_service(
                                cfg, backend, n, profiles, svc_n,
                                ticks=ticks, seed=seed, horizon=horizon)
                        stress = null_runs[(pname, n)]
                    else:
                        stress = _run_service(cfg, backend, n, profiles,
                                              svc_n, ticks=ticks,
                                              seed=seed, horizon=horizon)
                    base = calm[(pname, n)]
                    healthy = slice(0, n - n_slow)
                    s_usd = stress["usd_per_slo_hr"][healthy]
                    c_usd = base["usd_per_slo_hr"][healthy]
                    ratios = s_usd / np.maximum(c_usd, 1e-12)
                    lat = _latency_stats(stress["latencies_ms"])
                    deadline = float(svc_n.tick_deadline_ms)
                    rows[pname] = {
                        "healthy_usd_ratio_mean": round(
                            float(ratios.mean()), 6),
                        "healthy_usd_ratio_max": round(
                            float(ratios.max()), 6),
                        "healthy_bitwise_frac": round(float(np.mean(
                            s_usd == c_usd)), 4),
                        "latency_ms": lat,
                        "deadline_violations": int(sum(
                            1 for v in stress["latencies_ms"]
                            if v > deadline)),
                        "calm_latency_ms": _latency_stats(
                            base["latencies_ms"]),
                        "sheds_total": int(stress["sheds_total"]),
                        "deferrals_total": int(
                            stress["deferrals_total"]),
                        "cadence_skips_total": int(
                            stress["cadence_skips_total"]),
                        "bulkhead_skips_total": int(
                            stress["bulkhead_skips_total"]),
                        "scrape_timeouts_total": int(
                            stress["scrape_timeouts_total"]),
                        "scrape_failures_total": int(
                            stress["scrape_failures_total"]),
                        "actuation_giveups_total": int(
                            stress["actuation_giveups_total"]),
                        "breaker_transitions": stress[
                            "breaker_transitions"],
                        "chaos_injected": stress["chaos_injected"],
                        "cadence_divisor_last": int(
                            stress["cadence_divisor_last"]),
                        "stressed_fresh_frac": round(float(
                            stress["fresh_ticks"][n - n_slow:].mean()
                            / ticks), 4) if n_slow else None,
                        "healthy_fresh_frac": round(float(
                            stress["fresh_ticks"][healthy].mean()
                            / ticks), 4),
                    }
                    opened = rows[pname]["breaker_transitions"]["opened"]
                    print(f"# overload[n{n}/{intensity}/slow{frac:g}/"
                          f"{pname}]: ratio_max="
                          f"{rows[pname]['healthy_usd_ratio_max']:.4f} "
                          f"p99={lat['p99']:.1f}ms "
                          f"shed={rows[pname]['sheds_total']} "
                          f"opened={opened}", file=sys.stderr)
                out["cells"][f"n{n}/{intensity}/slow{frac:g}"] = {
                    "n_tenants": int(n),
                    "n_slow": n_slow,
                    "intensity": intensity,
                    "slow_frac": float(frac),
                    "admission_queue_cap": int(svc_n.admission_queue_cap),
                    "tick_deadline_ms": float(svc_n.tick_deadline_ms),
                    "rows": rows,
                }

    # Board-level invariants: the acceptance surface, stated on the
    # record itself (test_doc_sync parses these).
    all_rows = [(k, p, r) for k, c in out["cells"].items()
                for p, r in c["rows"].items()]
    out["invariants"] = {
        "healthy_usd_ratio_max": round(max(
            r["healthy_usd_ratio_max"] for _k, _p, r in all_rows), 6),
        "latency_p99_max_ms": round(max(
            r["latency_ms"]["p99"] for _k, _p, r in all_rows), 3),
        "deadline_violations_total": int(sum(
            r["deadline_violations"] for _k, _p, r in all_rows)),
        "sheds_total": int(sum(
            r["sheds_total"] for _k, _p, r in all_rows)),
        "breakers_opened_total": int(sum(
            r["breaker_transitions"]["opened"]
            for _k, _p, r in all_rows)),
    }
    null_ratios = [r["healthy_usd_ratio_max"] for k, _p, r in all_rows
                   if k.endswith("/slow0")]
    # The zero-overhead control only exists when the grid includes a
    # slow-frac-0 column; absent, the key says so instead of crashing.
    out["invariants"]["null_cell_ratio_max"] = (
        round(max(null_ratios), 6) if null_ratios else None)
    return out
