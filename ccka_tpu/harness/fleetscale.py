"""Fleet-scale tail-latency record: the 10^4-tenant host-loop sweep.

The overload board (`harness/overload.py`, BENCH_r13) proved the
breaker/bulkhead/shed semantics at 16-64 tenants; this harness proves
the HOST LOOP at fleet scale (ROADMAP open item 2). Three instruments,
one record (`bench.py --fleet-scale-only` → BENCH_r21.json):

- **paired parity** (the refactor gate): the vectorized tenant machine
  vs the pre-round-21 object loop, same seeded world on the det clock
  — per-tick decisions (lanes), patch streams (DryRunSink commands),
  and every ServiceTickReport counter must be bitwise identical at
  small N before the record may cite the vectorized numbers.
- **chunk parity**: the N=1024 fleet through `sim/lanes.chunk_layout`
  chunked dispatch vs the unchunked N=1024 program — chunking the
  tenant axis must not move a single byte of decision output.
- **the sweep**: N in {16 … 10240} x {calm, 25% slow + moderate
  chaos}, recording p50/p99/max tick latency, sheds/deferrals,
  host-loop µs/tenant, and the paired healthy-tenant $/SLO-hour ratio
  against a calm baseline at the same N (bulkheads working = exactly
  1.0: healthy decide rows are vmap-row-independent and the admission
  machine orders them ahead of every stressed tenant). The
  vectorized-vs-object host-loop speedup at N=4096 is the record's
  headline gate (>= 10x).

All knobs are validated up front (the chaos-eval convention); the
`ccka bench-diff` fleet-scale gates re-check the shipped record.
"""

from __future__ import annotations

import dataclasses
import sys

import numpy as np

from ccka_tpu.config import (CHAOS_PRESETS, SERVICE_PRESETS,
                             FrameworkConfig)

# Tenant counts at or above this ride the chunked tenant-axis dispatch
# (one compiled k-tenant program for the whole upper sweep).
_CHUNK_FROM = 1024
_CHUNK = 256


def _latency_stats(lats_ms) -> dict:
    arr = np.asarray(lats_ms, np.float64)
    return {
        "p50": round(float(np.percentile(arr, 50)), 3),
        "p99": round(float(np.percentile(arr, 99)), 3),
        "max": round(float(arr.max()), 3),
        "mean": round(float(arr.mean()), 3),
    }


def _det_clock():
    from ccka_tpu.harness.service import VirtualClock
    return VirtualClock(base=lambda: 0.0)


def _report_counters(rep) -> dict:
    """The deterministic slice of a ServiceTickReport (host timing
    fields excluded — they are real microseconds and may differ
    between two otherwise bitwise-identical runs)."""
    d = dataclasses.asdict(rep)
    for k in ("tick_latency_ms", "decide_ms", "fanout_ms",
              "host_loop_us_per_tenant"):
        d.pop(k, None)
    return d


def _patch_stream(service) -> list:
    """Per-sink rendered command streams (through any chaos wrap)."""
    out = []
    for snk in service.sinks:
        inner = getattr(snk, "inner", snk)
        out.append([repr(c) for c in inner.commands])
    return out


def _run_paired(cfg, backend, n, profiles, svc, *, ticks, seed,
                horizon, variants) -> dict:
    """Run the same seeded world once per (host_loop, dispatch_chunk)
    variant on the det clock and compare EVERYTHING deterministic."""
    from ccka_tpu.harness.service import fleet_service_from_config

    runs = {}
    for name, (host_loop, chunk) in variants.items():
        service = fleet_service_from_config(
            cfg, backend, n, profiles=profiles, service=svc,
            horizon_ticks=horizon, seed=seed, clock=_det_clock(),
            host_loop=host_loop, dispatch_chunk=chunk)
        service.warmup()
        reports = [_report_counters(r) for r in service.run(ticks)]
        runs[name] = {
            "reports": reports,
            "patches": _patch_stream(service),
            "held": service._held.copy(),
            "usd": service.tenant_cost_usd.copy(),
            "slo": service.tenant_slo_ticks.copy(),
            "transitions": service.breaker_transition_counts(),
        }
        service.close()
    names = list(runs)
    a, b = runs[names[0]], runs[names[1]]
    mismatches = []
    for t, (ra, rb) in enumerate(zip(a["reports"], b["reports"])):
        for k in ra:
            if ra[k] != rb[k]:
                mismatches.append(f"t{t}:{k}")
    if a["patches"] != b["patches"]:
        mismatches.append("patch_streams")
    for k in ("held", "usd", "slo"):
        if not np.array_equal(a[k], b[k]):
            mismatches.append(k)
    if a["transitions"] != b["transitions"]:
        mismatches.append("breaker_transitions")
    return {
        "n_tenants": int(n),
        "ticks": int(ticks),
        "variants": {k: {"host_loop": v[0], "dispatch_chunk": v[1]}
                     for k, v in variants.items()},
        "bitwise_identical": not mismatches,
        "mismatches": mismatches[:16],
        "checked": ["report_counters", "patch_streams", "held_rows",
                    "tenant_usd", "tenant_slo_ticks",
                    "breaker_transitions"],
    }


def fleet_scale_record(cfg: FrameworkConfig, *,
                       tenants=(16, 256, 1024, 4096, 10240),
                       slow_frac: float = 0.25,
                       intensity: str = "moderate",
                       slow_profile: str = "slow",
                       service_preset: str = "default",
                       cap_frac: float = 0.9,
                       ticks: int = 12,
                       parity_n: int = 16,
                       chunk_parity_n: int = 1024,
                       speedup_n: int = 4096,
                       seed: int = 211) -> dict:
    """The round-21 fleet-scale record (module docstring)."""
    from ccka_tpu.harness.service import TENANT_PROFILES
    from ccka_tpu.harness.service import fleet_service_from_config
    from ccka_tpu.policy.rule import RulePolicy

    if intensity not in CHAOS_PRESETS:
        raise ValueError(f"unknown chaos intensity {intensity!r}; "
                         f"presets: {sorted(CHAOS_PRESETS)}")
    if slow_profile not in TENANT_PROFILES:
        raise ValueError(f"unknown tenant profile {slow_profile!r}; "
                         f"known: {sorted(TENANT_PROFILES)}")
    if service_preset not in SERVICE_PRESETS or \
            not SERVICE_PRESETS[service_preset].enabled:
        raise ValueError(f"service preset {service_preset!r} must name "
                         "an enabled posture")
    if not 0.0 < slow_frac < 1.0:
        raise ValueError("slow_frac out of (0, 1)")
    if not 0.0 < cap_frac <= 1.0:
        raise ValueError("cap_frac out of (0, 1]")
    if ticks < 4:
        raise ValueError("fleet-scale runs need ticks >= 4")
    if parity_n > 64:
        raise ValueError("parity_n > 64 — the paired parity gate is a "
                         "small-N bitwise pin, not a perf run")
    bad = [n for n in tenants if int(n) < 2]
    if bad:
        raise ValueError(f"tenant counts must be >= 2: {bad}")
    if speedup_n not in tenants:
        raise ValueError(f"speedup_n={speedup_n} must be one of the "
                         f"swept tenant counts {tuple(tenants)}")

    base_svc = SERVICE_PRESETS[service_preset]
    # One horizon for every run (the speedup pair runs >= 24 ticks):
    # the compiled tick cache is keyed on it, so a uniform horizon
    # means ONE chunk program serves the whole upper sweep.
    horizon = max(int(ticks), 24) + 4
    backend = RulePolicy(cfg.cluster)
    slow_base = TENANT_PROFILES[slow_profile]
    stressed_prof = dataclasses.replace(
        slow_base,
        name=f"{slow_base.name}+{intensity}",
        chaos=(intensity if intensity != "off" else ""),
        priority=max(slow_base.priority, 2),
        stale_tolerant=True)

    def svc_for(n: int):
        return dataclasses.replace(
            base_svc,
            admission_queue_cap=max(1, int(np.ceil(cap_frac * n))))

    def chunk_for(n: int):
        return _CHUNK if n >= _CHUNK_FROM else None

    out: dict = {
        "engine": "vectorized fleet-service host loop (flat-array "
                  "admission machine, chunked tenant-axis dispatch)",
        "ticks_per_run": int(ticks),
        "seed": int(seed),
        "sweep_n": [int(n) for n in tenants],
        "scenarios": ["calm", f"slow{slow_frac:g}_{intensity}"],
        "slow_frac": float(slow_frac),
        "intensity": intensity,
        "service_preset": service_preset,
        "cap_frac": float(cap_frac),
        "dispatch_chunk": {str(int(n)): chunk_for(n) for n in tenants},
        "cells": {},
    }

    # -- gate 1: vectorized-vs-object bitwise parity (det clock) -------
    mix = ["healthy", "batch", "jittery", slow_profile, "flaky"]
    parity_profiles = [mix[i % len(mix)] for i in range(parity_n)]
    out["parity"] = _run_paired(
        cfg, backend, parity_n, parity_profiles, svc_for(parity_n),
        ticks=max(ticks, 12), seed=seed, horizon=horizon,
        variants={"vectorized": ("vectorized", None),
                  "object": ("object", None)})
    print(f"# fleet-scale parity n={parity_n}: bitwise="
          f"{out['parity']['bitwise_identical']}", file=sys.stderr)

    # -- gate 2: chunked-vs-unchunked bitwise parity (det clock) -------
    cp_chunk = (_CHUNK if chunk_parity_n % _CHUNK == 0
                and _CHUNK < chunk_parity_n
                else max(1, chunk_parity_n // 4))
    out["chunk_parity"] = _run_paired(
        cfg, backend, chunk_parity_n, ["healthy"] * chunk_parity_n,
        svc_for(chunk_parity_n), ticks=max(4, min(ticks, 6)),
        seed=seed, horizon=horizon,
        variants={"chunked": ("vectorized", cp_chunk),
                  "unchunked": ("vectorized", None)})
    print(f"# fleet-scale chunk parity n={chunk_parity_n}: bitwise="
          f"{out['chunk_parity']['bitwise_identical']}", file=sys.stderr)

    # -- the sweep -----------------------------------------------------
    def run_cell(n, profiles, host_loop, *, ticks=ticks):
        service = fleet_service_from_config(
            cfg, backend, n, profiles=profiles, service=svc_for(n),
            horizon_ticks=horizon, seed=seed, host_loop=host_loop,
            dispatch_chunk=chunk_for(n))
        service.warmup()
        reports = service.run(ticks)
        res = {
            "latencies_ms": list(service.latencies_ms),
            "host_loop_us": [r.host_loop_us_per_tenant
                             for r in reports],
            "active_tenants_last": reports[-1].active_tenants,
            "sheds_total": service.sheds_total,
            "deferrals_total": service.deferrals_total,
            "bulkhead_skips_total": service.bulkhead_skips_total,
            "scrape_timeouts_total": service.scrape_timeouts_total,
            "breaker_transitions": service.breaker_transition_counts(),
            "usd_per_slo_hr": service.tenant_usd_per_slo_hr(),
        }
        service.close()
        return res

    speedup = None
    for n in tenants:
        n = int(n)
        n_slow = min(int(round(slow_frac * n)), n - 1)
        calm = run_cell(n, ["healthy"] * n, "vectorized")
        scen = {
            "calm": (calm, 0, None),
        }
        stress = run_cell(
            n, ["healthy"] * (n - n_slow) + [stressed_prof] * n_slow,
            "vectorized")
        scen[out["scenarios"][1]] = (stress, n_slow, calm)
        for scenario, (res, ns, base) in scen.items():
            lat = _latency_stats(res["latencies_ms"])
            deadline = float(svc_for(n).tick_deadline_ms)
            us = [u for u in res["host_loop_us"] if u is not None]
            cell = {
                "n_tenants": n,
                "scenario": scenario,
                "n_slow": int(ns),
                "dispatch_chunk": chunk_for(n),
                "latency_ms": lat,
                "deadline_violations": int(sum(
                    1 for v in res["latencies_ms"] if v > deadline)),
                "host_loop_us_per_tenant": round(
                    float(np.mean(us)), 4) if us else None,
                "active_tenants_last": res["active_tenants_last"],
                "sheds_total": int(res["sheds_total"]),
                "deferrals_total": int(res["deferrals_total"]),
                "bulkhead_skips_total": int(
                    res["bulkhead_skips_total"]),
                "scrape_timeouts_total": int(
                    res["scrape_timeouts_total"]),
                "breakers_opened": int(
                    res["breaker_transitions"]["opened"]),
            }
            if base is not None:
                healthy = slice(0, n - ns)
                ratios = (res["usd_per_slo_hr"][healthy]
                          / np.maximum(base["usd_per_slo_hr"][healthy],
                                       1e-12))
                cell["healthy_usd_ratio_mean"] = round(
                    float(ratios.mean()), 6)
                cell["healthy_usd_ratio_max"] = round(
                    float(ratios.max()), 6)
                cell["healthy_bitwise_frac"] = round(float(np.mean(
                    res["usd_per_slo_hr"][healthy]
                    == base["usd_per_slo_hr"][healthy])), 4)
            out["cells"][f"n{n}/{scenario}"] = cell
            print(f"# fleet-scale[n{n}/{scenario}]: "
                  f"p99={lat['p99']:.1f}ms "
                  f"host={cell['host_loop_us_per_tenant']}us/tenant "
                  f"shed={cell['sheds_total']}", file=sys.stderr)

        # -- gate 3: the headline speedup pair at speedup_n ------------
        # Dedicated paired runs, post-warm window: the first two ticks
        # carry cold allocator/cache state for BOTH hosts; the record
        # compares the steady loops (the bench's best-of-N idiom).
        if n == speedup_n:
            sp_ticks = max(ticks, 24)
            warm = 2
            pair = {}
            for hl in ("object", "vectorized"):
                res = run_cell(n, ["healthy"] * n, hl, ticks=sp_ticks)
                us = [u for u in res["host_loop_us"][warm:]
                      if u is not None]
                pair[hl] = float(np.mean(us)) if us else 0.0
            speedup = {
                "n_tenants": n,
                "scenario": "calm",
                "ticks": int(sp_ticks),
                "warmup_ticks_dropped": warm,
                "object_us_per_tenant": round(pair["object"], 4),
                "vectorized_us_per_tenant": round(
                    pair["vectorized"], 4),
                "ratio": round(pair["object"]
                               / max(pair["vectorized"], 1e-9), 2),
            }
            print(f"# fleet-scale speedup n={n}: "
                  f"object={pair['object']:.2f} "
                  f"vec={pair['vectorized']:.2f} us/tenant -> "
                  f"{speedup['ratio']:.1f}x", file=sys.stderr)
    out["speedup"] = speedup

    # -- the acceptance surface, stated on the record itself -----------
    ratio_cells = [c for c in out["cells"].values()
                   if "healthy_usd_ratio_max" in c]
    p99_all = [c["latency_ms"]["p99"] for c in out["cells"].values()]
    out["invariants"] = {
        "parity_bitwise": bool(out["parity"]["bitwise_identical"]),
        "chunk_parity_bitwise": bool(
            out["chunk_parity"]["bitwise_identical"]),
        "speedup_ratio": (None if speedup is None
                          else speedup["ratio"]),
        "healthy_usd_ratio_max": round(max(
            c["healthy_usd_ratio_max"] for c in ratio_cells), 6),
        "healthy_ratio_exact_all": bool(all(
            c["healthy_usd_ratio_max"] == 1.0
            and c["healthy_usd_ratio_mean"] == 1.0
            for c in ratio_cells)),
        "latency_p99_max_ms": round(max(p99_all), 3),
        "max_tenants": int(max(tenants)),
    }
    return out
