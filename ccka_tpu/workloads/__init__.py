"""Heterogeneous workload families: queue dynamics + scenario library.

Three halves (ARCHITECTURE §13), mirroring `ccka_tpu/faults`:

- **Processes** (`workloads/process.py`): diurnal inference traffic
  with flash-crowd spikes, deadline-driven batch backfill with bursty
  arrival waves, and a best-effort background family — all pure-jnp,
  synthesized as extra lanes in the packed exo stream and keyed by the
  same ``(seed, shard, block)`` PRNG scheme as the exo signals, so
  every policy being compared sees the bitwise-identical family
  arrivals.
- **Consumption**: `sim/dynamics.step` (``workload=``/``wl_state=``
  kwargs) and the fused Pallas megakernel (workload lanes auto-detected
  from the packed stream's row count) drain per-family queues from the
  fleet's headroom — inference with latency/SLO-violation accounting,
  batch EDF with deadline-miss accounting — surfacing per-family
  StepMetrics/EpisodeSummary counters.
- **Scenarios + scoreboard** (`workloads/scenarios.py`,
  `workloads/scoreboard.py`): the named scenario library
  (`WORKLOAD_SCENARIOS`: diurnal-inference / flash-crowd /
  batch-backfill / mixed, composable with `FAULT_PRESETS`) and the
  per-family scoreboard — `bench.py bench_workloads` and
  `ccka scenario-eval` both drive it; `ccka scenarios` lists the
  library.
"""

from ccka_tpu.config import WorkloadsConfig  # noqa: F401
from ccka_tpu.workloads.process import (  # noqa: F401
    has_workload_lanes,
    packed_workload_lanes,
    sample_workload_steps,
    stream_layout,
    unpack_workload_lanes,
    workload_rows,
)
from ccka_tpu.workloads.scenarios import (  # noqa: F401
    Scenario,
    WORKLOAD_SCENARIOS,
    resolve_scenarios,
    scenario_source,
)
from ccka_tpu.workloads.types import WorkloadState, WorkloadStep  # noqa: F401

__all__ = [
    "WORKLOAD_SCENARIOS",
    "Scenario",
    "WorkloadState",
    "WorkloadStep",
    "WorkloadsConfig",
    "has_workload_lanes",
    "packed_workload_lanes",
    "resolve_scenarios",
    "sample_workload_steps",
    "scenario_source",
    "stream_layout",
    "unpack_workload_lanes",
    "workload_rows",
]
