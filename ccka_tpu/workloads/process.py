"""Batched workload-family demand generators, synthesized as packed lanes.

The workload subsystem's generation half, mirroring `faults/process.py`:
pure-jnp processes emitting ``[T_pad, workload_rows(Z), B]`` lane blocks
that ride the SAME packed exo stream the megakernel reads. Because the
lanes are part of stream synthesis they inherit every pairing property
of the exo signals: shard-local on a mesh (`parallel/sharded_kernel.
sharded_packed_trace` runs the generator per shard on ``fold_in(key,
shard)``), and bitwise identical for every policy scored on the stream —
rule, flagship and MPC-playback see the same flash crowd.

Lane layout, offsets relative to the workload block base (which sits
AFTER the fault block when one is present — see :func:`stream_layout`):

    row 0   inf_arrivals    inference work arriving this tick (pods)
    row 1   batch_arrivals  batch work arriving this tick (pod-ticks)
    row 2   bg_arrivals     best-effort background work
    rows pad to ``workload_rows(Z) = fault_rows(Z) + 8`` (zeros)

The +8 over the fault block's size is deliberate: layout detection is
purely row-count-based (`stream_layout`), and the four layouts — plain,
+faults, +workloads, +both — must be mutually distinguishable for any
zone count; sizing the workload block ``fault_rows(Z) + 8`` guarantees
all four counts are distinct without threading any side-channel flag.

Flash-crowd / burst-wave windows reuse the fault subsystem's
thresholded stationary AR(1) family (`faults/process._window`); diurnal
shape reuses the signal generator's `_bump`. The neutral contract: with
every rate at 0 the emitted lanes are EXACTLY 0 — consuming them is a
no-op (queues stay empty, counters zero), which is what lets the
zero-workload gate (`tests/test_workloads.py`) pin the widened pipeline
against the pre-workload one.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ccka_tpu.config import WorkloadsConfig
from ccka_tpu.faults.process import _window, _window_p
from ccka_tpu.signals.synthetic import _ar1_device, _bump
from ccka_tpu.sim import lanes
from ccka_tpu.workloads.types import WorkloadStep

_DAY_S = 86400.0

# Key-domain tag separating the workload latents from the exo noise AND
# the fault latents (FAULT_KEY_TAG = 0xFA117): folded into the same
# generation key, so widening a stream with workload lanes changes
# neither the exo rows nor the fault rows bitwise. Canonical value
# lives in the lane-family registry (`sim/lanes.py` — ISSUE 14).
WORKLOAD_KEY_TAG = lanes.LANE_FAMILIES["workloads"].key_tag


# The layout arithmetic lives in the neutral `sim/lanes.py` (the one
# layout module — faults and workloads both import it DOWNWARD, never
# each other); re-exported here for the existing `workloads.*` surface.
workload_rows = lanes.workload_rows
stream_layout = lanes.stream_layout
workload_base = lanes.workload_base


def packed_workload_lanes(wl: WorkloadsConfig, key, steps: int, t_pad: int,
                          Z: int, batch: int, *,
                          dt_s: float, start_unix_s: float = 0.0,
                          start_offset_s=None,
                          wrap_period_s: float | None = None) -> jnp.ndarray:
    """``[T_pad, workload_rows(Z), B]`` lane block for one stream.

    Pure jnp — runs inside the (possibly shard_map'd) generation jit.
    ``dt_s``/``start_unix_s`` anchor the diurnal shapes to the same
    clock the exo generator uses. ``start_offset_s``: optional ``[B]``
    per-trace second offsets added to that clock — the replay backend
    samples each window at a different offset into its stored trace, and
    the diurnal/anti-diurnal family shapes must stay phase-aligned with
    the exo demand each window actually replays (``None``: one shared
    clock, the synthetic backend's contract). ``wrap_period_s``: the
    store's length in seconds — a window running past the store end
    replays samples that jump back to store-start wall-clock, so the
    lane clock must wrap with it or the family shapes de-phase for the
    wrapped tail.
    """
    ki, kif, kb, kbf, kg = jax.random.split(
        jax.random.fold_in(key, WORKLOAD_KEY_TAG), 5)
    f32 = jnp.float32
    t = start_unix_s + np.arange(steps) * dt_s
    if start_offset_s is None:
        tod = jnp.asarray((t % _DAY_S) / _DAY_S, f32)[:, None]      # [T,1]
    else:
        # Per-window seconds into the store, wrapped to the store
        # period (the clock of the sample each tick actually replays),
        # then anchored to the recorded start. The day reduction
        # happens in float64 / at small magnitudes BEFORE the f32
        # cast: at unix-epoch scale (~1.7e9 s) the f32 ulp is 128 s,
        # which would quantize the 30 s tick grid into a staircase and
        # corrupt the per-window phase these offsets exist to carry.
        t_rel = (jnp.asarray(np.arange(steps) * dt_s, f32)[:, None]
                 + jnp.asarray(start_offset_s, f32)[None, :])       # [T,B]
        if wrap_period_s is not None:
            t_rel = t_rel % f32(wrap_period_s)
        tt = f32(start_unix_s % _DAY_S) + (t_rel % f32(_DAY_S))
        tod = (tt % _DAY_S) / _DAY_S

    # Inference: diurnal concurrent load (same 14:00-centered peak as the
    # demand signal) x flash-crowd spikes while a crowd window is active.
    diurnal = 0.4 + 0.6 * _bump(tod, center=14.0 / 24, width=5.0 / 24,
                                xp=jnp)                          # [T,1]
    noise_i = _ar1_device(ki, (steps, batch), rho=0.9, sigma=0.2, axis=0)
    flash = _window(kif, (steps, batch), frac=wl.inference_flash_frac,
                    mean_ticks=wl.inference_flash_mean_ticks)
    inf = (f32(wl.inference_rate_pods) * diurnal * (1.0 + noise_i)
           * (1.0 + (f32(wl.inference_flash_mult) - 1.0) * flash))
    inf = jnp.maximum(inf, 0.0)

    # Batch backfill: anti-diurnal (runs when the fleet is slack) with
    # bursty arrival waves.
    anti = 1.5 - _bump(tod, center=14.0 / 24, width=5.0 / 24, xp=jnp)
    noise_b = _ar1_device(kb, (steps, batch), rho=0.85, sigma=0.3, axis=0)
    burst = _window(kbf, (steps, batch), frac=wl.batch_burst_frac,
                    mean_ticks=wl.batch_burst_mean_ticks)
    bat = (f32(wl.batch_rate_pods) * anti * (1.0 + noise_b)
           * (1.0 + (f32(wl.batch_burst_mult) - 1.0) * burst))
    bat = jnp.maximum(bat, 0.0)

    # Background: flat best-effort filler with mild noise.
    noise_g = _ar1_device(kg, (steps, batch), rho=0.9, sigma=0.2, axis=0)
    bg = jnp.maximum(f32(wl.background_rate_pods) * (1.0 + noise_g), 0.0)

    block = jnp.stack([inf, bat, bg], axis=1).astype(f32)  # [T, 3, B]
    return jnp.pad(block, ((0, t_pad - steps),
                           (0, workload_rows(Z) - block.shape[1]), (0, 0)))


def packed_workload_lanes_p(wl: WorkloadsConfig, derived: dict, key,
                            steps: int, t_pad: int, Z: int, batch: int, *,
                            dt_s: float, start_unix_s: float = 0.0,
                            start_offset_s=None,
                            wrap_period_s: float | None = None
                            ) -> jnp.ndarray:
    """:func:`packed_workload_lanes` with the searchable rates and spike
    amplitudes TRACED (ISSUE 19): ``derived`` is
    `ScenarioParams.derived()["workloads"]` — f32 scalars (per-family
    rates, flash/burst window triples + mults) — vmapped over ``[S]`` by
    `search/axis.ScenarioAxisSource` with the key closed over (common
    random numbers across candidates). The diurnal/anti-diurnal clock
    shapes and the family noise AR(1)s are parameter-INDEPENDENT, so
    under vmap they are computed once and broadcast — the S axis pays
    only for what actually varies. Bitwise the baked path at any
    concrete value (the rate/mult multiplies are the same f32 ops on
    the same derived values; kernel-side knobs like queue_max stay in
    ``wl``/SimParams and are untouched here)."""
    del wl  # generation-side knobs all arrive via `derived`
    ki, kif, kb, kbf, kg = jax.random.split(
        jax.random.fold_in(key, WORKLOAD_KEY_TAG), 5)
    f32 = jnp.float32
    d = derived
    t = start_unix_s + np.arange(steps) * dt_s
    if start_offset_s is None:
        tod = jnp.asarray((t % _DAY_S) / _DAY_S, f32)[:, None]      # [T,1]
    else:
        t_rel = (jnp.asarray(np.arange(steps) * dt_s, f32)[:, None]
                 + jnp.asarray(start_offset_s, f32)[None, :])       # [T,B]
        if wrap_period_s is not None:
            t_rel = t_rel % f32(wrap_period_s)
        tt = f32(start_unix_s % _DAY_S) + (t_rel % f32(_DAY_S))
        tod = (tt % _DAY_S) / _DAY_S

    diurnal = 0.4 + 0.6 * _bump(tod, center=14.0 / 24, width=5.0 / 24,
                                xp=jnp)                          # [T,1]
    noise_i = _ar1_device(ki, (steps, batch), rho=0.9, sigma=0.2, axis=0)
    flash = _window_p(kif, (steps, batch), thresh=d["flash_thresh"],
                      rho=d["flash_rho"], scale=d["flash_scale"])
    inf = (d["inf_rate"] * diurnal * (1.0 + noise_i)
           * (1.0 + (d["flash_mult"] - 1.0) * flash))
    inf = jnp.maximum(inf, 0.0)

    anti = 1.5 - _bump(tod, center=14.0 / 24, width=5.0 / 24, xp=jnp)
    noise_b = _ar1_device(kb, (steps, batch), rho=0.85, sigma=0.3, axis=0)
    burst = _window_p(kbf, (steps, batch), thresh=d["burst_thresh"],
                      rho=d["burst_rho"], scale=d["burst_scale"])
    bat = (d["batch_rate"] * anti * (1.0 + noise_b)
           * (1.0 + (d["burst_mult"] - 1.0) * burst))
    bat = jnp.maximum(bat, 0.0)

    noise_g = _ar1_device(kg, (steps, batch), rho=0.9, sigma=0.2, axis=0)
    bg = jnp.maximum(d["bg_rate"] * (1.0 + noise_g), 0.0)

    block = jnp.stack([inf, bat, bg], axis=1).astype(f32)  # [T, 3, B]
    return jnp.pad(block, ((0, t_pad - steps),
                           (0, workload_rows(Z) - block.shape[1]), (0, 0)))


def has_workload_lanes(exo_packed, Z: int) -> bool:
    """Whether a packed stream carries the workload lane block — row-
    count detection like `faults.has_fault_lanes` (raises on malformed
    layouts)."""
    return stream_layout(int(exo_packed.shape[1]), Z)[1]


def unpack_workload_lanes(exo_packed, T: int, Z: int) -> WorkloadStep:
    """Workload lanes of a widened stream → batched time-major
    :class:`WorkloadStep` (leaves ``[B, T]``) for the lax rollout path —
    the parity-test/bench plumbing mirror of `megakernel.unpack_exo`
    (it pays the transpose the packed path exists to skip; hot paths
    never call it)."""
    wb = workload_base(int(exo_packed.shape[1]), Z)
    x = exo_packed[:T, wb:wb + 3]
    return WorkloadStep(
        inf_arrivals=jnp.transpose(x[:, 0], (1, 0)),     # [B, T]
        batch_arrivals=jnp.transpose(x[:, 1], (1, 0)),
        bg_arrivals=jnp.transpose(x[:, 2], (1, 0)),
    )


def sample_workload_steps(wl: WorkloadsConfig, key, steps: int, Z: int,
                          *, dt_s: float = 30.0,
                          start_unix_s: float = 0.0) -> WorkloadStep:
    """Single-trace time-major WorkloadStep (leaves ``[T]``) for
    standalone lax rollouts and the live controller's workload track —
    same processes, same key-tag scheme as the packed lanes (a batch=1
    synthesis, squeezed)."""
    lanes = packed_workload_lanes(wl, key, steps, steps, Z, 1,
                                  dt_s=dt_s, start_unix_s=start_unix_s)
    return WorkloadStep(
        inf_arrivals=lanes[:steps, 0, 0],
        batch_arrivals=lanes[:steps, 1, 0],
        bg_arrivals=lanes[:steps, 2, 0],
    )


def _registry_generate(cfg: WorkloadsConfig, key, steps: int, t_pad: int,
                       z: int, batch: int, *, ctx: dict):
    """Lane-family registry adapter (`sim/lanes.provide_lane_generator`)
    — :func:`packed_workload_lanes` on the stream key with the clock
    context the backends carry (bitwise the direct call)."""
    return packed_workload_lanes(
        cfg, key, steps, t_pad, z, batch, dt_s=ctx["dt_s"],
        start_unix_s=ctx.get("start_unix_s", 0.0),
        start_offset_s=ctx.get("start_offset_s"),
        wrap_period_s=ctx.get("wrap_period_s"))


def _registry_generate_p(cfg: WorkloadsConfig, derived: dict, key,
                         steps: int, t_pad: int, z: int, batch: int, *,
                         ctx: dict):
    """Traced-parameter registry adapter
    (`sim/lanes.provide_lane_param_generator`) —
    :func:`packed_workload_lanes_p` on the stream key with the clock
    context the backends carry."""
    return packed_workload_lanes_p(
        cfg, derived, key, steps, t_pad, z, batch, dt_s=ctx["dt_s"],
        start_unix_s=ctx.get("start_unix_s", 0.0),
        start_offset_s=ctx.get("start_offset_s"),
        wrap_period_s=ctx.get("wrap_period_s"))


lanes.provide_lane_generator("workloads", _registry_generate)
lanes.provide_lane_param_generator("workloads", _registry_generate_p)
