"""Workload-family pytrees consumed by the simulator step.

Kept in their own leaf module (imports only jnp) so `sim/dynamics.py`
can take a :class:`WorkloadStep`/:class:`WorkloadState` without creating
a cycle with the workload *synthesis* side (`workloads/process.py`,
which imports the signal layer) — the same split `faults/types.py` uses.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class WorkloadStep(NamedTuple):
    """One tick of workload-family arrivals (a time-slice of the
    workload lanes). All values are pod-equivalents of concurrent work:
    one pod serves one unit per tick. A leading batch/time axis, when
    present, is handled by ``vmap``/``scan`` like
    :class:`~ccka_tpu.sim.dynamics.ExoStep`.

    Attributes:
      inf_arrivals:   [] inference request load arriving this tick
        (diurnal + flash crowds; served from fleet headroom with
        priority).
      batch_arrivals: [] batch work arriving this tick (bursty backfill
        waves; drained EDF from the headroom left after inference, with
        a deadline of ``batch_deadline_ticks``).
      bg_arrivals:    [] best-effort background work (consumes whatever
        headroom remains; backlog only, no SLO).
    """

    inf_arrivals: jnp.ndarray
    batch_arrivals: jnp.ndarray
    bg_arrivals: jnp.ndarray

    @classmethod
    def neutral(cls) -> "WorkloadStep":
        """The no-op arrival tick: consuming it leaves every queue and
        counter at zero (pinned by `tests/test_workloads.py`)."""
        z = jnp.float32(0.0)
        return cls(inf_arrivals=z, batch_arrivals=z, bg_arrivals=z)


class WorkloadState(NamedTuple):
    """Per-family queue state carried across ticks.

    Attributes:
      inf_queue:     [] unserved inference work (bounded by
        ``inference_queue_max``; the excess is dropped = load-shed).
      batch_backlog: [D] unfinished batch work by age: slot k = work
        that has waited k ticks (slot 0 = arrived this tick). Slot D-1
        is always 0 after an update — work reaching that age unserved
        was dropped as a deadline miss. D = ``batch_deadline_ticks``.
      bg_backlog:    [] best-effort backlog (unbounded; arrival rates
        are bounded by config).
    """

    inf_queue: jnp.ndarray
    batch_backlog: jnp.ndarray
    bg_backlog: jnp.ndarray

    @classmethod
    def zero(cls, deadline_ticks: int) -> "WorkloadState":
        z = jnp.float32(0.0)
        return cls(inf_queue=z,
                   batch_backlog=jnp.zeros((deadline_ticks,), jnp.float32),
                   bg_backlog=z)
