"""The named scenario library: workload-family mixes x fault presets.

Round 10 gave fault intensities names (`config.FAULT_PRESETS`) so the
robustness board reads "severe", not a bag of floats; this module does
the same for workload mixes. A :class:`Scenario` is a named, validated
(workload-family mix, fault preset) pair — the benchmark vocabulary the
per-family scoreboard (`workloads/scoreboard.py`), `bench.py
bench_workloads`, and the `ccka scenarios` / `ccka scenario-eval` CLI
all share, and the axis every later mixed-workload comparison
(geo-arbitrage, fleet service, distillation factory) will sweep.

Rates are sized against the demo topology (60-pod burst peak, 9 pods/
node, 3 base nodes): the inference family is a material fraction of the
fleet's typical headroom so queues genuinely build under tight fleets,
and the batch family needs sustained slack to meet deadlines — which is
exactly what makes per-family columns separate policies that look
identical on the aggregate $/SLO-hr headline.
"""

from __future__ import annotations

from dataclasses import dataclass

from ccka_tpu.config import FAULT_PRESETS, WorkloadsConfig


@dataclass(frozen=True)
class Scenario:
    """One named benchmark scenario.

    ``fault_preset`` names a `config.FAULT_PRESETS` entry composed into
    the same stream ("" = calm weather, no fault lanes) — scenarios and
    fault intensities are orthogonal axes sharing one generation key,
    so a faulted scenario's exo AND workload rows stay bitwise identical
    to its calm twin's.
    """

    name: str
    description: str
    workloads: WorkloadsConfig
    fault_preset: str = ""

    def validate(self) -> None:
        self.workloads.validate()
        if not self.workloads.enabled:
            raise ValueError(f"scenario {self.name!r}: workloads disabled")
        if self.fault_preset and self.fault_preset not in FAULT_PRESETS:
            raise ValueError(
                f"scenario {self.name!r}: unknown fault preset "
                f"{self.fault_preset!r}; presets: {sorted(FAULT_PRESETS)}")

    def family_mix(self) -> dict[str, float]:
        """Mean arrival rate per family (the `ccka scenarios` listing)."""
        w = self.workloads
        return {"inference": w.inference_rate_pods,
                "batch": w.batch_rate_pods,
                "background": w.background_rate_pods}


WORKLOAD_SCENARIOS: dict[str, Scenario] = {
    "diurnal-inference": Scenario(
        name="diurnal-inference",
        description="latency-sensitive inference serving: diurnal "
                    "request load with occasional mild flash crowds",
        workloads=WorkloadsConfig(
            enabled=True, inference_rate_pods=6.0,
            inference_flash_frac=0.02, inference_flash_mult=3.0)),
    "flash-crowd": Scenario(
        name="flash-crowd",
        description="inference serving under heavy flash crowds: the "
                    "same diurnal base, 8x spikes in frequent windows",
        workloads=WorkloadsConfig(
            enabled=True, inference_rate_pods=6.0,
            inference_flash_frac=0.06, inference_flash_mult=8.0,
            inference_flash_mean_ticks=8)),
    "batch-backfill": Scenario(
        name="batch-backfill",
        description="deadline-driven batch backfill waves (anti-diurnal) "
                    "plus a best-effort background floor",
        workloads=WorkloadsConfig(
            enabled=True, batch_rate_pods=5.0, batch_burst_frac=0.08,
            batch_burst_mult=6.0, background_rate_pods=3.0)),
    "mixed": Scenario(
        name="mixed",
        description="all three families sharing one fleet, under mild "
                    "fault weather (the millions-of-users composite)",
        workloads=WorkloadsConfig(
            enabled=True, inference_rate_pods=6.0,
            inference_flash_frac=0.04, inference_flash_mult=6.0,
            batch_rate_pods=5.0, batch_burst_frac=0.06,
            background_rate_pods=3.0),
        fault_preset="mild"),
}


def resolve_scenarios(names) -> dict[str, Scenario]:
    """Validated name→Scenario map; rejects unknown names UP FRONT
    (mirroring the round-10 unknown-policy/intensity guard — a typo
    must not run a long sweep and emit a board missing that row)."""
    names = [n for n in names if n]
    if not names:
        raise ValueError(f"no scenarios named; library: "
                         f"{sorted(WORKLOAD_SCENARIOS)}")
    bad = [n for n in names if n not in WORKLOAD_SCENARIOS]
    if bad:
        raise ValueError(f"unknown scenarios {bad}; library: "
                         f"{sorted(WORKLOAD_SCENARIOS)}")
    out = {n: WORKLOAD_SCENARIOS[n] for n in names}
    for sc in out.values():
        sc.validate()
    return out


def scenario_source(cfg, scenario: Scenario):
    """A SyntheticSignalSource generating this scenario's widened stream
    (workload lanes, plus fault lanes when the scenario names a
    preset). All scenarios driven from ONE key share bitwise-identical
    exo rows — the cross-scenario pairing the scoreboard leans on."""
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    faults = (FAULT_PRESETS[scenario.fault_preset]
              if scenario.fault_preset else None)
    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals, faults=faults,
                                 workloads=scenario.workloads)
