"""The named scenario library: workload-family mixes x fault presets.

Round 10 gave fault intensities names (`config.FAULT_PRESETS`) so the
robustness board reads "severe", not a bag of floats; this module does
the same for workload mixes. A :class:`Scenario` is a named, validated
(workload-family mix, fault preset) pair — the benchmark vocabulary the
per-family scoreboard (`workloads/scoreboard.py`), `bench.py
bench_workloads`, and the `ccka scenarios` / `ccka scenario-eval` CLI
all share, and the axis every later mixed-workload comparison
(geo-arbitrage, fleet service, distillation factory) will sweep.

Rates are sized against the demo topology (60-pod burst peak, 9 pods/
node, 3 base nodes): the inference family is a material fraction of the
fleet's typical headroom so queues genuinely build under tight fleets,
and the batch family needs sustained slack to meet deadlines — which is
exactly what makes per-family columns separate policies that look
identical on the aggregate $/SLO-hr headline.

Since ISSUE 19 a scenario can also be MINTED by the adversarial search
(`search/adversarial.py`): explicit ``faults``/``geo`` sections instead
of a preset name, plus the provenance pair (``params_json``, the
canonical `search/params.ScenarioParams` JSON the cell was found at,
and ``params_digest``, its sha256). :meth:`Scenario.validate` REFUSES a
minted scenario whose digest does not match its stored params — the
snapshot-codec tamper discipline: a worst-case cell that cannot prove
it is the cell the search recorded is not reproducible evidence.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from ccka_tpu.config import (FAULT_PRESETS, FaultsConfig, GeoConfig,
                             WorkloadsConfig, _asdict, _from_dict)


@dataclass(frozen=True)
class Scenario:
    """One named benchmark scenario.

    ``fault_preset`` names a `config.FAULT_PRESETS` entry composed into
    the same stream ("" = calm weather, no fault lanes) — scenarios and
    fault intensities are orthogonal axes sharing one generation key,
    so a faulted scenario's exo AND workload rows stay bitwise identical
    to its calm twin's.

    Minted scenarios (adversarial search, ISSUE 19) carry EXPLICIT
    ``faults``/``geo`` sections (a searched cell is a point in the
    continuous box, not a preset) plus the ``params_json``/
    ``params_digest`` provenance pair; ``faults`` takes precedence over
    ``fault_preset`` in :func:`scenario_source`.
    """

    name: str
    description: str
    workloads: WorkloadsConfig
    fault_preset: str = ""
    faults: FaultsConfig | None = None
    geo: GeoConfig | None = None
    params_json: str = ""
    params_digest: str = ""
    minted_by: str = ""

    def validate(self) -> None:
        self.workloads.validate()
        if not self.workloads.enabled:
            raise ValueError(f"scenario {self.name!r}: workloads disabled")
        if self.fault_preset and self.fault_preset not in FAULT_PRESETS:
            raise ValueError(
                f"scenario {self.name!r}: unknown fault preset "
                f"{self.fault_preset!r}; presets: {sorted(FAULT_PRESETS)}")
        if self.faults is not None:
            self.faults.validate()
        if self.geo is not None:
            self.geo.validate()
        if bool(self.params_json) != bool(self.params_digest):
            raise ValueError(
                f"scenario {self.name!r}: minted provenance needs BOTH "
                "params_json and params_digest (one without the other "
                "is an unverifiable record)")
        if self.params_json:
            from ccka_tpu.search.params import params_digest

            got = params_digest(self.params_json)
            if got != self.params_digest:
                raise ValueError(
                    f"scenario {self.name!r}: params digest mismatch — "
                    f"stored {self.params_digest[:12]}…, params hash to "
                    f"{got[:12]}…. The stored parameters were modified "
                    "after minting; refusing a tampered scenario.")

    @property
    def minted(self) -> bool:
        """Whether this scenario carries search-mint provenance."""
        return bool(self.params_digest)

    def family_mix(self) -> dict[str, float]:
        """Mean arrival rate per family (the `ccka scenarios` listing)."""
        w = self.workloads
        return {"inference": w.inference_rate_pods,
                "batch": w.batch_rate_pods,
                "background": w.background_rate_pods}

    # -- mint codec (the `--mint-out` file format) --------------------

    def to_doc(self) -> dict:
        """JSON-serializable document — the snapshot-codec round trip
        :func:`scenario_from_doc` inverts (and `validate` re-checks)."""
        doc = {"name": self.name, "description": self.description,
               "workloads": _asdict(self.workloads),
               "fault_preset": self.fault_preset,
               "params_json": self.params_json,
               "params_digest": self.params_digest,
               "minted_by": self.minted_by}
        if self.faults is not None:
            doc["faults"] = _asdict(self.faults)
        if self.geo is not None:
            doc["geo"] = _asdict(self.geo)
        return doc


def scenario_from_doc(doc: dict) -> Scenario:
    """Rebuild (and VALIDATE — incl. the tamper digest check) a minted
    scenario from its stored document."""
    sc = Scenario(
        name=str(doc["name"]), description=str(doc.get("description", "")),
        workloads=_from_dict(WorkloadsConfig, doc["workloads"]),
        fault_preset=str(doc.get("fault_preset", "")),
        faults=(_from_dict(FaultsConfig, doc["faults"])
                if doc.get("faults") is not None else None),
        geo=(_from_dict(GeoConfig, doc["geo"])
             if doc.get("geo") is not None else None),
        params_json=str(doc.get("params_json", "")),
        params_digest=str(doc.get("params_digest", "")),
        minted_by=str(doc.get("minted_by", "")))
    sc.validate()
    return sc


def load_minted_scenarios(path: str) -> dict[str, Scenario]:
    """Minted scenarios from a ``--mint-out`` JSON file or a directory
    of them — each validated (digest-checked) on load. Name collisions
    with the hand-named library are rejected: a minted cell must not
    silently shadow a published row."""
    files = []
    if os.path.isdir(path):
        files = [os.path.join(path, f) for f in sorted(os.listdir(path))
                 if f.endswith(".json")]
    elif os.path.exists(path):
        files = [path]
    out: dict[str, Scenario] = {}
    for f in files:
        with open(f, encoding="utf-8") as fh:
            doc = json.load(fh)
        sc = scenario_from_doc(doc.get("scenario", doc))
        if sc.name in WORKLOAD_SCENARIOS or sc.name in out:
            raise ValueError(f"minted scenario {sc.name!r} ({f}) "
                             "collides with an existing scenario name")
        out[sc.name] = sc
    return out


WORKLOAD_SCENARIOS: dict[str, Scenario] = {
    "diurnal-inference": Scenario(
        name="diurnal-inference",
        description="latency-sensitive inference serving: diurnal "
                    "request load with occasional mild flash crowds",
        workloads=WorkloadsConfig(
            enabled=True, inference_rate_pods=6.0,
            inference_flash_frac=0.02, inference_flash_mult=3.0)),
    "flash-crowd": Scenario(
        name="flash-crowd",
        description="inference serving under heavy flash crowds: the "
                    "same diurnal base, 8x spikes in frequent windows",
        workloads=WorkloadsConfig(
            enabled=True, inference_rate_pods=6.0,
            inference_flash_frac=0.06, inference_flash_mult=8.0,
            inference_flash_mean_ticks=8)),
    "batch-backfill": Scenario(
        name="batch-backfill",
        description="deadline-driven batch backfill waves (anti-diurnal) "
                    "plus a best-effort background floor",
        workloads=WorkloadsConfig(
            enabled=True, batch_rate_pods=5.0, batch_burst_frac=0.08,
            batch_burst_mult=6.0, background_rate_pods=3.0)),
    "mixed": Scenario(
        name="mixed",
        description="all three families sharing one fleet, under mild "
                    "fault weather (the millions-of-users composite)",
        workloads=WorkloadsConfig(
            enabled=True, inference_rate_pods=6.0,
            inference_flash_frac=0.04, inference_flash_mult=6.0,
            batch_rate_pods=5.0, batch_burst_frac=0.06,
            background_rate_pods=3.0),
        fault_preset="mild"),
}


def resolve_scenarios(names) -> dict[str, Scenario]:
    """Validated name→Scenario map; rejects unknown names UP FRONT
    (mirroring the round-10 unknown-policy/intensity guard — a typo
    must not run a long sweep and emit a board missing that row)."""
    names = [n for n in names if n]
    if not names:
        raise ValueError(f"no scenarios named; library: "
                         f"{sorted(WORKLOAD_SCENARIOS)}")
    bad = [n for n in names if n not in WORKLOAD_SCENARIOS]
    if bad:
        raise ValueError(f"unknown scenarios {bad}; library: "
                         f"{sorted(WORKLOAD_SCENARIOS)}")
    out = {n: WORKLOAD_SCENARIOS[n] for n in names}
    for sc in out.values():
        sc.validate()
    return out


def scenario_source(cfg, scenario: Scenario):
    """A SyntheticSignalSource generating this scenario's widened stream
    (workload lanes, plus fault lanes when the scenario names a preset
    or carries an explicit minted section, plus region lanes for a
    minted geo section). All scenarios driven from ONE key share
    bitwise-identical exo rows — the cross-scenario pairing the
    scoreboard leans on."""
    from ccka_tpu.signals.synthetic import SyntheticSignalSource

    faults = scenario.faults
    if faults is None and scenario.fault_preset:
        faults = FAULT_PRESETS[scenario.fault_preset]
    extra = ({"regions": scenario.geo}
             if scenario.geo is not None and scenario.geo.enabled
             else None)
    return SyntheticSignalSource(cfg.cluster, cfg.workload, cfg.sim,
                                 cfg.signals, faults=faults,
                                 workloads=scenario.workloads,
                                 extra_lanes=extra)
