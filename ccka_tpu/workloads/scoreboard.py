"""Per-family scenario scoreboard: policies × named workload scenarios.

Scores {rule, flagship, MPC-playback} (plus optional carbon) on the SAME
``n_traces`` paired worlds for each named scenario
(`workloads/scenarios.WORKLOAD_SCENARIOS`) through the megakernel path,
and reports the aggregate $/SLO-hr headline NEXT TO the per-family
columns — inference SLO-violation ticks / queue depth / load-shed and
batch deadline misses / backlog — that separate policies the aggregate
hides. The pairing properties mirror the round-10 fault board:

- **Across policies**: every row of one scenario shares one
  (stream, seed, b_block, t_chunk) — identical worlds AND identical
  family arrivals (the lanes are part of the stream).
- **Across scenarios**: all scenarios are generated from one key, so
  the exo rows are bitwise identical — scenario columns differ only by
  the family mix (and, for fault-composed scenarios, the fault lanes),
  not by different price/carbon weather.
- **MPC plans blind**: the planner sees the clean exo trace (family
  arrivals are not part of its objective), the kernel executes the plan
  on the workload-laden world — open-loop plans pay for the headroom
  they didn't reserve, which is exactly the effect worth measuring.

On TPU this runs the Mosaic kernels in stochastic mode at full-day
horizons; elsewhere interpret-mode deterministic at CI sizes (labeled —
the per-family column CONTRASTS are the result, not wall-clock). Used
by `bench.py bench_workloads` (records BASELINE round11) and the
`ccka scenario-eval` CLI.
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

from ccka_tpu.config import FrameworkConfig
from ccka_tpu.workloads.scenarios import resolve_scenarios, scenario_source

# Aggregate headline + the per-family columns, per row.
_ROW_FIELDS = ("usd_per_slo_hour", "slo_attainment",
               "inf_slo_violations", "inf_queue_mean", "inf_dropped",
               "batch_deadline_misses", "batch_backlog_mean")


def _row(summary) -> dict:
    return {k: round(float(np.asarray(getattr(summary, k),
                                      np.float64).mean()), 4)
            for k in _ROW_FIELDS}


def workload_scoreboard(cfg: FrameworkConfig, *,
                        scenarios=("diurnal-inference", "flash-crowd",
                                   "batch-backfill", "mixed"),
                        policies=("rule", "flagship", "mpc"),
                        n_traces: int = 256,
                        eval_steps: int | None = None,
                        seed: int = 31,
                        trace_seed: int = 97) -> dict:
    """The scenario board (module docstring). ``scenarios`` name
    `WORKLOAD_SCENARIOS` entries, ``policies`` ⊆ {rule, carbon,
    flagship, mpc} — both validated UP FRONT (the round-10 guard: a
    typo must not run the sweep and emit a board missing that row)."""
    from ccka_tpu.models import action_to_latent, latent_to_action
    from ccka_tpu.policy import CarbonAwarePolicy
    from ccka_tpu.policy.rule import (neutral_action, offpeak_action,
                                      peak_action)
    from ccka_tpu.sim import SimParams, initial_state
    from ccka_tpu.sim.megakernel import (
        carbon_megakernel_summary_from_packed,
        megakernel_summary_from_packed,
        neural_megakernel_summary_from_packed, pack_plan,
        plan_megakernel_summary_from_packed, unpack_exo)
    from ccka_tpu.train.flagship import load_flagship_backend
    from ccka_tpu.train.mpc import receding_horizon_plan_batch
    from ccka_tpu.workloads.process import unpack_workload_lanes

    library = resolve_scenarios(scenarios)
    known_policies = ("rule", "carbon", "flagship", "mpc")
    bad = [p for p in policies if p not in known_policies]
    if bad:
        raise ValueError(f"unknown policies {bad}; known: "
                         f"{list(known_policies)}")

    on_tpu = jax.default_backend() == "tpu"
    steps = eval_steps or (2880 if on_tpu else 96)
    t_chunk = 64 if on_tpu else 32
    b_block = min(256, n_traces)
    if n_traces % b_block:
        raise ValueError(f"n_traces={n_traces} must be a multiple of "
                         f"b_block={b_block}")
    kw = dict(seed=seed, stochastic=on_tpu, b_block=b_block,
              t_chunk=t_chunk, interpret=not on_tpu)
    import dataclasses as _dc
    params = SimParams.from_config(cfg)
    # Queue/SLO/deadline knobs are SCENARIO properties (`ccka scenarios`
    # lists them per scenario) — score each scenario under its OWN
    # WorkloadsConfig, not the caller's.
    sc_params = {name: SimParams.from_config(
        _dc.replace(cfg, workloads=sc.workloads))
        for name, sc in library.items()}
    cluster = cfg.cluster
    Z = cluster.n_zones
    off_a, peak_a = offpeak_action(cluster), peak_action(cluster)
    key = jax.random.key(trace_seed)

    # One stream per scenario, all from ONE key: exo rows bitwise
    # shared, family lanes per scenario mix. Generated lazily — one
    # resident stream at a time; a full board would otherwise pin 4+
    # [T_pad, rows, B] device buffers for the whole multi-policy sweep.
    def _scenario_stream(sc):
        return scenario_source(cfg, sc).packed_trace_device(
            steps, key, n_traces, t_chunk=t_chunk)

    out: dict = {
        "engine": "megakernel(workload lanes)",
        "n_traces": n_traces, "eval_steps": steps,
        "stochastic": on_tpu, "interpret": not on_tpu,
        "b_block": b_block, "t_chunk": t_chunk, "seed": seed,
        "policies": list(policies),
        "row_fields": list(_ROW_FIELDS),
        "scenarios": {},
    }

    flagship = None
    if "flagship" in policies:
        flagship, meta = load_flagship_backend(cfg)
        if flagship is None:
            out["flagship_source"] = ("omitted: no flagship checkpoint "
                                      "for this topology (no stand-ins)")
        else:
            out["flagship_source"] = {
                "checkpoint": "topology-keyed flagship",
                "selected_iteration": meta.get("selected_iteration")}

    plan_packed = None
    first_stream = None
    if "mpc" in policies:
        # Plan ONCE on the clean exo world (exo rows are shared across
        # scenarios, and the planner is blind to family arrivals, so
        # one plan serves every scenario row): lax quick planner per
        # paired trace, kernel playback on the workload-laden worlds.
        quick = dict(horizon=8, replan_every=8, iters=2)
        out["mpc_planner"] = dict(
            quick, n_traces=n_traces,
            mode="lax_quick_plan(clean exo)->kernel_playback(scenario)")
        # Any scenario's stream carries the shared exo rows; the first
        # scenario's is generated here and handed to its own scoring
        # iteration below (not regenerated).
        first_stream = _scenario_stream(next(iter(library.values())))
        traces = unpack_exo(first_stream, steps, Z)
        base = jnp.zeros_like(action_to_latent(neutral_action(cluster),
                                               cluster))
        lat0 = jnp.broadcast_to(
            base, (n_traces, quick["horizon"]) + base.shape)
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n_traces,) + x.shape),
            initial_state(cfg))
        plans = receding_horizon_plan_batch(
            params, cluster, cfg.train, states, traces, lat0, **quick)
        plan_actions = jax.vmap(jax.vmap(
            lambda u: latent_to_action(u, cluster)))(plans)
        import math as _math
        t_pad = _math.ceil(steps / t_chunk) * t_chunk
        plan_packed = pack_plan(plan_actions, t_pad)
        # Plan-stream geometry on the record: the playback row streams
        # these rows ON TOP of the scenario stream (bench's mpc floor).
        out["mpc_planner"]["plan_rows"] = int(plan_packed.shape[1])

    cp = CarbonAwarePolicy(cluster)
    for name, sc in library.items():
        if first_stream is not None:
            stream, first_stream = first_stream, None
        else:
            stream = _scenario_stream(sc)
        sp = sc_params[name]
        rows: dict[str, dict] = {}
        if "rule" in policies:
            rows["rule"] = _row(megakernel_summary_from_packed(
                sp, off_a, peak_a, stream, steps, **kw))
        if "carbon" in policies:
            rows["carbon"] = _row(carbon_megakernel_summary_from_packed(
                sp, off_a, peak_a, stream, steps,
                sharpness=cp.sharpness, min_weight=cp.min_weight,
                stickiness=cp.stickiness, **kw))
        if flagship is not None:
            rows["flagship"] = _row(
                neural_megakernel_summary_from_packed(
                    sp, cluster, flagship.params, stream, steps,
                    **kw))
        if plan_packed is not None:
            rows["mpc"] = _row(plan_megakernel_summary_from_packed(
                sp, cluster, plan_packed, stream, steps, **kw))
        # Stream-level family exposure (identical for every policy row
        # — the pairing, stated on the record) + the stream geometry
        # bench needs for its per-row roofline floors.
        wl = unpack_workload_lanes(stream, steps, Z)
        exposure = {
            "inference_arrivals_mean": round(
                float(np.asarray(wl.inf_arrivals).mean()), 4),
            "batch_arrivals_mean": round(
                float(np.asarray(wl.batch_arrivals).mean()), 4),
            "background_arrivals_mean": round(
                float(np.asarray(wl.bg_arrivals).mean()), 4),
        }
        out["scenarios"][name] = {
            "description": sc.description,
            "family_mix": sc.family_mix(),
            "fault_preset": sc.fault_preset or None,
            "stream_rows": int(stream.shape[1]),
            "stream_bytes_per_cluster_tick": 4 * int(stream.shape[1]),
            "exposure": exposure,
            "rows": rows,
        }
        print(f"# workloads[{name}]: " + " ".join(
            f"{p}={r['inf_slo_violations']:.1f}viol/"
            f"{r['batch_deadline_misses']:.1f}miss"
            f"@{r['slo_attainment']:.3f}" for p, r in rows.items()),
            file=sys.stderr)

    # Cross-scenario per-family comparison table: one line per policy,
    # the columns every later mixed-workload axis sweeps.
    compare = {}
    for p in next(iter(out["scenarios"].values()))["rows"]:
        compare[p] = {
            "scenarios": list(out["scenarios"]),
            "inf_slo_violations": [
                out["scenarios"][s]["rows"][p]["inf_slo_violations"]
                for s in out["scenarios"]],
            "batch_deadline_misses": [
                out["scenarios"][s]["rows"][p]["batch_deadline_misses"]
                for s in out["scenarios"]],
            "usd_per_slo_hour": [
                out["scenarios"][s]["rows"][p]["usd_per_slo_hour"]
                for s in out["scenarios"]],
        }
    out["per_family_curves"] = compare
    return out
