"""Policy layer: the pluggable decision step.

The reference's "policy engine" is two bash scripts the operator runs by hand
— `demo_20_offpeak_configure.sh` (cost-biased) and `demo_21_peak_configure.sh`
(SLO-biased) — each hard-coding disruption settings, zone sets and
capacity-type sets (`SURVEY.md` §3.2). Here the decision step is a
:class:`~ccka_tpu.policy.base.PolicyBackend` with a jittable
``decide(state, exo, t) -> Action`` surface:

- :class:`~ccka_tpu.policy.rule.RulePolicy` — the CPU reference, reproducing
  Peak/Off-Peak semantics exactly (golden-tested against the reference's
  emitted patch JSON);
- :class:`~ccka_tpu.policy.carbon.CarbonAwarePolicy` — rule profiles with
  carbon-derived zone selection (cross-region "follow the sun" migration,
  BASELINE config #4);
- learned TPU backends (``ccka_tpu.train``) — diff-MPC and PPO over the
  batched simulator.

``constraints`` encodes the Kyverno admission guardrails (`04_kyverno.sh`)
as action feasibility projection, so *any* backend's output renders to valid,
policy-compliant Karpenter patches.
"""

from ccka_tpu.policy.base import Observation, PolicyBackend  # noqa: F401
from ccka_tpu.policy.rule import RulePolicy, offpeak_action, peak_action  # noqa: F401
from ccka_tpu.policy.carbon import CarbonAwarePolicy, carbon_zone_weight  # noqa: F401
from ccka_tpu.policy.constraints import project_feasible  # noqa: F401
