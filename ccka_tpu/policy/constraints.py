"""Action feasibility projection — the Kyverno guardrails as math.

The reference enforces safety at admission time with Kyverno ClusterPolicies
(`04_kyverno.sh`): `require-requests-limits` (all pods must carry
requests/limits, `:24-42`) and `critical-no-spot-without-pdb` (pods labeled
critical may never tolerate `karpenter.sh/capacity-type=spot`, `:47-75`).
Learned policies emit unconstrained continuous actions; this module projects
them into the feasible set *before* they reach the simulator or the actuation
layer, so every emitted Karpenter patch is admission-valid by construction
(SURVEY.md §7 hard part (4)).

Projections (all differentiable clamps/renormalizations):
  1. box-clamp every field to its domain;
  2. intersect capacity-type allowance with each pool's intrinsic set —
     the on-demand-slo pool can never offer spot (PoolSpec.capacity_types);
  3. SLO pools must always allow on-demand (the critical-workload guarantee:
     capacity for non-spot-tolerating pods always exists);
  4. a pool whose zone mask collapses to ~zero is reset to all-zones —
     an empty requirement set would make the NodePool unsatisfiable
     (the failure mode demo_30_burst_observe.sh:20-28 diagnoses);
  5. hpa_scale bounded to [0.1, 4] so the HPA lever cannot hard-zero a
     workload class.
"""

from __future__ import annotations

import jax.numpy as jnp

from ccka_tpu.config import ClusterConfig
from ccka_tpu.sim.types import CT_OD, N_CT, Action

_MIN_ZONE_MASS = 1e-3

# Single source of truth for the consolidateAfter action ceiling: the latent
# codec squashes into [0, MAX] and the projection clips to the same MAX, so
# the policy can express the entire nominally-feasible range (round-1 had
# 600s vs 3600s — a quarter of the projected range unreachable). 10 minutes
# spans the reference's whole operating set (30/60/120s) with slack.
CONSOLIDATE_AFTER_MAX_S = 600.0


def static_ct_allow(cluster: ClusterConfig) -> jnp.ndarray:
    allow = jnp.zeros((cluster.n_pools, N_CT), jnp.float32)
    for i, pool in enumerate(cluster.pools):
        for j, ct in enumerate(("spot", "on-demand")):
            if ct in pool.capacity_types:
                allow = allow.at[i, j].set(1.0)
    return allow


def slo_pool_mask(cluster: ClusterConfig) -> jnp.ndarray:
    return jnp.asarray(
        [1.0 if p.strategy == "slo" else 0.0 for p in cluster.pools],
        jnp.float32)


def project_feasible(action: Action, cluster: ClusterConfig) -> Action:
    """Project an arbitrary action into the Kyverno-feasible set.

    Traceable and differentiable (clamps + where), usable inside training
    loops so the learned policy is optimized *through* the projection.
    """
    static = static_ct_allow(cluster)
    slo_mask = slo_pool_mask(cluster)

    zone_w = jnp.clip(action.zone_weight, 0.0, 1.0)
    # Rule 4: never emit an unsatisfiable (all-zero) zone requirement.
    mass = zone_w.sum(axis=-1, keepdims=True)
    zone_w = jnp.where(mass < _MIN_ZONE_MASS, jnp.ones_like(zone_w), zone_w)

    ct = jnp.clip(action.ct_allow, 0.0, 1.0) * static          # rule 2
    # Rule 3: SLO pools always offer on-demand capacity.
    ct = ct.at[:, CT_OD].set(
        jnp.maximum(ct[:, CT_OD], slo_mask))

    return Action(
        zone_weight=zone_w,
        ct_allow=ct,
        consolidation_aggr=jnp.clip(action.consolidation_aggr, 0.0, 1.0),
        consolidate_after_s=jnp.clip(action.consolidate_after_s, 0.0,
                                     CONSOLIDATE_AFTER_MAX_S),
        hpa_scale=jnp.clip(action.hpa_scale, 0.1, 4.0),        # rule 5
    )
