"""The rule-based CPU reference policy: Peak / Off-Peak profiles.

Reproduces the reference's two profiles exactly (the golden tests in
`tests/test_policy_actuation.py` assert the rendered patch JSON byte-matches the
shapes written by the bash scripts):

Off-Peak (`demo_20_offpeak_configure.sh`):
  - spot pool disruption: `WhenEmptyOrUnderutilized` (aggressive, `:59`)
  - od pool disruption:   `WhenEmpty` + `consolidateAfter: 60s` (`:60`)
  - requirements (op:replace, `:69-79`): zones = OFFPEAK_ZONES;
    spot pool capacity types ["spot","on-demand"], od pool ["on-demand"]

Peak (`demo_21_peak_configure.sh`):
  - both pools: `WhenEmpty` + `consolidateAfter: 120s` (`:56-57`)
  - requirements (op:add, `:65-75`): zones = PEAK_ZONES; same capacity types

The profile *choice* — which the reference delegates to the human operator
(`README.md:52-57`) — is automated here from the peak-hours signal, closing
the reference's "autoscaling controller" gap (§2.3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ccka_tpu.config import ClusterConfig
from ccka_tpu.policy.base import PolicyBackend
from ccka_tpu.sim.dynamics import ExoStep
from ccka_tpu.sim.types import CT_OD, CT_SPOT, N_CT, Action, ClusterState


def _zone_onehot(cluster: ClusterConfig, zones: tuple[str, ...]) -> jnp.ndarray:
    w = [1.0 if z in zones else 0.0 for z in cluster.zones]
    return jnp.asarray(w, jnp.float32)


def _profile_ct_allow(cluster: ClusterConfig) -> jnp.ndarray:
    """Both profiles pin the same capacity-type sets: spot pool allows
    ["spot","on-demand"], od pool ["on-demand"]
    (`demo_20_offpeak_configure.sh:74-78`, `demo_21_peak_configure.sh:70-74`)."""
    allow = jnp.zeros((cluster.n_pools, N_CT), jnp.float32)
    for i, pool in enumerate(cluster.pools):
        if pool.strategy == "cost":
            allow = allow.at[i, CT_SPOT].set(1.0)
        allow = allow.at[i, CT_OD].set(1.0)
    return allow


def offpeak_action(cluster: ClusterConfig) -> Action:
    """The demo_20 profile as a canonical Action."""
    n_p = cluster.n_pools
    zone_w = jnp.stack([_zone_onehot(cluster, cluster.offpeak_zones)] * n_p)
    aggr = jnp.asarray(
        [1.0 if p.strategy == "cost" else 0.0 for p in cluster.pools],
        jnp.float32)
    # Karpenter requires consolidateAfter with WhenEmpty; the spot pool's
    # WhenEmptyOrUnderutilized patch omits it (demo_20:59) → Karpenter
    # default 0s. The od pool gets 60s (demo_20:60).
    after = jnp.asarray(
        [0.0 if p.strategy == "cost" else 60.0 for p in cluster.pools],
        jnp.float32)
    return Action(
        zone_weight=zone_w,
        ct_allow=_profile_ct_allow(cluster),
        consolidation_aggr=aggr,
        consolidate_after_s=after,
        hpa_scale=jnp.ones((2,), jnp.float32),
    )


def peak_action(cluster: ClusterConfig) -> Action:
    """The demo_21 profile as a canonical Action."""
    n_p = cluster.n_pools
    zone_w = jnp.stack([_zone_onehot(cluster, cluster.peak_zones)] * n_p)
    return Action(
        zone_weight=zone_w,
        ct_allow=_profile_ct_allow(cluster),
        consolidation_aggr=jnp.zeros((n_p,), jnp.float32),
        consolidate_after_s=jnp.full((n_p,), 120.0, jnp.float32),
        hpa_scale=jnp.ones((2,), jnp.float32),
    )


def neutral_action(cluster: ClusterConfig) -> Action:
    """The demo_19 reset profile: WhenEmpty/30s, all zones, intrinsic
    capacity types (`demo_19_reset_policies.sh:22-29`)."""
    return Action.neutral(cluster.n_pools, cluster.n_zones)


class RulePolicy(PolicyBackend):
    """Peak/Off-Peak switcher — the reference's decision logic, automated.

    ``decide`` is traceable: both profile actions are precomputed constants
    and selected per-tick with `lax.select`-style `where` on the peak-hours
    signal, so the rule policy runs inside `scan`/`vmap` batches as the
    baseline opponent for learned policies.
    """

    def __init__(self, cluster: ClusterConfig):
        self.cluster = cluster
        self._off = offpeak_action(cluster)
        self._peak = peak_action(cluster)

    def decide(self, state: ClusterState, exo: ExoStep,
               t: jnp.ndarray) -> Action:
        is_peak = exo.is_peak > 0.5
        return jax.tree.map(
            lambda a, b: jnp.where(is_peak, a, b), self._peak, self._off)

    def profile_name(self, is_peak: bool) -> str:
        return "peak" if is_peak else "offpeak"
