"""Carbon-aware zone-selection policy: "follow the sun" as a rule backend.

The reference stubs carbon awareness as static NodePool labels
(`carbon.simulated=low|medium`, `demo_10_setup_configure.sh:61-62`) and an
unused API key (`.env:14-16`); its multi-region/"migration" story is
paper-only (proposal PDF p.5). This backend realizes both: it keeps the
Peak/Off-Peak disruption and capacity-type semantics of the rule profiles
(`demo_20_offpeak_configure.sh:59-60`, `demo_21_peak_configure.sh:56-57`)
but derives the zone requirement from the *live carbon-intensity signal*
instead of the static OFFPEAK_ZONES/PEAK_ZONES sets — preferring
cleaner-than-fleet-average zones, across regions when the topology spans
them (BASELINE.json config #4).

Migration mechanics: the zone weight steers where Karpenter provisions new
capacity (`topology.kubernetes.io/zone In [...]`,
`demo_20_offpeak_configure.sh:71`); consolidation + spot churn then drain
the dirty zones, so the fleet walks toward the clean region over a few
provisioning cycles — node *migration* exactly as a real Karpenter fleet
would do it (no live-migration primitive exists for nodes).

``decide`` is traceable — the zone weight is a smooth function of the
carbon tick — so the backend drives scan/vmap rollouts and serves as a
baseline opponent for the learned backends.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ccka_tpu.config import ClusterConfig
from ccka_tpu.policy.base import PolicyBackend
from ccka_tpu.policy.rule import offpeak_action, peak_action
from ccka_tpu.sim.dynamics import ExoStep
from ccka_tpu.sim.types import Action, ClusterState


def carbon_zone_weight(carbon_g_kwh: jnp.ndarray,
                       *, sharpness: float = 10.0) -> jnp.ndarray:
    """[Z] carbon signal → [Z] zone weight in (0,1).

    A zone cleaner than the fleet mean gets weight > 0.5 (selected when the
    action is discretized into a zone requirement,
    `actuation/patches.py`), dirtier gets < 0.5; the margin is relative so
    a 10% cleaner-than-average zone saturates toward 1. Smooth (sigmoid),
    so diff-MPC gradients and the provisioning softmax in
    `sim/dynamics.py` both see the carbon ordering.
    """
    mean = carbon_g_kwh.mean()
    rel = (mean - carbon_g_kwh) / (mean + 1e-6)
    return jax.nn.sigmoid(sharpness * rel)


class CarbonAwarePolicy(PolicyBackend):
    """Rule profiles with carbon-derived zone selection.

    Disruption, capacity types and the HPA lever follow the Peak/Off-Peak
    profile chosen by the peak-hours signal (same switching rule as
    :class:`~ccka_tpu.policy.rule.RulePolicy`); the zone weight re-ranks
    zones every tick by grid carbon intensity.

    ``min_weight`` keeps a floor under every zone so the requirement can
    never render empty and provisioning never fully starves a zone that is
    about to become the cleanest (duck-curve crossovers happen twice a day).

    ``stickiness`` is hysteresis: zones already holding fleet get a logit
    bonus proportional to their share above uniform, so per-tick carbon
    noise around a crossover cannot flip the zone requirement (and churn
    real nodes) until the carbon margin genuinely exceeds
    ``stickiness / sharpness`` (~10% relative by default). Stateless and
    traceable — the "memory" is the fleet placement itself, which is
    already in :class:`ClusterState`.
    """

    def __init__(self, cluster: ClusterConfig, *, sharpness: float = 10.0,
                 min_weight: float = 0.05, stickiness: float = 1.0):
        self.cluster = cluster
        self.sharpness = sharpness
        self.min_weight = min_weight
        self.stickiness = stickiness
        self._off = offpeak_action(cluster)
        self._peak = peak_action(cluster)

    def decide(self, state: ClusterState, exo: ExoStep,
               t: jnp.ndarray) -> Action:
        is_peak = exo.is_peak > 0.5
        base = jax.tree.map(
            lambda a, b: jnp.where(is_peak, a, b), self._peak, self._off)
        mean = exo.carbon_g_kwh.mean()
        rel = (mean - exo.carbon_g_kwh) / (mean + 1e-6)        # [Z]
        nodes_z = state.nodes.sum(axis=(0, 2))                 # [Z]
        n_zones = nodes_z.shape[-1]
        share = nodes_z / (nodes_z.sum() + 1e-6)               # [Z]
        # 0 when uniform; clipped so a fully-concentrated fleet cannot
        # out-shout a genuinely large carbon divergence.
        occupancy = jnp.clip(share * n_zones - 1.0, -1.0, 1.0)
        w = jax.nn.sigmoid(self.sharpness * rel
                           + self.stickiness * occupancy)
        w = jnp.maximum(w, self.min_weight)                     # [Z]
        zone_w = jnp.broadcast_to(w, base.zone_weight.shape)    # [P, Z]
        return base._replace(zone_weight=zone_w)

    def profile_name(self, is_peak: bool) -> str:
        return ("peak" if is_peak else "offpeak") + "+carbon"
