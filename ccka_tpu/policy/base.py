"""PolicyBackend interface and the observation feature surface.

`BASELINE.json` north star: "Replace the hand-coded Peak/Off-Peak decision
logic with a pluggable PolicyBackend interface… demo_20/21 become thin
callers of PolicyBackend.decide()". The interface is deliberately jittable:
``decide`` is a pure function of (state, exogenous tick, time index) so the
same backend drives (a) the live 30s control loop, (b) million-step batched
simulation under `lax.scan`/`vmap`, and (c) gradient-based training.
"""

from __future__ import annotations

import abc
from typing import NamedTuple

import jax.numpy as jnp

from ccka_tpu.sim.dynamics import ExoStep
from ccka_tpu.sim.types import Action, ClusterState, SimParams


class Observation(NamedTuple):
    """Flat policy features, built from state + tick signals.

    This is the tensorized form of what the reference's operator looks at
    before choosing a profile: dashboards of cost, pending pods, node counts
    and the clock (`demo_40_watch_observe.sh`, `README.md:52-57`).
    """

    nodes_pzc: jnp.ndarray      # [P, Z, T_CT] fleet
    pipeline_ct: jnp.ndarray    # [T_CT] capacity in flight (nodes)
    running: jnp.ndarray        # [C]
    demand: jnp.ndarray         # [C] raw demand this tick
    spot_price_hr: jnp.ndarray  # [Z]
    od_price_hr: jnp.ndarray    # [Z]
    carbon_g_kwh: jnp.ndarray   # [Z]
    is_peak: jnp.ndarray        # []
    tod_frac: jnp.ndarray       # [] time of day in [0,1)

    def flatten(self) -> jnp.ndarray:
        """Single feature vector (for MLP policies)."""
        parts = [jnp.ravel(x) for x in self]
        return jnp.concatenate([p.astype(jnp.float32) for p in parts])


def observe(params: SimParams, state: ClusterState, exo: ExoStep) -> Observation:
    return Observation(
        nodes_pzc=state.nodes,
        pipeline_ct=state.pipeline.sum(axis=(0, 1, 2)),
        running=state.running,
        demand=exo.demand_pods,
        spot_price_hr=exo.spot_price_hr,
        od_price_hr=exo.od_price_hr,
        carbon_g_kwh=exo.carbon_g_kwh,
        is_peak=exo.is_peak,
        tod_frac=(state.time_s % 86400.0) / 86400.0,
    )


class PolicyBackend(abc.ABC):
    """A pluggable decision backend.

    Implementations must keep :meth:`decide` traceable (no Python branching
    on array values) so it can live inside `jit`/`scan`/`vmap`/`grad`.
    """

    @abc.abstractmethod
    def decide(self, state: ClusterState, exo: ExoStep,
               t: jnp.ndarray) -> Action:
        """Map the current cluster + signals to an action."""

    def action_fn(self):
        """Adapter for :func:`ccka_tpu.sim.rollout.rollout`."""
        return lambda state, exo, t: self.decide(state, exo, t)

    @property
    def name(self) -> str:
        return type(self).__name__
