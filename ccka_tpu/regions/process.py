"""Per-region geo exo processes, synthesized as packed-stream lanes.

The geo-arbitrage subsystem's generation half, mirroring
`faults/process.py` / `workloads/process.py`: pure-jnp processes
emitting ``[T_pad, region_rows(Z), B]`` lane blocks that ride the SAME
packed exo stream the megakernel reads. Because the lanes are part of
stream synthesis they inherit every pairing property of the exo
signals: shard-local on a mesh, and bitwise identical for every policy
scored on the stream — the no-migration baseline and every migration
policy see one regional spot storm.

Lane layout, offsets relative to the region block base (which sits
AFTER the fault and workload blocks when present — registration order,
`sim/lanes.resolve_layout`). Region values broadcast to each of the
region's zones (``GeoConfig.zone_region_index``); consumers read one
representative zone per region (:func:`region_slots`):

    rows 0..Z-1     price_dev[z]    relative spot-price deviation
                                    (storm surge + AR(1); 0 = neutral)
    rows Z..2Z-1    carbon_dev[z]   carbon-intensity deviation, g/kWh
    rows 2Z..3Z-1   capacity[z]     migratable capacity, pods/tick
                                    (collapses in denial windows)
    rows 3Z..4Z-1   inf_arrivals[z]   migratable inference work
    rows 4Z..5Z-1   batch_arrivals[z] migratable batch work
    rows 5Z..6Z-1   bg_arrivals[z]    migratable background work
    rows pad to ``region_rows(Z) = 4*fault_rows(Z) + 32`` (zeros)

Storm/denial windows reuse the fault subsystem's thresholded
stationary AR(1) family (`faults/process._window`); diurnal shape
reuses the signal generator's `_bump`. The neutral contract: with
every rate and sigma at 0 the emitted lanes are EXACTLY 0 — consuming
them is a no-op, which is what lets the zero-geo gate
(`tests/test_regions.py`) pin the widened pipeline against the
pre-geo one.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from ccka_tpu.config import GeoConfig
from ccka_tpu.faults.process import _window, _window_p
from ccka_tpu.signals.synthetic import _ar1_device, _bump
from ccka_tpu.sim import lanes

_DAY_S = 86400.0

# Key-domain tag separating the region latents from the exo noise AND
# the fault/workload latents: folded into the same generation key, so
# widening a stream with region lanes changes neither the exo nor the
# other family rows bitwise. Canonical value lives in the lane-family
# registry (`sim/lanes.py`).
REGION_KEY_TAG = lanes.LANE_FAMILIES["regions"].key_tag

# Layout arithmetic lives in the neutral `sim/lanes.py`; re-exported
# for the `regions.*` surface like `workloads.workload_rows`.
region_rows = lanes.region_rows

# Sub-block order inside the region lane block (each Z rows wide).
REGION_LANE_FIELDS = ("price_dev", "carbon_dev", "capacity",
                      "inf_arrivals", "batch_arrivals", "bg_arrivals")


class RegionStep(NamedTuple):
    """Per-REGION geo lane values, time-major ``[T, R, B]`` leaves
    (one representative zone per region — region values are broadcast
    zone-wise in the packed block)."""

    price_dev: jnp.ndarray
    carbon_dev: jnp.ndarray
    capacity: jnp.ndarray
    inf_arrivals: jnp.ndarray
    batch_arrivals: jnp.ndarray
    bg_arrivals: jnp.ndarray


def _zone_region_index(geo: GeoConfig, Z: int) -> tuple[int, ...]:
    """The zone→region map actually used at zone count ``Z``: the bound
    config's map when it matches, else the single-region fallback (a
    widened source on a foreign topology reads as one region rather
    than mis-indexing zones)."""
    zri = geo.zone_region_index
    if len(zri) == Z:
        return zri
    return (0,) * Z


def region_slots(geo: GeoConfig, Z: int) -> tuple[int, ...]:
    """First zone index of each region — the representative zone
    consumers read each region's broadcast value from."""
    zri = _zone_region_index(geo, Z)
    slots: list[int] = []
    for z, r in enumerate(zri):
        if r == len(slots):
            slots.append(z)
    return tuple(slots)


def packed_region_lanes(geo: GeoConfig, key, steps: int, t_pad: int,
                        Z: int, batch: int, *,
                        dt_s: float, start_unix_s: float = 0.0,
                        start_offset_s=None,
                        wrap_period_s: float | None = None) -> jnp.ndarray:
    """``[T_pad, region_rows(Z), B]`` lane block for one stream.

    Pure jnp — runs inside the (possibly shard_map'd) generation jit.
    Clock arguments mirror `workloads.packed_workload_lanes` so the
    diurnal/anti-diurnal shapes stay phase-aligned with the exo demand
    under both the synthetic and blocked/replay clocks.
    """
    kp, ks, kc, kcap, kd, ki, kb, kg = jax.random.split(
        jax.random.fold_in(key, REGION_KEY_TAG), 8)
    f32 = jnp.float32
    zri = _zone_region_index(geo, Z)
    R = max(zri) + 1
    zero = jnp.zeros((steps, R, batch), f32)

    t = start_unix_s + np.arange(steps) * dt_s
    if start_offset_s is None:
        tod = jnp.asarray((t % _DAY_S) / _DAY_S, f32)[:, None, None]
    else:
        t_rel = (jnp.asarray(np.arange(steps) * dt_s, f32)[:, None]
                 + jnp.asarray(start_offset_s, f32)[None, :])     # [T,B]
        if wrap_period_s is not None:
            t_rel = t_rel % f32(wrap_period_s)
        tt = f32(start_unix_s % _DAY_S) + (t_rel % f32(_DAY_S))
        tod = ((tt % _DAY_S) / _DAY_S)[:, None, :]                # [T,1,B]

    # Per-region spot-price deviation: storm surge windows + AR(1)
    # noise, each gated host-side so a zero config emits EXACT zeros.
    # The SAME storm window optionally dirties the regional grid
    # (peaker-plant dispatch, `price_storm_carbon_g_kwh`).
    price = zero
    storm = None
    if geo.price_dev_sigma > 0.0:
        price = price + _ar1_device(kp, (steps, R, batch), rho=0.97,
                                    sigma=geo.price_dev_sigma, axis=0)
    if geo.price_storm_frac > 0.0:
        storm = _window(ks, (steps, R, batch),
                        frac=geo.price_storm_frac,
                        mean_ticks=geo.price_storm_mean_ticks)
        price = price + (f32(geo.price_storm_mult) - 1.0) * storm

    carbon = zero
    if geo.carbon_dev_sigma_g_kwh > 0.0:
        carbon = carbon + _ar1_device(
            kc, (steps, R, batch), rho=0.95,
            sigma=geo.carbon_dev_sigma_g_kwh, axis=0)
    if storm is not None and geo.price_storm_carbon_g_kwh > 0.0:
        carbon = carbon + f32(geo.price_storm_carbon_g_kwh) * storm

    # Migratable capacity, collapsing by deny_frac in denial windows.
    cap = zero
    if geo.capacity_pods > 0.0:
        cap = jnp.full((steps, R, batch), f32(geo.capacity_pods))
        if geo.capacity_deny_window_frac > 0.0:
            deny = _window(kd, (steps, R, batch),
                           frac=geo.capacity_deny_window_frac,
                           mean_ticks=geo.capacity_deny_mean_ticks)
            cap = cap * (1.0 - f32(geo.capacity_deny_frac) * deny)
        _ = kcap  # reserved: capacity AR(1) texture
        cap = jnp.maximum(cap, 0.0)

    # Migratable family arrivals — diurnal inference, anti-diurnal
    # batch, flat background (the workload-family shapes).
    diurnal = 0.4 + 0.6 * _bump(tod, center=14.0 / 24, width=5.0 / 24,
                                xp=jnp)
    anti = 1.5 - _bump(tod, center=14.0 / 24, width=5.0 / 24, xp=jnp)
    inf = zero
    if geo.migratable_inference_pods > 0.0:
        noise_i = _ar1_device(ki, (steps, R, batch), rho=0.9,
                              sigma=0.2, axis=0)
        inf = jnp.maximum(f32(geo.migratable_inference_pods)
                          * diurnal * (1.0 + noise_i), 0.0)
    bat = zero
    if geo.migratable_batch_pods > 0.0:
        noise_b = _ar1_device(kb, (steps, R, batch), rho=0.85,
                              sigma=0.3, axis=0)
        bat = jnp.maximum(f32(geo.migratable_batch_pods)
                          * anti * (1.0 + noise_b), 0.0)
    bg = zero
    if geo.migratable_background_pods > 0.0:
        noise_g = _ar1_device(kg, (steps, R, batch), rho=0.9,
                              sigma=0.2, axis=0)
        bg = jnp.maximum(f32(geo.migratable_background_pods)
                         * (1.0 + noise_g), 0.0)

    # Region → zone broadcast, then the six Z-row sub-blocks in
    # REGION_LANE_FIELDS order.
    zri_ix = jnp.asarray(zri, jnp.int32)
    per_zone = [x[:, zri_ix, :] for x in
                (price, carbon, cap, inf, bat, bg)]     # each [T, Z, B]
    block = jnp.concatenate(per_zone, axis=1).astype(f32)  # [T, 6Z, B]
    return jnp.pad(block, ((0, t_pad - steps),
                           (0, region_rows(Z) - block.shape[1]), (0, 0)))


def packed_region_lanes_p(geo: GeoConfig, derived: dict, key, steps: int,
                          t_pad: int, Z: int, batch: int, *,
                          dt_s: float, start_unix_s: float = 0.0,
                          start_offset_s=None,
                          wrap_period_s: float | None = None
                          ) -> jnp.ndarray:
    """:func:`packed_region_lanes` with the SPOT-STORM block traced
    (ISSUE 19): ``derived`` is `ScenarioParams.derived()["regions"]` —
    the storm window triple plus surge mult / carbon coefficients as f32
    scalars. Only the storm block becomes unconditional traced
    arithmetic (``price += (mult-1)*storm``; ``carbon +=
    carbon_g*storm`` — exact no-ops when the window never opens, since
    the +inf threshold makes ``storm`` exact zeros); the sigma / capacity
    / migration blocks are NOT searchable and keep their host config
    gates verbatim, so a search never perturbs them and the compiled
    program stays specialized to the non-searched topology. Key
    consumption is identical to the baked path (all eight subkeys split
    regardless of gating)."""
    kp, ks, kc, kcap, kd, ki, kb, kg = jax.random.split(
        jax.random.fold_in(key, REGION_KEY_TAG), 8)
    f32 = jnp.float32
    zri = _zone_region_index(geo, Z)
    R = max(zri) + 1
    zero = jnp.zeros((steps, R, batch), f32)

    t = start_unix_s + np.arange(steps) * dt_s
    if start_offset_s is None:
        tod = jnp.asarray((t % _DAY_S) / _DAY_S, f32)[:, None, None]
    else:
        t_rel = (jnp.asarray(np.arange(steps) * dt_s, f32)[:, None]
                 + jnp.asarray(start_offset_s, f32)[None, :])     # [T,B]
        if wrap_period_s is not None:
            t_rel = t_rel % f32(wrap_period_s)
        tt = f32(start_unix_s % _DAY_S) + (t_rel % f32(_DAY_S))
        tod = ((tt % _DAY_S) / _DAY_S)[:, None, :]                # [T,1,B]

    price = zero
    if geo.price_dev_sigma > 0.0:
        price = price + _ar1_device(kp, (steps, R, batch), rho=0.97,
                                    sigma=geo.price_dev_sigma, axis=0)
    storm = _window_p(ks, (steps, R, batch), thresh=derived["storm_thresh"],
                      rho=derived["storm_rho"],
                      scale=derived["storm_scale"])
    price = price + (derived["storm_mult"] - 1.0) * storm

    carbon = zero
    if geo.carbon_dev_sigma_g_kwh > 0.0:
        carbon = carbon + _ar1_device(
            kc, (steps, R, batch), rho=0.95,
            sigma=geo.carbon_dev_sigma_g_kwh, axis=0)
    carbon = carbon + derived["storm_carbon"] * storm

    cap = zero
    if geo.capacity_pods > 0.0:
        cap = jnp.full((steps, R, batch), f32(geo.capacity_pods))
        if geo.capacity_deny_window_frac > 0.0:
            deny = _window(kd, (steps, R, batch),
                           frac=geo.capacity_deny_window_frac,
                           mean_ticks=geo.capacity_deny_mean_ticks)
            cap = cap * (1.0 - f32(geo.capacity_deny_frac) * deny)
        _ = kcap  # reserved: capacity AR(1) texture
        cap = jnp.maximum(cap, 0.0)

    diurnal = 0.4 + 0.6 * _bump(tod, center=14.0 / 24, width=5.0 / 24,
                                xp=jnp)
    anti = 1.5 - _bump(tod, center=14.0 / 24, width=5.0 / 24, xp=jnp)
    inf = zero
    if geo.migratable_inference_pods > 0.0:
        noise_i = _ar1_device(ki, (steps, R, batch), rho=0.9,
                              sigma=0.2, axis=0)
        inf = jnp.maximum(f32(geo.migratable_inference_pods)
                          * diurnal * (1.0 + noise_i), 0.0)
    bat = zero
    if geo.migratable_batch_pods > 0.0:
        noise_b = _ar1_device(kb, (steps, R, batch), rho=0.85,
                              sigma=0.3, axis=0)
        bat = jnp.maximum(f32(geo.migratable_batch_pods)
                          * anti * (1.0 + noise_b), 0.0)
    bg = zero
    if geo.migratable_background_pods > 0.0:
        noise_g = _ar1_device(kg, (steps, R, batch), rho=0.9,
                              sigma=0.2, axis=0)
        bg = jnp.maximum(f32(geo.migratable_background_pods)
                         * (1.0 + noise_g), 0.0)

    zri_ix = jnp.asarray(zri, jnp.int32)
    per_zone = [x[:, zri_ix, :] for x in
                (price, carbon, cap, inf, bat, bg)]     # each [T, Z, B]
    block = jnp.concatenate(per_zone, axis=1).astype(f32)  # [T, 6Z, B]
    return jnp.pad(block, ((0, t_pad - steps),
                           (0, region_rows(Z) - block.shape[1]), (0, 0)))


def has_region_lanes(exo_packed, Z: int) -> bool:
    """Whether a packed stream carries the region lane block — row-
    count detection via the registry resolver (raises on malformed
    layouts)."""
    return lanes.resolve_layout(int(exo_packed.shape[1]), Z).has("regions")


def region_step_from_block(block, T: int, Z: int,
                           geo: GeoConfig) -> RegionStep:
    """A bare ``[T_pad, >=6Z, B]`` region lane block → time-major
    :class:`RegionStep` (leaves ``[T, R, B]``), reading each region's
    representative zone."""
    slots = np.asarray(region_slots(geo, Z), np.int32)
    fields = [block[:T, i * Z:(i + 1) * Z][:, slots]
              for i in range(len(REGION_LANE_FIELDS))]
    return RegionStep(*fields)


def unpack_region_lanes(exo_packed, T: int, Z: int,
                        geo: GeoConfig) -> RegionStep:
    """Region lanes of a widened FULL stream (base exo + family
    blocks) → :class:`RegionStep` — the geo overlay's and the parity
    tests' consumption path."""
    lay = lanes.resolve_layout(int(exo_packed.shape[1]), Z)
    lo, _hi = lay.block("regions")
    return region_step_from_block(exo_packed[:, lo:lo + 6 * Z], T, Z, geo)


def sample_region_steps(geo: GeoConfig, key, steps: int, Z: int,
                        *, dt_s: float = 30.0,
                        start_unix_s: float = 0.0) -> RegionStep:
    """Single-trace RegionStep (leaves ``[T, R]``) for standalone
    rollouts — same processes, same key-tag scheme as the packed lanes
    (a batch=1 synthesis, squeezed)."""
    block = packed_region_lanes(geo, key, steps, steps, Z, 1,
                                dt_s=dt_s, start_unix_s=start_unix_s)
    slots = np.asarray(region_slots(geo, Z), np.int32)
    fields = [block[:steps, i * Z:(i + 1) * Z][:, slots, 0]
              for i in range(len(REGION_LANE_FIELDS))]
    return RegionStep(*fields)


def _registry_generate(cfg: GeoConfig, key, steps: int, t_pad: int,
                       z: int, batch: int, *, ctx: dict):
    """Lane-family registry adapter (`sim/lanes.provide_lane_generator`)
    — :func:`packed_region_lanes` on the stream key with the clock
    context the backends carry (bitwise the direct call)."""
    return packed_region_lanes(
        cfg, key, steps, t_pad, z, batch, dt_s=ctx["dt_s"],
        start_unix_s=ctx.get("start_unix_s", 0.0),
        start_offset_s=ctx.get("start_offset_s"),
        wrap_period_s=ctx.get("wrap_period_s"))


def _registry_generate_p(cfg: GeoConfig, derived: dict, key, steps: int,
                         t_pad: int, z: int, batch: int, *, ctx: dict):
    """Traced-parameter registry adapter
    (`sim/lanes.provide_lane_param_generator`) —
    :func:`packed_region_lanes_p` on the stream key with the clock
    context the backends carry."""
    return packed_region_lanes_p(
        cfg, derived, key, steps, t_pad, z, batch, dt_s=ctx["dt_s"],
        start_unix_s=ctx.get("start_unix_s", 0.0),
        start_offset_s=ctx.get("start_offset_s"),
        wrap_period_s=ctx.get("wrap_period_s"))


lanes.provide_lane_generator("regions", _registry_generate)
lanes.provide_lane_param_generator("regions", _registry_generate_p)
