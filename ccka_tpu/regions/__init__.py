"""Geo-arbitrage subsystem: per-region exo lane families, inter-region
workload migration, and the cost/carbon/SLO Pareto scoreboard.

Layering (ISSUE 16): `process` synthesizes the per-region price /
carbon / capacity / migratable-arrival lanes through the round-17 lane
registry (every engine derives them with zero per-engine edits);
`migrate` defines the migration action space and its conservation
sanitizer; `geo` runs the batched expectation dynamics that move
pending mass between regions; `pareto` scores policies as cost/carbon/
SLO fronts per workload class instead of one scalar.
"""

from ccka_tpu.regions.process import (  # noqa: F401
    REGION_KEY_TAG,
    REGION_LANE_FIELDS,
    RegionStep,
    has_region_lanes,
    packed_region_lanes,
    region_rows,
    region_slots,
    sample_region_steps,
    region_step_from_block,
    unpack_region_lanes,
)
